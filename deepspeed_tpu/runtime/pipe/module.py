"""Generic pipeline module: pipeline *your* model.

Reference parity: ``PipelineModule`` (reference runtime/pipe/module.py:86) —
the user expresses a model as a ``LayerSpec`` list; the module partitions
layers across stages (``_partition_layers``, module.py:393, methods
uniform / parameters / type:regex) and handles tied layers
(``TiedLayerSpec``, ``allreduce_tied_weight_gradients``, module.py:454).

TPU-native design (one SPMD program, not per-stage processes):

* The stage schedule is a ``lax.scan`` over T = M + P - 1 ticks inside a
  ``shard_map`` over the 'pipe' mesh axis; activations move between stages
  with ``ppermute`` (ring).  Autodiff of the scanned schedule IS the
  backward pipeline wave — no hand-written 1F1B instruction map needed.
* Each device executes ONLY its stage's layer group, via ``lax.switch`` on
  the stage index: the first stage's input mapping (e.g. embedding) and the
  last stage's head+loss run on exactly one stage each (the reference's
  LoadMicroBatch / loss-on-last-stage placement; fixes the all-stages
  masked-compute waste of the transformer-specific path).
* Per-stage parameter placement: when the per-stage groups are structurally
  identical (the common repeated-block case), layer params are stacked on a
  leading [num_stages, ...] dim sharded over 'pipe' — each stage holds only
  its own weights.  Structurally HETEROGENEOUS groups (distinct
  embed/middle/head stages — the reference always stage-locals these,
  pipe/module.py:393) are flat-packed: each stage's leaves are raveled and
  concatenated into one per-dtype vector, padded to the longest stage, and
  stacked [num_stages, maxlen] sharded over 'pipe'.  Every device holds only
  max-stage-size params; each ``lax.switch`` branch unflattens its own
  stage's layout statically.
* Tied layers (``TiedLayerSpec``): one shared param subtree, replicated
  over 'pipe'; ``shard_map``'s transpose psums the per-stage cotangents —
  the tied-weight gradient allreduce of the reference, for free.
* Memory is bounded like the reference's 1F1B ``TrainSchedule``
  (pipe/schedule.py:189): the scheduling scan's tick body is wrapped in
  ``jax.checkpoint`` (``checkpoint_ticks``), so autodiff saves only the
  O(ring-buffer) carry per tick and recomputes one tick's layer internals
  at a time in the backward wave — live residuals do NOT scale with
  ``num_microbatches`` (more micro-batches still means less bubble, not
  more memory).

Constraints of the SPMD formulation (differences from the reference):
  - stage-boundary activations must share one shape/dtype (the ring
    buffer); the LAST group is exempt (its output feeds the loss only).
  - dropout/rng inside pipelined layers is not threaded (pass deterministic
    apply fns).
"""

from __future__ import annotations

import dataclasses
import functools
import re
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ...parallel.mesh import BATCH_AXES, PIPE_AXIS, get_topology
from ...utils.jax_compat import shard_map
from ...utils.logging import logger
from ..module import ModelSpec


@dataclasses.dataclass
class LayerSpec:
    """One pipeline layer: ``init_fn(rng) -> params``,
    ``apply_fn(params, x) -> y`` (reference LayerSpec, pipe/module.py:43).
    A param-less layer (activation, reshape) may use ``init_fn=None``."""

    init_fn: Optional[Callable[[Any], Any]]
    apply_fn: Callable[[Any, Any], Any]
    name: str = ""

    def init(self, rng):
        return self.init_fn(rng) if self.init_fn is not None else ()


@dataclasses.dataclass
class TiedLayerSpec(LayerSpec):
    """A layer whose params are shared with every other TiedLayerSpec of the
    same ``key`` (reference TiedLayerSpec, pipe/module.py:62 — e.g. embedding
    reused as the LM head).  ``init_fn`` is taken from the first spec with
    the key; tied gradients sum across stages automatically."""

    key: str = ""


def partition_balanced(weights: Sequence[float], parts: int) -> List[int]:
    """Contiguous partition of ``weights`` into ``parts`` minimizing the max
    part weight (reference ds_utils.partition_balanced used by
    _partition_layers).  Returns part boundaries, len = parts + 1."""
    n = len(weights)
    prefix = np.concatenate([[0.0], np.cumsum(weights)])

    def parts_needed(cap: float) -> Optional[List[int]]:
        bounds, start = [0], 0
        for k in range(parts):
            # furthest end with sum(start..end) <= cap, leaving at least one
            # item for each later part (no empty stages)
            end = int(np.searchsorted(prefix, prefix[start] + cap, side="right")) - 1
            end = min(end, n - (parts - k - 1))
            if end <= start:  # a single item exceeds cap
                return None
            bounds.append(end)
            start = end
        if bounds[-1] != n:
            return None
        return bounds

    lo = max(float(max(weights)) if len(weights) else 0.0, 1e-9)
    hi = max(float(prefix[-1]), lo)
    for _ in range(50):
        mid = (lo + hi) / 2
        if parts_needed(mid) is None:
            lo = mid
        else:
            hi = mid
    bounds = parts_needed(hi)
    assert bounds is not None
    return bounds


class PipelineModule:
    """Partition a LayerSpec list over the 'pipe' mesh axis and expose the
    engine's ModelSpec contract (init_params / loss_fn / partition_rules).

    loss_fn: ``(last_stage_output, labels) -> scalar`` (mean over the
    micro-batch), the reference's ``loss_fn`` argument (pipe/module.py:86).
    Batches are ``(inputs, labels)`` tuples (or dicts with 'inputs'/
    'labels'); leaves carry the full (micro * b) batch dim like the dense
    engine path.
    """

    def __init__(self, layers: Sequence[LayerSpec], loss_fn: Callable,
                 num_stages: Optional[int] = None,
                 num_microbatches: int = 4,
                 partition_method: str = "parameters",
                 seed_layers: bool = False,
                 checkpoint_ticks: bool = True,
                 hop_compression: Any = None):
        self.layers = list(layers)
        self.user_loss_fn = loss_fn
        self.num_microbatches = num_microbatches
        self.partition_method = partition_method
        self.checkpoint_ticks = checkpoint_ticks
        # stage-boundary activations move as int8/fp8 codes + block scales
        # (comm/collectives/compressed.ppermute) instead of full-width fp;
        # same knob surface as pipeline.hop_compression on the transformer
        # pipe path (docs/PIPELINE.md).  EF residual state needs the engine's
        # comm_errors lifecycle, so the generic module keeps the stateless
        # verb (backward hop compressed per spec.compress_backward).
        if hop_compression:
            from ...comm.collectives.codec import CompressionSpec
            self.hop_spec = (hop_compression
                             if isinstance(hop_compression, CompressionSpec)
                             else CompressionSpec.parse(hop_compression))
        else:
            self.hop_spec = None
        topo = get_topology()
        self.num_stages = num_stages or topo.pipe_parallel_size
        if topo.pipe_parallel_size not in (1, self.num_stages):
            raise ValueError(
                f"num_stages {self.num_stages} != mesh pipe axis "
                f"{topo.pipe_parallel_size}")
        if len(self.layers) < self.num_stages:
            raise ValueError(f"{len(self.layers)} layers < {self.num_stages} stages")
        del seed_layers  # reference arg, rng handling is explicit here
        self._partition()

    # -- partitioning (reference _partition_layers, pipe/module.py:393) ------
    def _layer_weight(self, spec: LayerSpec) -> float:
        if spec.init_fn is None:
            return 0.0
        shapes = jax.eval_shape(spec.init, jax.random.PRNGKey(0))
        return float(sum(int(np.prod(l.shape)) for l in
                         jax.tree_util.tree_leaves(shapes)))

    def _partition(self) -> None:
        method = self.partition_method.lower()
        n, parts = len(self.layers), self.num_stages
        if method == "uniform":
            bounds = [round(i * n / parts) for i in range(parts + 1)]
        elif method == "parameters":
            bounds = partition_balanced(
                [self._layer_weight(s) + 1.0 for s in self.layers], parts)
        elif method.startswith("type:"):
            regex = method.split(":", 1)[1]
            marks = [1.0 if re.search(regex, s.name or type(s).__name__,
                                      re.IGNORECASE) else 0.0
                     for s in self.layers]
            bounds = partition_balanced([m + 1e-6 for m in marks], parts)
        else:
            raise ValueError(f"unknown partition_method {self.partition_method}")
        self.bounds = bounds
        self.groups: List[List[LayerSpec]] = [
            self.layers[bounds[i]:bounds[i + 1]] for i in range(parts)]
        logger.info(f"PipelineModule: {n} layers over {parts} stages, "
                    f"bounds={bounds} ({self.partition_method})")

    # -- init ----------------------------------------------------------------
    def _split_tied(self):
        tied_inits = {}
        for spec in self.layers:
            if isinstance(spec, TiedLayerSpec) and spec.key not in tied_inits:
                tied_inits[spec.key] = spec.init_fn
        return tied_inits

    def _group_tree_struct(self, group, rng):
        return jax.eval_shape(
            lambda r: tuple(s.init(k) for s, k in
                            zip(group, jax.random.split(r, max(len(group), 1)))
                            if not isinstance(s, TiedLayerSpec)), rng)

    @property
    def stackable(self) -> bool:
        """Per-stage groups structurally identical -> stack over 'pipe'."""
        if getattr(self, "_stackable", None) is None:
            rng = jax.random.PRNGKey(0)
            structs = [self._group_tree_struct(g, rng) for g in self.groups]
            first = jax.tree_util.tree_structure(structs[0])
            leaves0 = jax.tree_util.tree_leaves(structs[0])
            ok = all(
                jax.tree_util.tree_structure(s) == first and
                all(a.shape == b.shape and a.dtype == b.dtype
                    for a, b in zip(jax.tree_util.tree_leaves(s), leaves0))
                for s in structs[1:])
            self._stackable = ok
            if not ok:
                logger.info(
                    "PipelineModule: per-stage layer groups are not "
                    "structurally identical; flat-packing each stage's "
                    "params into pipe-sharded per-dtype vectors")
        return self._stackable

    # -- heterogeneous stage-local placement (flat-pack) ---------------------
    @functools.cached_property
    def _flat_meta(self):
        """Static per-stage layout for the flat-packed representation:
        for each stage, the non-tied group treedef plus, per dtype, the
        (offset, shape) of every leaf inside that dtype's packed vector."""
        rng = jax.random.PRNGKey(0)
        metas = []
        maxlen: dict = {}
        for group in self.groups:
            struct = self._group_tree_struct(group, rng)
            leaves, treedef = jax.tree_util.tree_flatten(struct)
            offsets = {}
            layout = []
            for leaf in leaves:
                dt = str(jnp.dtype(leaf.dtype))
                off = offsets.get(dt, 0)
                size = int(np.prod(leaf.shape)) if leaf.shape else 1
                layout.append((dt, off, leaf.shape, jnp.dtype(leaf.dtype)))
                offsets[dt] = off + size
            metas.append({"treedef": treedef, "layout": layout})
            for dt, ln in offsets.items():
                maxlen[dt] = max(maxlen.get(dt, 0), ln)
        return metas, maxlen

    def _flat_pack(self, group_trees):
        """[per-stage param tuples] -> {dtype: [num_stages, maxlen]}."""
        metas, maxlen = self._flat_meta
        stacked = {}
        for dt, ln in maxlen.items():
            rows = []
            for g, tree in enumerate(group_trees):
                leaves = jax.tree_util.tree_leaves(tree)
                segs = [jnp.ravel(l) for l, (d, _, _, _) in
                        zip(leaves, metas[g]["layout"]) if d == dt]
                vec = (jnp.concatenate(segs) if segs
                       else jnp.zeros((0,), jnp.dtype(dt)))
                pad = ln - vec.shape[0]
                if pad:
                    vec = jnp.concatenate([vec, jnp.zeros((pad,), vec.dtype)])
                rows.append(vec)
            stacked[dt] = jnp.stack(rows)
        return stacked

    def _flat_unpack(self, g: int, flat_row):
        """One stage's {dtype: [maxlen]} view -> that stage's param tuple.
        All offsets/shapes are static, so this is free slicing under jit."""
        metas, _ = self._flat_meta
        meta = metas[g]
        leaves = []
        for dt, off, shape, dtype in meta["layout"]:
            size = int(np.prod(shape)) if shape else 1
            leaves.append(jax.lax.slice(flat_row[dt], (off,),
                                        (off + size,)).reshape(shape))
        return jax.tree_util.tree_unflatten(meta["treedef"], leaves)

    def _stage_group_params(self, params, g: int, local: bool = False):
        """Stage ``g``'s non-tied layer params from either representation.
        ``local``: params are a shard_map per-device view (leading pipe dim
        is 1, holding exactly this device's stage)."""
        if self.stackable:
            return jax.tree_util.tree_map(
                lambda a: a[0 if local else g], params["stages"])
        flat = params["stages_flat"]
        row = {dt: v[0 if local else g] for dt, v in flat.items()}
        return self._flat_unpack(g, row)

    def init_params(self, rng) -> Any:
        tied_inits = self._split_tied()
        keys = jax.random.split(rng, len(self.layers) + len(tied_inits))
        group_trees = []
        ki = 0
        for group in self.groups:
            layers_p = []
            for spec in group:
                if isinstance(spec, TiedLayerSpec):
                    ki += 1
                    continue  # tied params live in the shared subtree
                layers_p.append(spec.init(keys[ki]))
                ki += 1
            group_trees.append(tuple(layers_p))
        tied = {k: fn(keys[len(self.layers) + i]) if fn is not None else ()
                for i, (k, fn) in enumerate(tied_inits.items())}
        return self._pack_group_trees(group_trees, tied)

    def _pack_group_trees(self, group_trees, tied) -> Any:
        """Per-stage non-tied layer tuples -> the params tree in this
        module's representation (stacked or flat-packed)."""
        if self.stackable:
            stages = jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *group_trees)
            return {"stages": stages, "tied": tied}
        return {"stages_flat": self._flat_pack(group_trees), "tied": tied}

    def partition_rules(self) -> List[Tuple[str, P]]:
        if self.stackable:
            return [(r"^stages/", P(PIPE_AXIS))]
        return [(r"^stages_flat/", P(PIPE_AXIS))]

    # -- stage-count resharding (reference 3D reshape: checkpoint's
    # reshape_3d_utils regroups pp stages; here the per-LAYER canonical
    # view converts between any two stage partitionings) ---------------
    def export_layer_params(self, params) -> List[Any]:
        """Params in GLOBAL layer order (one entry per LayerSpec; ``None``
        for tied layers, whose params live in the shared subtree)."""
        out: List[Any] = []
        for g in range(self.num_stages):
            it = iter(self._stage_group_params(params, g))
            for spec in self.groups[g]:
                out.append(None if isinstance(spec, TiedLayerSpec)
                           else next(it))
        return out

    def import_layer_params(self, layer_params: List[Any], tied) -> Any:
        """Inverse of ``export_layer_params`` under THIS module's
        partitioning (stage count / bounds may differ from the source)."""
        if len(layer_params) != len(self.layers):
            raise ValueError(f"{len(layer_params)} layer params for "
                             f"{len(self.layers)} layers")
        group_trees, idx = [], 0
        for group in self.groups:
            layers_p = []
            for spec in group:
                lp = layer_params[idx]
                idx += 1
                if isinstance(spec, TiedLayerSpec):
                    continue
                layers_p.append(lp)
            group_trees.append(tuple(layers_p))
        return self._pack_group_trees(group_trees, tied)

    @staticmethod
    def reshard_params(src: "PipelineModule", params, dst: "PipelineModule"):
        """Convert ``params`` trained under ``src``'s stage partitioning to
        ``dst``'s (e.g. pipe=2 -> pipe=4 on a resized cluster).  The layer
        lists must describe the same model; tied params pass through."""
        if len(src.layers) != len(dst.layers):
            raise ValueError("src/dst pipeline modules have different "
                             f"layer counts ({len(src.layers)} vs "
                             f"{len(dst.layers)})")
        for i, (a, b) in enumerate(zip(src.layers, dst.layers)):
            ta, tb = isinstance(a, TiedLayerSpec), isinstance(b, TiedLayerSpec)
            if ta != tb or (ta and a.key != b.key):
                # a tie mismatch would silently swap a trained weight for
                # the shared one (or desync every later layer's params)
                raise ValueError(
                    f"layer {i} tie structure differs between src and dst "
                    f"({'tied:' + a.key if ta else 'untied'} vs "
                    f"{'tied:' + b.key if tb else 'untied'})")
        return dst.import_layer_params(src.export_layer_params(params),
                                       params["tied"])

    # -- forward -------------------------------------------------------------
    def _apply_group(self, g: int, group_params, tied, x):
        """Run stage g's layers sequentially.  group_params: tuple of
        non-tied layer params in group order."""
        it = iter(group_params)
        for spec in self.groups[g]:
            p = tied[spec.key] if isinstance(spec, TiedLayerSpec) else next(it)
            x = spec.apply_fn(p, x)
        return x

    def _dense_loss(self, params, xs, ys):
        x = xs
        for g in range(self.num_stages):
            x = self._apply_group(g, self._stage_group_params(params, g),
                                  params["tied"], x)
        return self.user_loss_fn(x, ys)

    def _ring_struct(self, params, xs_micro, local: bool = False):
        """Shape/dtype of the stage-boundary activation (output of group 0 on
        one micro-batch); validates groups 0..P-2 agree.  ``local``: params
        are a shard_map view (stacked leading dim is 1, not num_stages)."""
        def run_to(g_end, x):
            for g in range(g_end + 1):
                x = self._apply_group(
                    g, self._stage_group_params(params, g, local=local),
                    params["tied"], x)
            return x

        shapes = [jax.eval_shape(functools.partial(run_to, g), xs_micro)
                  for g in range(self.num_stages - 1)]
        for g, s in enumerate(shapes[1:], 1):
            if s.shape != shapes[0].shape or s.dtype != shapes[0].dtype:
                raise ValueError(
                    f"pipeline stage boundaries must share one activation "
                    f"shape: stage 0 -> {shapes[0].shape}, stage {g} -> "
                    f"{s.shape}.  Regroup layers (partition_method) or pad.")
        return shapes[0]

    def _pipe_body(self, params, xs, ys, *, pp: int):
        stage = jax.lax.axis_index(PIPE_AXIS)
        M = self.num_microbatches
        b = xs.shape[0] // M  # xs here is the LOCAL batch shard
        xs_mb = xs.reshape(M, b, *xs.shape[1:])
        ys_mb = ys.reshape(M, b, *ys.shape[1:])
        tied = params["tied"]
        ring = self._ring_struct(
            params, jax.ShapeDtypeStruct((b, *xs.shape[1:]), xs.dtype),
            local=True)
        ring_shape, ring_dtype = ring.shape, ring.dtype

        def local_group_params(g: int):
            # the local pipe shard [1, ...] IS this stage's group; branch g
            # interprets it with stage g's (static) layout
            return self._stage_group_params(params, g, local=True)

        # every switch branch returns one pytree: (ring buffer, last-stage
        # output).  Only the executed branch pays its group's compute: embed
        # runs on stage 0 only, head+loss on the last stage only.
        last_struct = jax.eval_shape(
            lambda x: self._apply_group(pp - 1, local_group_params(pp - 1),
                                        tied, x),
            jax.ShapeDtypeStruct(ring_shape, ring_dtype))

        def branch(g: int, x_in, buf):
            out = self._apply_group(g, local_group_params(g),
                                    tied, x_in if g == 0 else buf)
            if g == pp - 1:
                # the last group's output feeds only the loss; its ring slot
                # is dead (stage 0 injects over it after the permute)
                return jnp.zeros(ring_shape, ring_dtype), out
            return (out.astype(ring_dtype),
                    jnp.zeros(last_struct.shape, last_struct.dtype))

        branches = [functools.partial(branch, g) for g in range(pp)]
        perm = tuple((i, (i + 1) % pp) for i in range(pp))
        T = M + pp - 1
        hop_spec = self.hop_spec
        if hop_spec is not None:
            from ...comm.collectives import compressed as _cc

        def tick(carry, t):
            buf, loss_acc = carry
            x_in = xs_mb[jnp.minimum(t, M - 1)]
            ring, out = jax.lax.switch(stage, branches, x_in, buf)
            mb_out = t - (pp - 1)
            valid = jnp.logical_and(stage == pp - 1,
                                    jnp.logical_and(mb_out >= 0, mb_out < M))
            y = ys_mb[jnp.clip(mb_out, 0, M - 1)]
            # RANK-1 [1] accumulator: grad partial-eval saves known-side
            # scalars as residuals, and the check_vma=False shard_map
            # transpose stacks residuals over a leading device dim —
            # rank-0 residuals fail its spec check (broke every pipe
            # backward before PR 16; see runtime/pipe/engine.py)
            loss_t = jax.lax.cond(
                valid,
                lambda: self.user_loss_fn(out, y).astype(jnp.float32).reshape(1),
                lambda: jnp.zeros((1,), jnp.float32))
            if hop_spec is not None:
                buf = _cc.ppermute(ring, perm, PIPE_AXIS, hop_spec)
            else:
                buf = jax.lax.ppermute(ring, PIPE_AXIS, perm)
            return (buf, loss_acc + loss_t), None

        buf0 = jnp.zeros(ring_shape, ring_dtype)
        # 1F1B-equivalent memory bound: remat the tick so the scan's
        # backward saves only the O(ring) carry per tick and recomputes one
        # tick's layer internals at a time — residuals don't scale with M
        # (reference TrainSchedule, pipe/schedule.py:189).  prevent_cse is
        # unnecessary inside scan and would only block fusion.
        tick_fn = (jax.checkpoint(tick, prevent_cse=False)
                   if self.checkpoint_ticks else tick)
        (_, loss), _ = jax.lax.scan(
            tick_fn, (buf0, jnp.zeros((1,), jnp.float32)), jnp.arange(T))
        loss = jax.lax.psum(loss, PIPE_AXIS) / M
        for ax in BATCH_AXES:
            loss = jax.lax.pmean(loss, ax)
        return loss[0]

    def loss_fn(self, params, batch, rng=None):
        del rng
        if isinstance(batch, dict):
            xs, ys = batch["inputs"], batch["labels"]
        else:
            xs, ys = batch
        topo = get_topology()
        pp = topo.pipe_parallel_size
        if pp == 1:
            return self._dense_loss(params, xs, ys)
        if pp != self.num_stages:
            raise ValueError(f"mesh pipe={pp} != num_stages={self.num_stages}")
        M = self.num_microbatches
        shards = 1
        for ax in BATCH_AXES:
            shards *= topo.axis_size(ax)
        if xs.shape[0] % shards != 0 or (xs.shape[0] // shards) % M != 0:
            raise ValueError(
                f"batch dim {xs.shape[0]} must divide into {shards} "
                f"data shards x num_microbatches {M} (local micro-batch "
                f"size must be a positive integer)")

        from ..zero.strategy import ZeroShardingPlan

        plan = ZeroShardingPlan(topo, None, self.partition_rules())
        param_specs = plan.tree_specs(params, "param")
        body = functools.partial(self._pipe_body, pp=pp)
        data_spec = P(BATCH_AXES, *([None] * (xs.ndim - 1)))
        label_spec = P(BATCH_AXES, *([None] * (ys.ndim - 1)))
        fn = shard_map(body, mesh=topo.mesh,
                       in_specs=(param_specs, data_spec, label_spec),
                       out_specs=P(), check_vma=False)
        return fn(params, xs, ys)

    def to_model_spec(self) -> ModelSpec:
        spec = ModelSpec(init_params=self.init_params, loss_fn=self.loss_fn,
                         partition_rules=self.partition_rules())
        spec.num_microbatches = self.num_microbatches
        spec.pipeline_module = self
        return spec
