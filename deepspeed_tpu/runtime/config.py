"""DeepSpeed-compatible JSON configuration.

Accepts the same ``ds_config.json`` surface as the reference
(``deepspeed/runtime/config.py``): the batch-size triangle
(train_batch_size = micro_batch * grad_accum * dp_world_size), optimizer /
scheduler blocks, fp16/bf16 blocks, zero_optimization, and the feature
sub-configs.  TPU-specific additions live under the ``"mesh"`` key
(axis sizes for data/model/pipe/sequence/expert parallelism).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, Optional

from .config_utils import AUTO, ConfigModel
from ..serving.config import ServingConfig
from ..utils.logging import logger

TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"


@dataclasses.dataclass
class FP16Config(ConfigModel):
    enabled: bool = False
    auto_cast: bool = False
    loss_scale: float = 0.0  # 0 => dynamic
    initial_scale_power: int = 16
    loss_scale_window: int = 1000
    hysteresis: int = 2
    consecutive_hysteresis: bool = False
    min_loss_scale: float = 1.0


@dataclasses.dataclass
class BF16Config(ConfigModel):
    enabled: bool = False
    # Keep a master fp32 copy of params for the optimizer (reference
    # BF16_Optimizer semantics, runtime/bf16_optimizer.py:35).
    master_weights: bool = True


@dataclasses.dataclass
class OffloadConfig(ConfigModel):
    """Param/optimizer offload target (reference zero/offload_config.py)."""

    device: str = "none"  # none | cpu | nvme
    nvme_path: str = "/tmp/dstpu_nvme"
    pin_memory: bool = True
    buffer_count: int = 5
    buffer_size: int = 100_000_000
    ratio: float = 1.0
    max_in_cpu: int = 1_000_000_000
    # SuperOffload (reference runtime/superoffload/): fan the host Adam out
    # over a pool of CPU optimizer workers
    super_offload: bool = False
    cpu_worker_count: int = 4

    @property
    def enabled(self) -> bool:
        return self.device not in ("none", None)

    def validate(self) -> None:
        if self.super_offload and not self.enabled:
            raise ValueError("super_offload requires offload_optimizer.device="
                             "'cpu' (or 'nvme'); got device='none'")


@dataclasses.dataclass
class ZenFlowConfig(ConfigModel):
    """zenflow block inside zero_optimization (reference
    runtime/zenflow/zenflow_config.py:12)."""

    enabled: bool = False
    topk_ratio: float = 0.1  # fraction of columns on the immediate fast path
    update_interval: int = 4  # deferred CPU pass cadence (boundaries)
    full_warm_up_rounds: int = 0  # full synchronous updates first
    overlap_step: bool = True  # run the deferred pass in a background thread

    def validate(self) -> None:
        if not (0.0 < self.topk_ratio <= 1.0):
            raise ValueError(f"topk_ratio must be in (0, 1], got {self.topk_ratio}")
        if self.update_interval < 1:
            raise ValueError("update_interval must be >= 1")


@dataclasses.dataclass
class ZeroConfig(ConfigModel):
    """zero_optimization block (reference zero/config.py).

    Accepted-but-delegated knobs: ``reduce_bucket_size`` /
    ``allgather_bucket_size`` / ``overlap_comm`` / ``contiguous_gradients``
    / ``round_robin_gradients`` / ``stage3_prefetch_bucket_size`` /
    ``stage3_max_live_parameters`` / ``stage3_max_reuse_distance`` /
    ``sub_group_size`` exist in the reference because its hook-driven
    runtime hand-schedules buckets, overlap, and prefetch.  Here the
    collectives are compiled into the step program and the XLA
    latency-hiding scheduler owns those decisions — the keys are accepted
    for config compatibility and carry no behavior.  Knobs that DO reach
    mechanisms: ``stage``, ``offload_param`` / ``offload_optimizer``,
    ``stage3_param_persistence_threshold``, ``zero_quantized_weights`` /
    ``zero_quantized_gradients`` / ``zero_hpz_partition_size``,
    ``mics_shard_size``, ``zenflow``."""

    stage: int = 0
    overlap_comm: bool = True
    contiguous_gradients: bool = True
    reduce_scatter: bool = True
    reduce_bucket_size: int = 500_000_000
    allgather_bucket_size: int = 500_000_000
    offload_param: OffloadConfig = dataclasses.field(default_factory=OffloadConfig)
    offload_optimizer: OffloadConfig = dataclasses.field(default_factory=OffloadConfig)
    sub_group_size: int = 1_000_000_000
    stage3_max_live_parameters: int = 1_000_000_000
    stage3_max_reuse_distance: int = 1_000_000_000
    stage3_prefetch_bucket_size: int = 50_000_000
    # Params at or below this many elements keep an unpartitioned live copy
    # at stage 3 (reference persistence_threshold, default 1e5 there because
    # every fetch pays fixed Python-hook + NCCL-launch overhead).  Default 0
    # here: XLA compiles per-layer gathers into the step with no per-op
    # launch cost, so persistence is purely an opt-in memory/latency trade.
    stage3_param_persistence_threshold: int = 0
    stage3_gather_16bit_weights_on_model_save: bool = False
    #: MANUAL stage-3 prefetch: run the layer scan 2x-unrolled
    #: (models/transformer.py) so consecutive layers' param gathers and
    #: compute can overlap, instead of leaving scheduling slack entirely
    #: to XLA.  With the overlap wrap active (it is, whenever this knob
    #: or overlap_grad_reduce is on and the model supports it), the
    #: gathers are EXPLICIT in-loop collectives issued at the body top
    #: (runtime/zero/overlap.py) — the unrolled pair of gather->compute
    #: chains is the double buffer.  Off by default — A/B on hardware
    #: (bench STAGE=3 PREFETCH=1) decides; the reference's analogue is
    #: the PartitionedParameterCoordinator prefetch.
    zero3_param_prefetch: bool = False
    #: issue each layer-bucket's gradient reduce inside the BACKWARD
    #: scan, as soon as the bucket's cotangents materialize
    #: (runtime/zero/overlap.py custom_vjp hook; Domino-style — the
    #: collective rides the dataflow graph, no post-backward block).
    #: Scheduling only: bit-exact with the unbucketed path, A/B'd by
    #: ``bench.py --ab-overlap``.  Needs a models/* transformer.  With
    #: qgZ (or ``overlap_compression``) also set, the in-loop exchange
    #: itself compresses — docs/COMM.md "Compressed overlap"; with
    #: ``overlap_compression: false`` the wrap stands down under qgZ /
    #: hierarchical and those bucketed explicit reducers own the
    #: exchange (see overlap_bucket_mb).
    overlap_grad_reduce: bool = False
    #: compress the IN-LOOP bucketed gradient exchange (docs/COMM.md
    #: "Compressed overlap"): None (default) derives it — int8 +
    #: error feedback when ``zero_quantized_gradients`` is also on,
    #: exact fp otherwise; "int8"/"fp8" or a CompressionSpec kwargs
    #: dict forces a codec (error_feedback defaults ON for this path —
    #: pass {"format": ..., "error_feedback": false} to drop the
    #: residual); False forces the exact fp exchange even under qgZ
    #: (the wrap then stands down and qgZ keeps its post-backward
    #: bucketed reduce).  Residuals live in TrainState.comm_errors —
    #: ONE per bucket — and survive checkpoint/preemption-resume.
    overlap_compression: Any = None
    #: size target (MB) for the ONE shared bucketer
    #: (comm/collectives/bucketer.py): the overlap hook's per-layer
    #: reduce groups AND the leaf coalescing inside the explicit
    #: compressed reducers (qgZ / hierarchical — one collective and one
    #: error-feedback residual per bucket).  0 = per-leaf (no
    #: coalescing, the pre-bucketing behavior).
    overlap_bucket_mb: float = 4.0
    # ZeRO++ style knobs: quantized weight gather / hierarchical partition
    zero_quantized_weights: bool = False
    zero_quantized_gradients: bool = False
    zero_hpz_partition_size: int = 1
    #: hierarchical two-hop gradient reduce (comm/collectives/hierarchical):
    #: intra-slice reduce-scatter -> inter-slice exchange -> intra-slice
    #: all-gather over a split of the data axis.  With
    #: zero_quantized_gradients also on, the inter-slice hop moves int8
    #: codes + block scales (the ZeRO++ 4x cross-slice reduction shape).
    zero_hierarchical_grad_reduce: bool = False
    #: intra-slice group size for that split (0 = auto:
    #: utils/groups.hierarchy_split — local device count, else ~sqrt)
    zero_hierarchy_inner: int = 0
    #: error feedback on the POST-BACKWARD qgZ / hierarchical gradient
    #: reduce (the path that runs when the in-loop overlap wrap is off
    #: or unsupported): per-bucket residuals carried in
    #: TrainState.comm_errors["reduce"], so checkpoint/resume keeps
    #: them (docs/COMM.md).  Off by default — it changes the reduce's
    #: numerics vs HEAD (convergence improves, bit-compat breaks).
    grad_reduce_error_feedback: bool = False
    # MiCS-style replica-group sharding: shard within groups of this size,
    # replicate across groups (reference zero/mics.py).
    mics_shard_size: int = -1
    mics_hierarchical_params_gather: bool = False
    round_robin_gradients: bool = False
    ignore_unused_parameters: bool = True
    # ZenFlow stall-free offload (reference runtime/zenflow/zenflow_config.py)
    zenflow: ZenFlowConfig = dataclasses.field(default_factory=ZenFlowConfig)

    def validate(self) -> None:
        if self.stage not in (0, 1, 2, 3):
            raise ValueError(f"zero_optimization.stage must be 0-3, got {self.stage}")
        if self.overlap_bucket_mb < 0:
            raise ValueError("zero_optimization.overlap_bucket_mb must be "
                             f">= 0, got {self.overlap_bucket_mb}")
        if self.overlap_compression not in (None, False):
            from ..comm.collectives.codec import CompressionSpec

            try:
                CompressionSpec.parse(self.overlap_compression)
            except (TypeError, ValueError) as e:
                raise ValueError(
                    f"zero_optimization.overlap_compression: {e}") from e

    @classmethod
    def deprecated_fields(cls):
        return {"cpu_offload": "offload_optimizer"}


@dataclasses.dataclass
class OptimizerConfig(ConfigModel):
    type: str = "adamw"
    params: Dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class SchedulerConfig(ConfigModel):
    type: Optional[str] = None
    params: Dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class MeshConfig(ConfigModel):
    """TPU mesh axis sizes. -1 on ``data`` means 'all remaining devices'."""

    pipe: int = 1
    # MiCS replica groups (zero_optimization.mics_shard_size sets data and
    # lets repl absorb the rest): ZeRO shards within 'data', replicates
    # across 'repl'
    repl: int = 1
    data: int = -1
    expert: int = 1
    sequence: int = 1
    model: int = 1
    # How ICI/DCN axes are stacked for multi-slice: 'ici_major' keeps model/
    # sequence axes on the fastest links.
    axis_order: str = "pipe,repl,data,expert,sequence,model"


@dataclasses.dataclass
class PipelineConfig(ConfigModel):
    """``pipeline`` block: knobs for the scan-based pipe schedule
    (runtime/pipe/, docs/PIPELINE.md).

    ``hop_compression`` puts the per-tick activation ``ppermute`` (and
    its backward-wave transpose) on a quantized wire — "int8"/"fp8", a
    dict ({"format", "block", "error_feedback", "compress_backward"}),
    or None/False for the exact fp hop.  Error feedback on the backward
    hop defaults ON (residuals live in ``TrainState.comm_errors["pipe"]``
    and follow the checkpoint/donation lifecycle contract); pass
    ``{"error_feedback": false}`` explicitly to run straight-through.
    """

    hop_compression: Any = None

    def validate(self) -> None:
        if self.hop_compression not in (None, False):
            from ..comm.collectives.codec import CompressionSpec

            try:
                CompressionSpec.parse(self.hop_compression)
            except (TypeError, ValueError) as e:
                raise ValueError(f"pipeline.hop_compression: {e}") from e


@dataclasses.dataclass
class ActivationCheckpointingConfig(ConfigModel):
    partition_activations: bool = False
    cpu_checkpointing: bool = False
    contiguous_memory_optimization: bool = False
    number_checkpoints: Optional[int] = None
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False
    # TPU-native: jax.remat policy name (see runtime/activation_checkpointing)
    policy: str = "nothing_saveable"


@dataclasses.dataclass
class FlopsProfilerConfig(ConfigModel):
    enabled: bool = False
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None


@dataclasses.dataclass
class StallWatchdogConfig(ConfigModel):
    """stall_watchdog sub-block of ``telemetry``: flag steps exceeding
    ``multiple`` x the rolling median over the last ``window`` steps."""

    enabled: bool = True
    multiple: float = 3.0
    window: int = 32

    def validate(self) -> None:
        if self.multiple <= 1.0:
            raise ValueError(f"stall_watchdog.multiple must be > 1, "
                             f"got {self.multiple}")
        if self.window < 2:
            raise ValueError("stall_watchdog.window must be >= 2")


@dataclasses.dataclass
class SpanTraceConfig(ConfigModel):
    """``spans`` sub-block of ``telemetry``: the host-side span ring
    (telemetry/spans.py) feeding Chrome-trace dumps and the flight
    recorder.  ``profiler_annotations`` nests each span in a
    ``jax.profiler.TraceAnnotation`` so XProf captures carry the same
    names."""

    enabled: bool = True
    ring_size: int = 4096
    profiler_annotations: bool = True

    def validate(self) -> None:
        if self.ring_size < 16:
            raise ValueError("telemetry.spans.ring_size must be >= 16")


@dataclasses.dataclass
class FlightRecorderConfig(ConfigModel):
    """``flight_recorder`` sub-block of ``telemetry``: dump the span
    ring + recent log events + a registry snapshot to a timestamped
    JSONL on exception-in-step, watchdog trip, or demand (``path`` is
    the dump DIRECTORY, default ./flight_recorder)."""

    enabled: bool = True
    path: str = ""
    events: int = 256

    def validate(self) -> None:
        if self.events < 16:
            raise ValueError("telemetry.flight_recorder.events must be >= 16")


@dataclasses.dataclass
class RecompileSentinelConfig(ConfigModel):
    """``recompile_sentinel`` sub-block of ``telemetry``: count XLA
    compiles per step (telemetry/compile_sentinel.py) and warn when a
    step recompiles after ``steady_after`` steady steps with unchanged
    arg shapes."""

    enabled: bool = True
    steady_after: int = 3

    def validate(self) -> None:
        if self.steady_after < 0:
            raise ValueError(
                "telemetry.recompile_sentinel.steady_after must be >= 0")


@dataclasses.dataclass
class MemoryLedgerConfig(ConfigModel):
    """``memory`` sub-block of ``telemetry``: the HBM memory ledger
    (telemetry/memory.py).  When enabled the engines attribute device
    bytes to named components (params / master params / grads /
    optimizer state / KV pool), track per-phase peak watermarks off the
    span enters/exits, and upgrade RESOURCE_EXHAUSTED step failures to
    OOM incident reports through the flight recorder.
    ``top_buffers`` bounds the live-buffer table in an incident."""

    enabled: bool = True
    top_buffers: int = 10

    def validate(self) -> None:
        if self.top_buffers < 1:
            raise ValueError("telemetry.memory.top_buffers must be >= 1")


@dataclasses.dataclass
class TimelineConfig(ConfigModel):
    """``timeline`` sub-block of ``telemetry``: measured step-time
    attribution (telemetry/timeline.py).  Every ``every_n_steps`` the
    engine captures a ``jax.profiler`` trace of ONE step and publishes
    the ``deepspeed_tpu_timeline_*`` decomposition (0 = no periodic
    captures; one-shot captures via ``engine.capture_timeline()`` /
    bench stamps still work).  ``artifact_dir`` receives one merged
    host-span + device-op Chrome-trace file per capture ("" = no
    artifact files)."""

    enabled: bool = True
    every_n_steps: int = 0
    artifact_dir: str = ""

    def validate(self) -> None:
        if self.every_n_steps < 0:
            raise ValueError(
                "telemetry.timeline.every_n_steps must be >= 0")


@dataclasses.dataclass
class GoodputConfig(ConfigModel):
    """``goodput`` sub-block of ``telemetry``: the run-level goodput /
    badput ledger (telemetry/goodput.py).  ``run_file`` is the
    cross-attempt union ledger for preempted runs; when left "" on a
    resilient engine it defaults into the resilience ``save_dir`` so a
    relaunched attempt attributes recomputed steps to restart badput."""

    enabled: bool = True
    run_file: str = ""


@dataclasses.dataclass
class NumericsConfig(ConfigModel):
    """``numerics`` sub-block of ``telemetry``: the numerics observatory
    (telemetry/numerics.py; docs/OBSERVABILITY.md "Numerics
    observatory").  When enabled the fused train step carries per-layer
    / per-leaf health stats (grad/param norm, max-abs, nonfinite count,
    EF-residual norm per comm slot, loss-scale state) as EXTRA DEVICE
    OUTPUTS, pulled only at the ``steps_per_print`` boundary where the
    anomaly sentinel runs its detectors.  ``activation_stats``
    additionally threads a ``[L, 3]`` activation-health side output
    through the transformer layer scan (per-stage through the pipe
    scan).  The divergence audit checksums master params across the
    data axis every ``divergence_every``-th boundary (ZeRO stage <= 1
    only — ranks must be bit-identical there; higher stages skip it).

    Detector knobs: spikes fire when the boundary value exceeds
    ``*_factor`` x the rolling median of the last ``history`` healthy
    boundaries (armed after ``min_history``); ``overflow_storm`` is the
    skipped-step delta between boundaries that rates as a storm;
    ``stagnant_boundaries``/``stagnant_tol`` flag a loss pinned within
    tolerance for that many consecutive boundaries (0 disables)."""

    enabled: bool = False
    activation_stats: bool = True
    history: int = 64
    min_history: int = 8
    loss_spike_factor: float = 3.0
    grad_spike_factor: float = 10.0
    overflow_storm: int = 3
    stagnant_boundaries: int = 8
    stagnant_tol: float = 0.0
    divergence_audit: bool = True
    divergence_every: int = 1

    def validate(self) -> None:
        if self.history < 2:
            raise ValueError("telemetry.numerics.history must be >= 2")
        if self.min_history < 2:
            raise ValueError("telemetry.numerics.min_history must be >= 2")
        if self.loss_spike_factor <= 1.0 or self.grad_spike_factor <= 1.0:
            raise ValueError(
                "telemetry.numerics spike factors must be > 1")
        if self.overflow_storm < 1:
            raise ValueError("telemetry.numerics.overflow_storm must be >= 1")
        if self.stagnant_boundaries < 0 or self.stagnant_tol < 0:
            raise ValueError(
                "telemetry.numerics stagnant knobs must be >= 0")
        if self.divergence_every < 1:
            raise ValueError(
                "telemetry.numerics.divergence_every must be >= 1")


@dataclasses.dataclass
class TelemetryConfig(ConfigModel):
    """``telemetry`` block: the unified metrics registry + export paths
    (see deepspeed_tpu/telemetry/ and docs/OBSERVABILITY.md).

    ``enabled`` turns on registry collection in the engines; each export
    sink is then individually opt-in: ``prometheus_path`` rewrites a
    Prometheus textfile every ``export_interval`` steps,
    ``prometheus_port`` serves /metrics over HTTP (0 = off),
    ``jsonl_path`` appends snapshot events to a JSON-lines log.
    ``trace_annotations`` wraps steps in ``jax.profiler`` step/phase
    annotations (no-op without a live profiler capture).  ``spans``,
    ``flight_recorder``, ``recompile_sentinel`` and ``memory`` configure
    the timeline/memory side (all default-on once ``enabled`` is set;
    see docs/OBSERVABILITY.md "Tracing & flight recorder" and "Memory
    ledger & OOM forensics")."""

    enabled: bool = False
    prometheus_path: str = ""
    prometheus_port: int = 0
    jsonl_path: str = ""
    export_interval: int = 10
    trace_annotations: bool = True
    stall_watchdog: StallWatchdogConfig = dataclasses.field(
        default_factory=StallWatchdogConfig)
    spans: SpanTraceConfig = dataclasses.field(
        default_factory=SpanTraceConfig)
    flight_recorder: FlightRecorderConfig = dataclasses.field(
        default_factory=FlightRecorderConfig)
    recompile_sentinel: RecompileSentinelConfig = dataclasses.field(
        default_factory=RecompileSentinelConfig)
    memory: MemoryLedgerConfig = dataclasses.field(
        default_factory=MemoryLedgerConfig)
    timeline: TimelineConfig = dataclasses.field(
        default_factory=TimelineConfig)
    goodput: GoodputConfig = dataclasses.field(
        default_factory=GoodputConfig)
    numerics: NumericsConfig = dataclasses.field(
        default_factory=NumericsConfig)

    def validate(self) -> None:
        if self.export_interval < 1:
            raise ValueError("telemetry.export_interval must be >= 1")
        if not (0 <= self.prometheus_port < 65536):
            raise ValueError(f"telemetry.prometheus_port out of range: "
                             f"{self.prometheus_port}")


@dataclasses.dataclass
class CommsLoggerConfig(ConfigModel):
    enabled: bool = False
    verbose: bool = False
    prof_all: bool = True
    debug: bool = False
    prof_ops: list = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class MonitorConfig(ConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedTPUJob"


@dataclasses.dataclass
class TensorBoardConfig(MonitorConfig):
    pass


@dataclasses.dataclass
class WandbConfig(ConfigModel):
    enabled: bool = False
    group: Optional[str] = None
    team: Optional[str] = None
    project: str = "deepspeed_tpu"


@dataclasses.dataclass
class CometConfig(ConfigModel):
    """Reference monitor/config.py CometConfig (comet_ml writer)."""

    enabled: bool = False
    samples_log_interval: int = 100
    project: Optional[str] = None
    workspace: Optional[str] = None
    api_key: Optional[str] = None
    experiment_name: Optional[str] = None
    experiment_key: Optional[str] = None
    online: Optional[bool] = None
    mode: Optional[str] = None


@dataclasses.dataclass
class CSVConfig(MonitorConfig):
    pass


@dataclasses.dataclass
class AIOConfig(ConfigModel):
    block_size: int = 1048576
    queue_depth: int = 8
    thread_count: int = 1
    single_submit: bool = False
    overlap_events: bool = True
    use_gds: bool = False


@dataclasses.dataclass
class CheckpointConfig(ConfigModel):
    tag_validation: str = "Warn"
    load_universal: bool = False
    use_node_local_storage: bool = False
    parallel_write_pipeline: bool = False
    async_save: bool = False
    writer: str = ""  # "" | nebula | datastates (async engine flavors)


@dataclasses.dataclass
class ResilienceConfig(ConfigModel):
    """``resilience`` block: preemption-aware emergency checkpoints,
    verified atomic commits, auto-resume and checkpoint-I/O retries
    (see ``deepspeed_tpu/resilience/`` and ``docs/RESILIENCE.md``).

    ``save_dir`` is both where emergency checkpoints go and where
    ``auto_resume`` looks for the latest *verified* checkpoint on
    engine startup.  ``keep_n`` bounds the committed tags kept on disk
    (partial ``tmp.*`` staging dirs are always garbage-collected).
    ``watch_signals`` installs SIGTERM/SIGINT handlers for the
    preemption watcher (off for embedded/test use — ``notify()`` still
    works)."""

    enabled: bool = False
    save_dir: str = ""
    auto_resume: bool = True
    emergency_save: bool = True
    keep_n: int = 3
    io_retries: int = 3
    io_retry_base_s: float = 0.1
    watch_signals: bool = True

    def validate(self) -> None:
        if self.keep_n < 1:
            raise ValueError(f"resilience.keep_n must be >= 1, got {self.keep_n}")
        if self.io_retries < 0:
            raise ValueError("resilience.io_retries must be >= 0")
        if self.enabled and (self.auto_resume or self.emergency_save) \
                and not self.save_dir:
            raise ValueError(
                "resilience.enabled with auto_resume/emergency_save needs "
                "resilience.save_dir (where checkpoints live)")


@dataclasses.dataclass
class HybridEngineConfig(ConfigModel):
    """hybrid_engine block (reference runtime/hybrid_engine.py config):
    RLHF-style flip-flopping between training and generation on one copy
    of the weights."""

    enabled: bool = False
    max_out_tokens: int = 512
    inference_tp_size: int = 1
    release_inference_cache: bool = False
    pin_parameters: bool = True
    tp_gather_partition_size: int = 8


@dataclasses.dataclass
class GradientCompressionConfig(ConfigModel):
    """1-bit / compressed-communication style gradient compression."""

    enabled: bool = False
    bits: int = 8  # int8 compressed allreduce over ICI
    error_feedback: bool = True


@dataclasses.dataclass
class DeepSpeedConfig:
    """Parsed top-level config.

    Mirrors reference ``DeepSpeedConfig`` (runtime/config.py): constructed
    from a dict or a json path, resolves the batch-size triangle against the
    data-parallel world size.
    """

    raw: Dict[str, Any]
    train_batch_size: Optional[int]
    train_micro_batch_size_per_gpu: Optional[int]
    gradient_accumulation_steps: Optional[int]
    steps_per_print: int
    gradient_clipping: float
    prescale_gradients: bool
    gradient_predivide_factor: float
    communication_data_type: Optional[str]
    seed: int
    wall_clock_breakdown: bool
    memory_breakdown: bool
    sanity_checks: bool
    dump_state: bool
    fp16: FP16Config
    bf16: BF16Config
    zero_config: ZeroConfig
    optimizer: OptimizerConfig
    scheduler: SchedulerConfig
    mesh: MeshConfig
    pipeline: PipelineConfig
    activation_checkpointing: ActivationCheckpointingConfig
    flops_profiler: FlopsProfilerConfig
    comms_logger: CommsLoggerConfig
    telemetry: TelemetryConfig
    tensorboard: TensorBoardConfig
    wandb: WandbConfig
    comet: CometConfig
    csv_monitor: CSVConfig
    aio: AIOConfig
    checkpoint: CheckpointConfig
    compression: GradientCompressionConfig
    hybrid_engine: HybridEngineConfig
    resilience: ResilienceConfig
    serving: ServingConfig
    zero_allow_untested_optimizer: bool
    gradient_accumulation_dtype: str

    def __init__(self, config: Any, dp_world_size: Optional[int] = None):
        if isinstance(config, str):
            with open(config) as f:
                config = json.load(f)
        if config is None:
            config = {}
        if not isinstance(config, dict):
            raise TypeError(f"config must be a dict or json path, got {type(config)}")
        self.raw = config

        g = config.get
        self.train_batch_size = _maybe_int(g(TRAIN_BATCH_SIZE))
        self.train_micro_batch_size_per_gpu = _maybe_int(g(TRAIN_MICRO_BATCH_SIZE_PER_GPU))
        self.gradient_accumulation_steps = _maybe_int(g(GRADIENT_ACCUMULATION_STEPS))
        self.steps_per_print = max(1, int(g("steps_per_print", 10) or 1))
        self.gradient_clipping = float(g("gradient_clipping", 0.0))
        self.prescale_gradients = bool(g("prescale_gradients", False))
        self.gradient_predivide_factor = float(g("gradient_predivide_factor", 1.0))
        self.communication_data_type = g("communication_data_type")
        self.seed = int(g("seed", 1234))
        self.wall_clock_breakdown = bool(g("wall_clock_breakdown", False))
        self.memory_breakdown = bool(g("memory_breakdown", False))
        # reference is_sanity_checks_enabled (engine.py:1119): opt-in NaN
        # guard — costs a host sync per step, so off by default
        self.sanity_checks = bool(g("sanity_checks", False))
        self.dump_state = bool(g("dump_state", False))
        self.zero_allow_untested_optimizer = bool(g("zero_allow_untested_optimizer", False))
        self.gradient_accumulation_dtype = g("data_types", {}).get(
            "grad_accum_dtype", "fp32") or "fp32"

        self.fp16 = FP16Config.from_dict(g("fp16"))
        self.bf16 = BF16Config.from_dict(g("bf16") or g("bfloat16"))
        self.zero_config = ZeroConfig.from_dict(g("zero_optimization"))
        self.optimizer = OptimizerConfig.from_dict(g("optimizer"))
        self.scheduler = SchedulerConfig.from_dict(g("scheduler"))
        self.mesh = MeshConfig.from_dict(g("mesh"))
        self.pipeline = PipelineConfig.from_dict(g("pipeline"))
        self.activation_checkpointing = ActivationCheckpointingConfig.from_dict(
            g("activation_checkpointing"))
        self.flops_profiler = FlopsProfilerConfig.from_dict(g("flops_profiler"))
        self.comms_logger = CommsLoggerConfig.from_dict(g("comms_logger"))
        self.telemetry = TelemetryConfig.from_dict(g("telemetry"))
        self.tensorboard = TensorBoardConfig.from_dict(g("tensorboard"))
        self.wandb = WandbConfig.from_dict(g("wandb"))
        self.comet = CometConfig.from_dict(g("comet"))
        self.csv_monitor = CSVConfig.from_dict(g("csv_monitor"))
        self.aio = AIOConfig.from_dict(g("aio"))
        self.checkpoint = CheckpointConfig.from_dict(g("checkpoint"))
        self.compression = GradientCompressionConfig.from_dict(g("gradient_compression"))
        self.hybrid_engine = HybridEngineConfig.from_dict(g("hybrid_engine"))
        self.resilience = ResilienceConfig.from_dict(g("resilience"))
        # fleet front tier (serving/config.py): router + replica pools;
        # parsed here so one ds-config json describes the whole process.
        # Nested blocks (serving.speculative, serving.kv_tier — the
        # tiered KV cache) coerce + validate inside ServingConfig.
        self.serving = ServingConfig.from_dict(g("serving"))

        if self.fp16.enabled and self.bf16.enabled:
            raise ValueError("fp16 and bf16 cannot both be enabled")

        if dp_world_size is not None:
            self.resolve_batch_size(dp_world_size)

    # -- batch-size triangle ------------------------------------------------
    def resolve_batch_size(self, dp_world_size: int) -> None:
        """Resolve train_batch = micro_batch * grad_accum * dp_world_size.

        Same rules as reference ``DeepSpeedConfig._configure_train_batch_size``:
        any two determine the third; one alone assumes the others are 1/derived;
        none => micro=1, gas=1.
        """
        tb, mb, gas = (self.train_batch_size, self.train_micro_batch_size_per_gpu,
                       self.gradient_accumulation_steps)
        if all(v is not None for v in (tb, mb, gas)):
            if tb != mb * gas * dp_world_size:
                raise ValueError(
                    f"Batch-size inconsistency: train_batch_size={tb} != "
                    f"micro({mb}) * gas({gas}) * dp({dp_world_size})")
        elif tb is not None and mb is not None:
            gas = tb // (mb * dp_world_size)
            if gas * mb * dp_world_size != tb:
                raise ValueError(
                    f"train_batch_size {tb} not divisible by micro*dp = {mb * dp_world_size}")
        elif tb is not None and gas is not None:
            mb = tb // (gas * dp_world_size)
            if mb * gas * dp_world_size != tb:
                raise ValueError(
                    f"train_batch_size {tb} not divisible by gas*dp = {gas * dp_world_size}")
        elif mb is not None:
            gas = gas or 1
            tb = mb * gas * dp_world_size
        elif tb is not None:
            mb = tb // dp_world_size
            gas = 1
            if mb * dp_world_size != tb:
                raise ValueError(f"train_batch_size {tb} not divisible by dp {dp_world_size}")
        else:
            mb, gas = 1, 1
            tb = mb * gas * dp_world_size
        self.train_batch_size, self.train_micro_batch_size_per_gpu = tb, mb
        self.gradient_accumulation_steps = gas

    # ----------------------------------------------------------------------
    @property
    def zero_enabled(self) -> bool:
        return self.zero_config.stage > 0

    @property
    def compute_dtype(self):
        import jax.numpy as jnp

        if self.bf16.enabled:
            return jnp.bfloat16
        if self.fp16.enabled:
            return jnp.float16
        return jnp.float32

    def print_config(self) -> None:
        logger.info(f"DeepSpeedTPU config: {json.dumps(self.raw, indent=2, default=str)}")


def _maybe_int(v: Any) -> Optional[int]:
    if v is None or v == AUTO:
        return None
    return int(v)
