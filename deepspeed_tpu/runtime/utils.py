"""Runtime utilities.

Reference parity: ``deepspeed/runtime/utils.py`` — ``see_memory_usage``,
``clip_grad_norm_``, flatten/unflatten helpers, partition helpers.  The
tensor-surgery helpers shrink drastically on TPU (pytrees + jnp do the
work); memory reporting reads the accelerator ABI.
"""

from __future__ import annotations

from typing import Any, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..accelerator import get_accelerator
from ..utils.logging import logger
from .precision import clip_by_global_norm, global_grad_norm

__all__ = ["see_memory_usage", "clip_grad_norm_", "flatten_tree",
           "unflatten_tree", "partition_uniform", "partition_balanced", "set_random_seed"]


def see_memory_usage(message: str, force: bool = False) -> None:
    """Log device + host memory (reference runtime/utils.py
    see_memory_usage, which prints torch.cuda stats + psutil).

    Re-homed onto the memory ledger: every call publishes the live
    ``deepspeed_tpu_memory_bytes_in_use`` / ``_peak_bytes_in_use`` /
    ``_bytes_limit`` gauges (no longer silently a no-op when
    ``force=False``); ``force`` only gates the LOG LINE, whose format is
    unchanged.  Degrades gracefully when the accelerator reports no
    stats (bare CPU builds): gauges are left untouched and the log says
    so instead of printing zeros."""
    try:
        from ..telemetry.memory import get_memory_ledger

        # no-arg: the ledger publishes its own process-aggregate view so
        # the gauges stay consistent with the ledger's residual math
        get_memory_ledger().publish_stats()
    # dstpu-lint: allow[swallow] telemetry must never break the caller
    except Exception:
        pass
    if not force:
        return
    acc = get_accelerator()
    s = acc.memory_stats()
    if not s:
        logger.info(f"{message} | device memory stats unavailable on "
                    f"accelerator '{acc.device_name()}'")
        return
    used = s.get("bytes_in_use", 0) / 2**30
    peak = s.get("peak_bytes_in_use", 0) / 2**30
    limit = s.get("bytes_limit", 0) / 2**30
    logger.info(f"{message} | device MA {used:.2f} GB  Max_MA {peak:.2f} GB  "
                f"limit {limit:.2f} GB")


def clip_grad_norm_(grads: Any, max_norm: float) -> Tuple[Any, jnp.ndarray]:
    """Global-norm clip over a gradient pytree; returns (clipped, norm)
    (reference clip_grad_norm_ with the norm allreduce — on TPU the norm is
    computed on global arrays, the collective is implicit)."""
    norm = global_grad_norm(grads)
    return clip_by_global_norm(grads, norm, max_norm), norm


def flatten_tree(tree: Any) -> Tuple[jnp.ndarray, Any, List[Tuple[int, ...]]]:
    """Flatten a pytree of arrays into one 1-D buffer (reference
    flatten/_flatten_dense_tensors).  Returns (flat, treedef, shapes)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    shapes = [tuple(x.shape) for x in leaves]
    flat = jnp.concatenate([jnp.ravel(x) for x in leaves]) if leaves else jnp.zeros((0,))
    return flat, treedef, shapes


def unflatten_tree(flat: jnp.ndarray, treedef: Any,
                   shapes: Sequence[Tuple[int, ...]]) -> Any:
    """Inverse of flatten_tree (reference unflatten/_unflatten_dense_tensors)."""
    out, off = [], 0
    for shp in shapes:
        n = int(np.prod(shp)) if shp else 1
        out.append(flat[off:off + n].reshape(shp))
        off += n
    return jax.tree_util.tree_unflatten(treedef, out)


def partition_uniform(num_items: int, num_parts: int) -> List[int]:
    """Boundaries of a uniform split (reference partition_uniform):
    returns num_parts+1 offsets."""
    parts = [0] * (num_parts + 1)
    chunk = num_items // num_parts
    residual = num_items % num_parts
    for p in range(num_parts):
        parts[p + 1] = parts[p] + chunk + (1 if p < residual else 0)
    return parts


def partition_balanced(weights: Sequence[float], num_parts: int) -> List[int]:
    """Greedy prefix-sum balanced partition of weighted items (reference
    partition_balanced, used by pipeline layer placement)."""
    n = len(weights)
    if num_parts >= n:
        return list(range(n + 1)) + [n] * (num_parts - n)
    prefix = np.concatenate([[0.0], np.cumsum(np.asarray(weights, np.float64))])
    total = prefix[-1]
    bounds = [0]
    for p in range(1, num_parts):
        target = total * p / num_parts
        idx = int(np.searchsorted(prefix, target))
        idx = max(bounds[-1] + 1, min(idx, n - (num_parts - p)))
        bounds.append(idx)
    bounds.append(n)
    return bounds


def set_random_seed(seed: int):
    """Seed every host RNG the framework touches (reference
    ``runtime/utils.py set_random_seed``: random, numpy, torch).  Device
    RNG in JAX is explicit (`jax.random.PRNGKey` threaded through the
    engine), so this covers the HOST side — dataloader shuffling, samplers,
    numpy-based augmentation — and returns a fresh PRNGKey for device use."""
    import random as _random

    _random.seed(seed)
    np.random.seed(seed)
    try:  # torch datasets (CPU) are supported; seed even before first import
        import torch as _torch

        _torch.manual_seed(seed)
    # dstpu-lint: allow[swallow] torch is optional; a broken install must
    # not break jax-only seeding (see body comment)
    except Exception:
        # absent torch (ImportError) and broken installs (OSError on a
        # missing shared lib, RuntimeError) alike must not break jax-only
        # seeding
        pass
    return jax.random.PRNGKey(seed)
