"""Hessian eigenvalue estimation by power iteration.

Reference: ``runtime/eigenvalue.py`` — per-block curvature estimates used to
schedule compression quantization.  JAX makes this clean: hessian-vector
products are ``jax.jvp`` over ``jax.grad`` (no double-backward hooks).
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp


def _normalize(tree: Any) -> Tuple[Any, jnp.ndarray]:
    sq = sum(jnp.sum(jnp.square(x)) for x in jax.tree_util.tree_leaves(tree))
    norm = jnp.sqrt(sq)
    return jax.tree_util.tree_map(lambda x: x / (norm + 1e-12), tree), norm


def top_eigenvalue(loss_fn: Callable[[Any], jnp.ndarray], params: Any,
                   rng, max_iters: int = 20, tol: float = 1e-2) -> jnp.ndarray:
    """Largest |eigenvalue| of the Hessian of ``loss_fn`` at ``params``."""
    grad_fn = jax.grad(loss_fn)

    def hvp(v):
        return jax.jvp(grad_fn, (params,), (v,))[1]

    leaves = jax.tree_util.tree_leaves(params)
    keys = jax.random.split(rng, len(leaves))
    v = jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(params),
        [jax.random.normal(k, x.shape, jnp.float32) for k, x in zip(keys, leaves)])
    v, _ = _normalize(v)

    def body(carry, _):
        v, prev = carry
        hv = hvp(v)
        v, norm = _normalize(hv)
        return (v, norm), norm

    (_, eig), _ = jax.lax.scan(body, (v, jnp.asarray(0.0)), None, length=max_iters)
    return eig
