"""Curriculum learning + data efficiency.

Reference: ``runtime/data_pipeline/`` — CurriculumScheduler (difficulty
ramps, e.g. sequence length), DeepSpeedDataSampler (curriculum-aware
sampling), variable batch size & LR.  The TPU twist: difficulty changes must
not retrigger XLA compilation every step, so sequence-length curricula step
through a FIXED ladder of bucket lengths (each bucket compiles once).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

import numpy as np

from ...utils.logging import logger


@dataclasses.dataclass
class CurriculumConfig:
    enabled: bool = False
    curriculum_type: str = "seqlen"
    min_difficulty: int = 64
    max_difficulty: int = 1024
    schedule_type: str = "fixed_linear"  # fixed_linear | fixed_root | fixed_discrete
    total_curriculum_step: int = 10000
    difficulty_step: int = 8
    root_degree: int = 2
    difficulty: Optional[List[int]] = None  # for fixed_discrete
    max_step: Optional[List[int]] = None

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "CurriculumConfig":
        d = dict(d or {})
        sched = d.pop("schedule_config", {})
        merged = {**d, **sched}
        return cls(**{k: v for k, v in merged.items()
                      if k in {f.name for f in dataclasses.fields(cls)}})


class CurriculumScheduler:
    """step -> difficulty (reference data_pipeline/curriculum_scheduler.py)."""

    def __init__(self, config: CurriculumConfig):
        self.config = config
        self.current_difficulty = config.min_difficulty

    def get_difficulty(self, global_step: int) -> int:
        c = self.config
        if c.schedule_type == "fixed_discrete":
            diffs = c.difficulty or [c.max_difficulty]
            steps = c.max_step or []
            idx = sum(1 for s in steps if global_step >= s)
            return diffs[min(idx, len(diffs) - 1)]
        frac = min(1.0, global_step / max(1, c.total_curriculum_step))
        if c.schedule_type == "fixed_root":
            frac = frac ** (1.0 / c.root_degree)
        raw = c.min_difficulty + (c.max_difficulty - c.min_difficulty) * frac
        # snap to the difficulty_step ladder so XLA shapes form a small set
        snapped = int(raw // c.difficulty_step) * c.difficulty_step
        return max(c.min_difficulty, min(snapped, c.max_difficulty))

    def update_difficulty(self, global_step: int) -> int:
        self.current_difficulty = self.get_difficulty(global_step)
        return self.current_difficulty


def apply_seqlen_curriculum(batch: Dict[str, Any], difficulty: int) -> Dict[str, Any]:
    """Truncate token batches to the current difficulty (reference
    seqlen-based curriculum applied in the GPT pretrain path)."""
    out = {}
    for k, v in batch.items():
        if hasattr(v, "ndim") and v.ndim >= 2 and v.shape[-1] > difficulty:
            out[k] = v[..., :difficulty]
        else:
            out[k] = v
    return out


class DeepSpeedDataSampler:
    """Curriculum-aware sampler: difficulty-scored samples released as the
    curriculum advances (reference data_sampling/data_sampler.py)."""

    def __init__(self, difficulties: np.ndarray, scheduler: CurriculumScheduler,
                 batch_size: int, seed: int = 0, drop_last: bool = True):
        self.difficulties = np.asarray(difficulties)
        self.scheduler = scheduler
        self.batch_size = batch_size
        self.seed = seed
        self.global_step = 0

    def set_step(self, step: int) -> None:
        self.global_step = step

    def next_indices(self) -> np.ndarray:
        diff = self.scheduler.update_difficulty(self.global_step)
        eligible = np.nonzero(self.difficulties <= diff)[0]
        if eligible.size == 0:
            eligible = np.argsort(self.difficulties)[:self.batch_size]
        rng = np.random.RandomState(self.seed + self.global_step)
        return rng.choice(eligible, size=self.batch_size,
                          replace=eligible.size < self.batch_size)


@dataclasses.dataclass
class VariableBatchConfig:
    """Variable batch size & LR (reference
    data_sampling/variable_batch_size_and_lr.py:492): batch by token budget,
    scale LR by batch-size ratio."""

    max_tokens_per_batch: int = 8192
    lr_scaling_method: str = "linear"  # linear | sqrt | none


def batch_by_token_budget(seq_lens: np.ndarray, cfg: VariableBatchConfig):
    """Greedy pack sample indices into batches under the token budget;
    returns (list of index arrays, lr multipliers)."""
    order = np.argsort(seq_lens)
    batches, cur, cur_tokens = [], [], 0
    max_len_in_cur = 0
    for i in order:
        sl = int(seq_lens[i])
        new_max = max(max_len_in_cur, sl)
        if cur and new_max * (len(cur) + 1) > cfg.max_tokens_per_batch:
            batches.append(np.asarray(cur))
            cur, max_len_in_cur = [], 0
            new_max = sl
        cur.append(i)
        max_len_in_cur = new_max
    if cur:
        batches.append(np.asarray(cur))
    ref = max(len(b) for b in batches)
    mults = []
    for b in batches:
        r = len(b) / ref
        if cfg.lr_scaling_method == "linear":
            mults.append(r)
        elif cfg.lr_scaling_method == "sqrt":
            mults.append(float(np.sqrt(r)))
        else:
            mults.append(1.0)
    return batches, mults
