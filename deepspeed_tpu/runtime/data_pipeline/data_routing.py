"""Data routing: random-LTD and progressive layer drop (PLD).

Reference parity:
* random-LTD — ``runtime/data_pipeline/data_routing/`` + csrc/random_ltd:
  each middle layer processes only a random subset of tokens; the kept
  count follows a linear schedule from ``start_token_budget`` to the full
  sequence, and dropped tokens bypass the layer (identity).  The reference
  sorts/gathers with CUDA kernels; XLA's gather/scatter fuse fine on TPU
  (SURVEY §2.4 random-LTD row).
* PLD — ``runtime/progressive_layer_drop.py``: layer *i* of *L* is kept
  with probability ``p_i(t) = (theta(t)) ** (i / L)``-style schedule,
  theta decaying from 1 toward ``theta_min`` with factor ``gamma``; kept
  layers rescale activations at eval.

Both integrate with the scan-layers transformer through pure functions:
``random_ltd_apply(block_fn, x, keep_idx)`` and
``pld_apply(block_fn, x, keep, theta)`` — jit-safe (fixed shapes: the
token budget is static per compilation; schedules step per boundary like
the reference's schedulers).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


# ------------------------------------------------------------- random-LTD
@dataclasses.dataclass
class RandomLTDConfig:
    """random_ltd block of data_efficiency config (reference
    data_pipeline/config.py random_ltd keys)."""

    enabled: bool = False
    total_layer_num: int = 12
    random_ltd_layer_num: int = 8  # middle layers under LTD
    start_token_budget: int = 128
    schedule_steps: int = 1000  # linear ramp to the full sequence

    def token_budget(self, step: int, seq_len: int) -> int:
        """Kept-token count at ``step`` (reference BaseScheduler linear)."""
        if not self.enabled or step >= self.schedule_steps:
            return seq_len
        frac = step / max(1, self.schedule_steps)
        k = int(self.start_token_budget +
                frac * (seq_len - self.start_token_budget))
        return min(max(k, 1), seq_len)


def random_ltd_indices(rng: jax.Array, seq_len: int, budget: int,
                       batch: int) -> jnp.ndarray:
    """Sample ``budget`` kept token positions per batch row, sorted
    (reference token_sort kernel).  [B, budget] int32."""
    def one(r):
        return jnp.sort(jax.random.permutation(r, seq_len)[:budget])

    return jax.vmap(one)(jax.random.split(rng, batch))


def random_ltd_apply(block_fn: Callable[[jnp.ndarray], jnp.ndarray],
                     x: jnp.ndarray, keep_idx: jnp.ndarray) -> jnp.ndarray:
    """Run ``block_fn`` on the kept tokens only; dropped tokens pass
    through unchanged (reference gather→layer→scatter data path).

    x: [B, S, H]; keep_idx: [B, K] sorted positions.
    """
    B = x.shape[0]
    gathered = jnp.take_along_axis(x, keep_idx[..., None], axis=1)  # [B, K, H]
    processed = block_fn(gathered)
    return x.at[jnp.arange(B)[:, None], keep_idx].set(processed)


# ------------------------------------------------------------------ PLD
@dataclasses.dataclass
class PLDConfig:
    """progressive_layer_drop block (reference
    runtime/progressive_layer_drop.py ProgressiveLayerDrop)."""

    enabled: bool = False
    theta: float = 0.5  # asymptotic keep probability
    gamma: float = 0.001  # decay speed


class ProgressiveLayerDrop:
    """Keep-probability schedule (reference ProgressiveLayerDrop.update_state):
    theta(t) = (1 - theta_bar) * exp(-gamma t) + theta_bar."""

    def __init__(self, config: Optional[PLDConfig] = None,
                 theta: float = 0.5, gamma: float = 0.001):
        cfg = config or PLDConfig(enabled=True, theta=theta, gamma=gamma)
        self.config = cfg
        self.current_theta = 1.0

    def get_theta(self) -> float:
        return self.current_theta

    def update_state(self, global_step: int) -> float:
        c = self.config
        self.current_theta = float(
            (1.0 - c.theta) * np.exp(-c.gamma * global_step) + c.theta)
        return self.current_theta

    def get_state(self) -> Dict[str, Any]:
        return {"progressive_layer_drop": True, "pld_theta": self.get_theta()}

    def layer_keep_prob(self, layer_idx: int, num_layers: int) -> float:
        """Deeper layers drop more (reference: p_l = theta ** (l / L) shape
        — keep probability decreases with depth)."""
        depth_frac = (layer_idx + 1) / max(1, num_layers)
        return float(self.current_theta ** depth_frac)


def pld_apply(block_fn: Callable[[jnp.ndarray], jnp.ndarray],
              x: jnp.ndarray, rng: jax.Array, keep_prob: float,
              training: bool = True) -> jnp.ndarray:
    """Stochastically skip a block (identity) with prob 1-keep_prob;
    at eval, run it always (expectation-preserving residual scaling is the
    block's residual-branch scale, matching stochastic depth)."""
    if not training or keep_prob >= 1.0:
        return block_fn(x)
    keep = jax.random.bernoulli(rng, keep_prob)
    # lax.cond executes one branch at runtime: skipped layers cost nothing;
    # the kept branch rescales the block delta to preserve the expectation
    return jax.lax.cond(
        keep,
        lambda v: v + (block_fn(v) - v) / keep_prob,
        lambda v: v,
        x)
