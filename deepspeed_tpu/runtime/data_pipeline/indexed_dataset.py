"""Memory-mapped indexed dataset (Megatron ``.bin``/``.idx`` format).

Reference parity: ``runtime/data_pipeline/data_sampling/indexed_dataset.py``
(the Megatron-LM mmap format DeepSpeed's data sampler reads).  The on-disk
layout is byte-compatible so corpora tokenized by Megatron/DeepSpeed
tooling load directly:

  .idx: magic ``MMIDIDX\\x00\\x00`` | version u64 | dtype code u8 |
        n_sequences u64 | n_docs u64 | sizes i32[n] | pointers i64[n] |
        doc_idx i64[n_docs]
  .bin: the token arrays, back to back.

Reads are zero-copy numpy views over one mmap — the host-side analogue of
the reference's pinned-buffer reader, and what the curriculum/sampler
layers consume.
"""

from __future__ import annotations

import os
import struct
from typing import List, Union

import numpy as np

_MAGIC = b"MMIDIDX\x00\x00"
_VERSION = 1

#: dtype codes — the reference's `dtypes` table (its indexed_dataset.py
#: line ~102).  NOTE: codes 6-8 differ from CLASSIC Megatron/fairseq
#: (which used 6=float32, 7=float64, 8=uint16); we match the reference
#: this framework tracks.  Corpora from old-Megatron tooling with codes
#: 6-8 would need re-encoding (4/5, the int tokens, are identical).
_CODE_TO_DTYPE = {1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32,
                  5: np.int64, 6: np.uint16, 7: np.uint32, 8: np.uint64}
_DTYPE_TO_CODE = {np.dtype(v): k for k, v in _CODE_TO_DTYPE.items()}


def data_file_path(prefix: str) -> str:
    return prefix + ".bin"


def index_file_path(prefix: str) -> str:
    return prefix + ".idx"


class MMapIndexedDataset:
    """Random-access reader; ``ds[i]`` returns sequence i as a numpy view."""

    def __init__(self, path_prefix: str):
        self.path_prefix = path_prefix
        with open(index_file_path(path_prefix), "rb") as f:
            magic = f.read(9)
            if magic != _MAGIC:
                raise ValueError(f"{index_file_path(path_prefix)}: not an "
                                 "MMIDIDX indexed dataset")
            (version,) = struct.unpack("<Q", f.read(8))
            if version != _VERSION:
                raise ValueError(f"unsupported index version {version}")
            (code,) = struct.unpack("<B", f.read(1))
            if code not in _CODE_TO_DTYPE:
                raise ValueError(
                    f"{index_file_path(path_prefix)}: unknown dtype code "
                    f"{code} (corrupt index, or a foreign format?)")
            self.dtype = np.dtype(_CODE_TO_DTYPE[code])
            (n_seq,) = struct.unpack("<Q", f.read(8))
            (n_doc,) = struct.unpack("<Q", f.read(8))
            offset = f.tell()
        idx_buf = np.memmap(index_file_path(path_prefix), mode="r", order="C")
        self.sizes = np.frombuffer(idx_buf, np.int32, count=n_seq,
                                   offset=offset)
        offset += n_seq * 4
        self.pointers = np.frombuffer(idx_buf, np.int64, count=n_seq,
                                      offset=offset)
        offset += n_seq * 8
        self.doc_idx = np.frombuffer(idx_buf, np.int64, count=n_doc,
                                     offset=offset)
        bin_path = data_file_path(path_prefix)
        if os.path.getsize(bin_path) == 0:  # valid empty shard
            self._bin = np.zeros(0, np.uint8)
        else:
            self._bin = np.memmap(bin_path, mode="r", order="C")

    def __len__(self) -> int:
        return len(self.sizes)

    def __getitem__(self, i: Union[int, slice]):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        ptr, size = int(self.pointers[i]), int(self.sizes[i])
        return np.frombuffer(self._bin, self.dtype, count=size, offset=ptr)

    def get(self, i: int, offset: int = 0, length: int = None):
        """Sub-range of sequence i (reference ``MMapIndexedDataset.get``)."""
        seq = self[i]
        end = len(seq) if length is None else offset + length
        return seq[offset:end]


class MMapIndexedDatasetBuilder:
    """Streaming writer producing the byte-compatible pair of files."""

    def __init__(self, out_prefix: str, dtype=np.int32):
        self.prefix = out_prefix
        self.dtype = np.dtype(dtype)
        if self.dtype not in _DTYPE_TO_CODE:
            raise ValueError(f"unsupported dtype {dtype}")
        self._bin = open(data_file_path(out_prefix), "wb")
        self.sizes: List[int] = []
        self.doc_idx: List[int] = [0]

    def add_item(self, tokens) -> None:
        arr = np.asarray(tokens)
        if arr.size and arr.dtype != self.dtype:
            if not np.issubdtype(arr.dtype, np.integer):
                # float/NaN token arrays would truncate or be undefined
                raise ValueError(
                    f"token array dtype {arr.dtype} is not integral; "
                    "tokenize to ints before building")
            info = np.iinfo(self.dtype)
            lo, hi = int(arr.min()), int(arr.max())
            if lo < info.min or hi > info.max:
                raise ValueError(
                    f"token ids [{lo}, {hi}] do not fit dtype "
                    f"{self.dtype} — silent casting would wrap them")
        arr = arr.astype(self.dtype, copy=False)
        self._bin.write(arr.tobytes(order="C"))
        self.sizes.append(arr.size)

    def end_document(self) -> None:
        self.doc_idx.append(len(self.sizes))

    def finalize(self) -> str:
        self._bin.close()
        _write_index(self.prefix, self.dtype, self.sizes, self.doc_idx)
        return self.prefix


def _write_index(prefix: str, dtype: np.dtype, sizes: List[int],
                 doc_idx: List[int]) -> None:
    pointers = np.zeros(len(sizes), np.int64)
    if len(sizes) > 1:  # exclusive scan of byte sizes
        np.cumsum(np.asarray(sizes[:-1], np.int64) * dtype.itemsize,
                  out=pointers[1:])
    with open(index_file_path(prefix), "wb") as f:
        f.write(_MAGIC)
        f.write(struct.pack("<Q", _VERSION))
        f.write(struct.pack("<B", _DTYPE_TO_CODE[dtype]))
        f.write(struct.pack("<Q", len(sizes)))
        f.write(struct.pack("<Q", len(doc_idx)))
        f.write(np.asarray(sizes, np.int32).tobytes(order="C"))
        f.write(pointers.tobytes(order="C"))
        f.write(np.asarray(doc_idx, np.int64).tobytes(order="C"))


def merge_datasets(prefixes: List[str], out_prefix: str) -> str:
    """Concatenate datasets (reference ``merge_file_``): bulk-copies each
    ``.bin`` and rebases the index arrays — no per-sequence re-encode.
    Doc-boundary semantics match the reference exactly (doc_idx rebased by
    ``(offset + doc_idx)[1:]``): a shard's trailing OPEN document — items
    after its last ``end_document`` — fuses into the next shard's first
    document, so close documents before finalizing shards you merge."""
    import shutil

    datasets = [MMapIndexedDataset(p) for p in prefixes]
    dtype = datasets[0].dtype
    for p, ds in zip(prefixes, datasets):
        if ds.dtype != dtype:
            raise ValueError(
                f"merge_datasets: dtype mismatch — {prefixes[0]} is {dtype}, "
                f"{p} is {ds.dtype}; re-encode before merging (silent "
                "casting would wrap out-of-range token ids)")

    sizes, doc_idx = [], [0]
    seq_base = 0
    with open(data_file_path(out_prefix), "wb") as out_bin:
        for p, ds in zip(prefixes, datasets):
            with open(data_file_path(p), "rb") as f:
                shutil.copyfileobj(f, out_bin)
            sizes.extend(int(s) for s in ds.sizes)
            doc_idx.extend(int(d) + seq_base for d in ds.doc_idx[1:])
            seq_base += len(ds)

    _write_index(out_prefix, dtype, sizes, doc_idx)
    return out_prefix


def make_dataset(path_prefix: str, impl: str = "mmap") -> MMapIndexedDataset:
    """Reference ``make_dataset`` entry (only the mmap impl exists here —
    the cached/lazy fairseq variants predate mmap and were superseded)."""
    if impl not in ("mmap", "infer"):
        raise ValueError(f"unsupported indexed dataset impl {impl!r}; "
                         "only 'mmap' is provided")
    if not os.path.exists(index_file_path(path_prefix)):
        raise FileNotFoundError(index_file_path(path_prefix))
    return MMapIndexedDataset(path_prefix)
