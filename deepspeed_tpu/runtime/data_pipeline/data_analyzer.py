"""Offline data analysis for curriculum learning.

Reference parity: ``runtime/data_pipeline/data_sampling/data_analyzer.py``
— maps metric functions over a dataset (optionally splitting the work
across workers), writes per-sample metric values plus a
sample-index-sorted-by-metric file, which the curriculum sampler then
consumes (``DeepSpeedDataSampler`` reads index_to_sample /
index_to_metric).

Host-side numpy throughout: analysis runs once, offline, before training.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence

import numpy as np

from ...utils.logging import logger

MetricFn = Callable[[Any], float]


# built-in metrics (reference: seqlen / vocab rarity metrics)
def metric_seqlen(sample: Any) -> float:
    ids = sample["input_ids"] if isinstance(sample, dict) else sample
    arr = np.asarray(ids)
    return float(arr.shape[-1] if arr.ndim else 0)


def metric_total_vocab_freq(vocab_freq: np.ndarray) -> MetricFn:
    """Rarity: -sum(log freq) of the sample's tokens (rarer = harder)."""
    logf = np.log(np.maximum(vocab_freq, 1)) - np.log(max(vocab_freq.sum(), 1))

    def fn(sample: Any) -> float:
        ids = np.asarray(sample["input_ids"] if isinstance(sample, dict)
                         else sample).ravel()
        return float(-logf[ids].sum())

    return fn


def metric_vocab_histogram(vocab_size: int) -> MetricFn:
    """ACCUMULATE-type metric: per-sample token histogram, summed over the
    corpus by map-reduce (reference vocab_rarity two-pass: accumulate the
    corpus frequency first, then score samples against it)."""

    def fn(sample: Any) -> np.ndarray:
        ids = np.asarray(sample["input_ids"] if isinstance(sample, dict)
                         else sample).ravel()
        return np.bincount(ids, minlength=vocab_size).astype(np.float64)

    return fn


class DataAnalyzer:
    """Run metrics over a dataset and persist curriculum index files
    (reference DataAnalyzer.run_map / run_reduce)."""

    def __init__(self, dataset: Sequence[Any],
                 metric_names: Optional[List[str]] = None,
                 metric_functions: Optional[List[MetricFn]] = None,
                 metric_types: Optional[List[str]] = None,
                 save_path: str = "./data_analysis",
                 num_workers: int = 1, worker_id: int = 0):
        self.dataset = dataset
        self.metric_names = metric_names or ["seqlen"]
        self.metric_functions = metric_functions or [metric_seqlen]
        if len(self.metric_names) != len(self.metric_functions):
            raise ValueError("metric_names and metric_functions must pair up")
        # reference metric types (data_analyzer.py:22): per-sample values
        # feed the curriculum index; accumulate-type metrics sum an array
        # over the whole corpus (e.g. vocab frequency) for a later pass
        self.metric_types = (metric_types
                             or ["single_value_per_sample"] * len(self.metric_names))
        for t in self.metric_types:
            if t not in ("single_value_per_sample",
                         "accumulate_value_over_samples"):
                raise ValueError(f"unknown metric_type {t}")
        self.save_path = save_path
        self.num_workers = max(1, num_workers)
        self.worker_id = worker_id

    def _my_indices(self) -> np.ndarray:
        n = len(self.dataset)
        return np.arange(self.worker_id, n, self.num_workers)

    def run_map(self) -> Dict[str, np.ndarray]:
        """Compute this worker's metric shard and write it to disk."""
        os.makedirs(self.save_path, exist_ok=True)
        idx = self._my_indices()
        out: Dict[str, np.ndarray] = {}
        for name, fn, mtype in zip(self.metric_names, self.metric_functions,
                                   self.metric_types):
            if mtype == "accumulate_value_over_samples":
                acc = None
                for i in idx:
                    v = np.asarray(fn(self.dataset[int(i)]), np.float64)
                    acc = v if acc is None else acc + v
                if acc is None:
                    acc = np.zeros(0, np.float64)
                np.save(self._shard_file(name, self.worker_id), acc)
                out[name] = acc
            else:
                vals = np.asarray([fn(self.dataset[int(i)]) for i in idx],
                                  np.float64)
                np.save(self._shard_file(name, self.worker_id),
                        np.stack([idx.astype(np.float64), vals]))
                out[name] = vals
        logger.info(f"DataAnalyzer: worker {self.worker_id} mapped "
                    f"{idx.size} samples x {len(self.metric_names)} metrics")
        return out

    def run_reduce(self) -> Dict[str, Dict[str, np.ndarray]]:
        """Merge all worker shards; write index_to_metric /
        index_to_sample_percentile_merged files (reference naming).
        Accumulate-type metrics reduce by summation instead."""
        result: Dict[str, Dict[str, np.ndarray]] = {}
        for name, mtype in zip(self.metric_names, self.metric_types):
            shards = []
            for w in range(self.num_workers):
                f = self._shard_file(name, w)
                if not os.path.exists(f):
                    raise FileNotFoundError(
                        f"missing shard {f}: did worker {w} run run_map()?")
                shards.append(np.load(f))
            if mtype == "accumulate_value_over_samples":
                width = max(s.size for s in shards)
                total = np.zeros(width, np.float64)
                for s in shards:
                    total[:s.size] += s
                result[name] = {"accumulated": total}
                np.save(os.path.join(self.save_path,
                                     f"{name}_accumulated.npy"), total)
                continue
            merged = np.concatenate(shards, axis=1)
            order = np.argsort(merged[0])
            sample_idx = merged[0][order].astype(np.int64)
            values = merged[1][order]
            by_metric = np.argsort(values, kind="stable")
            result[name] = {
                "index_to_metric": values,
                "metric_to_sample": sample_idx[by_metric],
            }
            np.save(os.path.join(self.save_path, f"{name}_index_to_metric.npy"),
                    values)
            np.save(os.path.join(self.save_path, f"{name}_metric_to_sample.npy"),
                    sample_idx[by_metric])
        with open(os.path.join(self.save_path, "analysis_summary.json"), "w") as f:
            json.dump({"num_samples": len(self.dataset),
                       "metrics": self.metric_names}, f)
        return result

    def _shard_file(self, metric: str, worker: int) -> str:
        return os.path.join(self.save_path, f"{metric}_worker{worker}.npy")

    @classmethod
    def run_map_reduce(cls, dataset: Sequence[Any], save_path: str,
                       num_workers: int = 1,
                       max_parallel: Optional[int] = None,
                       **kw) -> Dict[str, Dict[str, np.ndarray]]:
        """Concurrent map-reduce driver (reference run_map_reduce,
        data_analyzer.py:22 — there over torch.distributed workers; here a
        thread pool runs the per-worker maps concurrently, then one reduce
        merges the shards).  Metric fns are numpy-bound, so threads give
        real parallelism for IO-heavy corpora; each worker touches only its
        own shard files."""
        from concurrent.futures import ThreadPoolExecutor

        workers = [cls(dataset, save_path=save_path, num_workers=num_workers,
                       worker_id=w, **kw) for w in range(num_workers)]
        with ThreadPoolExecutor(max_workers=max_parallel or num_workers) as pool:
            futures = [pool.submit(w.run_map) for w in workers]
            for f in futures:
                f.result()
        return workers[0].run_reduce()


def load_difficulties(save_path: str, metric: str) -> np.ndarray:
    """Per-sample difficulty values for DeepSpeedDataSampler."""
    return np.load(os.path.join(save_path, f"{metric}_index_to_metric.npy"))
