"""1-bit optimizer family: OneBitAdam, ZeroOneAdam, OneBitLamb.

Reference parity: ``deepspeed/runtime/fp16/onebit/{adam,zoadam,lamb}.py`` —
communication-compressed optimizers.  Their shared recipe: run exact
Adam/LAMB for ``freeze_step`` warmup steps; then freeze (or rarely update)
the variance and communicate the *momentum* through an error-feedback
compressed allreduce (runtime/comm/compressed.py in the reference).

TPU translation: under SPMD the gradient reduction is a compiler-inserted
XLA collective, so the compression is expressed where it has semantic
effect — the error-feedback quantize-dequantize sits inside the update
(the value every rank folds into its momentum is exactly the value the
reference puts on the wire), and the persistent error buffer rides the
optimizer state.  For flows that own their collectives (shard_map paths),
``runtime/comm/compressed.compressed_all_reduce`` provides the matching
wire-level primitive.

All three are optax ``GradientTransformation``s, selected by the usual
optimizer names in the config (optimizers.py).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax


class OneBitState(NamedTuple):
    count: jnp.ndarray  # int32 step
    m: optax.Updates  # momentum
    v: optax.Updates  # variance (frozen after freeze_step)
    error: optax.Updates  # error-feedback residual


def _qdq_block_int8(x: jnp.ndarray) -> jnp.ndarray:
    """Per-128-block symmetric int8 quantize-dequantize (the wire format of
    the compressed allreduce; 1-bit sign+scale in the reference's final
    stage — int8 here matches runtime/comm/compressed.py)."""
    n = x.size
    if n == 0:
        return x
    pad = (-n) % 128
    flat = jnp.pad(x.reshape(-1), (0, pad)) if pad else x.reshape(-1)
    blocks = flat.reshape(-1, 128)
    scale = jnp.maximum(jnp.max(jnp.abs(blocks), -1, keepdims=True), 1e-12) / 127.0
    q = jnp.clip(jnp.round(blocks / scale), -127, 127)
    return (q * scale).reshape(-1)[:n].reshape(x.shape).astype(x.dtype)


def _compressed(g, err):
    comp = g + err
    sent = _qdq_block_int8(comp)
    return sent, comp - sent


def one_bit_adam(learning_rate, b1: float = 0.9, b2: float = 0.999,
                 eps: float = 1e-8, weight_decay: float = 0.0,
                 freeze_step: int = 100) -> optax.GradientTransformation:
    """OneBitAdam (reference onebit/adam.py): exact AdamW warmup, then
    frozen variance + compressed momentum updates with error feedback."""
    return _one_bit_family(learning_rate, b1, b2, eps, weight_decay,
                           freeze_step, var_update_interval=0, lamb=False)


def zero_one_adam(learning_rate, b1: float = 0.9, b2: float = 0.999,
                  eps: float = 1e-8, weight_decay: float = 0.0,
                  var_freeze_step: int = 100,
                  var_update_interval: int = 16) -> optax.GradientTransformation:
    """ZeroOneAdam (reference onebit/zoadam.py): like OneBitAdam but the
    variance still refreshes every ``var_update_interval`` steps after the
    freeze point (the '0/1' schedule)."""
    return _one_bit_family(learning_rate, b1, b2, eps, weight_decay,
                           var_freeze_step,
                           var_update_interval=var_update_interval, lamb=False)


def one_bit_lamb(learning_rate, b1: float = 0.9, b2: float = 0.999,
                 eps: float = 1e-6, weight_decay: float = 0.0,
                 freeze_step: int = 100) -> optax.GradientTransformation:
    """OneBitLamb (reference onebit/lamb.py): the compressed stage applies
    the LAMB per-layer trust ratio on top of the frozen-variance update."""
    return _one_bit_family(learning_rate, b1, b2, eps, weight_decay,
                           freeze_step, var_update_interval=0, lamb=True)


def _one_bit_family(learning_rate, b1, b2, eps, weight_decay, freeze_step,
                    var_update_interval, lamb) -> optax.GradientTransformation:
    sched = learning_rate if callable(learning_rate) else (lambda _: learning_rate)

    def init(params):
        z = lambda: jax.tree_util.tree_map(jnp.zeros_like, params)  # noqa: E731
        return OneBitState(count=jnp.zeros((), jnp.int32), m=z(), v=z(),
                           error=z())

    def update(grads, state, params=None):
        if params is None and (weight_decay or lamb):
            # decoupled weight decay / LAMB trust ratio read the parameter
            # values; silently substituting grads would corrupt the update
            raise ValueError(
                "one-bit optimizer with weight_decay or LAMB needs params: "
                "call update(grads, state, params)")
        count = state.count + 1
        warm = count <= freeze_step

        def leaf(g, m, v, e, p):
            # compressed stage feeds the qdq'd compensated grad into the
            # momentum; warmup feeds the exact grad and accrues no error
            sent, new_e = _compressed(g, e)
            g_eff = jnp.where(warm, g, sent)
            new_e = jnp.where(warm, jnp.zeros_like(new_e), new_e)
            new_m = b1 * m + (1 - b1) * g_eff
            # variance: exact during warmup; frozen after (ZeroOneAdam:
            # refreshed on its interval)
            v_next = b2 * v + (1 - b2) * jnp.square(g_eff)
            if var_update_interval > 0:
                refresh = warm | (count % var_update_interval == 0)
            else:
                refresh = warm
            new_v = jnp.where(refresh, v_next, v)

            mh = new_m / (1 - b1 ** count.astype(jnp.float32))
            vh = new_v / (1 - b2 ** count.astype(jnp.float32))
            upd = mh / (jnp.sqrt(vh) + eps)
            if weight_decay:
                upd = upd + weight_decay * p
            if lamb:
                wn = jnp.sqrt(jnp.sum(jnp.square(p)))
                un = jnp.sqrt(jnp.sum(jnp.square(upd)))
                trust = jnp.where((wn > 0) & (un > 0), wn / un, 1.0)
                upd = trust * upd
            return -sched(state.count) * upd, new_m, new_v, new_e

        flat_out = jax.tree_util.tree_map(
            leaf, grads, state.m, state.v, state.error,
            params if params is not None else grads)
        updates = jax.tree_util.tree_map(lambda t: t[0], flat_out,
                                         is_leaf=lambda t: isinstance(t, tuple))
        new_m = jax.tree_util.tree_map(lambda t: t[1], flat_out,
                                       is_leaf=lambda t: isinstance(t, tuple))
        new_v = jax.tree_util.tree_map(lambda t: t[2], flat_out,
                                       is_leaf=lambda t: isinstance(t, tuple))
        new_e = jax.tree_util.tree_map(lambda t: t[3], flat_out,
                                       is_leaf=lambda t: isinstance(t, tuple))
        return updates, OneBitState(count=count, m=new_m, v=new_v, error=new_e)

    return optax.GradientTransformation(init, update)
