"""Typed config models.

Lightweight, dependency-free replacement for the pydantic
``DeepSpeedConfigModel`` machinery in the reference
(``deepspeed/runtime/config_utils.py:17``): dataclass-style field
declaration, type coercion, ``"auto"`` passthrough, unknown-key warnings, and
deprecated-field redirection.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Type, TypeVar, get_args, get_origin, Union

from ..utils.logging import logger

AUTO = "auto"

T = TypeVar("T", bound="ConfigModel")


def _coerce(value: Any, ann: Any) -> Any:
    """Best-effort coercion of a JSON value into the annotated type."""
    if value is None or value == AUTO:
        return value
    origin = get_origin(ann)
    if origin is Union:  # Optional[X] and friends
        for arg in get_args(ann):
            if arg is type(None):
                continue
            try:
                return _coerce(value, arg)
            except (TypeError, ValueError):
                continue
        return value
    if isinstance(ann, type) and dataclasses.is_dataclass(ann) and isinstance(value, dict):
        return ann.from_dict(value)  # type: ignore[attr-defined]
    if ann is bool and isinstance(value, bool):
        return value
    if ann is bool and isinstance(value, str):
        return value.lower() in ("true", "1", "yes")
    if ann in (int, float) and not isinstance(value, bool):
        return ann(value)
    if ann is str:
        return str(value)
    return value


@dataclasses.dataclass
class ConfigModel:
    """Base class: ``MyConfig.from_dict({...})`` with coercion + warnings."""

    #: map of old key -> new key, applied before field resolution
    _deprecated: Dict[str, str] = dataclasses.field(default=None, repr=False, compare=False)  # type: ignore[assignment]

    @classmethod
    def deprecated_fields(cls) -> Dict[str, str]:
        return {}

    @classmethod
    def from_dict(cls: Type[T], data: Optional[Dict[str, Any]]) -> T:
        data = dict(data or {})
        for old, new in cls.deprecated_fields().items():
            if old in data:
                logger.warning(f"Config field '{old}' is deprecated; use '{new}'")
                data.setdefault(new, data.pop(old))
        fields = {f.name: f for f in dataclasses.fields(cls) if f.name != "_deprecated"}
        kwargs = {}
        for key, value in data.items():
            if key in fields:
                kwargs[key] = _coerce(value, fields[key].type_resolved if hasattr(fields[key], "type_resolved") else _resolve(cls, fields[key]))
            else:
                logger.warning(f"{cls.__name__}: unknown config key '{key}' ignored")
        obj = cls(**kwargs)  # type: ignore[arg-type]
        obj.validate()
        return obj

    def validate(self) -> None:
        """Override for cross-field checks; raise ValueError on bad config."""

    def to_dict(self) -> Dict[str, Any]:
        out = {}
        for f in dataclasses.fields(self):
            if f.name == "_deprecated":
                continue
            v = getattr(self, f.name)
            out[f.name] = v.to_dict() if isinstance(v, ConfigModel) else v
        return out


def _resolve(cls: type, field: dataclasses.Field) -> Any:
    """Resolve possibly-string annotations (from __future__ annotations)."""
    ann = field.type
    if isinstance(ann, str):
        import typing

        module = __import__(cls.__module__, fromlist=["_"])
        try:
            ann = eval(ann, vars(typing) | vars(module) | {"__builtins__": {}})  # noqa: S307
        except Exception:
            return Any
    return ann


def get_scalar_param(d: Dict[str, Any], name: str, default: Any) -> Any:
    return d.get(name, default)
