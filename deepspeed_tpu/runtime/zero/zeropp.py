"""ZeRO++ — quantized/hierarchical ZeRO communication.

Reference parity:
  * qwZ — quantized weight all-gather: ``zero_quantized_weights``
    (reference zero/partition_parameters.py:704 ``AllGatherCoalescedHandle``
    quantized path, csrc/quantization swizzled-quant kernels).
  * qgZ — quantized gradient reduce via all-to-all:
    ``zero_quantized_gradients`` (reference
    runtime/comm/coalesced_collectives.py:31 ``all_to_all_quant_reduce``).
  * hpZ — hierarchical (secondary) weight partition:
    ``zero_hpz_partition_size`` (reference engine.py:1101-1113 config keys,
    secondary tensors in stage3) — implemented in strategy.py by sharding
    master/grads over (repl x data) while stage-3 live-param gathers ride
    only the small 'data' axis.

TPU-native expression: the collectives are XLA's, so compression is
expressed as dtype changes across forced sharding boundaries —
quantize (sharded) -> constraint to the gathered spec (XLA all-gathers the
int8 codes + fp32 block scales) -> dequantize.  The bytes on the wire are
the int8 payload, verifiable in the compiled HLO (test_zeropp.py greps the
collective ops' operand dtypes).
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ...comm.collectives.codec import (CompressionSpec, dequantize_blockwise,
                                       quantize_blockwise)
from ...parallel.mesh import DATA_AXIS
from ...utils.jax_compat import shard_map

QBLOCK = 128  # quantization block (reference csrc/quantization group size)

#: the ZeRO++ wire format, expressed on the shared codec
#: (comm/collectives/codec.py) — qwZ/qgZ are configurations of the
#: first-class compressed-collective layer, not parallel implementations
_WIRE = CompressionSpec(format="int8", block=QBLOCK)


# ---------------------------------------------------------------------------
# shape-preserving blockwise int8 quant — thin aliases over the shared
# codec (kept: the qwZ gather below and test_zeropp address this module)
# ---------------------------------------------------------------------------
def quantize_lastdim(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray, int]:
    """Symmetric int8 per-QBLOCK along the last dim, keeping array rank:
    returns (codes int8 [..., Dpad], scales fp32 [..., Dpad/QBLOCK], D)."""
    return quantize_blockwise(x, _WIRE)


def dequantize_lastdim(q: jnp.ndarray, scale: jnp.ndarray, d: int,
                       dtype=jnp.bfloat16) -> jnp.ndarray:
    return dequantize_blockwise(q, scale, d, dtype)


# ---------------------------------------------------------------------------
# qwZ: quantized weight gather
# ---------------------------------------------------------------------------
def _qwz_gather_impl(leaf: jnp.ndarray, gathered_spec: P, mesh,
                     dtype) -> jnp.ndarray:
    q, s, d = quantize_lastdim(leaf)
    # the barriers pin the s8 dtype across the resharding boundary: without
    # them XLA folds convert(s8)->convert(f32) away and gathers fp32
    q, s = jax.lax.optimization_barrier((q, s))
    q_spec = P(*(tuple(gathered_spec) + (None,) * (q.ndim - len(gathered_spec))))
    s_spec = P(*(tuple(q_spec) + (None,))[:s.ndim])
    q = jax.lax.with_sharding_constraint(q, NamedSharding(mesh, q_spec))
    s = jax.lax.with_sharding_constraint(s, NamedSharding(mesh, s_spec))
    q, s = jax.lax.optimization_barrier((q, s))
    return dequantize_lastdim(q, s, d, dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def qwz_gather(leaf: jnp.ndarray, gathered_spec: P, mesh,
               dtype=jnp.bfloat16) -> jnp.ndarray:
    """fp master shard -> int8 codes (sharded) -> FORCED gather of codes +
    scales (the constraint boundary makes XLA move int8, not bf16) ->
    dequantized compute-dtype value (reference quantized all-gather,
    partition_parameters.py:704).

    Straight-through gradient: the quantize/round is communication
    compression, not part of the learned function — the cotangent passes
    through as if the gather were exact (the reference quantizes only the
    collective payload; grads stay full precision)."""
    return _qwz_gather_impl(leaf, gathered_spec, mesh, dtype)


def _qwz_fwd(leaf, gathered_spec, mesh, dtype):
    out = _qwz_gather_impl(leaf, gathered_spec, mesh, dtype)
    return out, jnp.zeros((0,), leaf.dtype)  # dtype token (residuals must be jax types)


def _qwz_bwd(gathered_spec, mesh, dtype, dtype_token, ct):
    return (ct.astype(dtype_token.dtype),)


qwz_gather.defvjp(_qwz_fwd, _qwz_bwd)


# ---------------------------------------------------------------------------
# qgZ: quantized gradient reduce (all-to-all int8, reference
# all_to_all_quant_reduce, coalesced_collectives.py:31)
# ---------------------------------------------------------------------------
def _a2a_quant_reduce_flat(g: jnp.ndarray, axis: str, world: int) -> jnp.ndarray:
    """Inside shard_map: ``g`` is this rank's partial gradient [n]; returns
    the mean over ``axis`` with int8 codes on the wire in both hops — the
    shared layer's two-hop compressed all-reduce
    (``comm/collectives/compressed.all_reduce``: quantized all_to_all
    reduce-scatter, then quantized all_gather back to a full gradient).
    Leaves whose target sharding IS data-partitioned skip hop 2 via
    ``_a2a_quant_reduce_scattered``."""
    from ...comm.collectives import compressed as _cc

    # out_dtype fp32: the mean is fp32-accumulated and the engine casts to
    # grad_accum_dtype itself — rounding to the compute dtype here would
    # add a lossy step the pre-rebase implementation never had
    return _cc.all_reduce(g, op="mean", axis=axis, spec=_WIRE,
                          out_dtype=jnp.float32)


def _a2a_quant_reduce_scattered(g: jnp.ndarray, axis: str, world: int,
                                shard_dim: int) -> jnp.ndarray:
    """Inside shard_map: rank r keeps only ITS shard of the mean along
    ``shard_dim`` — the slot layout IS the target sharding, so the single
    all_to_all is the whole reduction (reference all_to_all_quant_reduce
    returns the scattered partition, coalesced_collectives.py:31; no
    follow-up gather).  Delegates to the shared layer's compressed
    reduce-scatter."""
    from ...comm.collectives import compressed as _cc

    return _cc.reduce_scatter(g, op="mean", axis=axis, spec=_WIRE,
                              scatter_dim=shard_dim, out_dtype=jnp.float32)


def _entry_axes(entry) -> tuple:
    if entry is None:
        return ()
    return tuple(entry) if isinstance(entry, (tuple, list)) else (entry,)


def _scatter_dim(target_spec: Optional[P], chunk_spec: P, axis: str) -> int:
    """Dim where the all_to_all slot layout lands EXACTLY on the target
    sharding: the target entry must be the chunked-grad entry plus a
    trailing ``axis`` (XLA orders a tuple entry major-to-minor, so slots
    within the already-applied prefix shard ARE the ``axis`` blocks), and
    every other dim's entry must agree.  -1 -> two-hop fallback."""
    if target_spec is None:
        return -1
    t = tuple(target_spec)
    c = tuple(chunk_spec)[1:]  # drop the leading chunk (data) dim

    def cent(d):
        return _entry_axes(c[d]) if d < len(c) else ()

    for dim, entry in enumerate(t):
        ax = _entry_axes(entry)
        if not ax or ax[-1] != axis:
            continue
        if cent(dim) != ax[:-1]:
            continue
        if all(_entry_axes(t[d]) == cent(d) for d in range(len(t)) if d != dim):
            return dim
    return -1


def quantized_grad_reduce(grads_chunked: Any, chunk_specs: Any, mesh,
                          axis: str = DATA_AXIS,
                          target_specs: Any = None,
                          bucket_bytes: int = 0,
                          errors: Optional[Any] = None) -> Any:
    """Reduce vmap-chunked gradients (leading dim = data-axis chunks) with
    int8 on the wire.  ``chunk_specs``: per-leaf PartitionSpec of the
    chunked grads (leading entry = the data axis).

    ``target_specs`` (per-leaf, optional): the accumulation buffer's
    sharding.  Leaves whose target shards a dim by exactly ``axis`` return
    the SCATTERED partition straight out of the all_to_all — one collective,
    no hop-2 gather (reference all_to_all_quant_reduce returns the
    partitioned result, coalesced_collectives.py:31).  Other leaves get the
    fully-reduced value via the two-hop path, coalesced into size-targeted
    flat buckets (``bucket_bytes`` — ``zero_optimization.overlap_bucket_mb``;
    0 = per-leaf): one collective chain per bucket instead of per leaf, so
    small leaves stop paying a full two-hop each and the per-bucket chains
    overlap (bucket k's exchange under bucket k+1's quantize).

    ``errors``: per-BUCKET error-feedback residuals for the flat (two-hop)
    path — global ``[W, S_k]`` fp32 arrays, axis-sharded, carried across
    steps in ``engine.state.comm_errors`` so checkpoint/resume keeps them
    (the EF lifecycle contract, docs/COMM.md).  Returns
    ``(grads, new_errors)`` then.  Scattered-path leaves are single-hop
    and stay EF-free by construction.  ``errors=None``: the legacy exact
    payload layout and single-value return, bit-identical to HEAD."""
    from ...comm.collectives.bucketer import bucketed_map

    world = mesh.shape[axis]
    flat_chunk, treedef = jax.tree_util.tree_flatten(chunk_specs)
    flat_target = (jax.tree_util.tree_flatten(target_specs)[0]
                   if target_specs is not None else [None] * len(flat_chunk))
    grads_flat = treedef.flatten_up_to(grads_chunked)
    sdims = [_scatter_dim(t, c, axis)
             for t, c in zip(flat_target, flat_chunk)]
    ef = errors is not None
    errors = list(errors) if ef else []
    n_leaves = len(flat_chunk)
    ef_wire = CompressionSpec(format=_WIRE.format, block=_WIRE.block,
                              error_feedback=True)

    def body(flat_tree, errs):
        out: list = [None] * len(flat_tree)
        flat_path = []
        for i, (g, sd) in enumerate(zip(flat_tree, sdims)):
            if sd >= 0:
                # the slot layout IS the target sharding: per leaf by
                # construction (distinct scatter layouts cannot coalesce)
                out[i] = _a2a_quant_reduce_scattered(g[0], axis, world, sd)
            else:
                flat_path.append(i)
        new_errs = []

        def reduce_bucket(flat, k):
            if not ef:
                return _a2a_quant_reduce_flat(flat, axis, world)
            from ...comm.collectives import compressed as _cc

            red, ne = _cc.all_reduce(flat, op="mean", axis=axis,
                                     spec=ef_wire, error=errs[k][0],
                                     out_dtype=jnp.float32)
            new_errs.append(ne[None])
            return red

        reduced = bucketed_map(
            [flat_tree[i][0] for i in flat_path], bucket_bytes,
            reduce_bucket, out_dtype=jnp.float32,
            align=(_WIRE.block if ef else 0))
        for i, o in zip(flat_path, reduced):
            out[i] = o
        return tuple(out) + tuple(new_errs)

    out_specs = tuple(
        (t if sd >= 0 else P(*tuple(c)[1:]))
        for c, t, sd in zip(flat_chunk, flat_target, sdims)) \
        + tuple(P(axis) for _ in errors)
    fn = shard_map(body, mesh=mesh,
                   in_specs=(tuple(flat_chunk), tuple(P(axis) for _ in errors)),
                   out_specs=out_specs, check_vma=False)
    out_flat = fn(tuple(grads_flat), tuple(errors))
    grads = jax.tree_util.tree_unflatten(treedef, out_flat[:n_leaves])
    if not ef:
        return grads
    return grads, list(out_flat[n_leaves:])
