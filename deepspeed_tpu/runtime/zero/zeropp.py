"""ZeRO++ — quantized/hierarchical ZeRO communication.

Reference parity:
  * qwZ — quantized weight all-gather: ``zero_quantized_weights``
    (reference zero/partition_parameters.py:704 ``AllGatherCoalescedHandle``
    quantized path, csrc/quantization swizzled-quant kernels).
  * qgZ — quantized gradient reduce via all-to-all:
    ``zero_quantized_gradients`` (reference
    runtime/comm/coalesced_collectives.py:31 ``all_to_all_quant_reduce``).
  * hpZ — hierarchical (secondary) weight partition:
    ``zero_hpz_partition_size`` (reference engine.py:1101-1113 config keys,
    secondary tensors in stage3) — implemented in strategy.py by sharding
    master/grads over (repl x data) while stage-3 live-param gathers ride
    only the small 'data' axis.

TPU-native expression: the collectives are XLA's, so compression is
expressed as dtype changes across forced sharding boundaries —
quantize (sharded) -> constraint to the gathered spec (XLA all-gathers the
int8 codes + fp32 block scales) -> dequantize.  The bytes on the wire are
the int8 payload, verifiable in the compiled HLO (test_zeropp.py greps the
collective ops' operand dtypes).
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ...parallel.mesh import DATA_AXIS
from ...utils.logging import logger

QBLOCK = 128  # quantization block (reference csrc/quantization group size)


# ---------------------------------------------------------------------------
# shape-preserving blockwise int8 quant (jnp: fuses + shards under SPMD)
# ---------------------------------------------------------------------------
def quantize_lastdim(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray, int]:
    """Symmetric int8 per-QBLOCK along the last dim, keeping array rank:
    returns (codes int8 [..., Dpad], scales fp32 [..., Dpad/QBLOCK], D)."""
    d = x.shape[-1]
    pad = (-d) % QBLOCK
    if pad:
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, pad)])
    blocks = x.reshape(*x.shape[:-1], x.shape[-1] // QBLOCK, QBLOCK)
    blocks = blocks.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(blocks), -1), 1e-12) / 127.0
    q = jnp.clip(jnp.round(blocks / scale[..., None]), -127, 127)
    return q.reshape(*x.shape).astype(jnp.int8), scale, d


def dequantize_lastdim(q: jnp.ndarray, scale: jnp.ndarray, d: int,
                       dtype=jnp.bfloat16) -> jnp.ndarray:
    blocks = q.reshape(*q.shape[:-1], q.shape[-1] // QBLOCK, QBLOCK)
    x = blocks.astype(jnp.float32) * scale[..., None]
    x = x.reshape(*q.shape)
    if d != q.shape[-1]:
        x = x[..., :d]
    return x.astype(dtype)


# ---------------------------------------------------------------------------
# qwZ: quantized weight gather
# ---------------------------------------------------------------------------
def _qwz_gather_impl(leaf: jnp.ndarray, gathered_spec: P, mesh,
                     dtype) -> jnp.ndarray:
    q, s, d = quantize_lastdim(leaf)
    # the barriers pin the s8 dtype across the resharding boundary: without
    # them XLA folds convert(s8)->convert(f32) away and gathers fp32
    q, s = jax.lax.optimization_barrier((q, s))
    q_spec = P(*(tuple(gathered_spec) + (None,) * (q.ndim - len(gathered_spec))))
    s_spec = P(*(tuple(q_spec) + (None,))[:s.ndim])
    q = jax.lax.with_sharding_constraint(q, NamedSharding(mesh, q_spec))
    s = jax.lax.with_sharding_constraint(s, NamedSharding(mesh, s_spec))
    q, s = jax.lax.optimization_barrier((q, s))
    return dequantize_lastdim(q, s, d, dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def qwz_gather(leaf: jnp.ndarray, gathered_spec: P, mesh,
               dtype=jnp.bfloat16) -> jnp.ndarray:
    """fp master shard -> int8 codes (sharded) -> FORCED gather of codes +
    scales (the constraint boundary makes XLA move int8, not bf16) ->
    dequantized compute-dtype value (reference quantized all-gather,
    partition_parameters.py:704).

    Straight-through gradient: the quantize/round is communication
    compression, not part of the learned function — the cotangent passes
    through as if the gather were exact (the reference quantizes only the
    collective payload; grads stay full precision)."""
    return _qwz_gather_impl(leaf, gathered_spec, mesh, dtype)


def _qwz_fwd(leaf, gathered_spec, mesh, dtype):
    out = _qwz_gather_impl(leaf, gathered_spec, mesh, dtype)
    return out, jnp.zeros((0,), leaf.dtype)  # dtype token (residuals must be jax types)


def _qwz_bwd(gathered_spec, mesh, dtype, dtype_token, ct):
    return (ct.astype(dtype_token.dtype),)


qwz_gather.defvjp(_qwz_fwd, _qwz_bwd)


# ---------------------------------------------------------------------------
# qgZ: quantized gradient reduce (all-to-all int8, reference
# all_to_all_quant_reduce, coalesced_collectives.py:31)
# ---------------------------------------------------------------------------
def _a2a_quant_reduce_flat(g: jnp.ndarray, axis: str, world: int) -> jnp.ndarray:
    """Inside shard_map: ``g`` is this rank's partial gradient [n]; returns
    the mean over ``axis`` with int8 codes on the wire in both hops.

    hop 1: split into ``world`` slots, quantize, all_to_all (each rank
           receives its slot from everyone), dequantize + mean  — the
           quantized reduce-scatter.
    hop 2: quantize the reduced slot, all_gather, dequantize — the
           quantized all-gather back to a full gradient.
    """
    n = g.size
    slot = -(-n // world)
    slot = -(-slot // QBLOCK) * QBLOCK  # whole quant blocks per slot
    pad = slot * world - n
    flat = jnp.pad(g.reshape(-1), (0, pad)) if pad else g.reshape(-1)
    chunks = flat.reshape(world, slot)

    q, s, _ = quantize_lastdim(chunks)  # [W, slot] int8, [W, slot/B] f32
    # split_axis=0/concat_axis=0 with tiled=False: receive [W, slot] — rank
    # r's row w is rank w's chunk r
    q_r = jax.lax.all_to_all(q, axis, split_axis=0, concat_axis=0)
    s_r = jax.lax.all_to_all(s, axis, split_axis=0, concat_axis=0)
    partials = dequantize_lastdim(q_r, s_r, slot, jnp.float32)  # [W, slot]
    reduced = jnp.mean(partials, axis=0)  # this rank's slot, reduced

    # hop 2 gathers the reduced slots back to a full gradient (int8 wire).
    # For stage 2 the accumulation buffer is data-sharded, so XLA re-slices
    # the replicated result locally; returning the raw reduce-scattered slot
    # instead would save this hop but requires mapping the flat slot layout
    # onto each leaf's sharded dim — a follow-up optimization.
    q2, s2, _ = quantize_lastdim(reduced[None])  # [1, slot]
    q2 = jax.lax.all_gather(q2, axis, axis=0, tiled=True)  # [W, slot]
    s2 = jax.lax.all_gather(s2, axis, axis=0, tiled=True)
    full = dequantize_lastdim(q2, s2, slot, jnp.float32).reshape(-1)
    return full[:n].reshape(g.shape)


def quantized_grad_reduce(grads_chunked: Any, chunk_specs: Any, mesh,
                          axis: str = DATA_AXIS) -> Any:
    """Reduce vmap-chunked gradients (leading dim = data-axis chunks) with
    int8 on the wire.  ``chunk_specs``: per-leaf PartitionSpec of the
    chunked grads (leading entry = the data axis).  Returns the reduced
    (mean) gradient tree, replicated over ``axis``."""

    def body(tree):
        # local view: chunk dim W sharded over W ranks -> leading dim 1
        return jax.tree_util.tree_map(
            lambda g: _a2a_quant_reduce_flat(g[0], axis, mesh.shape[axis]),
            tree)

    out_specs = jax.tree_util.tree_map(
        lambda spec: P(*tuple(spec)[1:]), chunk_specs)
    fn = jax.shard_map(body, mesh=mesh, in_specs=(chunk_specs,),
                       out_specs=out_specs, check_vma=False)
    return fn(grads_chunked)
