"""Optimizer-state host offload (ZeRO-Offload) and NVMe spill (ZeRO-Infinity).

Reference: ``zero/offload_config.py`` + CPU-Adam (csrc/adam) + swap_tensor
(``runtime/swap_tensor/partitioned_param_swapper.py``).  TPU design: fp32
master weights + Adam moments live in host RAM as numpy arrays; each
gradient-accumulation boundary pulls the (already reduced) grads from HBM,
runs the SIMD C++ Adam (ops/cpu/adam.py), and pushes compute-dtype params
back — HBM then only holds compute params + grads.  With device="nvme",
moment arrays are spilled to disk through the AIO engine between steps
(prefetched back right before the update, reads overlapped per-leaf).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from ...ops.cpu.adam import DeepSpeedCPUAdam
from ...utils.logging import log_dist, logger


def scale_and_clip(grads_flat: List[np.ndarray], denom: float,
                   grad_clip: float,
                   shapes: Optional[List[Tuple[int, ...]]] = None
                   ) -> Tuple[List[np.ndarray], float]:
    """Scale grads by 1/denom, compute the global norm, clip.  Shared by the
    plain/SuperOffload/ZenFlow host optimizers so clipping semantics can't
    drift between them.  ``shapes=None`` flattens each leaf (the C++ Adam
    works on contiguous 1-D shards); otherwise leaves are reshaped."""
    gs = []
    sq = 0.0
    for i, g in enumerate(grads_flat):
        g = np.asarray(g, np.float32)
        g = (g.ravel() if shapes is None else g.reshape(shapes[i])) / denom
        sq += float(np.dot(g.ravel(), g.ravel()))
        gs.append(g)
    norm = float(np.sqrt(sq))
    if grad_clip > 0 and norm > grad_clip:
        scale = grad_clip / (norm + 1e-6)
        gs = [g * scale for g in gs]
    return gs, norm


class HostOffloadedOptimizer:
    """Holds host master state and applies boundary steps."""

    def __init__(self, abstract_params: Any, optimizer_config: Dict[str, Any],
                 grad_clip: float = 0.0, nvme_path: Optional[str] = None,
                 aio_threads: int = 4):
        params = dict(optimizer_config.get("params") or {})
        otype = str(optimizer_config.get("type", "adamw")).lower()
        wd = float(params.get("weight_decay", 0.0))
        if "lion" in otype:
            from ...ops.cpu.lion import DeepSpeedCPULion

            betas = params.get("betas", (0.9, 0.99))
            self.cpu_adam = DeepSpeedCPULion(
                lr=float(params.get("lr", 1e-4)),
                betas=(float(betas[0]), float(betas[1])), weight_decay=wd)
        elif "adagrad" in otype:
            from ...ops.cpu.adagrad import DeepSpeedCPUAdagrad

            self.cpu_adam = DeepSpeedCPUAdagrad(
                lr=float(params.get("lr", 1e-2)),
                eps=float(params.get("eps", 1e-10)), weight_decay=wd)
        else:
            betas = params.get("betas", (0.9, 0.999))
            self.cpu_adam = DeepSpeedCPUAdam(
                lr=float(params.get("lr", 1e-3)),
                betas=(float(betas[0]), float(betas[1])),
                eps=float(params.get("eps", 1e-8)),
                weight_decay=wd,
                adamw_mode=bool(params.get("adam_w_mode", True)) or
                otype.endswith("w"),
            )
        self.grad_clip = grad_clip
        self.leaves, self.treedef = jax.tree_util.tree_flatten(abstract_params)
        self.master: List[np.ndarray] = []
        self.nvme_path = nvme_path
        self._aio = None
        if nvme_path:
            import os

            from ...ops.cpu.aio import AsyncIOHandle

            os.makedirs(nvme_path, exist_ok=True)
            self._aio = AsyncIOHandle(thread_count=aio_threads)

    def initialize_master(self, init_params: Any) -> None:
        flat = jax.tree_util.tree_leaves(init_params)
        self.master = [np.asarray(jax.device_get(x), np.float32).ravel().copy()
                       for x in flat]
        log_dist(f"host-offload: {sum(m.size for m in self.master) / 1e6:.1f}M "
                 f"fp32 master elements in host RAM")

    def _moment_dicts(self):
        """Per-kernel moment buffers: Adam has m+v, Lion m only, Adagrad v
        only — spill/fetch whatever exists."""
        out = []
        for attr in ("_m", "_v"):
            d = getattr(self.cpu_adam, attr, None)
            if d is not None:
                out.append((attr.strip("_"), d))
        return out

    def _spill(self, key: int) -> None:
        if self._aio is None:
            return
        dicts = self._moment_dicts()
        if any(d.get(key) is None for _, d in dicts):
            return
        if not any(key in d for _, d in dicts):
            return
        for name, d in dicts:
            self._aio.async_pwrite(d[key], f"{self.nvme_path}/{name}_{key}.bin")
        self._aio.drain()
        for _, d in dicts:
            d[key] = None  # type: ignore[assignment]  (spilled)

    def _fetch(self, key: int, n: int) -> None:
        if self._aio is None:
            return
        # key present but None => spilled to disk; absent => first step, the
        # kernel will zero-init
        dicts = self._moment_dicts()
        if not dicts or key not in dicts[0][1] or dicts[0][1][key] is not None:
            return
        bufs = []
        for name, d in dicts:
            buf = np.empty(n, np.float32)
            self._aio.async_pread(buf, f"{self.nvme_path}/{name}_{key}.bin")
            bufs.append((d, buf))
        self._aio.drain()
        for d, buf in bufs:
            d[key] = buf

    def apply_step(self, grads_flat: List[np.ndarray], lr: float,
                   denom: float) -> Tuple[List[np.ndarray], float]:
        """Run the C++ Adam on every leaf; returns (new master leaves,
        global grad norm)."""
        gs, norm = scale_and_clip(grads_flat, denom, self.grad_clip)
        for i, g in enumerate(gs):
            if self.master[i].size != g.size:
                raise ValueError(f"grad/master size mismatch at leaf {i}")
            self._fetch(i, g.size)
            self.cpu_adam.step(self.master[i], g, key=i, lr=lr)
            self._spill(i)
        return self.master, norm

    def master_as_tree(self, like: Any) -> Any:
        flat = jax.tree_util.tree_leaves(like)
        arrs = [m.reshape(x.shape) for m, x in zip(self.master, flat)]
        return jax.tree_util.tree_unflatten(jax.tree_util.tree_structure(like), arrs)

    def state_dict(self) -> Dict[str, Any]:
        return {"adam": self.cpu_adam.state_dict(),
                "master": [m.copy() for m in self.master]}

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self.cpu_adam.load_state_dict(sd["adam"])
        self.master = [np.asarray(m) for m in sd["master"]]
