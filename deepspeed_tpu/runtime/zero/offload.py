"""Optimizer-state host offload (ZeRO-Offload) and NVMe spill (ZeRO-Infinity).

Reference: ``zero/offload_config.py`` + CPU-Adam (csrc/adam) + swap_tensor
(``runtime/swap_tensor/partitioned_param_swapper.py``,
``pipelined_optimizer_swapper.py:52``).  TPU design: fp32 master weights +
Adam moments live in host RAM as numpy arrays; each gradient-accumulation
boundary pulls the (already reduced) grads from HBM, runs the SIMD C++ Adam
(ops/cpu/adam.py), and pushes compute-dtype params back — HBM then only
holds compute params + grads.

With device="nvme" the boundary step is PIPELINED like the reference's
PipelinedOptimizerSwapper: leaf i+1's moment reads are in flight while
leaf i runs its Adam step (ping-pong read handles, so waiting on leaf i
never waits on i+1's prefetch), and spills drain in windows behind the
compute instead of per leaf.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from ...ops.cpu.adam import DeepSpeedCPUAdam
from ...utils.logging import log_dist, logger


def scale_and_clip(grads_flat: List[np.ndarray], denom: float,
                   grad_clip: float,
                   shapes: Optional[List[Tuple[int, ...]]] = None
                   ) -> Tuple[List[np.ndarray], float]:
    """Scale grads by 1/denom, compute the global norm, clip.  Shared by the
    plain/SuperOffload/ZenFlow host optimizers so clipping semantics can't
    drift between them.  ``shapes=None`` flattens each leaf (the C++ Adam
    works on contiguous 1-D shards); otherwise leaves are reshaped."""
    gs = []
    sq = 0.0
    for i, g in enumerate(grads_flat):
        g = np.asarray(g, np.float32)
        g = (g.ravel() if shapes is None else g.reshape(shapes[i])) / denom
        sq += float(np.dot(g.ravel(), g.ravel()))
        gs.append(g)
    norm = float(np.sqrt(sq))
    if grad_clip > 0 and norm > grad_clip:
        scale = grad_clip / (norm + 1e-6)
        gs = [g * scale for g in gs]
    return gs, norm


class HostOffloadedOptimizer:
    """Holds host master state and applies boundary steps."""

    def __init__(self, abstract_params: Any, optimizer_config: Dict[str, Any],
                 grad_clip: float = 0.0, nvme_path: Optional[str] = None,
                 aio_threads: int = 4, shared_handles: bool = True):
        params = dict(optimizer_config.get("params") or {})
        otype = str(optimizer_config.get("type", "adamw")).lower()
        wd = float(params.get("weight_decay", 0.0))
        if "lion" in otype:
            from ...ops.cpu.lion import DeepSpeedCPULion

            betas = params.get("betas", (0.9, 0.99))
            self.cpu_adam = DeepSpeedCPULion(
                lr=float(params.get("lr", 1e-4)),
                betas=(float(betas[0]), float(betas[1])), weight_decay=wd)
        elif "adagrad" in otype:
            from ...ops.cpu.adagrad import DeepSpeedCPUAdagrad

            self.cpu_adam = DeepSpeedCPUAdagrad(
                lr=float(params.get("lr", 1e-2)),
                eps=float(params.get("eps", 1e-10)), weight_decay=wd)
        else:
            betas = params.get("betas", (0.9, 0.999))
            self.cpu_adam = DeepSpeedCPUAdam(
                lr=float(params.get("lr", 1e-3)),
                betas=(float(betas[0]), float(betas[1])),
                eps=float(params.get("eps", 1e-8)),
                weight_decay=wd,
                adamw_mode=bool(params.get("adam_w_mode", True)) or
                otype.endswith("w"),
            )
        self.grad_clip = grad_clip
        self.leaves, self.treedef = jax.tree_util.tree_flatten(abstract_params)
        self.master: List[np.ndarray] = []
        self.nvme_path = nvme_path
        self._nvme = bool(nvme_path)
        self._aio = None
        #: spill-drain cadence: bounds host RAM to ~window live moment sets
        #: while keeping writes off the critical path
        self.spill_window = 4
        if nvme_path:
            import os

            os.makedirs(nvme_path, exist_ok=True)
        # shared_handles=False: a subclass brings its own per-worker handles
        # (SuperOffload); don't spawn idle shared IO threads
        if nvme_path and shared_handles:
            from ...ops.cpu.aio import AsyncIOHandle

            self._aio = AsyncIOHandle(thread_count=aio_threads)
            # ping-pong read handles: drain(one) waits only that handle's
            # in-flight prefetch, so fetch(i+1) rides through step(i)
            self._fetch_aio = [AsyncIOHandle(thread_count=max(1, aio_threads // 2)),
                               AsyncIOHandle(thread_count=max(1, aio_threads // 2))]
            self._inflight_fetch = [[], []]  # per slot: (key, [(dict, buf)])
            self._spill_pending: List[int] = []

    def initialize_master(self, init_params: Any) -> None:
        flat = jax.tree_util.tree_leaves(init_params)
        self.master = [np.asarray(jax.device_get(x), np.float32).ravel().copy()
                       for x in flat]
        log_dist(f"host-offload: {sum(m.size for m in self.master) / 1e6:.1f}M "
                 f"fp32 master elements in host RAM")

    # -- memory-ledger accounting (telemetry/memory.py providers) -----------
    def master_bytes(self) -> int:
        """Host RAM held by the fp32 master leaves."""
        return int(sum(m.nbytes for m in self.master if m is not None))

    def moment_bytes(self) -> int:
        """Host RAM held by RESIDENT optimizer moments (NVMe-spilled
        leaves are on disk, not RAM, and count 0)."""
        total = 0
        for _name, d in self._moment_dicts():
            for v in d.values():
                if v is not None:
                    total += int(v.nbytes)
        return total

    def _moment_dicts(self):
        """Per-kernel moment buffers: Adam has m+v, Lion m only, Adagrad v
        only — spill/fetch whatever exists."""
        out = []
        for attr in ("_m", "_v"):
            d = getattr(self.cpu_adam, attr, None)
            if d is not None:
                out.append((attr.strip("_"), d))
        return out

    # shared submit/install/free primitives: ONE copy of the on-disk layout
    # and guard logic, parameterized by handle, used by both the pipelined
    # boundary path (shared ping-pong handles) and SuperOffload's workers
    # (one private handle each — thread-safe because handles share no
    # in-flight state and the moment dicts are only written per-key).
    def _submit_fetch(self, aio, key: int, n: int):
        entries = []
        for name, d in self._moment_dicts():
            buf = np.empty(n, np.float32)
            aio.async_pread(buf, f"{self.nvme_path}/{name}_{key}.bin")
            entries.append((d, buf))
        return entries

    @staticmethod
    def _install_fetch(entries, key: int) -> None:
        for d, buf in entries:
            d[key] = buf

    def _submit_spill(self, aio, key: int) -> bool:
        dicts = self._moment_dicts()
        # key absent or already spilled (None) -> nothing to write
        if not dicts or any(d.get(key) is None for _, d in dicts):
            return False
        for name, d in dicts:
            aio.async_pwrite(d[key], f"{self.nvme_path}/{name}_{key}.bin")
        return True

    def _free_moments(self, key: int) -> None:
        for _, d in self._moment_dicts():
            d[key] = None  # type: ignore[assignment]  (spilled)

    def _fetch_with(self, aio, key: int, n: int) -> None:
        """Synchronous fetch on a private handle (SuperOffload workers)."""
        if not self._nvme or not self._needs_fetch(key):
            return
        entries = self._submit_fetch(aio, key, n)
        aio.drain()
        self._install_fetch(entries, key)

    def _spill_with(self, aio, key: int) -> None:
        """Spill leaf ``key``'s moments on a private handle and free them."""
        if not self._nvme:
            return
        if self._submit_spill(aio, key):
            aio.drain()
            self._free_moments(key)

    # -- pipelined NVMe swap (reference PipelinedOptimizerSwapper,
    # runtime/swap_tensor/pipelined_optimizer_swapper.py:52) ----------------
    def _needs_fetch(self, key: int) -> bool:
        dicts = self._moment_dicts()
        # key present but None => spilled to disk; absent => first step, the
        # kernel will zero-init
        return bool(dicts) and key in dicts[0][1] and dicts[0][1][key] is None

    def _issue_fetch(self, key: int, n: int, slot: int) -> None:
        """Submit leaf ``key``'s moment preads on ping-pong handle ``slot``
        without waiting (the prefetch of the pipelined swapper)."""
        if self._aio is None or not self._needs_fetch(key):
            return
        entries = self._submit_fetch(self._fetch_aio[slot], key, n)
        self._inflight_fetch[slot].append((key, entries))

    def _commit_fetch(self, slot: int) -> None:
        """Wait for handle ``slot``'s in-flight reads and install them."""
        if self._aio is None or not self._inflight_fetch[slot]:
            return
        self._fetch_aio[slot].drain()
        for key, entries in self._inflight_fetch[slot]:
            self._install_fetch(entries, key)
        self._inflight_fetch[slot] = []

    def _issue_spill(self, key: int) -> None:
        if self._aio is None:
            return
        if self._submit_spill(self._aio, key):
            self._spill_pending.append(key)

    def _flush_spills(self) -> None:
        """Wait for in-flight writes, then free the spilled moments."""
        if self._aio is None or not self._spill_pending:
            return
        self._aio.drain()
        for key in self._spill_pending:
            self._free_moments(key)
        self._spill_pending = []

    def apply_step(self, grads_flat: List[np.ndarray], lr: float,
                   denom: float) -> Tuple[List[np.ndarray], float]:
        """Run the C++ Adam on every leaf; returns (new master leaves,
        global grad norm).  NVMe moments ride the pipelined swap: fetch of
        leaf i+1 overlaps the Adam step of leaf i, spills drain every
        ``spill_window`` leaves behind the compute."""
        gs, norm = scale_and_clip(grads_flat, denom, self.grad_clip)
        n = len(gs)
        for i, g in enumerate(gs):
            if self.master[i].size != g.size:
                raise ValueError(f"grad/master size mismatch at leaf {i}")
        if self._aio is None:
            for i, g in enumerate(gs):
                self.cpu_adam.step(self.master[i], g, key=i, lr=lr)
            return self.master, norm

        if n > 0:
            self._issue_fetch(0, gs[0].size, 0)
        if n > 1:
            self._issue_fetch(1, gs[1].size, 1)
        for i, g in enumerate(gs):
            slot = i % 2
            self._commit_fetch(slot)
            self.cpu_adam.step(self.master[i], g, key=i, lr=lr)
            self._issue_spill(i)
            if i + 2 < n:
                self._issue_fetch(i + 2, gs[i + 2].size, slot)
            if len(self._spill_pending) >= self.spill_window:
                self._flush_spills()
        self._flush_spills()
        return self.master, norm

    def master_as_tree(self, like: Any) -> Any:
        flat = jax.tree_util.tree_leaves(like)
        arrs = [m.reshape(x.shape) for m, x in zip(self.master, flat)]
        return jax.tree_util.tree_unflatten(jax.tree_util.tree_structure(like), arrs)

    def state_dict(self) -> Dict[str, Any]:
        return {"adam": self.cpu_adam.state_dict(),
                "master": [m.copy() for m in self.master]}

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self.cpu_adam.load_state_dict(sd["adam"])
        self.master = [np.asarray(m) for m in sd["master"]]
