"""Optimizer-state host offload (ZeRO-Offload) and NVMe spill (ZeRO-Infinity).

Reference: ``zero/offload_config.py`` + CPU-Adam (csrc/adam) + swap_tensor
(``runtime/swap_tensor/partitioned_param_swapper.py``).  TPU design: fp32
master weights + Adam moments live in host RAM as numpy arrays; each
gradient-accumulation boundary pulls the (already reduced) grads from HBM,
runs the SIMD C++ Adam (ops/cpu/adam.py), and pushes compute-dtype params
back — HBM then only holds compute params + grads.  With device="nvme",
moment arrays are spilled to disk through the AIO engine between steps
(prefetched back right before the update, reads overlapped per-leaf).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from ...ops.cpu.adam import DeepSpeedCPUAdam
from ...utils.logging import log_dist, logger


def scale_and_clip(grads_flat: List[np.ndarray], denom: float,
                   grad_clip: float,
                   shapes: Optional[List[Tuple[int, ...]]] = None
                   ) -> Tuple[List[np.ndarray], float]:
    """Scale grads by 1/denom, compute the global norm, clip.  Shared by the
    plain/SuperOffload/ZenFlow host optimizers so clipping semantics can't
    drift between them.  ``shapes=None`` flattens each leaf (the C++ Adam
    works on contiguous 1-D shards); otherwise leaves are reshaped."""
    gs = []
    sq = 0.0
    for i, g in enumerate(grads_flat):
        g = np.asarray(g, np.float32)
        g = (g.ravel() if shapes is None else g.reshape(shapes[i])) / denom
        sq += float(np.dot(g.ravel(), g.ravel()))
        gs.append(g)
    norm = float(np.sqrt(sq))
    if grad_clip > 0 and norm > grad_clip:
        scale = grad_clip / (norm + 1e-6)
        gs = [g * scale for g in gs]
    return gs, norm


class HostOffloadedOptimizer:
    """Holds host master state and applies boundary steps."""

    def __init__(self, abstract_params: Any, optimizer_config: Dict[str, Any],
                 grad_clip: float = 0.0, nvme_path: Optional[str] = None,
                 aio_threads: int = 4):
        params = dict(optimizer_config.get("params") or {})
        betas = params.get("betas", (0.9, 0.999))
        self.cpu_adam = DeepSpeedCPUAdam(
            lr=float(params.get("lr", 1e-3)),
            betas=(float(betas[0]), float(betas[1])),
            eps=float(params.get("eps", 1e-8)),
            weight_decay=float(params.get("weight_decay", 0.0)),
            adamw_mode=bool(params.get("adam_w_mode", True)) or
            optimizer_config.get("type", "adamw").lower().endswith("w"),
        )
        self.grad_clip = grad_clip
        self.leaves, self.treedef = jax.tree_util.tree_flatten(abstract_params)
        self.master: List[np.ndarray] = []
        self.nvme_path = nvme_path
        self._aio = None
        if nvme_path:
            import os

            from ...ops.cpu.aio import AsyncIOHandle

            os.makedirs(nvme_path, exist_ok=True)
            self._aio = AsyncIOHandle(thread_count=aio_threads)

    def initialize_master(self, init_params: Any) -> None:
        flat = jax.tree_util.tree_leaves(init_params)
        self.master = [np.asarray(jax.device_get(x), np.float32).ravel().copy()
                       for x in flat]
        log_dist(f"host-offload: {sum(m.size for m in self.master) / 1e6:.1f}M "
                 f"fp32 master elements in host RAM")

    def _spill(self, key: int) -> None:
        if self._aio is None:
            return
        m = self.cpu_adam._m.get(key)
        v = self.cpu_adam._v.get(key)
        if m is None:
            return
        self._aio.async_pwrite(m, f"{self.nvme_path}/m_{key}.bin")
        self._aio.async_pwrite(v, f"{self.nvme_path}/v_{key}.bin")
        self._aio.drain()
        # release host copies (spilled)
        self.cpu_adam._m[key] = None  # type: ignore[assignment]
        self.cpu_adam._v[key] = None  # type: ignore[assignment]

    def _fetch(self, key: int, n: int) -> None:
        if self._aio is None:
            return
        # key present but None => spilled to disk; absent => first step, the
        # adam kernel will zero-init
        if key in self.cpu_adam._m and self.cpu_adam._m[key] is None:
            m = np.empty(n, np.float32)
            v = np.empty(n, np.float32)
            self._aio.async_pread(m, f"{self.nvme_path}/m_{key}.bin")
            self._aio.async_pread(v, f"{self.nvme_path}/v_{key}.bin")
            self._aio.drain()
            self.cpu_adam._m[key] = m
            self.cpu_adam._v[key] = v

    def apply_step(self, grads_flat: List[np.ndarray], lr: float,
                   denom: float) -> Tuple[List[np.ndarray], float]:
        """Run the C++ Adam on every leaf; returns (new master leaves,
        global grad norm)."""
        gs, norm = scale_and_clip(grads_flat, denom, self.grad_clip)
        for i, g in enumerate(gs):
            if self.master[i].size != g.size:
                raise ValueError(f"grad/master size mismatch at leaf {i}")
            self._fetch(i, g.size)
            self.cpu_adam.step(self.master[i], g, key=i, lr=lr)
            self._spill(i)
        return self.master, norm

    def master_as_tree(self, like: Any) -> Any:
        flat = jax.tree_util.tree_leaves(like)
        arrs = [m.reshape(x.shape) for m, x in zip(self.master, flat)]
        return jax.tree_util.tree_unflatten(jax.tree_util.tree_structure(like), arrs)

    def state_dict(self) -> Dict[str, Any]:
        return {"adam": self.cpu_adam.state_dict(),
                "master": [m.copy() for m in self.master]}

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self.cpu_adam.load_state_dict(sd["adam"])
        self.master = [np.asarray(m) for m in sd["master"]]
