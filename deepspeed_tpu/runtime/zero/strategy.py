"""ZeRO as sharding strategy.

The reference implements ZeRO with runtime hooks and hand-written bucketed
collectives (``runtime/zero/stage_1_and_2.py``, ``stage3.py``).  On TPU the
same memory partitioning is expressed *declaratively*: each stage is a rule
for which pieces of training state carry a sharded ``PartitionSpec`` over the
ZeRO mesh axes, and XLA-SPMD schedules the all-gathers / reduce-scatters that
the reference issues by hand (IPG buckets -> latency-hiding scheduler).

  stage 0: params/grads/optimizer replicated; grads psum over data.
  stage 1: optimizer state (and fp32 master weights) sharded.
  stage 2: + gradient accumulation buffer sharded (reduce-scatter not
           all-reduce — XLA derives this because the only consumer is the
           sharded update).
  stage 3: + parameters themselves sharded (FSDP); XLA all-gathers each
           layer's params just before use, frees after (the reference's
           fetch/release hooks, partitioned_param_coordinator.py:285/425).

MiCS (reference zero/mics.py): ``mics_shard_size`` limits sharding to
subgroups of the data axis — expressed by splitting the data axis logically.
Cited parity: DeepSpeedZeroOptimizer (stage_1_and_2.py:125),
DeepSpeedZeroOptimizer_Stage3 (stage3.py:129).
"""

from __future__ import annotations

import re
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ...parallel.mesh import (DATA_AXIS, EXPERT_AXIS, MeshTopology, REPL_AXIS,
                              ZERO_AXES)
from ...utils.logging import logger
from ..config import ZeroConfig

#: params whose leading dim is an expert dim are sharded over the expert axis
#: by the model's partition rules; their ZeRO axes exclude "expert".
PartitionRule = Tuple[str, P]


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


def _spec_axes(spec: P) -> set:
    used = set()
    for entry in spec:
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            used.update(entry)
        else:
            used.add(entry)
    return used


class ZeroShardingPlan:
    """Computes NamedShardings for params / master+optimizer / gradients."""

    def __init__(self, topology: MeshTopology, config: Optional[ZeroConfig] = None,
                 partition_rules: Optional[Sequence[PartitionRule]] = None):
        self.topology = topology
        self.config = config or ZeroConfig()
        self.stage = self.config.stage
        self.partition_rules = list(partition_rules or [])
        # effective shard group size (MiCS): -1 => whole zero axis group
        self._zero_axes = [a for a in ZERO_AXES if topology.axis_size(a) > 1]
        # hpZ (ZeRO++ hierarchical partition, reference engine.py:1101-1113):
        # master/grads shard over the FULL dp (repl x data) while stage-3
        # live-param gathers ride only the small 'data' axis ("intra-node"
        # secondary partition).  Mesh contract: data == hpz, repl == dp/hpz.
        self._state_zero_axes = self._zero_axes
        hpz = int(getattr(self.config, "zero_hpz_partition_size", 1) or 1)
        if hpz > 1:
            if getattr(self.config, "mics_shard_size", -1) and \
                    self.config.mics_shard_size > 1:
                raise ValueError("zero_hpz_partition_size and mics_shard_size "
                                 "are mutually exclusive uses of the repl axis")
            if topology.axis_size(DATA_AXIS) != hpz:
                raise ValueError(
                    f"zero_hpz_partition_size={hpz} needs mesh data axis == "
                    f"{hpz} and repl == dp/{hpz} (got data="
                    f"{topology.axis_size(DATA_AXIS)}, repl="
                    f"{topology.axis_size(REPL_AXIS)}); set mesh "
                    f"{{'repl': dp//{hpz}, 'data': {hpz}}}")
            if topology.axis_size(REPL_AXIS) > 1:
                self._state_zero_axes = [REPL_AXIS] + self._zero_axes

    # -- model-parallel (TP/EP) base spec -----------------------------------
    def base_spec(self, path_str: str, ndim: int) -> P:
        for pattern, spec in self.partition_rules:
            if re.search(pattern, path_str):
                if len(spec) > ndim:
                    raise ValueError(
                        f"Partition rule {pattern} spec {spec} has more dims than "
                        f"param {path_str} with ndim {ndim}")
                return P(*(tuple(spec) + (None,) * (ndim - len(spec))))
        return P(*((None,) * ndim))

    def _check_divisible(self, spec: P, shape: Tuple[int, ...], path_str: str) -> P:
        """Replicate (with a warning) instead of crashing at placement when a
        rule shards a dim the mesh doesn't divide — e.g. an AutoTP-classified
        classification head with num_labels < tp_size."""
        sizes = self.topology.axis_sizes
        for dim, entry in enumerate(spec):
            if entry is None:
                continue
            axes = tuple(entry) if isinstance(entry, (tuple, list)) else (entry,)
            need = int(np.prod([sizes[a] for a in axes]))
            if dim >= len(shape) or shape[dim] % need != 0:
                logger.warning(
                    f"partition rule for {path_str}: dim {dim} of {shape} not "
                    f"divisible by mesh axes {axes} (={need}); replicating")
                return P(*((None,) * len(shape)))
        return spec

    # -- zero extension ------------------------------------------------------
    def _extend_with_zero(self, spec: P, shape: Tuple[int, ...], path_str: str,
                          axes: Optional[Sequence[str]] = None) -> P:
        """Insert the ZeRO axes on the largest dim they divide evenly."""
        zero_axes = [a for a in (axes if axes is not None else self._zero_axes)
                     if a not in _spec_axes(spec)]
        # expert params: their replicas only exist within an expert group, so
        # the expert axis is already consumed by the rule; nothing special.
        if not zero_axes:
            return spec
        zsize = int(np.prod([self.topology.axis_size(a) for a in zero_axes]))
        if zsize == 1:
            return spec
        # candidate dims: unsharded first (add axes alone), then sharded dims
        # (append zero axes after the existing model axes on that dim).
        best_dim, best_len, best_combined = -1, -1, None
        mesh_sizes = self.topology.axis_sizes
        for dim, dim_size in enumerate(shape):
            entry = spec[dim] if dim < len(spec) else None
            existing = () if entry is None else (tuple(entry) if isinstance(entry, (tuple, list)) else (entry,))
            existing_size = int(np.prod([mesh_sizes[a] for a in existing])) if existing else 1
            if dim_size % (existing_size * zsize) == 0 and dim_size > best_len:
                best_dim, best_len = dim, dim_size
                best_combined = existing + tuple(zero_axes)
        if best_dim < 0:
            logger.debug(f"ZeRO: param {path_str} shape {shape} not divisible by "
                         f"{zsize}; replicating")
            return spec
        entries = list(spec) + [None] * (len(shape) - len(spec))
        entries[best_dim] = best_combined if len(best_combined) > 1 else best_combined[0]
        return P(*entries)

    # -- public API ----------------------------------------------------------
    def param_spec(self, path_str: str, shape: Tuple[int, ...]) -> P:
        """Sharding of the live (compute) parameters.

        ``stage3_param_persistence_threshold`` (reference
        partitioned_param_coordinator persistence, stage3.py
        persistence_threshold): live copies of params at or below the
        threshold stay unpartitioned — the reference keeps them permanently
        gathered to skip tiny fetch collectives; here they simply never get
        a ZeRO axis (master/optimizer state still shards)."""
        spec = self._check_divisible(self.base_spec(path_str, len(shape)), shape, path_str)
        if self.stage >= 3:
            persist = int(getattr(self.config,
                                  "stage3_param_persistence_threshold", 0) or 0)
            n_elem = int(np.prod(shape)) if shape else 1
            if n_elem <= persist:
                return spec
            spec = self._extend_with_zero(spec, shape, path_str)
        return spec

    def master_spec(self, path_str: str, shape: Tuple[int, ...]) -> P:
        """Sharding of fp32 master weights + optimizer moments (hpZ: over the
        full repl x data group)."""
        spec = self._check_divisible(self.base_spec(path_str, len(shape)), shape, path_str)
        if self.stage >= 1:
            spec = self._extend_with_zero(spec, shape, path_str,
                                          self._state_zero_axes)
        return spec

    def grad_spec(self, path_str: str, shape: Tuple[int, ...]) -> P:
        """Sharding of the gradient-accumulation buffer."""
        spec = self._check_divisible(self.base_spec(path_str, len(shape)), shape, path_str)
        if self.stage >= 2:
            spec = self._extend_with_zero(spec, shape, path_str,
                                          self._state_zero_axes)
        return spec

    # -- tree-level helpers --------------------------------------------------
    def _kind_fn(self, kind: str) -> Callable[[str, Tuple[int, ...]], P]:
        return {"param": self.param_spec, "master": self.master_spec,
                "grad": self.grad_spec}[kind]

    def tree_specs(self, tree: Any, kind: str) -> Any:
        """PartitionSpec pytree (same structure as ``tree``) for a
        parameter-shaped pytree.  kind in {"param", "master", "grad"}."""
        fn = self._kind_fn(kind)
        return jax.tree_util.tree_map_with_path(
            lambda path, leaf: fn(_path_str(path), tuple(getattr(leaf, "shape", ()))), tree)

    def tree_shardings(self, tree: Any, kind: str) -> Any:
        fn = self._kind_fn(kind)
        mesh = self.topology.mesh
        return jax.tree_util.tree_map_with_path(
            lambda path, leaf: NamedSharding(
                mesh, fn(_path_str(path), tuple(getattr(leaf, "shape", ())))), tree)

    def constrain(self, tree: Any, kind: str) -> Any:
        """Apply with_sharding_constraint to a pytree inside jit."""
        fn = self._kind_fn(kind)
        mesh = self.topology.mesh
        return jax.tree_util.tree_map_with_path(
            lambda path, leaf: jax.lax.with_sharding_constraint(
                leaf, NamedSharding(mesh, fn(_path_str(path), tuple(leaf.shape)))), tree)
