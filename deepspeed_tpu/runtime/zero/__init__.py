"""ZeRO public surface.

Reference parity: ``deepspeed.zero`` — ``Init`` (partition_parameters.py:878)
and ``GatheredParameters`` (partition_parameters.py) plus the sharding plan
that replaces the hook machinery on TPU.
"""

from __future__ import annotations

import contextlib
from typing import Any, Optional

import jax
import numpy as np

from .offload import HostOffloadedOptimizer  # noqa: F401
from .strategy import ZeroShardingPlan  # noqa: F401


class Init:
    """API-parity context for constructing a model with partitioned params
    (reference ``zero.Init``: patches tensor constructors so params are born
    sharded).

    On TPU no patching is needed: model definitions are pure init functions
    (ModelSpec.init_params), and the engine jits them with sharded
    ``out_shardings`` so full replicas never materialize
    (engine._init_state).  The context is therefore a no-op that exists so
    reference-style code — ``with zero.Init(): model = build()`` — runs
    unchanged; it records the config it was given for inspection.
    """

    def __init__(self, module: Any = None, data_parallel_group: Any = None,
                 mem_efficient_linear: bool = True, remote_device: str = None,
                 pin_memory: bool = False, config_dict_or_path: Any = None,
                 **kwargs):
        self.config = dict(kwargs, remote_device=remote_device,
                           pin_memory=pin_memory,
                           config=config_dict_or_path)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


@contextlib.contextmanager
def GatheredParameters(params: Any, modifier_rank: Optional[int] = 0,
                       fwd_module: Any = None, enabled: bool = True):
    """Yield a fully-materialized host copy of (possibly sharded) params
    (reference ``zero.GatheredParameters``: allgather partitioned params
    for inspection/modification inside the context).

    JAX arrays are immutable, so in-place modification inside the context
    cannot write back; use the yielded numpy tree to build new params.
    """
    if not enabled or params is None:
        yield params
        return
    gathered = jax.tree_util.tree_map(
        lambda x: np.asarray(jax.device_get(x)) if hasattr(x, "sharding") else x,
        params)
    yield gathered
