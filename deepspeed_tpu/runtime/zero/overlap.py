"""Fine-grained compute/collective overlap for the fused train step.

Today's step compiles the ZeRO gradient exchange as one post-backward
block: the backward scan accumulates every layer's cotangent into the
stacked gradient buffer and GSPMD places the data-axis reduce wherever
its propagation lands it — in practice hoisted out of the layer loops,
serialized against nothing.  That is the exposed-communication problem
T3 (PAPERS.md) attacks with fine-grained tracking/triggering, and the
in-tree Domino module solves for TP by making the overlap *be* the
dataflow graph.

This module is the ZeRO-side analogue.  Sharding *constraints* cannot
pin a reduction point (GSPMD folds them into propagation — measured:
at stage 1 a replicated cotangent constraint makes the partitioner
replicate the whole backward, 6x FLOPs), so the scanned transformer
block is instead wrapped in a **shard_map over the data axis** (other
mesh axes stay auto/GSPMD — TP rules untouched), where collectives are
explicit ops the partitioner must execute in place:

* **stage <= 2** — layer params enter the body replicated; shard_map's
  transpose inserts an explicit ``psum`` over ``data`` for each leaf's
  cotangent *inside the backward scan trip*, right where the partial
  grads materialize.  A ``custom_vjp`` hook groups the cotangents into
  size-targeted buckets (``overlap_bucket_mb``,
  ``comm/collectives/bucketer.py``) between ``optimization_barrier``
  pairs, so each bucket forms one reduce wavefront the latency-hiding
  scheduler can hide under the next layer's backward compute.
* **stage 3** — layer params enter the body as their ZeRO shards and
  the hook's fwd issues an explicit ``lax.all_gather`` per leaf at the
  body top (bucket-barriered): with the 2x-unrolled scan
  (``zero3_param_prefetch``) each trip holds two independent
  gather->compute chains, so layer i+1's gather overlaps layer i's
  compute — the double buffer.  The gather's AD transpose is an
  explicit ``psum_scatter``: the grad reduce-scatter rides the
  backward loop for free, per layer, no handles or waits.

Residual discipline: the hooked (gathered) param values are tagged
``overlap_params`` and the body is checkpointed with a policy that
refuses to save the whole hook chain (:func:`_overlap_remat_policy`) —
the backward re-derives them (a re-gather at stage 3) instead of
saving every layer's gathered params, which would defeat stage-3
partitioning (the carry-based double buffer tried earlier failed
exactly this way; see the scan comment in models/transformer.py).

The wrap is value-identity — per-shard compute is the same arithmetic
and the explicit collectives compute the same sums — so overlap-on
training is bit-exact with overlap-off (asserted per-run by
``bench.py --ab-overlap`` and tests/unit/test_overlap.py).  Every
bucket logs a trace-time collective event (``grad_bucket_reduce``)
into the span ring; the engine publishes the exposure split
(``telemetry/overlap.py``) as
``deepspeed_tpu_train_overlapped_fraction`` /
``_exposed_collective_seconds``.

Compressed overlap (docs/COMM.md "Compressed overlap"): with a
``CompressionSpec`` on the plan the in-loop exchange moves codes + block
scales instead of fp32 — stage <= 2 buckets ride the shared two-hop
compressed all-reduce (or the hierarchical three-hop when the data axis
is split), stage 3's explicit ``psum_scatter`` becomes the quantized
reduce-scatter — with ONE error-feedback residual per bucket carried as
a train-state leaf (``TrainState.comm_errors``), so residuals survive
donation, checkpoint and preemption-resume bit-identically.

Mechanically the compressed path cannot let the cotangent cross the
shard_map boundary (a replicated input's transpose is a full-width fp
``psum`` — exactly the bytes being eliminated), so the hook threads two
aux channels per bucket through the scan as extra xs:

* ``gslot`` — a zeros input whose COTANGENT carries the reduced bucket
  gradient out (axis-sharded ``[L, W, S]``: every rank writes the
  identical reduced value into its own row, so the boundary transpose
  is communication-free and the engine collapses rows locally);
* ``eslot`` — the residual input whose cotangent carries the NEW
  residual (same shape; each rank's row is its own compensation).

The param leaves whose exchange rides the gslot channel are
``stop_gradient``-ed inside the body, so their boundary cotangent is a
symbolic zero — no psum is ever emitted for them.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import NamedSharding, PartitionSpec as P

from ...comm.collectives.bucketer import assign_buckets, bucketed_map
from ...comm.collectives.codec import CompressionSpec
from ...telemetry.spans import record_event
from ...utils.logging import logger

#: checkpoint_name tag on hook outputs (see module docstring)
OVERLAP_TAG = "overlap_params"


def _overlap_remat_policy():
    """Residual policy for the wrapped block: save the default residual
    set EXCEPT the hook's (gathered) parameter values — those are
    re-derived in the backward loop from the sharded inputs (a
    re-gather at stage 3), never stacked per layer.

    ``save_anything_except_these_names(TAG)`` alone is NOT enough: the
    name tag sits on the hook's final output, and partial eval simply
    saves the nearest saveable ANCESTOR — the (identical) gather /
    barrier output right above the tag.  The whole hook chain must be
    unsaveable, and inside the wrapped body the hook is the only
    producer of ``all_gather`` / ``optimization_barrier`` values, so
    the policy blocks those primitives by NAME (stable public strings;
    everything else keeps the default residual choice)."""
    #: primitives only the hook emits inside the wrapped body — their
    #: outputs are the (gathered) param values that must be re-derived,
    #: not saved per layer
    blocked = ("name", "all_gather", "optimization_barrier", "psum_scatter")

    def policy(prim, *_args, **params):
        pname = getattr(prim, "name", str(prim))
        if pname == "name":
            return params.get("name") != OVERLAP_TAG
        return pname not in blocked

    return policy


class OverlapPlan:
    """Static (trace-time) description of the shard_map'd block wrap.

    Built once per engine from the abstract stacked layer tree; passed
    to the model per trace (``TransformerConfig.overlap_plan``, the
    same engine-set-per-trace pattern as ``qwz``).  Hashable by
    identity — it is a ``custom_vjp`` nondiff argument."""

    TAG = OVERLAP_TAG

    def __init__(self, mesh, axis: str, treedef, paths: Sequence[str],
                 leaf_specs: Sequence[P], gather_dims: Sequence[Optional[int]],
                 buckets: Sequence[Sequence[int]],
                 bucket_bytes: Sequence[int],
                 bucket_step_bytes: Sequence[int],
                 compression: Optional[CompressionSpec] = None,
                 hier_inner: int = 0, n_layers: int = 1,
                 slice_shapes: Sequence[Tuple[int, ...]] = ()):
        self.mesh = mesh
        self.axis = axis
        self.treedef = treedef
        self.paths = tuple(paths)
        self.leaf_specs = tuple(leaf_specs)
        self.gather_dims = tuple(gather_dims)
        self.buckets = tuple(tuple(b) for b in buckets)
        self.bucket_bytes = tuple(int(b) for b in bucket_bytes)
        #: per-optimizer-step coverage of each bucket (slice bytes x
        #: n_layers) — what the trace-time events report, so the span
        #: accounting adds up against the structural totals
        self.bucket_step_bytes = tuple(int(b) for b in bucket_step_bytes)
        #: in-loop codec (None = the PR-12 exact fp exchange, bit-compat)
        self.compression = compression
        #: > 0: the stage<=2 in-loop reduce takes the hierarchical
        #: three-hop shape (intra-slice reduce-scatter, quantized
        #: inter-slice exchange, intra-slice gather)
        self.hier_inner = int(hier_inner)
        self.n_layers = int(n_layers)
        self.slice_shapes = tuple(tuple(s) for s in slice_shapes)
        # per-bucket comm-channel layout (compressed mode): the flat
        # (non-gathered) leaves coalesce — block-ALIGNED, so bucketed ==
        # unbucketed stays bit-exact — into one payload of _gslot_sizes[k]
        # elements reduced by ONE two-hop/hier chain; gathered leaves
        # follow per-leaf.  The bucket's eslot holds the flat payload's
        # residual at [0, gslot_size) and each gathered leaf's full-slice
        # residual after it — ONE residual leaf per bucket.
        self._flat_idx: List[List[int]] = []
        self._gath_idx: List[List[int]] = []
        self._offsets: List[dict] = []
        self._gslot_sizes: List[int] = []
        self._eslot_sizes: List[int] = []
        if compression is not None:
            blk = compression.block
            for idxs in self.buckets:
                fi = [i for i in idxs if self.gather_dims[i] is None]
                gi = [i for i in idxs if self.gather_dims[i] is not None]
                offs, off = {}, 0
                for i in fi:
                    offs[i] = off
                    n = int(np.prod(self.slice_shapes[i] or (1,)))
                    off += -(-n // blk) * blk
                sflat = off
                for i in gi:
                    offs[i] = off
                    off += int(np.prod(self.slice_shapes[i] or (1,)))
                self._flat_idx.append(fi)
                self._gath_idx.append(gi)
                self._offsets.append(offs)
                self._gslot_sizes.append(sflat)
                self._eslot_sizes.append(off if compression.error_feedback
                                         else 0)

    # ------------------------------------------------------- comm channel
    @property
    def error_feedback(self) -> bool:
        return (self.compression is not None
                and self.compression.error_feedback)

    def eslot_key(self, k: int) -> str:
        return f"b{k:03d}"  # zero-padded: checkpoint key order == bucket order

    def init_errors(self):
        """Fresh per-bucket EF residual leaves for ``TrainState.comm_errors``
        (eager; engine init / loud reset).  Global ``[L, W, S]`` fp32,
        axis-sharded on W: each rank stores only its own compensation."""
        W = int(self.mesh.shape[self.axis])
        sh = NamedSharding(self.mesh, P(None, self.axis))
        return {
            self.eslot_key(k): jax.device_put(
                jnp.zeros((self.n_layers, W, self._eslot_sizes[k]),
                          jnp.float32), sh)
            for k in range(len(self.buckets))}

    def grad_slots(self):
        """In-trace zero gslots (the reduced-gradient cotangent channel);
        rebuilt every step — only the RESIDUALS are state."""
        W = int(self.mesh.shape[self.axis])
        sh = NamedSharding(self.mesh, P(None, self.axis))
        return tuple(
            jax.lax.with_sharding_constraint(
                jnp.zeros((self.n_layers, W, self._gslot_sizes[k]),
                          jnp.float32), sh)
            for k in range(len(self.buckets)))

    def residual_bytes(self) -> int:
        """Total bytes of EF residual state held in train state (the
        ``deepspeed_tpu_comm_compression_residual_bytes`` gauge)."""
        W = int(self.mesh.shape[self.axis])
        return sum(self.n_layers * W * s * 4 for s in self._eslot_sizes)

    def residual_norms(self, comm_errors) -> Dict[str, Any]:
        """Per-bucket L2 norm of the carried EF residuals (in-trace fp32
        scalars, keyed like ``init_errors``).  residual_bytes says how
        much compensation state exists STRUCTURALLY; these say how big
        the compensation actually IS — a bucket norm growing without
        bound means error feedback is diverging, not catching up.  Rides
        the numerics stats tree; the engine publishes it as the
        ``deepspeed_tpu_comm_compression_residual_norm`` gauge."""
        slots = comm_errors.get("overlap", {}) if comm_errors else {}
        return {k: jnp.sqrt(jnp.sum(jnp.square(v.astype(jnp.float32))))
                for k, v in slots.items()}

    def eslot_state(self, comm_errors):
        """The eslot tree for this step: the carried train-state
        residuals under error feedback, zero-width placeholders when the
        codec runs straight-through (the hook signature is uniform)."""
        if self.error_feedback:
            return comm_errors["overlap"]
        W = int(self.mesh.shape[self.axis])
        return {self.eslot_key(k): jnp.zeros((self.n_layers, W, 0),
                                             jnp.float32)
                for k in range(len(self.buckets))}

    def comm_tuples(self, comm) -> Tuple[Tuple[Any, ...], Tuple[Any, ...]]:
        """Split the model-side comm tree ``{"g": seq, "e": dict}`` into
        the hook's positional (gslots, eslots) tuples, bucket-ordered."""
        g = tuple(comm["g"])
        e = tuple(comm["e"][self.eslot_key(k)]
                  for k in range(len(self.buckets)))
        return g, e

    def merge_comm_grads(self, layer_grads: Any, gslot_cts: Sequence[Any]
                         ) -> Any:
        """Engine-side (in-trace, post-``jax.grad``): replace the
        stop-gradient-zeroed flat-leaf grads with the reduced values the
        gslot cotangents carried out.  Every rank's ``[L, W, S]`` row
        holds the identical reduced payload, so the collapse is a LOCAL
        squeeze (out_specs claims replication; no collective)."""
        from ...utils.jax_compat import shard_map

        leaves = list(self.treedef.flatten_up_to(layer_grads))
        ks = [k for k in range(len(self.buckets))
              if self._flat_idx[k] and self._gslot_sizes[k]]
        if not ks:
            return layer_grads
        collapse = shard_map(
            lambda *gs: tuple(g[:, 0] for g in gs), mesh=self.mesh,
            in_specs=tuple(P(None, self.axis) for _ in ks),
            out_specs=tuple(P() for _ in ks), check_vma=False,
            axis_names={self.axis})
        cols = collapse(*[gslot_cts[k] for k in ks])
        for k, col in zip(ks, cols):
            for i in self._flat_idx[k]:
                off = self._offsets[k][i]
                n_i = int(np.prod(self.slice_shapes[i] or (1,)))
                leaves[i] = col[:, off:off + n_i].reshape(
                    (self.n_layers,) + self.slice_shapes[i])
        return jax.tree_util.tree_unflatten(self.treedef, leaves)

    # ------------------------------------------------------------- model API
    def wrap_block(self, raw_block, has_mask: bool):
        """Wrap ``raw_block(x, positions, mask, layer_tree) -> (y, aux)``
        in the data-axis shard_map (model side; the scan body calls the
        result with the same signature).  ``has_mask=False`` drops the
        mask slot (shard_map in_specs cannot carry a None leaf)."""
        from ...utils.jax_compat import shard_map

        plan = self

        def body(x, positions, *rest):
            mask = rest[0] if has_mask else None
            leaves = rest[1:] if has_mask else rest
            leaves = _overlap_hook(tuple(leaves), plan)
            leaves = tuple(checkpoint_name(v, OVERLAP_TAG) for v in leaves)
            layer = jax.tree_util.tree_unflatten(plan.treedef, leaves)
            return raw_block(x, positions, mask, layer)

        # residual discipline INSIDE the body: the policy must see the
        # hook call and its name tags, and shard_map residuals are
        # opaque from outside — so the checkpoint sits under the
        # shard_map
        body = jax.checkpoint(body, policy=_overlap_remat_policy())

        bsp = P(self.axis)  # batch-leading operands shard the lead dim
        mask_specs = (bsp,) if has_mask else ()
        sm = shard_map(
            body, mesh=self.mesh,
            in_specs=(bsp, bsp) + mask_specs + self.leaf_specs,
            out_specs=(bsp, P()),
            check_vma=False, axis_names={self.axis})

        sm_c = None
        if self.compression is not None:
            nl, nb = len(self.paths), len(self.buckets)

            def body_c(x, positions, *rest):
                mask = rest[0] if has_mask else None
                rest = rest[1:] if has_mask else rest
                leaves = tuple(rest[:nl])
                gslots = tuple(rest[nl:nl + nb])
                eslots = tuple(rest[nl + nb:])
                # flat-path leaves deliver their gradient via the gslot
                # cotangent channel; stop_gradient makes their boundary
                # cotangent a SYMBOLIC zero, so the shard_map transpose
                # emits no fp psum for them
                prepped = tuple(
                    lax.stop_gradient(v) if plan.gather_dims[i] is None
                    else v for i, v in enumerate(leaves))
                out_leaves = _overlap_hook_comm(prepped, gslots, eslots,
                                                plan)
                out_leaves = tuple(checkpoint_name(v, OVERLAP_TAG)
                                   for v in out_leaves)
                layer = jax.tree_util.tree_unflatten(plan.treedef,
                                                     out_leaves)
                return raw_block(x, positions, mask, layer)

            body_c = jax.checkpoint(body_c, policy=_overlap_remat_policy())
            comm_specs = tuple(P(self.axis) for _ in range(2 * nb))
            sm_c = shard_map(
                body_c, mesh=self.mesh,
                in_specs=(bsp, bsp) + mask_specs + self.leaf_specs
                + comm_specs,
                out_specs=(bsp, P()),
                check_vma=False, axis_names={self.axis})

        world = int(self.mesh.shape[self.axis])

        def wrapped(x, positions, mask, layer_tree, comm=None):
            if comm is not None and x.shape[0] % world != 0:
                raise ValueError(
                    f"compressed overlap: batch {x.shape[0]} does not "
                    f"divide the data axis ({world}) — training batches "
                    "divide by construction; the eval path must not pass "
                    "comm state")
            if x.shape[0] % world != 0:
                # e.g. an eval_batch whose batch does not divide the
                # data axis: the wrap cannot shard it — run the plain
                # GSPMD block (training batches divide by construction)
                from ...utils.logging import warning_once

                warning_once(
                    f"overlap wrap bypassed: batch {x.shape[0]} does not "
                    f"divide the data axis ({world})")
                return raw_block(x, positions, mask, layer_tree)
            leaves, treedef = jax.tree_util.tree_flatten(layer_tree)
            if treedef != self.treedef:
                raise ValueError(
                    "overlap plan was built for a different layer structure "
                    f"(plan {self.treedef} vs model {treedef}); rebuild the "
                    "engine after changing the model")
            args = (x, positions) + ((mask,) if has_mask else ()) + tuple(leaves)
            if comm is not None and sm_c is not None:
                gslots, eslots = self.comm_tuples(comm)
                return sm_c(*(args + gslots + eslots))
            return sm(*args)

        return wrapped

    # ------------------------------------------------------------ internals
    def _fwd(self, leaves: Tuple[Any, ...]) -> Tuple[Any, ...]:
        """Inside the body: stage-3 leaves are local ZeRO shards — issue
        their all-gathers per bucket at the body top, barrier-pinned, so
        the unrolled trip's two chains start independent."""
        if all(d is None for d in self.gather_dims):
            return leaves
        out = list(leaves)
        for k, idxs in enumerate(self.buckets):
            group = jax.lax.optimization_barrier(
                tuple(out[i] for i in idxs))
            gathered = []
            for i, v in zip(idxs, group):
                d = self.gather_dims[i]
                if d is not None:
                    v = lax.all_gather(v, self.axis, axis=d, tiled=True)
                gathered.append(v)
            group = jax.lax.optimization_barrier(tuple(gathered))
            for i, v in zip(idxs, group):
                out[i] = v
        return tuple(out)

    def _bwd(self, cts: Tuple[Any, ...]) -> Tuple[Any, ...]:
        """Inside the transposed body: per bucket, group the cotangents
        between barriers and issue the gather transposes (an explicit
        ``psum_scatter`` — the per-layer grad reduce-scatter) as one
        wavefront per backward trip.  Identity (stage <= 2) leaves pass
        through barrier-grouped; shard_map's boundary then psums them
        over the axis — also inside the trip."""
        out: List[Any] = list(cts)
        for k, idxs in enumerate(self.buckets):
            group = jax.lax.optimization_barrier(
                tuple(out[i] for i in idxs))
            reduced = []
            for i, v in zip(idxs, group):
                d = self.gather_dims[i]
                if d is not None:
                    # all_gather's transpose, written out so the bucket
                    # barriers pin it: this rank keeps ITS shard of the
                    # summed cotangent
                    v = lax.psum_scatter(v, self.axis,
                                         scatter_dimension=d, tiled=True)
                reduced.append(v)
            group = jax.lax.optimization_barrier(tuple(reduced))
            # trace-time collective event (the comm._log convention):
            # one point per bucket per traced program, carrying the
            # bytes the bucket reduces — the overlap accountant reads
            # these against the compute spans
            _record_bucket_reduce(self.bucket_step_bytes[k], k, len(idxs))
            for i, v in zip(idxs, group):
                out[i] = v
        return tuple(out)

    def _bwd_compressed(self, cts: Tuple[Any, ...],
                        eslots: Tuple[Any, ...]):
        """Compressed in-loop exchange (inside the transposed body, per
        backward scan trip): per bucket, the flat leaves coalesce into
        ONE block-aligned payload reduced by the shared compressed
        two-hop (or hierarchical three-hop) — codes + scales on the
        wire — and each gathered (stage-3) leaf's ``psum_scatter``
        becomes a quantized reduce-scatter.  Error feedback compensates
        from the bucket's eslot row and the NEW residual leaves through
        the eslot cotangent; the reduced flat payload leaves through the
        gslot cotangent (see module docstring).

        Returns ``(leaf_cts, gslot_cts, eslot_cts)``."""
        from ...comm.collectives import compressed as _cc

        spec = self.compression
        ef = spec.error_feedback
        # reduce_scatter branches on spec.error_feedback itself, so the
        # bucket spec is used as-is in both modes
        rs_spec = spec
        out: List[Any] = list(cts)
        gslot_cts: List[Any] = []
        eslot_cts: List[Any] = []
        for k, idxs in enumerate(self.buckets):
            group = jax.lax.optimization_barrier(
                tuple(out[i] for i in idxs))
            vals = dict(zip(idxs, group))
            e_all = eslots[k][0] if ef else None  # local [S_e] row
            reduced = {}
            e_parts_g = []
            for i in self._gath_idx[k]:
                v = vals[i]
                d = self.gather_dims[i]
                if ef:
                    off = self._offsets[k][i]
                    n_i = int(np.prod(self.slice_shapes[i] or (1,)))
                    err = e_all[off:off + n_i].reshape(v.shape)
                    red, ne = _cc.reduce_scatter(
                        v, op="sum", axis=self.axis, spec=rs_spec,
                        scatter_dim=d, error=err)
                    e_parts_g.append(ne.reshape(-1))
                else:
                    red = _cc.reduce_scatter(v, op="sum", axis=self.axis,
                                             spec=rs_spec, scatter_dim=d)
                reduced[i] = red.astype(v.dtype)
            fi = self._flat_idx[k]
            new_e_flat = None
            if fi:
                sflat = self._gslot_sizes[k]
                err = e_all[:sflat] if ef else None
                R, new_e_flat = _compressed_bucket_reduce(
                    [vals[i] for i in fi], err, spec, self.axis,
                    self.hier_inner)
                gslot_cts.append(R[None])
                for i in fi:
                    # dies at the body's stop_gradient (symbolic zero at
                    # the boundary); the real value rode the gslot
                    reduced[i] = jnp.zeros_like(vals[i])
            else:
                gslot_cts.append(jnp.zeros((1, 0), jnp.float32))
            if ef:
                parts = ([new_e_flat] if new_e_flat is not None else []) \
                    + e_parts_g
                flat_e = (jnp.concatenate(parts) if len(parts) > 1
                          else parts[0])
                eslot_cts.append(flat_e[None].astype(jnp.float32))
            else:
                eslot_cts.append(jnp.zeros_like(eslots[k]))
            new_group = jax.lax.optimization_barrier(
                tuple(reduced[i] for i in idxs))
            _record_bucket_reduce(self.bucket_step_bytes[k], k, len(idxs),
                                  compressed=True, format=spec.format)
            for i, v in zip(idxs, new_group):
                out[i] = v
        return tuple(out), tuple(gslot_cts), tuple(eslot_cts)


def _compressed_bucket_reduce(leaves: Sequence[Any], error: Optional[Any],
                              spec: CompressionSpec, axis: str,
                              hier_inner: int):
    """The compressed IN-LOOP bucket reducer: coalesce the bucket's flat
    leaves through ``bucketer.bucketed_map`` — the ONE coalesce pipeline
    every bucketed reducer shares (lint: ``grad-overlap``) — into one
    block-aligned fp32 payload, then run ONE compressed all-reduce chain
    over it: the shared two-hop (all_to_all + all_gather, codes on the
    wire both hops) or, with ``hier_inner``, the hierarchical three-hop.

    Returns ``(reduced_flat_payload, new_error_or_None)``."""
    from ...comm.collectives import compressed as _cc
    from ...comm.collectives.hierarchical import hier_all_reduce

    ef = spec.error_feedback and error is not None
    run_spec = spec if ef else dataclasses.replace(spec,
                                                   error_feedback=False)
    holder = {}

    def reduce_flat(flat, _k):
        if hier_inner:
            r = hier_all_reduce(flat, op="sum", axis=axis, inner=hier_inner,
                                spec=run_spec,
                                error=error if ef else None)
            red, holder["e"] = r if ef else (r, None)
        elif ef:
            # hop2_ef=False: the hop-2 owner reinjection is slot-layout
            # dependent; only the layout-stable hop-1 residual keeps
            # bucketed == unbucketed bit-exact (see compressed.all_reduce)
            red, holder["e"] = _cc.all_reduce(
                flat, op="sum", axis=axis, spec=run_spec, error=error,
                out_dtype=jnp.float32, hop2_ef=False)
        else:
            red = _cc.all_reduce(flat, op="sum", axis=axis, spec=run_spec,
                                 out_dtype=jnp.float32)
        holder["R"] = red
        return red

    bucketed_map(leaves, 1 << 62, reduce_flat, out_dtype=jnp.float32,
                 align=spec.block)
    return holder["R"], holder.get("e")


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _overlap_hook_comm(leaves: Tuple[Any, ...], gslots: Tuple[Any, ...],
                       eslots: Tuple[Any, ...], plan: OverlapPlan):
    """The compressed-overlap hook: forward identical to the exact hook
    (stage-3 gathers stay fp — gradient compression only); the backward
    routes every layer-bucket through the codec and hijacks the
    gslot/eslot input cotangents as the gradient/residual out-channels
    (they are scan xs, so the per-trip values stack into the
    ``[L, W, S]`` train-state layout)."""
    return plan._fwd(leaves)


def _overlap_hook_comm_fwd(leaves, gslots, eslots, plan):
    return plan._fwd(leaves), (eslots,)


def _overlap_hook_comm_bwd(plan, res, cts):
    (eslots,) = res
    return plan._bwd_compressed(cts, eslots)


_overlap_hook_comm.defvjp(_overlap_hook_comm_fwd, _overlap_hook_comm_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _overlap_hook(leaves: Tuple[Any, ...], plan: OverlapPlan):
    return plan._fwd(leaves)


def _overlap_hook_fwd(leaves, plan):
    return plan._fwd(leaves), None


def _overlap_hook_bwd(plan, _res, cts):
    return (plan._bwd(cts),)


_overlap_hook.defvjp(_overlap_hook_fwd, _overlap_hook_bwd)


def record_tail_reduce(nbytes: int) -> None:
    """Trace-time event for gradient bytes NOT covered by the hook (the
    non-layer leaves — embeddings, head, final norm — whose reduce stays
    post-backward).  One owner site for the span name."""
    record_event("grad_tail_reduce", cat="comm", bytes=int(nbytes),
                 overlapped=False)


def _record_bucket_reduce(nbytes: int, bucket: int, leaves: int,
                          compressed: bool = False,
                          format: Optional[str] = None) -> None:
    """ONE owner site for the ``grad_bucket_reduce`` trace event (the
    exact and compressed in-loop reducers share it; the span lint pins
    single ownership)."""
    attrs = dict(bytes=int(nbytes), bucket=int(bucket), leaves=int(leaves),
                 overlapped=True)
    if compressed:
        attrs.update(compressed=True, format=format)
    record_event("grad_bucket_reduce", cat="comm", **attrs)


def _entry_axes(entry) -> tuple:
    if entry is None:
        return ()
    return tuple(entry) if isinstance(entry, (tuple, list)) else (entry,)


def build_overlap_plan(zero_plan, abstract_layers: Any, *,
                       bucket_bytes: int, axis: str, stage: int,
                       grad_dtype,
                       compression: Optional[CompressionSpec] = None,
                       hier_inner: int = 0) -> Optional[OverlapPlan]:
    """Derive the wrap's static plan from the stacked layer tree.

    ``abstract_layers``: ``state.params["layers"]`` (stacked, leading
    dim = n_layers) — shapes/dtypes only.  ``axis``: the (single) batch
    mesh axis the wrap manages manually.  At ``stage`` 3 each leaf's
    in-body spec is its live ZeRO shard (gathered explicitly by the
    hook); below 3 the leaves enter replicated over ``axis``.
    ``compression``/``hier_inner``: the in-loop codec and hierarchy
    split for the compressed-overlap path (None/0 = exact fp exchange).
    """
    from .strategy import _path_str

    flat, treedef = jax.tree_util.tree_flatten_with_path(abstract_layers)
    if not flat:
        return None
    mesh = zero_plan.topology.mesh
    paths, leaf_specs, gather_dims, sizes, step_sizes = [], [], [], [], []
    slice_shapes = []
    grad_itemsize = np.dtype(grad_dtype).itemsize
    for path, leaf in flat:
        pstr = "layers/" + _path_str(path)
        shape = tuple(leaf.shape)
        paths.append(pstr)
        n_layers = shape[0] or 1
        slice_shapes.append(shape[1:])
        step_sizes.append(int(np.prod(shape)) * grad_itemsize)
        sizes.append(int(np.prod(shape)) // n_layers * grad_itemsize)
        gdim = None
        if stage >= 3:
            # the live param's stacked spec, restricted to `axis`, minus
            # the leading layer dim = where this leaf's ZeRO shard lives
            # inside the body (and therefore its explicit gather dim)
            full = zero_plan.param_spec(pstr, shape)
            for dim, entry in enumerate(tuple(full)[1:]):
                if axis in _entry_axes(entry):
                    gdim = dim
                    break
        if gdim is None:
            leaf_specs.append(P(*((None,) * (len(shape) - 1))))
        else:
            entries = [None] * (len(shape) - 1)
            entries[gdim] = axis
            leaf_specs.append(P(*entries))
        gather_dims.append(gdim)
    buckets = assign_buckets(sizes, bucket_bytes)
    bucket_sizes = [sum(sizes[i] for i in b) for b in buckets]
    bucket_step = [sum(step_sizes[i] for i in b) for b in buckets]
    logger.info(
        f"overlap plan: {len(flat)} layer leaves -> {len(buckets)} "
        f"bucket(s) (target {bucket_bytes / 2**20:.1f} MB, stage {stage}, "
        f"gathered={sum(d is not None for d in gather_dims)}"
        + (f", {compression.format} in-loop wire"
           + (" + EF" if compression.error_feedback else "")
           + (f", hier inner={hier_inner}" if hier_inner else "")
           if compression is not None else "") + ")")
    n_layers = tuple(flat[0][1].shape)[0] or 1
    return OverlapPlan(mesh, axis, treedef, paths, leaf_specs, gather_dims,
                       buckets, bucket_sizes, bucket_step,
                       compression=compression, hier_inner=hier_inner,
                       n_layers=n_layers, slice_shapes=slice_shapes)
