"""Fine-grained compute/collective overlap for the fused train step.

Today's step compiles the ZeRO gradient exchange as one post-backward
block: the backward scan accumulates every layer's cotangent into the
stacked gradient buffer and GSPMD places the data-axis reduce wherever
its propagation lands it — in practice hoisted out of the layer loops,
serialized against nothing.  That is the exposed-communication problem
T3 (PAPERS.md) attacks with fine-grained tracking/triggering, and the
in-tree Domino module solves for TP by making the overlap *be* the
dataflow graph.

This module is the ZeRO-side analogue.  Sharding *constraints* cannot
pin a reduction point (GSPMD folds them into propagation — measured:
at stage 1 a replicated cotangent constraint makes the partitioner
replicate the whole backward, 6x FLOPs), so the scanned transformer
block is instead wrapped in a **shard_map over the data axis** (other
mesh axes stay auto/GSPMD — TP rules untouched), where collectives are
explicit ops the partitioner must execute in place:

* **stage <= 2** — layer params enter the body replicated; shard_map's
  transpose inserts an explicit ``psum`` over ``data`` for each leaf's
  cotangent *inside the backward scan trip*, right where the partial
  grads materialize.  A ``custom_vjp`` hook groups the cotangents into
  size-targeted buckets (``overlap_bucket_mb``,
  ``comm/collectives/bucketer.py``) between ``optimization_barrier``
  pairs, so each bucket forms one reduce wavefront the latency-hiding
  scheduler can hide under the next layer's backward compute.
* **stage 3** — layer params enter the body as their ZeRO shards and
  the hook's fwd issues an explicit ``lax.all_gather`` per leaf at the
  body top (bucket-barriered): with the 2x-unrolled scan
  (``zero3_param_prefetch``) each trip holds two independent
  gather->compute chains, so layer i+1's gather overlaps layer i's
  compute — the double buffer.  The gather's AD transpose is an
  explicit ``psum_scatter``: the grad reduce-scatter rides the
  backward loop for free, per layer, no handles or waits.

Residual discipline: the hooked (gathered) param values are tagged
``overlap_params`` and the body is checkpointed with a policy that
refuses to save the whole hook chain (:func:`_overlap_remat_policy`) —
the backward re-derives them (a re-gather at stage 3) instead of
saving every layer's gathered params, which would defeat stage-3
partitioning (the carry-based double buffer tried earlier failed
exactly this way; see the scan comment in models/transformer.py).

The wrap is value-identity — per-shard compute is the same arithmetic
and the explicit collectives compute the same sums — so overlap-on
training is bit-exact with overlap-off (asserted per-run by
``bench.py --ab-overlap`` and tests/unit/test_overlap.py).  Every
bucket logs a trace-time collective event (``grad_bucket_reduce``)
into the span ring; the engine publishes the exposure split
(``telemetry/overlap.py``) as
``deepspeed_tpu_train_overlapped_fraction`` /
``_exposed_collective_seconds``.
"""

from __future__ import annotations

import functools
from typing import Any, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax import lax
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import PartitionSpec as P

from ...comm.collectives.bucketer import assign_buckets
from ...telemetry.spans import record_event
from ...utils.logging import logger

#: checkpoint_name tag on hook outputs (see module docstring)
OVERLAP_TAG = "overlap_params"


def _overlap_remat_policy():
    """Residual policy for the wrapped block: save the default residual
    set EXCEPT the hook's (gathered) parameter values — those are
    re-derived in the backward loop from the sharded inputs (a
    re-gather at stage 3), never stacked per layer.

    ``save_anything_except_these_names(TAG)`` alone is NOT enough: the
    name tag sits on the hook's final output, and partial eval simply
    saves the nearest saveable ANCESTOR — the (identical) gather /
    barrier output right above the tag.  The whole hook chain must be
    unsaveable, and inside the wrapped body the hook is the only
    producer of ``all_gather`` / ``optimization_barrier`` values, so
    the policy blocks those primitives by NAME (stable public strings;
    everything else keeps the default residual choice)."""
    #: primitives only the hook emits inside the wrapped body — their
    #: outputs are the (gathered) param values that must be re-derived,
    #: not saved per layer
    blocked = ("name", "all_gather", "optimization_barrier", "psum_scatter")

    def policy(prim, *_args, **params):
        pname = getattr(prim, "name", str(prim))
        if pname == "name":
            return params.get("name") != OVERLAP_TAG
        return pname not in blocked

    return policy


class OverlapPlan:
    """Static (trace-time) description of the shard_map'd block wrap.

    Built once per engine from the abstract stacked layer tree; passed
    to the model per trace (``TransformerConfig.overlap_plan``, the
    same engine-set-per-trace pattern as ``qwz``).  Hashable by
    identity — it is a ``custom_vjp`` nondiff argument."""

    TAG = OVERLAP_TAG

    def __init__(self, mesh, axis: str, treedef, paths: Sequence[str],
                 leaf_specs: Sequence[P], gather_dims: Sequence[Optional[int]],
                 buckets: Sequence[Sequence[int]],
                 bucket_bytes: Sequence[int],
                 bucket_step_bytes: Sequence[int]):
        self.mesh = mesh
        self.axis = axis
        self.treedef = treedef
        self.paths = tuple(paths)
        self.leaf_specs = tuple(leaf_specs)
        self.gather_dims = tuple(gather_dims)
        self.buckets = tuple(tuple(b) for b in buckets)
        self.bucket_bytes = tuple(int(b) for b in bucket_bytes)
        #: per-optimizer-step coverage of each bucket (slice bytes x
        #: n_layers) — what the trace-time events report, so the span
        #: accounting adds up against the structural totals
        self.bucket_step_bytes = tuple(int(b) for b in bucket_step_bytes)

    # ------------------------------------------------------------- model API
    def wrap_block(self, raw_block, has_mask: bool):
        """Wrap ``raw_block(x, positions, mask, layer_tree) -> (y, aux)``
        in the data-axis shard_map (model side; the scan body calls the
        result with the same signature).  ``has_mask=False`` drops the
        mask slot (shard_map in_specs cannot carry a None leaf)."""
        from ...utils.jax_compat import shard_map

        plan = self

        def body(x, positions, *rest):
            mask = rest[0] if has_mask else None
            leaves = rest[1:] if has_mask else rest
            leaves = _overlap_hook(tuple(leaves), plan)
            leaves = tuple(checkpoint_name(v, OVERLAP_TAG) for v in leaves)
            layer = jax.tree_util.tree_unflatten(plan.treedef, leaves)
            return raw_block(x, positions, mask, layer)

        # residual discipline INSIDE the body: the policy must see the
        # hook call and its name tags, and shard_map residuals are
        # opaque from outside — so the checkpoint sits under the
        # shard_map
        body = jax.checkpoint(body, policy=_overlap_remat_policy())

        bsp = P(self.axis)  # batch-leading operands shard the lead dim
        mask_specs = (bsp,) if has_mask else ()
        sm = shard_map(
            body, mesh=self.mesh,
            in_specs=(bsp, bsp) + mask_specs + self.leaf_specs,
            out_specs=(bsp, P()),
            check_vma=False, axis_names={self.axis})

        world = int(self.mesh.shape[self.axis])

        def wrapped(x, positions, mask, layer_tree):
            if x.shape[0] % world != 0:
                # e.g. an eval_batch whose batch does not divide the
                # data axis: the wrap cannot shard it — run the plain
                # GSPMD block (training batches divide by construction)
                from ...utils.logging import warning_once

                warning_once(
                    f"overlap wrap bypassed: batch {x.shape[0]} does not "
                    f"divide the data axis ({world})")
                return raw_block(x, positions, mask, layer_tree)
            leaves, treedef = jax.tree_util.tree_flatten(layer_tree)
            if treedef != self.treedef:
                raise ValueError(
                    "overlap plan was built for a different layer structure "
                    f"(plan {self.treedef} vs model {treedef}); rebuild the "
                    "engine after changing the model")
            args = (x, positions) + ((mask,) if has_mask else ()) + tuple(leaves)
            return sm(*args)

        return wrapped

    # ------------------------------------------------------------ internals
    def _fwd(self, leaves: Tuple[Any, ...]) -> Tuple[Any, ...]:
        """Inside the body: stage-3 leaves are local ZeRO shards — issue
        their all-gathers per bucket at the body top, barrier-pinned, so
        the unrolled trip's two chains start independent."""
        if all(d is None for d in self.gather_dims):
            return leaves
        out = list(leaves)
        for k, idxs in enumerate(self.buckets):
            group = jax.lax.optimization_barrier(
                tuple(out[i] for i in idxs))
            gathered = []
            for i, v in zip(idxs, group):
                d = self.gather_dims[i]
                if d is not None:
                    v = lax.all_gather(v, self.axis, axis=d, tiled=True)
                gathered.append(v)
            group = jax.lax.optimization_barrier(tuple(gathered))
            for i, v in zip(idxs, group):
                out[i] = v
        return tuple(out)

    def _bwd(self, cts: Tuple[Any, ...]) -> Tuple[Any, ...]:
        """Inside the transposed body: per bucket, group the cotangents
        between barriers and issue the gather transposes (an explicit
        ``psum_scatter`` — the per-layer grad reduce-scatter) as one
        wavefront per backward trip.  Identity (stage <= 2) leaves pass
        through barrier-grouped; shard_map's boundary then psums them
        over the axis — also inside the trip."""
        out: List[Any] = list(cts)
        for k, idxs in enumerate(self.buckets):
            group = jax.lax.optimization_barrier(
                tuple(out[i] for i in idxs))
            reduced = []
            for i, v in zip(idxs, group):
                d = self.gather_dims[i]
                if d is not None:
                    # all_gather's transpose, written out so the bucket
                    # barriers pin it: this rank keeps ITS shard of the
                    # summed cotangent
                    v = lax.psum_scatter(v, self.axis,
                                         scatter_dimension=d, tiled=True)
                reduced.append(v)
            group = jax.lax.optimization_barrier(tuple(reduced))
            # trace-time collective event (the comm._log convention):
            # one point per bucket per traced program, carrying the
            # bytes the bucket reduces — the overlap accountant reads
            # these against the compute spans
            record_event("grad_bucket_reduce", cat="comm",
                         bytes=self.bucket_step_bytes[k], bucket=k,
                         leaves=len(idxs), overlapped=True)
            for i, v in zip(idxs, group):
                out[i] = v
        return tuple(out)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _overlap_hook(leaves: Tuple[Any, ...], plan: OverlapPlan):
    return plan._fwd(leaves)


def _overlap_hook_fwd(leaves, plan):
    return plan._fwd(leaves), None


def _overlap_hook_bwd(plan, _res, cts):
    return (plan._bwd(cts),)


_overlap_hook.defvjp(_overlap_hook_fwd, _overlap_hook_bwd)


def record_tail_reduce(nbytes: int) -> None:
    """Trace-time event for gradient bytes NOT covered by the hook (the
    non-layer leaves — embeddings, head, final norm — whose reduce stays
    post-backward).  One owner site for the span name."""
    record_event("grad_tail_reduce", cat="comm", bytes=int(nbytes),
                 overlapped=False)


def _entry_axes(entry) -> tuple:
    if entry is None:
        return ()
    return tuple(entry) if isinstance(entry, (tuple, list)) else (entry,)


def build_overlap_plan(zero_plan, abstract_layers: Any, *,
                       bucket_bytes: int, axis: str, stage: int,
                       grad_dtype) -> Optional[OverlapPlan]:
    """Derive the wrap's static plan from the stacked layer tree.

    ``abstract_layers``: ``state.params["layers"]`` (stacked, leading
    dim = n_layers) — shapes/dtypes only.  ``axis``: the (single) batch
    mesh axis the wrap manages manually.  At ``stage`` 3 each leaf's
    in-body spec is its live ZeRO shard (gathered explicitly by the
    hook); below 3 the leaves enter replicated over ``axis``.
    """
    from .strategy import _path_str

    flat, treedef = jax.tree_util.tree_flatten_with_path(abstract_layers)
    if not flat:
        return None
    mesh = zero_plan.topology.mesh
    paths, leaf_specs, gather_dims, sizes, step_sizes = [], [], [], [], []
    grad_itemsize = np.dtype(grad_dtype).itemsize
    for path, leaf in flat:
        pstr = "layers/" + _path_str(path)
        shape = tuple(leaf.shape)
        paths.append(pstr)
        n_layers = shape[0] or 1
        step_sizes.append(int(np.prod(shape)) * grad_itemsize)
        sizes.append(int(np.prod(shape)) // n_layers * grad_itemsize)
        gdim = None
        if stage >= 3:
            # the live param's stacked spec, restricted to `axis`, minus
            # the leading layer dim = where this leaf's ZeRO shard lives
            # inside the body (and therefore its explicit gather dim)
            full = zero_plan.param_spec(pstr, shape)
            for dim, entry in enumerate(tuple(full)[1:]):
                if axis in _entry_axes(entry):
                    gdim = dim
                    break
        if gdim is None:
            leaf_specs.append(P(*((None,) * (len(shape) - 1))))
        else:
            entries = [None] * (len(shape) - 1)
            entries[gdim] = axis
            leaf_specs.append(P(*entries))
        gather_dims.append(gdim)
    buckets = assign_buckets(sizes, bucket_bytes)
    bucket_sizes = [sum(sizes[i] for i in b) for b in buckets]
    bucket_step = [sum(step_sizes[i] for i in b) for b in buckets]
    logger.info(
        f"overlap plan: {len(flat)} layer leaves -> {len(buckets)} "
        f"bucket(s) (target {bucket_bytes / 2**20:.1f} MB, stage {stage}, "
        f"gathered={sum(d is not None for d in gather_dims)})")
    return OverlapPlan(mesh, axis, treedef, paths, leaf_specs, gather_dims,
                       buckets, bucket_sizes, bucket_step)
