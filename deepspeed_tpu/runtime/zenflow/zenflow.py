"""ZenFlow: stall-free optimizer offloading with importance-aware updates.

Reference parity: ``runtime/zenflow/`` — ``ZenFlowZeroOptimizer``
(zenflow_stage_1_and_2.py:47) and ``ZenFlowConfig`` (zenflow_config.py:12).
The reference's mechanism: each step, the top-k "important" gradient
columns are applied immediately on the accelerator; the remaining
gradients are accumulated and applied on the CPU every
``update_interval`` steps, asynchronously, so the device never stalls on
the full CPU optimizer pass.

TPU translation of the same split:

* fast path  — selected columns of each 2-D parameter get a vectorized
  numpy Adam update at every gradient boundary (small slices; host cost
  is a fraction of a full pass).  1-D parameters (norms/biases) are tiny
  and always take the fast path.
* slow path  — unselected gradients accumulate in a host buffer; every
  ``update_interval`` boundaries the residual is applied by a background
  thread that runs across the whole next interval (device micro-batches
  AND the intervening fast-path boundaries proceed meanwhile).
* merge      — the slow pass works on snapshots and its results merge at
  the next interval boundary, column-wise: only columns the slow pass
  touched are taken, and columns the fast path wrote during the overlap
  window keep their fast-path values (important columns are owned by the
  fast path, exactly the reference's split).

Interface-compatible with zero/offload.HostOffloadedOptimizer so the
engine can swap it in via config (zero_optimization.zenflow block).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..config import ZenFlowConfig  # noqa: F401  (re-exported)
from ..zero.offload import scale_and_clip
from ...utils.logging import log_dist


def _adam_update(master, g, m, v, step, lr, b1, b2, eps, wd, adamw):
    """Vectorized numpy Adam(W) on (views of) master/m/v, in place."""
    m *= b1
    m += (1 - b1) * g
    v *= b2
    v += (1 - b2) * g * g
    mh = m / (1 - b1 ** step)
    vh = v / (1 - b2 ** step)
    if adamw and wd:
        master *= (1 - lr * wd)
    master -= lr * mh / (np.sqrt(vh) + eps)


class ZenFlowOptimizer:
    """Host optimizer with the ZenFlow fast/slow split."""

    def __init__(self, abstract_params: Any, optimizer_config: Dict[str, Any],
                 zenflow_config: Optional[ZenFlowConfig] = None,
                 grad_clip: float = 0.0):
        p = dict(optimizer_config.get("params") or {})
        betas = p.get("betas", (0.9, 0.999))
        self.lr = float(p.get("lr", 1e-3))
        self.b1, self.b2 = float(betas[0]), float(betas[1])
        self.eps = float(p.get("eps", 1e-8))
        self.wd = float(p.get("weight_decay", 0.0))
        self.adamw = bool(p.get("adam_w_mode", True)) or \
            str(optimizer_config.get("type", "adamw")).lower().endswith("w")
        self.zf = zenflow_config or ZenFlowConfig(enabled=True)
        self.grad_clip = grad_clip

        self.leaves, self.treedef = (jax.tree_util.tree_flatten(abstract_params)
                                     if abstract_params is not None else ([], None))
        self.master: List[np.ndarray] = []
        self._m: List[np.ndarray] = []
        self._v: List[np.ndarray] = []
        self._accum: List[np.ndarray] = []
        # columns written by the fast path since the running slow pass launched
        self._fast_mask: List[Optional[np.ndarray]] = []
        # columns that received slow-path residual this interval (drives the
        # slow pass instead of a g!=0 proxy, so zero-grad elements inside a
        # touched column still get Adam's moment decay)
        self._slow_touched: List[Optional[np.ndarray]] = []
        self.step_count = 0
        self._slow_thread: Optional[threading.Thread] = None
        # (master, m, v, touched, accum) snapshots produced by _slow_pass
        self._slow_result: Optional[Tuple[List, List, List, List, List]] = None

    # -- lifecycle (mirrors HostOffloadedOptimizer) -------------------------
    def initialize_master(self, init_params: Any) -> None:
        flat = jax.tree_util.tree_leaves(init_params)
        self.master = [np.asarray(jax.device_get(x), np.float32).copy() for x in flat]
        self._m = [np.zeros_like(x) for x in self.master]
        self._v = [np.zeros_like(x) for x in self.master]
        self._accum = [np.zeros_like(x) for x in self.master]
        self._fast_mask = [None] * len(self.master)
        self._slow_touched = [np.zeros(x.shape[-1], bool) if x.ndim >= 2 else None
                              for x in self.master]
        log_dist(f"zenflow: {sum(x.size for x in self.master) / 1e6:.1f}M master "
                 f"elements; topk_ratio={self.zf.topk_ratio} "
                 f"interval={self.zf.update_interval}")

    # -- memory-ledger accounting (telemetry/memory.py providers) -----------
    def master_bytes(self) -> int:
        """Host RAM held by the fp32 master leaves."""
        return int(sum(m.nbytes for m in self.master if m is not None))

    def moment_bytes(self) -> int:
        """Host RAM held by the Adam moments + accumulation buffers."""
        total = 0
        for bufs in (self._m, self._v, self._accum):
            total += sum(int(b.nbytes) for b in bufs if b is not None)
        return total

    # -- slow path ----------------------------------------------------------
    def _slow_pass(self, snap_master, snap_m, snap_v, snap_accum, snap_touched,
                   step, lr):
        denom = float(self.zf.update_interval)
        for i in range(len(snap_master)):
            tm = snap_touched[i]
            if tm is None or not tm.any():
                continue
            # whole touched columns update — including elements whose
            # accumulated grad is exactly zero (moments decay, weight decay
            # applies: exact Adam semantics for the slow partition)
            if tm.all():  # common case (selection churns): update in place
                _adam_update(snap_master[i], snap_accum[i] / denom, snap_m[i],
                             snap_v[i], step, lr, self.b1, self.b2, self.eps,
                             self.wd, self.adamw)
                continue
            sel = np.nonzero(tm)[0]
            g = snap_accum[i][..., sel] / denom
            xs = snap_master[i][..., sel]
            ms = snap_m[i][..., sel]
            vs = snap_v[i][..., sel]
            _adam_update(xs, g, ms, vs, step, lr, self.b1, self.b2, self.eps,
                         self.wd, self.adamw)
            snap_master[i][..., sel] = xs
            snap_m[i][..., sel] = ms
            snap_v[i][..., sel] = vs
        self._slow_result = (snap_master, snap_m, snap_v, snap_touched,
                             snap_accum)

    def _join_slow(self) -> None:
        if self._slow_thread is None:
            return
        self._slow_thread.join()
        self._slow_thread = None
        new_master, new_m, new_v, snap_touched, snap_accum = self._slow_result
        self._slow_result = None
        for i in range(len(self.master)):
            tm = snap_touched[i]
            if tm is None or not tm.any():
                continue  # slow pass never touched this param: keep live values
            take = tm.copy()
            fm = self._fast_mask[i]
            if fm is not None:
                # important columns are owned by the fast path: keep the
                # values it wrote during the overlap window ...
                take &= ~fm
                # ... but their pre-window residual must not vanish with the
                # discarded slow result: re-queue it for the next slow pass
                dropped = tm & fm
                if dropped.any():
                    cols = np.nonzero(dropped)[0]
                    self._accum[i][..., cols] += snap_accum[i][..., cols]
                    self._slow_touched[i][cols] = True
            if take.any():
                cols = np.nonzero(take)[0]
                self.master[i][..., cols] = new_master[i][..., cols]
                self._m[i][..., cols] = new_m[i][..., cols]
                self._v[i][..., cols] = new_v[i][..., cols]
        self._fast_mask = [None] * len(self.master)

    def _launch_slow(self, lr: float) -> None:
        snap = ([x.copy() for x in self.master], [x.copy() for x in self._m],
                [x.copy() for x in self._v], [x.copy() for x in self._accum],
                [t.copy() if t is not None else None for t in self._slow_touched])
        for a in self._accum:
            a[...] = 0.0
        for t in self._slow_touched:
            if t is not None:
                t[:] = False
        for i, x in enumerate(self.master):
            self._fast_mask[i] = (np.zeros(x.shape[-1], bool)
                                  if x.ndim >= 2 else None)
        if self.zf.overlap_step:
            self._slow_thread = threading.Thread(
                target=self._slow_pass, args=(*snap, self.step_count, lr),
                daemon=True)
            self._slow_thread.start()
        else:
            self._slow_pass(*snap, self.step_count, lr)
            self._slow_thread = None
            new_master, new_m, new_v, _, _ = self._slow_result
            self._slow_result = None
            self.master, self._m, self._v = new_master, new_m, new_v
            self._fast_mask = [None] * len(self.master)

    # -- the boundary step --------------------------------------------------
    def apply_step(self, grads_flat: List[np.ndarray], lr: float,
                   denom: float) -> Tuple[List[np.ndarray], float]:
        self.step_count += 1
        step = self.step_count
        self.lr = lr
        warm_now = step <= self.zf.full_warm_up_rounds
        will_launch = (not warm_now) and step % self.zf.update_interval == 0
        if will_launch:
            # the slow pass launched at the previous interval boundary ran
            # while the intervening fast-only boundaries proceeded (the
            # stall-free overlap); merge it before snapshotting the next one.
            # Columns the fast path wrote in that window keep their fast
            # values (_join_slow's fast-mask merge).
            self._join_slow()

        gs, norm = scale_and_clip(grads_flat, denom, self.grad_clip,
                                  shapes=[x.shape for x in self.master])

        warm = warm_now
        for i, g in enumerate(gs):
            x = self.master[i]
            if warm or x.ndim < 2 or self.zf.topk_ratio >= 1.0:
                _adam_update(x, g, self._m[i], self._v[i], step, lr,
                             self.b1, self.b2, self.eps, self.wd, self.adamw)
                continue
            ncols = x.shape[-1]
            k = max(1, int(round(self.zf.topk_ratio * ncols)))
            col_imp = np.sum(g * g, axis=tuple(range(g.ndim - 1)))
            sel = np.argpartition(col_imp, ncols - k)[ncols - k:]
            # fast path: immediate update of the important columns.  Fancy
            # indexing copies, so gather → update → scatter back.
            xs, gsel = x[..., sel], g[..., sel]
            ms, vs = self._m[i][..., sel], self._v[i][..., sel]
            _adam_update(xs, gsel, ms, vs, step, lr, self.b1, self.b2,
                         self.eps, self.wd, self.adamw)
            x[..., sel] = xs
            self._m[i][..., sel] = ms
            self._v[i][..., sel] = vs
            if self._fast_mask[i] is not None:
                self._fast_mask[i][sel] = True
            # slow path: everything else accumulates for the interval pass.
            # Zero only THIS step's contribution at the selected columns —
            # residual from steps where they were unselected stays queued
            # for the slow pass (zeroing the whole column would drop it).
            g_slow = g.copy()
            g_slow[..., sel] = 0.0
            self._accum[i] += g_slow
            if self._slow_touched[i] is not None:
                unsel = np.ones(ncols, bool)
                unsel[sel] = False
                self._slow_touched[i] |= unsel

        if will_launch:
            self._launch_slow(lr)
        return self.master, norm

    def master_as_tree(self, like: Any) -> Any:
        self._join_slow()
        flat = jax.tree_util.tree_leaves(like)
        arrs = [m.reshape(x.shape) for m, x in zip(self.master, flat)]
        return jax.tree_util.tree_unflatten(jax.tree_util.tree_structure(like), arrs)

    def state_dict(self) -> Dict[str, Any]:
        self._join_slow()
        return {"step": self.step_count,
                "master": [x.copy() for x in self.master],
                "m": [x.copy() for x in self._m],
                "v": [x.copy() for x in self._v],
                "accum": [x.copy() for x in self._accum],
                "touched": [t.copy() if t is not None else None
                            for t in self._slow_touched]}

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self._join_slow()
        self.step_count = int(sd["step"])
        self.master = [np.asarray(x, np.float32) for x in sd["master"]]
        self._m = [np.asarray(x, np.float32) for x in sd["m"]]
        self._v = [np.asarray(x, np.float32) for x in sd["v"]]
        self._accum = [np.asarray(x, np.float32) for x in sd["accum"]]
        self._fast_mask = [None] * len(self.master)
        if "touched" in sd:
            self._slow_touched = [np.asarray(t, bool) if t is not None else None
                                  for t in sd["touched"]]
        else:  # older checkpoints: conservatively mark every column touched
            # (one extra moment-decay pass, vs re-freezing zero-grad columns)
            self._slow_touched = [np.ones(x.shape[-1], bool) if x.ndim >= 2
                                  else None for x in self.master]
