"""ZenFlow: stall-free optimizer offloading with importance-aware updates.

Reference parity: ``runtime/zenflow/`` — ``ZenFlowZeroOptimizer``
(zenflow_stage_1_and_2.py:47) and ``ZenFlowConfig`` (zenflow_config.py:12).
The reference's mechanism: each step, the top-k "important" gradient
columns are applied immediately on the accelerator; the remaining
gradients are accumulated and applied on the CPU every
``update_interval`` steps, asynchronously, so the device never stalls on
the full CPU optimizer pass.

TPU translation of the same split:

* fast path  — selected columns of each 2-D parameter get a vectorized
  numpy Adam update at every gradient boundary (small slices; host cost
  is a fraction of a full pass).  1-D parameters (norms/biases) are tiny
  and always take the fast path.
* slow path  — unselected gradients accumulate in a host buffer; every
  ``update_interval`` boundaries the residual is applied by a background
  thread while the device runs the next micro-batches.
* merge      — the slow pass works on snapshots and its results are
  merged at the next boundary; columns the fast path touched in the
  overlap window keep their fast-path values (important columns are
  owned by the fast path, exactly the reference's split).

Interface-compatible with zero/offload.HostOffloadedOptimizer so the
engine can swap it in via config (zero_optimization.zenflow block).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..config import ZenFlowConfig  # noqa: F401  (re-exported)
from ..zero.offload import scale_and_clip
from ...utils.logging import log_dist


def _adam_update(master, g, m, v, step, lr, b1, b2, eps, wd, adamw):
    """Vectorized numpy Adam(W) on (views of) master/m/v, in place."""
    m *= b1
    m += (1 - b1) * g
    v *= b2
    v += (1 - b2) * g * g
    mh = m / (1 - b1 ** step)
    vh = v / (1 - b2 ** step)
    if adamw and wd:
        master *= (1 - lr * wd)
    master -= lr * mh / (np.sqrt(vh) + eps)


class ZenFlowOptimizer:
    """Host optimizer with the ZenFlow fast/slow split."""

    def __init__(self, abstract_params: Any, optimizer_config: Dict[str, Any],
                 zenflow_config: Optional[ZenFlowConfig] = None,
                 grad_clip: float = 0.0):
        p = dict(optimizer_config.get("params") or {})
        betas = p.get("betas", (0.9, 0.999))
        self.lr = float(p.get("lr", 1e-3))
        self.b1, self.b2 = float(betas[0]), float(betas[1])
        self.eps = float(p.get("eps", 1e-8))
        self.wd = float(p.get("weight_decay", 0.0))
        self.adamw = bool(p.get("adam_w_mode", True)) or \
            str(optimizer_config.get("type", "adamw")).lower().endswith("w")
        self.zf = zenflow_config or ZenFlowConfig(enabled=True)
        self.grad_clip = grad_clip

        self.leaves, self.treedef = (jax.tree_util.tree_flatten(abstract_params)
                                     if abstract_params is not None else ([], None))
        self.master: List[np.ndarray] = []
        self._m: List[np.ndarray] = []
        self._v: List[np.ndarray] = []
        self._accum: List[np.ndarray] = []
        # columns written by the fast path since the running slow pass launched
        self._fast_mask: List[Optional[np.ndarray]] = []
        self.step_count = 0
        self._slow_thread: Optional[threading.Thread] = None
        self._slow_result: Optional[Tuple[List, List, List]] = None

    # -- lifecycle (mirrors HostOffloadedOptimizer) -------------------------
    def initialize_master(self, init_params: Any) -> None:
        flat = jax.tree_util.tree_leaves(init_params)
        self.master = [np.asarray(jax.device_get(x), np.float32).copy() for x in flat]
        self._m = [np.zeros_like(x) for x in self.master]
        self._v = [np.zeros_like(x) for x in self.master]
        self._accum = [np.zeros_like(x) for x in self.master]
        self._fast_mask = [None] * len(self.master)
        log_dist(f"zenflow: {sum(x.size for x in self.master) / 1e6:.1f}M master "
                 f"elements; topk_ratio={self.zf.topk_ratio} "
                 f"interval={self.zf.update_interval}")

    # -- slow path ----------------------------------------------------------
    def _slow_pass(self, snap_master, snap_m, snap_v, snap_accum, step, lr):
        denom = float(self.zf.update_interval)
        for i in range(len(snap_master)):
            g = snap_accum[i] / denom
            nz = g != 0  # only elements with accumulated (slow-path) gradient
            if not nz.any():
                continue
            x0, m0, v0 = snap_master[i].copy(), snap_m[i].copy(), snap_v[i].copy()
            _adam_update(snap_master[i], g, snap_m[i], snap_v[i], step,
                         lr, self.b1, self.b2, self.eps, self.wd, self.adamw)
            snap_master[i][~nz] = x0[~nz]
            snap_m[i][~nz] = m0[~nz]
            snap_v[i][~nz] = v0[~nz]
        self._slow_result = (snap_master, snap_m, snap_v)

    def _join_slow(self) -> None:
        if self._slow_thread is None:
            return
        self._slow_thread.join()
        self._slow_thread = None
        new_master, new_m, new_v = self._slow_result
        self._slow_result = None
        for i in range(len(self.master)):
            mask = self._fast_mask[i]
            if mask is not None and mask.any():
                # important columns are owned by the fast path: keep the
                # values it wrote during the overlap window
                new_master[i][..., mask] = self.master[i][..., mask]
                new_m[i][..., mask] = self._m[i][..., mask]
                new_v[i][..., mask] = self._v[i][..., mask]
            self.master[i] = new_master[i]
            self._m[i] = new_m[i]
            self._v[i] = new_v[i]
            self._fast_mask[i] = None

    def _launch_slow(self, lr: float) -> None:
        snap = ([x.copy() for x in self.master], [x.copy() for x in self._m],
                [x.copy() for x in self._v], [x.copy() for x in self._accum])
        for a in self._accum:
            a[...] = 0.0
        for i, x in enumerate(self.master):
            self._fast_mask[i] = (np.zeros(x.shape[-1], bool)
                                  if x.ndim >= 2 else None)
        if self.zf.overlap_step:
            self._slow_thread = threading.Thread(
                target=self._slow_pass, args=(*snap, self.step_count, lr),
                daemon=True)
            self._slow_thread.start()
        else:
            self._slow_pass(*snap, self.step_count, lr)
            self._slow_thread = None
            new_master, new_m, new_v = self._slow_result
            self._slow_result = None
            self.master, self._m, self._v = new_master, new_m, new_v
            self._fast_mask = [None] * len(self.master)

    # -- the boundary step --------------------------------------------------
    def apply_step(self, grads_flat: List[np.ndarray], lr: float,
                   denom: float) -> Tuple[List[np.ndarray], float]:
        self._join_slow()
        self.step_count += 1
        step = self.step_count
        self.lr = lr

        gs, norm = scale_and_clip(grads_flat, denom, self.grad_clip,
                                  shapes=[x.shape for x in self.master])

        warm = step <= self.zf.full_warm_up_rounds
        for i, g in enumerate(gs):
            x = self.master[i]
            if warm or x.ndim < 2 or self.zf.topk_ratio >= 1.0:
                _adam_update(x, g, self._m[i], self._v[i], step, lr,
                             self.b1, self.b2, self.eps, self.wd, self.adamw)
                continue
            ncols = x.shape[-1]
            k = max(1, int(round(self.zf.topk_ratio * ncols)))
            col_imp = np.sum(g * g, axis=tuple(range(g.ndim - 1)))
            sel = np.argpartition(col_imp, ncols - k)[ncols - k:]
            # fast path: immediate update of the important columns.  Fancy
            # indexing copies, so gather → update → scatter back.
            xs, gsel = x[..., sel], g[..., sel]
            ms, vs = self._m[i][..., sel], self._v[i][..., sel]
            _adam_update(xs, gsel, ms, vs, step, lr, self.b1, self.b2,
                         self.eps, self.wd, self.adamw)
            x[..., sel] = xs
            self._m[i][..., sel] = ms
            self._v[i][..., sel] = vs
            if self._fast_mask[i] is not None:
                self._fast_mask[i][sel] = True
            # slow path: everything else accumulates for the interval pass.
            # Zero only THIS step's contribution at the selected columns —
            # residual from steps where they were unselected stays queued
            # for the slow pass (zeroing the whole column would drop it).
            g_slow = g.copy()
            g_slow[..., sel] = 0.0
            self._accum[i] += g_slow

        if not warm and step % self.zf.update_interval == 0:
            self._launch_slow(lr)
        return self.master, norm

    def master_as_tree(self, like: Any) -> Any:
        self._join_slow()
        flat = jax.tree_util.tree_leaves(like)
        arrs = [m.reshape(x.shape) for m, x in zip(self.master, flat)]
        return jax.tree_util.tree_unflatten(jax.tree_util.tree_structure(like), arrs)

    def state_dict(self) -> Dict[str, Any]:
        self._join_slow()
        return {"step": self.step_count,
                "master": [x.copy() for x in self.master],
                "m": [x.copy() for x in self._m],
                "v": [x.copy() for x in self._v],
                "accum": [x.copy() for x in self._accum]}

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self._join_slow()
        self.step_count = int(sd["step"])
        self.master = [np.asarray(x, np.float32) for x in sd["master"]]
        self._m = [np.asarray(x, np.float32) for x in sd["m"]]
        self._v = [np.asarray(x, np.float32) for x in sd["v"]]
        self._accum = [np.asarray(x, np.float32) for x in sd["accum"]]
        self._fast_mask = [None] * len(self.master)
