from .zenflow import ZenFlowConfig, ZenFlowOptimizer  # noqa: F401
