"""Device-mesh topology.

TPU-native replacement for the reference's process-group machinery
(``deepspeed/utils/groups.py``, ``runtime/pipe/topology.py``): instead of
creating torch.distributed process groups for DP/TP/PP/SP/EP, we build ONE
``jax.sharding.Mesh`` with named axes and express every parallel strategy as
a sharding over those axes.  XLA then inserts the collectives (over ICI
within a slice, DCN across slices).

Axes (sizes from ``MeshConfig``):
  pipe      pipeline stages          (reference: PipelineParallelGrid)
  data      data parallelism / ZeRO  (reference: data_parallel_group)
  expert    MoE expert parallelism   (reference: expert_parallel_group)
  sequence  Ulysses/ring seq-par     (reference: sequence_parallel_group)
  model     tensor parallelism       (reference: model_parallel_group)

The ZeRO sharding axes are ``("data", "expert", "sequence")`` for non-expert
parameters (those axes all see the same replica of a dense param, mirroring
``seq_data_parallel_group`` in the reference, engine.py:1835) and
``("data",)`` for expert parameters.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..runtime.config import MeshConfig
from ..utils.logging import logger

PIPE_AXIS = "pipe"
REPL_AXIS = "repl"  # MiCS replica groups: ZeRO shards within, replicates across
DATA_AXIS = "data"
EXPERT_AXIS = "expert"
SEQ_AXIS = "sequence"
MODEL_AXIS = "model"

ALL_AXES = (PIPE_AXIS, REPL_AXIS, DATA_AXIS, EXPERT_AXIS, SEQ_AXIS, MODEL_AXIS)
#: axes over which ZeRO partitions dense (non-expert) state.  The MiCS
#: "repl" axis is deliberately absent: state is sharded within a data group
#: and replicated across repl groups (reference zero/mics.py:447) — gradient
#: averaging across repl happens through the batch sharding alone.
ZERO_AXES = (DATA_AXIS, EXPERT_AXIS, SEQ_AXIS)
#: axes over which ZeRO partitions expert state
EXPERT_ZERO_AXES = (DATA_AXIS,)
#: the batch dimension of inputs is sharded over these
BATCH_AXES = (REPL_AXIS, DATA_AXIS, EXPERT_AXIS)


class MeshTopology:
    """Builds and owns the global device mesh."""

    def __init__(self, config: Optional[MeshConfig] = None,
                 devices: Optional[Sequence[jax.Device]] = None):
        self.config = config or MeshConfig()
        devices = list(devices if devices is not None else jax.devices())
        n = len(devices)

        sizes = {
            PIPE_AXIS: self.config.pipe,
            REPL_AXIS: getattr(self.config, "repl", 1),
            DATA_AXIS: self.config.data,
            EXPERT_AXIS: self.config.expert,
            SEQ_AXIS: self.config.sequence,
            MODEL_AXIS: self.config.model,
        }
        fixed = math.prod(v for v in sizes.values() if v != -1)
        free = [k for k, v in sizes.items() if v == -1]
        if len(free) > 1:
            raise ValueError(f"At most one mesh axis may be -1, got {free}")
        if free:
            if n % fixed != 0:
                raise ValueError(
                    f"{n} devices not divisible by fixed axis product {fixed}")
            sizes[free[0]] = n // fixed
        elif fixed != n:
            raise ValueError(f"Mesh axis product {fixed} != device count {n}")

        shape = tuple(sizes[a] for a in ALL_AXES)
        try:
            from jax.experimental import mesh_utils

            device_array = mesh_utils.create_device_mesh(shape, devices=devices)
        except Exception:  # pragma: no cover - fallback for odd topologies
            device_array = np.asarray(devices).reshape(shape)
        self.mesh = Mesh(device_array, ALL_AXES)
        self.axis_sizes = sizes
        logger.info(f"MeshTopology: {sizes} over {n} devices")

    # -- world sizes (reference groups.get_*_world_size) --------------------
    @property
    def world_size(self) -> int:
        return self.mesh.size

    def axis_size(self, axis: str) -> int:
        return self.axis_sizes[axis]

    @property
    def dp_world_size(self) -> int:
        """Data-parallel degree for batch-size math: everything that consumes
        distinct micro-batches (repl × data × expert axes; sequence ranks
        share a batch, pipeline/model ranks share a batch)."""
        return (self.axis_sizes[REPL_AXIS] * self.axis_sizes[DATA_AXIS]
                * self.axis_sizes[EXPERT_AXIS])

    @property
    def zero_world_size(self) -> int:
        return math.prod(self.axis_sizes[a] for a in ZERO_AXES)

    @property
    def model_parallel_size(self) -> int:
        return self.axis_sizes[MODEL_AXIS]

    @property
    def seq_parallel_size(self) -> int:
        return self.axis_sizes[SEQ_AXIS]

    @property
    def expert_parallel_size(self) -> int:
        return self.axis_sizes[EXPERT_AXIS]

    @property
    def pipe_parallel_size(self) -> int:
        return self.axis_sizes[PIPE_AXIS]

    # -- sharding helpers ---------------------------------------------------
    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def batch_sharding(self, with_seq: bool = False) -> NamedSharding:
        """Input batches: batch dim over data(+expert), seq dim optionally
        over the sequence axis (Ulysses-style sharded dataloader)."""
        if with_seq:
            return self.sharding(BATCH_AXES, SEQ_AXIS)
        return self.sharding(BATCH_AXES)

    def __enter__(self):
        self._ctx = self.mesh
        return self._ctx.__enter__()

    def __exit__(self, *exc):
        return self._ctx.__exit__(*exc)


# --- global topology registry (reference deepspeed/utils/groups.py) ---------
_TOPOLOGY: Optional[MeshTopology] = None


def initialize_topology(config: Optional[MeshConfig] = None,
                        devices: Optional[Sequence[jax.Device]] = None) -> MeshTopology:
    global _TOPOLOGY
    _TOPOLOGY = MeshTopology(config, devices)
    return _TOPOLOGY


def get_topology() -> MeshTopology:
    global _TOPOLOGY
    if _TOPOLOGY is None:
        _TOPOLOGY = MeshTopology()
    return _TOPOLOGY


def peek_topology() -> Optional[MeshTopology]:
    """The initialized topology, or None — never creates one (safe to call
    from library code at trace time without the side effect of building a
    default mesh over all devices)."""
    return _TOPOLOGY


def reset_topology() -> None:
    global _TOPOLOGY
    _TOPOLOGY = None


# reference-compatible getters (deepspeed/utils/groups.py)
def get_data_parallel_world_size() -> int:
    return get_topology().dp_world_size


def get_model_parallel_world_size() -> int:
    return get_topology().model_parallel_size


def get_expert_parallel_world_size() -> int:
    return get_topology().expert_parallel_size


def get_sequence_parallel_world_size() -> int:
    return get_topology().seq_parallel_size
