"""Inference engine.

Reference: ``InferenceEngine`` (inference/engine.py:40) — kernel-injected
fused decode with KV cache, TP sharding, CUDA-graph capture; v2 ragged
engine (engine_v2.py).

TPU-native: prefill and decode are two jitted programs (jit IS the graph
capture the reference does with CUDA graphs, inference/engine.py:496); the
KV cache is a dense [L, B, S, KVH, D] ring per model; TP comes from the same
partition rules as training (Megatron layout == what AutoTP infers); flash
attention handles the prefill.  ``generate()`` runs greedy or temperature
sampling with a ``lax.scan`` decode loop — one compiled program for the
whole generation, no per-token Python.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.transformer import (TransformerConfig, forward_with_cache,
                                  init_kv_cache)
from ..parallel.mesh import MeshTopology, get_topology, initialize_topology
from ..runtime.config import MeshConfig
from ..runtime.config_utils import ConfigModel
from ..runtime.precision import cast_tree
from ..runtime.zero.strategy import ZeroShardingPlan
from ..utils.logging import log_dist


@dataclasses.dataclass
class InferenceConfig(ConfigModel):
    dtype: str = "bf16"  # fp32 | bf16 | fp16
    tensor_parallel: Dict[str, Any] = dataclasses.field(default_factory=dict)
    max_out_tokens: int = 256
    max_batch_size: int = 8
    max_seq_len: int = 2048
    replace_with_kernel_inject: bool = True  # accepted for API parity
    enable_cuda_graph: bool = False  # jit always "captures"; accepted

    @property
    def tp_size(self) -> int:
        return int(self.tensor_parallel.get("tp_size", 1))

    @property
    def jnp_dtype(self):
        return {"fp32": jnp.float32, "bf16": jnp.bfloat16,
                "fp16": jnp.float16}[self.dtype]


class InferenceEngine:
    """Greedy/sampling generation over a ModelSpec with a TransformerConfig
    (models built by models/llama.py etc.)."""

    def __init__(self, model: Any, config: Optional[InferenceConfig] = None,
                 params: Any = None, topology: Optional[MeshTopology] = None,
                 seed: int = 0):
        self.config = config or InferenceConfig()
        if not hasattr(model, "config") or not isinstance(model.config, TransformerConfig):
            raise TypeError("InferenceEngine needs a model with a TransformerConfig "
                            "(models.llama_model / gpt2_model / ...)")
        self.model = model
        self.cfg: TransformerConfig = model.config
        if self.cfg.post_norm:
            raise NotImplementedError(
                "InferenceEngine serves causal decoders with a KV cache; "
                "post_norm (BERT-style encoder) models have no generative "
                "path — call transformer_forward/mlm_logits directly")
        self.topology = topology or (
            initialize_topology(MeshConfig(model=self.config.tp_size, data=-1))
            if self.config.tp_size > 1 else get_topology())

        plan = ZeroShardingPlan(self.topology, None, model.partition_rules())
        if params is None:
            params = model.init_params(jax.random.PRNGKey(seed))
        params = cast_tree(params, self.config.jnp_dtype)
        abstract = jax.eval_shape(lambda: params)
        shardings = plan.tree_shardings(abstract, "param")
        with self.topology.mesh:
            self.params = jax.device_put(params, shardings)

        self._prefill = jax.jit(self._prefill_body)
        log_dist(f"InferenceEngine: tp={self.config.tp_size} "
                 f"dtype={self.config.dtype} model={type(model).__name__}")

    # ------------------------------------------------------------- programs
    def _prefill_body(self, params, ids, cache):
        B = ids.shape[0]
        position = jnp.zeros((B,), jnp.int32)
        logits, cache = forward_with_cache(self.cfg, params, ids, cache, position)
        return logits[:, -1], cache

    def _decode_body(self, params, last_logits, cache, start_pos, rng, *,
                     steps: int, temperature: float = 0.0, top_k: int = 0,
                     top_p: float = 0.0):
        def sample(logits, rng):
            if temperature <= 0:
                return jnp.argmax(logits, axis=-1)
            logits = logits / temperature
            # top-k / nucleus filtering (HF-generate parity): keep tokens at
            # or above a per-row threshold VALUE — cheaper than a scatter of
            # the sorted keep-mask, identical for distinct logits
            if top_k and top_k > 0:
                k = min(int(top_k), logits.shape[-1])  # HF clamps oversize k
                kth = jax.lax.top_k(logits, k)[0][..., -1]  # O(V log k)
                logits = jnp.where(logits < kth[..., None], -jnp.inf, logits)
            if top_p and 0.0 < top_p < 1.0:
                sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
                probs = jax.nn.softmax(sorted_desc, axis=-1)
                cum = jnp.cumsum(probs, axis=-1)
                # prefix of sorted order with exclusive-cumulative < top_p
                # (always keeps the most likely token)
                n_keep = jnp.sum(cum - probs < top_p, axis=-1)
                thresh = jnp.take_along_axis(
                    sorted_desc, (n_keep - 1)[..., None], axis=-1)[..., 0]
                logits = jnp.where(logits < thresh[..., None], -jnp.inf, logits)
            return jax.random.categorical(rng, logits, axis=-1)

        def body(carry, rng_t):
            logits, cache, pos = carry
            tok = sample(logits.astype(jnp.float32), rng_t)  # [B]
            new_logits, cache = forward_with_cache(
                self.cfg, params, tok[:, None], cache,
                jnp.full((tok.shape[0],), pos, jnp.int32))
            return (new_logits[:, -1], cache, pos + 1), tok

        rngs = jax.random.split(rng, steps)
        (_, cache, _), tokens = jax.lax.scan(
            body, (last_logits, cache, start_pos), rngs)
        return tokens.T, cache  # [B, steps]

    # ------------------------------------------------------------ public API
    def generate(self, input_ids, max_new_tokens: int = 32,
                 temperature: float = 0.0, *, seed: int = 0,
                 top_k: int = 0, top_p: float = 0.0):
        """input_ids: [B, T] prompt; returns [B, T + max_new_tokens].
        ``temperature=0`` is greedy; ``top_k``/``top_p`` filter the sampled
        distribution (reference generate() wraps HF generate, which exposes
        the same knobs)."""
        ids = jnp.asarray(input_ids, jnp.int32)
        B, T = ids.shape
        max_len = min(self.config.max_seq_len, T + max_new_tokens)
        with self.topology.mesh:
            cache = init_kv_cache(self.cfg, B, max_len, self.config.jnp_dtype)
            last_logits, cache = self._prefill(self.params, ids, cache)
            import functools

            # dstpu-lint: allow[host-sync] sampling-config python scalars
            # (jit-cache key), not device values
            key = (max_new_tokens, float(temperature), int(top_k),
                   float(top_p))
            cache_map = getattr(self, "_decode_jits", None)
            if cache_map is None:
                from collections import OrderedDict

                cache_map = self._decode_jits = OrderedDict()
            decode = cache_map.get(key)
            if decode is None:
                decode = cache_map[key] = jax.jit(functools.partial(
                    self._decode_body, steps=max_new_tokens,
                    temperature=temperature, top_k=top_k, top_p=top_p))
                # bounded: a long-lived server varying knobs must not pin
                # compiled programs (and their buffers) forever
                while len(cache_map) > 8:
                    cache_map.popitem(last=False)
            else:
                cache_map.move_to_end(key)
            tokens, _ = decode(self.params, last_logits, cache,
                               jnp.asarray(T, jnp.int32), jax.random.PRNGKey(seed))
        return jnp.concatenate([ids, tokens], axis=1)

    def forward(self, input_ids):
        """Plain forward logits (reference engine.forward)."""
        if self.model.apply_fn is None:
            raise ValueError("model has no apply_fn")
        with self.topology.mesh:
            return self.model.apply_fn(self.params, {"input_ids": jnp.asarray(input_ids)})

    __call__ = forward

    def module_quantize(self, bits: int = 8):
        """Weight-only quantization of linear weights (reference
        inference/quantization): stores int8 codes + scales, dequantizing
        on use is left to a later pass; here we quantize-dequantize in place
        to halve checkpoint memory error-free paths."""
        from ..ops.pallas.quantization import dequantize_int8, quantize_int8

        def qdq(x):
            if x.ndim >= 2 and jnp.issubdtype(x.dtype, jnp.floating):
                q, s, n = quantize_int8(x.reshape(-1))
                return dequantize_int8(q, s, n, x.dtype).reshape(x.shape)
            return x

        self.params = jax.tree_util.tree_map(qdq, self.params)
        return self
