"""Weight-only quantization for inference.

Reference parity: ``deepspeed/inference/quantization/`` — post-training
weight-only int8/int4: the big matmul weights are stored as codes + group
scales and dequantized on-chip at use (ops/pallas/wq_matmul.py), roughly
halving (int8) / quartering (int4) the weight HBM footprint at near-bf16
logits.
"""

from __future__ import annotations

import re
from typing import Any, Tuple

import jax
import jax.numpy as jnp

from ..ops.pallas.wq_matmul import quantize_weight
from ..utils.logging import log_dist

#: weight leaves eligible for weight-only quantization: the seven big
#: matmuls of the transformer core plus the (untied) LM head.  Embeddings
#: stay full precision (gather, not matmul); MoE experts (4-D) are skipped.
WQ_PATTERNS = (r"attn/w[qkvo]$", r"mlp/w_(gate|up|down)$", r"lm_head/w$")


def _path_str(path) -> str:
    parts = []
    for p in path:
        parts.append(str(getattr(p, "key", getattr(p, "idx", getattr(p, "name", p)))))
    return "/".join(parts)


def quantize_inference_params(params: Any, bits: int = 8, group: int = 128,
                              min_size: int = 1 << 14) -> Tuple[Any, int, int]:
    """Replace eligible weight leaves with {"wq", "scale"} dicts.

    Stacked [L, K, N] layer weights quantize per layer (vmapped) so the
    scan path slices codes/scales like it slices weights.  Returns
    (quantized params, bytes before, bytes after)."""
    q2d = lambda w: quantize_weight(w, bits, group)  # noqa: E731

    before = after = 0

    def leaf_fn(path, leaf):
        nonlocal before, after
        if not hasattr(leaf, "shape"):
            return leaf
        before += leaf.size * leaf.dtype.itemsize
        key = _path_str(path)
        # gate on the PER-LAYER matrix size: a stacked [L, K, N] leaf is L
        # small matmuls, not one big one
        mat_size = leaf.size // leaf.shape[0] if leaf.ndim == 3 else leaf.size
        eligible = (any(re.search(p, key) for p in WQ_PATTERNS)
                    and leaf.ndim in (2, 3) and mat_size >= min_size)
        if not eligible:
            after += leaf.size * leaf.dtype.itemsize
            return leaf
        if leaf.ndim == 3:  # stacked layers
            codes, scale = jax.vmap(q2d)(leaf)
        else:
            codes, scale = q2d(leaf)
        after += codes.size * codes.dtype.itemsize + \
            scale.size * scale.dtype.itemsize
        return {"wq": codes, "scale": scale}

    out = jax.tree_util.tree_map_with_path(leaf_fn, params)
    log_dist(f"weight-only quantization: int{bits}, "
             f"{before / 1e6:.1f}MB -> {after / 1e6:.1f}MB")
    return out, before, after
