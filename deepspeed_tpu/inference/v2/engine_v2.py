"""Continuous-batching inference engine.

Reference parity: ``InferenceEngineV2`` (inference/v2/engine_v2.py) with
its ragged batch scheduler (``DSStateManager``/``RaggedBatchWrapper``,
inference/v2/ragged/): requests enter a queue, are admitted when KV pages
and a decode slot are available, prefill and decode interleave, finished
sequences release their pages immediately so new requests can start while
others are mid-generation.

The device work is two compiled programs (model_runner.py); everything
here is host-side bookkeeping between steps.  DECODE samples on device
(greedy argmax / Gumbel-max temperature inside the jitted program) and
returns only [max_seqs] token ids — fetching the full [max_seqs, vocab]
logits every step through a tunneled device link costs ~1MB/step of
transfer where 32 bytes suffice.  Prefill (once per admitted request)
still returns logits and samples on host.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...models.transformer import TransformerConfig
from ...runtime.config_utils import ConfigModel
from ...telemetry import get_registry
from ...telemetry.compile_sentinel import RecompileSentinel
from ...telemetry.compile_sentinel import \
    expect_recompile as sentinel_expect_recompile
from ...telemetry.flight import dump_on_exception
from ...telemetry.reqtrace import get_reqtrace_ledger, slo_exemplar
from ...telemetry.spans import begin_span, end_span, record_event, span
from ...telemetry.tracing import PhaseTimer
from ...utils.logging import logger
from .model_runner import (pad_pages_pow2, paged_copy_page, paged_decode,
                           paged_gather_pages, paged_multi_decode,
                           paged_prefill, paged_prefill_chunk,
                           paged_scatter_pages, paged_verify, sample_tokens)
from .ragged import (PRIORITY_NORMAL, BlockAllocator, KVBlockConfig,
                     KVPageBundle, PagedKVCache, PrefixCache, RejectedError,
                     SequenceState)
from .speculative import (SpeculativeConfig, build_proposer, longest_accepted)


@dataclasses.dataclass
class RaggedInferenceConfig(ConfigModel):
    dtype: str = "bf16"
    page_size: int = 16
    num_pages: int = 256
    max_seqs: int = 8
    max_pages_per_seq: int = 16
    min_prefill_bucket: int = 16
    #: chunked prefill (FastGen Dynamic SplitFuse): process prompts in
    #: chunks of this many tokens (rounded up to page_size) so decode
    #: steps interleave between chunks — bounded per-step latency for
    #: running streams.  0 = whole-prompt prefill.
    prefill_chunk: int = 0
    # weight-only quantization (reference inference/quantization/): 0 = off
    quant_bits: int = 0
    quant_group: int = 128
    quant_min_size: int = 1 << 14  # per-matrix eligibility floor
    #: int8 KV pages + per-(page,slot,head) scales: half the KV pool HBM
    kv_quant: bool = False
    #: automatic prefix caching: retired/preempted sequences leave their
    #: full KV pages in a content-hash index; new requests map the longest
    #: cached page-aligned prefix straight into their page table and
    #: prefill only the uncached suffix.  GREEDY decoding is bit-exact
    #: vs. cache-off, EXCEPT under kv_quant (the suffix attends
    #: dequantized cached pages where a whole-prompt prefill attends
    #: fresh full-precision keys — the same inherent cross-chunk
    #: approximation chunked prefill has).  Temperature sampling stays
    #: distributionally correct but not stream-identical: a fully-cached
    #: prompt samples its first token on the device RNG (decode entry)
    #: instead of the host RNG
    enable_prefix_cache: bool = False
    #: cap on cached-but-UNREFERENCED pages retained for reuse (LRU);
    #: 0 = bounded only by the pool itself
    prefix_cache_pages: int = 0
    #: tiered KV cache (serving/kv_tier.py, docs/SERVING.md "Tiered KV
    #: cache"): a ``KVTierConfig`` (or its dict form) enabling host-RAM
    #: spill & restore of cold prefix pages — prefix-cache LRU
    #: evictions are captured (D2H, async at step boundaries, pages
    #: ref-pinned until the copy commits) into a byte-budgeted host LRU
    #: and restored CRC-verified bit-identical when a later prefix walk
    #: reaches past the device hit.  Requires ``enable_prefix_cache``.
    #: Typed ``Any`` to keep this module import-light — the block's
    #: home is ``serving/config.py`` (serving imports inference, never
    #: the reverse at module scope)
    kv_tier: Any = None
    #: recompile sentinel for the serving loop (telemetry/
    #: compile_sentinel.py): attribute XLA compiles to steps via the
    #: step's program shapes and warn on steady-state recompilation.
    #: The serving engine takes no `telemetry` config block, so the
    #: knob lives here; `sentinel_steady_after` mirrors
    #: telemetry.recompile_sentinel.steady_after
    recompile_sentinel: bool = True
    sentinel_steady_after: int = 3
    #: step-time attribution (telemetry/timeline.py): every N engine
    #: steps, capture one profiler trace and publish the measured
    #: decomposition (0 = only on explicit `force_timeline_capture()`).
    #: The serving engine takes no `telemetry` block, so — like the
    #: sentinel above — the knob lives here
    timeline_every_n_steps: int = 0
    #: where per-capture merged Chrome traces land ("" = no artifacts)
    timeline_artifact_dir: str = ""
    #: memory ledger (telemetry/memory.py): attach the weight copy + KV
    #: page pool to the process ledger and watch prefill/decode phase
    #: watermarks.  The serving engine takes no `telemetry` block, so —
    #: like the sentinel above — the knob lives here
    memory_ledger: bool = True
    #: speculative decoding (speculative.py): multi-token-per-step
    #: decode — a proposer drafts up to k tokens, ONE batched verify
    #: program scores them all, the longest prefix matching the model's
    #: own greedy choices is accepted (+ the model's correction token),
    #: rejected tokens' pages roll back through the allocator.  GREEDY
    #: decoding is bit-identical to the non-speculative baseline;
    #: non-greedy sequences fall back to the plain decode program
    #: (sampling guard) so the output distribution is never touched
    speculative: SpeculativeConfig = dataclasses.field(
        default_factory=SpeculativeConfig)
    #: fused multi-step decode (docs/SERVING.md "Multi-step decode"):
    #: decode up to this many tokens per host round-trip via an
    #: on-device ``lax.scan`` over the decode body — ONE ``[B, K]``
    #: token pull per dispatch instead of one ``[B]`` pull per token,
    #: with per-row EOS/length/deadline masking computed in-scan
    #: (finished rows write to the trash page and stop consuming
    #: pages).  Greedy AND sampled streams are bit-identical across
    #: horizons (the sampling key folds per position, never per
    #: dispatch).  1 = the classic one-step decode loop.  Engines with
    #: speculative decoding enabled stand the horizon down loudly —
    #: one designed exclusive decode path at a time, like the
    #: sampling guard
    decode_horizon: int = 1
    #: bounded request queue (admission control): once this many
    #: requests wait for admission, ``put()`` raises
    #: :class:`RejectedError` (load shedding — the submitter backs off
    #: ``retry_after_s`` instead of growing the queue without bound).
    #: <= 0 = unbounded (the pre-SLO behavior)
    max_queue_depth: int = 0
    #: latency SLOs (seconds; <= 0 = untracked): TTFT / TPOT observations
    #: past these thresholds count the
    #: ``deepspeed_tpu_serving_slo_{ttft,tpot}_violations_total``
    #: counters and emit an ``slo_violation`` trace event
    slo_ttft_s: float = 0.0
    slo_tpot_s: float = 0.0

    @property
    def jnp_dtype(self):
        return {"fp32": jnp.float32, "bf16": jnp.bfloat16,
                "fp16": jnp.float16}[self.dtype]

    @property
    def block(self) -> KVBlockConfig:
        return KVBlockConfig(page_size=self.page_size, num_pages=self.num_pages,
                             max_seqs=self.max_seqs,
                             max_pages_per_seq=self.max_pages_per_seq)


@dataclasses.dataclass
class RaggedRequest:
    prompt_ids: List[int]
    max_new_tokens: int = 32
    temperature: float = 0.0  # 0 => greedy
    eos_id: Optional[int] = None
    uid: Optional[int] = None
    #: priority class (``ragged.PRIORITY_*``, smaller = more urgent):
    #: orders admission, picks preemption victims under KV pressure
    #: (lowest class first), and gates load shedding under overload
    priority: int = PRIORITY_NORMAL
    #: wall-clock budget in seconds from enqueue (None = no deadline):
    #: past it the engine expires the request at the next step boundary
    #: with ``finish_reason="deadline"`` instead of letting it wait (or
    #: decode) forever
    deadline_s: Optional[float] = None
    #: fleet trace id minted by ``FleetRouter.submit`` (None when the
    #: engine is driven standalone): rides the request span, every
    #: lifecycle trace event, and the KV-migration wire so one request
    #: is ONE connected trace across replicas
    trace_id: Optional[str] = None


def _horizon_pages_needed(length: int, budget: int, page_size: int) -> int:
    """Pages a decode row needs to emit ``budget`` more tokens: its t-th
    token this dispatch (1-indexed) writes KV at position
    ``length - 2 + t``, so the page table must cover position
    ``length - 2 + budget`` — the headroom-reservation arithmetic of
    the fused multi-step decode (pure, unit-tested)."""
    return (length - 2 + budget) // page_size + 1


def _shrink_horizon(k: int, cap: int) -> int:
    """Walk the halving chain ``K, ceil(K/2), ...`` down to the smallest
    value still covering ``cap`` (floor 1).  The dispatch horizon only
    ever takes values ON the chain, so the fused scan's compiled-shape
    set is O(log K) — short row budgets and pool pressure shrink the
    dispatch instead of minting arbitrary scan lengths (pure,
    unit-tested)."""
    while k > 1 and (k + 1) // 2 >= cap:
        k = (k + 1) // 2
    return max(1, k)


def _deadline_clamp(budget: int, deadline_left: float,
                    tpot_est: Optional[float]) -> int:
    """Clamp a row's effective horizon when its deadline lands
    mid-horizon: at ~``tpot_est`` seconds per fused step, emit only the
    tokens that fit the remaining budget (floor 1 — a single step would
    emit one token before the boundary sweep too).  Without an
    estimate (first dispatch) the budget passes through: the boundary
    sweep still expires the row, at most one horizon late (pure,
    unit-tested)."""
    if tpot_est is None or tpot_est <= 0.0:
        return budget
    return min(budget, max(1, int(deadline_left / tpot_est)))


class InferenceEngineV2:
    """Paged continuous batching over a models/* transformer."""

    @classmethod
    def from_pretrained(cls, model_dir: str,
                        config: Optional["RaggedInferenceConfig"] = None,
                        **kw) -> "InferenceEngineV2":
        """Serve a published Hugging Face checkpoint directory with paged
        continuous batching (the reference's inference-v2 checkpoint
        loading, model_implementations/*)."""
        from ...checkpoint.hf_import import load_hf_model
        from ...models.llama import llama_model

        cfg = config or RaggedInferenceConfig()
        mcfg, params = load_hf_model(model_dir, dtype=cfg.jnp_dtype)
        return cls(llama_model(config=mcfg), config=cfg, params=params, **kw)

    def __init__(self, model: Any, config: Optional[RaggedInferenceConfig] = None,
                 params: Any = None, seed: int = 0, proposer: Any = None):
        self.config = config or RaggedInferenceConfig()
        if isinstance(self.config.speculative, dict):  # hand-built configs
            self.config.speculative = SpeculativeConfig.from_dict(
                self.config.speculative)
        if not hasattr(model, "config") or not isinstance(model.config, TransformerConfig):
            raise TypeError("InferenceEngineV2 needs a models/* model carrying "
                            "a TransformerConfig")
        # own COPY of the model config: quantization flags must not leak
        # into other engines sharing the model object
        self.cfg: TransformerConfig = dataclasses.replace(model.config)
        if self.cfg.post_norm:
            raise NotImplementedError(
                "InferenceEngineV2 serves causal decoders; post_norm "
                "(BERT-style encoder) models have no generative path")
        block = self.config.block
        if block.num_pages < block.max_pages_per_seq:
            raise ValueError(
                f"num_pages ({block.num_pages}) < max_pages_per_seq "
                f"({block.max_pages_per_seq}): one sequence could never run to "
                "completion even with the whole pool")
        if params is None:
            params = model.init_params(jax.random.PRNGKey(seed))
        # deferred: runtime.precision pulls runtime.config, which imports
        # serving.config -> inference.v2 — a top-level import here would
        # close that cycle during runtime.config's own initialization
        from ...runtime.precision import cast_tree

        self.params = cast_tree(params, self.config.jnp_dtype)
        self.param_bytes = sum(l.size * l.dtype.itemsize for l in
                               jax.tree_util.tree_leaves(self.params))
        if self.config.quant_bits:
            from ..quantization import quantize_inference_params

            self.cfg.wq_bits = int(self.config.quant_bits)
            self.cfg.wq_group = int(self.config.quant_group)
            self.params, _, self.param_bytes = quantize_inference_params(
                self.params, self.cfg.wq_bits, self.cfg.wq_group,
                min_size=self.config.quant_min_size)
        self._pools = PagedKVCache.init(
            self.cfg.n_layers, self.cfg.kv_heads, self.cfg.head_dim, block,
            self.config.jnp_dtype, kv_quant=self.config.kv_quant)
        self.block = block
        # A learned-position model cannot attend past its position table; cap
        # the paged window to the model's trained context.
        self.max_seq_len = min(block.max_seq_len, self.cfg.max_seq_len)
        self.allocator = BlockAllocator(
            block.num_pages,
            cache_pages=(self.config.prefix_cache_pages
                         if self.config.enable_prefix_cache else 0))
        self.prefix_cache = (PrefixCache(block.page_size, self.allocator)
                             if self.config.enable_prefix_cache else None)
        # tiered KV cache (serving/kv_tier.py): host-RAM spill & restore
        # of cold prefix pages.  Deferred import like the admission hook
        # in put(): serving imports inference, never the reverse at
        # module scope.
        self.kv_tier = None
        self._pending_spills: List[Tuple[int, Any]] = []
        self._pending_spill_keys: set = set()
        self._prefetched = True  # armed per step (see step())
        tier_cfg = self.config.kv_tier
        if isinstance(tier_cfg, dict):
            from ...serving.config import KVTierConfig

            tier_cfg = self.config.kv_tier = KVTierConfig.from_dict(tier_cfg)
        if tier_cfg is not None and tier_cfg.enabled:
            if not self.config.enable_prefix_cache:
                raise ValueError(
                    "kv_tier.enabled requires enable_prefix_cache: the "
                    "host tier captures prefix-cache LRU evictions")
            tier_cfg.validate()
            from ...serving.kv_tier import HostKVTier

            self.kv_tier = HostKVTier(tier_cfg)
            self.allocator.spill_hook = self._capture_evicted_page
        # serving counters (cache_stats / publish_metrics): token-level
        # admission vs. computation, so hit_rate is FLOP-meaningful
        self._stats = {"prefill_admitted_tokens": 0,
                       "prefill_computed_tokens": 0,
                       "prefix_hit_tokens": 0}
        # decode-phase counters (decode_stats / bench_serving A/B): model
        # invocations vs tokens produced is THE speculative-decoding
        # figure of merit — tokens per invocation
        self._dstats = {"decode_model_invocations": 0, "decode_tokens": 0,
                        "decode_host_syncs": 0, "decode_horizon_shrinks": 0,
                        "spec_proposed_tokens": 0, "spec_accepted_tokens": 0,
                        "spec_verify_calls": 0, "spec_rollback_pages": 0,
                        "spec_fallback_requests": 0}
        self._init_serving_metrics()
        self._uid = itertools.count()
        self._admit_counter = itertools.count()
        self._enqueue_counter = itertools.count()
        self._rng = np.random.RandomState(seed)

        self._queue: List[SequenceState] = []
        self._slots: List[Optional[SequenceState]] = [None] * block.max_seqs
        #: set by drain(): the engine is retiring, put() refuses admissions
        self._draining = False
        # host mirror of the device page tables, trash-filled
        self._page_table = np.full((block.max_seqs, block.max_pages_per_seq),
                                   block.trash_page, dtype=np.int32)

        cfg = self.cfg

        def _decode_and_sample(params, pools, last, pos, table, act, temps,
                               sids, key):
            logits, pools = paged_decode(cfg, params, pools, last, pos,
                                         table, act)
            # sample_tokens folds the key per (request uid, position)
            # INSIDE the program — no extra dispatch, and the SAME fold
            # the fused multi-step scan uses, so decode horizons are
            # stream-identical (greedy and sampled alike) and a sampled
            # stream keeps its noise through preemption / migration
            return sample_tokens(logits, temps, key, sids, pos + 1), pools

        self._decode = jax.jit(_decode_and_sample, donate_argnums=(1,))
        self._prefill = jax.jit(
            lambda *a: paged_prefill(cfg, *a), donate_argnums=(1,))
        self._prefill_chunk = jax.jit(
            lambda *a: paged_prefill_chunk(cfg, *a), donate_argnums=(1,))
        self._copy_page = jax.jit(paged_copy_page, donate_argnums=(0,))
        ps = self.block.page_size
        self._chunk = (-(-self.config.prefill_chunk // ps) * ps
                       if self.config.prefill_chunk > 0 else 0)
        self._sample_key = jax.random.PRNGKey(seed)
        self._decode_steps = 0
        # speculative decoding: an explicit ``proposer=`` argument wins
        # (and enables speculation regardless of mode); otherwise the
        # config block builds one.  The verify program has ONE compiled
        # width (k + 1) so every acceptance outcome reuses it.
        self.spec = self.config.speculative
        if proposer is not None:
            if self.spec.k < 1:  # the one field the engine still uses
                raise ValueError("speculative.k must be >= 1")
            self._proposer = proposer
        else:
            self.spec.validate()  # directly-built configs skip from_dict
            self._proposer = build_proposer(self.spec)
        self._spec_fallback_uids: set = set()
        self._spec_fallback_warned = False
        if self._proposer is not None:
            def _verify_and_greedy(params, pools, ids, pos, table, act, nv):
                logits, pools = paged_verify(cfg, params, pools, ids, pos,
                                             table, act, nv)
                # greedy argmax on device: [B, W] int32 crosses the link,
                # not [B, W, vocab] logits (same economics as decode)
                return (jnp.argmax(logits.astype(jnp.float32), axis=-1)
                        .astype(jnp.int32), pools)

            self._verify = jax.jit(_verify_and_greedy, donate_argnums=(1,))
        # fused multi-step decode (docs/SERVING.md "Multi-step decode"):
        # one designed exclusive decode path at a time — a configured
        # proposer owns the decode loop, so the horizon stands down
        # LOUDLY (the multi-step twin of the sampling guard)
        self._horizon = int(self.config.decode_horizon)
        if self._horizon < 1:
            raise ValueError(
                f"decode_horizon must be >= 1, got {self._horizon}")
        if self._proposer is not None and self._horizon > 1:
            logger.warning(
                f"multi-step decode: speculative decoding is enabled and "
                f"owns the decode loop — decode_horizon {self._horizon} "
                "stands down to 1 (disable speculative.mode to fuse "
                "decode steps)")
            self._horizon = 1
        #: EMA of per-token decode wall time: the deadline clamp's TPOT
        #: estimate (None until a WARM dispatch lands — a dispatch that
        #: compiled its horizon shape would seed the EMA with XLA
        #: compile seconds and poison the clamp for ~10 dispatches)
        self._tpot_ema: Optional[float] = None
        self._warm_horizons: set = set()
        if self._horizon > 1:
            def _multi_fn(params, pools, last, pos, table, act, temps,
                          eos, budg, sids, key, horizon):
                return paged_multi_decode(cfg, params, pools, last, pos,
                                          table, act, temps, eos, budg,
                                          sids, key, horizon)

            # horizon is static (the scan length); the engine only ever
            # dispatches halving-chain values, so the compiled-shape
            # set stays O(log decode_horizon)
            self._multi = jax.jit(_multi_fn, donate_argnums=(1,),
                                  static_argnums=(11,))
        else:
            self._multi = None
        # request lifecycle bookkeeping: enqueue/first-token stamps + the
        # open request span, keyed by uid (survives preemption, which
        # resets the SequenceState but not the request)
        self._req_meta: Dict[int, Dict[str, Any]] = {}
        # per-step program signature parts for the recompile sentinel:
        # each prefill bucket / chunk size and the decode program are
        # components — a compile during a step that introduced no new
        # component after warmup is a steady-state recompilation
        self._step_parts: set = set()
        self._sentinel = (RecompileSentinel(
            loop="serve", steady_after=self.config.sentinel_steady_after)
            if self.config.recompile_sentinel else None)
        # step-time attribution: periodic (timeline_every_n_steps) or
        # on-demand (force_timeline_capture); only the captured step
        # pays the profiler cost
        from ...telemetry.timeline import StepTimeline

        self._timeline = StepTimeline(
            every_n_steps=self.config.timeline_every_n_steps,
            artifact_dir=self.config.timeline_artifact_dir)
        self._wire_memory_ledger()

    def _wire_memory_ledger(self) -> None:
        """Attach the serving engine's HBM residents to the process
        memory ledger (telemetry/memory.py): the weight copy, the KV
        page pool, and — informationally, it is a sub-slice of the pool
        — the bytes pinned by prefix-cache LRU pages.  Providers read
        ``self`` dynamically so the donated pool buffers of the latest
        step are measured.  Co-located engines replace each other's
        components (latest owner wins); ``close()`` detaches exactly
        what this engine attached so a torn-down engine's weights and
        KV pool are not kept alive by the process-lifetime ledger."""
        self._ledger_components = []
        if not self.config.memory_ledger:
            return
        from ...telemetry.memory import get_memory_ledger

        led = get_memory_ledger()
        led.install_phase_watch()  # prefill/decode peak watermarks

        def _attach(name, provider, **kw):
            led.attach(name, provider, **kw)
            self._ledger_components.append((name, provider))

        _attach("serving_params", lambda: self.params)
        _attach("kv_pool", lambda: self._pools)
        _attach("kv_prefix_pinned",
                lambda: {"device": self._pinned_page_bytes()},
                informational=True)
        if self.kv_tier is not None:
            # spilled pages are real host RAM this engine owns
            _attach("kv_host_tier",
                    lambda: {"host": self.kv_tier.host_bytes})
        led.update_context(
            kv_num_pages=self.block.num_pages,
            kv_page_size=self.block.page_size,
            kv_max_seqs=self.block.max_seqs,
            kv_quant=self.config.kv_quant,
            prefix_cache=self.config.enable_prefix_cache)

    def _pinned_page_bytes(self) -> int:
        """Device bytes held by prefix-cache-pinned (LRU) pages: the
        pool's per-page cost times the parked-page count."""
        from ...telemetry.memory import tree_bytes

        dev, _host = tree_bytes(self._pools)
        return dev * self.allocator.lru_pages // (self.block.num_pages + 1)

    # -- telemetry -----------------------------------------------------------
    def _init_serving_metrics(self) -> None:
        """Register the serving metric family on the process telemetry
        registry (get-or-create: several engines in one process share
        the cumulative series; ``cache_stats`` keeps the per-engine view
        via ``self._stats`` and the allocator/prefix-cache counters)."""
        reg = get_registry()
        self._m_queue = reg.gauge(
            "deepspeed_tpu_serving_queue_depth",
            "requests waiting for admission")
        self._m_occupancy = reg.gauge(
            "deepspeed_tpu_serving_batch_occupancy",
            "occupied decode slots / max_seqs")
        self._m_prefill_h = reg.histogram(
            "deepspeed_tpu_serving_prefill_seconds",
            "per-sequence prefill program wall time (one chunk or whole "
            "prompt, incl. the prefix-end sample)")
        self._m_decode_h = reg.histogram(
            "deepspeed_tpu_serving_decode_seconds",
            "one batched decode step wall time (dispatch + token fetch)")
        self._m_requests = reg.counter(
            "deepspeed_tpu_serving_requests_total", "requests enqueued")
        self._m_gen_tokens = reg.counter(
            "deepspeed_tpu_serving_tokens_generated_total",
            "tokens produced by the decode program")
        self._m_admitted = reg.counter(
            "deepspeed_tpu_serving_prefill_admitted_tokens_total",
            "prompt tokens admitted for prefill")
        self._m_computed = reg.counter(
            "deepspeed_tpu_serving_prefill_computed_tokens_total",
            "prompt tokens actually computed (admitted minus prefix hits)")
        self._m_hit_tokens = reg.counter(
            "deepspeed_tpu_serving_prefix_hit_tokens_total",
            "prompt tokens served from the prefix cache")
        self._m_cache_hits = reg.counter(
            "deepspeed_tpu_serving_prefix_cache_hits_total",
            "prefix-cache page lookups that matched")
        self._m_cache_misses = reg.counter(
            "deepspeed_tpu_serving_prefix_cache_misses_total",
            "admission walks ending on a missing page")
        self._m_cache_evict = reg.counter(
            "deepspeed_tpu_serving_prefix_cache_evictions_total",
            "cached pages evicted (LRU or cap trim)")
        self._m_cached_pages = reg.gauge(
            "deepspeed_tpu_serving_prefix_cached_pages",
            "pages currently parked in the prefix cache")
        self._m_preemptions = reg.counter(
            "deepspeed_tpu_serving_preemptions_total",
            "sequences evicted to the queue under KV-pool pressure")
        # KV page-pool occupancy: used + free == num_pages; pinned pages
        # (cached-but-unreferenced LRU) are a subset of free — allocatable,
        # but evicting them costs future prefix hits
        self._m_kv_used = reg.gauge(
            "deepspeed_tpu_serving_kv_pages_used",
            "KV pool pages referenced by live sequences")
        self._m_kv_free = reg.gauge(
            "deepspeed_tpu_serving_kv_pages_free",
            "allocatable KV pool pages (truly free + cached-unreferenced)")
        self._m_kv_pinned = reg.gauge(
            "deepspeed_tpu_serving_kv_pages_pinned",
            "cached-but-unreferenced pages parked in the prefix-cache LRU")
        self._m_ttft_h = reg.histogram(
            "deepspeed_tpu_serving_ttft_seconds",
            "time to first token: enqueue to first sampled token "
            "(includes queue wait)")
        self._m_tpot_h = reg.histogram(
            "deepspeed_tpu_serving_tpot_seconds",
            "mean time per output token after the first, observed once "
            "per finished request")
        # speculative decoding family (speculative.py; all still valid —
        # flat zeros — with speculation off, like the cache counters)
        self._m_invocations = reg.counter(
            "deepspeed_tpu_serving_decode_model_invocations_total",
            "decode-phase model program calls (plain decode steps + "
            "speculative verify calls) — tokens/invocation is the "
            "speculative figure of merit")
        self._m_spec_proposed = reg.counter(
            "deepspeed_tpu_serving_spec_proposed_tokens_total",
            "draft tokens proposed for verification")
        self._m_spec_accepted = reg.counter(
            "deepspeed_tpu_serving_spec_accepted_tokens_total",
            "draft tokens accepted (matched the model's greedy choice)")
        self._m_spec_rollback = reg.counter(
            "deepspeed_tpu_serving_spec_rollback_pages_total",
            "draft-reserved KV pages rolled back after rejection")
        self._m_spec_fallback = reg.counter(
            "deepspeed_tpu_serving_spec_fallback_requests_total",
            "non-greedy requests routed to the plain decode program by "
            "the sampling guard (speculation never changes the "
            "sampling distribution)")
        self._m_spec_tps = reg.histogram(
            "deepspeed_tpu_serving_spec_tokens_per_step",
            "tokens emitted per sequence per verify call (accepted "
            "prefix + the model's correction token; >= 1)")
        self._m_spec_rate = reg.gauge(
            "deepspeed_tpu_serving_spec_acceptance_rate",
            "cumulative accepted / proposed draft tokens")
        self._m_spec_verify_h = reg.histogram(
            "deepspeed_tpu_serving_spec_verify_seconds",
            "one batched speculative verify program wall time")
        # fused multi-step decode family (decode_horizon > 1,
        # docs/SERVING.md "Multi-step decode"): the dispatch economics
        # of the K-step decode scan — tokens banked per device
        # round-trip, round-trips paid, horizons shrunk under pressure
        self._m_tokens_per_dispatch = reg.histogram(
            "deepspeed_tpu_serving_decode_tokens_per_dispatch",
            "tokens emitted per decode-phase device dispatch (a fused "
            "multi-step scan emits up to horizon x batch per dispatch; "
            "the K=1 loop at most batch)")
        self._m_host_syncs = reg.counter(
            "deepspeed_tpu_serving_decode_host_syncs_total",
            "decode-phase host round-trips (device token pulls): the "
            "fused multi-step scan pays ONE per horizon where the K=1 "
            "loop pays one per token")
        self._m_horizon_shrink = reg.counter(
            "deepspeed_tpu_serving_decode_horizon_shrink_total",
            "multi-step dispatches whose horizon was shrunk below "
            "decode_horizon (KV-pool headroom pressure or short row "
            "budgets) instead of preempting mid-scan")
        # serving-SLO family (docs/OBSERVABILITY.md): deadline expiry,
        # queue wait, and TTFT/TPOT SLO-violation accounting live on the
        # engine; the shed + breaker halves of the family live on the
        # fleet tier (serving/admission.py, serving/router.py)
        self._m_deadline = reg.counter(
            "deepspeed_tpu_serving_slo_deadline_exceeded_total",
            "requests expired past their deadline at a step boundary "
            '(finish_reason="deadline")')
        self._m_queue_wait_h = reg.histogram(
            "deepspeed_tpu_serving_slo_queue_wait_seconds",
            "enqueue -> admission wait, observed per admission (a "
            "preempted sequence re-admitting observes again)")
        self._m_ttft_viol = reg.counter(
            "deepspeed_tpu_serving_slo_ttft_violations_total",
            "first tokens arriving later than slo_ttft_s")
        self._m_tpot_viol = reg.counter(
            "deepspeed_tpu_serving_slo_tpot_violations_total",
            "finished requests whose mean inter-token time exceeded "
            "slo_tpot_s")
        # last-published absolutes for the per-engine cache counters, so
        # the process-cumulative registry counters only receive deltas
        self._cache_pub = {"hits": 0, "misses": 0, "evictions": 0}

    def _phase(self, name: str, hist, **attrs) -> PhaseTimer:
        """Profiler annotation + wall-time histogram + trace-ring span
        for one serving phase (prefill/decode); ``attrs`` land on the
        span only."""
        return PhaseTimer(name, sink=lambda _n, dt: hist.observe(dt), **attrs)

    # -- request lifecycle bookkeeping ---------------------------------------
    def _reqtrace(self, seq: SequenceState):
        """The fleet ledger entry for ``seq`` (None when the engine runs
        standalone — every reqtrace hook below is then a no-op)."""
        if seq is None or seq.trace_id is None:
            return None
        led = get_reqtrace_ledger()
        return None if led is None else led.get(seq.trace_id)

    def _note_tokens(self, seq: SequenceState, n: int = 1,
                     t: Optional[float] = None) -> None:
        """Account ``n`` newly emitted tokens against the request: the
        first one closes the TTFT window (enqueue -> first token,
        queue wait included).  ``t`` is the token's emit timestamp — a
        fused multi-step dispatch passes per-token timestamps
        RECONSTRUCTED from the horizon (token j landed ~j+1 device
        steps in), so TTFT/TPOT and their SLO-violation checks never
        see a K-token burst stamped at one instant."""
        m = self._req_meta.get(seq.uid)
        if m is None:
            return
        now = t if t is not None else time.perf_counter()
        if m["t_first"] is None:
            m["t_first"] = now
            ttft = now - m["t0"]
            self._m_ttft_h.observe(ttft)
            tr = self._reqtrace(seq)
            if tr is not None:
                # ledger TTFT is set-once from FIRST submission (a
                # re-dispatched request keeps its original clock); the
                # histogram above keeps per-(re)enqueue semantics
                tr.note_first_token(now)
                tr.transition("decode",
                              getattr(self, "trace_owner", "engine"), now)
            if 0 < self.config.slo_ttft_s < ttft:
                self._m_ttft_viol.inc()
                slo_exemplar("deepspeed_tpu_serving_slo_ttft_violations_total",
                             seq.trace_id, uid=seq.uid,
                             ttft_s=round(ttft, 6))
                self._slo_violation("ttft", ttft, self.config.slo_ttft_s,
                                    seq.uid, seq.trace_id)
        m["t_last"] = now
        m["n"] += n

    def _slo_violation(self, kind: str, value: float, limit: float,
                       uid: int, trace_id: Optional[str] = None) -> None:
        """One call site for the ``slo_violation`` event (TTFT and TPOT
        both thread through here — the name lint wants a single owner)."""
        record_event("slo_violation", cat="serve", kind=kind,
                     value=round(value, 6), limit=limit, uid=uid,
                     **({} if trace_id is None else {"trace_id": trace_id}))

    def _finish_request(self, seq: SequenceState) -> None:
        """Close the request span and observe TPOT (mean inter-token
        time after the first — the decode-side latency SLO)."""
        m = self._req_meta.pop(seq.uid, None)
        if m is None:
            return
        if m["n"] > 1 and m["t_first"] is not None:
            tpot = (m["t_last"] - m["t_first"]) / (m["n"] - 1)
            self._m_tpot_h.observe(tpot)
            if 0 < self.config.slo_tpot_s < tpot:
                self._m_tpot_viol.inc()
                slo_exemplar("deepspeed_tpu_serving_slo_tpot_violations_total",
                             seq.trace_id, uid=seq.uid,
                             tpot_s=round(tpot, 6))
                self._slo_violation("tpot", tpot, self.config.slo_tpot_s,
                                    seq.uid, seq.trace_id)
        end_span(m["span"], generated=m["n"],
                 total_s=round(time.perf_counter() - m["t0"], 6))
        if seq.trace_id is not None:
            led = get_reqtrace_ledger()
            if led is not None:
                led.finish(seq.trace_id, seq.finish_reason or "complete")

    def _pool_occupancy(self) -> Dict[str, int]:
        """Current KV page-pool occupancy, attached to every admission/
        preemption event so scheduling decisions are explainable from
        the event log alone."""
        a = self.allocator
        return {"pages_used": a.used_pages, "pages_free": a.free_pages,
                "pages_pinned": a.lru_pages}

    def _publish_pool_gauges(self) -> None:
        occ = self._pool_occupancy()
        self._m_kv_used.set(occ["pages_used"])
        self._m_kv_free.set(occ["pages_free"])
        self._m_kv_pinned.set(occ["pages_pinned"])

    def _sync_cache_counters(self) -> None:
        """Forward allocator/prefix-cache counter deltas to the registry
        (those objects stay the per-engine source of truth; re-homing
        them wholesale would break per-engine ``cache_stats``)."""
        self._publish_pool_gauges()
        pub = self._cache_pub
        ev = self.allocator.evictions
        if ev > pub["evictions"]:
            self._m_cache_evict.inc(ev - pub["evictions"])
            pub["evictions"] = ev
        if self.prefix_cache is not None:
            h, m = self.prefix_cache.hits, self.prefix_cache.misses
            if h > pub["hits"]:
                self._m_cache_hits.inc(h - pub["hits"])
                pub["hits"] = h
            if m > pub["misses"]:
                self._m_cache_misses.inc(m - pub["misses"])
                pub["misses"] = m
        self._m_cached_pages.set(self.allocator.cached_pages)

    # -- request API ---------------------------------------------------------
    def put(self, request: RaggedRequest, *, record_shed: bool = True
            ) -> int:
        """Queue a request; returns its uid.

        ``record_shed=False`` hands shed accounting to the caller: a
        multi-candidate placer (the fleet router) tries several engines
        and must count at most ONE shed per request, not one per
        refusing engine."""
        if self._draining:
            raise RuntimeError("engine is draining/retired: no new "
                               "admissions (route to another replica)")
        uid = request.uid if request.uid is not None else next(self._uid)
        n = len(request.prompt_ids)
        if n == 0:
            raise ValueError("empty prompt")
        if n >= self.max_seq_len:
            raise ValueError(f"prompt length {n} >= max_seq_len "
                             f"{self.max_seq_len}")
        if (self.config.max_queue_depth > 0
                and len(self._queue) >= self.config.max_queue_depth):
            # bounded queue: shed LOUDLY instead of growing the queue
            # into an OOM/preemption storm.  Deferred import: admission
            # (serving tier) owns the shed counter; serving imports
            # inference, never the reverse at module scope.
            from ...serving.admission import (record_shed as _record_shed,
                                              retry_after_hint)

            hint = retry_after_hint(len(self._queue))
            if record_shed:
                _record_shed(request.priority, "engine_queue_full", hint,
                             uid=request.uid, trace_id=request.trace_id)
            raise RejectedError("engine_queue_full", retry_after_s=hint,
                                priority=request.priority)
        now = time.perf_counter()
        self._queue.append(SequenceState(
            uid=uid, tokens=list(request.prompt_ids), prompt_len=n,
            max_new_tokens=request.max_new_tokens,
            temperature=request.temperature, eos_id=request.eos_id,
            priority=int(request.priority),
            deadline=(now + max(0.0, float(request.deadline_s))
                      if request.deadline_s is not None else 0.0),
            enqueue_order=next(self._enqueue_counter),
            queued_at=now, trace_id=request.trace_id))
        self._req_meta[uid] = {
            "t0": now, "t_first": None, "t_last": None,
            "n": 0,
            "span": begin_span("request", cat="serve", uid=uid,
                               prompt_tokens=n, priority=request.priority,
                               max_new_tokens=request.max_new_tokens,
                               **({} if request.trace_id is None
                                  else {"trace_id": request.trace_id,
                                        "replica": getattr(
                                            self, "trace_owner", "engine")}))}
        self._m_requests.inc()
        self._m_queue.set(len(self._queue))
        return uid

    def has_work(self) -> bool:
        return bool(self._queue) or any(s is not None for s in self._slots)

    @property
    def queue_depth(self) -> int:
        """Requests waiting for admission (what the queue-depth gauge
        publishes) — the router's load signal."""
        return len(self._queue)

    @property
    def active_count(self) -> int:
        """Occupied decode slots (what the batch-occupancy gauge
        publishes, un-normalized)."""
        return sum(1 for s in self._slots if s is not None)

    def inflight_uids(self) -> List[int]:
        """uids of every unfinished request this engine owns: admitted
        (in a slot) first, then queued."""
        return ([s.uid for s in self._slots if s is not None]
                + [s.uid for s in self._queue])

    def ready_uids(self) -> List[int]:
        """uids of admitted sequences that are decode-ready (prefill
        complete, first token sampled) — the migration candidates a
        disaggregated router streams from prefill to decode replicas."""
        return [s.uid for s in self._slots
                if s is not None and self._ready_to_decode(s)]

    # -- KV-page migration (export / import / release) -----------------------
    def _find_slotted(self, uid: int) -> SequenceState:
        seq = next((s for s in self._slots
                    if s is not None and s.uid == uid), None)
        if seq is None:
            raise KeyError(f"uid {uid} is not in a decode slot (queued or "
                           "unknown sequences have no KV pages to export)")
        return seq

    def export_sequence(self, uid: int) -> KVPageBundle:
        """Serialize an admitted sequence's KV pages + scheduling state
        into a :class:`KVPageBundle` (host arrays, bit-exact).  The
        sequence KEEPS running here — callers release it only after a
        successful import elsewhere, so a failed handoff loses nothing."""
        seq = self._find_slotted(uid)
        ps = self.block.page_size
        immutable = seq.prefilled // ps  # pages never written again
        keys = list(seq.page_keys[:min(immutable, len(seq.page_keys))])
        bundle = KVPageBundle(
            uid=seq.uid, tokens=list(seq.tokens), prompt_len=seq.prompt_len,
            max_new_tokens=seq.max_new_tokens, temperature=seq.temperature,
            eos_id=seq.eos_id, prefilled=seq.prefilled,
            decode_entry=seq.decode_entry, page_size=ps, page_keys=keys,
            priority=seq.priority, deadline=seq.deadline,
            src_pages=self.allocator.export_meta(seq.pages),
            arrays=paged_gather_pages(self._pools, seq.pages),
            model_sig=(self.cfg.n_layers, self.cfg.kv_heads,
                       self.cfg.head_dim),
            kv_quant=bool(self.config.kv_quant), dtype=self.config.dtype)
        tr = self._reqtrace(seq)
        if tr is not None:
            # the handoff starts here: the ledger phase flips to
            # kv_transfer, and the bundle carries the trace context —
            # trace id, clock-free ledger snapshot, per-hop stamp list
            # (the wire codec appends wall stamps as the bytes move)
            tr.transition("kv_transfer",
                          getattr(self, "trace_owner", "engine"))
            bundle.trace = {"trace_id": seq.trace_id,
                            "snapshot": tr.wire_snapshot(), "hops": []}
        elif seq.trace_id is not None:
            bundle.trace = {"trace_id": seq.trace_id, "snapshot": None,
                            "hops": []}
        record_event("kv_export", cat="serve", uid=uid,
                     pages=len(seq.pages), tokens=len(seq.tokens),
                     **({} if seq.trace_id is None
                        else {"trace_id": seq.trace_id}))
        # the gather runs op-by-op outside the step programs: announce
        # its compiles so no sentinel flags them as steady-state
        sentinel_expect_recompile("kv_export")
        return bundle

    def _check_bundle(self, b: KVPageBundle) -> None:
        sig = (self.cfg.n_layers, self.cfg.kv_heads, self.cfg.head_dim)
        if tuple(b.model_sig) != sig:
            raise ValueError(f"bundle model_sig {tuple(b.model_sig)} != "
                             f"engine {sig}")
        if b.page_size != self.block.page_size:
            raise ValueError(f"bundle page_size {b.page_size} != "
                             f"{self.block.page_size}")
        if bool(b.kv_quant) != bool(self.config.kv_quant):
            raise ValueError("kv_quant mismatch between bundle and engine")
        if str(b.dtype) != str(self.config.dtype):
            # checked here, not just in the fresh-page scatter: an
            # all-adopted import never scatters, and sharing pages
            # across precisions would silently break bit-identity
            raise ValueError(f"bundle dtype {b.dtype!r} != engine dtype "
                             f"{self.config.dtype!r}")
        if b.n_pages > self.block.max_pages_per_seq:
            raise ValueError(f"bundle spans {b.n_pages} pages > "
                             f"max_pages_per_seq {self.block.max_pages_per_seq}")
        if len(b.tokens) >= self.max_seq_len:
            raise ValueError(f"bundle length {len(b.tokens)} >= max_seq_len "
                             f"{self.max_seq_len}: nothing left to decode")
        ready = (b.generated > 0 or b.decode_entry) \
            and b.prefilled >= len(b.tokens) - 1
        if not ready:
            raise ValueError(
                "bundle is not decode-ready (mid-prefill handoff is not "
                "supported: re-dispatch the request instead)")

    def import_sequence(self, bundle: KVPageBundle) -> bool:
        """Adopt a migrated sequence: place its KV pages in this pool
        (sharing content-matched registered pages instead of copying —
        ref-count adoption) and schedule it straight into a decode slot.

        Returns ``False`` — with the engine untouched — when no slot or
        not enough pages are free (the caller tries another replica);
        raises ``ValueError`` on genuine incompatibility (different
        model geometry / page size / kv_quant / dtype)."""
        self._check_bundle(bundle)
        slot = next((i for i, s in enumerate(self._slots) if s is None), None)
        if slot is None:
            return False
        n = bundle.n_pages
        keys = list(bundle.page_keys)
        adopt_keys: List[Any] = [None] * n
        if self.prefix_cache is not None:
            for j, k in enumerate(keys[:n]):
                adopt_keys[j] = k
        try:
            pages, reused = self.allocator.adopt(adopt_keys)
        except MemoryError:
            return False
        fresh = [j for j, r in enumerate(reused) if not r]
        if fresh:
            # dtype mismatches raise inside the scatter — but only after
            # pages were allocated; free them so a refused import does
            # not leak pool capacity
            try:
                self._pools = paged_scatter_pages(
                    self._pools, [pages[j] for j in fresh],
                    {k: v[:, fresh] for k, v in bundle.arrays.items()})
            except ValueError:
                self.allocator.free(pages)
                raise
            # op-by-op scatter outside the step programs (see export)
            sentinel_expect_recompile("kv_import")
        if self.prefix_cache is not None:
            # publish freshly-written FULL pages locally (first writer
            # wins) so the importing replica's cache warms too; adopted
            # pages are already registered here
            for j in fresh:
                if j < len(keys):
                    self.allocator.register(pages[j], keys[j])
        trace_id = None
        if bundle.trace is not None:
            trace_id = bundle.trace.get("trace_id")
        seq = SequenceState(
            uid=bundle.uid, tokens=list(bundle.tokens),
            prompt_len=bundle.prompt_len,
            max_new_tokens=bundle.max_new_tokens,
            temperature=bundle.temperature, eos_id=bundle.eos_id,
            slot=slot, pages=pages, prefilled=bundle.prefilled,
            decode_entry=bundle.decode_entry, page_keys=keys,
            registered_upto=len(keys),
            priority=bundle.priority, deadline=bundle.deadline,
            enqueue_order=next(self._enqueue_counter), trace_id=trace_id)
        seq.admit_order = next(self._admit_counter)
        self._slots[slot] = seq
        self._page_table[slot, :] = self.block.trash_page
        self._page_table[slot, :len(pages)] = pages
        now = time.perf_counter()
        if trace_id is not None:
            led = get_reqtrace_ledger()
            if led is not None:
                tr = led.get(trace_id)
                if tr is None and bundle.trace.get("snapshot") is not None:
                    # cross-process import: re-anchor the sender's
                    # ledger here, wire transit folded into kv_transfer
                    tr = led.adopt(bundle.trace["snapshot"],
                                   transit_s=float(bundle.trace.get(
                                       "transit_s", 0.0)))
                if tr is not None:
                    tr.transition("decode",
                                  getattr(self, "trace_owner", "engine"),
                                  now)
        # TTFT belongs to the exporting engine (it sampled the first
        # token); local TPOT accounting restarts at the handoff
        self._req_meta[bundle.uid] = {
            "t0": now, "t_first": now if bundle.generated > 0 else None,
            "t_last": now, "n": bundle.generated,
            "span": begin_span("request_migrated", cat="serve",
                               uid=bundle.uid, tokens=len(bundle.tokens),
                               adopted_pages=sum(reused),
                               **({} if trace_id is None
                                  else {"trace_id": trace_id,
                                        "replica": getattr(
                                            self, "trace_owner",
                                            "engine")}))}
        record_event("kv_import", cat="serve", uid=bundle.uid, slot=slot,
                     pages=n, adopted=sum(reused),
                     **({} if trace_id is None else {"trace_id": trace_id}),
                     **self._pool_occupancy())
        self._publish_pool_gauges()
        return True

    def release_sequence(self, uid: int, reason: str = "migrated") -> None:
        """Drop an admitted sequence WITHOUT finishing it (its pages are
        freed, its request span closed) — the source side of a completed
        migration, after ``import_sequence`` succeeded elsewhere."""
        seq = self._find_slotted(uid)
        self.allocator.free(seq.pages)
        self._page_table[seq.slot, :] = self.block.trash_page
        self._slots[seq.slot] = None
        seq.slot, seq.pages = -1, []
        m = self._req_meta.pop(uid, None)
        if m is not None:
            end_span(m["span"], released=reason, generated=m["n"])
        self._publish_pool_gauges()

    # -- tiered KV cache: host-RAM spill & restore ---------------------------
    def _capture_evicted_page(self, page: int, key: Any) -> bool:
        """``BlockAllocator.spill_hook``: decide whether an LRU-evicted
        prefix page is captured for the host tier.  Capturing only
        QUEUES the page (bounded by ``kv_tier.spill_inflight``) — the
        allocator pins it via refcount so it cannot be handed out, and
        therefore never overwritten, until :meth:`_drain_spills` commits
        the D2H copy at the next step boundary."""
        tier = self.kv_tier
        if tier is None or key is None:
            return False
        if len(self._pending_spills) >= tier.config.spill_inflight:
            tier.note_capture_dropped()
            return False
        if tier.has(key) or key in self._pending_spill_keys:
            # same chain key => bit-identical content (the programs are
            # deterministic): the copy already sits in the host tier, or
            # is already queued this drain window — don't pin a second
            # page and D2H the same bytes twice
            return False
        self._pending_spills.append((page, key))
        self._pending_spill_keys.add(key)
        return True

    def _drain_spills(self) -> None:
        """Commit pending host-tier spills in ONE batched D2H gather
        (step boundary, off the hot device path): gather the pinned
        pages' slices across every pool leaf — the exact-dtype
        ``paged_gather_pages`` layout KV migration uses — stamp the
        wire format's per-page CRC32, insert into the host LRU, then
        release the pins so the pages rejoin the free list."""
        if not self._pending_spills:
            return
        from ...serving.kv_tier import batch_page_crcs, page_slices

        pend, self._pending_spills = self._pending_spills, []
        self._pending_spill_keys = set()
        t0 = time.perf_counter()
        # bucket the gather rows to powers of two (trash-padded) so the
        # op-by-op path keeps a small fixed compiled-shape set
        rows = pad_pages_pow2([p for p, _ in pend], self.block.trash_page)
        self._step_parts.add(("kv_spill", len(rows)))
        sentinel_expect_recompile("kv_tier_spill")
        arrays = paged_gather_pages(self._pools, rows)
        arrays = {n: a[:, :len(pend)] for n, a in arrays.items()}
        crcs = batch_page_crcs(arrays)
        for j, (page, key) in enumerate(pend):
            self.kv_tier.insert(key, page_slices(arrays, j), crcs[j])
            self.allocator.release_spill_pin(page)
        self.kv_tier.note_spill(len(pend), time.perf_counter() - t0)

    def flush_spills(self) -> None:
        """Commit any pending host-tier spills NOW (tests, retirement,
        bench leg boundaries) — the engine otherwise drains them at the
        next step boundary."""
        self._drain_spills()

    def _current_match(self, seq: SequenceState):
        """Memoized device prefix match for a queued sequence: walked
        only when the registry generation moved, and RESUMED from the
        memo's end when only registrations happened (see _admit)."""
        if seq.match_gen != self.allocator.generation:
            resume = (seq.cached_match
                      if seq.match_evict_gen
                      == self.allocator.evict_generation else None)
            seq.cached_match = self.prefix_cache.match(seq.tokens,
                                                       resume=resume)
            seq.match_gen = self.allocator.generation
            seq.match_evict_gen = self.allocator.evict_generation
        return seq.cached_match

    def _tier_restore(self, tokens: List[int], shared: List[int],
                      keys: List[Any], park: bool = False
                      ) -> Tuple[List[int], List[Any], List[int]]:
        """Extend a device prefix match with HOST-tier pages: continue
        the chain-key walk into the host LRU past the device hit,
        allocate fresh pages, H2D-scatter the restored KV (the same
        ``paged_scatter_pages`` path KV import uses, bucketed so one
        compiled shape set serves all restores), and REGISTER the pages
        under their chain keys — from here on they behave exactly like
        device cache hits (suffix-only prefill, CoW on a full hit,
        bit-identical streams).

        Returns ``(shared, keys, restored)`` — new lists; ``restored``
        pages arrive REFERENCED (their alloc ref), exactly like the
        claimed device matches the admission holds — the caller keeps
        the refs as the sequence's own, or frees them to re-park if it
        blocks.  With ``park=True`` (the prefetch path) the refs are
        dropped here: the pages sit registered + LRU-parked at the MRU
        end, and the eventual admission maps them as device hits.  The
        prefetch path spends only truly-free pages and never overflows
        the LRU cap — prefetch must not evict content admission is
        about to need."""
        tier = self.kv_tier
        ps = self.block.page_size
        n_full = len(tokens) // ps
        if tier is None or len(shared) >= n_full:
            return shared, keys, []
        host_keys = self.prefix_cache.host_extend(tokens, keys, tier)
        # miss accounting (admission attempts only — prefetch re-walks a
        # blocked head every step and must not inflate the rate): the
        # tier missed when the walk needed pages it does not hold — an
        # EMPTY extension past a short device match included
        missed = len(shared) + len(host_keys) < n_full
        if not host_keys:
            if missed and not park:
                tier.note_miss()
            return shared, keys, []
        if not park:
            # hopeless-admission guard: every non-device-matched page
            # (restored or computed, +1 for a possible CoW duplicate)
            # must come out of the pool — if even that total cannot fit,
            # the admission will block regardless, and restoring now
            # would churn restore -> block -> park -> trim every step
            n_total = -(-len(tokens) // ps)
            if n_total - len(shared) + 1 > self.allocator.free_pages:
                return shared, keys, []
        cap = self.allocator.free_pages
        if park:
            cap = min(self.allocator.uncached_free_pages,
                      (self.allocator.cache_cap - self.allocator.lru_pages
                       if self.allocator.cache_cap > 0 else cap))
        if cap <= 0:
            return shared, keys, []
        entries = []
        for k in host_keys[:cap]:
            e = tier.get(k)  # CRC-verified; a corrupt page refuses
            if e is None:    # loudly and the chain ends here (miss)
                break
            entries.append(e)
        if len(entries) < min(len(host_keys), cap):
            missed = True  # a corrupt refusal cut the chain
        if missed and not park:
            tier.note_miss()
        if not entries:
            return shared, keys, []
        host_keys = host_keys[:len(entries)]
        t0 = time.perf_counter()
        fresh = self.allocator.alloc(len(entries))
        rows = pad_pages_pow2(fresh, self.block.trash_page)
        arrays: Dict[str, Any] = {}
        for name in entries[0]:
            parts = [e[name] for e in entries]
            if len(rows) > len(entries):
                pad_shape = (parts[0].shape[0], len(rows) - len(entries)) \
                    + parts[0].shape[2:]
                parts.append(np.zeros(pad_shape, dtype=parts[0].dtype))
            arrays[name] = np.concatenate(parts, axis=1)
        self._step_parts.add(("kv_restore", len(rows)))
        sentinel_expect_recompile("kv_tier_restore")
        # pad rows point at the trash page: scattered zeros land where
        # every step already writes garbage
        self._pools = paged_scatter_pages(self._pools, rows, arrays)
        for p, k in zip(fresh, host_keys):
            self.allocator.register(p, k)
        tier.note_restore(len(entries), time.perf_counter() - t0)
        if park:
            self.allocator.free(fresh)  # park at the LRU MRU end,
            # registered: the next admission maps them as device hits
            return shared + fresh, keys + host_keys, []
        return shared + fresh, keys + host_keys, fresh

    def _prefetch_restores(self) -> None:
        """Host-tier restore prefetch for queued-but-not-admitted
        requests: while the current batch decodes on device, the host
        walks the head-of-queue prefixes into the host tier and stages
        their pages back into the device pool (the H2D scatter chains
        behind the in-flight decode program).  At most once per step."""
        if self._prefetched:
            return
        self._prefetched = True
        tier = self.kv_tier
        if tier is None or not self._queue:
            return
        n = tier.config.prefetch_requests
        if n <= 0:
            return
        heads = sorted(self._queue,
                       key=lambda s: (s.priority, s.enqueue_order))[:n]
        for seq in heads:
            shared, keys = self._current_match(seq)
            self._tier_restore(seq.tokens, shared, keys, park=True)

    def tier_stats(self) -> Dict[str, float]:
        """Host-tier counters (``HostKVTier.stats``); empty dict with
        the tier off — dashboards need no conditional wiring."""
        return dict(self.kv_tier.stats()) if self.kv_tier else {}

    # -- replica retirement --------------------------------------------------
    def drain(self, max_steps: int = 10_000) -> Dict[str, Any]:
        """Stop admission and run every ADMITTED sequence to completion.

        Returns ``{"finished": {uid: SequenceState}, "pending":
        [SequenceState, ...]}``: ``finished`` holds the final states
        (full token lists, ``done`` flags) of the sequences that were
        in flight; ``pending`` are queued-but-never-admitted requests,
        returned UN-RUN for the caller to re-dispatch elsewhere.  After
        ``drain()`` the engine refuses new ``put()`` calls — this is
        clean replica retirement (``close()`` alone would drop in-flight
        work)."""
        self._draining = True
        pending = list(self._queue)
        self._queue.clear()
        for s in pending:
            m = self._req_meta.pop(s.uid, None)
            if m is not None:
                end_span(m["span"], requeued=True)
        inflight = {s.uid: s for s in self._slots if s is not None}
        steps = 0
        while any(s is not None for s in self._slots) or self._queue:
            if steps >= max_steps:
                logger.warning("engine_v2.drain: max_steps reached with "
                               "work pending")
                break
            self.step()
            steps += 1
        self._m_queue.set(len(self._queue))
        self._drain_spills()  # retirement commits captures, frees pins
        record_event("engine_drain", cat="serve", finished=len(inflight),
                     requeued=len(pending), steps=steps)
        return {"finished": inflight, "pending": pending}

    def abort_all(self, reason: str = "abort") -> List[int]:
        """Free every queued and admitted request WITHOUT running them
        (pages released, request spans closed); returns their uids.
        The hard-stop half of retirement — used after KV migration has
        moved what it could off a preempted replica, and by ``close()``
        so dropped work is never silent."""
        uids = [s.uid for s in self._queue]
        self._queue.clear()
        for i, s in enumerate(self._slots):
            if s is None:
                continue
            self.allocator.free(s.pages)
            self._page_table[i, :] = self.block.trash_page
            self._slots[i] = None
            s.slot, s.pages = -1, []
            uids.append(s.uid)
        for uid in uids:
            self._spec_fallback_uids.discard(uid)
            m = self._req_meta.pop(uid, None)
            if m is not None:
                end_span(m["span"], aborted=reason, generated=m["n"])
        if uids:
            self._m_queue.set(0)
            self._publish_pool_gauges()
        return uids

    # -- scheduling ----------------------------------------------------------
    def _bucket(self, n: int) -> int:
        # power-of-two growth from a page-size multiple keeps every bucket a
        # multiple of page_size (prefill scatters whole pages)
        ps = self.block.page_size
        b = max(self.config.min_prefill_bucket, ps)
        b = -(-b // ps) * ps  # round up: prefill scatters whole pages
        while b < n:
            b *= 2
        # cap at the page-rounded model window (self.max_seq_len, not
        # block.max_seq_len): a learned-position model must not be prefetched
        # past its position table; paged_prefill clamps the residual < ps
        cap = -(-self.max_seq_len // ps) * ps
        return min(b, cap)

    def _preempt(self, seq: SequenceState) -> None:
        """Evict a running sequence to the queue head; it will re-prefill its
        prefix (recompute, the reference scheduler's KV-pressure relief)
        when pages free up — hitting the prefix cache it just populated,
        so with caching on the "recompute" is mostly a table lookup."""
        self.allocator.free(seq.pages)
        self._page_table[seq.slot, :] = self.block.trash_page
        self._slots[seq.slot] = None
        seq.slot, seq.pages, seq.prefilled = -1, [], 0
        seq.page_keys, seq.registered_upto, seq.decode_entry = [], 0, False
        seq.cached_match, seq.match_gen, seq.match_evict_gen = None, -1, -1
        seq.queued_at = time.perf_counter()
        self._queue.insert(0, seq)
        self._m_preemptions.inc()
        tr = self._reqtrace(seq)
        if tr is not None:
            # back to queue_wait; the re-run prefill chunks will ledger
            # as recompute (work the eviction bought, not first prefill)
            tr.note_preempt(getattr(self, "trace_owner", "engine"),
                            seq.queued_at)
        occ = self._pool_occupancy()
        record_event("preempt", cat="serve", uid=seq.uid,
                     prefix_tokens=seq.length,
                     **({} if seq.trace_id is None
                        else {"trace_id": seq.trace_id}), **occ)
        # preemptions are rare and always a capacity question — log the
        # occupancy that forced this one so "why was this request
        # preempted" is answerable without a trace dump
        logger.info(
            f"serving: preempted uid={seq.uid} (prefix {seq.length} tokens) "
            f"under KV-pool pressure: {occ['pages_used']} pages used, "
            f"{occ['pages_free']} free ({occ['pages_pinned']} of them "
            f"prefix-cache pinned) of {self.block.num_pages}")

    def _admit(self) -> List[SequenceState]:
        admitted = []
        ps = self.block.page_size
        for i, slot in enumerate(self._slots):
            if not self._queue:
                break
            if slot is not None:
                continue
            # admission head: highest priority class first, FCFS within
            # a class (enqueue_order; preempted sequences keep their
            # original stamp, so they re-admit at the front of their
            # class — the old insert-at-head behavior, now per class)
            seq = min(self._queue,
                      key=lambda s: (s.priority, s.enqueue_order))
            shared: List[int] = []
            keys: List[Any] = []
            if self.prefix_cache is not None:
                # memoized while the registry is unchanged: a blocked
                # head of queue must not re-hash its prompt every step.
                # Registrations only EXTEND a valid match, so unless an
                # eviction happened the walk resumes from the memo's end
                shared, keys = self._current_match(seq)
                # CLAIM the matched pages (+1 ref) before any further
                # allocation: the tier restore's alloc below — and this
                # admission's own alloc — must never evict a page this
                # sequence is about to map (an evicted-then-reused
                # match would alias two prefix positions onto one
                # physical page).  Released again if the admission
                # blocks; share()/free() touch neither registry
                # generation, so the memo above stays valid.
                for p in shared:
                    self.allocator.share(p)
                # the host tier extends the device hit: spilled pages
                # are restored (H2D, CRC-verified, registered) and from
                # here on the admission treats them as device hits.
                # Restored pages arrive referenced (alloc), exactly
                # like the claimed matches above.
                shared, keys, _restored = self._tier_restore(
                    seq.tokens, shared, keys)
            n_total = -(-seq.length // ps)
            m = len(shared)
            # fully-cached prompt (page-aligned): the last cached page is
            # replaced by a private COPY-ON-WRITE duplicate — the decode
            # program recomputes only the final prompt token and writes
            # its KV into the copy, never into the shared page
            full_hit = m > 0 and m * ps >= seq.length
            need_new = n_total - m + (1 if full_hit else 0)
            # exact admission check: every matched page is already
            # referenced (claimed above), so free_pages alone is the
            # allocatable budget — nothing here touches the LRU
            def _fits() -> bool:
                return need_new <= self.allocator.free_pages

            while not _fits():
                # priority admission: under pool pressure a high class
                # preempts strictly-lower-class running sequences
                # (lowest class, then youngest — cheapest prefix to
                # recompute) instead of waiting behind them.  _fits()
                # recomputes per eviction; a victim's ref drop on a
                # CLAIMED page changes nothing (we still hold it).
                victims = [s for s in self._slots
                           if s is not None and s.priority > seq.priority]
                if not victims:
                    break
                # futility guard: if even reclaiming EVERY victim's
                # pages cannot cover the head (optimistic upper bound —
                # shared pages may free less), evict nobody: a
                # mass-recompute that still fails to admit is the worst
                # outcome under exactly the pressure this path serves
                if need_new > (self.allocator.free_pages
                               + sum(len(v.pages) for v in victims)):
                    break
                self._preempt(max(victims,
                                  key=lambda s: (s.priority, s.admit_order)))
            if not _fits():
                if shared:
                    # blocked: release the claims — device matches and
                    # restored pages alike park (registered, MRU end) so
                    # the next attempt re-maps them as plain device hits
                    self.allocator.free(shared)
                break  # head-of-line blocking, like the reference's FCFS
            # the claims above ARE this sequence's references: one ref
            # per ``shared`` page is held from here on
            self._queue.remove(seq)
            seq.cached_match, seq.match_gen, seq.match_evict_gen = None, -1, -1
            if seq.queued_at > 0.0:
                self._m_queue_wait_h.observe(
                    time.perf_counter() - seq.queued_at)
            fresh = self.allocator.alloc(need_new)
            if full_hit:
                src, dst = shared[-1], fresh[-1]
                self._step_parts.add("copy_page")
                self._pools = self._copy_page(self._pools, jnp.int32(src),
                                              jnp.int32(dst))
                self.allocator.free([src])  # drop our ref on the original
                seq.pages = shared[:-1] + [dst]
                seq.prefilled = seq.length - 1
                seq.decode_entry = True
            else:
                seq.pages = shared + fresh
                seq.prefilled = m * ps
            seq.page_keys = keys
            # matched pages are already registered; the CoW copy stays
            # private (the original remains the canonical cached page)
            seq.registered_upto = n_total if full_hit else m
            if self.prefix_cache is not None:
                self.prefix_cache.count(m, seq.length // ps)
            self._stats["prefill_admitted_tokens"] += seq.length
            self._stats["prefix_hit_tokens"] += seq.prefilled
            self._stats["prefill_computed_tokens"] += seq.length - seq.prefilled
            self._m_admitted.inc(seq.length)
            self._m_hit_tokens.inc(seq.prefilled)
            self._m_computed.inc(seq.length - seq.prefilled)
            seq.slot = i
            seq.admit_order = next(self._admit_counter)
            self._page_table[i, :] = self.block.trash_page
            self._page_table[i, :len(seq.pages)] = seq.pages
            tr = self._reqtrace(seq)
            if tr is not None:
                # queue_wait closes here; "prefill" self-classifies as
                # recompute after a preemption or re-dispatch
                tr.transition("prefill",
                              getattr(self, "trace_owner", "engine"))
            record_event("admit", cat="serve", uid=seq.uid, slot=i,
                         cache_hit_pages=m, new_pages=len(fresh),
                         full_hit=full_hit,
                         **({} if seq.trace_id is None
                            else {"trace_id": seq.trace_id}),
                         **self._pool_occupancy())
            admitted.append(seq)
            self._slots[i] = seq
        self._publish_pool_gauges()
        return admitted

    def _register_pages(self, seq: SequenceState) -> None:
        """Offer every fully-written, not-yet-registered page of ``seq``
        to the prefix-cache registry (first writer wins).  Called after
        each KV-writing program, BEFORE any retire can free the pages —
        a registered page freed later parks in the LRU with its content
        intact."""
        if self.prefix_cache is None:
            return
        full = seq.prefilled // self.block.page_size
        if full <= seq.registered_upto:
            return
        seq.page_keys = self.prefix_cache.page_keys(seq.tokens, full,
                                                    seq.page_keys)
        for j in range(seq.registered_upto, full):
            self.allocator.register(seq.pages[j], seq.page_keys[j])
        seq.registered_upto = full

    def _emit_sampled(self, seq: SequenceState, logits, out) -> None:
        """Sample off prefix-end logits, append, record, maybe retire —
        shared by the whole-prompt and final-chunk prefill paths."""
        # dstpu-lint: allow[host-sync] host sampling of the prefix-end
        # logits: one [vocab] row per ADMISSION, not per decode step
        tok = self._sample(seq, np.asarray(logits, np.float32))
        seq.tokens.append(tok)
        self._note_tokens(seq)
        out[seq.uid] = {"tokens": [tok], "done": False}
        self._maybe_finish(seq, tok)
        if seq.done:
            out[seq.uid]["done"] = True
            out[seq.uid]["finish_reason"] = seq.finish_reason

    @staticmethod
    def _ready_to_decode(seq: SequenceState) -> bool:
        """KV written for tokens[0:length-1] AND a token has been sampled
        off the prefix end — mid-chunked-prefill sequences (and preempted
        ones re-prefilling their prefix) must not enter the decode batch.
        Exception: a fully-cached prompt (decode_entry) starts decoding
        immediately — its first decode step recomputes the final prompt
        token's KV (into its CoW page) and samples the first token."""
        return ((seq.generated > 0 or seq.decode_entry)
                and seq.prefilled >= seq.length - 1)

    def _sample(self, seq: SequenceState, logits: np.ndarray) -> int:
        if seq.temperature <= 0.0:
            return int(np.argmax(logits))
        z = logits.astype(np.float64) / seq.temperature
        z -= z.max()
        p = np.exp(z)
        p /= p.sum()
        return int(self._rng.choice(len(p), p=p))

    def _retire(self, seq: SequenceState) -> None:
        self.allocator.free(seq.pages)
        self._page_table[seq.slot, :] = self.block.trash_page
        self._slots[seq.slot] = None
        seq.slot, seq.pages, seq.done = -1, [], True
        self._spec_fallback_uids.discard(seq.uid)
        self._finish_request(seq)

    # -- deadlines -----------------------------------------------------------
    def _expire(self, seq: SequenceState,
                out: Dict[int, Dict[str, Any]]) -> None:
        """Retire one past-deadline sequence (queued or admitted) with
        ``finish_reason="deadline"``: its pages free immediately, the
        request span closes, and the expiry is a *finished* step-output
        record — the stream ends loudly, it does not hang."""
        seq.finish_reason = "deadline"
        self._m_deadline.inc()
        slo_exemplar("deepspeed_tpu_serving_slo_deadline_exceeded_total",
                     seq.trace_id, uid=seq.uid, generated=seq.generated)
        record_event("deadline_expired", cat="serve", uid=seq.uid,
                     generated=seq.generated, priority=seq.priority,
                     **({} if seq.trace_id is None
                        else {"trace_id": seq.trace_id}))
        if seq.slot >= 0:
            self._retire(seq)  # single owner of the slotted teardown
        else:
            self.allocator.free(seq.pages)  # queued: normally none
            seq.pages, seq.done = [], True
            self._spec_fallback_uids.discard(seq.uid)
            self._finish_request(seq)
        out[seq.uid] = {"tokens": [], "done": True,
                        "finish_reason": "deadline"}

    def _expire_deadlines(self, out: Dict[int, Dict[str, Any]]) -> None:
        """Step-boundary deadline sweep over the queue AND the decode
        slots: a request whose ``deadline_s`` budget ran out stops
        consuming pool pages and decode slots NOW — under overload the
        pool drains toward work that can still meet its SLO."""
        now = time.perf_counter()
        for seq in [s for s in self._queue
                    if s.deadline and now >= s.deadline]:
            self._queue.remove(seq)
            self._expire(seq, out)
        for seq in list(self._slots):
            if seq is not None and seq.deadline and now >= seq.deadline:
                self._expire(seq, out)

    def _finish_reason_for(self, seq: SequenceState, token: int) -> str:
        """THE finish predicate ("" = keep running) — also stops
        mid-round emission in ``_spec_step`` via ``_should_finish``, so
        any new condition added here automatically drops accepted draft
        tokens past the boundary too.  Deadline expiry is NOT here: it
        happens at the step boundary (``_expire_deadlines``), never
        mid-emission."""
        if seq.generated >= seq.max_new_tokens:
            return "length"
        if seq.eos_id is not None and token == seq.eos_id:
            return "eos"
        if seq.length >= self.max_seq_len:
            return "max_seq_len"
        return ""

    def _should_finish(self, seq: SequenceState, token: int) -> bool:
        return bool(self._finish_reason_for(seq, token))

    def _maybe_finish(self, seq: SequenceState, token: int) -> None:
        reason = self._finish_reason_for(seq, token)
        if reason:
            seq.finish_reason = reason
            self._retire(seq)

    def _run_prefill_chunk(self, seq: SequenceState, start: int, c_n: int,
                           C: int):
        """One start-offset prefill call covering tokens
        [start, start+c_n) in a C-token program (C a page multiple) —
        shared by chunked prefill and the cached-prefix suffix path.
        Returns the logits of token start+c_n-1."""
        ps = self.block.page_size
        ids = np.zeros((C,), np.int32)
        ids[:c_n] = seq.tokens[start:start + c_n]
        rows = np.full((C // ps,), self.block.trash_page, np.int32)
        npg = -(-c_n // ps)
        rows[:npg] = seq.pages[start // ps:start // ps + npg]
        # bucket the window THROUGH this chunk (power-of-two
        # page counts): early chunks of a long prompt must not
        # gather the full max window, and the kernel path needs
        # the chunk's own pages in the table (pool-slot index ==
        # global position); few shapes -> few compiles
        used = -(-(start + c_n) // ps)
        b = 1
        while b < max(used, 1):
            b *= 2
        prev = self._page_table[seq.slot][:min(
            b, self.block.max_pages_per_seq)]
        self._step_parts.add(("prefill_chunk", C, int(prev.shape[0])))
        logits, self._pools = self._prefill_chunk(
            self.params, self._pools, jnp.asarray(ids),
            jnp.asarray(rows), jnp.asarray(prev),
            jnp.int32(start), jnp.int32(c_n))
        seq.prefilled = start + c_n
        self._register_pages(seq)
        return logits

    # -- the engine step -----------------------------------------------------
    def step(self) -> Dict[int, Dict[str, Any]]:
        """Admit + prefill new sequences, decode one token for running ones.

        Returns {uid: {"tokens": [newly generated], "done": bool}};
        finished records also carry ``"finish_reason"``
        ("length"/"eos"/"max_seq_len"/"deadline").  Past-deadline
        requests (queued or running) expire FIRST, at the step boundary,
        before admission.

        A step that raises dumps the flight recorder (when one is
        installed) before propagating; a step that compiled is reported
        to the recompile sentinel with the set of program shapes it
        dispatched (prefill buckets/chunks, decode, page copies)."""
        self._step_parts = set()
        self._prefetched = False
        try:
            if self._timeline.should_capture(self._decode_steps):
                # periodic step-time attribution: only this step pays
                # the profiler start/stop + parse (capture context is
                # exception-safe; a failed step still propagates)
                with self._timeline.capture(self._decode_steps):
                    out = self._step_impl()
            else:
                out = self._step_impl()
            # idle / prefill-only steps still restore-prefetch for the
            # queue head (the decode-overlap call site won if it ran)
            self._prefetch_restores()
        except Exception as e:
            dump_on_exception("engine_v2.step", e)
            raise
        if self._step_parts and self._sentinel is not None:
            self._sentinel.observe_step(frozenset(self._step_parts),
                                        step=self._decode_steps)
        return out

    def force_timeline_capture(self) -> None:
        """Arm the step-time attribution capture for the NEXT ``step()``
        regardless of cadence (bench_serving stamps its JSON from the
        record this produces)."""
        self._timeline.force_next()

    def timeline_record(self) -> Optional[Dict[str, Any]]:
        """Last completed step-time attribution record, or None."""
        return self._timeline.last_record()

    def _step_impl(self) -> Dict[int, Dict[str, Any]]:
        out: Dict[int, Dict[str, Any]] = {}
        ps = self.block.page_size

        # step boundary: commit last step's captured evictions to the
        # host tier (one batched D2H gather) and unpin their pages
        self._drain_spills()
        self._expire_deadlines(out)
        admitted = self._admit()
        self._m_queue.set(len(self._queue))
        self._m_occupancy.set(
            sum(1 for s in self._slots if s is not None)
            / max(1, self.block.max_seqs))
        if self._chunk:
            # Dynamic-SplitFuse-style chunked prefill: ONE chunk per
            # pending-prefill sequence per step; decode for ready
            # sequences runs below in the SAME step, between chunks.
            # A cached-prefix admission starts mid-prompt: seq.prefilled
            # was set to the mapped prefix end, so the first chunk is
            # already suffix-only.
            pending = [s for s in self._slots if s is not None
                       and not self._ready_to_decode(s)]
            for seq in pending:
                start = seq.prefilled  # page-aligned: chunk % ps == 0
                c_n = min(self._chunk, seq.length - start)
                with self._phase("prefill", self._m_prefill_h, uid=seq.uid,
                                 start=start, tokens=c_n):
                    logits = self._run_prefill_chunk(seq, start, c_n,
                                                     self._chunk)
                    if seq.prefilled >= seq.length:
                        self._emit_sampled(seq, logits, out)
        else:
            for seq in admitted:
                if seq.decode_entry:
                    continue  # fully cached: enters via the decode program
                if seq.prefilled:
                    # cached prefix: suffix-only prefill through the
                    # start-offset program, bucketed like whole prompts
                    # so the shape set stays fixed
                    n_suf = seq.length - seq.prefilled
                    with self._phase("prefill", self._m_prefill_h,
                                     uid=seq.uid, start=seq.prefilled,
                                     tokens=n_suf):
                        logits = self._run_prefill_chunk(
                            seq, seq.prefilled, n_suf, self._bucket(n_suf))
                        self._emit_sampled(seq, logits, out)
                    continue
                # seq.length, not prompt_len: a preempted sequence
                # re-prefills its whole prefix (prompt + tokens generated
                # before eviction)
                n = seq.length
                bucket = self._bucket(n)
                ids = np.zeros((bucket,), np.int32)
                ids[:n] = seq.tokens
                rows = np.full((bucket // ps,), self.block.trash_page,
                               np.int32)
                rows[:len(seq.pages)] = seq.pages
                self._step_parts.add(("prefill", bucket))
                with self._phase("prefill", self._m_prefill_h, uid=seq.uid,
                                 tokens=n, bucket=bucket):
                    logits, self._pools = self._prefill(
                        self.params, self._pools,
                        jnp.asarray(ids), jnp.asarray(rows), jnp.int32(n))
                    seq.prefilled = n
                    self._register_pages(seq)
                    self._emit_sampled(seq, logits, out)

        active = [s for s in self._slots
                  if s is not None and self._ready_to_decode(s)]
        if not active:
            return out

        # grow page tables where the pending token crosses a page boundary;
        # under pool pressure, preempt running sequences (youngest first) to
        # recompute later — never crash mid-step (reference: the v2 scheduler
        # holds requests back under KV pressure rather than failing)
        for seq in list(active):
            if seq.slot < 0:
                continue  # already preempted this step
            pos = seq.length - 1  # position the pending token will occupy
            if pos // ps == len(seq.pages):
                while self.allocator.free_pages < 1:
                    victims = [s for s in self._slots
                               if s is not None and s is not seq]
                    # evict the lowest priority class first, then the
                    # most recently admitted (cheapest prefix to
                    # recompute) — interactive work decodes through
                    # pool pressure at batch work's expense.  Never
                    # upward: when every other slotted sequence is MORE
                    # urgent than the requester, the requester preempts
                    # ITSELF (mirrors the admission-side victim rule)
                    victim = (max(victims,
                                  key=lambda s: (s.priority, s.admit_order))
                              if victims else seq)
                    if victim is not seq and victim.priority < seq.priority:
                        victim = seq
                    self._preempt(victim)
                    if victim is seq:
                        break
                if seq.slot < 0:
                    continue
                page = self.allocator.alloc(1)[0]
                seq.pages.append(page)
                self._page_table[seq.slot, len(seq.pages) - 1] = page
        active = [s for s in self._slots
                  if s is not None and self._ready_to_decode(s)]
        if not active:
            return out

        # speculative split: greedy sequences go through the batched
        # verify program (multi-token), non-greedy ones LOUDLY fall back
        # to the plain decode program — the sampling guard: the verify
        # accept rule is exact only for argmax, and silently speculating
        # a sampled stream would change its distribution
        if self._proposer is not None:
            spec_seqs = [s for s in active if s.temperature <= 0.0]
            decode_seqs = [s for s in active if s.temperature > 0.0]
            for seq in decode_seqs:
                if seq.uid not in self._spec_fallback_uids:
                    self._spec_fallback_uids.add(seq.uid)
                    self._dstats["spec_fallback_requests"] += 1
                    self._m_spec_fallback.inc()
                    if not self._spec_fallback_warned:
                        self._spec_fallback_warned = True
                        logger.warning(
                            "speculative decoding: non-greedy sampling "
                            "params fall back to the plain decode program "
                            "(distribution-preserving; acceptance gains "
                            "apply to greedy requests only)")
            if spec_seqs:
                decode_seqs += self._spec_step(spec_seqs, out)
        else:
            decode_seqs = active

        if decode_seqs and self._horizon > 1:
            # fused multi-step decode: K tokens per host round-trip
            # through ONE on-device scan (docs/SERVING.md "Multi-step
            # decode"); speculative engines never reach here (the
            # horizon stood down at construction)
            self._multi_decode(decode_seqs, out)
        elif decode_seqs:
            last, pos, act, temps, sids = self._decode_inputs(decode_seqs)
            self._decode_steps += 1
            self._step_parts.add("decode")
            with self._phase("decode", self._m_decode_h,
                             batch=len(decode_seqs)):
                tokens, self._pools = self._decode(
                    self.params, self._pools,
                    jnp.asarray(last), jnp.asarray(pos),
                    jnp.asarray(self._page_table), jnp.asarray(act),
                    jnp.asarray(temps), jnp.asarray(sids),
                    self._sample_key)
                # restore-prefetch rides the in-flight decode: the host
                # walks queued prefixes into the host tier while the
                # device decodes, and the H2D scatter chains behind the
                # decode program; the token fetch below waits only on
                # decode's own output
                self._prefetch_restores()
                # dstpu-lint: allow[host-sync] THE designed sync of the
                # K=1 decode path: [B] int32 tokens cross, never
                # [B,vocab] logits; decode_horizon > 1 amortizes this
                # to one [B,K] pull per horizon (_multi_decode)
                tokens = np.asarray(tokens)
            self._m_gen_tokens.inc(len(decode_seqs))
            self._m_invocations.inc()
            self._m_host_syncs.inc()
            self._m_tokens_per_dispatch.observe(len(decode_seqs))
            self._dstats["decode_model_invocations"] += 1
            self._dstats["decode_host_syncs"] += 1
            self._dstats["decode_tokens"] += len(decode_seqs)

            for seq in decode_seqs:
                tok = int(tokens[seq.slot])
                seq.tokens.append(tok)
                self._note_tokens(seq)
                # the decode step wrote KV for the token it consumed
                seq.prefilled = seq.length - 1
                if self.prefix_cache is not None and seq.prefilled % ps == 0:
                    # the decode write completed a page: publish it so a
                    # preempted-then-readmitted (or forked) sequence can
                    # remap instead of recomputing
                    self._register_pages(seq)
                rec = out.setdefault(seq.uid, {"tokens": [], "done": False})
                rec["tokens"].append(tok)
                self._maybe_finish(seq, tok)
                rec["done"] = seq.done
                if seq.done:
                    rec["finish_reason"] = seq.finish_reason
        self._sync_cache_counters()
        return out

    def _decode_inputs(self, seqs: List[SequenceState]):
        """Dense ``[max_seqs]`` dispatch arrays for a decode-phase
        batch — ONE assembly shared by the K=1 and fused paths (the two
        are asserted stream-identical; independently-built inputs could
        silently diverge)."""
        B = self.block.max_seqs
        last = np.zeros((B,), np.int32)
        pos = np.zeros((B,), np.int32)
        act = np.zeros((B,), bool)
        temps = np.zeros((B,), np.float32)
        sids = np.zeros((B,), np.int32)
        for seq in seqs:
            last[seq.slot] = seq.tokens[-1]
            pos[seq.slot] = seq.length - 1
            act[seq.slot] = True
            temps[seq.slot] = max(seq.temperature, 0.0)
            sids[seq.slot] = seq.uid % (1 << 31)  # stable sampling id
        return last, pos, act, temps, sids

    # -- fused multi-step decode ---------------------------------------------
    def _multi_decode(self, seqs: List[SequenceState],
                      out: Dict[int, Dict[str, Any]]) -> None:
        """One fused multi-step decode dispatch (docs/SERVING.md
        "Multi-step decode"): clamp each row's effective horizon
        (remaining max_new / model window / deadline), shrink the
        dispatch horizon along the halving chain under KV-pool
        pressure — never preempting mid-scan — pre-reserve every row's
        page headroom, run the K-step on-device scan, then advance ALL
        published state (tokens, prefilled, page registration,
        retirement) from the ONE ``[B, K]`` host pull.  Prefix-cache
        registration, deadline expiry, admission, spill drains, and
        restore-prefetch all stay at host boundaries, exactly as for
        the K=1 loop."""
        ps = self.block.page_size
        B = self.block.max_seqs
        now = time.perf_counter()
        budgets: Dict[int, int] = {}
        for seq in seqs:
            b = min(self._horizon,
                    seq.max_new_tokens - seq.generated,
                    self.max_seq_len - seq.length)
            if seq.deadline > 0.0:
                # deadline lands mid-horizon: clamp the row's effective
                # K so a fused dispatch cannot overshoot the deadline
                # by K x TPOT; the boundary sweep then expires it on
                # time with the tokens it legitimately produced
                b = _deadline_clamp(b, seq.deadline - now, self._tpot_ema)
            budgets[seq.uid] = max(1, b)

        # dispatch horizon: the smallest halving-chain value covering
        # the largest row budget (short tails don't scan dead
        # iterations), shrunk further while the TRULY-free pool cannot
        # cover the headroom — headroom backs tokens a row may never
        # produce (mid-horizon EOS), so like speculative draft
        # reservation it never evicts prefix-cache LRU content; the
        # horizon shrinks instead.  k=1 always fits: the page-boundary
        # loop in _step_impl already guaranteed every pending token's
        # page (claiming LRU pages there exactly like the K=1 loop).
        k = _shrink_horizon(self._horizon, max(budgets.values()))

        def _extra_pages(k_: int) -> int:
            return sum(
                max(0, _horizon_pages_needed(
                    s.length, min(k_, budgets[s.uid]), ps) - len(s.pages))
                for s in seqs)

        while k > 1 and _extra_pages(k) > self.allocator.uncached_free_pages:
            k = (k + 1) // 2
        if k < self._horizon:
            self._m_horizon_shrink.inc()
            self._dstats["decode_horizon_shrinks"] += 1
            record_event("horizon_shrink", cat="serve", horizon=k,
                         configured=self._horizon,
                         **self._pool_occupancy())

        # pre-reserve each row's horizon headroom; a refused
        # reservation (spill pins landed between the check and here)
        # clamps THAT row to the headroom it already holds — the
        # dispatch never fails and nothing is preempted mid-scan
        for seq in seqs:
            b = min(k, budgets[seq.uid])
            extra = _horizon_pages_needed(seq.length, b, ps) \
                - len(seq.pages)
            if extra > 0:
                fresh = self.allocator.try_alloc(extra, uncached_only=True)
                if fresh is None:
                    b = max(1, len(seq.pages) * ps - seq.length + 1)
                else:
                    base = len(seq.pages)
                    seq.pages.extend(fresh)
                    self._page_table[seq.slot, base:base + extra] = fresh
            budgets[seq.uid] = b

        last, pos, act, temps, sids = self._decode_inputs(seqs)
        eos = np.full((B,), -1, np.int32)
        budg = np.zeros((B,), np.int32)
        for seq in seqs:
            if seq.eos_id is not None:
                eos[seq.slot] = seq.eos_id
            budg[seq.slot] = budgets[seq.uid]

        self._decode_steps += 1
        self._step_parts.add(("multi_decode", k))
        warm = k in self._warm_horizons
        self._warm_horizons.add(k)
        t0 = time.perf_counter()
        with self._phase("multi_decode", self._m_decode_h,
                         batch=len(seqs), horizon=k):
            toks, produced, self._pools = self._multi(
                self.params, self._pools,
                jnp.asarray(last), jnp.asarray(pos),
                jnp.asarray(self._page_table), jnp.asarray(act),
                jnp.asarray(temps), jnp.asarray(eos), jnp.asarray(budg),
                jnp.asarray(sids), self._sample_key, k)
            # restore-prefetch rides the in-flight scan, like K=1
            self._prefetch_restores()
            # dstpu-lint: allow[host-sync] THE designed sync per decode horizon
            # [B,K] int32 tokens + [B] produced counts cross the link
            # once per K tokens — the fused form of the per-step decode
            # sync, amortized K-fold
            toks, produced = np.asarray(toks), np.asarray(produced)
        t1 = time.perf_counter()

        # the scan ALWAYS executes k iterations (finished rows run
        # masked, they don't shorten the program): per-device-step wall
        # is wall / k, not wall / produced — dividing by produced would
        # inflate the estimate on every stream tail
        per_step = (t1 - t0) / k
        # EMA of per-token decode wall, the deadline clamp's estimate —
        # updated only from WARM dispatches: a dispatch that compiled
        # its horizon shape measures XLA compile time, not decode time
        if warm:
            self._tpot_ema = (per_step if self._tpot_ema is None
                              else 0.5 * self._tpot_ema + 0.5 * per_step)
        total = int(produced.sum())
        self._m_gen_tokens.inc(total)
        self._m_invocations.inc()
        self._m_host_syncs.inc()
        self._m_tokens_per_dispatch.observe(total)
        self._dstats["decode_model_invocations"] += 1
        self._dstats["decode_host_syncs"] += 1
        self._dstats["decode_tokens"] += total

        for seq in seqs:
            n = int(produced[seq.slot])
            rec = out.setdefault(seq.uid, {"tokens": [], "done": False})
            reason = ""
            for j in range(n):
                tok = int(toks[seq.slot, j])
                seq.tokens.append(tok)
                rec["tokens"].append(tok)
                # token j landed ~(j+1) device steps into the dispatch:
                # reconstructed per-token emit timestamps, so
                # TTFT/TPOT and the SLO-violation checks never see a
                # K-token burst stamped at one instant
                self._note_tokens(seq, t=t0 + (j + 1) * per_step)
                reason = self._finish_reason_for(seq, tok)
                if reason:
                    break  # the scan stopped the row here by contract
            # the scan wrote KV for every token it consumed; the last
            # emitted token is the pending one, exactly like K=1
            seq.prefilled = seq.length - 1
            self._register_pages(seq)
            if reason:
                seq.finish_reason = reason
                self._retire(seq)  # frees unused horizon headroom too
            rec["done"] = seq.done
            if seq.done:
                rec["finish_reason"] = seq.finish_reason

    # -- speculative decoding ------------------------------------------------
    def _spec_step(self, seqs: List[SequenceState],
                   out: Dict[int, Dict[str, Any]]
                   ) -> List[SequenceState]:
        """One speculative decode round for greedy-ready sequences:
        propose -> reserve -> ONE batched verify -> accept longest
        prefix + bonus token -> roll back rejected pages.  Returns the
        sequences it did NOT run — the whole batch when every proposal
        came up empty — for the caller's plain decode program.

        Every sequence emits at least one token per round (the model's
        own greedy choice rides in the verify output even on a total
        miss or an empty draft), so speculation never does worse than
        plain decode in tokens per model invocation.  Mixed accept
        lengths coexist in one batch: acceptance is per-row host logic
        over the per-position argmax the program returns."""
        ps = self.block.page_size
        k = self.spec.k
        W = k + 1
        B = self.block.max_seqs

        # -- propose + reserve (host) --
        drafts: Dict[int, List[int]] = {}
        with span("spec_propose", cat="serve", seqs=len(seqs)):
            for seq in seqs:
                d = list(self._proposer.propose(seq.tokens, k))[:k]
                # cap to the model window, the page-table width, and the
                # request's remaining budget (emitting past max_new /
                # max_seq_len would be discarded — don't verify it)
                cap = min(self.max_seq_len - seq.length,
                          len(self._page_table[seq.slot]) * ps
                          - seq.length,
                          seq.max_new_tokens - seq.generated - 1)
                if len(d) > cap:
                    d = d[:max(cap, 0)]
                if d:
                    # reserve pages for the draft window, spending ONLY
                    # truly-free pages: draft tokens may be rejected, so
                    # neither prefix-cache LRU content nor other
                    # sequences (no preemption) are sacrificed for them
                    need = (seq.length - 1 + len(d)) // ps + 1
                    extra = need - len(seq.pages)
                    while (extra > 0
                           and extra > self.allocator.uncached_free_pages):
                        d.pop()
                        need = (seq.length - 1 + len(d)) // ps + 1
                        extra = need - len(seq.pages)
                    if extra > 0:
                        fresh = self.allocator.alloc(extra)
                        base = len(seq.pages)
                        seq.pages.extend(fresh)
                        self._page_table[seq.slot,
                                         base:base + extra] = fresh
                drafts[seq.uid] = d
                self._dstats["spec_proposed_tokens"] += len(d)
                self._m_spec_proposed.inc(len(d))

        if not any(drafts.values()):
            # nothing to verify (proposer drew blanks everywhere): the
            # plain decode program emits the same one greedy token per
            # row at 1/W the program width — hand the batch back so
            # low-acceptance traffic never pays for verify it can't use
            return list(seqs)

        # -- one batched verify call --
        ids = np.zeros((B, W), np.int32)
        pos = np.zeros((B,), np.int32)
        act = np.zeros((B,), bool)
        nv = np.ones((B,), np.int32)
        for seq in seqs:
            row = [seq.tokens[-1]] + drafts[seq.uid]
            ids[seq.slot, :len(row)] = row
            pos[seq.slot] = seq.length - 1
            act[seq.slot] = True
            nv[seq.slot] = len(row)
        self._step_parts.add(("verify", W))
        with self._phase("spec_verify", self._m_spec_verify_h,
                         batch=len(seqs), width=W):
            greedy, self._pools = self._verify(
                self.params, self._pools, jnp.asarray(ids),
                jnp.asarray(pos), jnp.asarray(self._page_table),
                jnp.asarray(act), jnp.asarray(nv))
            # dstpu-lint: allow[host-sync] one [B,W] int32 pull per verify
            # round; acceptance is per-row host logic by design
            greedy = np.asarray(greedy)  # [B, W] argmax per position
        self._m_invocations.inc()
        self._m_host_syncs.inc()
        self._dstats["decode_model_invocations"] += 1
        self._dstats["decode_host_syncs"] += 1
        self._dstats["spec_verify_calls"] += 1

        # -- accept + emit + rollback (host) --
        rollback_pages = 0
        for seq in seqs:
            accepted, bonus = longest_accepted(drafts[seq.uid],
                                               greedy[seq.slot])
            base_len = seq.length  # L: tokens before this round
            self._dstats["spec_accepted_tokens"] += len(accepted)
            self._m_spec_accepted.inc(len(accepted))
            rec = out.setdefault(seq.uid, {"tokens": [], "done": False})
            emitted = 0
            for tok in accepted + [bonus]:
                seq.tokens.append(tok)
                emitted += 1
                rec["tokens"].append(tok)
                self._note_tokens(seq)
                if self._should_finish(seq, tok):
                    break  # drop accepted tokens past a finish boundary
            self._m_gen_tokens.inc(emitted)
            self._dstats["decode_tokens"] += emitted
            self._m_spec_tps.observe(emitted)
            # KV is valid through the accepted region (the bonus token is
            # the pending one, exactly like a plain decode step)
            seq.prefilled = min(seq.length - 1,
                                base_len + len(accepted))
            self._register_pages(seq)
            self._maybe_finish(seq, seq.tokens[-1])
            rec["done"] = seq.done
            if seq.done:
                rec["finish_reason"] = seq.finish_reason
            if not seq.done:
                # rollback: pages reserved for rejected draft tokens are
                # released; rejected KV inside kept pages is overwritten
                # by the next window before any query can attend it
                needed = (seq.prefilled - 1) // ps + 1
                if needed < len(seq.pages):
                    drop = seq.pages[needed:]
                    self.allocator.free(drop)
                    del seq.pages[needed:]
                    self._page_table[seq.slot, needed:] = \
                        self.block.trash_page
                    rollback_pages += len(drop)
        if rollback_pages:
            self._dstats["spec_rollback_pages"] += rollback_pages
            self._m_spec_rollback.inc(rollback_pages)
            record_event("spec_rollback", cat="serve",
                         pages=rollback_pages, seqs=len(seqs))
        prop = self._dstats["spec_proposed_tokens"]
        if prop:
            self._m_spec_rate.set(
                self._dstats["spec_accepted_tokens"] / prop)
        return []

    def close(self) -> None:
        """Release this engine's memory-ledger slots (provider identity
        guards: slots a newer co-located engine claimed stay attached).
        Idempotent; safe without the ledger enabled.

        In-flight/queued requests are NOT finished by close(): they are
        aborted LOUDLY (warning + closed request spans) — call
        ``drain()`` first for clean retirement that runs admitted
        sequences to completion and hands queued ones back."""
        # pending spill captures die with the engine (their host tier
        # does too): detach the hook FIRST — abort_all below frees
        # sequence pages, and cap trims there must not capture fresh
        # pins after this release — then drop the pins so a post-close
        # allocator audit sees a clean pool
        if self.kv_tier is not None:
            self.allocator.spill_hook = None
        for page, _key in self._pending_spills:
            self.allocator.release_spill_pin(page)
        self._pending_spills = []
        self._pending_spill_keys = set()
        dropped = self.abort_all(reason="close")
        if dropped:
            logger.warning(
                f"engine_v2.close: aborted {len(dropped)} unfinished "
                f"request(s) (uids {dropped[:8]}{'…' if len(dropped) > 8 else ''}) "
                "— call drain() before close() to retire cleanly")
        comps = getattr(self, "_ledger_components", [])
        if comps:
            from ...telemetry.memory import get_memory_ledger

            led = get_memory_ledger()
            for name, prov in comps:
                led.detach(name, provider=prov)
        self._ledger_components = []

    # -- serving metrics -----------------------------------------------------
    def cache_stats(self) -> Dict[str, float]:
        """Prefix-cache and prefill-work counters (cumulative).  Valid —
        all zeros for the cache-specific entries — with caching off, so
        dashboards need no conditional wiring."""
        self._sync_cache_counters()
        s: Dict[str, float] = dict(self._stats)
        s["cache_hits"] = self.prefix_cache.hits if self.prefix_cache else 0
        s["cache_misses"] = (self.prefix_cache.misses
                             if self.prefix_cache else 0)
        s["cache_evictions"] = self.allocator.evictions
        s["cached_pages"] = self.allocator.cached_pages
        adm = s["prefill_admitted_tokens"]
        s["prefix_hit_rate"] = (s["prefix_hit_tokens"] / adm) if adm else 0.0
        return s

    def decode_stats(self) -> Dict[str, float]:
        """Decode-phase counters (cumulative; all-zero spec entries with
        speculation off): model invocations, tokens produced, and the
        speculative propose/accept/rollback tallies.  The derived
        ``decode_tokens_per_invocation`` is the speculative-decoding
        figure of merit ``tools/bench_serving.py --ab-speculative``
        machine-checks."""
        s: Dict[str, float] = dict(self._dstats)
        inv = s["decode_model_invocations"]
        s["decode_tokens_per_invocation"] = (
            s["decode_tokens"] / inv) if inv else 0.0
        syncs = s["decode_host_syncs"]
        # the multi-step figure of merit (bench_serving --ab-multistep):
        # decode tokens banked per host round-trip
        s["decode_tokens_per_host_sync"] = (
            s["decode_tokens"] / syncs) if syncs else 0.0
        prop = s["spec_proposed_tokens"]
        s["spec_acceptance_rate"] = (
            s["spec_accepted_tokens"] / prop) if prop else 0.0
        return s

    def assert_no_leaks(self) -> None:
        """Exact allocator audit against this engine's live sequences
        (ragged.BlockAllocator.assert_no_leaks): every KV page's
        refcount must equal its live references, every refcount-0 page
        must be free or LRU-parked.  Tests and ``fleet_drill`` call this
        after speculative rollback / migration / preemption churn."""
        self.allocator.assert_no_leaks(
            [s.pages for s in self._slots if s is not None])

    def reset_cache_stats(self) -> None:
        """Zero the counters (cache CONTENTS are kept) — benches call this
        after warmup so compile-wave admissions don't pollute the rates.
        The registry counters stay cumulative (Prometheus counters never
        go backwards); only the delta baseline resets with the sources."""
        self._stats = {k: 0 for k in self._stats}
        self._dstats = {k: 0 for k in self._dstats}
        self.allocator.evictions = 0
        if self.prefix_cache is not None:
            self.prefix_cache.hits = self.prefix_cache.misses = 0
        if self.kv_tier is not None:
            # tier CONTENTS are kept (like the device cache); only the
            # counters re-baseline so a bench wave measures its own
            # spill/restore traffic
            t = self.kv_tier
            t.spilled_pages = t.restored_pages = 0
            t.hits = t.misses = 0
            t.host_evictions = t.corrupt_pages = t.dropped_spills = 0
        self._cache_pub = {"hits": 0, "misses": 0, "evictions": 0}

    def publish_metrics(self, monitor, step: int) -> None:
        """Surface the serving counters through a monitor/* writer
        (MonitorMaster or any object with ``write_events``)."""
        monitor.write_events([(f"serving/{k}", float(v), int(step))
                              for k, v in self.cache_stats().items()])

    def generate_all(self, requests: List[RaggedRequest],
                     max_steps: int = 10_000) -> Dict[int, List[int]]:
        """Convenience: run requests to completion, returning full
        generations keyed by uid."""
        uids = [self.put(r) for r in requests]
        got: Dict[int, List[int]] = {u: [] for u in uids}
        for _ in range(max_steps):
            if not self.has_work():
                break
            for uid, rec in self.step().items():
                got[uid].extend(rec["tokens"])
        else:
            logger.warning("generate_all: max_steps reached with work pending")
        return got
