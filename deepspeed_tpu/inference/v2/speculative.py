"""Speculative decoding: proposers + the greedy accept rule.

Reference parity: the multi-token-per-step decode path of the reference
inference stack (speculative acceptance over a draft, as in FastGen's
roadmap and the DeepSpeed-MII speculative decoding mode).  The engine
(engine_v2.py) drives the loop: a *proposer* guesses up to ``k``
continuation tokens from host state, one batched **verify** program
(model_runner.paged_verify) scores all of them in a single model
invocation, and the longest prefix that matches the model's own greedy
choices is accepted — plus the model's correction token at the first
mismatch, so every verify call emits at least one token and the engine
never does worse than plain decode per invocation.

The contract is **lossless**: greedy speculative decoding is
bit-identical to the non-speculative baseline (the accepted tokens are
exactly the tokens greedy decode would have produced, because each is
checked against the model's own argmax given the same KV state).
Non-greedy sampling is NOT speculated — the engine falls back to the
plain decode program for those sequences (see the sampling guard in
engine_v2) rather than silently changing the output distribution.

Proposers are pluggable: anything with ``propose(tokens, k) -> list``
works.  Two built-ins:

* :class:`NgramProposer` — self-speculative prompt-lookup (no extra
  weights): the trailing n-gram of the sequence is searched in its own
  history (prompt + generated), and the tokens that followed an
  earlier occurrence are proposed.  Strong on summarization /
  extraction / code-edit traffic where outputs copy their inputs, free
  everywhere else.
* :class:`DraftModelProposer` — a small draft model proposes greedily.
  The draft runs a bucket-padded dense forward per proposed token (no
  separate KV pool to keep coherent with the target's paged state), so
  it is a *reference* implementation sized for tiny drafts; the
  interface is what matters.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from ...runtime.config_utils import ConfigModel

SPEC_MODES = ("off", "ngram", "draft")


@dataclasses.dataclass
class SpeculativeConfig(ConfigModel):
    """``speculative`` config block (RaggedInferenceConfig.speculative,
    also accepted fleet-wide under ``serving.speculative``).

    ``k`` is the max draft tokens per verify call: the verify program is
    compiled for a fixed width of ``k + 1`` tokens (last accepted token
    + drafts), so one shape serves every acceptance outcome."""

    mode: str = "off"
    #: max draft tokens proposed per step (verify width = k + 1)
    k: int = 4
    #: n-gram proposer: shortest/longest trailing n-gram searched in the
    #: sequence's own history (longest match wins)
    ngram_min: int = 1
    ngram_max: int = 3
    #: draft-model proposer: models/llama size ref (e.g. "tiny").  Real
    #: deployments pass a DraftModelProposer with loaded weights to the
    #: engine instead; a size ref alone gets seed-initialized weights —
    #: functional (the accept rule keeps it lossless) but low-acceptance.
    draft_model: str = ""

    @property
    def enabled(self) -> bool:
        return self.mode != "off"

    def validate(self) -> None:
        if self.mode not in SPEC_MODES:
            raise ValueError(f"speculative.mode {self.mode!r} not in "
                             f"{SPEC_MODES}")
        if self.k < 1:
            raise ValueError("speculative.k must be >= 1")
        if not (1 <= self.ngram_min <= self.ngram_max):
            raise ValueError("need 1 <= speculative.ngram_min <= ngram_max")
        if self.mode == "draft" and not self.draft_model:
            raise ValueError("speculative.mode='draft' needs "
                             "speculative.draft_model")


class NgramProposer:
    """Self-speculative prompt-lookup: propose the continuation of an
    earlier occurrence of the sequence's trailing n-gram.

    Host-only and O(n * ngram) per call over a Python token list —
    it runs between device steps, off the hot path, like the rest of
    the v2 scheduler.  Longest n-gram wins (tried ``ngram_max`` down to
    ``ngram_min``); among same-length matches, the most recent
    occurrence whose continuation can FILL ``k`` wins (in a loop the
    nearest occurrence sits one period from the tail with its
    continuation clipped by end-of-history; one period further back the
    same cycle supplies all ``k``), falling back to the longest clipped
    continuation, most recent first."""

    def __init__(self, ngram_min: int = 1, ngram_max: int = 3):
        if not (1 <= ngram_min <= ngram_max):
            raise ValueError("need 1 <= ngram_min <= ngram_max")
        self.ngram_min = ngram_min
        self.ngram_max = ngram_max

    def propose(self, tokens: Sequence[int], k: int) -> List[int]:
        n_tok = len(tokens)
        if k < 1 or n_tok < self.ngram_min + 1:
            return []
        # the whole-history scan is vectorized (one windowed compare per
        # n-gram length) so a long context costs microseconds, not a
        # per-position Python loop between device steps
        arr = np.asarray(tokens, dtype=np.int64)
        for n in range(min(self.ngram_max, n_tok - 1), self.ngram_min - 1, -1):
            tail = arr[n_tok - n:]
            # candidate start positions 0..n_tok-n-1 (the tail itself
            # excluded); a match at i proposes tokens[i+n : i+n+k].
            # The most recent match whose continuation can FILL k wins —
            # in a generation loop the nearest occurrence sits one
            # period from the tail with its continuation clipped by the
            # end of history, while one more period back the same cycle
            # supplies all k tokens; fall back to the longest clipped
            # continuation (most recent first) otherwise
            wins = np.lib.stride_tricks.sliding_window_view(arr[:-1], n)
            hits = np.nonzero((wins == tail).all(axis=1))[0]
            best: List[int] = []
            for i in hits[::-1]:
                cont = arr[i + n:i + n + k]
                if len(cont) == k:
                    return [int(t) for t in cont]
                if len(cont) > len(best):
                    best = [int(t) for t in cont]
            if best:
                return best
        return []


class DraftModelProposer:
    """Greedy proposals from a small draft model (models/* spec).

    Each proposed token is one bucket-padded dense forward of the draft
    over the full history — padding to power-of-two buckets keeps the
    compile set bounded.  No draft KV cache: the draft's state never has
    to be kept coherent with the target's paged pool across accept/
    rollback, at the cost of recompute that only a *tiny* draft can
    afford (which is the only draft worth running on-host anyway)."""

    def __init__(self, model: Any, params: Any = None, seed: int = 0,
                 min_bucket: int = 32):
        import jax
        import jax.numpy as jnp

        self.cfg = model.config
        self.params = (params if params is not None
                       else model.init_params(jax.random.PRNGKey(seed)))
        self.min_bucket = min_bucket

        from ...models.transformer import logits_fn, transformer_forward

        cfg = self.cfg

        def _greedy_next(params, ids, length):
            h, _aux = transformer_forward(cfg, params, ids[None])
            logits = logits_fn(cfg, params, h[:, length - 1][:, None])
            return jnp.argmax(logits.astype(jnp.float32), axis=-1)[0, 0]

        self._next = jax.jit(_greedy_next)

    def _bucket(self, n: int) -> int:
        b = self.min_bucket
        while b < n:
            b *= 2
        return min(b, self.cfg.max_seq_len)

    def propose(self, tokens: Sequence[int], k: int) -> List[int]:
        import numpy as np

        hist = [int(t) for t in tokens]
        out: List[int] = []
        for _ in range(k):
            if len(hist) >= self.cfg.max_seq_len:
                break
            ids = np.zeros((self._bucket(len(hist)),), np.int32)
            ids[:len(hist)] = hist
            tok = int(self._next(self.params, ids, len(hist)))
            out.append(tok)
            hist.append(tok)
        return out


def build_proposer(spec: SpeculativeConfig) -> Optional[Any]:
    """Proposer for a config block (None when mode is off).  The engine
    calls this once at construction; callers wanting real draft weights
    pass ``proposer=DraftModelProposer(model, params)`` instead."""
    if not spec.enabled:
        return None
    if spec.mode == "ngram":
        return NgramProposer(spec.ngram_min, spec.ngram_max)
    from ...models.llama import llama_model

    return DraftModelProposer(llama_model(spec.draft_model))


def longest_accepted(draft: Sequence[int], verified: Sequence[int]
                     ) -> Tuple[List[int], int]:
    """Greedy accept rule: ``verified[w]`` is the model's argmax after
    consuming the last accepted token followed by ``draft[:w]``.  The
    longest prefix of ``draft`` matching ``verified`` position-by-
    position is accepted, and ``verified[m]`` — the model's own choice
    at the first mismatch (or past a fully-accepted draft) — is the
    bonus token.  Returns ``(accepted_tokens, bonus_token)``; the step
    emits ``accepted + [bonus]``, which is exactly the token stream
    plain greedy decode would have produced."""
    m = 0
    while m < len(draft) and int(draft[m]) == int(verified[m]):
        m += 1
    return [int(t) for t in draft[:m]], int(verified[m])


__all__ = ["SpeculativeConfig", "NgramProposer", "DraftModelProposer",
           "build_proposer", "longest_accepted", "SPEC_MODES"]
