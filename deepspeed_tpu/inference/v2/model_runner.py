"""Jitted programs over the paged KV cache.

Reference parity: the ragged kernel set — blocked rotary + KV copy
(inference/v2/kernels/ragged_ops/blocked_kv_rotary), ragged attention via
blocked KV, logits gather (ragged_ops/logits_gather).  On TPU these are
two XLA programs:

* ``paged_prefill`` — one (bucket-padded) prompt: dense causal attention,
  K/V scattered into the sequence's pages.
* ``paged_decode`` — one token for *all* decode slots at once, regardless
  of per-sequence lengths: gather pages by table, mask by length.  This is
  the continuous-batching hot loop; lengths/page tables are data, not
  shapes, so one compiled program serves every batch composition.

Scatters are unconditional: inactive slots and pad chunks write to the
trash page (ragged.py) instead of branching.
"""

from __future__ import annotations

import math
from typing import Any, Tuple

import jax
import jax.numpy as jnp

from ...models.transformer import (MODEL_AXIS, TransformerConfig, _mm,
                                   _norm, _repeat_kv, alibi_slopes,
                                   attn_qkv, logits_fn, mlp_block)


def _use_paged_kernel() -> bool:
    """Pallas kernels on TPU by default; DSTPU_PAGED_KERNEL=0/1 forces
    either path (read at trace time — tests force the kernel in interpret
    mode on CPU)."""
    import os

    default = "1" if jax.default_backend() == "tpu" else "0"
    return os.environ.get("DSTPU_PAGED_KERNEL", default) == "1"


def _kv_quantize(x):
    """[..., KVH, D] -> (int8 codes, fp32 scale [..., KVH]) per head."""
    s = jnp.maximum(jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1) / 127.0,
                    1e-8)
    q = jnp.round(x.astype(jnp.float32) / s[..., None]).astype(jnp.int8)
    return q, s.astype(jnp.float32)


def _pools_per_layer(pools):
    """Split the pools dict into per-layer scan operands (None-safe)."""
    return (pools["k"], pools["v"],
            pools.get("k_scale"), pools.get("v_scale"))


def _pools_from_scan(new_pools):
    """Inverse of _pools_per_layer over the scan outputs."""
    out = {"k": new_pools[0], "v": new_pools[1]}
    if new_pools[2] is not None:
        out["k_scale"], out["v_scale"] = new_pools[2], new_pools[3]
    return out


def _ffn(cfg: TransformerConfig, layer, x):
    """mlp_block shared with the training forward; inference drops aux loss."""
    out, _aux = mlp_block(cfg, layer, x, training=False)
    return out


def _alibi_bias(cfg: TransformerConfig, qpos, kpos):
    """ALiBi score bias: qpos [..., Q], kpos [..., K] (leading dims
    broadcastable against batch) -> [..., NH, Q, K].  One definition for
    all three paged programs so the formulations cannot diverge."""
    rel = (qpos[..., :, None] - kpos[..., None, :]).astype(jnp.float32)
    return -alibi_slopes(cfg.n_heads)[:, None, None] * rel[..., None, :, :]


def _attn_out(cfg: TransformerConfig, layer, x, attn):
    """Output projection + residual/parallel-block epilogue shared by the
    prefill/chunk/decode scan bodies."""
    attn_delta = (_mm(cfg, attn, layer["attn"]["wo"], MODEL_AXIS, None)
                  + (layer["attn"]["bo"] if cfg.use_bias else 0))
    if cfg.parallel_block:
        return _ffn(cfg, layer, x) + attn_delta
    return _ffn(cfg, layer, x + attn_delta)


def _write_pages(quant, rows, k_pages, v_pages, k_c, v_c, ks_c, vs_c):
    """Scatter whole pages of fresh K/V into the pools (quantizing when
    the pool is int8) — shared by whole-prompt and chunked prefill."""
    if quant:
        kq, ksc = _kv_quantize(k_pages)
        vq, vsc = _kv_quantize(v_pages)
        return (k_c.at[rows].set(kq), v_c.at[rows].set(vq),
                ks_c.at[rows].set(ksc), vs_c.at[rows].set(vsc))
    return (k_c.at[rows].set(k_pages.astype(k_c.dtype)),
            v_c.at[rows].set(v_pages.astype(v_c.dtype)), ks_c, vs_c)


def paged_prefill(cfg: TransformerConfig, params, pools,
                  ids, page_rows, length) -> Tuple[jnp.ndarray, Any]:
    """Prefill one prompt.

    pools: {"k", "v"[, "k_scale", "v_scale"]} page pools (int8 codes +
    per-(page,slot,head) scales when KV quantization is on).
    ids: [S_pad] bucket-padded prompt; page_rows: [S_pad // page_size]
    page index per chunk (trash for pad chunks); length: real prompt length.
    Returns (last-token logits [V], pools).
    """
    quant = "k_scale" in pools
    S = ids.shape[0]
    ps = pools["k"].shape[2]
    x = params["embed"]["tok"][ids][None]  # [1, S, H]
    if cfg.position == "learned":
        # the bucket may pad up to page_size-1 slots past the position
        # table; clamp explicitly (pad positions >= length never influence
        # real-token outputs under the causal mask)
        pos_idx = jnp.minimum(jnp.arange(S), params["embed"]["pos"].shape[0] - 1)
        x = x + params["embed"]["pos"][pos_idx][None]
    if "norm" in params["embed"]:  # bloom-style word_embeddings_layernorm
        x = _norm(x, params["embed"]["norm"]["scale"],
                  params["embed"]["norm"].get("bias"), cfg.norm, cfg.norm_eps)
    positions = jnp.arange(S)[None]

    use_flash = _use_paged_kernel()

    def body(x, inputs):
        layer, k_c, v_c, ks_c, vs_c = inputs  # k_c: [P+1, ps, KVH, D]
        q, k, v = attn_qkv(cfg, layer, x, positions)
        k_c, v_c, ks_c, vs_c = _write_pages(
            quant, page_rows, k[0].reshape(S // ps, ps, *k.shape[2:]),
            v[0].reshape(S // ps, ps, *v.shape[2:]), k_c, v_c, ks_c, vs_c)
        if use_flash:
            # GQA-native flash kernel: no [S, S] score materialization.
            # Pad tokens past ``length`` see only earlier slots (causal)
            # and their outputs are discarded; real tokens see real slots.
            from ...ops.pallas.flash_attention import flash_attention

            attn = flash_attention(
                q, k, v, causal=True,
                alibi_slopes=(alibi_slopes(cfg.n_heads)
                              if cfg.position == "alibi" else None)
            ).reshape(1, S, -1)
        else:
            kk = _repeat_kv(k, cfg.n_heads // cfg.kv_heads)
            vv = _repeat_kv(v, cfg.n_heads // cfg.kv_heads)
            scores = jnp.einsum("btnd,bsnd->bnts", q, kk).astype(jnp.float32)
            scores = scores / math.sqrt(cfg.head_dim)
            if cfg.position == "alibi":
                scores = scores + _alibi_bias(cfg, jnp.arange(S),
                                              jnp.arange(S))
            causal = jnp.arange(S)[None, None, :, None] >= jnp.arange(S)[None, None, None, :]
            scores = jnp.where(causal, scores, -1e30)
            probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
            attn = jnp.einsum("bnts,bsnd->btnd", probs, vv).reshape(1, S, -1)
        return _attn_out(cfg, layer, x, attn), (k_c, v_c, ks_c, vs_c)

    ops = (params["layers"],) + _pools_per_layer(pools)
    x, new_pools = jax.lax.scan(body, x, ops)
    out_pools = _pools_from_scan(new_pools)
    hidden = _norm(x[:, length - 1], params["final_norm"]["scale"],
                   params["final_norm"].get("bias"), cfg.norm, cfg.norm_eps)
    logits = logits_fn(cfg, params, hidden[:, None])[0, 0]
    return logits, out_pools


def paged_copy_page(pools, src, dst):
    """Copy-on-write for the prefix cache: duplicate page ``src`` into
    ``dst`` across every pool leaf (K/V codes and, under kv_quant, their
    scales — all laid out ``[L, P+1, ...]``).  A sequence whose whole
    prompt is cached must write the KV of its final prompt token through
    the decode program; that write lands in its private copy so the
    shared cached page is never mutated.  The engine jits this with the
    pools donated — one compiled program regardless of src/dst."""
    return jax.tree_util.tree_map(
        lambda a: a.at[:, dst].set(a[:, src]), pools)


def pad_pages_pow2(pages, trash_page):
    """Pad a page list to the next power-of-two length with trash rows.
    The op-by-op gather/scatter path compiles one XLA program per row
    COUNT; the host KV tier's spill drains and restores batch arbitrary
    page counts every step, so bucketing keeps that a small fixed shape
    set (gathered trash content is discarded; scattered pad rows write
    zeros into the trash page, which every step clobbers anyway)."""
    n = 1
    while n < max(1, len(pages)):
        n *= 2
    return list(pages) + [trash_page] * (n - len(pages))


def paged_gather_pages(pools, pages):
    """Host copy of the given pool pages (KV export): one numpy array
    per pool leaf, shaped ``[L, n_pages, page_size, KVH, D]`` in the
    pool's exact dtype (bf16 round-trips through ml_dtypes) — the
    device half of KV-page migration and of the host-RAM spill
    (``serving/kv_tier.py`` captures evicted prefix pages through
    exactly this layout, CRC-stamped by ``kv_transfer.page_crcs``)."""
    import numpy as np

    rows = jnp.asarray(np.asarray(pages, np.int32))
    return {name: np.asarray(leaf[:, rows]) for name, leaf in pools.items()}


def paged_scatter_pages(pools, pages, arrays):
    """Write host page arrays (``paged_gather_pages`` layout) into pool
    rows ``pages`` (KV import, and the H2D half of host-tier restore —
    one scatter path serves both).  Dtypes must match the pool exactly —
    a silent cast would break the bit-identical import contract.  Runs
    op-by-op outside jit (imports happen between steps, off the hot
    path); returns the updated pools dict."""
    import numpy as np

    if set(arrays) != set(pools):
        raise ValueError(f"pool leaves {sorted(pools)} != bundle leaves "
                         f"{sorted(arrays)} (kv_quant mismatch?)")
    rows = jnp.asarray(np.asarray(pages, np.int32))
    out = {}
    for name, leaf in pools.items():
        src = arrays[name]
        if jnp.dtype(leaf.dtype) != jnp.dtype(src.dtype):
            raise ValueError(f"pool leaf {name!r} dtype {leaf.dtype} != "
                             f"bundle dtype {src.dtype}: import must be "
                             "bit-identical, refusing to cast")
        out[name] = leaf.at[:, rows].set(jnp.asarray(src))
    return out


def paged_prefill_chunk(cfg: TransformerConfig, params, pools,
                        ids, chunk_rows, prev_table, start, n
                        ) -> Tuple[jnp.ndarray, Any]:
    """Prefill ONE CHUNK of a prompt (FastGen Dynamic-SplitFuse-style
    chunked prefill, reference inference/v2 scheduler + blogs/deepspeed-
    fastgen): long prompts are processed in fixed-size chunks so decode
    steps for other sequences interleave between chunks, bounding
    per-step latency instead of stalling every running stream for a full
    prompt.

    This is also the engine's START-OFFSET prefill for automatic prefix
    caching: a request whose leading pages were mapped from the prefix
    cache prefills only the uncached suffix by calling this with
    ``start`` at the first uncached (page-aligned) position — the cached
    pages sit in ``prev_table`` at their position-ordered rows, so the
    ``< start`` visibility mask attends them exactly like
    previously-computed chunks.  The engine buckets the suffix length to
    the same power-of-two page counts as chunked prefill, keeping the
    suffix-only path a fixed set of compiled shapes.

    ids: [C] chunk tokens (C fixed, multiple of page_size);
    chunk_rows: [C // ps] pages receiving this chunk's K/V;
    prev_table: [MPb] the sequence's page-table prefix covering the
    window THROUGH this chunk (the kernel path reads the chunk's own
    keys from the pool; the caller buckets the length to power-of-two
    page counts so early chunks don't gather the full max window);
    start: global position of ids[0]; n: valid tokens.
    Chunk queries attend to all previously-written positions (< start,
    via the page pool) plus causally within the chunk.  Returns (logits
    of token start+n-1 — meaningful on the FINAL chunk — and pools)."""
    quant = "k_scale" in pools
    C = ids.shape[0]
    ps = pools["k"].shape[2]
    S_prev = prev_table.shape[0] * ps
    x = params["embed"]["tok"][ids][None]  # [1, C, H]
    positions = start + jnp.arange(C)[None]
    if cfg.position == "learned":
        pos_idx = jnp.minimum(positions[0],
                              params["embed"]["pos"].shape[0] - 1)
        x = x + params["embed"]["pos"][pos_idx][None]
    if "norm" in params["embed"]:
        x = _norm(x, params["embed"]["norm"]["scale"],
                  params["embed"]["norm"].get("bias"), cfg.norm, cfg.norm_eps)

    # visibility of pooled (previous-chunk) slots: strictly before start
    prev_vis = jnp.arange(S_prev)[None, :] < start  # [1, S_prev]
    causal = jnp.arange(C)[:, None] >= jnp.arange(C)[None, :]  # [C(q), C(k)]

    # quant + chunked stays on the XLA path: the kernel window would put
    # the chunk's OWN keys through the int8 round-trip while the fallback
    # (and whole-prompt prefill) attend fresh in-chunk keys — keeping the
    # chunked/whole divergence limited to the inherent cross-chunk case
    use_flash = _use_paged_kernel() and not quant

    def body(x, inputs):
        layer, k_c, v_c, ks_c, vs_c = inputs
        q, k, v = attn_qkv(cfg, layer, x, positions)
        k_c, v_c, ks_c, vs_c = _write_pages(
            quant, chunk_rows, k[0].reshape(C // ps, ps, *k.shape[2:]),
            v[0].reshape(C // ps, ps, *v.shape[2:]), k_c, v_c, ks_c, vs_c)
        kp = k_c[prev_table].reshape(S_prev, *k_c.shape[2:])
        vp = v_c[prev_table].reshape(S_prev, *v_c.shape[2:])
        if quant:
            kp = (kp.astype(jnp.float32)
                  * ks_c[prev_table].reshape(S_prev, -1)[..., None])
            vp = (vp.astype(jnp.float32)
                  * vs_c[prev_table].reshape(S_prev, -1)[..., None])
        if use_flash:
            # the table covers the window THROUGH this chunk (engine
            # buckets it to >= start + C), and pool-slot index == global
            # position — offset-flash's causal mask handles previous
            # chunks, in-chunk causality, AND trash/pad slots (they sit
            # at positions > every query) in one kernel, with no
            # [C, S_win] fp32 score materialization
            from ...ops.pallas.flash_attention import flash_attention

            attn = flash_attention(
                q, kp.astype(x.dtype)[None], vp.astype(x.dtype)[None],
                causal=True, q_offset=start,
                alibi_slopes=(alibi_slopes(cfg.n_heads)
                              if cfg.position == "alibi" else None)
            ).reshape(1, C, -1)
            return _attn_out(cfg, layer, x, attn), (k_c, v_c, ks_c, vs_c)
        # keys = [previous pooled slots | this chunk]; the pooled half is
        # masked to < start, the chunk half causally within the chunk
        kk = jnp.concatenate([kp.astype(x.dtype)[None], k], axis=1)
        vv = jnp.concatenate([vp.astype(x.dtype)[None], v], axis=1)
        kk = _repeat_kv(kk, cfg.n_heads // cfg.kv_heads)
        vv = _repeat_kv(vv, cfg.n_heads // cfg.kv_heads)
        scores = jnp.einsum("btnd,bsnd->bnts", q, kk).astype(jnp.float32)
        scores = scores / math.sqrt(cfg.head_dim)
        if cfg.position == "alibi":
            # query i sits at global start+i; prev slots at their pool
            # index (page tables are position-ordered), chunk keys at
            # start+j
            scores = scores + _alibi_bias(
                cfg, start + jnp.arange(C),
                jnp.concatenate([jnp.arange(S_prev),
                                 start + jnp.arange(C)]))
        mask = jnp.concatenate(
            [jnp.broadcast_to(prev_vis, (C, S_prev)), causal], axis=1)
        scores = jnp.where(mask[None, None], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
        attn = jnp.einsum("bnts,bsnd->btnd", probs, vv).reshape(1, C, -1)
        return _attn_out(cfg, layer, x, attn), (k_c, v_c, ks_c, vs_c)

    ops = (params["layers"],) + _pools_per_layer(pools)
    x, new_pools = jax.lax.scan(body, x, ops)
    out_pools = _pools_from_scan(new_pools)
    hidden = _norm(x[:, n - 1], params["final_norm"]["scale"],
                   params["final_norm"].get("bias"), cfg.norm, cfg.norm_eps)
    logits = logits_fn(cfg, params, hidden[:, None])[0, 0]
    return logits, out_pools


def _gather_window_attend(cfg: TransformerConfig, quant: bool, q,
                          k_c, v_c, ks_c, vs_c, page_table, q_pos, vis
                          ) -> jnp.ndarray:
    """[B, T] written-through queries attend the pooled pages via the
    XLA gather path — THE shared formulation of the paged_decode
    fallback (T=1) and paged_verify (T=k+1), so the dequant / GQA /
    alibi / mask / softmax chain cannot diverge between them.

    q: [B, T, NH, D]; q_pos: [B, T] global positions; vis: [B, T, S]
    per-query visibility over pool slots.  Returns [B, T, NH*D]."""
    B, S = vis.shape[0], vis.shape[2]
    kk = k_c[page_table].reshape(B, S, *k_c.shape[2:])  # [B, S, KVH, D]
    vv = v_c[page_table].reshape(B, S, *v_c.shape[2:])
    if quant:
        kk = kk.astype(jnp.float32) \
            * ks_c[page_table].reshape(B, S, -1)[..., None]
        vv = vv.astype(jnp.float32) \
            * vs_c[page_table].reshape(B, S, -1)[..., None]
        kk = kk.astype(q.dtype)
        vv = vv.astype(q.dtype)
    kk = _repeat_kv(kk, cfg.n_heads // cfg.kv_heads)
    vv = _repeat_kv(vv, cfg.n_heads // cfg.kv_heads)
    scores = jnp.einsum("btnd,bsnd->bnts", q, kk).astype(jnp.float32)
    scores = scores / math.sqrt(cfg.head_dim)
    if cfg.position == "alibi":
        scores = scores + _alibi_bias(cfg, q_pos, jnp.arange(S)[None])
    scores = jnp.where(vis[:, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bnts,bsnd->btnd", probs, vv).reshape(
        B, q.shape[1], -1)


def paged_verify(cfg: TransformerConfig, params, pools,
                 ids, positions, page_table, active, n_valid
                 ) -> Tuple[jnp.ndarray, Any]:
    """Score a W-token window for every decode slot in ONE model call —
    the batched verify step of speculative decoding (engine_v2).

    This is ``paged_decode`` generalized from one pending token to a
    fixed-width window of ``W = k + 1`` tokens per sequence (the last
    accepted token followed by up to ``k`` draft tokens): each valid
    token's K/V is written into the sequence's pages exactly where plain
    decode would have written it, then every window query attends the
    pooled window ``slot_pos <= its position`` — the same
    write-then-gather data flow as decode, so position ``w``'s logits
    are what a plain decode step would have produced after consuming
    ``ids[:, :w+1]``.  The host accepts the longest draft prefix
    matching the per-position argmax and *rolls back* the pages of
    rejected tokens; rejected KV left inside kept pages is harmless —
    every read is masked to ``<= query position`` and the next window
    starts at the first rejected position, overwriting it before any
    query can see it.

    ids: [B, W] window tokens (ids[:, 0] = last accepted token);
    positions: [B] position of ids[:, 0]; page_table: [B, MP]
    (trash-filled); active: [B] bool; n_valid: [B] valid tokens per row
    (1..W — rows propose fewer than k drafts on an n-gram miss).
    Invalid/inactive tokens write to the trash page and their outputs
    are garbage the host never reads.  Returns (logits [B, W, V],
    pools).

    Like quantized chunked prefill this stays on the XLA gather path
    (the Pallas decode kernel is single-query; a multi-query window
    kernel is a future optimization) — the win measured here is model
    *invocations*, not attention FLOPs."""
    quant = "k_scale" in pools
    B, W = ids.shape
    ps = pools["k"].shape[2]
    trash = pools["k"].shape[1] - 1
    pos_w = positions[:, None] + jnp.arange(W)[None]  # [B, W]
    x = params["embed"]["tok"][ids]  # [B, W, H]
    if cfg.position == "learned":
        pos_idx = jnp.minimum(pos_w, params["embed"]["pos"].shape[0] - 1)
        x = x + params["embed"]["pos"][pos_idx]
    if "norm" in params["embed"]:
        x = _norm(x, params["embed"]["norm"]["scale"],
                  params["embed"]["norm"].get("bias"), cfg.norm, cfg.norm_eps)

    valid = active[:, None] & (jnp.arange(W)[None] < n_valid[:, None])
    S = page_table.shape[1] * ps
    page_idx = jnp.where(
        valid, page_table[jnp.arange(B)[:, None],
                          jnp.minimum(pos_w // ps, page_table.shape[1] - 1)],
        trash)
    off = pos_w % ps
    slot_pos = jnp.arange(S)[None, None]          # [1, 1, S]
    vis = slot_pos <= pos_w[:, :, None]           # [B, W, S]

    def body(x, inputs):
        layer, k_c, v_c, ks_c, vs_c = inputs
        q, k, v = attn_qkv(cfg, layer, x, pos_w)  # [B, W, NH/KVH, D]
        if quant:
            kq, ksc = _kv_quantize(k)
            vq, vsc = _kv_quantize(v)
            k_c = k_c.at[page_idx, off].set(kq)
            v_c = v_c.at[page_idx, off].set(vq)
            ks_c = ks_c.at[page_idx, off].set(ksc)
            vs_c = vs_c.at[page_idx, off].set(vsc)
        else:
            k_c = k_c.at[page_idx, off].set(k.astype(k_c.dtype))
            v_c = v_c.at[page_idx, off].set(v.astype(v_c.dtype))
        attn = _gather_window_attend(cfg, quant, q, k_c, v_c, ks_c,
                                     vs_c, page_table, pos_w, vis)
        return _attn_out(cfg, layer, x, attn), (k_c, v_c, ks_c, vs_c)

    ops = (params["layers"],) + _pools_per_layer(pools)
    x, new_pools = jax.lax.scan(body, x, ops)
    out_pools = _pools_from_scan(new_pools)
    hidden = _norm(x, params["final_norm"]["scale"],
                   params["final_norm"].get("bias"), cfg.norm, cfg.norm_eps)
    logits = logits_fn(cfg, params, hidden)  # [B, W, V]
    return logits, out_pools


def paged_decode(cfg: TransformerConfig, params, pools,
                 last_tokens, positions, page_table, active
                 ) -> Tuple[jnp.ndarray, Any]:
    """One token for every decode slot.

    pools: page pools dict (see paged_prefill).  last_tokens: [B];
    positions: [B] position of that token; page_table: [B, MP]
    (trash-filled beyond each sequence's pages); active: [B] bool.
    Returns (logits [B, V], pools).

    This is also the per-iteration body of :func:`paged_multi_decode` —
    ONE formulation, so the fused K-step scan cannot diverge from the
    single-step program it must be bit-identical to.
    """
    quant = "k_scale" in pools
    B = last_tokens.shape[0]
    ps = pools["k"].shape[2]
    trash = pools["k"].shape[1] - 1
    x = params["embed"]["tok"][last_tokens][:, None]  # [B, 1, H]
    if cfg.position == "learned":
        x = x + params["embed"]["pos"][positions][:, None]
    if "norm" in params["embed"]:
        x = _norm(x, params["embed"]["norm"]["scale"],
                  params["embed"]["norm"].get("bias"), cfg.norm, cfg.norm_eps)

    # clamp the page lookup for INACTIVE rows: inside the multi-step
    # scan a finished row's position stops advancing but may already sit
    # one past its last page; the gathered index is discarded (the
    # jnp.where routes the write to the trash page), active rows always
    # index in range by the engine's headroom-reservation contract
    page_idx = jnp.where(
        active,
        page_table[jnp.arange(B),
                   jnp.minimum(positions // ps, page_table.shape[1] - 1)],
        trash)
    off = positions % ps
    S = page_table.shape[1] * ps
    slot_pos = jnp.arange(S)[None]  # [1, S]
    vis = slot_pos <= positions[:, None]  # [B, S]

    use_kernel = _use_paged_kernel()

    def body(x, inputs):
        layer, k_c, v_c, ks_c, vs_c = inputs
        q, k, v = attn_qkv(cfg, layer, x, positions[:, None])
        if quant:
            kq, ksc = _kv_quantize(k[:, 0])
            vq, vsc = _kv_quantize(v[:, 0])
            k_c = k_c.at[page_idx, off].set(kq)
            v_c = v_c.at[page_idx, off].set(vq)
            ks_c = ks_c.at[page_idx, off].set(ksc)
            vs_c = vs_c.at[page_idx, off].set(vsc)
        else:
            k_c = k_c.at[page_idx, off].set(k[:, 0].astype(k_c.dtype))
            v_c = v_c.at[page_idx, off].set(v[:, 0].astype(v_c.dtype))
        if use_kernel:
            # Pallas paged kernel: pages addressed in place through the
            # scalar-prefetched table — no [B, S, KVH, D] materialization
            # (reference ragged_ops decode kernels)
            from ...ops.pallas.paged_attention import paged_decode_attention

            attn = paged_decode_attention(
                q[:, 0], k_c, v_c, page_table, positions,
                k_scale=ks_c, v_scale=vs_c,
                alibi_slopes=(alibi_slopes(cfg.n_heads)
                              if cfg.position == "alibi" else None)
            ).reshape(B, 1, -1)
        else:
            attn = _gather_window_attend(cfg, quant, q, k_c, v_c, ks_c,
                                         vs_c, page_table,
                                         positions[:, None],
                                         vis[:, None, :])
        return _attn_out(cfg, layer, x, attn), (k_c, v_c, ks_c, vs_c)

    ops = (params["layers"],) + _pools_per_layer(pools)
    x, new_pools = jax.lax.scan(body, x, ops)
    out_pools = _pools_from_scan(new_pools)
    hidden = _norm(x, params["final_norm"]["scale"],
                   params["final_norm"].get("bias"), cfg.norm, cfg.norm_eps)
    logits = logits_fn(cfg, params, hidden)[:, 0]
    return logits, out_pools


def sample_tokens(logits, temps, key, sids, positions) -> jnp.ndarray:
    """On-device sampling shared by the single-step decode program and
    the fused multi-step scan: greedy argmax, or Gumbel-max categorical
    at temperature > 0.

    The sampling key is folded per **(request id, position)** — the
    engine passes each row's uid, a STABLE identity, and the position
    of the token being generated — never per dispatch and never per
    decode slot: a K-step fused scan draws exactly the noise K
    single-step dispatches would (sampled rows bit-identical across
    decode horizons, greedy trivially so), a preempted-and-readmitted
    or migrated sampled stream continues with ITS noise regardless of
    which slot it lands in, and co-batched requests at equal positions
    never share noise.  logits: [B, V]; temps: [B] (<= 0 = greedy);
    sids: [B] int32 per-row request ids; positions: [B] position the
    sampled token will occupy.  Returns [B] int32 token ids.
    """
    z = logits.astype(jnp.float32)
    greedy = jnp.argmax(z, axis=-1).astype(jnp.int32)

    def _one(sid, p, zrow, t):
        k = jax.random.fold_in(jax.random.fold_in(key, sid), p)
        return jax.random.categorical(
            k, zrow / jnp.maximum(t, 1e-6)).astype(jnp.int32)

    sampled = jax.vmap(_one)(sids.astype(jnp.int32),
                             positions.astype(jnp.int32), z, temps)
    return jnp.where(temps > 0.0, sampled, greedy)


def paged_multi_decode(cfg: TransformerConfig, params, pools,
                       last_tokens, positions, page_table, active,
                       temps, eos_ids, budgets, sids, key, horizon: int
                       ) -> Tuple[jnp.ndarray, jnp.ndarray, Any]:
    """``horizon`` decode steps in ONE device program: a ``lax.scan``
    over the :func:`paged_decode` body (paged KV write → attention →
    on-device :func:`sample_tokens` with the per-position key fold →
    position/page-index advance), with per-row active/EOS/budget
    masking computed **in-scan** — finished rows write to the trash
    page and stop consuming pages.  ONE host pull per K tokens instead
    of K round-trips (engine_v2 ``_multi_decode``).

    last_tokens/positions/active/temps: as :func:`paged_decode`;
    page_table: [B, MP] covering each row's PRE-RESERVED horizon
    headroom (the engine reserves pages for ``budgets[b]`` tokens
    before dispatch — nothing allocates mid-scan); eos_ids: [B] int32
    (-1 = no EOS); budgets: [B] int32 tokens row ``b`` may emit this
    dispatch (min of the request's remaining max_new / model-window /
    deadline/headroom clamps and the horizon; 0 = inactive); sids: [B]
    int32 per-row request ids for the sampling fold.

    Returns ``(tokens [B, K] int32, produced [B] int32, pools)``:
    row ``b``'s emitted tokens are ``tokens[b, :produced[b]]``
    (positions past ``produced`` hold -1).  A row stops — and its
    later iterations write to the trash page — after its EOS token or
    its budget'th token, exactly where K single steps would have
    retired it; contract: the emitted stream is bit-identical to K
    single-step dispatches (greedy AND sampled — see sample_tokens).
    """
    B = last_tokens.shape[0]

    def step(carry, _):
        pools, last, pos, act, produced = carry
        logits, pools = paged_decode(cfg, params, pools, last, pos,
                                     page_table, act)
        tok = sample_tokens(logits, temps, key, sids, pos + 1)
        emit = act
        tok = jnp.where(emit, tok, jnp.int32(-1))
        produced = produced + emit.astype(jnp.int32)
        eos_hit = emit & (eos_ids >= 0) & (tok == eos_ids)
        act = emit & jnp.logical_not(eos_hit) & (produced < budgets)
        last = jnp.where(emit, tok, last)
        pos = pos + emit.astype(jnp.int32)
        return (pools, last, pos, act, produced), tok

    act0 = active & (budgets > 0)
    carry0 = (pools, last_tokens, positions, act0,
              jnp.zeros((B,), jnp.int32))
    (pools, _l, _p, _a, produced), toks = jax.lax.scan(
        step, carry0, None, length=horizon)
    return jnp.transpose(toks), produced, pools
