"""Paged KV-cache state.

Reference parity: the blocked KV cache of inference v2 —
``BlockedAllocator`` / ``KVCacheManager`` (inference/v2/ragged/,
ragged/csrc/fast_host_buffer.cpp and friends).  The reference manages
blocks with a C++ host allocator feeding CUDA ragged kernels; here the
allocator is host Python (it runs between jitted steps, off the hot
device path) and the cache is a dense page pool the decode program
indexes with page tables.

Layout: ``k``/``v`` are ``[L, num_pages + 1, page_size, KVH, D]``.  The
last page (index ``num_pages``) is the *trash page*: writes from inactive
slots and pad positions are routed there, keeping every device-side
scatter unconditional (no data-dependent control flow under jit).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List

import jax.numpy as jnp


@dataclasses.dataclass
class KVBlockConfig:
    page_size: int = 16
    num_pages: int = 256
    max_seqs: int = 8  # concurrent decode slots
    max_pages_per_seq: int = 16

    @property
    def max_seq_len(self) -> int:
        return self.page_size * self.max_pages_per_seq

    @property
    def trash_page(self) -> int:
        return self.num_pages


class BlockAllocator:
    """Free-list page allocator (reference inference/v2/ragged
    BlockedAllocator): O(1) alloc/free, host-side."""

    def __init__(self, num_pages: int):
        self.num_pages = num_pages
        self._free: List[int] = list(range(num_pages - 1, -1, -1))

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def alloc(self, n: int) -> List[int]:
        if n > len(self._free):
            raise MemoryError(f"KV pool exhausted: need {n} pages, "
                              f"{len(self._free)} free")
        out = [self._free.pop() for _ in range(n)]
        return out

    def free(self, pages: List[int]) -> None:
        for p in pages:
            if not (0 <= p < self.num_pages):
                raise ValueError(f"freeing invalid page {p}")
        self._free.extend(pages)


class PagedKVCache:
    """Device arrays of the page pool.

    ``kv_quant``: store K/V as int8 codes + one fp32 scale per
    (page, slot, kv-head) — half the pool HBM of bf16, so twice the KV
    capacity (the reference's blocked-KV analogue of weight-only
    quantization, applied to the cache).  Quantize-on-write,
    dequantize-on-read; the paged Pallas kernel dequantizes in VMEM."""

    @staticmethod
    def init(n_layers: int, kv_heads: int, head_dim: int,
             block: KVBlockConfig, dtype=jnp.bfloat16,
             kv_quant: bool = False) -> Dict[str, Any]:
        shape = (n_layers, block.num_pages + 1, block.page_size, kv_heads, head_dim)
        if kv_quant:
            sshape = shape[:-1]
            return {"k": jnp.zeros(shape, jnp.int8),
                    "v": jnp.zeros(shape, jnp.int8),
                    "k_scale": jnp.zeros(sshape, jnp.float32),
                    "v_scale": jnp.zeros(sshape, jnp.float32)}
        return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


@dataclasses.dataclass
class SequenceState:
    """Host-side descriptor of one in-flight sequence (reference
    DSSequenceDescriptor, inference/v2/ragged/sequence_descriptor.py)."""

    uid: int
    tokens: List[int]  # prompt + generated so far
    prompt_len: int
    max_new_tokens: int
    temperature: float
    eos_id: int | None
    slot: int = -1  # decode slot index, -1 = not scheduled
    pages: List[int] = dataclasses.field(default_factory=list)
    done: bool = False
    admit_order: int = -1  # monotonic admission stamp (preemption policy)
    #: tokens of the prefix already prefilled (chunked prefill); a
    #: sequence decodes only once prefilled == length at chunk end
    prefilled: int = 0

    @property
    def length(self) -> int:
        return len(self.tokens)

    @property
    def generated(self) -> int:
        return self.length - self.prompt_len
