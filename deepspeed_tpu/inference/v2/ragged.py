"""Paged KV-cache state.

Reference parity: the blocked KV cache of inference v2 —
``BlockedAllocator`` / ``KVCacheManager`` (inference/v2/ragged/,
ragged/csrc/fast_host_buffer.cpp and friends).  The reference manages
blocks with a C++ host allocator feeding CUDA ragged kernels; here the
allocator is host Python (it runs between jitted steps, off the hot
device path) and the cache is a dense page pool the decode program
indexes with page tables.

Layout: ``k``/``v`` are ``[L, num_pages + 1, page_size, KVH, D]``.  The
last page (index ``num_pages``) is the *trash page*: writes from inactive
slots and pad positions are routed there, keeping every device-side
scatter unconditional (no data-dependent control flow under jit).
"""

from __future__ import annotations

import dataclasses
import hashlib
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp

#: request priority classes (smaller = more urgent).  Priorities order
#: admission (the scheduler admits the highest class first), choose
#: preemption victims under KV-pool pressure (lowest class, then
#: youngest), and gate load shedding (``serving/admission.py`` sheds
#: only classes above the protected threshold under overload).
PRIORITY_INTERACTIVE = 0
PRIORITY_NORMAL = 1
PRIORITY_BATCH = 2


class RejectedError(RuntimeError):
    """A request refused by admission control (load shedding).

    Not a bug and not data loss: the submitter still holds the request
    and should back off ``retry_after_s`` seconds before resubmitting.
    Raised by ``InferenceEngineV2.put`` (bounded queue,
    ``max_queue_depth``) and by the fleet router's admission controller
    (queue bound / KV-pool occupancy shed threshold) — loudly, instead
    of queuing work into an OOM/preemption storm."""

    def __init__(self, reason: str, retry_after_s: float = 1.0,
                 priority: Optional[int] = None):
        super().__init__(
            f"request rejected ({reason}); retry after {retry_after_s:.2f}s")
        self.reason = reason
        self.retry_after_s = float(retry_after_s)
        self.priority = priority


@dataclasses.dataclass
class KVBlockConfig:
    page_size: int = 16
    num_pages: int = 256
    max_seqs: int = 8  # concurrent decode slots
    max_pages_per_seq: int = 16

    @property
    def max_seq_len(self) -> int:
        return self.page_size * self.max_pages_per_seq

    @property
    def trash_page(self) -> int:
        return self.num_pages


class BlockAllocator:
    """Ref-counted page allocator (reference inference/v2/ragged
    BlockedAllocator, grown for automatic prefix caching): O(1)
    alloc/share/free, host-side.

    Every live page carries a refcount: ``alloc`` hands out pages at
    refcount 1, ``share`` maps an already-written page into another
    sequence (+1), ``free`` drops a reference.  A page is *never* recycled
    while referenced.  Pages may additionally be **registered** under a
    content key (PrefixCache): when a registered page's refcount drops to
    0 it is parked in an LRU of cached-but-unreferenced pages instead of
    the raw free list, so later requests with the same prefix can re-map
    it.  ``alloc`` prefers truly-free pages and only then evicts from the
    LRU tail (unregistering the evicted key) — referenced pages are never
    eviction candidates because they are never in the LRU.
    """

    def __init__(self, num_pages: int, cache_pages: int = 0):
        self.num_pages = num_pages
        self._free: List[int] = list(range(num_pages - 1, -1, -1))
        self._ref: List[int] = [0] * num_pages
        #: cap on cached-but-unreferenced pages retained (0 = pool-bounded)
        self.cache_cap = cache_pages
        self._by_key: Dict[Any, int] = {}   # content key -> page
        self._key_of: Dict[int, Any] = {}   # page -> content key
        self._lru: "OrderedDict[int, None]" = OrderedDict()  # oldest first
        self.evictions = 0
        #: tiered KV cache (serving/kv_tier.py): called as
        #: ``hook(page, key) -> bool`` for every page evicted from the
        #: prefix-cache LRU.  Returning True CAPTURES the page for a
        #: host-RAM spill: the allocator pins it (refcount 1, tracked in
        #: ``_spill_pinned``) so it cannot be handed out — and therefore
        #: never overwritten — until the spill's D2H copy commits and
        #: the owner calls :meth:`release_spill_pin`.
        self.spill_hook = None
        self._spill_pinned: set = set()
        #: pin headroom for the CURRENT ``alloc`` call: each captured
        #: eviction consumes one unit of the capacity beyond the request,
        #: so capturing can never starve the allocation mid-loop.
        #: Outside ``alloc`` (cap trims) pinning is unconstrained.
        self._pin_slack = num_pages
        #: bumped on every registry change (register/evict) so match
        #: results can be memoized: a blocked head-of-queue request must
        #: not re-hash its whole prompt every engine step when nothing
        #: it could match against has changed
        self.generation = 0
        #: bumped only on unregister: registrations can only EXTEND an
        #: existing match, so while this is unchanged a memoized match
        #: prefix stays valid and the walk can RESUME from its end
        #: instead of re-hashing the whole prompt
        self.evict_generation = 0

    @property
    def free_pages(self) -> int:
        """Allocatable pages: truly free + cached-but-unreferenced."""
        return len(self._free) + len(self._lru)

    @property
    def used_pages(self) -> int:
        """Pages referenced by live sequences (refcount > 0)."""
        return self.num_pages - len(self._free) - len(self._lru)

    @property
    def uncached_free_pages(self) -> int:
        """Truly-free pages, excluding cached-but-unreferenced LRU pages.
        This is the budget speculative draft reservation spends: draft
        tokens may be rejected, so the engine never evicts prefix-cache
        content (guaranteed future savings) to reserve pages for them —
        only the base token may claim LRU pages, exactly like plain
        decode."""
        return len(self._free)

    @property
    def lru_pages(self) -> int:
        """Cached-but-unreferenced pages parked in the LRU: they occupy
        pool HBM purely for prefix reuse (the "pinned" occupancy the
        serving gauges and the memory ledger report)."""
        return len(self._lru)

    @property
    def cached_pages(self) -> int:
        return len(self._by_key)

    def refcount(self, page: int) -> int:
        return self._ref[page]

    def alloc(self, n: int) -> List[int]:
        if n > self.free_pages:
            raise MemoryError(f"KV pool exhausted: need {n} pages, "
                              f"{self.free_pages} free")
        # spill captures during the evictions below consume ONLY the
        # headroom beyond this request: free_pages was just proven >= n,
        # and every loop iteration takes one page from (free + LRU) for
        # the caller plus at most slack pages for pins — the request
        # itself can never fail mid-loop with refcounts half-mutated
        self._pin_slack = self.free_pages - n
        try:
            out = []
            for _ in range(n):
                if self._free:
                    p = self._free.pop()
                else:
                    p = self._evict_lru()
                self._ref[p] = 1
                out.append(p)
        finally:
            self._pin_slack = self.num_pages
        return out

    def try_alloc(self, n: int,
                  uncached_only: bool = False) -> Optional[List[int]]:
        """Headroom reservation (fused multi-step decode): allocate ``n``
        pages, or return ``None`` — allocator untouched — when the pool
        cannot cover them.  The engine pre-reserves each decode row's
        page headroom for the whole horizon before dispatch and SHRINKS
        the horizon on refusal instead of preempting mid-scan, so this
        is the non-raising twin of :meth:`alloc` for callers whose
        fallback is "ask for less", not "crash the step".

        ``uncached_only=True`` spends TRULY-free pages only: horizon
        headroom backs tokens a row may never produce (mid-horizon
        EOS), so — exactly like speculative draft reservation — it must
        never evict prefix-cache LRU content (guaranteed future
        savings) to cover it; ``alloc`` prefers the free list, so a
        grant within it never touches the LRU."""
        budget = self.uncached_free_pages if uncached_only \
            else self.free_pages
        if n > budget:
            return None
        return self.alloc(n)

    def share(self, page: int) -> int:
        """Map an already-written page into another sequence (+1 ref).
        A cached page at refcount 0 leaves the LRU: it is live again."""
        if not (0 <= page < self.num_pages):
            raise ValueError(f"sharing invalid page {page}")
        if self._ref[page] == 0:
            if page not in self._lru:
                raise ValueError(f"sharing unreferenced uncached page {page}")
            del self._lru[page]
        self._ref[page] += 1
        return page

    def free(self, pages: List[int]) -> None:
        # validate the WHOLE list before mutating (duplicate-aware): a
        # bad page mid-list must not leave earlier refcounts decremented
        counts: Dict[int, int] = {}
        for p in pages:
            if not (0 <= p < self.num_pages):
                raise ValueError(f"freeing invalid page {p}")
            counts[p] = counts.get(p, 0) + 1
        for p, c in counts.items():
            if self._ref[p] < c:
                raise ValueError(f"double free of page {p}")
        for p in pages:
            self._ref[p] -= 1
            if self._ref[p] == 0:
                if p in self._key_of:
                    # registered content survives: park in the LRU (MRU
                    # end) for prefix reuse instead of the free list
                    self._lru[p] = None
                    self._trim_cache()
                else:
                    self._free.append(p)

    # -- debug leak/invariant audit ------------------------------------------
    def check_invariants(
            self, live_pages: Optional[Sequence[Sequence[int]]] = None
    ) -> None:
        """Audit the allocator's internal invariants; raise
        ``AssertionError`` naming the first violation.  Cheap (O(pages))
        and read-only — tests and ``tools/fleet_drill.py`` run it after
        KV churn (speculative rollback, migration, preemption) so a
        leaked page or refcount can never pass silently.

        Structural invariants (always checked):

        * every page is in exactly one of {free list, LRU, referenced};
        * the free list has no duplicates and only refcount-0 pages;
        * every LRU page is refcount-0 AND registered;
        * ``_by_key``/``_key_of`` are a bijection over registered pages;
        * ``cache_cap`` (when set) bounds the LRU;
        * every spill-pinned page (host-tier capture awaiting its D2H
          commit) is referenced (its pin IS a reference) and
          unregistered — it sits in the "referenced" partition with no
          sequence owner.

        ``live_pages`` — one page list per live owner (e.g. every
        slotted sequence's ``seq.pages``) — additionally audits the
        refcounts *exactly*: each page's refcount must equal its total
        occurrence count across owners, PLUS one for an in-flight spill
        pin.  A surplus refcount is a leak (freed sequence still holding
        pages); a deficit is a use-after-free in waiting."""
        # explicit raises (not bare asserts) so ``python -O`` can't
        # compile the audit out and vacuously pass the leak gates
        free_set = set(self._free)
        if len(free_set) != len(self._free):
            raise AssertionError(
                f"free list has duplicates: {sorted(self._free)}")
        if free_set & set(self._lru):
            raise AssertionError(
                f"pages in free list AND LRU: {sorted(free_set & set(self._lru))}")
        for p in self._free:
            if self._ref[p] != 0:
                raise AssertionError(
                    f"page {p} in free list with refcount {self._ref[p]}")
        for p in self._lru:
            if self._ref[p] != 0:
                raise AssertionError(
                    f"LRU page {p} has refcount {self._ref[p]}")
            if p not in self._key_of:
                raise AssertionError(f"LRU page {p} is not registered")
        referenced = {p for p in range(self.num_pages) if self._ref[p] > 0}
        if referenced & free_set:
            raise AssertionError(
                f"referenced pages in free list: {sorted(referenced & free_set)}")
        covered = len(free_set) + len(self._lru) + len(referenced)
        if covered != self.num_pages:
            raise AssertionError(
                f"page partition broken: {len(free_set)} free + "
                f"{len(self._lru)} LRU + {len(referenced)} referenced "
                f"!= {self.num_pages} pages (a refcount-0 page outside "
                "free/LRU is a leaked page)")
        if len(self._by_key) != len(self._key_of):
            raise AssertionError("registry maps disagree in size")
        for key, p in self._by_key.items():
            if self._key_of.get(p) != key:
                raise AssertionError(f"registry not a bijection at page {p}")
        if self.cache_cap > 0 and len(self._lru) > self.cache_cap:
            raise AssertionError(
                f"LRU {len(self._lru)} exceeds cache_cap {self.cache_cap}")
        for p in self._spill_pinned:
            if self._ref[p] < 1:
                raise AssertionError(
                    f"spill-pinned page {p} has refcount {self._ref[p]} "
                    "(the pin itself must hold a reference)")
            if p in self._key_of:
                raise AssertionError(
                    f"spill-pinned page {p} is still registered (eviction "
                    "must unregister before the capture)")
        if live_pages is not None:
            want: Dict[int, int] = {}
            for p in self._spill_pinned:
                want[p] = 1  # the in-flight spill's pin is a live ref
            for owner in live_pages:
                for p in owner:
                    want[p] = want.get(p, 0) + 1
            for p in range(self.num_pages):
                w = want.get(p, 0)
                if self._ref[p] != w:
                    raise AssertionError(
                        f"page {p}: refcount {self._ref[p]} != {w} live "
                        f"reference(s) — "
                        f"{'leak' if self._ref[p] > w else 'use-after-free'}")

    def assert_no_leaks(
            self, live_pages: Sequence[Sequence[int]] = ()) -> None:
        """``check_invariants`` with an exact refcount audit against the
        given live owners (default: none live, so every page must be
        free or LRU-parked).  The speculative-rollback / KV-churn gate."""
        self.check_invariants(list(live_pages))

    def adopt(self, keys: Sequence[Optional[Any]]
              ) -> Tuple[List[int], List[bool]]:
        """Import-side page placement with **ref-count adoption** (the KV
        migration refactor): for each position, when ``keys[j]`` is
        already registered locally the existing page is *shared* (+1 ref)
        instead of duplicated — content-chain keys are content
        addresses, so the local page holds bit-identical KV and the
        imported sequence can map it directly.  Unmatched positions (or
        ``None`` keys — partial tail pages, cache-off imports) get fresh
        pages for the caller to fill from the bundle's arrays.

        All-or-nothing: insufficient capacity raises ``MemoryError``
        BEFORE any refcount moves, so a failed import leaves the
        allocator untouched.  Returns ``(pages, reused)`` where
        ``reused[j]`` says position ``j`` adopted a local page (its
        content must NOT be overwritten)."""
        matched = [self._by_key.get(k) if k is not None else None
                   for k in keys]
        # matched pages at refcount 0 sit in the LRU: counted in
        # free_pages but claimed by share(), not alloc() (same exactness
        # rule as engine_v2._admit)
        lru_matched = sum(1 for p in matched
                          if p is not None and self._ref[p] == 0)
        need = sum(1 for p in matched if p is None)
        if need > self.free_pages - lru_matched:
            raise MemoryError(
                f"KV import needs {need} fresh pages "
                f"(+{lru_matched} adopted from the LRU), only "
                f"{self.free_pages - lru_matched} allocatable")
        # share FIRST: matched LRU pages must be protected from being
        # evicted by the alloc() calls below
        for p in matched:
            if p is not None:
                self.share(p)
        fresh = iter(self.alloc(need))
        pages = [p if p is not None else next(fresh) for p in matched]
        return pages, [p is not None for p in matched]

    def export_meta(self, pages: Sequence[int]) -> List[Dict[str, Any]]:
        """Block-table metadata for a page list (serialization side of
        KV migration): per page, its id, refcount, and registered
        content key (None for unregistered/private pages)."""
        return [{"page": int(p), "refcount": self._ref[p],
                 "key": self._key_of.get(p)} for p in pages]

    # -- prefix-cache registry ----------------------------------------------
    def register(self, page: int, key: Any) -> bool:
        """Publish ``page`` as the cached page for ``key``.  First writer
        wins: duplicate keys (concurrent identical prefills) and pages
        already registered under another key are skipped."""
        if key in self._by_key or page in self._key_of:
            return False
        self._by_key[key] = page
        self._key_of[page] = key
        self.generation += 1
        return True

    def lookup(self, key: Any) -> Optional[int]:
        return self._by_key.get(key)

    def _unregister(self, page: int) -> None:
        key = self._key_of.pop(page, None)
        if key is not None and self._by_key.get(key) == page:
            del self._by_key[key]
            self.generation += 1
            self.evict_generation += 1

    def _evict_one(self) -> Optional[int]:
        """Pop + unregister the LRU tail and offer it to the spill hook.
        Returns the page when it is immediately reusable, or None when
        the hook captured it for a host-RAM spill (pinned at refcount 1
        until :meth:`release_spill_pin` — never handed out, so the spill
        copy can never race a new writer)."""
        page, _ = self._lru.popitem(last=False)
        key = self._key_of.get(page)
        self._unregister(page)
        self.evictions += 1
        if (self.spill_hook is not None and self._pin_slack > 0
                and self.spill_hook(page, key)):
            self._ref[page] = 1
            self._spill_pinned.add(page)
            self._pin_slack -= 1
            return None
        return page

    def _evict_lru(self) -> int:
        """Evict LRU pages until one is NOT captured for spill; returns
        that (allocatable) page.  Bounded: captures are limited by
        ``_pin_slack``, so the loop always terminates with a page."""
        while True:
            p = self._evict_one()
            if p is not None:
                return p

    def _trim_cache(self) -> None:
        if self.cache_cap > 0:
            while len(self._lru) > self.cache_cap:
                # _evict_one, not _evict_lru: when the hook captures the
                # tail page the LRU already shrank by one — looping for a
                # returnable page here would over-evict content still
                # within the cap
                p = self._evict_one()
                if p is not None:
                    self._free.append(p)

    # -- host-tier spill pins -------------------------------------------------
    @property
    def spill_pinned_pages(self) -> int:
        """Pages pinned by in-flight host-tier spills: evicted from the
        prefix-cache LRU but held out of circulation until their D2H
        copy commits.  Counted in neither ``free_pages`` nor
        ``lru_pages`` — they are temporarily ``used``."""
        return len(self._spill_pinned)

    def release_spill_pin(self, page: int) -> None:
        """Drop a spill pin after its D2H copy committed (or was
        abandoned): the page returns to the truly-free list."""
        if page not in self._spill_pinned:
            raise ValueError(f"page {page} is not spill-pinned")
        self._spill_pinned.discard(page)
        self.free([page])


class PrefixCache:
    """Automatic prefix caching: a content-hash chain over FULL pages.

    Page ``j``'s key is ``hash((key[j-1], tokens[j*ps:(j+1)*ps]))`` — the
    chain makes a page's identity depend on its entire token prefix, so a
    lookup walk from the root finds the longest cached page-aligned
    prefix.  Only full pages are hashed: partial tail pages stay private
    to their sequence (the engine copy-on-writes the one case where a
    shared full page must be written — see engine_v2._admit).  Counters
    (``hits``/``misses`` here, ``evictions`` on the allocator) feed the
    serving monitor and bench_serving.py.
    """

    def __init__(self, page_size: int, allocator: BlockAllocator):
        self.page_size = page_size
        self.allocator = allocator
        self.hits = 0    # page lookups that matched (counted on admission)
        self.misses = 0  # admission walks that ended on a missing page

    @staticmethod
    def chain_key(parent_key: Any, page_tokens: Sequence[int]) -> bytes:
        """sha256 digest chain, NOT Python hash(): registry lookups go by
        key equality alone, and a non-cryptographic 64-bit hash collision
        (or an offline-constructed colliding token sequence from another
        tenant) would silently map a request onto someone else's KV."""
        h = hashlib.sha256()
        if parent_key is not None:
            h.update(parent_key)
        h.update(",".join(str(int(t)) for t in page_tokens).encode())
        return h.digest()

    def page_keys(self, tokens: Sequence[int], n_pages: int,
                  prefix_keys: Sequence[Any] = ()) -> List[Any]:
        """Chain keys for full pages ``[len(prefix_keys), n_pages)``,
        extending an already-computed prefix of keys."""
        keys = list(prefix_keys)
        ps = self.page_size
        for j in range(len(keys), n_pages):
            parent = keys[j - 1] if j else None
            keys.append(self.chain_key(parent, tokens[j * ps:(j + 1) * ps]))
        return keys

    def match(self, tokens: Sequence[int],
              resume: Optional[Tuple[List[int], List[Any]]] = None,
              host_tier: Any = None):
        """Longest cached page-aligned prefix of ``tokens``: walks the
        hash chain over full pages until a key misses.  Pure — the caller
        bumps hits/misses only when an admission actually consumes the
        match (a blocked head-of-queue peek must not inflate the rate).

        ``resume``: a previous (pages, keys) match for the SAME tokens,
        known still valid (allocator.evict_generation unchanged since) —
        the walk continues from its end, so a blocked head of queue under
        heavy registration traffic re-hashes only the frontier page.

        ``host_tier``: a :class:`~...serving.kv_tier.HostKVTier` (or
        anything with ``has(key)``) consulted PAST the device hit: the
        walk continues into the host tier's spilled pages and the return
        grows a third element — the chain keys of consecutive host-held
        pages the engine can restore (H2D) before prefilling the rest.
        Without it the return stays the 2-tuple ``(pages, keys)``."""
        ps = self.page_size
        pages: List[int] = list(resume[0]) if resume else []
        keys: List[Any] = list(resume[1]) if resume else []
        parent = keys[-1] if keys else None
        for j in range(len(pages), len(tokens) // ps):
            key = self.chain_key(parent, tokens[j * ps:(j + 1) * ps])
            page = self.allocator.lookup(key)
            if page is None:
                break
            pages.append(page)
            keys.append(key)
            parent = key
        if host_tier is not None:
            return pages, keys, self.host_extend(tokens, keys, host_tier)
        return pages, keys

    def host_extend(self, tokens: Sequence[int], keys: Sequence[Any],
                    host_tier: Any) -> List[Any]:
        """Continue a device match's hash-chain walk into the HOST tier:
        chain keys for the consecutive full pages past the device hit
        that ``host_tier`` holds.  Pure — no counters, no restore (the
        engine restores and accounts when it consumes the extension)."""
        ps = self.page_size
        out: List[Any] = []
        parent = keys[-1] if keys else None
        for j in range(len(keys), len(tokens) // ps):
            key = self.chain_key(parent, tokens[j * ps:(j + 1) * ps])
            if not host_tier.has(key):
                break
            out.append(key)
            parent = key
        return out

    def count(self, matched_pages: int, n_full_pages: int) -> None:
        """Record a consumed match in the hit/miss counters."""
        self.hits += matched_pages
        if matched_pages < n_full_pages:
            self.misses += 1


class PagedKVCache:
    """Device arrays of the page pool.

    ``kv_quant``: store K/V as int8 codes + one fp32 scale per
    (page, slot, kv-head) — half the pool HBM of bf16, so twice the KV
    capacity (the reference's blocked-KV analogue of weight-only
    quantization, applied to the cache).  Quantize-on-write,
    dequantize-on-read; the paged Pallas kernel dequantizes in VMEM."""

    @staticmethod
    def init(n_layers: int, kv_heads: int, head_dim: int,
             block: KVBlockConfig, dtype=jnp.bfloat16,
             kv_quant: bool = False) -> Dict[str, Any]:
        shape = (n_layers, block.num_pages + 1, block.page_size, kv_heads, head_dim)
        if kv_quant:
            sshape = shape[:-1]
            return {"k": jnp.zeros(shape, jnp.int8),
                    "v": jnp.zeros(shape, jnp.int8),
                    "k_scale": jnp.zeros(sshape, jnp.float32),
                    "v_scale": jnp.zeros(sshape, jnp.float32)}
        return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


@dataclasses.dataclass
class KVPageBundle:
    """Serialized KV pages + block-table metadata of one in-flight
    sequence — the unit of **KV-page migration** between engines
    (prefill→decode disaggregation, replica drain) and, later, of
    host-RAM spill of cold pages.

    ``arrays`` holds one host array per pool leaf (``k``/``v`` and,
    under kv_quant, their scales), shaped ``[L, n_pages, page_size,
    KVH, D]`` in the pool's exact dtype — import is bit-identical by
    contract.  ``page_keys`` covers only the *immutable* leading full
    pages (index < ``prefilled // page_size``): those are the pages an
    importing engine may adopt by content key instead of copying; later
    pages (partial tails, copy-on-write duplicates about to be
    rewritten) are always transferred by value.  ``src_pages`` is the
    exporting allocator's block-table metadata (``export_meta``) —
    informational, page ids are meaningless across pools."""

    uid: int
    tokens: List[int]
    prompt_len: int
    max_new_tokens: int
    temperature: float
    eos_id: Optional[int]
    #: tokens of the prefix whose KV is already written in ``arrays``
    prefilled: int
    #: fully-cached prompt mid-handoff: enters through the decode program
    decode_entry: bool
    page_size: int
    page_keys: List[Any]
    src_pages: List[Dict[str, Any]]
    arrays: Dict[str, Any]
    #: (n_layers, kv_heads, head_dim) — pools must agree to import
    model_sig: Tuple[int, int, int]
    kv_quant: bool
    dtype: str
    #: SLO identity travels with the sequence: priority class and the
    #: absolute in-process deadline (``time.perf_counter`` clock, 0 =
    #: none).  The wire format re-bases the deadline as seconds-left so
    #: it survives a clock-domain change across processes.
    priority: int = PRIORITY_NORMAL
    deadline: float = 0.0
    #: fleet trace context (docs/OBSERVABILITY.md "Request tracing"):
    #: ``{"trace_id", "snapshot", "hops"}`` — the router-minted trace id,
    #: the sender's clock-free ledger snapshot, and per-hop wall stamps.
    #: None on legacy bundles and engine-standalone exports; the wire
    #: format carries it as an optional header block (tolerant parse).
    trace: Optional[Dict[str, Any]] = None

    @property
    def n_pages(self) -> int:
        return next(iter(self.arrays.values())).shape[1]

    @property
    def generated(self) -> int:
        return len(self.tokens) - self.prompt_len


@dataclasses.dataclass
class SequenceState:
    """Host-side descriptor of one in-flight sequence (reference
    DSSequenceDescriptor, inference/v2/ragged/sequence_descriptor.py)."""

    uid: int
    tokens: List[int]  # prompt + generated so far
    prompt_len: int
    max_new_tokens: int
    temperature: float
    eos_id: int | None
    slot: int = -1  # decode slot index, -1 = not scheduled
    pages: List[int] = dataclasses.field(default_factory=list)
    done: bool = False
    admit_order: int = -1  # monotonic admission stamp (preemption policy)
    #: tokens of the prefix already prefilled (chunked prefill / cached
    #: prefix pages mapped at admission); a sequence decodes only once
    #: prefilled == length at chunk end
    prefilled: int = 0
    #: prefix-cache bookkeeping: chain keys of full pages computed so far,
    #: and how many leading pages have been offered to the registry
    page_keys: List[Any] = dataclasses.field(default_factory=list)
    registered_upto: int = 0
    #: fully-cached prompt: every prompt page was mapped from the cache
    #: (last one copy-on-write); the sequence enters through the decode
    #: program, which recomputes only the final prompt token
    decode_entry: bool = False
    #: memoized prefix-cache match for a QUEUED sequence, valid while
    #: the allocator's registry generation is unchanged; while only
    #: REGISTRATIONS happened (evict generation unchanged) the match is
    #: resumed from its end rather than recomputed
    cached_match: Any = None
    match_gen: int = -1
    match_evict_gen: int = -1
    #: priority class (PRIORITY_*): orders admission, picks preemption
    #: victims (lowest class evicted first), and gates load shedding
    priority: int = PRIORITY_NORMAL
    #: absolute expiry on the ``time.perf_counter`` clock (0 = none);
    #: past it the engine retires the sequence with
    #: ``finish_reason="deadline"`` at the next step boundary
    deadline: float = 0.0
    #: monotonic enqueue stamp: FCFS order within a priority class
    enqueue_order: int = -1
    #: perf_counter stamp of the LAST (re-)enqueue — queue-wait
    #: observations measure from here, so a preempted sequence's time
    #: spent RUNNING before eviction never counts as queueing
    queued_at: float = 0.0
    #: why the sequence finished: "length" (max_new_tokens), "eos",
    #: "max_seq_len", "deadline"; "" while running
    finish_reason: str = ""
    #: router-minted fleet trace id (None when the engine is used
    #: standalone): the cross-replica correlation key — uids are
    #: per-engine and collide across a fleet
    trace_id: Optional[str] = None

    @property
    def length(self) -> int:
        return len(self.tokens)

    @property
    def generated(self) -> int:
        return self.length - self.prompt_len
