"""Inference v2: ragged / continuous batching engine.

Reference: ``deepspeed/inference/v2`` — ``InferenceEngineV2``
(engine_v2.py), blocked KV cache + scheduling state (``ragged/``), and the
ragged kernel set (``kernels/ragged_ops``).

TPU re-design: XLA needs static shapes, so "ragged" becomes *paged*: a
fixed pool of KV pages + per-sequence page tables, one jitted decode
program for all active sequences regardless of their lengths, and
bucket-padded prefill programs.  The scheduler (admission, page
allocation, eviction of finished sequences) runs on the host between
device steps — same split as the reference's C++ atom-builder vs CUDA
kernels.
"""

from .ragged import (PRIORITY_BATCH, PRIORITY_INTERACTIVE,  # noqa: F401
                     PRIORITY_NORMAL, BlockAllocator, KVBlockConfig,
                     KVPageBundle, PagedKVCache, PrefixCache, RejectedError)
from .engine_v2 import InferenceEngineV2, RaggedInferenceConfig, RaggedRequest  # noqa: F401
from .speculative import (DraftModelProposer, NgramProposer,  # noqa: F401
                          SpeculativeConfig)
