"""Multinode runners: pdsh / OpenMPI / MPICH / Intel-MPI / SLURM / MVAPICH.

Reference parity: ``deepspeed/launcher/multinode_runner.py:55-411`` — each
runner turns (hostfile, script, args, env exports) into ONE scheduler/MPI
command that starts the job on every node.  The TPU process model stays
one-process-per-host (JAX drives all local chips), so every runner must
deliver three facts to each process: coordinator address, process count,
and its own process id.  DSTPU_* env carries the first two; the per-process
id comes from the backend's own rank variable (OMPI_COMM_WORLD_RANK /
PMI_RANK / SLURM_PROCID — resolved by ``comm.init_distributed``) or, for
pdsh, from matching the local hostname against DSTPU_HOSTS.
"""

from __future__ import annotations

import os
import shlex
import sys
from collections import OrderedDict
from typing import Dict, List, Optional

DEFAULT_COORD_PORT = 29500

# the rank/size env contract lives with its consumer (comm.init_distributed)
from ..comm.comm import RANK_ENVS, SIZE_ENVS  # noqa: E402,F401


class MultiNodeRunner:
    """Base: shared env assembly (reference MultiNodeRunner, :55)."""

    name = "base"

    def __init__(self, hosts: "OrderedDict[str, int]",
                 master_addr: Optional[str] = None,
                 master_port: int = DEFAULT_COORD_PORT,
                 export_env: Optional[Dict[str, str]] = None):
        if not hosts:
            raise ValueError("no hosts")
        self.hosts = hosts
        self.master_addr = master_addr or next(iter(hosts))
        self.master_port = master_port
        self.export_env = dict(export_env or {})

    @property
    def n(self) -> int:
        return len(self.hosts)

    def base_env(self) -> Dict[str, str]:
        env = {
            "DSTPU_COORDINATOR": f"{self.master_addr}:{self.master_port}",
            "DSTPU_NUM_PROCESSES": str(self.n),
        }
        env.update(self.export_env)
        return env

    def backend_exists(self) -> bool:  # pragma: no cover - env dependent
        import shutil

        return shutil.which(self.launcher_binary) is not None

    launcher_binary = ""

    def get_cmd(self, script: str, script_args: List[str]) -> List[str]:
        raise NotImplementedError


def _script_part(script: str, script_args: List[str]) -> List[str]:
    return [sys.executable, "-u", script] + list(script_args)


class PDSHRunner(MultiNodeRunner):
    """pdsh fan-out (reference PDSHRunner, :92).  Process id comes from
    pdsh's ``%n`` substitution (the target's rank in the -w list) — no
    hostname matching, so IP/FQDN hostfiles work."""

    name = "pdsh"
    launcher_binary = "pdsh"

    def get_cmd(self, script: str, script_args: List[str]) -> List[str]:
        env = self.base_env()
        env["PDSH_RCMD_TYPE"] = env.get("PDSH_RCMD_TYPE", "ssh")
        env["DSTPU_PROCESS_ID"] = "%n"  # pdsh expands to the host's rank
        exports = " ".join(f"export {k}={shlex.quote(v) if v != '%n' else v};"
                           for k, v in env.items())
        inner = (f"{exports} cd {shlex.quote(os.getcwd())} && "
                 + " ".join(shlex.quote(p) for p in
                            _script_part(script, script_args)))
        return ["pdsh", "-S", "-f", "1024", "-w", ",".join(self.hosts), inner]


class OpenMPIRunner(MultiNodeRunner):
    """mpirun, one slot per host (reference OpenMPIRunner, :142).  Rank
    arrives as OMPI_COMM_WORLD_RANK."""

    name = "openmpi"
    launcher_binary = "mpirun"

    def get_cmd(self, script: str, script_args: List[str]) -> List[str]:
        cmd = ["mpirun", "-n", str(self.n), "--host",
               ",".join(f"{h}:1" for h in self.hosts),
               "--mca", "btl", "^openib"]  # NIC selection left to OMPI
        for k, v in self.base_env().items():
            cmd += ["-x", f"{k}={v}"]
        return cmd + _script_part(script, script_args)


class MPICHRunner(MultiNodeRunner):
    """mpiexec (hydra); rank arrives as PMI_RANK (reference MPICHRunner,
    :212)."""

    name = "mpich"
    launcher_binary = "mpiexec"

    def get_cmd(self, script: str, script_args: List[str]) -> List[str]:
        cmd = ["mpiexec", "-n", str(self.n), "-hosts", ",".join(self.hosts),
               "-ppn", "1"]
        for k, v in self.base_env().items():
            cmd += ["-genv", k, v]
        return cmd + _script_part(script, script_args)


class IMPIRunner(MPICHRunner):
    """Intel MPI: hydra-compatible (reference IMPIRunner, :260)."""

    name = "impi"


class SlurmRunner(MultiNodeRunner):
    """srun allocation launch (reference SlurmRunner, :322).  Rank arrives
    as SLURM_PROCID."""

    name = "slurm"
    launcher_binary = "srun"

    def get_cmd(self, script: str, script_args: List[str]) -> List[str]:
        cmd = ["srun", "--ntasks", str(self.n), "--ntasks-per-node", "1",
               "--nodelist", ",".join(self.hosts)]
        exports = self.base_env()
        cmd += [f"--export=ALL,{','.join(f'{k}={v}' for k, v in exports.items())}"]
        return cmd + _script_part(script, script_args)


class MVAPICHRunner(MultiNodeRunner):
    """mpirun_rsh (reference MVAPICHRunner, :360); rank arrives as
    MV2_COMM_WORLD_RANK.  The host list is written to a real temp hostfile
    (mpirun_rsh has no inline host syntax)."""

    name = "mvapich"
    launcher_binary = "mpirun_rsh"

    def get_cmd(self, script: str, script_args: List[str]) -> List[str]:
        import tempfile

        hf = tempfile.NamedTemporaryFile("w", prefix="dstpu_mvapich_",
                                         suffix=".hosts", delete=False)
        hf.write("\n".join(self.hosts) + "\n")
        hf.close()
        cmd = ["mpirun_rsh", "-np", str(self.n), "-hostfile", hf.name]
        for k, v in self.base_env().items():
            cmd += [f"{k}={v}"]
        return cmd + _script_part(script, script_args)


RUNNERS = {r.name: r for r in (PDSHRunner, OpenMPIRunner, MPICHRunner,
                               IMPIRunner, SlurmRunner, MVAPICHRunner)}


def get_runner(name: str, hosts: "OrderedDict[str, int]",
               **kw) -> MultiNodeRunner:
    if name not in RUNNERS:
        raise ValueError(f"unknown launcher backend {name!r}; "
                         f"available: {sorted(RUNNERS)}")
    return RUNNERS[name](hosts, **kw)
