"""Cluster launcher.

Reference: ``deepspeed`` CLI (launcher/runner.py:436 -> per-node
launch.py:145): hostfile parsing, include/exclude filters, pdsh/mpirun
multi-node, per-device process spawn with RANK/WORLD_SIZE env.

TPU model: ONE process per host (JAX drives all local chips), rendezvous via
``jax.distributed`` — the launcher assigns DSTPU_COORDINATOR /
DSTPU_NUM_PROCESSES / DSTPU_PROCESS_ID and execs the training script on
every host (ssh for multi-host, plain subprocess for single).
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import shlex
import subprocess
import sys
from collections import OrderedDict
from typing import Dict, List, Optional

from ..utils.logging import logger

DEFAULT_COORD_PORT = 29500


def parse_hostfile(path_or_text: str, is_text: bool = False) -> "OrderedDict[str, int]":
    """``host slots=N`` per line (reference fetch_hostfile, runner.py:230)."""
    text = path_or_text if is_text else open(path_or_text).read()
    hosts: "OrderedDict[str, int]" = OrderedDict()
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.split("#")[0].strip()
        if not line:
            continue
        parts = line.split()
        host = parts[0]
        slots = 1
        for p in parts[1:]:
            if p.startswith("slots="):
                slots = int(p.split("=", 1)[1])
        if host in hosts:
            raise ValueError(f"hostfile line {lineno}: duplicate host {host}")
        hosts[host] = slots
    if not hosts:
        raise ValueError("hostfile is empty")
    return hosts


def filter_hosts(hosts: "OrderedDict[str, int]", include: str = "",
                 exclude: str = "") -> "OrderedDict[str, int]":
    """``--include host1@host2`` / ``--exclude`` (reference parse_inclusion_exclusion,
    runner.py:310).  Slot-level filters (host:0,1) select chip subsets — on
    TPU chips aren't individually addressable per process, so only
    whole-host filtering is supported."""
    def parse(sel: str) -> List[str]:
        return [h.split(":")[0] for h in sel.split("@") if h]

    out = OrderedDict(hosts)
    if include:
        keep = parse(include)
        unknown = [h for h in keep if h not in hosts]
        if unknown:
            raise ValueError(f"--include hosts not in hostfile: {unknown}")
        out = OrderedDict((h, hosts[h]) for h in hosts if h in keep)
    if exclude:
        drop = parse(exclude)
        unknown = [h for h in drop if h not in hosts]
        if unknown:
            raise ValueError(f"--exclude hosts not in hostfile: {unknown}")
        out = OrderedDict((h, s) for h, s in out.items() if h not in drop)
    if not out:
        raise ValueError("no hosts remain after include/exclude filtering")
    return out


def build_launch_commands(hosts: "OrderedDict[str, int]", script: str,
                          script_args: List[str], master_addr: Optional[str] = None,
                          master_port: int = DEFAULT_COORD_PORT,
                          export_env: Optional[Dict[str, str]] = None,
                          ssh_port: int = 22) -> List[List[str]]:
    """One command per host (reference PDSHRunner.get_cmd equivalent)."""
    master_addr = master_addr or next(iter(hosts))
    n = len(hosts)
    cmds = []
    for pid, host in enumerate(hosts):
        env = {
            "DSTPU_COORDINATOR": f"{master_addr}:{master_port}",
            "DSTPU_NUM_PROCESSES": str(n),
            "DSTPU_PROCESS_ID": str(pid),
            "DSTPU_LOCAL_RANK": "0",
        }
        env.update(export_env or {})
        envstr = " ".join(f"{k}={shlex.quote(v)}" for k, v in env.items())
        inner = f"cd {shlex.quote(os.getcwd())} && {envstr} " \
                f"{shlex.quote(sys.executable)} -u {shlex.quote(script)} " + \
                " ".join(shlex.quote(a) for a in script_args)
        local = host in ("localhost", "127.0.0.1")
        if local and all(h in ("localhost", "127.0.0.1") for h in hosts):
            # ALL-local job (the reference's local num_gpus>1 launch):
            # spawn directly, no sshd needed.  Mixed local/remote jobs ssh
            # every rank so each gets the same clean login environment —
            # a bash-spawned local rank inheriting the launcher's shell
            # (XLA_FLAGS etc.) while remote ranks don't would desync the
            # rendezvous topology.
            cmds.append(["bash", "-c", inner])
        else:
            cmds.append(["ssh", "-p", str(ssh_port), host, inner])
    return cmds


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser("deepspeed_tpu.launcher")
    parser.add_argument("--hostfile", default=None)
    parser.add_argument("--include", default="")
    parser.add_argument("--exclude", default="")
    parser.add_argument("--master_addr", default=None)
    parser.add_argument("--master_port", type=int, default=DEFAULT_COORD_PORT)
    parser.add_argument("--ssh_port", type=int, default=22)
    parser.add_argument("--force_multi", action="store_true")
    parser.add_argument("--launcher", default="ssh",
                        choices=["ssh", "pdsh", "openmpi", "mpich", "impi",
                                 "slurm", "mvapich"],
                        help="multinode backend (reference multinode_runner)")
    parser.add_argument("--elastic_training", action="store_true",
                        help="watchdog relaunch on failure with per-attempt "
                             "host re-discovery (reference DSElasticAgent)")
    parser.add_argument("--max_elastic_restarts", type=int, default=3)
    parser.add_argument("script")
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    if args.elastic_training:
        if args.launcher != "ssh":
            parser.error("--elastic_training currently relaunches over "
                         "ssh only; --launcher "
                         f"{args.launcher} is not supported with it")
        from ..elasticity.elastic_agent import ElasticAgent

        agent = ElasticAgent(hostfile=args.hostfile, include=args.include,
                             exclude=args.exclude,
                             max_restarts=args.max_elastic_restarts,
                             master_addr=args.master_addr,
                             master_port=args.master_port,
                             ssh_port=args.ssh_port)
        return agent.run(args.script, args.script_args)

    if args.hostfile:
        hosts = filter_hosts(parse_hostfile(args.hostfile), args.include, args.exclude)
    else:
        hosts = OrderedDict([("localhost", 1)])

    if args.launcher != "ssh":
        from .multinode_runner import get_runner

        runner = get_runner(args.launcher, hosts,
                            master_addr=args.master_addr,
                            master_port=args.master_port)
        cmd = runner.get_cmd(args.script, args.script_args)
        logger.info(f"launcher[{args.launcher}]: {' '.join(cmd)}")
        return subprocess.call(cmd)

    cmds = build_launch_commands(hosts, args.script, args.script_args,
                                 args.master_addr, args.master_port,
                                 ssh_port=args.ssh_port)
    procs = [subprocess.Popen(cmd) for cmd in cmds]
    rc = 0
    for p in procs:
        p.wait()
        rc = rc or p.returncode
    return rc


if __name__ == "__main__":
    sys.exit(main())
