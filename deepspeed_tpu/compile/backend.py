"""DeepCompile equivalent: compiler passes over the training step.

Reference parity: ``deepspeed/compile/`` — a torch.compile backend
(compile/backend.py) plus graph passes (compile/passes/): ``zero3_compile``
(turn ZeRO-3 hooks into graph ops), ``prefetch`` (schedule allgathers
early), ``selective_gather`` (keep hot params resident), and
``offload_adam_states`` / ``offload_activation`` (move state/activations to
host inside the compiled graph), with C++ runtime support in
csrc/compile/.  The engine API is ``engine.compile()`` (engine.py:4243).

On TPU the training step is *already* one compiled XLA program, so the
first three passes are the compiler's own job: XLA SPMD schedules the
ZeRO allgathers/reduce-scatters and its latency-hiding scheduler overlaps
them with compute — there is nothing to rewrite, and those passes reduce
to (logged) no-ops kept for config/API parity.  The passes that *do* have
a TPU-side transformation:

* ``offload_adam_states`` — re-place the optimizer-state pytree in host
  memory (``memory_kind='pinned_host'``) and re-jit the step so XLA
  streams moments in/out around the update (reference
  compile/passes/offload_adam_states.py).
* ``offload_activation``  — rebuild the model's remat policy to
  rematerialize (and where supported, host-offload) activations
  (reference compile/passes/offload_activation.py).

Every pass is ``(engine) -> None`` and is recorded on
``engine.compile_passes_applied``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterable, List, Optional

import jax

from ..utils.logging import logger

PassFn = Callable[[Any], None]
PASS_REGISTRY: Dict[str, PassFn] = {}

#: XLA flags that let the TPU scheduler actually hide the in-loop
#: collectives the overlap wrap issues (runtime/zero/overlap.py): the
#: latency-hiding scheduler reorders collective-starts ahead of
#: consuming compute, and async collective fusion keeps the gather /
#: reduce-scatter wavefronts asynchronous.  These are the BACKSTOP for
#: whatever XLA can already reorder — pinned (not merely hoped for) by
#: bench.py for TPU child processes and validated by the engine when an
#: overlap plan is active.  Flag set, not behavior, is asserted: the
#: values only take effect when present in XLA_FLAGS before backend
#: init.
LATENCY_HIDING_FLAGS: Dict[str, str] = {
    "--xla_tpu_enable_latency_hiding_scheduler": "true",
    "--xla_tpu_enable_async_collective_fusion": "true",
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather": "true",
}


def parse_xla_flags(flags: Optional[str]) -> Dict[str, str]:
    """``XLA_FLAGS`` string -> {flag: value} (bare flags map to "true")."""
    out: Dict[str, str] = {}
    for tok in (flags or "").split():
        if "=" in tok:
            k, v = tok.split("=", 1)
            out[k] = v
        elif tok.startswith("--"):
            out[tok] = "true"
    return out


def latency_hiding_flag_status(env: Optional[Dict[str, str]] = None
                               ) -> Dict[str, str]:
    """Per-flag status against :data:`LATENCY_HIDING_FLAGS`:
    ``"pinned"`` (present with the recommended value), ``"missing"``,
    or ``"overridden=<value>"`` (present with another value — an
    explicit operator choice, reported but never clobbered)."""
    import os

    env = os.environ if env is None else env
    current = parse_xla_flags(env.get("XLA_FLAGS", ""))
    status = {}
    for flag, want in LATENCY_HIDING_FLAGS.items():
        if flag not in current:
            status[flag] = "missing"
        elif current[flag].lower() == want:
            status[flag] = "pinned"
        else:
            status[flag] = f"overridden={current[flag]}"
    return status


def pin_latency_hiding_flags(env: Optional[Dict[str, str]] = None
                             ) -> List[str]:
    """Append the missing latency-hiding flags to ``env["XLA_FLAGS"]``
    and return what was added.  Only meaningful BEFORE the XLA backend
    initializes (bench.py pins for its TPU child processes); explicit
    operator overrides are left alone.  TPU-only flags — never pin into
    a CPU process, where unknown flags abort backend init."""
    import os

    env = os.environ if env is None else env
    status = latency_hiding_flag_status(env)
    added = [f"{flag}={want}" for flag, want in LATENCY_HIDING_FLAGS.items()
             if status[flag] == "missing"]
    if added:
        env["XLA_FLAGS"] = " ".join(
            [env.get("XLA_FLAGS", "").strip()] + added).strip()
    return added


def validate_latency_hiding_flags() -> Dict[str, str]:
    """Engine-side check (the backend is already up, so this can only
    REPORT): warn when an overlap plan is active on TPU but the
    scheduler flags are not pinned — the in-loop collectives would then
    rely on default scheduling to hide."""
    import jax

    status = latency_hiding_flag_status()
    if jax.default_backend() != "tpu":
        return status
    missing = [f for f, s in status.items() if s == "missing"]
    if missing:
        logger.warning(
            "compute/collective overlap is active but the XLA "
            f"latency-hiding flags are not pinned ({missing}); set them "
            "in XLA_FLAGS before process start (bench.py pins them for "
            "its TPU children; see docs/COMM.md 'Overlap & scheduling')")
    return status


def shape_signature(*trees: Any) -> tuple:
    """Hashable ``(shape, dtype)`` signature of the array leaves of
    ``trees`` — the arg-shape key the recompilation sentinel
    (telemetry/compile_sentinel.py) attributes compiles with: a jitted
    program retraces exactly when this signature (or a static arg)
    changes, so an unchanged signature that still compiled is the
    steady-state-recompile smell.  Host-side only: reads ``.shape`` /
    ``.dtype`` avals, never device values."""
    parts = []
    for tree in trees:
        for leaf in jax.tree_util.tree_leaves(tree):
            parts.append((tuple(getattr(leaf, "shape", ())),
                          str(getattr(leaf, "dtype",
                                      type(leaf).__name__))))
    return tuple(parts)


def _register(name: str):
    def deco(fn: PassFn) -> PassFn:
        PASS_REGISTRY[name] = fn
        return fn

    return deco


@_register("zero3_compile")
def _zero3_compile(engine) -> None:
    """ZeRO-3 gather/release as graph ops: on XLA the sharded step IS the
    graph; param allgathers are inserted by SPMD partitioning already."""
    logger.info("compile pass zero3_compile: handled by XLA SPMD partitioner "
                "(sharded train step is already one graph)")


@_register("prefetch")
def _prefetch(engine) -> None:
    """Early allgather scheduling: XLA's latency-hiding scheduler moves
    collective-starts ahead of consuming compute on TPU."""
    logger.info("compile pass prefetch: handled by the XLA latency-hiding "
                "scheduler")


@_register("selective_gather")
def _selective_gather(engine) -> None:
    """Keeping hot params resident: covered by the persistence-threshold
    behavior of the sharding plan (small params replicate, see
    zero/strategy.py)."""
    logger.info("compile pass selective_gather: small parameters already "
                "replicate under the sharding plan's persistence threshold")



def _pin_tree_to_host(engine, tree, what: str):
    """device_put every array leaf of ``tree`` into pinned host memory
    (scalars stay committed on device — annotating their placement trips
    the SPMD partitioner).  Returns the re-placed tree, or None with a
    warning where the backend lacks host memory spaces."""
    from jax.sharding import NamedSharding, PartitionSpec

    scalar_sh = NamedSharding(engine.topology.mesh, PartitionSpec())

    def to_host(x):
        if not hasattr(x, "sharding") or getattr(x, "ndim", 0) < 1:
            return jax.device_put(x, scalar_sh) if hasattr(x, "sharding") else x
        try:
            return jax.device_put(x, x.sharding.with_memory_kind("pinned_host"))
        except Exception as e:
            raise NotImplementedError(
                f"host memory spaces unavailable on this backend: {e}") from e

    try:
        return jax.tree_util.tree_map(to_host, tree)
    except NotImplementedError as e:
        logger.warning(f"{what} unavailable: {e}")
        return None


@_register("offload_adam_states")
def _offload_adam_states(engine) -> None:
    """Pin optimizer moments in host memory; XLA streams them through the
    update (reference compile/passes/offload_adam_states.py)."""
    state = engine.state
    if not state.opt_state:
        logger.warning("offload_adam_states: no device optimizer state "
                       "(host offload already active?); skipping")
        return
    new_opt = _pin_tree_to_host(engine, state.opt_state, "offload_adam_states")
    if new_opt is None:
        return
    import dataclasses as _dc

    engine.state = _dc.replace(state, opt_state=new_opt)
    # re-jit; on TPU the step program writes updated moments straight back
    # to host memory (out_shardings), on host platforms the engine re-pins
    # them eagerly after each boundary (_repin_opt_state)
    engine._compile_steps(opt_state_memory_kind="pinned_host")
    logger.info("compile pass offload_adam_states: optimizer state pinned "
                "to host memory")


@_register("offload_params")
def _offload_params(engine) -> None:
    """Pin the fp32 master params in host memory (ZeRO-Infinity
    ``offload_param``, reference zero/partition_parameters NVMe/CPU param
    path): XLA streams each step's param reads from pinned host memory, so
    HBM holds only activations + transient gathers.  Config-gated via
    zero_optimization.offload_param.device (engine __init__), also
    available as an explicit compile pass."""
    state = engine.state
    new_params = _pin_tree_to_host(engine, state.params, "offload_params")
    if new_params is None:
        return
    import dataclasses as _dc

    engine.state = _dc.replace(state, params=new_params)
    engine._compile_steps(param_memory_kind="pinned_host")
    logger.info("compile pass offload_params: master params pinned to host "
                "memory")


@_register("offload_activation")
def _offload_activation(engine) -> None:
    """Rematerialize activations (host-offload where the model supports it)
    — reference compile/passes/offload_activation.py."""
    model = engine.model
    cfg = getattr(model, "config", None)
    if cfg is None or not hasattr(cfg, "remat"):
        logger.warning("offload_activation: model has no remat-capable "
                       "config; skipping")
        return
    # mutate in place: the model's loss_fn closure captured this config
    # object, so the rebuilt step traces with the new remat policy
    cfg.remat = True
    cfg.remat_policy = "nothing_saveable"
    engine._compile_steps()
    logger.info("compile pass offload_activation: remat enabled "
                "(nothing_saveable policy)")


DEFAULT_PASSES = ("zero3_compile", "prefetch", "selective_gather")


def compile_engine(engine, backend: str = "xla",
                   passes: Optional[Iterable[str]] = None) -> Any:
    """``engine.compile()`` (reference engine.py:4243, compile/backend.py).

    Applies the named passes in order; unknown names raise.  Returns the
    engine for chaining.
    """
    if backend not in ("xla", "inductor", "eager"):
        raise ValueError(f"unknown compile backend '{backend}'")
    names: List[str] = list(passes if passes is not None else DEFAULT_PASSES)
    applied = []
    from ..telemetry.compile_sentinel import expect_recompile

    for name in names:
        if name not in PASS_REGISTRY:
            raise KeyError(f"unknown compile pass '{name}'; "
                           f"known: {sorted(PASS_REGISTRY)}")
        PASS_REGISTRY[name](engine)
        # a pass that re-jits the step legitimately compiles on the next
        # call — tell the sentinel so it is not flagged as steady-state
        expect_recompile(f"compile_pass:{name}")
        applied.append(name)
    existing = list(getattr(engine, "compile_passes_applied", []))
    engine.compile_passes_applied = existing + applied
    engine.is_compiled = True
    return engine
