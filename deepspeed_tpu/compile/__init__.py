from .backend import PASS_REGISTRY, compile_engine  # noqa: F401
