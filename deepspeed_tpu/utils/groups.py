"""Mesh-axis group arithmetic (reference ``deepspeed/utils/groups.py``).

The reference carves torch process groups out of the world; on TPU the
mesh axes already name every parallel group, so what is left to own here
is the *hierarchy split*: dividing one mesh axis of size ``world`` into
``inner`` (intra-slice, fast ICI) x ``outer`` (inter-slice, slow DCN)
rank groups for the two-hop collectives in
``comm/collectives/hierarchical.py`` (ZeRO++ hpZ-style, PAPERS.md).

Groups are expressed as ``axis_index_groups`` lists for ``jax.lax``
collectives — ranks are indices along the named axis, contiguous runs of
``inner`` form a slice (how ``mesh_utils.create_device_mesh`` lays
slices out along an axis).
"""

from __future__ import annotations

import math
import os
from typing import List, Optional, Tuple


def hierarchy_split(world: int, inner: Optional[int] = None
                    ) -> Tuple[int, int]:
    """Split a ``world``-rank axis into ``(inner, outer)`` groups.

    ``inner`` explicit: validated (must divide ``world``, 1 < inner <
    world).  ``inner=None``: auto — prefer the local-device count (the
    physical slice boundary) when it yields a real split, else the
    largest divisor <= sqrt(world).  Raises when no split exists
    (world < 4 or prime).
    """
    if world < 4:
        raise ValueError(
            f"hierarchy_split: a {world}-rank axis has no two-hop split "
            "(needs world >= 4)")
    if inner is not None:
        if inner <= 1 or inner >= world or world % inner:
            raise ValueError(
                f"hierarchy_split: inner={inner} must divide world="
                f"{world} with 1 < inner < world")
        return inner, world // inner
    env = os.environ.get("DSTPU_HIERARCHY_INNER")
    if env:
        return hierarchy_split(world, int(env))
    try:
        import jax

        local = jax.local_device_count()
    except Exception:
        local = 0
    if 1 < local < world and world % local == 0:
        return local, world // local
    root = int(math.isqrt(world))
    for cand in range(root, 1, -1):
        if world % cand == 0:
            return cand, world // cand
    raise ValueError(f"hierarchy_split: world={world} is prime; no split")


def inner_groups(world: int, inner: int) -> List[List[int]]:
    """Contiguous intra-slice groups: ``[[0..inner-1], [inner..], ...]``."""
    outer = world // inner
    return [[s * inner + i for i in range(inner)] for s in range(outer)]


def outer_groups(world: int, inner: int) -> List[List[int]]:
    """Strided inter-slice groups: rank ``s*inner + i`` talks to every
    other slice's rank ``i`` — the peers holding the same intra-slice
    scatter slot."""
    outer = world // inner
    return [[s * inner + i for s in range(outer)] for i in range(inner)]
