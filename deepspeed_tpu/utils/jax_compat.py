"""Version compatibility shims over moved/renamed jax APIs.

One place to absorb jax API churn instead of try/except at every call
site.  Currently: ``shard_map``, which graduated from
``jax.experimental.shard_map.shard_map(f, mesh, in_specs, out_specs,
check_rep=..., auto=...)`` to ``jax.shard_map(f, mesh=..., in_specs=...,
out_specs=..., check_vma=..., axis_names=...)``.  Callers use the NEW
spelling; on older jax the kwargs are translated (``check_vma`` ->
``check_rep``; ``axis_names`` — the axes handled manually — becomes its
complement ``auto``).
"""

from __future__ import annotations

import jax


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True,
              axis_names=None):
    """``jax.shard_map`` with old-jax fallback (new-API kwargs)."""
    try:
        sm = jax.shard_map
    except AttributeError:
        sm = None
    if sm is not None:
        kw = {"check_vma": check_vma}
        if axis_names is not None:
            kw["axis_names"] = axis_names
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as legacy

    kw = {"check_rep": check_vma}
    if axis_names is not None:
        kw["auto"] = frozenset(mesh.axis_names) - frozenset(axis_names)
    return legacy(f, mesh, in_specs=in_specs, out_specs=out_specs, **kw)
