"""Rank-aware logging.

TPU-native analogue of the reference's ``deepspeed/utils/logging.py``: a
process-wide logger plus ``log_dist`` which only emits on the requested
process indices (JAX process index replaces torch.distributed rank).
"""

from __future__ import annotations

import logging
import os
import sys
from typing import Iterable, Optional

log_levels = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "critical": logging.CRITICAL,
}


def _create_logger(name: str = "DeepSpeedTPU", level: int = logging.INFO) -> logging.Logger:
    lg = logging.getLogger(name)
    lg.setLevel(level)
    lg.propagate = False
    if not lg.handlers:
        handler = logging.StreamHandler(stream=sys.stdout)
        handler.setFormatter(
            logging.Formatter("[%(asctime)s] [%(levelname)s] [%(name)s] %(message)s"))
        lg.addHandler(handler)
    return lg


#: level env override, in priority order: the spelled-out name first,
#: then the short historical one.  Values are the ``log_levels`` names
#: ("debug" ... "critical", case-insensitive); unknown values fall back
#: to info rather than failing an import.
LEVEL_ENVS = ("DEEPSPEED_TPU_LOG_LEVEL", "DSTPU_LOG_LEVEL")


def _env_log_level(default: int = logging.INFO) -> int:
    for name in LEVEL_ENVS:
        v = os.environ.get(name)
        if v:
            return log_levels.get(v.strip().lower(), default)
    return default


logger = _create_logger(level=_env_log_level())


def _process_index() -> int:
    # Deferred import: logging must be importable before jax initializes.
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def log_dist(message: str, ranks: Optional[Iterable[int]] = None, level: int = logging.INFO) -> None:
    """Log ``message`` only on the given process indices (default: process 0).

    ``ranks=[-1]`` logs on every process.
    """
    ranks = list(ranks) if ranks is not None else [0]
    my_rank = _process_index()
    if -1 in ranks or my_rank in ranks:
        logger.log(level, f"[Rank {my_rank}] {message}")


#: messages already emitted by warning_once (module-level, not a default
#: argument: a mutable default is invisible shared state at the call site)
_WARNED_ONCE: set = set()


def warning_once(message: str) -> None:
    if message not in _WARNED_ONCE:
        _WARNED_ONCE.add(message)
        logger.warning(message)
