"""Wall-clock timers and throughput accounting.

Analogue of the reference ``SynchronizedWallClockTimer`` / ``ThroughputTimer``
(``deepspeed/utils/timer.py``).  "Synchronized" on TPU means blocking on the
result of the last dispatched computation (``block_until_ready``) instead of
``cuda.synchronize``.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

import jax

from .logging import logger

FORWARD_MICRO_TIMER = "fwd_microstep"
FORWARD_GLOBAL_TIMER = "fwd"
BACKWARD_MICRO_TIMER = "bwd_microstep"
BACKWARD_GLOBAL_TIMER = "bwd"
STEP_MICRO_TIMER = "step_microstep"
STEP_GLOBAL_TIMER = "step"


_SYNC_PROGRAM = None


def _device_sync() -> None:
    """Block until previously dispatched device work completes.

    ``jax.effects_barrier`` only flushes *effects* (io_callback and
    friends) — it does NOT wait on pending computations, so it cannot
    close a timing window on an async backend.  A bare
    ``device_put(0.0)`` is not enough either: host-to-device transfers
    ride the transfer path, not the compute queue, so they can complete
    while a long program is still running.  Enqueue a tiny COMPILED
    program instead — per-device program execution is in dispatch order,
    so blocking on its output orders behind all previously dispatched
    computations (the ``cuda.synchronize`` analogue this module's
    docstring promises)."""
    global _SYNC_PROGRAM
    if _SYNC_PROGRAM is None:
        import jax.numpy as jnp

        _SYNC_PROGRAM = jax.jit(lambda: jnp.zeros(()))
    _SYNC_PROGRAM().block_until_ready()


class _Timer:
    def __init__(self, name: str, sink=None):
        self.name = name
        self.started = False
        self._start = 0.0
        self._elapsed = 0.0
        self.count = 0
        #: optional ``(name, seconds)`` callback fired on every stop —
        #: how phase times reach the telemetry registry
        self.sink = sink

    def start(self):
        if self.started:
            return
        self.started = True
        self._start = time.perf_counter()

    def stop(self, sync: bool = False):
        if not self.started:
            return
        if sync:
            _device_sync()
        dt = time.perf_counter() - self._start
        self._elapsed += dt
        self.count += 1
        self.started = False
        if self.sink is not None:
            self.sink(self.name, dt)

    def elapsed(self, reset: bool = True) -> float:
        e = self._elapsed
        if reset:
            self._elapsed = 0.0
            self.count = 0
        return e

    def mean(self) -> float:
        return self._elapsed / max(1, self.count)


class SynchronizedWallClockTimer:
    def __init__(self, sink=None):
        self.timers: Dict[str, _Timer] = {}
        #: per-stop ``(name, seconds)`` callback installed on every timer
        self.sink = sink

    def __call__(self, name: str) -> _Timer:
        if name not in self.timers:
            self.timers[name] = _Timer(name, sink=self.sink)
        return self.timers[name]

    def log(self, names: List[str], normalizer: float = 1.0, reset: bool = True,
            memory_breakdown: bool = False) -> None:
        parts = []
        for name in names:
            if name in self.timers:
                ms = self.timers[name].elapsed(reset) * 1000.0 / normalizer
                parts.append(f"{name}: {ms:.2f}ms")
        if parts:
            logger.info(" | ".join(parts))

    def get_mean(self, names: List[str]) -> Dict[str, float]:
        return {n: self.timers[n].mean() for n in names if n in self.timers}


class ThroughputTimer:
    """samples/sec + tokens/sec reporting (reference utils/timer.py:~200)."""

    def __init__(self, batch_size: int, steps_per_output: int = 10, monitor_memory=False,
                 logging_fn=None):
        self.batch_size = max(1, batch_size)
        self.steps_per_output = steps_per_output
        self.logging = logging_fn or logger.info
        self.global_step_count = 0
        self.total_elapsed = 0.0
        self._start = None
        # per-window stats: the engine only DRAINS the device queue at the
        # reporting boundary, so a single step's dt is async-dispatch noise;
        # the window [boundary, boundary] is real wall time
        self._win_elapsed = 0.0
        self._win_steps = 0

    def start(self):
        self._start = time.perf_counter()

    def stop(self, global_step: bool = True, report_speed: bool = True):
        if self._start is None:
            return
        dt = time.perf_counter() - self._start
        self._start = None
        if global_step:
            self.global_step_count += 1
            self.total_elapsed += dt
            self._win_elapsed += dt
            self._win_steps += 1
            if report_speed and self.global_step_count % self.steps_per_output == 0:
                per_step = self._win_elapsed / max(self._win_steps, 1)
                win_sps = self._win_steps * self.batch_size / \
                    max(self._win_elapsed, 1e-9)
                self.logging(
                    f"step={self.global_step_count} "
                    f"samples/sec={win_sps:.2f} "
                    f"iter_time={per_step * 1000:.1f}ms")
                self._win_elapsed = 0.0
                self._win_steps = 0

    def avg_samples_per_sec(self) -> float:
        if self.total_elapsed == 0:
            return 0.0
        return self.global_step_count * self.batch_size / self.total_elapsed
