"""Metrics monitor.

Analogue of ``MonitorMaster`` (reference monitor/monitor.py:30): fans
``(tag, value, step)`` events out to TensorBoard / W&B / CSV writers on
process 0 only.  TensorBoard and W&B degrade gracefully when the packages
are absent (CSV always works).
"""

from __future__ import annotations

import csv
import os
from typing import List, Optional, Tuple

import jax

from ..utils.logging import logger

Event = Tuple[str, float, int]


class Monitor:
    def write_events(self, events: List[Event]) -> None:
        raise NotImplementedError


class CSVMonitor(Monitor):
    def __init__(self, output_path: str, job_name: str):
        self.dir = os.path.join(output_path or "./csv_monitor", job_name)
        os.makedirs(self.dir, exist_ok=True)
        self._files = {}

    def write_events(self, events: List[Event]) -> None:
        for tag, value, step in events:
            fname = os.path.join(self.dir, tag.replace("/", "_") + ".csv")
            new = not os.path.exists(fname)
            with open(fname, "a", newline="") as f:
                w = csv.writer(f)
                if new:
                    w.writerow(["step", tag])
                w.writerow([step, value])


class TensorBoardMonitor(Monitor):
    def __init__(self, output_path: str, job_name: str):
        from torch.utils.tensorboard import SummaryWriter  # torch cpu is baked in

        self.writer = SummaryWriter(log_dir=os.path.join(output_path or "./runs", job_name))

    def write_events(self, events: List[Event]) -> None:
        for tag, value, step in events:
            self.writer.add_scalar(tag, value, step)
        self.writer.flush()


class WandbMonitor(Monitor):
    def __init__(self, project: str, group, team):
        import wandb

        wandb.init(project=project, group=group, entity=team)
        self._wandb = wandb

    def write_events(self, events: List[Event]) -> None:
        for tag, value, step in events:
            self._wandb.log({tag: value}, step=step)


class CometMonitor(Monitor):
    """comet_ml writer (reference monitor/comet.py): modern API via
    comet_ml.start (online/mode/name ride there), Experiment fallback for
    old installs; events throttled to every ``samples_log_interval``-th
    step like the reference."""

    def __init__(self, cfg):
        import comet_ml

        self._interval = max(1, int(getattr(cfg, "samples_log_interval", 1) or 1))
        base = {k: v for k, v in (("api_key", cfg.api_key),
                                  ("workspace", cfg.workspace)) if v}
        if hasattr(comet_ml, "start"):
            kw = dict(base, project=cfg.project)
            for name in ("online", "mode", "experiment_key"):
                v = getattr(cfg, name, None)
                if v is not None:
                    kw[name] = v
            self._exp = comet_ml.start(**{k: v for k, v in kw.items()
                                          if v is not None})
        else:  # legacy comet_ml: Experiment takes project_name only
            kw = dict(base)
            if cfg.project:
                kw["project_name"] = cfg.project
            self._exp = comet_ml.Experiment(**kw)
        if getattr(cfg, "experiment_name", None):
            try:
                self._exp.set_name(cfg.experiment_name)
            except Exception:
                pass

    def write_events(self, events: List[Event]) -> None:
        for tag, value, step in events:
            if step % self._interval == 0:
                self._exp.log_metric(tag, value, step=step)


class MonitorMaster(Monitor):
    def __init__(self, config):
        self.monitors: List[Monitor] = []
        if jax.process_index() != 0:
            return
        if config.csv_monitor.enabled:
            self.monitors.append(CSVMonitor(config.csv_monitor.output_path,
                                            config.csv_monitor.job_name))
        if config.tensorboard.enabled:
            try:
                self.monitors.append(TensorBoardMonitor(
                    config.tensorboard.output_path, config.tensorboard.job_name))
            except Exception as e:  # tensorboard not installed
                logger.warning(f"TensorBoard monitor unavailable: {e}")
        if config.wandb.enabled:
            try:
                self.monitors.append(WandbMonitor(config.wandb.project,
                                                  config.wandb.group, config.wandb.team))
            except Exception as e:
                logger.warning(f"W&B monitor unavailable: {e}")
        if getattr(config, "comet", None) is not None and config.comet.enabled:
            try:
                self.monitors.append(CometMonitor(config.comet))
            except Exception as e:  # comet_ml not installed
                logger.warning(f"Comet monitor unavailable: {e}")

    @property
    def enabled(self) -> bool:
        return bool(self.monitors)

    def write_events(self, events: List[Event]) -> None:
        for m in self.monitors:
            m.write_events(events)
