"""Metrics monitor.

Analogue of ``MonitorMaster`` (reference monitor/monitor.py:30): fans
``(tag, value, step)`` events out to TensorBoard / W&B / CSV writers on
process 0 only.  TensorBoard and W&B degrade gracefully when the packages
are absent (CSV always works).
"""

from __future__ import annotations

import csv
import os
from typing import List, Optional, Tuple

import jax

from ..utils.logging import logger

Event = Tuple[str, float, int]


class Monitor:
    def write_events(self, events: List[Event]) -> None:
        raise NotImplementedError


class CSVMonitor(Monitor):
    def __init__(self, output_path: str, job_name: str):
        self.dir = os.path.join(output_path or "./csv_monitor", job_name)
        os.makedirs(self.dir, exist_ok=True)
        self._files = {}

    def write_events(self, events: List[Event]) -> None:
        for tag, value, step in events:
            fname = os.path.join(self.dir, tag.replace("/", "_") + ".csv")
            new = not os.path.exists(fname)
            with open(fname, "a", newline="") as f:
                w = csv.writer(f)
                if new:
                    w.writerow(["step", tag])
                w.writerow([step, value])


class TensorBoardMonitor(Monitor):
    def __init__(self, output_path: str, job_name: str):
        from torch.utils.tensorboard import SummaryWriter  # torch cpu is baked in

        self.writer = SummaryWriter(log_dir=os.path.join(output_path or "./runs", job_name))

    def write_events(self, events: List[Event]) -> None:
        for tag, value, step in events:
            self.writer.add_scalar(tag, value, step)
        self.writer.flush()


class WandbMonitor(Monitor):
    def __init__(self, project: str, group, team):
        import wandb

        wandb.init(project=project, group=group, entity=team)
        self._wandb = wandb

    def write_events(self, events: List[Event]) -> None:
        for tag, value, step in events:
            self._wandb.log({tag: value}, step=step)


class MonitorMaster(Monitor):
    def __init__(self, config):
        self.monitors: List[Monitor] = []
        if jax.process_index() != 0:
            return
        if config.csv_monitor.enabled:
            self.monitors.append(CSVMonitor(config.csv_monitor.output_path,
                                            config.csv_monitor.job_name))
        if config.tensorboard.enabled:
            try:
                self.monitors.append(TensorBoardMonitor(
                    config.tensorboard.output_path, config.tensorboard.job_name))
            except Exception as e:  # tensorboard not installed
                logger.warning(f"TensorBoard monitor unavailable: {e}")
        if config.wandb.enabled:
            try:
                self.monitors.append(WandbMonitor(config.wandb.project,
                                                  config.wandb.group, config.wandb.team))
            except Exception as e:
                logger.warning(f"W&B monitor unavailable: {e}")

    @property
    def enabled(self) -> bool:
        return bool(self.monitors)

    def write_events(self, events: List[Event]) -> None:
        for m in self.monitors:
            m.write_events(events)
