"""Metrics monitor.

Analogue of ``MonitorMaster`` (reference monitor/monitor.py:30): fans
``(tag, value, step)`` events out to TensorBoard / W&B / CSV writers on
process 0 only.  TensorBoard and W&B degrade gracefully when the packages
are absent (CSV always works).
"""

from __future__ import annotations

import csv
import os
from typing import List, Optional, Tuple

import jax

from ..utils.logging import logger

Event = Tuple[str, float, int]


class Monitor:
    def write_events(self, events: List[Event]) -> None:
        raise NotImplementedError

    def close(self) -> None:
        """Release writer resources (file handles, background threads)."""


class CSVMonitor(Monitor):
    """One CSV per tag.  File handles are opened once per tag and kept —
    a per-event open/close costs a syscall storm at high tag cardinality
    (the registry fan-out emits dozens of tags per step).  Each
    ``write_events`` batch ends with an explicit flush of the touched
    handles so readers (tests, tail -f dashboards) see complete rows."""

    def __init__(self, output_path: str, job_name: str):
        self.dir = os.path.join(output_path or "./csv_monitor", job_name)
        os.makedirs(self.dir, exist_ok=True)
        self._files = {}    # tag -> open file handle
        self._writers = {}  # tag -> csv.writer over that handle

    def _writer(self, tag: str):
        w = self._writers.get(tag)
        if w is None:
            safe = tag.replace("/", "_").replace("=", "-")
            fname = os.path.join(self.dir, safe + ".csv")
            # header exactly once: only when the file is created empty
            # (appending to a previous run's file must not re-header)
            new = not os.path.exists(fname) or os.path.getsize(fname) == 0
            f = open(fname, "a", newline="")
            self._files[tag] = f
            w = self._writers[tag] = csv.writer(f)
            if new:
                w.writerow(["step", tag])
        return w

    def write_events(self, events: List[Event]) -> None:
        touched = set()
        for tag, value, step in events:
            self._writer(tag).writerow([step, value])
            touched.add(tag)
        for tag in touched:
            self._files[tag].flush()

    def close(self) -> None:
        for f in self._files.values():
            try:
                f.close()
            # dstpu-lint: allow[swallow] teardown flush is best-effort; one
            # broken writer handle must not block closing the rest
            except Exception:
                pass
        self._files.clear()
        self._writers.clear()


class TensorBoardMonitor(Monitor):
    def __init__(self, output_path: str, job_name: str):
        from torch.utils.tensorboard import SummaryWriter  # torch cpu is baked in

        self.writer = SummaryWriter(log_dir=os.path.join(output_path or "./runs", job_name))

    def write_events(self, events: List[Event]) -> None:
        for tag, value, step in events:
            self.writer.add_scalar(tag, value, step)
        self.writer.flush()

    def close(self) -> None:
        self.writer.close()


class WandbMonitor(Monitor):
    def __init__(self, project: str, group, team):
        import wandb

        wandb.init(project=project, group=group, entity=team)
        self._wandb = wandb

    def write_events(self, events: List[Event]) -> None:
        for tag, value, step in events:
            self._wandb.log({tag: value}, step=step)

    def close(self) -> None:
        self._wandb.finish()


class CometMonitor(Monitor):
    """comet_ml writer (reference monitor/comet.py): modern API via
    comet_ml.start (online/mode/name ride there), Experiment fallback for
    old installs; events throttled to every ``samples_log_interval``-th
    step like the reference."""

    def __init__(self, cfg):
        import comet_ml

        self._interval = max(1, int(getattr(cfg, "samples_log_interval", 1) or 1))
        base = {k: v for k, v in (("api_key", cfg.api_key),
                                  ("workspace", cfg.workspace)) if v}
        if hasattr(comet_ml, "start"):
            kw = dict(base, project=cfg.project)
            for name in ("online", "mode", "experiment_key"):
                v = getattr(cfg, name, None)
                if v is not None:
                    kw[name] = v
            self._exp = comet_ml.start(**{k: v for k, v in kw.items()
                                          if v is not None})
        else:  # legacy comet_ml: Experiment takes project_name only
            kw = dict(base)
            if cfg.project:
                kw["project_name"] = cfg.project
            self._exp = comet_ml.Experiment(**kw)
        if getattr(cfg, "experiment_name", None):
            try:
                self._exp.set_name(cfg.experiment_name)
            # dstpu-lint: allow[swallow] cosmetic experiment rename on a
            # third-party client; the run proceeds under the default name
            except Exception:
                pass

    def write_events(self, events: List[Event]) -> None:
        for tag, value, step in events:
            if step % self._interval == 0:
                self._exp.log_metric(tag, value, step=step)


class MonitorMaster(Monitor):
    def __init__(self, config):
        self.monitors: List[Monitor] = []
        if jax.process_index() != 0:
            return
        if config.csv_monitor.enabled:
            self.monitors.append(CSVMonitor(config.csv_monitor.output_path,
                                            config.csv_monitor.job_name))
        if config.tensorboard.enabled:
            try:
                self.monitors.append(TensorBoardMonitor(
                    config.tensorboard.output_path, config.tensorboard.job_name))
            except Exception as e:  # tensorboard not installed
                logger.warning(f"TensorBoard monitor unavailable: {e}")
        if config.wandb.enabled:
            try:
                self.monitors.append(WandbMonitor(config.wandb.project,
                                                  config.wandb.group, config.wandb.team))
            except Exception as e:
                logger.warning(f"W&B monitor unavailable: {e}")
        if getattr(config, "comet", None) is not None and config.comet.enabled:
            try:
                self.monitors.append(CometMonitor(config.comet))
            except Exception as e:  # comet_ml not installed
                logger.warning(f"Comet monitor unavailable: {e}")

    @property
    def enabled(self) -> bool:
        return bool(self.monitors)

    def write_events(self, events: List[Event]) -> None:
        for m in self.monitors:
            m.write_events(events)

    def write_registry(self, registry, step: int) -> None:
        """Fan a telemetry ``MetricsRegistry`` snapshot out through every
        writer: counters/gauges as scalar tags, histograms as
        p50/p95/p99/count/sum sub-tags (see registry.snapshot_events)."""
        if not self.monitors:
            return
        events = registry.snapshot_events(step)
        if events:
            self.write_events(events)

    def close(self) -> None:
        """Close every writer (flush + release handles).  Safe to call
        more than once; a writer that fails to close must not block the
        rest."""
        for m in self.monitors:
            try:
                m.close()
            except Exception as e:
                logger.warning(f"monitor close failed: {e}")
