"""Checkpoint conversion CLI (reference ``ds_to_universal.py`` /
``zero_to_fp32.py`` scripts).

  python -m deepspeed_tpu.checkpoint to-universal CKPT_DIR TAG OUT_DIR
  python -m deepspeed_tpu.checkpoint zero-to-fp32 CKPT_DIR TAG OUT.npz
"""

from __future__ import annotations

import argparse
import sys

from .partitioned import to_universal, zero_to_fp32


def main(argv=None) -> int:
    ap = argparse.ArgumentParser("deepspeed_tpu.checkpoint")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p1 = sub.add_parser("to-universal",
                        help="merge a partitioned checkpoint into per-"
                             "parameter atom files loadable on ANY mesh")
    p1.add_argument("ckpt_dir")
    p1.add_argument("tag")
    p1.add_argument("out_dir")
    p2 = sub.add_parser("zero-to-fp32",
                        help="export consolidated fp32 model params")
    p2.add_argument("ckpt_dir")
    p2.add_argument("tag")
    p2.add_argument("output_file")
    args = ap.parse_args(argv)
    if args.cmd == "to-universal":
        out = to_universal(args.ckpt_dir, args.tag, args.out_dir)
    else:
        out = zero_to_fp32(args.ckpt_dir, args.tag, args.output_file)
    print(out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
