"""Checkpoint conversion CLI (reference ``ds_to_universal.py`` /
``zero_to_fp32.py`` scripts).

  python -m deepspeed_tpu.checkpoint to-universal CKPT_DIR TAG OUT_DIR
  python -m deepspeed_tpu.checkpoint zero-to-fp32 CKPT_DIR TAG OUT.npz
"""

from __future__ import annotations

import argparse
import sys

from .partitioned import to_universal, zero_to_fp32


def main(argv=None) -> int:
    ap = argparse.ArgumentParser("deepspeed_tpu.checkpoint")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p1 = sub.add_parser("to-universal",
                        help="merge a partitioned checkpoint into per-"
                             "parameter atom files loadable on ANY mesh")
    p1.add_argument("ckpt_dir")
    p1.add_argument("tag")
    p1.add_argument("out_dir")
    p2 = sub.add_parser("zero-to-fp32",
                        help="export consolidated fp32 model params")
    p2.add_argument("ckpt_dir")
    p2.add_argument("tag")
    p2.add_argument("output_file")
    p3 = sub.add_parser(
        "to-hf", help="export a partitioned checkpoint as a transformers-"
                      "loadable directory (config.json + model.safetensors)")
    p3.add_argument("ckpt_dir")
    p3.add_argument("tag")
    p3.add_argument("out_dir")
    p3.add_argument("--model", required=True,
                    help="family:size of the trained model, e.g. llama:7b")
    p3.add_argument("--model-type", default=None,
                    help="HF model_type for the export map (default: family)")
    p3.add_argument("--dtype", default=None,
                    help="cast floating weights, e.g. bfloat16")
    p3.add_argument("--override", action="append", default=[],
                    metavar="KEY=VALUE",
                    help="config field override (repeatable), e.g. "
                         "--override vocab_size=32000 --override "
                         "max_seq_len=4096 — must match the trained model")
    args = ap.parse_args(argv)
    if args.cmd == "to-universal":
        out = to_universal(args.ckpt_dir, args.tag, args.out_dir)
    elif args.cmd == "zero-to-fp32":
        out = zero_to_fp32(args.ckpt_dir, args.tag, args.output_file)
    else:
        import json

        from .hf_export import checkpoint_to_hf
        from .. import models

        family, _, size = args.model.partition(":")
        # only families the exporter has a name map for — anything else
        # would write a llama-layout checkpoint with the wrong model_type
        supported = ("llama", "mistral", "qwen2", "mixtral", "gpt2",
                     "opt", "phi", "phi3", "falcon", "bert")
        if family not in supported:
            raise SystemExit(
                f"to-hf supports families {supported}; got '{family}'")
        # config factories live on the models package (mistral/qwen come
        # from families.py, not their own modules); HF calls qwen "qwen2"
        factory_name = {"qwen2": "qwen_config"}.get(family,
                                                    f"{family}_config")
        factory = getattr(models, factory_name)
        import dataclasses as _dc

        from ..models.transformer import TransformerConfig

        valid_fields = {f.name for f in _dc.fields(TransformerConfig)}
        over = {}
        for item in args.override:
            k, sep, v = item.partition("=")
            if not sep:
                raise SystemExit(f"--override needs KEY=VALUE, got '{item}'")
            if k not in valid_fields:
                raise SystemExit(
                    f"--override '{k}' is not a TransformerConfig field "
                    f"(did you use the HF name? e.g. max_position_embeddings"
                    f" -> max_seq_len)")
            try:  # JSON covers ints, floats, and true/false properly
                over[k] = json.loads(v)
            except ValueError:
                over[k] = v
        # each family has its own default size — only pass one if given
        try:
            cfg = factory(size, **over) if size else factory(**over)
        except KeyError:
            mod = __import__(f"deepspeed_tpu.models.{family}",
                             fromlist=["SIZES"]) \
                if family in ("llama", "mixtral", "gpt2") else None
            sizes = sorted(getattr(mod, "SIZES", {})) if mod else []
            raise SystemExit(
                f"unknown size '{size}' for family '{family}'"
                + (f"; available: {sizes}" if sizes else "")) from None
        out = checkpoint_to_hf(args.ckpt_dir, args.tag, args.out_dir, cfg,
                               model_type=args.model_type or family,
                               dtype=args.dtype)
    print(out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
