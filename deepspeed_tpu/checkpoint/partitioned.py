"""Partitioned (multi-host / ZeRO-layout) checkpointing + universal format.

Reference layout (engine.py:3609): per-mp-rank model files + per-dp-rank
ZeRO optimizer partition files; ``ds_to_universal.py`` merges them into
per-parameter "atom" files loadable into ANY new dp/tp/pp layout
(``deepspeed/checkpoint/universal_checkpoint.py:146``).

TPU layout: every *process* writes the shards it owns for every leaf of the
TrainState, keyed by pytree path with the global index of each shard
(``zero_shard_rank_{proc}.npz`` + shard index json).  ``to_universal``
assembles shard files into one full array per parameter (atom files);
``load_partitioned`` goes straight from shard files to a differently-meshed
engine — the resharding promise, without torch-style reshape heuristics
because the index metadata is exact.
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from .. import comm
from ..utils.logging import log_dist, logger

SHARD_FILE = "zero_shard_rank_{rank}.npz"
INDEX_FILE = "shard_index_rank_{rank}.json"
META_FILE = "partitioned_meta.json"


def _leaf_items(state: Any):
    flat = []

    def visit(path, leaf):
        if leaf is not None:
            flat.append((jax.tree_util.keystr(path), leaf))
        return leaf

    jax.tree_util.tree_map_with_path(visit, state)
    return flat


def save_partitioned(engine, save_dir: str, tag: str,
                     client_state: Optional[dict] = None,
                     checkpoint_engine=None,
                     keep_n: Optional[int] = None) -> str:
    """Each process writes its addressable shards (one file per process —
    the analogue of per-dp-rank optim_states files).

    All ranks write into the ``tmp.<tag>`` staging dir; after the save
    barrier rank 0 finalizes the verified atomic commit (checksum
    manifest over every rank's files, fsync, atomic rename, ``latest``
    pointer, GC) — see ``resilience/commit.py``."""
    from ..resilience.commit import begin_commit, finalize_commit, staging_path
    from ..runtime.checkpoint_engine.engines import NumpyCheckpointEngine

    ce = checkpoint_engine or NumpyCheckpointEngine()
    rank = jax.process_index()
    if rank == 0:
        begin_commit(save_dir, tag)
    comm.barrier("stage-prep")
    path = staging_path(save_dir, tag)

    arrays: Dict[str, np.ndarray] = {}
    index: Dict[str, Any] = {}
    for key, leaf in _leaf_items(engine.state):
        entries = []
        seen = set()
        for shard in leaf.addressable_shards:
            idx = shard.index  # tuple of slices into the global shape
            norm = tuple((s.start or 0, s.stop if s.stop is not None else dim)
                         for s, dim in zip(idx, leaf.shape)) if idx else ()
            if norm in seen:  # replicated across devices: store once
                continue
            seen.add(norm)
            skey = f"{key}::{len(entries)}"
            data = np.asarray(shard.data)
            if data.dtype.name == "bfloat16":
                data = data.view(np.uint16)
                bf16 = True
            else:
                bf16 = False
            arrays[skey] = data
            # per-array checksum (forensics: WHICH shard flipped — the
            # commit manifest's per-file CRCs gate loading); buffer
            # protocol, no .tobytes() copy
            crc = zlib.crc32(np.ascontiguousarray(data)) & 0xFFFFFFFF
            entries.append({"key": skey, "start": [s[0] for s in norm],
                            "stop": [s[1] for s in norm], "bf16": bf16,
                            "crc32": crc})
        index[key] = {"shape": list(leaf.shape), "dtype": str(leaf.dtype),
                      "shards": entries}

    ce.save(arrays, os.path.join(path, SHARD_FILE.format(rank=rank).replace(".npz", "")))
    # decoupled/async engines: join the background write BEFORE the
    # commit barrier — a failure here is attributed to THIS tag (the
    # owning step boundary), and the manifest below must checksum
    # fully-written files
    ce.commit(tag)
    with open(os.path.join(path, INDEX_FILE.format(rank=rank)), "w") as f:
        json.dump(index, f)
    if rank == 0:
        meta = {
            "tag": tag, "format": "partitioned-v1",
            "world": jax.process_count(),
            "global_steps": engine.global_steps,
            "micro_steps": engine.micro_steps,
            "lr_scheduler": engine.lr_scheduler.state_dict()
            if hasattr(engine.lr_scheduler, "state_dict") else None,
            "client_state": client_state or {},
            "zero_stage": engine.config.zero_config.stage,
            "mesh": engine.topology.axis_sizes,
            "elasticity": (engine.config.raw or {}).get("elasticity", {}),
        }
        with open(os.path.join(path, META_FILE), "w") as f:
            json.dump(meta, f, indent=2, default=str)
    comm.barrier("partitioned-save")
    final = os.path.join(save_dir, tag)
    if rank == 0:
        commit_meta = {
            "global_steps": engine.global_steps,
            "world": jax.process_count(),
            "mesh": dict(engine.topology.axis_sizes),
        }
        try:
            # numerics incident annotation — same contract as
            # saving.save_checkpoint: consume-once, never blocks the save
            from ..telemetry.numerics import pending_incident_meta

            inc = pending_incident_meta()
            if inc is not None:
                commit_meta["numerics_incident"] = inc
        # dstpu-lint: allow[swallow] annotation only
        except Exception:
            pass
        finalize_commit(save_dir, tag, keep_n=keep_n, meta=commit_meta)
    comm.barrier("partitioned-commit")
    log_dist(f"saved partitioned checkpoint {final}")
    return final


def _load_shard_arrays(base: str) -> Dict[str, np.ndarray]:
    """Load one rank's shard file regardless of which checkpoint engine
    wrote it: ``<base>.npz`` (sync/decoupled Numpy layout) or a
    ``<base>/`` directory with ``manifest.json`` + per-tensor bins
    (FastCheckpointEngine layout).  Reads directly (np.fromfile) so the
    universal/fp32 CLI tools need no AIO engine."""
    if os.path.isdir(base):
        with open(os.path.join(base, "manifest.json")) as f:
            manifest = json.load(f)
        out = {}
        for key, info in manifest.items():
            dtype = np.dtype(info["dtype"])
            shape = tuple(info["shape"])
            if info.get("empty") or 0 in shape:
                out[key] = np.empty(shape, dtype)
            else:
                out[key] = np.fromfile(os.path.join(base, info["file"]),
                                       dtype).reshape(shape)
        return out
    from ..runtime.checkpoint_engine.engines import NumpyCheckpointEngine

    return NumpyCheckpointEngine().load(base)


def _assemble(path: str, keys: Optional[List[str]] = None,
              prefix: Optional[str] = None) -> Dict[str, np.ndarray]:
    """Merge all ranks' shards into full arrays keyed by pytree path.
    ``prefix`` filters keys at load time — an export that only needs
    ``.params`` must not materialize optimizer moments (2-3x the bytes)."""
    import glob

    full: Dict[str, np.ndarray] = {}
    for idx_file in sorted(glob.glob(os.path.join(path, "shard_index_rank_*.json"))):
        rank = int(os.path.basename(idx_file).split("_rank_")[1].split(".")[0])
        with open(idx_file) as f:
            index = json.load(f)
        arrays = _load_shard_arrays(os.path.join(path, SHARD_FILE.format(rank=rank).replace(".npz", "")))
        for key, info in index.items():
            if keys is not None and key not in keys:
                continue
            if prefix is not None and not key.startswith(prefix):
                continue
            if key not in full:
                dtype = info["dtype"]
                np_dtype = np.uint16 if dtype == "bfloat16" else np.dtype(dtype)
                full[key] = np.zeros(info["shape"], np_dtype)
            for entry in info["shards"]:
                data = arrays[entry["key"]]
                if entry["start"]:
                    sl = tuple(slice(a, b) for a, b in zip(entry["start"], entry["stop"]))
                    full[key][sl] = data.reshape(full[key][sl].shape)
                else:
                    full[key] = data.reshape(info["shape"]) if info["shape"] else data
    return full


def load_partitioned(engine, load_dir: str, tag: Optional[str] = None,
                     load_lr_scheduler_states: bool = True) -> Tuple[Optional[str], dict]:
    """Load a partitioned checkpoint into an engine with ANY mesh/stage."""
    import jax.numpy as jnp

    if tag is None:
        from ..resilience.commit import resolve_tag

        tag, _report = resolve_tag(load_dir)
        if tag is None:
            logger.warning(f"no loadable checkpoint in {load_dir}")
            return None, {}
    path = os.path.join(load_dir, tag)
    with open(os.path.join(path, META_FILE)) as f:
        meta = json.load(f)

    # elastic resume (reference DSElasticAgent + --load_universal): a
    # different mesh than the checkpoint's is fine — shards reassemble and
    # re-place into the current topology below.  With elasticity configured,
    # the config must not have drifted across the resize (reference
    # ensure_immutable_elastic_config, elasticity.py:208).
    saved_mesh = meta.get("mesh")
    if saved_mesh and dict(saved_mesh) != dict(engine.topology.axis_sizes):
        log_dist(f"elastic resume: resharding checkpoint mesh {saved_mesh} "
                 f"-> current {engine.topology.axis_sizes}")
    # config drift breaks the batch-size guarantee at ANY scale, not just
    # across resizes — validate on every elastic resume
    saved_el = meta.get("elasticity") or {}
    cur_el = (engine.config.raw or {}).get("elasticity", {})
    if saved_el.get("enabled") or cur_el.get("enabled"):
        from ..elasticity.elasticity import ensure_immutable_elastic_config

        ensure_immutable_elastic_config({"elasticity": cur_el},
                                        {"elasticity": saved_el})
    full = _assemble(path)

    from jax.sharding import NamedSharding

    def restore(path_key, current):
        key = jax.tree_util.keystr(path_key)
        if key not in full:
            logger.warning(f"partitioned ckpt missing {key}; keeping current")
            return current
        arr = full[key]
        if str(current.dtype) == "bfloat16" and arr.dtype == np.uint16:
            arr = arr.view(jnp.bfloat16)
        sh = current.sharding if isinstance(current.sharding, NamedSharding) \
            else engine.topology.replicated()
        return jax.device_put(
            jnp.asarray(arr, current.dtype).reshape(current.shape), sh)

    engine.state = jax.tree_util.tree_map_with_path(restore, engine.state)
    engine.global_steps = meta["global_steps"]
    engine.micro_steps = meta.get("micro_steps", 0)
    if load_lr_scheduler_states and meta.get("lr_scheduler") and \
            hasattr(engine.lr_scheduler, "load_state_dict"):
        engine.lr_scheduler.load_state_dict(meta["lr_scheduler"])
    log_dist(f"loaded partitioned checkpoint {path}")
    return path, meta.get("client_state", {})


# --------------------------------------------------------------------------
# universal checkpoint (atom files) + fp32 export
# --------------------------------------------------------------------------
def to_universal(ckpt_dir: str, tag: str, out_dir: str) -> str:
    """Merge a partitioned checkpoint into per-parameter atom files
    (reference ds_to_universal.py)."""
    path = os.path.join(ckpt_dir, tag)
    full = _assemble(path)
    os.makedirs(out_dir, exist_ok=True)
    atoms = {}
    for key, arr in full.items():
        fname = key.strip("[]'").replace("']['", "__").replace("/", "_") + ".npy"
        np.save(os.path.join(out_dir, fname), arr)
        atoms[key] = fname
    with open(os.path.join(path, META_FILE)) as f:
        meta = json.load(f)
    meta["format"] = "universal-v1"
    meta["atoms"] = atoms
    with open(os.path.join(out_dir, "universal_meta.json"), "w") as f:
        json.dump(meta, f, indent=2, default=str)
    return out_dir


def load_universal(engine, universal_dir: str) -> None:
    """Load atom files into any engine layout (reference --load_universal)."""
    import jax.numpy as jnp

    with open(os.path.join(universal_dir, "universal_meta.json")) as f:
        meta = json.load(f)
    atoms = meta["atoms"]

    def restore(path_key, current):
        key = jax.tree_util.keystr(path_key)
        if key not in atoms:
            return current
        arr = np.load(os.path.join(universal_dir, atoms[key]))
        if str(current.dtype) == "bfloat16" and arr.dtype == np.uint16:
            arr = arr.view(jnp.bfloat16)
        from jax.sharding import NamedSharding

        sh = current.sharding if isinstance(current.sharding, NamedSharding) \
            else engine.topology.replicated()
        return jax.device_put(jnp.asarray(arr, current.dtype).reshape(current.shape), sh)

    engine.state = jax.tree_util.tree_map_with_path(restore, engine.state)
    engine.global_steps = meta["global_steps"]


def zero_to_fp32(ckpt_dir: str, tag: str, output_file: str) -> str:
    """Export consolidated fp32 model params from a partitioned checkpoint
    (reference utils/zero_to_fp32.py)."""
    path = os.path.join(ckpt_dir, tag)
    full = _assemble(path)
    params = {}
    for key, arr in full.items():
        if ".params" in key or key.startswith("['params']") or "params" in key.split("']")[0]:
            if arr.dtype == np.uint16:  # stored bf16
                import jax.numpy as jnp

                arr = np.asarray(arr.view(jnp.bfloat16), np.float32)
            params[key] = np.asarray(arr, np.float32)
    np.savez(output_file, **params)
    return output_file
