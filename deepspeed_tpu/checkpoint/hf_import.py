"""Import published Hugging Face checkpoints into the runtime.

Reference parity: the reference loads real HF checkpoints into its engines —
inference v2 model implementations
(``/root/reference/deepspeed/inference/v2/model_implementations/``) and
``module_inject`` sharded loading.  Here ONE name-mapping importer produces
the ``init_transformer_params`` tree, so a published llama / mistral / qwen
/ mixtral / gpt2 checkpoint drops into both the training engine and the
inference engines (the tree is what every entry point consumes).

Formats: ``*.safetensors`` (read natively — 8-byte header length + JSON
header + raw little-endian buffer; no external dependency) and
``pytorch_model*.bin`` (via torch, CPU map).  Multi-shard index files of
both flavors are followed.

Families: llama / mistral / qwen2 / qwen2-moe / mixtral / gpt2 / opt /
phi / phi3 / falcon / bloom / gpt-neox / bert — all with logit parity
against ``transformers`` (bert rides the
transformer core's post-norm mode: norm after each residual add,
embeddings LayerNorm, segment embeddings, full MLM prediction head).

Conventions handled:
  * torch ``nn.Linear`` stores [out, in]; this runtime right-multiplies
    ([in, out]) — mapped weights are transposed.  GPT-2 uses Conv1D
    ([in, out] already) — not transposed.
  * llama-family RoPE is the rotate-half convention, identical to
    ``transformer._rope`` — no head-dim permutation needed.
  * per-layer tensors are stacked on a leading [n_layers, ...] dim (the
    scan-layers layout of ``init_transformer_params``).
"""

from __future__ import annotations

import json
import os
import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..utils.logging import logger

_ST_DTYPES = {
    "F64": np.float64, "F32": np.float32, "F16": np.float16,
    "I64": np.int64, "I32": np.int32, "I16": np.int16, "I8": np.int8,
    "U8": np.uint8, "BOOL": np.bool_,
}


def _st_dtype(name: str):
    if name == "BF16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(_ST_DTYPES[name])


def read_safetensors(path: str) -> Dict[str, np.ndarray]:
    """Minimal native safetensors reader (zero-copy via memmap)."""
    out: Dict[str, np.ndarray] = {}
    with open(path, "rb") as f:
        (hlen,) = struct.unpack("<Q", f.read(8))
        header = json.loads(f.read(hlen))
    base = 8 + hlen
    mm = np.memmap(path, mode="r", dtype=np.uint8)
    for name, meta in header.items():
        if name == "__metadata__":
            continue
        dt = _st_dtype(meta["dtype"])
        start, end = meta["data_offsets"]
        buf = mm[base + start:base + end]
        out[name] = buf.view(dt).reshape(meta["shape"])
    return out


def load_state_dict(model_dir: str) -> Dict[str, np.ndarray]:
    """All weights of an HF checkpoint directory as numpy arrays."""
    sd: Dict[str, np.ndarray] = {}
    st_index = os.path.join(model_dir, "model.safetensors.index.json")
    pt_index = os.path.join(model_dir, "pytorch_model.bin.index.json")
    if os.path.exists(st_index):
        with open(st_index) as f:
            shards = sorted(set(json.load(f)["weight_map"].values()))
        for shard in shards:
            sd.update(read_safetensors(os.path.join(model_dir, shard)))
        return sd
    single_st = os.path.join(model_dir, "model.safetensors")
    if os.path.exists(single_st):
        return read_safetensors(single_st)
    if os.path.exists(pt_index):
        with open(pt_index) as f:
            shards = sorted(set(json.load(f)["weight_map"].values()))
    elif os.path.exists(os.path.join(model_dir, "pytorch_model.bin")):
        shards = ["pytorch_model.bin"]
    else:
        raise FileNotFoundError(
            f"no model.safetensors[.index.json] or pytorch_model.bin "
            f"in {model_dir}")
    import torch

    for shard in shards:
        t = torch.load(os.path.join(model_dir, shard), map_location="cpu",
                       weights_only=True)
        for k, v in t.items():
            sd[k] = _torch_to_numpy(v)
    return sd


def _torch_to_numpy(t) -> np.ndarray:
    if t.dtype.__str__() == "torch.bfloat16":
        import ml_dtypes

        return t.view(__import__("torch").int16).numpy().view(
            np.dtype(ml_dtypes.bfloat16))
    return t.numpy()


def config_from_hf(model_dir_or_cfg) -> "TransformerConfig":
    """HF ``config.json`` -> TransformerConfig (the reference's model
    implementations read the same fields)."""
    from ..models.transformer import TransformerConfig

    if isinstance(model_dir_or_cfg, dict):
        c = model_dir_or_cfg
    else:
        with open(os.path.join(model_dir_or_cfg, "config.json")) as f:
            c = json.load(f)
    mtype = c.get("model_type", "llama")
    if mtype == "gpt2":
        h = c["n_embd"]
        return TransformerConfig(
            vocab_size=c["vocab_size"], hidden_size=h,
            n_layers=c["n_layer"], n_heads=c["n_head"],
            intermediate_size=c.get("n_inner") or 4 * h,
            max_seq_len=c.get("n_positions", 1024), norm="layernorm",
            activation="gelu", position="learned", causal=True,
            use_bias=True, tie_embeddings=True,
            norm_eps=c.get("layer_norm_epsilon", 1e-5))
    if mtype == "opt":
        # OPT: pre-norm decoder (do_layer_norm_before), learned positions
        # with the +2 padding offset handled at weight import, relu FFN
        if not c.get("do_layer_norm_before", True):
            raise ValueError("hf_import: post-layernorm OPT variants "
                             "(do_layer_norm_before=false, 350m) are not "
                             "supported by the pre-norm runtime")
        if c.get("word_embed_proj_dim", c["hidden_size"]) != c["hidden_size"]:
            raise ValueError(
                "hf_import: OPT variants with an embedding projection "
                "(word_embed_proj_dim != hidden_size) are not supported — "
                "project_in/project_out have no runtime counterpart")
        act = c.get("activation_function", "relu")  # galactica ships gelu
        if act not in ("relu", "gelu", "gelu_new"):
            raise ValueError(f"hf_import: OPT activation_function '{act}' "
                             f"not supported (relu/gelu)")
        return TransformerConfig(
            vocab_size=c["vocab_size"], hidden_size=c["hidden_size"],
            n_layers=c["num_hidden_layers"],
            n_heads=c["num_attention_heads"],
            intermediate_size=c["ffn_dim"],
            max_seq_len=c.get("max_position_embeddings", 2048),
            # HF OPT's 'gelu' is the exact erf form; only gpt2/phi use the
            # tanh approximation ('gelu_new')
            norm="layernorm",
            activation=("relu" if act == "relu"
                        else "gelu" if act == "gelu_new" else "gelu_exact"),
            position="learned",
            causal=True, use_bias=True,
            tie_embeddings=bool(c.get("tie_word_embeddings", True)))
    if mtype == "phi":
        if c.get("qk_layernorm"):
            raise ValueError("hf_import: phi variants with qk_layernorm "
                             "are not supported — the q/k layernorm "
                             "weights have no runtime counterpart")
        return TransformerConfig(
            vocab_size=c["vocab_size"], hidden_size=c["hidden_size"],
            n_layers=c["num_hidden_layers"],
            n_heads=c["num_attention_heads"],
            n_kv_heads=c.get("num_key_value_heads")
            or c["num_attention_heads"],
            intermediate_size=c["intermediate_size"],
            max_seq_len=c.get("max_position_embeddings", 2048),
            norm="layernorm", activation="gelu", position="rope",
            causal=True, use_bias=True, parallel_block=True,
            rotary_pct=float(c.get("partial_rotary_factor", 0.5)),
            norm_eps=c.get("layer_norm_eps", 1e-5),
            rope_theta=float(c.get("rope_theta", 10000.0)),
            tie_embeddings=bool(c.get("tie_word_embeddings", False)))
    if mtype == "bert":
        act = c.get("hidden_act", "gelu")
        if act not in ("gelu", "gelu_new", "relu"):
            raise ValueError(f"hf_import: bert hidden_act '{act}' "
                             f"not supported")
        if c.get("position_embedding_type", "absolute") != "absolute":
            raise ValueError(
                "hf_import: relative-position BERT variants "
                "(position_embedding_type != absolute) are not supported — "
                "their attention bias has no runtime counterpart")
        return TransformerConfig(
            vocab_size=c["vocab_size"], hidden_size=c["hidden_size"],
            n_layers=c["num_hidden_layers"],
            n_heads=c["num_attention_heads"],
            intermediate_size=c["intermediate_size"],
            max_seq_len=c.get("max_position_embeddings", 512),
            norm="layernorm",
            activation={"gelu": "gelu_exact", "gelu_new": "gelu",
                        "relu": "relu"}[act],
            position="learned", causal=False, use_bias=True,
            tie_embeddings=True, post_norm=True,
            type_vocab_size=c.get("type_vocab_size", 2),
            norm_eps=c.get("layer_norm_eps", 1e-12))
    if mtype == "bloom":
        if c.get("apply_residual_connection_post_layernorm"):
            raise ValueError(
                "hf_import: bloom variants with "
                "apply_residual_connection_post_layernorm are not "
                "supported — the runtime's residual reads the raw stream")
        h = c["hidden_size"]
        return TransformerConfig(
            vocab_size=c["vocab_size"], hidden_size=h,
            n_layers=c["n_layer"], n_heads=c["n_head"],
            intermediate_size=4 * h,
            max_seq_len=c.get("seq_length", 2048),  # ALiBi: no pos table
            norm="layernorm", activation="gelu",  # BloomGelu = tanh approx
            position="alibi", causal=True, use_bias=True, embed_norm=True,
            # HF bloom defaults to a tied head but honors the flag; a
            # hardcoded True silently dropped untied lm_head weights
            tie_embeddings=bool(c.get("tie_word_embeddings", True)),
            norm_eps=c.get("layer_norm_epsilon", 1e-5))
    if mtype == "gpt_neox":
        if not c.get("use_parallel_residual", True):
            raise ValueError("hf_import: gpt_neox with "
                             "use_parallel_residual=false (sequential "
                             "residual) is not supported by the "
                             "parallel-block runtime")
        return TransformerConfig(
            vocab_size=c["vocab_size"], hidden_size=c["hidden_size"],
            n_layers=c["num_hidden_layers"],
            n_heads=c["num_attention_heads"],
            intermediate_size=c["intermediate_size"],
            max_seq_len=c.get("max_position_embeddings", 2048),
            norm="layernorm",
            activation={"gelu": "gelu_exact", "gelu_new": "gelu",
                        "gelu_fast": "gelu"}.get(
                c.get("hidden_act", "gelu"), "gelu_exact"),
            position="rope", rotary_pct=float(c.get("rotary_pct", 0.25)),
            rope_theta=float(c.get("rotary_emb_base", 10000.0)),
            causal=True, use_bias=True, parallel_block=True,
            parallel_norms=2,
            tie_embeddings=bool(c.get("tie_word_embeddings", False)),
            norm_eps=c.get("layer_norm_eps", 1e-5))
    if mtype == "falcon":
        if not c.get("parallel_attn", True):
            raise ValueError("hf_import: sequential-attention falcon "
                             "variants are not supported by the "
                             "parallel-block runtime")
        new_arch = bool(c.get("new_decoder_architecture"))
        if not new_arch and not c.get("multi_query", True):
            # old-arch multi_query=false interleaves q/k/v PER HEAD inside
            # the fused weight; the block split below would silently
            # misread it
            raise ValueError("hf_import: falcon multi_query=false "
                             "(per-head-interleaved fused QKV) is not "
                             "supported — 7b-style multi-query is")
        if c.get("alibi"):
            raise ValueError("hf_import: alibi-position falcon variants "
                             "are not supported (runtime is rotary)")
        if c.get("bias"):
            raise ValueError("hf_import: biased falcon variants are not "
                             "supported (7b/40b-style bias=false is)")
        nh = c["num_attention_heads"]
        # new arch defaults to separate ln_attn/ln_mlp; falcon-11B-style
        # sets num_ln_in_parallel_attn=1 (single input_layernorm)
        n_ln = int(c.get("num_ln_in_parallel_attn")
                   or (2 if new_arch else 1))
        return TransformerConfig(
            vocab_size=c["vocab_size"], hidden_size=c["hidden_size"],
            n_layers=c["num_hidden_layers"], n_heads=nh,
            parallel_norms=n_ln,
            # new arch (40b/180b): grouped KV; old arch: multi-query
            n_kv_heads=c.get("num_kv_heads", nh) if new_arch else 1,
            intermediate_size=4 * c["hidden_size"],
            max_seq_len=c.get("max_position_embeddings", 2048),
            norm="layernorm", activation="gelu_exact", position="rope",
            causal=True, parallel_block=True,
            norm_eps=c.get("layer_norm_epsilon", 1e-5),
            rope_theta=float(c.get("rope_theta", 10000.0)),
            tie_embeddings=bool(c.get("tie_word_embeddings", True)))
    kv = c.get("num_key_value_heads", c["num_attention_heads"])
    cfg = TransformerConfig(
        vocab_size=c["vocab_size"], hidden_size=c["hidden_size"],
        n_layers=c["num_hidden_layers"], n_heads=c["num_attention_heads"],
        n_kv_heads=kv, intermediate_size=c["intermediate_size"],
        max_seq_len=c.get("max_position_embeddings", 2048),
        norm="rmsnorm", activation="swiglu", position="rope", causal=True,
        norm_eps=c.get("rms_norm_eps", 1e-6),
        rope_theta=float(c.get("rope_theta", 10000.0)),
        tie_embeddings=bool(c.get("tie_word_embeddings", False)))
    if mtype == "mixtral":
        cfg.moe_experts = c["num_local_experts"]
        cfg.moe_top_k = c.get("num_experts_per_tok", 2)
    if mtype == "qwen2":
        cfg.qkv_bias = True
    if mtype == "phi3" and c.get("rope_scaling"):
        # long-context phi3 variants use longrope (per-dim scale tables);
        # only the plain-rope (4k) variants map onto our rope
        raise ValueError("hf_import: phi3 rope_scaling (longrope) is "
                         "unsupported; use a 4k-context phi3 variant")
    if mtype == "qwen2_moe":
        if c.get("decoder_sparse_step", 1) != 1 or c.get("mlp_only_layers"):
            raise ValueError(
                "hf_import: qwen2_moe variants mixing dense and sparse "
                "layers (decoder_sparse_step != 1 / mlp_only_layers) are "
                "unsupported — every layer must be MoE")
        cfg.qkv_bias = True
        cfg.moe_experts = c["num_experts"]
        cfg.moe_top_k = c.get("num_experts_per_tok", 4)
        # experts use moe_intermediate_size, NOT the dense
        # intermediate_size the default path read
        cfg.intermediate_size = c["moe_intermediate_size"]
        cfg.moe_shared_expert = c.get("shared_expert_intermediate_size", 0)
        cfg.moe_norm_topk = bool(c.get("norm_topk_prob", False))
        cfg.moe_drop_tokens = False  # exact per-token routing for parity
    return cfg


def _stack(state: Dict[str, np.ndarray], pattern: str, n: int,
           transpose: bool = True) -> np.ndarray:
    mats = []
    for i in range(n):
        w = np.asarray(state[pattern.format(i=i)])
        mats.append(w.T if transpose else w)
    return np.stack(mats)


def import_hf_params(cfg, state: Dict[str, np.ndarray],
                     model_type: str = "llama") -> Dict[str, Any]:
    """HF state dict -> ``init_transformer_params`` layout."""
    L = cfg.n_layers
    if model_type == "gpt2":
        return _import_gpt2(cfg, state)
    if model_type == "opt":
        return _import_opt(cfg, state)
    if model_type == "phi":
        return _import_phi(cfg, state)
    if model_type == "falcon":
        return _import_falcon(cfg, state)
    if model_type == "bloom":
        return _import_bloom(cfg, state)
    if model_type == "gpt_neox":
        return _import_gpt_neox(cfg, state)
    if model_type == "bert":
        return _import_bert(cfg, state)
    if model_type == "phi3":
        # phi3 is llama-shaped with FUSED projections: qkv_proj rows are
        # [q | k | v] and gate_up_proj rows are [gate | up] (reference
        # model_implementations/phi3 unfuses the same way); split them
        # into llama names and fall through to the llama mapping
        state = dict(state)
        qd = cfg.n_heads * cfg.head_dim
        kvd = cfg.n_kv_heads * cfg.head_dim
        for i in range(L):
            pre = f"model.layers.{i}"
            qkv = np.asarray(state.pop(f"{pre}.self_attn.qkv_proj.weight"))
            state[f"{pre}.self_attn.q_proj.weight"] = qkv[:qd]
            state[f"{pre}.self_attn.k_proj.weight"] = qkv[qd:qd + kvd]
            state[f"{pre}.self_attn.v_proj.weight"] = qkv[qd + kvd:]
            gu = np.asarray(state.pop(f"{pre}.mlp.gate_up_proj.weight"))
            state[f"{pre}.mlp.gate_proj.weight"] = gu[:cfg.ffn_size]
            state[f"{pre}.mlp.up_proj.weight"] = gu[cfg.ffn_size:]
    p: Dict[str, Any] = {
        "embed": {"tok": np.asarray(state["model.embed_tokens.weight"])},
        "final_norm": {"scale": np.asarray(state["model.norm.weight"])},
    }
    attn = {
        "wq": _stack(state, "model.layers.{i}.self_attn.q_proj.weight", L),
        "wk": _stack(state, "model.layers.{i}.self_attn.k_proj.weight", L),
        "wv": _stack(state, "model.layers.{i}.self_attn.v_proj.weight", L),
        "wo": _stack(state, "model.layers.{i}.self_attn.o_proj.weight", L),
    }
    if getattr(cfg, "qkv_bias", False):  # qwen2
        attn["bq"] = _stack(state, "model.layers.{i}.self_attn.q_proj.bias",
                            L, transpose=False)
        attn["bk"] = _stack(state, "model.layers.{i}.self_attn.k_proj.bias",
                            L, transpose=False)
        attn["bv"] = _stack(state, "model.layers.{i}.self_attn.v_proj.bias",
                            L, transpose=False)
    layers: Dict[str, Any] = {
        "attn": attn,
        "norm1": {"scale": _stack(
            state, "model.layers.{i}.input_layernorm.weight", L,
            transpose=False)},
        "norm2": {"scale": _stack(
            state, "model.layers.{i}.post_attention_layernorm.weight", L,
            transpose=False)},
    }
    if model_type == "qwen2_moe":
        E = cfg.moe_experts

        def _experts(name):
            return np.stack([np.stack([np.asarray(state[
                f"model.layers.{i}.mlp.experts.{e}.{name}.weight"]).T
                for e in range(E)]) for i in range(L)])

        layers["mlp"] = {
            "router": _stack(state, "model.layers.{i}.mlp.gate.weight", L),
            "w_gate": _experts("gate_proj"),
            "w_up": _experts("up_proj"),
            "w_down": _experts("down_proj"),
            # always-on shared expert + its per-token sigmoid gate
            "shared_w_gate": _stack(
                state, "model.layers.{i}.mlp.shared_expert.gate_proj.weight", L),
            "shared_w_up": _stack(
                state, "model.layers.{i}.mlp.shared_expert.up_proj.weight", L),
            "shared_w_down": _stack(
                state, "model.layers.{i}.mlp.shared_expert.down_proj.weight", L),
            "shared_gate": _stack(
                state, "model.layers.{i}.mlp.shared_expert_gate.weight", L),
        }
    elif cfg.moe_experts > 0:  # mixtral
        E = cfg.moe_experts
        layers["mlp"] = {
            "router": _stack(
                state, "model.layers.{i}.block_sparse_moe.gate.weight", L),
            "w_gate": np.stack([np.stack([np.asarray(state[
                f"model.layers.{i}.block_sparse_moe.experts.{e}.w1.weight"]).T
                for e in range(E)]) for i in range(L)]),
            "w_down": np.stack([np.stack([np.asarray(state[
                f"model.layers.{i}.block_sparse_moe.experts.{e}.w2.weight"]).T
                for e in range(E)]) for i in range(L)]),
            "w_up": np.stack([np.stack([np.asarray(state[
                f"model.layers.{i}.block_sparse_moe.experts.{e}.w3.weight"]).T
                for e in range(E)]) for i in range(L)]),
        }
    else:
        layers["mlp"] = {
            "w_gate": _stack(state, "model.layers.{i}.mlp.gate_proj.weight", L),
            "w_up": _stack(state, "model.layers.{i}.mlp.up_proj.weight", L),
            "w_down": _stack(state, "model.layers.{i}.mlp.down_proj.weight", L),
        }
    p["layers"] = layers
    if not cfg.tie_embeddings:
        key = ("lm_head.weight" if "lm_head.weight" in state
               else "model.embed_tokens.weight")
        p["lm_head"] = {"w": np.asarray(state[key]).T}
    return p


def _import_gpt2(cfg, state: Dict[str, np.ndarray]) -> Dict[str, Any]:
    L, H = cfg.n_layers, cfg.hidden_size

    def g(k):
        return np.asarray(state[k])

    # Conv1D stores [in, out]: no transpose anywhere
    c_attn_w = np.stack([g(f"transformer.h.{i}.attn.c_attn.weight")
                         for i in range(L)])  # [L, H, 3H]
    c_attn_b = np.stack([g(f"transformer.h.{i}.attn.c_attn.bias")
                         for i in range(L)])  # [L, 3H]
    wq, wk, wv = np.split(c_attn_w, 3, axis=2)
    bq, bk, bv = np.split(c_attn_b, 3, axis=1)
    p = {
        "embed": {"tok": g("transformer.wte.weight"),
                  "pos": g("transformer.wpe.weight")},
        "final_norm": {"scale": g("transformer.ln_f.weight"),
                       "bias": g("transformer.ln_f.bias")},
        "layers": {
            "attn": {
                "wq": wq, "wk": wk, "wv": wv,
                "bq": bq, "bk": bk, "bv": bv,
                "wo": np.stack([g(f"transformer.h.{i}.attn.c_proj.weight")
                                for i in range(L)]),
                "bo": np.stack([g(f"transformer.h.{i}.attn.c_proj.bias")
                                for i in range(L)]),
            },
            "mlp": {
                "w_up": np.stack([g(f"transformer.h.{i}.mlp.c_fc.weight")
                                  for i in range(L)]),
                "b_up": np.stack([g(f"transformer.h.{i}.mlp.c_fc.bias")
                                  for i in range(L)]),
                "w_down": np.stack([g(f"transformer.h.{i}.mlp.c_proj.weight")
                                    for i in range(L)]),
                "b_down": np.stack([g(f"transformer.h.{i}.mlp.c_proj.bias")
                                    for i in range(L)]),
            },
            "norm1": {"scale": np.stack([g(f"transformer.h.{i}.ln_1.weight")
                                         for i in range(L)]),
                      "bias": np.stack([g(f"transformer.h.{i}.ln_1.bias")
                                        for i in range(L)])},
            "norm2": {"scale": np.stack([g(f"transformer.h.{i}.ln_2.weight")
                                         for i in range(L)]),
                      "bias": np.stack([g(f"transformer.h.{i}.ln_2.bias")
                                        for i in range(L)])},
        },
    }
    return p


def _import_opt(cfg, state: Dict[str, np.ndarray]) -> Dict[str, Any]:
    """OPTForCausalLM: pre-norm decoder; ``embed_positions`` carries a +2
    padding offset — rows 0-1 are dropped so our ``positions = arange(S)``
    indexes the table the way OPT's ``position + 2`` does."""
    L = cfg.n_layers
    pre = "model.decoder"

    def g(k):
        return np.asarray(state[f"{pre}.{k}"])

    p: Dict[str, Any] = {
        "embed": {"tok": g("embed_tokens.weight"),
                  "pos": g("embed_positions.weight")[2:]},
        "final_norm": {"scale": g("final_layer_norm.weight"),
                       "bias": g("final_layer_norm.bias")},
    }
    attn = {k: _stack(state, f"{pre}.layers.{{i}}.self_attn.{hf}.weight", L)
            for k, hf in (("wq", "q_proj"), ("wk", "k_proj"),
                          ("wv", "v_proj"), ("wo", "out_proj"))}
    for k, hf in (("bq", "q_proj"), ("bk", "k_proj"), ("bv", "v_proj"),
                  ("bo", "out_proj")):
        attn[k] = _stack(state, f"{pre}.layers.{{i}}.self_attn.{hf}.bias", L,
                         transpose=False)
    p["layers"] = {
        "attn": attn,
        "mlp": {
            "w_up": _stack(state, f"{pre}.layers.{{i}}.fc1.weight", L),
            "b_up": _stack(state, f"{pre}.layers.{{i}}.fc1.bias", L,
                           transpose=False),
            "w_down": _stack(state, f"{pre}.layers.{{i}}.fc2.weight", L),
            "b_down": _stack(state, f"{pre}.layers.{{i}}.fc2.bias", L,
                             transpose=False),
        },
        "norm1": {"scale": _stack(
            state, f"{pre}.layers.{{i}}.self_attn_layer_norm.weight", L,
            transpose=False),
            "bias": _stack(
            state, f"{pre}.layers.{{i}}.self_attn_layer_norm.bias", L,
            transpose=False)},
        "norm2": {"scale": _stack(
            state, f"{pre}.layers.{{i}}.final_layer_norm.weight", L,
            transpose=False),
            "bias": _stack(
            state, f"{pre}.layers.{{i}}.final_layer_norm.bias", L,
            transpose=False)},
    }
    if not cfg.tie_embeddings and "lm_head.weight" in state:
        p["lm_head"] = {"w": np.asarray(state["lm_head.weight"]).T}
    return p


def _import_phi(cfg, state: Dict[str, np.ndarray]) -> Dict[str, Any]:
    """PhiForCausalLM: parallel attention+MLP sharing one input layernorm,
    partial rotary, biased projections AND a biased lm_head."""
    L = cfg.n_layers
    attn = {
        "wq": _stack(state, "model.layers.{i}.self_attn.q_proj.weight", L),
        "wk": _stack(state, "model.layers.{i}.self_attn.k_proj.weight", L),
        "wv": _stack(state, "model.layers.{i}.self_attn.v_proj.weight", L),
        "wo": _stack(state, "model.layers.{i}.self_attn.dense.weight", L),
        "bq": _stack(state, "model.layers.{i}.self_attn.q_proj.bias", L,
                     transpose=False),
        "bk": _stack(state, "model.layers.{i}.self_attn.k_proj.bias", L,
                     transpose=False),
        "bv": _stack(state, "model.layers.{i}.self_attn.v_proj.bias", L,
                     transpose=False),
        "bo": _stack(state, "model.layers.{i}.self_attn.dense.bias", L,
                     transpose=False),
    }
    p: Dict[str, Any] = {
        "embed": {"tok": np.asarray(state["model.embed_tokens.weight"])},
        "final_norm": {
            "scale": np.asarray(state["model.final_layernorm.weight"]),
            "bias": np.asarray(state["model.final_layernorm.bias"])},
        "layers": {
            "attn": attn,
            "mlp": {
                "w_up": _stack(state, "model.layers.{i}.mlp.fc1.weight", L),
                "b_up": _stack(state, "model.layers.{i}.mlp.fc1.bias", L,
                               transpose=False),
                "w_down": _stack(state, "model.layers.{i}.mlp.fc2.weight", L),
                "b_down": _stack(state, "model.layers.{i}.mlp.fc2.bias", L,
                                 transpose=False),
            },
            "norm1": {"scale": _stack(
                state, "model.layers.{i}.input_layernorm.weight", L,
                transpose=False),
                "bias": _stack(
                state, "model.layers.{i}.input_layernorm.bias", L,
                transpose=False)},
        },
    }
    if not cfg.tie_embeddings:
        p["lm_head"] = {"w": np.asarray(state["lm_head.weight"]).T,
                        "b": np.asarray(state["lm_head.bias"])}
    return p


def _import_bert(cfg, state: Dict[str, np.ndarray]) -> Dict[str, Any]:
    """BertForMaskedLM: post-norm encoder — attention.output.LayerNorm is
    the post-attention norm (our norm1), output.LayerNorm the post-FFN norm
    (norm2); embeddings get word+position+token_type then LayerNorm; the
    MLM head is dense+gelu+LayerNorm+tied-decoder+bias (cls.predictions)."""
    L = cfg.n_layers
    pre = "bert.encoder.layer"

    def g(k):
        return np.asarray(state[k])

    p: Dict[str, Any] = {
        "embed": {
            "tok": g("bert.embeddings.word_embeddings.weight"),
            "pos": g("bert.embeddings.position_embeddings.weight"),
            "type": g("bert.embeddings.token_type_embeddings.weight"),
            "norm": {"scale": g("bert.embeddings.LayerNorm.weight"),
                     "bias": g("bert.embeddings.LayerNorm.bias")},
        },
        "layers": {
            "attn": {
                "wq": _stack(state, pre + ".{i}.attention.self.query.weight", L),
                "wk": _stack(state, pre + ".{i}.attention.self.key.weight", L),
                "wv": _stack(state, pre + ".{i}.attention.self.value.weight", L),
                "wo": _stack(state, pre + ".{i}.attention.output.dense.weight", L),
                "bq": _stack(state, pre + ".{i}.attention.self.query.bias", L,
                             transpose=False),
                "bk": _stack(state, pre + ".{i}.attention.self.key.bias", L,
                             transpose=False),
                "bv": _stack(state, pre + ".{i}.attention.self.value.bias", L,
                             transpose=False),
                "bo": _stack(state, pre + ".{i}.attention.output.dense.bias", L,
                             transpose=False),
            },
            "mlp": {
                "w_up": _stack(state, pre + ".{i}.intermediate.dense.weight", L),
                "b_up": _stack(state, pre + ".{i}.intermediate.dense.bias", L,
                               transpose=False),
                "w_down": _stack(state, pre + ".{i}.output.dense.weight", L),
                "b_down": _stack(state, pre + ".{i}.output.dense.bias", L,
                                 transpose=False),
            },
            "norm1": {"scale": _stack(
                state, pre + ".{i}.attention.output.LayerNorm.weight", L,
                transpose=False),
                "bias": _stack(
                state, pre + ".{i}.attention.output.LayerNorm.bias", L,
                transpose=False)},
            "norm2": {"scale": _stack(
                state, pre + ".{i}.output.LayerNorm.weight", L,
                transpose=False),
                "bias": _stack(
                state, pre + ".{i}.output.LayerNorm.bias", L,
                transpose=False)},
        },
    }
    if "cls.predictions.transform.dense.weight" in state:
        p["mlm_head"] = {
            "dense_w": g("cls.predictions.transform.dense.weight").T,
            "dense_b": g("cls.predictions.transform.dense.bias"),
            "norm_scale": g("cls.predictions.transform.LayerNorm.weight"),
            "norm_bias": g("cls.predictions.transform.LayerNorm.bias"),
            "bias": g("cls.predictions.bias"),
        }
    return p


def _import_falcon(cfg, state: Dict[str, np.ndarray]) -> Dict[str, Any]:
    """FalconForCausalLM.  7b-style (old arch, multi-query): fused
    ``query_key_value`` rows are all query heads, then the shared k/v —
    block split; one shared ``input_layernorm``.  40b/180b-style (new
    decoder architecture, detected by the ``ln_attn`` keys): rows are
    GROUPED per kv-head as [q_1..q_{NH/KVH}, k, v], and the parallel
    branches carry separate ``ln_attn``/``ln_mlp`` norms (mlp_block uses a
    parallel layer's norm2 when present)."""
    L, NH, KVH, D = cfg.n_layers, cfg.n_heads, cfg.kv_heads, cfg.head_dim
    wq, wk, wv = [], [], []
    for i in range(L):
        w = np.asarray(
            state[f"transformer.h.{i}.self_attention.query_key_value.weight"])
        # grouped per-kv-head layout [q_1..q_{NH/KVH}, k, v]; at KVH=1
        # (old-arch multi-query) this coincides with the block layout, so
        # ONE split covers every supported falcon flavor
        g = w.reshape(KVH, NH // KVH + 2, D, w.shape[-1])
        q = g[:, :-2].reshape(NH * D, -1)
        k = g[:, -2].reshape(KVH * D, -1)
        v = g[:, -1].reshape(KVH * D, -1)
        wq.append(q.T)
        wk.append(k.T)
        wv.append(v.T)
    # config (not key-sniffing) decides the norm layout: a config/weights
    # mismatch then fails loudly on a missing key instead of silently
    # misreading (falcon-11B: new arch with ONE input_layernorm)
    norm1_name = ("ln_attn" if getattr(cfg, "parallel_norms", 1) >= 2
                  else "input_layernorm")
    new_arch = getattr(cfg, "parallel_norms", 1) >= 2
    p: Dict[str, Any] = {
        "embed": {"tok": np.asarray(state["transformer.word_embeddings.weight"])},
        "final_norm": {"scale": np.asarray(state["transformer.ln_f.weight"]),
                       "bias": np.asarray(state["transformer.ln_f.bias"])},
        "layers": {
            "attn": {
                "wq": np.stack(wq), "wk": np.stack(wk), "wv": np.stack(wv),
                "wo": _stack(
                    state, "transformer.h.{i}.self_attention.dense.weight", L),
            },
            "mlp": {
                "w_up": _stack(
                    state, "transformer.h.{i}.mlp.dense_h_to_4h.weight", L),
                "w_down": _stack(
                    state, "transformer.h.{i}.mlp.dense_4h_to_h.weight", L),
            },
            "norm1": {"scale": _stack(
                state, "transformer.h.{i}." + norm1_name + ".weight", L,
                transpose=False),
                "bias": _stack(
                state, "transformer.h.{i}." + norm1_name + ".bias", L,
                transpose=False)},
        },
    }
    if new_arch:
        p["layers"]["norm2"] = {
            "scale": _stack(state, "transformer.h.{i}.ln_mlp.weight", L,
                            transpose=False),
            "bias": _stack(state, "transformer.h.{i}.ln_mlp.bias", L,
                           transpose=False)}
    if not cfg.tie_embeddings and "lm_head.weight" in state:
        p["lm_head"] = {"w": np.asarray(state["lm_head.weight"]).T}
    return p


def load_hf_model(model_dir: str, dtype=None) -> Tuple[Any, Dict[str, Any]]:
    """One call from a published checkpoint directory to (config, params)
    ready for the training or inference engine:

        cfg, params = load_hf_model("/path/to/llama-2-7b")
        engine = InferenceEngine(llama_model(config=cfg), params=params)
    """
    import jax

    with open(os.path.join(model_dir, "config.json")) as f:
        raw = json.load(f)
    cfg = config_from_hf(raw)
    state = load_state_dict(model_dir)
    params = import_hf_params(cfg, state, raw.get("model_type", "llama"))
    dt = np.dtype(dtype) if dtype is not None else np.dtype(cfg.dtype)

    def to_host(a):
        # stay NUMPY (host): the engine's sharded device_put must be the
        # only transfer, or a 13B import OOMs one chip before TP/ZeRO ever
        # gets to shard it
        a = np.asarray(a)
        floating = (np.issubdtype(a.dtype, np.floating)
                    or str(a.dtype) == "bfloat16")
        return a.astype(dt) if floating else a

    params = jax.tree_util.tree_map(to_host, params)
    n = sum(int(np.prod(np.shape(a)))
            for a in jax.tree_util.tree_leaves(params))
    logger.info(f"hf_import: loaded {n / 1e6:.1f}M params "
                f"({raw.get('model_type', 'llama')}) from {model_dir}")
    return cfg, params


def _split_fused_qkv_per_head(w, b, NH, D):
    """HF bloom/gpt-neox fused ``query_key_value``: rows are PER-HEAD
    [q_h, k_h, v_h] triples — layout (NH, 3, D, in).  Returns transposed
    ([in, NH*D]) weights and [NH*D] biases for q/k/v."""
    win = w.shape[-1]
    g = np.asarray(w).reshape(NH, 3, D, win)
    ws = [g[:, j].reshape(NH * D, win).T for j in range(3)]
    bs = [None] * 3
    if b is not None:
        gb = np.asarray(b).reshape(NH, 3, D)
        bs = [gb[:, j].reshape(NH * D) for j in range(3)]
    return ws, bs


def _import_neox_style(cfg, state, layer_fmt: str, attn: str):
    """Shared bloom/gpt-neox layer importer: per-head fused QKV split,
    dense_h_to_4h/dense_4h_to_h MLP, input/post-attention layernorms.
    ``layer_fmt``: e.g. "transformer.h.{i}."; ``attn``: the attention
    module name ("self_attention" / "attention")."""
    L, NH, D = cfg.n_layers, cfg.n_heads, cfg.head_dim
    wq, wk, wv, bq, bk, bv = [], [], [], [], [], []
    for i in range(L):
        pre = layer_fmt.format(i=i) + attn + ".query_key_value"
        ws, bs = _split_fused_qkv_per_head(
            state[f"{pre}.weight"], state.get(f"{pre}.bias"), NH, D)
        wq.append(ws[0]); wk.append(ws[1]); wv.append(ws[2])
        bq.append(bs[0]); bk.append(bs[1]); bv.append(bs[2])
    h = layer_fmt
    return {
        "attn": {
            "wq": np.stack(wq), "wk": np.stack(wk), "wv": np.stack(wv),
            "bq": np.stack(bq), "bk": np.stack(bk), "bv": np.stack(bv),
            "wo": _stack(state, h + attn + ".dense.weight", L),
            "bo": _stack(state, h + attn + ".dense.bias", L,
                         transpose=False),
        },
        "mlp": {
            "w_up": _stack(state, h + "mlp.dense_h_to_4h.weight", L),
            "b_up": _stack(state, h + "mlp.dense_h_to_4h.bias", L,
                           transpose=False),
            "w_down": _stack(state, h + "mlp.dense_4h_to_h.weight", L),
            "b_down": _stack(state, h + "mlp.dense_4h_to_h.bias", L,
                             transpose=False),
        },
        "norm1": {
            "scale": _stack(state, h + "input_layernorm.weight", L,
                            transpose=False),
            "bias": _stack(state, h + "input_layernorm.bias", L,
                           transpose=False)},
        "norm2": {
            "scale": _stack(state, h + "post_attention_layernorm.weight",
                            L, transpose=False),
            "bias": _stack(state, h + "post_attention_layernorm.bias", L,
                           transpose=False)},
    }


def _import_bloom(cfg, state: Dict[str, np.ndarray]) -> Dict[str, Any]:
    """BloomForCausalLM: ALiBi (no position table), per-head-fused QKV,
    word_embeddings_layernorm, biases everywhere, head tied by default
    (untied variants carry their own lm_head.weight)."""
    p = {
        "embed": {
            "tok": np.asarray(state["transformer.word_embeddings.weight"]),
            "norm": {
                "scale": np.asarray(
                    state["transformer.word_embeddings_layernorm.weight"]),
                "bias": np.asarray(
                    state["transformer.word_embeddings_layernorm.bias"])},
        },
        "final_norm": {"scale": np.asarray(state["transformer.ln_f.weight"]),
                       "bias": np.asarray(state["transformer.ln_f.bias"])},
        "layers": _import_neox_style(cfg, state, "transformer.h.{i}.",
                                     "self_attention"),
    }
    if not cfg.tie_embeddings:
        p["lm_head"] = {"w": np.asarray(state["lm_head.weight"]).T}
    return p


def _import_gpt_neox(cfg, state: Dict[str, np.ndarray]) -> Dict[str, Any]:
    """GPTNeoXForCausalLM: per-head-fused QKV, partial rotary, parallel
    residual with separate input/post-attention norms, untied embed_out."""
    p = {
        "embed": {"tok": np.asarray(state["gpt_neox.embed_in.weight"])},
        "final_norm": {
            "scale": np.asarray(state["gpt_neox.final_layer_norm.weight"]),
            "bias": np.asarray(state["gpt_neox.final_layer_norm.bias"])},
        "layers": _import_neox_style(cfg, state, "gpt_neox.layers.{i}.",
                                     "attention"),
    }
    if not cfg.tie_embeddings:
        p["lm_head"] = {"w": np.asarray(state["embed_out.weight"]).T}
    return p
