"""Export trained parameters to a Hugging Face checkpoint directory.

Reference parity: the consolidation/export story —
``utils/zero_to_fp32.py`` (offline fp32 state-dict consolidation),
``engine.save_16bit_model`` / ``_zero3_consolidated_16bit_state_dict``
(gathered 16-bit export for downstream serving).  Here the engine's param
tree is already reassembled by ``jax.device_get`` (XLA gathers shards), so
export reduces to the inverse name map of ``hf_import`` plus a native
safetensors writer — the result loads in ``transformers.from_pretrained``.

Families: llama / mistral / qwen2 (rotate-half RoPE, same layout), mixtral
(expert-stacked MoE), gpt2 (Conv1D, no transposes), opt (position offset
re-added), phi (biased head), falcon (7b-style re-fused multi-query QKV).
Unrepresentable states (PR-MoE residuals, untied gpt2 head, biased or
grouped-KV falcon) are refused rather than silently dropped.
"""

from __future__ import annotations

import json
import os
import struct
from typing import Any, Dict

import numpy as np

from ..utils.logging import logger

_NP_TO_ST = {"float64": "F64", "float32": "F32", "float16": "F16",
             "int64": "I64", "int32": "I32", "int16": "I16", "int8": "I8",
             "uint8": "U8", "bool": "BOOL", "bfloat16": "BF16"}


def write_safetensors(path: str, tensors: Dict[str, np.ndarray]) -> None:
    """Native safetensors writer (inverse of hf_import.read_safetensors)."""
    header: Dict[str, Any] = {}
    off = 0
    for name, arr in tensors.items():
        raw_len = arr.nbytes
        header[name] = {"dtype": _NP_TO_ST[str(arr.dtype)],
                        "shape": list(arr.shape),
                        "data_offsets": [off, off + raw_len]}
        off += raw_len
    hjson = json.dumps(header).encode()
    with open(path, "wb") as f:
        f.write(struct.pack("<Q", len(hjson)))
        f.write(hjson)
        for arr in tensors.values():
            f.write(np.ascontiguousarray(arr).tobytes())


def _unstack(stacked, transpose: bool = True):
    for i in range(stacked.shape[0]):
        w = np.asarray(stacked[i])
        yield i, (w.T if transpose else w)


def export_hf_state(cfg, params: Dict[str, Any],
                    model_type: str = "llama") -> Dict[str, np.ndarray]:
    """Param tree -> HF state dict (numpy)."""
    host = {}

    def get(tree):
        import jax

        return np.asarray(jax.device_get(tree))

    if model_type == "bert":
        return _export_bert(cfg, params, get)
    if model_type == "opt":
        return _export_opt(cfg, params, get)
    if model_type == "phi":
        return _export_phi(cfg, params, get)
    if model_type == "falcon":
        return _export_falcon(cfg, params, get)
    if model_type == "bloom":
        return _export_bloom(cfg, params, get)
    if model_type == "gpt_neox":
        return _export_gpt_neox(cfg, params, get)
    if model_type == "qwen2_moe":
        return _export_qwen2_moe(cfg, params, get)
    if model_type == "phi3":
        # llama layout first, then RE-FUSE the projections the way HF
        # Phi3 stores them: qkv_proj rows are [q | k | v], gate_up_proj
        # rows are [gate | up] (exact inverse of the import split)
        host = export_hf_state(cfg, params, "llama")
        for i in range(cfg.n_layers):
            pre = f"model.layers.{i}"
            host[f"{pre}.self_attn.qkv_proj.weight"] = np.concatenate(
                [host.pop(f"{pre}.self_attn.{n}_proj.weight")
                 for n in ("q", "k", "v")], axis=0)
            host[f"{pre}.mlp.gate_up_proj.weight"] = np.concatenate(
                [host.pop(f"{pre}.mlp.gate_proj.weight"),
                 host.pop(f"{pre}.mlp.up_proj.weight")], axis=0)
        return host
    if model_type == "gpt2":
        if not cfg.tie_embeddings and "lm_head" in params:
            # GPT2LMHeadModel always ties lm_head to wte on load — an
            # untied head has no representation; refuse rather than let
            # transformers silently re-tie to different weights
            raise ValueError(
                "hf_export: gpt2 checkpoints are always tied in HF; an "
                "untied lm_head cannot be represented — retrain with "
                "tie_embeddings=True or export another family")
        return _export_gpt2(cfg, params, get)
    host["model.embed_tokens.weight"] = get(params["embed"]["tok"])
    host["model.norm.weight"] = get(params["final_norm"]["scale"])
    if not cfg.tie_embeddings and "lm_head" in params:
        host["lm_head.weight"] = get(params["lm_head"]["w"]).T
    layers = params["layers"]
    names = {"wq": "q_proj", "wk": "k_proj", "wv": "v_proj", "wo": "o_proj"}
    for ours, theirs in names.items():
        for i, w in _unstack(get(layers["attn"][ours])):
            host[f"model.layers.{i}.self_attn.{theirs}.weight"] = w
    if getattr(cfg, "qkv_bias", False):
        for ours, theirs in (("bq", "q_proj"), ("bk", "k_proj"),
                             ("bv", "v_proj")):
            for i, b in _unstack(get(layers["attn"][ours]), transpose=False):
                host[f"model.layers.{i}.self_attn.{theirs}.bias"] = b
    for i, s in _unstack(get(layers["norm1"]["scale"]), transpose=False):
        host[f"model.layers.{i}.input_layernorm.weight"] = s
    for i, s in _unstack(get(layers["norm2"]["scale"]), transpose=False):
        host[f"model.layers.{i}.post_attention_layernorm.weight"] = s
    mlp = layers["mlp"]
    if cfg.moe_experts > 0:  # mixtral
        if getattr(cfg, "moe_use_residual", False):
            # PR-MoE residual weights (res_w_up/res_w_down/coef) have no HF
            # mixtral counterpart — refuse rather than silently drop them
            raise ValueError(
                "hf_export: PR-MoE (moe_use_residual) has no mixtral "
                "checkpoint representation; export without residual experts")
        if getattr(cfg, "moe_shared_expert", 0) or not getattr(
                cfg, "moe_norm_topk", True):
            # qwen2-moe states (shared expert / raw-softmax routing) would
            # be silently dropped by the mixtral name map
            raise ValueError(
                "hf_export: this model carries qwen2-moe states "
                "(moe_shared_expert / moe_norm_topk=False) — export with "
                "model_type='qwen2_moe' instead of 'mixtral'")
        for i, g in _unstack(get(mlp["router"])):
            host[f"model.layers.{i}.block_sparse_moe.gate.weight"] = g
        wmap = {"w_gate": "w1", "w_down": "w2", "w_up": "w3"}
        for ours, theirs in wmap.items():
            full = get(mlp[ours])  # [L, E, in, out]
            for i in range(full.shape[0]):
                for e in range(full.shape[1]):
                    host[f"model.layers.{i}.block_sparse_moe.experts.{e}."
                         f"{theirs}.weight"] = np.asarray(full[i, e]).T
    else:
        wmap = {"w_gate": "gate_proj", "w_up": "up_proj", "w_down": "down_proj"}
        for ours, theirs in wmap.items():
            for i, w in _unstack(get(mlp[ours])):
                host[f"model.layers.{i}.mlp.{theirs}.weight"] = w
    return host


def _export_gpt2(cfg, params, get) -> Dict[str, np.ndarray]:
    L = cfg.n_layers
    host = {"transformer.wte.weight": get(params["embed"]["tok"]),
            "transformer.wpe.weight": get(params["embed"]["pos"]),
            "transformer.ln_f.weight": get(params["final_norm"]["scale"]),
            "transformer.ln_f.bias": get(params["final_norm"]["bias"])}
    a, m = params["layers"]["attn"], params["layers"]["mlp"]
    # one device_get per stacked tensor, OUTSIDE the per-layer loop
    wq, wk, wv = get(a["wq"]), get(a["wk"]), get(a["wv"])
    bq, bk, bv = get(a["bq"]), get(a["bk"]), get(a["bv"])
    wo, bo = get(a["wo"]), get(a["bo"])
    w_up, b_up = get(m["w_up"]), get(m["b_up"])
    w_down, b_down = get(m["w_down"]), get(m["b_down"])
    norms = {ln: (get(params["layers"][ln]["scale"]),
                  get(params["layers"][ln]["bias"]))
             for ln in ("norm1", "norm2")}
    for i in range(L):
        pre = f"transformer.h.{i}"
        host[f"{pre}.attn.c_attn.weight"] = np.concatenate(
            [wq[i], wk[i], wv[i]], axis=1)
        host[f"{pre}.attn.c_attn.bias"] = np.concatenate([bq[i], bk[i], bv[i]])
        host[f"{pre}.attn.c_proj.weight"] = wo[i]
        host[f"{pre}.attn.c_proj.bias"] = bo[i]
        host[f"{pre}.mlp.c_fc.weight"] = w_up[i]
        host[f"{pre}.mlp.c_fc.bias"] = b_up[i]
        host[f"{pre}.mlp.c_proj.weight"] = w_down[i]
        host[f"{pre}.mlp.c_proj.bias"] = b_down[i]
        for ln, theirs in (("norm1", "ln_1"), ("norm2", "ln_2")):
            host[f"{pre}.{theirs}.weight"] = norms[ln][0][i]
            host[f"{pre}.{theirs}.bias"] = norms[ln][1][i]
    return host


def _emit_stacked(host, get, tree, spec, fmt):
    """Write stacked [L, ...] tensors to per-layer HF names: ``spec`` is
    (hf_suffix, our_key, transpose) triples, ``fmt`` the name template."""
    for hf, ours, transpose in spec:
        for i, w in _unstack(get(tree[ours]), transpose=transpose):
            host[fmt.format(i=i, hf=hf)] = w


def _export_bert(cfg, params, get) -> Dict[str, np.ndarray]:
    if not getattr(cfg, "post_norm", False):
        raise ValueError(
            "hf_export: bert checkpoints are post-norm; a pre-norm model "
            "has no BERT representation")
    if "type" not in params.get("embed", {}):
        raise ValueError(
            "hf_export: bert checkpoints carry token_type embeddings; a "
            "model trained with type_vocab_size=0 has no representation")
    if "mlm_head" not in params:
        # BertForMaskedLM would random-init cls.predictions on load and
        # produce garbage MLM logits with only a warning
        raise ValueError(
            "hf_export: this bert model has no mlm_head (plain tied "
            "projection); BERT checkpoints need the full prediction head — "
            "import one from HF or add an mlm_head before exporting")
    host = {
        "bert.embeddings.word_embeddings.weight": get(params["embed"]["tok"]),
        "bert.embeddings.position_embeddings.weight": get(params["embed"]["pos"]),
        "bert.embeddings.token_type_embeddings.weight": get(params["embed"]["type"]),
        "bert.embeddings.LayerNorm.weight": get(params["embed"]["norm"]["scale"]),
        "bert.embeddings.LayerNorm.bias": get(params["embed"]["norm"]["bias"]),
    }
    a, m = params["layers"]["attn"], params["layers"]["mlp"]
    fmt = "bert.encoder.layer.{i}.{hf}"
    _emit_stacked(host, get, a, [
        ("attention.self.query.weight", "wq", True),
        ("attention.self.key.weight", "wk", True),
        ("attention.self.value.weight", "wv", True),
        ("attention.output.dense.weight", "wo", True),
        ("attention.self.query.bias", "bq", False),
        ("attention.self.key.bias", "bk", False),
        ("attention.self.value.bias", "bv", False),
        ("attention.output.dense.bias", "bo", False)], fmt)
    _emit_stacked(host, get, m, [
        ("intermediate.dense.weight", "w_up", True),
        ("intermediate.dense.bias", "b_up", False),
        ("output.dense.weight", "w_down", True),
        ("output.dense.bias", "b_down", False)], fmt)
    for ln, hf in (("norm1", "attention.output.LayerNorm"),
                   ("norm2", "output.LayerNorm")):
        _emit_stacked(host, get, params["layers"][ln], [
            (f"{hf}.weight", "scale", False), (f"{hf}.bias", "bias", False)],
            fmt)
    mh = params.get("mlm_head")
    if mh is not None:
        host["cls.predictions.transform.dense.weight"] = get(mh["dense_w"]).T
        host["cls.predictions.transform.dense.bias"] = get(mh["dense_b"])
        host["cls.predictions.transform.LayerNorm.weight"] = get(mh["norm_scale"])
        host["cls.predictions.transform.LayerNorm.bias"] = get(mh["norm_bias"])
        host["cls.predictions.bias"] = get(mh["bias"])
    return host


def _export_opt(cfg, params, get) -> Dict[str, np.ndarray]:
    pre = "model.decoder"
    pos = get(params["embed"]["pos"])
    host = {
        f"{pre}.embed_tokens.weight": get(params["embed"]["tok"]),
        # re-add OPT's two padding-offset rows (dropped at import; zeros —
        # they are only read for pad positions)
        f"{pre}.embed_positions.weight": np.concatenate(
            [np.zeros((2, pos.shape[1]), pos.dtype), pos]),
        f"{pre}.final_layer_norm.weight": get(params["final_norm"]["scale"]),
        f"{pre}.final_layer_norm.bias": get(params["final_norm"]["bias"]),
    }
    a, m = params["layers"]["attn"], params["layers"]["mlp"]
    fmt = pre + ".layers.{i}.{hf}"
    _emit_stacked(host, get, a, [
        ("self_attn.q_proj.weight", "wq", True),
        ("self_attn.k_proj.weight", "wk", True),
        ("self_attn.v_proj.weight", "wv", True),
        ("self_attn.out_proj.weight", "wo", True),
        ("self_attn.q_proj.bias", "bq", False),
        ("self_attn.k_proj.bias", "bk", False),
        ("self_attn.v_proj.bias", "bv", False),
        ("self_attn.out_proj.bias", "bo", False)], fmt)
    _emit_stacked(host, get, m, [
        ("fc1.weight", "w_up", True), ("fc1.bias", "b_up", False),
        ("fc2.weight", "w_down", True), ("fc2.bias", "b_down", False)], fmt)
    for ln, hf in (("norm1", "self_attn_layer_norm"),
                   ("norm2", "final_layer_norm")):
        _emit_stacked(host, get, params["layers"][ln], [
            (f"{hf}.weight", "scale", False), (f"{hf}.bias", "bias", False)],
            fmt)
    if not cfg.tie_embeddings and "lm_head" in params:
        host["lm_head.weight"] = get(params["lm_head"]["w"]).T
    return host


def _export_phi(cfg, params, get) -> Dict[str, np.ndarray]:
    if not getattr(cfg, "parallel_block", False):
        raise ValueError(
            "hf_export: phi checkpoints are parallel-attention; a "
            "sequential-block model's norm2 weights have no representation")
    host = {
        "model.embed_tokens.weight": get(params["embed"]["tok"]),
        "model.final_layernorm.weight": get(params["final_norm"]["scale"]),
        "model.final_layernorm.bias": get(params["final_norm"]["bias"]),
    }
    a, m = params["layers"]["attn"], params["layers"]["mlp"]
    fmt = "model.layers.{i}.{hf}"
    _emit_stacked(host, get, a, [
        ("self_attn.q_proj.weight", "wq", True),
        ("self_attn.k_proj.weight", "wk", True),
        ("self_attn.v_proj.weight", "wv", True),
        ("self_attn.dense.weight", "wo", True),
        ("self_attn.q_proj.bias", "bq", False),
        ("self_attn.k_proj.bias", "bk", False),
        ("self_attn.v_proj.bias", "bv", False),
        ("self_attn.dense.bias", "bo", False)], fmt)
    _emit_stacked(host, get, m, [
        ("mlp.fc1.weight", "w_up", True), ("mlp.fc1.bias", "b_up", False),
        ("mlp.fc2.weight", "w_down", True),
        ("mlp.fc2.bias", "b_down", False)], fmt)
    _emit_stacked(host, get, params["layers"]["norm1"], [
        ("input_layernorm.weight", "scale", False),
        ("input_layernorm.bias", "bias", False)], fmt)
    if not cfg.tie_embeddings and "lm_head" in params:
        host["lm_head.weight"] = get(params["lm_head"]["w"]).T
        b = params["lm_head"].get("b")
        # natively-trained phi-family models init only 'w'; PhiForCausalLM
        # always has the bias parameter, so write zeros when absent
        host["lm_head.bias"] = (get(b) if b is not None else
                                np.zeros(cfg.vocab_size,
                                         host["lm_head.weight"].dtype))
    return host


def _export_falcon(cfg, params, get) -> Dict[str, np.ndarray]:
    if not getattr(cfg, "parallel_block", False):
        raise ValueError(
            "hf_export: falcon checkpoints are parallel-attention; a "
            "sequential-block model's norm2 weights have no representation")
    if getattr(cfg, "use_bias", False):
        raise ValueError(
            "hf_export: biased falcon-family models have no 7b-style "
            "checkpoint representation (falcon bias=false) — retrain "
            "without use_bias or export another family")
    if cfg.kv_heads != 1 or getattr(cfg, "parallel_norms", 1) != 1:
        raise ValueError(
            "hf_export: only multi-query (kv_heads=1, single-norm) falcon "
            "models map onto the 7b-style fused QKV layout; grouped-KV / "
            "dual-norm falcon (new_decoder_architecture) is not supported")
    L = cfg.n_layers
    host = {
        "transformer.word_embeddings.weight": get(params["embed"]["tok"]),
        "transformer.ln_f.weight": get(params["final_norm"]["scale"]),
        "transformer.ln_f.bias": get(params["final_norm"]["bias"]),
    }
    a, m = params["layers"]["attn"], params["layers"]["mlp"]
    wq, wk, wv = get(a["wq"]), get(a["wk"]), get(a["wv"])
    wo = get(a["wo"])
    w_up, w_down = get(m["w_up"]), get(m["w_down"])
    sc, bi = get(params["layers"]["norm1"]["scale"]), get(params["layers"]["norm1"]["bias"])
    for i in range(L):
        pre = f"transformer.h.{i}"
        # re-fuse q|k|v rows ([out, in] orientation)
        host[f"{pre}.self_attention.query_key_value.weight"] = \
            np.concatenate([np.asarray(wq[i]).T, np.asarray(wk[i]).T,
                            np.asarray(wv[i]).T])
        host[f"{pre}.self_attention.dense.weight"] = np.asarray(wo[i]).T
        host[f"{pre}.mlp.dense_h_to_4h.weight"] = np.asarray(w_up[i]).T
        host[f"{pre}.mlp.dense_4h_to_h.weight"] = np.asarray(w_down[i]).T
        host[f"{pre}.input_layernorm.weight"] = np.asarray(sc[i])
        host[f"{pre}.input_layernorm.bias"] = np.asarray(bi[i])
    if not cfg.tie_embeddings and "lm_head" in params:
        host["lm_head.weight"] = get(params["lm_head"]["w"]).T
    return host


def _export_qwen2_moe(cfg, params, get) -> Dict[str, np.ndarray]:
    """Inverse of the qwen2_moe import map: routed experts under
    mlp.experts.{e}, the shared expert + its sigmoid gate, router at
    mlp.gate, qwen2-style qkv biases."""
    if not getattr(cfg, "moe_experts", 0):
        raise ValueError("hf_export: qwen2_moe export needs an MoE model "
                         "(moe_experts > 0)")
    if getattr(cfg, "moe_use_residual", False):
        raise ValueError("hf_export: PR-MoE residual weights have no "
                         "qwen2_moe representation")
    if not getattr(cfg, "moe_shared_expert", 0):
        # HF Qwen2Moe unconditionally builds the shared expert, and the
        # importer expects its weights back
        raise ValueError("hf_export: qwen2_moe checkpoints require a "
                         "shared expert (moe_shared_expert > 0); export "
                         "shared-expert-free MoE as model_type='mixtral'")
    if not getattr(cfg, "qkv_bias", False):
        raise ValueError("hf_export: qwen2_moe checkpoints carry q/k/v "
                         "biases; retrain with qkv_bias=True (an absent "
                         "bias would crash the qwen2_moe importer)")
    host: Dict[str, np.ndarray] = {}
    host["model.embed_tokens.weight"] = get(params["embed"]["tok"])
    host["model.norm.weight"] = get(params["final_norm"]["scale"])
    if not cfg.tie_embeddings and "lm_head" in params:
        host["lm_head.weight"] = get(params["lm_head"]["w"]).T
    layers = params["layers"]
    _emit_stacked(host, get, layers["attn"], [
        ("q_proj.weight", "wq", True), ("k_proj.weight", "wk", True),
        ("v_proj.weight", "wv", True), ("o_proj.weight", "wo", True),
        ("q_proj.bias", "bq", False), ("k_proj.bias", "bk", False),
        ("v_proj.bias", "bv", False),
    ], "model.layers.{i}.self_attn.{hf}")
    _emit_stacked(host, get, layers["norm1"], [
        ("weight", "scale", False)], "model.layers.{i}.input_layernorm.{hf}")
    _emit_stacked(host, get, layers["norm2"], [
        ("weight", "scale", False)],
        "model.layers.{i}.post_attention_layernorm.{hf}")
    mlp = layers["mlp"]
    _emit_stacked(host, get, mlp, [
        ("gate.weight", "router", True),
        ("shared_expert.gate_proj.weight", "shared_w_gate", True),
        ("shared_expert.up_proj.weight", "shared_w_up", True),
        ("shared_expert.down_proj.weight", "shared_w_down", True),
        ("shared_expert_gate.weight", "shared_gate", True),
    ], "model.layers.{i}.mlp.{hf}")
    for ours, theirs in {"w_gate": "gate_proj", "w_up": "up_proj",
                         "w_down": "down_proj"}.items():
        full = get(mlp[ours])  # [L, E, in, out]
        for i in range(full.shape[0]):
            for e in range(full.shape[1]):
                host[f"model.layers.{i}.mlp.experts.{e}.{theirs}.weight"] = \
                    np.asarray(full[i, e]).T
    return host


def hf_config_dict(cfg, model_type: str = "llama") -> Dict[str, Any]:
    if model_type == "gpt2":
        return {"model_type": "gpt2", "architectures": ["GPT2LMHeadModel"],
                "vocab_size": cfg.vocab_size, "n_embd": cfg.hidden_size,
                "n_layer": cfg.n_layers, "n_head": cfg.n_heads,
                "n_positions": cfg.max_seq_len,
                "n_inner": cfg.ffn_size,
                "layer_norm_epsilon": cfg.norm_eps}
    if model_type == "bert":
        return {"model_type": "bert", "architectures": ["BertForMaskedLM"],
                "vocab_size": cfg.vocab_size,
                "hidden_size": cfg.hidden_size,
                "num_hidden_layers": cfg.n_layers,
                "num_attention_heads": cfg.n_heads,
                "intermediate_size": cfg.ffn_size,
                "max_position_embeddings": cfg.max_seq_len,
                "type_vocab_size": getattr(cfg, "type_vocab_size", 2),
                # inverse of the import map: our "gelu" is HF's tanh
                # approximation ("gelu_new"); "gelu_exact" is HF "gelu"
                "hidden_act": {"gelu_exact": "gelu", "gelu": "gelu_new",
                               "relu": "relu"}.get(cfg.activation, "gelu"),
                "layer_norm_eps": cfg.norm_eps,
                "tie_word_embeddings": True}
    if model_type == "opt":
        return {"model_type": "opt", "architectures": ["OPTForCausalLM"],
                "vocab_size": cfg.vocab_size,
                "hidden_size": cfg.hidden_size,
                "num_hidden_layers": cfg.n_layers,
                "num_attention_heads": cfg.n_heads,
                "ffn_dim": cfg.ffn_size,
                "max_position_embeddings": cfg.max_seq_len,
                "do_layer_norm_before": True,
                "word_embed_proj_dim": cfg.hidden_size,
                "activation_function": ("relu" if cfg.activation == "relu"
                                        else "gelu_new" if cfg.activation == "gelu"
                                        else "gelu"),
                "tie_word_embeddings": bool(cfg.tie_embeddings)}
    if model_type == "phi":
        return {"model_type": "phi", "architectures": ["PhiForCausalLM"],
                "vocab_size": cfg.vocab_size,
                "hidden_size": cfg.hidden_size,
                "num_hidden_layers": cfg.n_layers,
                "num_attention_heads": cfg.n_heads,
                "num_key_value_heads": cfg.kv_heads,
                "intermediate_size": cfg.ffn_size,
                "max_position_embeddings": cfg.max_seq_len,
                "partial_rotary_factor": cfg.rotary_pct,
                "layer_norm_eps": cfg.norm_eps,
                "rope_theta": cfg.rope_theta,
                "tie_word_embeddings": bool(cfg.tie_embeddings)}
    if model_type == "bloom":
        return {"model_type": "bloom",
                "architectures": ["BloomForCausalLM"],
                "vocab_size": cfg.vocab_size,
                "hidden_size": cfg.hidden_size,
                "n_layer": cfg.n_layers, "n_head": cfg.n_heads,
                "seq_length": cfg.max_seq_len,
                "layer_norm_epsilon": cfg.norm_eps,
                "tie_word_embeddings": bool(cfg.tie_embeddings)}
    if model_type == "gpt_neox":
        return {"model_type": "gpt_neox",
                "architectures": ["GPTNeoXForCausalLM"],
                "vocab_size": cfg.vocab_size,
                "hidden_size": cfg.hidden_size,
                "num_hidden_layers": cfg.n_layers,
                "num_attention_heads": cfg.n_heads,
                "intermediate_size": cfg.ffn_size,
                "max_position_embeddings": cfg.max_seq_len,
                "rotary_pct": cfg.rotary_pct,
                "rotary_emb_base": cfg.rope_theta,
                "use_parallel_residual": True,
                "hidden_act": ("gelu" if cfg.activation == "gelu_exact"
                               else "gelu_new"),
                "layer_norm_eps": cfg.norm_eps,
                "tie_word_embeddings": bool(cfg.tie_embeddings)}
    if model_type == "falcon":
        return {"model_type": "falcon",
                "architectures": ["FalconForCausalLM"],
                "vocab_size": cfg.vocab_size,
                "hidden_size": cfg.hidden_size,
                "num_hidden_layers": cfg.n_layers,
                "num_attention_heads": cfg.n_heads,
                "multi_query": True,
                "num_kv_heads": 1,
                "new_decoder_architecture": False,
                "parallel_attn": True, "bias": False,
                "max_position_embeddings": cfg.max_seq_len,
                "layer_norm_epsilon": cfg.norm_eps,
                "rope_theta": cfg.rope_theta,
                "tie_word_embeddings": bool(cfg.tie_embeddings)}
    arch = {"llama": "LlamaForCausalLM", "mistral": "MistralForCausalLM",
            "qwen2": "Qwen2ForCausalLM", "phi3": "Phi3ForCausalLM",
            "mixtral": "MixtralForCausalLM"}.get(model_type,
                                                 "LlamaForCausalLM")
    out = {"model_type": model_type, "architectures": [arch],
           "vocab_size": cfg.vocab_size, "hidden_size": cfg.hidden_size,
           "num_hidden_layers": cfg.n_layers,
           "num_attention_heads": cfg.n_heads,
           "num_key_value_heads": cfg.kv_heads,
           "intermediate_size": cfg.intermediate_size or cfg.ffn_size,
           "max_position_embeddings": cfg.max_seq_len,
           "rms_norm_eps": cfg.norm_eps, "rope_theta": cfg.rope_theta,
           "tie_word_embeddings": bool(cfg.tie_embeddings)}
    if model_type == "mixtral":
        out["num_local_experts"] = cfg.moe_experts
        out["num_experts_per_tok"] = cfg.moe_top_k
    if model_type == "qwen2_moe":
        out["architectures"] = ["Qwen2MoeForCausalLM"]
        out["num_experts"] = cfg.moe_experts
        out["num_experts_per_tok"] = cfg.moe_top_k
        out["moe_intermediate_size"] = cfg.ffn_size
        out["shared_expert_intermediate_size"] = cfg.moe_shared_expert
        out["norm_topk_prob"] = bool(cfg.moe_norm_topk)
        out["decoder_sparse_step"] = 1
        out["mlp_only_layers"] = []
    if model_type == "phi3":
        # Phi3Config's default pad_token_id (32000) would exceed a small
        # exported vocab and fail Embedding construction on load
        out["pad_token_id"] = 0
    return out


def checkpoint_to_hf(ckpt_dir: str, tag: str, out_dir: str, cfg,
                     model_type: str = "llama", dtype=None) -> str:
    """Native checkpoint -> transformers-loadable directory (the
    reference's offline ``zero_to_fp32.py`` + HF-export flow, without
    loading an engine).  Handles BOTH layouts: the partitioned per-rank
    shard files (assembled from the exact index metadata) and the simple
    consolidated ``model_states.npz``.  Keys are ``jax.tree_util.keystr``
    paths under ``.params``."""
    import re

    from .partitioned import META_FILE as PART_META, _assemble

    path = os.path.join(ckpt_dir, tag)
    if os.path.exists(os.path.join(path, PART_META)):
        # only materialize .params — optimizer moments are 2-3x the bytes
        full = _assemble(path, prefix=".params")
    else:
        from .saving import META_FILE, MODEL_FILE

        with np.load(os.path.join(path, MODEL_FILE)) as z:
            full = {k: z[k] for k in z.files if k.startswith(".params")}
        with open(os.path.join(path, META_FILE)) as f:
            bf16 = json.load(f).get("bfloat16_keys", {})
        for k in bf16:
            if k not in full:
                continue
            import ml_dtypes

            full[k] = full[k].view(np.dtype(ml_dtypes.bfloat16))
    params: Dict[str, Any] = {}
    for key, arr in full.items():
        if not key.startswith(".params"):
            continue
        if arr.dtype == np.uint16:  # stored bf16
            import ml_dtypes

            arr = arr.view(np.dtype(ml_dtypes.bfloat16))
        node = params
        parts = re.findall(r"\['([^']+)'\]", key)
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr
    # the config is supplied by the caller (family:size), not stored in the
    # checkpoint — validate it against the actual tensors before mapping,
    # or a dims mismatch surfaces as a confusing transformers load error
    tok = params.get("embed", {}).get("tok")
    if tok is not None and tuple(tok.shape) != (cfg.vocab_size,
                                                cfg.hidden_size):
        raise ValueError(
            f"checkpoint embed table is {tuple(tok.shape)} but the supplied "
            f"config says (vocab={cfg.vocab_size}, hidden={cfg.hidden_size})"
            f" — pass the config the model was trained with (CLI: "
            f"--override vocab_size=... hidden_size=...)")
    wq = params.get("layers", {}).get("attn", {}).get("wq")
    if wq is not None and wq.shape[0] != cfg.n_layers:
        raise ValueError(
            f"checkpoint has {wq.shape[0]} layers but the supplied config "
            f"says n_layers={cfg.n_layers}")
    if ("lm_head" in params) != (not cfg.tie_embeddings):
        # a tied checkpoint exported as untied would make transformers
        # random-init lm_head — garbage logits with only a warning
        raise ValueError(
            f"checkpoint {'has' if 'lm_head' in params else 'lacks'} an "
            f"lm_head but the supplied config says tie_embeddings="
            f"{cfg.tie_embeddings} — pass --override tie_embeddings="
            f"{str('lm_head' not in params).lower()}")
    save_hf_checkpoint(out_dir, cfg, params, model_type, dtype=dtype)
    return out_dir


def save_hf_checkpoint(model_dir: str, cfg, params: Dict[str, Any],
                       model_type: str = "llama", dtype=None) -> None:
    """Write a transformers-loadable checkpoint directory:
    ``config.json`` + ``model.safetensors``.

        engine.save_checkpoint(...)                  # native resume format
        save_hf_checkpoint("out/", cfg, engine.state.params)  # HF export
    """
    os.makedirs(model_dir, exist_ok=True)
    state = export_hf_state(cfg, params, model_type)
    if dtype is not None:
        dt = np.dtype(dtype)
        state = {k: (v.astype(dt)
                     if np.issubdtype(v.dtype, np.floating)
                     or str(v.dtype) == "bfloat16" else v)
                 for k, v in state.items()}
    write_safetensors(os.path.join(model_dir, "model.safetensors"), state)
    hf_cfg = hf_config_dict(cfg, model_type)
    # torch_dtype must describe what was actually WRITTEN, or
    # from_pretrained(torch_dtype='auto') materializes the wrong precision
    widest = max((str(v.dtype) for v in state.values()
                  if np.issubdtype(v.dtype, np.floating)
                  or str(v.dtype) == "bfloat16"),
                 key=lambda s: {"float16": 2, "bfloat16": 2,
                                "float32": 4, "float64": 8}.get(s, 4),
                 default="float32")
    hf_cfg["torch_dtype"] = widest
    with open(os.path.join(model_dir, "config.json"), "w") as f:
        json.dump(hf_cfg, f, indent=1)
    n = sum(v.size for v in state.values())
    logger.info(f"hf_export: wrote {n / 1e6:.1f}M params "
                f"({model_type}) to {model_dir}")


def _fuse_qkv_per_head(wq, wk, wv, bq, bk, bv, NH, D):
    """Inverse of hf_import._split_fused_qkv_per_head: [in, NH*D] weights
    (and [NH*D] biases) -> per-head-interleaved fused [(NH*3*D), in]."""
    win = wq.shape[0]
    g = np.stack([np.asarray(w).T.reshape(NH, D, win)
                  for w in (wq, wk, wv)], axis=1)  # [NH, 3, D, in]
    fused_w = g.reshape(NH * 3 * D, win)
    fused_b = np.stack([np.asarray(b).reshape(NH, D)
                        for b in (bq, bk, bv)], axis=1).reshape(NH * 3 * D)
    return fused_w, fused_b


def _export_neox_style_layers(cfg, params, get, host, layer_fmt, attn):
    """Shared bloom/gpt-neox layer exporter (inverse of
    hf_import._import_neox_style)."""
    L, NH, D = cfg.n_layers, cfg.n_heads, cfg.head_dim
    lay = params["layers"]
    a, m = lay["attn"], lay["mlp"]
    for i in range(L):
        pre = layer_fmt.format(i=i)
        fw, fb = _fuse_qkv_per_head(
            get(a["wq"][i]), get(a["wk"][i]), get(a["wv"][i]),
            get(a["bq"][i]), get(a["bk"][i]), get(a["bv"][i]), NH, D)
        host[f"{pre}{attn}.query_key_value.weight"] = fw
        host[f"{pre}{attn}.query_key_value.bias"] = fb
        host[f"{pre}{attn}.dense.weight"] = get(a["wo"][i]).T
        host[f"{pre}{attn}.dense.bias"] = get(a["bo"][i])
        host[f"{pre}mlp.dense_h_to_4h.weight"] = get(m["w_up"][i]).T
        host[f"{pre}mlp.dense_h_to_4h.bias"] = get(m["b_up"][i])
        host[f"{pre}mlp.dense_4h_to_h.weight"] = get(m["w_down"][i]).T
        host[f"{pre}mlp.dense_4h_to_h.bias"] = get(m["b_down"][i])
        for ours, theirs in (("norm1", "input_layernorm"),
                             ("norm2", "post_attention_layernorm")):
            host[f"{pre}{theirs}.weight"] = get(lay[ours]["scale"][i])
            host[f"{pre}{theirs}.bias"] = get(lay[ours]["bias"][i])
    return host


def _export_bloom(cfg, params, get) -> Dict[str, np.ndarray]:
    emb = params["embed"]
    host = {
        "transformer.word_embeddings.weight": get(emb["tok"]),
        "transformer.word_embeddings_layernorm.weight": get(emb["norm"]["scale"]),
        "transformer.word_embeddings_layernorm.bias": get(emb["norm"]["bias"]),
        "transformer.ln_f.weight": get(params["final_norm"]["scale"]),
        "transformer.ln_f.bias": get(params["final_norm"]["bias"]),
    }
    host = _export_neox_style_layers(cfg, params, get, host,
                                     "transformer.h.{i}.", "self_attention")
    if not cfg.tie_embeddings and "lm_head" in params:
        host["lm_head.weight"] = get(params["lm_head"]["w"]).T
    return host


def _export_gpt_neox(cfg, params, get) -> Dict[str, np.ndarray]:
    host = {
        "gpt_neox.embed_in.weight": get(params["embed"]["tok"]),
        "gpt_neox.final_layer_norm.weight": get(params["final_norm"]["scale"]),
        "gpt_neox.final_layer_norm.bias": get(params["final_norm"]["bias"]),
    }
    host = _export_neox_style_layers(cfg, params, get, host,
                                     "gpt_neox.layers.{i}.", "attention")
    if not cfg.tie_embeddings and "lm_head" in params:
        host["embed_out.weight"] = get(params["lm_head"]["w"]).T
    return host
