"""Checkpoint save/load.

Analogue of ``engine.save_checkpoint`` / ``load_checkpoint`` (reference
runtime/engine.py:3609/2770-style): writes a tagged directory with the full
TrainState plus client state, and a ``latest`` pointer file.  Arrays are
stored keyed by pytree path, so a checkpoint can be reloaded into ANY
ZeRO-stage/mesh layout — each leaf is re-placed with the target engine's
shardings on load (the seed of universal-checkpoint resharding; the
partitioned multi-host writer lives in checkpoint/partitioned.py).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from .. import comm
from ..utils.logging import log_dist, logger

MODEL_FILE = "model_states.npz"
META_FILE = "meta.json"
LATEST = "latest"


def _flat_with_paths(tree: Any) -> Dict[str, Any]:
    flat = {}

    def visit(path, leaf):
        if leaf is None:
            return leaf
        key = jax.tree_util.keystr(path)
        flat[key] = leaf
        return leaf

    jax.tree_util.tree_map_with_path(visit, tree)
    return flat


def save_checkpoint(engine, save_dir: str, tag: Optional[str] = None,
                    client_state: Optional[dict] = None,
                    keep_n: Optional[int] = None) -> str:
    """Save through the verified atomic commit protocol
    (``resilience/commit.py``): files land in a ``tmp.<tag>`` staging
    dir, a checksum manifest is written, and one atomic rename commits
    — a mid-write crash can never leave a loadable-looking torn tag."""
    from ..resilience.commit import array_checksums, checkpoint_commit

    tag = tag or f"global_step{engine.global_steps}"
    path = os.path.join(save_dir, tag)
    if jax.process_count() > 1:
        # multi-host state is not fully addressable from one process; needs
        # the per-process partitioned writer (planned: checkpoint/partitioned)
        raise NotImplementedError(
            "save_checkpoint currently supports single-host jobs only; "
            "multi-host partitioned checkpointing is not yet implemented")
    comm.barrier("pre-save")
    if jax.process_index() == 0:
        flat = _flat_with_paths(engine.state)
        arrays = {k: np.asarray(jax.device_get(v)) for k, v in flat.items()}
        # bfloat16 has no numpy dtype; store as uint16 view + dtype note
        dtypes = {}
        for k, v in list(arrays.items()):
            if v.dtype.name == "bfloat16":
                arrays[k] = v.view(np.uint16)
                dtypes[k] = "bfloat16"
        commit_meta = {
            "global_steps": engine.global_steps,
            "world": jax.process_count(),
            "mesh": dict(engine.topology.axis_sizes),
            "array_crc32": array_checksums(arrays),
        }
        try:
            # numerics observatory: if the anomaly sentinel fired since
            # the last save, stamp the incident into this tag's commit
            # manifest — resume-time triage (``resolve_tag`` reports /
            # ``manifest_meta``) sees WHAT fired and WHICH layer without
            # hunting for the flight dump.  consume-once: only the first
            # checkpoint after the incident carries it.
            from ..telemetry.numerics import pending_incident_meta

            inc = pending_incident_meta()
            if inc is not None:
                commit_meta["numerics_incident"] = inc
        # dstpu-lint: allow[swallow] annotation only — a broken sentinel
        # must never block the checkpoint itself
        except Exception:
            pass
        with checkpoint_commit(save_dir, tag, meta=commit_meta,
                               keep_n=keep_n) as staging:
            np.savez(os.path.join(staging, MODEL_FILE), **arrays)
            meta = {
                "tag": tag,
                "global_steps": engine.global_steps,
                "micro_steps": engine.micro_steps,
                "lr_scheduler": engine.lr_scheduler.state_dict()
                if hasattr(engine.lr_scheduler, "state_dict") else None,
                "client_state": client_state or {},
                "bfloat16_keys": dtypes,
                "zero_stage": engine.config.zero_config.stage,
            }
            with open(os.path.join(staging, META_FILE), "w") as f:
                json.dump(meta, f, indent=2, default=str)
    comm.barrier("post-save")
    log_dist(f"saved checkpoint {path}")
    return path


def load_checkpoint(engine, load_dir: str, tag: Optional[str] = None,
                    load_optimizer_states: bool = True,
                    load_lr_scheduler_states: bool = True) -> Tuple[Optional[str], dict]:
    if tag is None:
        from ..resilience.commit import resolve_tag

        tag, _report = resolve_tag(load_dir)
        if tag is None:
            logger.warning(f"no loadable checkpoint in {load_dir}; "
                           "nothing loaded")
            return None, {}
    path = os.path.join(load_dir, tag)
    with open(os.path.join(path, META_FILE)) as f:
        meta = json.load(f)
    data = np.load(os.path.join(path, MODEL_FILE))
    bf16_keys = set(meta.get("bfloat16_keys", {}))

    import jax.numpy as jnp

    def restore(path_key, current):
        key = jax.tree_util.keystr(path_key)
        if key not in data.files:
            logger.warning(f"checkpoint missing {key}; keeping current value")
            return current
        arr = data[key]
        if key in bf16_keys:
            arr = arr.view(jnp.bfloat16)
        from jax.sharding import NamedSharding

        target_sharding = getattr(current, "sharding", None)
        if not isinstance(target_sharding, NamedSharding):
            # scalars / single-device leaves: re-place replicated on the mesh
            # so the whole restored state shares one device set
            target_sharding = engine.topology.replicated()
        arr = jnp.asarray(arr, dtype=current.dtype).reshape(current.shape)
        return jax.device_put(arr, target_sharding)

    new_state = jax.tree_util.tree_map_with_path(restore, engine.state)
    if not load_optimizer_states:
        import dataclasses

        new_state = dataclasses.replace(new_state, opt_state=engine.state.opt_state)
    engine.state = new_state
    engine.global_steps = meta["global_steps"]
    engine.micro_steps = meta.get("micro_steps", 0)
    if load_lr_scheduler_states and meta.get("lr_scheduler") and \
            hasattr(engine.lr_scheduler, "load_state_dict"):
        engine.lr_scheduler.load_state_dict(meta["lr_scheduler"])
    log_dist(f"loaded checkpoint {path}")
    return path, meta.get("client_state", {})
