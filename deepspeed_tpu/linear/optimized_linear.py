"""Optimized / LoRA linear layers.

Reference: ``deepspeed/linear/optimized_linear.py`` — OptimizedLinear with
LoRAConfig (low-rank adapters over an optionally quantized frozen base) and
QuantizationConfig.

Functional TPU form: params are a dict {base (frozen, optionally int8),
lora_a, lora_b}; ``lora_linear`` applies y = x @ dequant(base) +
(x @ a) @ b * (alpha/r).  The engine trains only the lora leaves when the
partition-rule path is wrapped in ``trainable_lora_params``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp


@dataclasses.dataclass
class LoRAConfig:
    lora_r: int = 64
    lora_alpha: float = 16.0
    base_weight_sharding: int = 1


@dataclasses.dataclass
class QuantizationConfig:
    q_bits: int = 8
    group_size: int = 128


def init_lora_linear(rng, in_dim: int, out_dim: int, lora: LoRAConfig,
                     quantize: Optional[QuantizationConfig] = None,
                     base: Optional[jnp.ndarray] = None,
                     dtype=jnp.float32) -> Dict[str, Any]:
    k1, k2 = jax.random.split(rng)
    if base is None:
        base = jax.random.normal(k1, (in_dim, out_dim), dtype) * 0.02
    params: Dict[str, Any] = {"lora_a": jax.random.normal(
        k2, (in_dim, lora.lora_r), dtype) * (1.0 / lora.lora_r),
        "lora_b": jnp.zeros((lora.lora_r, out_dim), dtype)}
    if quantize is not None:
        from ..ops.pallas.quantization import quantize_int8

        q, s, n = quantize_int8(base.reshape(-1))
        params["base_q"] = q
        params["base_scale"] = s
        params["base_meta"] = jnp.asarray([in_dim, out_dim, n], jnp.int32)
    else:
        params["base"] = base
    return params


def lora_linear(params: Dict[str, Any], x: jnp.ndarray, lora: LoRAConfig) -> jnp.ndarray:
    if "base" in params:
        base = params["base"]
    else:
        from ..ops.pallas.quantization import dequantize_int8

        meta = params["base_meta"]
        base = dequantize_int8(params["base_q"], params["base_scale"],
                               int(meta[2]), x.dtype).reshape(int(meta[0]), int(meta[1]))
    y = x @ jax.lax.stop_gradient(base)  # frozen base
    scale = lora.lora_alpha / lora.lora_r
    return y + (x @ params["lora_a"]) @ params["lora_b"] * scale


def trainable_lora_params(params: Any) -> Any:
    """optax mask: True only for lora leaves (freeze everything else)."""
    return jax.tree_util.tree_map_with_path(
        lambda path, _: "lora_" in jax.tree_util.keystr(path), params)
