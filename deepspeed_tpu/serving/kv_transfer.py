"""KV-page transfer between engine replicas.

The disaggregation hot path is :func:`migrate_sequence` — export a
decode-ready sequence's KV pages + block-table metadata from one engine
(``InferenceEngineV2.export_sequence``), import them into another with
ref-count adoption (``import_sequence`` / ``BlockAllocator.adopt``),
and release the source only after the import committed, so a failed
handoff never loses the request.

For replicas in one process (the CPU drill, single-host multi-engine)
the bundle's host arrays move by reference.  For cross-process /
cross-host transport, :func:`bundle_to_bytes` / :func:`bundle_from_bytes`
give a self-describing wire format (json header + raw little-endian
page arrays) — the same serialization a host-RAM spill of cold pages
will reuse.  Bit-exactness is the contract end to end: dtypes are
carried exactly (bf16 via ml_dtypes), the importing engine refuses to
cast, and (wire v2) every page carries a CRC32 across its slice of
every leaf — a torn, truncated or bit-flipped bundle is REJECTED with a
clear :class:`CorruptBundleError` instead of silently seeding garbage
KV.  A refused import loses nothing: the source engine still holds the
sequence and its pages.
"""

from __future__ import annotations

import io
import json
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..inference.v2.ragged import KVPageBundle
from ..utils.logging import logger

#: wire-format magic + version: bump on any layout change.
#: v2 added per-page CRC32s (``page_crcs`` in the header) and the
#: request's SLO identity (priority, seconds of deadline budget left).
_MAGIC = b"DSTPUKV2"
_OLD_MAGICS = (b"DSTPUKV1",)


class CorruptBundleError(ValueError):
    """A serialized bundle failed integrity checks (bad magic /
    unsupported version / truncation / per-page CRC mismatch).  The
    import side refuses it — the exporter still owns the sequence, so
    the correct reaction is to retry or re-export, never to import."""


def _trace_crc(trace: Dict[str, Any]) -> int:
    """CRC32 over the canonical JSON of the trace block — its OWN
    checksum, separate from the page CRCs: a torn trace block must be
    refused by name, not silently imported as a null trace (which would
    be indistinguishable from a legacy bundle)."""
    blob = json.dumps(trace, sort_keys=True, separators=(",", ":"))
    return zlib.crc32(blob.encode()) & 0xFFFFFFFF


def migrate_sequence(src_engine: Any, dst_engine: Any, uid: int) -> int:
    """Move one decode-ready sequence from ``src_engine`` to
    ``dst_engine``.  Returns the number of KV pages moved (truthy) on
    success; 0 when the destination has no capacity (the sequence keeps
    running on the source — a failed handoff loses nothing).
    Incompatible engines (different model geometry / page size) raise
    ``ValueError`` — that is a fleet-construction bug, not load."""
    bundle = src_engine.export_sequence(uid)
    if not dst_engine.import_sequence(bundle):
        return 0
    src_engine.release_sequence(uid, reason="migrated")
    return bundle.n_pages


def rebase_deadline_left(left: Any, sent_unix: Any) -> Optional[float]:
    """THE transit clamp: wall time elapsed since the ``sent_unix``
    stamp CONSUMES the remaining deadline budget, and skew-negative
    elapsed (receiver clock behind the sender's) clamps to zero so a
    backwards clock never *grants* budget.  One rule for every path a
    bundle can sit outside an engine — the cross-process wire
    (:func:`bundle_from_bytes`) and the NVMe/host tier's spilled-bundle
    restore (``kv_tier.NVMeKVTier.restore_bundle``) both re-base
    through here: a page that sat spilled gets no free deadline."""
    if left is None:
        return None
    if sent_unix is not None:
        # dstpu-lint: allow[wall-clock] transit vs the sender's wall-clock
        # stamp; clamped non-negative so skew never grants budget back
        transit = max(0.0, time.time() - float(sent_unix))
        left = max(0.0, float(left) - transit)
    return float(left)


def _dtype_name(arr: np.ndarray) -> str:
    return arr.dtype.name  # "bfloat16" round-trips through ml_dtypes


def _np_dtype(name: str):
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


def page_crcs(arrays: Dict[str, np.ndarray],
              leaves: List[str]) -> List[int]:
    """CRC32 per page: each page's slice of EVERY leaf (axis 1 is the
    page axis, ``[L, n_pages, ...]``), chained in sorted-leaf order.
    One checksum per page — a flipped bit, a torn page, or a shifted
    byte stream names the exact page it corrupted.

    THE page-integrity serialization, shared by the wire format here
    and the host KV tier (``serving/kv_tier.py``): spill capture stamps
    it, restore recomputes and refuses mismatches — one layout, one
    checksum rule, everywhere a page leaves the device."""
    if not leaves:
        return []
    n_pages = arrays[leaves[0]].shape[1]
    crcs = [0] * n_pages
    for n in leaves:
        # ONE contiguous page-major copy per leaf (not one slice copy
        # per page): row j is exactly arrays[n][:, j]'s C-order bytes,
        # checksummed as a zero-copy memoryview row
        rows = np.ascontiguousarray(np.moveaxis(arrays[n], 1, 0)) \
            .view(np.uint8).reshape(n_pages, -1)
        for j in range(n_pages):
            crcs[j] = zlib.crc32(rows[j], crcs[j])
    return [c & 0xFFFFFFFF for c in crcs]


def pages_to_bytes(arrays: Dict[str, np.ndarray],
                   meta: Optional[Dict[str, Any]] = None) -> bytes:
    """THE DSTPUKV2 page-record serialization: magic, a json header
    (``meta`` + per-leaf shape/dtype + per-page CRC32s), then each
    leaf's raw C-order bytes in sorted-leaf order.  The record layer
    shared by the wire format (:func:`bundle_to_bytes` rides on it) and
    the NVMe tier's on-disk page files (``kv_tier.NVMeKVTier``) — one
    layout, one checksum rule, everywhere pages leave the process."""
    leaves = sorted(arrays)
    header = dict(meta or {})
    header["leaves"] = [{"name": n, "shape": list(arrays[n].shape),
                         "dtype": _dtype_name(arrays[n])} for n in leaves]
    header["page_crcs"] = page_crcs(arrays, leaves)
    buf = io.BytesIO()
    hdr = json.dumps(header).encode()
    buf.write(_MAGIC)
    buf.write(len(hdr).to_bytes(8, "little"))
    buf.write(hdr)
    for n in leaves:
        buf.write(np.ascontiguousarray(arrays[n]).tobytes())
    return buf.getvalue()


def pages_from_bytes(data: bytes
                     ) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
    """Inverse of :func:`pages_to_bytes` (bit-identical arrays).

    Integrity first: bad magic, a retired wire version, truncation, or
    a per-page CRC32 mismatch raises :class:`CorruptBundleError` naming
    the page — refusal loses nothing, the source record/engine still
    holds the pages."""
    if data[:len(_MAGIC)] in _OLD_MAGICS:
        raise CorruptBundleError(
            f"serialized KVPageBundle uses retired wire version "
            f"{data[:len(_MAGIC)]!r} (no per-page checksums); current is "
            f"{_MAGIC!r} — re-export from the source engine")
    if data[:len(_MAGIC)] != _MAGIC:
        raise CorruptBundleError("not a serialized KVPageBundle (bad magic)")
    off = len(_MAGIC)
    if len(data) < off + 8:
        raise CorruptBundleError("truncated bundle: header length missing")
    hlen = int.from_bytes(data[off:off + 8], "little")
    off += 8
    if len(data) < off + hlen:
        raise CorruptBundleError(
            f"truncated bundle: header needs {hlen} bytes, "
            f"{len(data) - off} present")
    try:
        header = json.loads(data[off:off + hlen].decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise CorruptBundleError(f"corrupt bundle header: {e}") from e
    off += hlen
    arrays = {}
    for leaf in header["leaves"]:
        dt = _np_dtype(leaf["dtype"])
        n = int(np.prod(leaf["shape"])) * dt.itemsize
        if len(data) < off + n:
            raise CorruptBundleError(
                f"truncated bundle: leaf {leaf['name']!r} needs {n} bytes, "
                f"{len(data) - off} present")
        arrays[leaf["name"]] = np.frombuffer(
            data[off:off + n], dtype=dt).reshape(leaf["shape"]).copy()
        off += n
    if off != len(data):
        logger.warning(f"pages_from_bytes: {len(data) - off} trailing "
                       "bytes ignored")
    leaves = sorted(arrays)
    want = list(header.get("page_crcs", []))
    got = page_crcs(arrays, leaves)
    if len(want) != len(got):
        raise CorruptBundleError(
            f"corrupt bundle: header carries {len(want)} page CRCs for "
            f"{len(got)} pages")
    bad = [j for j, (w, g) in enumerate(zip(want, got)) if w != g]
    if bad:
        raise CorruptBundleError(
            f"corrupt bundle: CRC32 mismatch on page(s) {bad} of "
            f"{len(got)} (bit flip or torn write in transport) — "
            "refused; source still holds the sequence")
    return arrays, header


def bundle_to_bytes(bundle: KVPageBundle) -> bytes:
    """Serialize a bundle for cross-process transport: magic, a json
    header (metadata + per-leaf shape/dtype + per-page CRC32s, page
    keys hex-encoded), then each leaf's raw C-order bytes in header
    order.  The absolute in-process ``deadline`` is re-based to
    seconds-left (``deadline_left_s``) — perf_counter clocks don't
    survive a process boundary."""
    header = {
        "uid": bundle.uid, "tokens": list(map(int, bundle.tokens)),
        "prompt_len": bundle.prompt_len,
        "max_new_tokens": bundle.max_new_tokens,
        "temperature": bundle.temperature, "eos_id": bundle.eos_id,
        "prefilled": bundle.prefilled, "decode_entry": bundle.decode_entry,
        "page_size": bundle.page_size,
        "priority": bundle.priority,
        "deadline_left_s": (max(0.0, bundle.deadline - time.perf_counter())
                            if bundle.deadline else None),
        # wall-clock send stamp: transit time must CONSUME the deadline
        # budget (best-effort across hosts — skew-negative elapsed is
        # clamped to 0, never granting budget back)
        # dstpu-lint: allow[wall-clock] cross-host wire timestamp; monotonic
        # clocks do not compare across machines (see comment above)
        "sent_unix": time.time(),
        "page_keys": [k.hex() if isinstance(k, bytes) else k
                      for k in bundle.page_keys],
        "src_pages": [{"page": m["page"], "refcount": m["refcount"],
                       "key": (m["key"].hex()
                               if isinstance(m.get("key"), bytes) else None)}
                      for m in bundle.src_pages],
        "model_sig": list(bundle.model_sig), "kv_quant": bundle.kv_quant,
        "dtype": bundle.dtype,
    }
    if bundle.trace is not None:
        # optional trace-context block (fleet request tracing): the
        # router-minted trace_id, a clock-free ledger snapshot, and the
        # per-hop send stamps.  OPTIONAL by construction — absent on
        # legacy bundles, and its absence never fails an import.
        trace = dict(bundle.trace)
        # dstpu-lint: allow[wall-clock] per-hop wire timestamp; transit
        # is measured sender-wall vs receiver-wall (same contract as
        # sent_unix above — monotonic clocks don't cross machines)
        hop = {"sent_unix": time.time()}
        trace["hops"] = list(trace.get("hops") or []) + [hop]
        header["trace"] = trace
        header["trace_crc"] = _trace_crc(trace)
    return pages_to_bytes(bundle.arrays, header)


def bundle_from_bytes(data: bytes) -> KVPageBundle:
    """Inverse of :func:`bundle_to_bytes` (bit-identical arrays).

    Integrity is verified BEFORE anything is adopted: bad magic, an
    old/unknown wire version, a truncated payload, or a per-page CRC32
    mismatch raises :class:`CorruptBundleError` — a refused import
    loses nothing (the exporting engine still holds the pages)."""
    arrays, header = pages_from_bytes(data)
    trace = None
    if "trace" in header:
        trace = header["trace"]
        want_crc = header.get("trace_crc")
        if (not isinstance(trace, dict) or want_crc is None
                or _trace_crc(trace) != int(want_crc)):
            raise CorruptBundleError(
                "corrupt bundle: trace block failed its CRC32 (torn or "
                "bit-flipped trace context) — refused; a legacy bundle "
                "would OMIT the block, not carry a broken one")
        hops = trace.get("hops") or []
        if hops and hops[-1].get("sent_unix") is not None:
            # dstpu-lint: allow[wall-clock] receive stamp paired with the
            # sender's wall-clock hop stamp (cross-host transit measure)
            now_unix = time.time()
            hops[-1]["recv_unix"] = now_unix
            trace["transit_s"] = max(
                0.0, now_unix - float(hops[-1]["sent_unix"]))
    left = rebase_deadline_left(header.get("deadline_left_s"),
                                header.get("sent_unix"))
    return KVPageBundle(
        uid=header["uid"], tokens=list(header["tokens"]),
        prompt_len=header["prompt_len"],
        max_new_tokens=header["max_new_tokens"],
        temperature=header["temperature"], eos_id=header["eos_id"],
        prefilled=header["prefilled"], decode_entry=header["decode_entry"],
        page_size=header["page_size"],
        page_keys=[bytes.fromhex(k) if isinstance(k, str) else k
                   for k in header["page_keys"]],
        src_pages=[{"page": m["page"], "refcount": m["refcount"],
                    "key": (bytes.fromhex(m["key"])
                            if m.get("key") else None)}
                   for m in header["src_pages"]],
        arrays=arrays, model_sig=tuple(header["model_sig"]),
        kv_quant=header["kv_quant"], dtype=header["dtype"],
        priority=int(header.get("priority", 1)),
        deadline=(time.perf_counter() + float(left)
                  if left is not None else 0.0),
        trace=trace)


__all__ = ["migrate_sequence", "bundle_to_bytes", "bundle_from_bytes",
           "pages_to_bytes", "pages_from_bytes", "page_crcs",
           "rebase_deadline_left", "CorruptBundleError"]

