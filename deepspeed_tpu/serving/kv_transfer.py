"""KV-page transfer between engine replicas.

The disaggregation hot path is :func:`migrate_sequence` — export a
decode-ready sequence's KV pages + block-table metadata from one engine
(``InferenceEngineV2.export_sequence``), import them into another with
ref-count adoption (``import_sequence`` / ``BlockAllocator.adopt``),
and release the source only after the import committed, so a failed
handoff never loses the request.

For replicas in one process (the CPU drill, single-host multi-engine)
the bundle's host arrays move by reference.  For cross-process /
cross-host transport, :func:`bundle_to_bytes` / :func:`bundle_from_bytes`
give a self-describing wire format (json header + raw little-endian
page arrays) — the same serialization a host-RAM spill of cold pages
will reuse.  Bit-exactness is the contract end to end: dtypes are
carried exactly (bf16 via ml_dtypes) and the importing engine refuses
to cast.
"""

from __future__ import annotations

import io
import json
from typing import Any

import numpy as np

from ..inference.v2.ragged import KVPageBundle
from ..utils.logging import logger

#: wire-format magic + version: bump on any layout change
_MAGIC = b"DSTPUKV1"


def migrate_sequence(src_engine: Any, dst_engine: Any, uid: int) -> int:
    """Move one decode-ready sequence from ``src_engine`` to
    ``dst_engine``.  Returns the number of KV pages moved (truthy) on
    success; 0 when the destination has no capacity (the sequence keeps
    running on the source — a failed handoff loses nothing).
    Incompatible engines (different model geometry / page size) raise
    ``ValueError`` — that is a fleet-construction bug, not load."""
    bundle = src_engine.export_sequence(uid)
    if not dst_engine.import_sequence(bundle):
        return 0
    src_engine.release_sequence(uid, reason="migrated")
    return bundle.n_pages


def _dtype_name(arr: np.ndarray) -> str:
    return arr.dtype.name  # "bfloat16" round-trips through ml_dtypes


def _np_dtype(name: str):
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


def bundle_to_bytes(bundle: KVPageBundle) -> bytes:
    """Serialize a bundle for cross-process transport: magic, a json
    header (metadata + per-leaf shape/dtype, page keys hex-encoded),
    then each leaf's raw C-order bytes in header order."""
    leaves = sorted(bundle.arrays)
    header = {
        "uid": bundle.uid, "tokens": list(map(int, bundle.tokens)),
        "prompt_len": bundle.prompt_len,
        "max_new_tokens": bundle.max_new_tokens,
        "temperature": bundle.temperature, "eos_id": bundle.eos_id,
        "prefilled": bundle.prefilled, "decode_entry": bundle.decode_entry,
        "page_size": bundle.page_size,
        "page_keys": [k.hex() if isinstance(k, bytes) else k
                      for k in bundle.page_keys],
        "src_pages": [{"page": m["page"], "refcount": m["refcount"],
                       "key": (m["key"].hex()
                               if isinstance(m.get("key"), bytes) else None)}
                      for m in bundle.src_pages],
        "model_sig": list(bundle.model_sig), "kv_quant": bundle.kv_quant,
        "dtype": bundle.dtype,
        "leaves": [{"name": n, "shape": list(bundle.arrays[n].shape),
                    "dtype": _dtype_name(bundle.arrays[n])}
                   for n in leaves],
    }
    buf = io.BytesIO()
    hdr = json.dumps(header).encode()
    buf.write(_MAGIC)
    buf.write(len(hdr).to_bytes(8, "little"))
    buf.write(hdr)
    for n in leaves:
        buf.write(np.ascontiguousarray(bundle.arrays[n]).tobytes())
    return buf.getvalue()


def bundle_from_bytes(data: bytes) -> KVPageBundle:
    """Inverse of :func:`bundle_to_bytes` (bit-identical arrays)."""
    if data[:len(_MAGIC)] != _MAGIC:
        raise ValueError("not a serialized KVPageBundle (bad magic)")
    off = len(_MAGIC)
    hlen = int.from_bytes(data[off:off + 8], "little")
    off += 8
    header = json.loads(data[off:off + hlen].decode())
    off += hlen
    arrays = {}
    for leaf in header["leaves"]:
        dt = _np_dtype(leaf["dtype"])
        n = int(np.prod(leaf["shape"])) * dt.itemsize
        arrays[leaf["name"]] = np.frombuffer(
            data[off:off + n], dtype=dt).reshape(leaf["shape"]).copy()
        off += n
    if off != len(data):
        logger.warning(f"bundle_from_bytes: {len(data) - off} trailing "
                       "bytes ignored")
    return KVPageBundle(
        uid=header["uid"], tokens=list(header["tokens"]),
        prompt_len=header["prompt_len"],
        max_new_tokens=header["max_new_tokens"],
        temperature=header["temperature"], eos_id=header["eos_id"],
        prefilled=header["prefilled"], decode_entry=header["decode_entry"],
        page_size=header["page_size"],
        page_keys=[bytes.fromhex(k) if isinstance(k, str) else k
                   for k in header["page_keys"]],
        src_pages=[{"page": m["page"], "refcount": m["refcount"],
                    "key": (bytes.fromhex(m["key"])
                            if m.get("key") else None)}
                   for m in header["src_pages"]],
        arrays=arrays, model_sig=tuple(header["model_sig"]),
        kv_quant=header["kv_quant"], dtype=header["dtype"])


__all__ = ["migrate_sequence", "bundle_to_bytes", "bundle_from_bytes"]
