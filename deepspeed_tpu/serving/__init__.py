"""Serving fleet: the front tier above ``inference/v2`` engines.

A router with prefix-cache-affinity placement, prefill/decode
disaggregation with KV-page migration, and replica lifecycle handling
(drain / health / re-dispatch on death or preemption) — see
docs/SERVING.md "Fleet serving".

``ServingConfig`` imports eagerly (``runtime/config.py`` parses the
``serving`` block); the router/replica/transfer machinery loads lazily
so config parsing never pulls in jax-facing engine code.
"""

from .config import (AutoscaleConfig, KVTierConfig,  # noqa: F401
                     ServingConfig, TransportConfig)

_LAZY = {
    "HostKVTier": "kv_tier", "NVMeKVTier": "kv_tier",
    "FleetRouter": "router", "build_fleet": "router",
    "affinity_key": "router", "hrw_score": "router",
    "pick_replica": "router",
    "EngineReplica": "replica", "ROLE_PREFILL": "replica",
    "ROLE_DECODE": "replica", "ROLE_MIXED": "replica",
    "BREAKER_CLOSED": "replica", "BREAKER_OPEN": "replica",
    "BREAKER_HALF_OPEN": "replica",
    "migrate_sequence": "kv_transfer", "bundle_to_bytes": "kv_transfer",
    "bundle_from_bytes": "kv_transfer", "CorruptBundleError": "kv_transfer",
    "pages_to_bytes": "kv_transfer", "pages_from_bytes": "kv_transfer",
    "rebase_deadline_left": "kv_transfer",
    "AdmissionController": "admission", "RejectedError": "admission",
    "retry_after_hint": "admission", "estimate_pages": "admission",
    "EngineServer": "transport", "RemoteEngineProxy": "transport",
    "BundleSender": "transport", "pipelined_migrate": "transport",
    "spawn_engine_server": "transport", "TransportError": "transport",
    "FleetAutoscaler": "autoscale",
}

__all__ = ["ServingConfig", "KVTierConfig", "AutoscaleConfig",
           "TransportConfig"] + sorted(_LAZY)


def __getattr__(name):
    if name in _LAZY:
        import importlib

        mod = importlib.import_module(f".{_LAZY[name]}", __name__)
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
