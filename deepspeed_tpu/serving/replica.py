"""One engine replica as the router sees it.

Wraps an :class:`InferenceEngineV2` with the fleet-level state the
router schedules on: a **role** (``prefill`` / ``decode`` / ``mixed`` —
a placement *preference*, not a capability gate: any engine can do
both, which is what makes lossless fallback possible when a pool
empties), a **health** state (alive / retired), and a PR-5
:class:`PreemptionWatcher` so a maintenance notice or SIGTERM-style
signal against one replica turns into graceful drain-and-migrate
instead of dropped streams.

``load()`` is the router's least-loaded signal: queue depth + occupied
decode slots — the same quantities the engine publishes as the
``deepspeed_tpu_serving_queue_depth`` / ``_batch_occupancy`` gauges, read
directly so the N co-located replicas (which share one process-global
gauge) stay individually observable.
"""

from __future__ import annotations

from typing import Any, Dict

from ..resilience.preemption import PreemptionWatcher

ROLE_PREFILL = "prefill"
ROLE_DECODE = "decode"
ROLE_MIXED = "mixed"
ROLES = (ROLE_PREFILL, ROLE_DECODE, ROLE_MIXED)


class EngineReplica:
    """A named engine + fleet-level lifecycle state."""

    def __init__(self, name: str, engine: Any, role: str = ROLE_MIXED):
        if role not in ROLES:
            raise ValueError(f"replica role {role!r} not in {ROLES}")
        self.name = name
        self.engine = engine
        self.role = role
        #: signal/maintenance-notice injection point (PR 5): the router
        #: polls ``preempted`` each pump and retires the replica
        #: gracefully.  No process-level signal handlers here — N
        #: replicas share one process in the CPU drill, and a real
        #: deployment installs per-process watchers in the replica's
        #: launcher instead.
        self.watcher = PreemptionWatcher(install_signals=False)
        #: False after a hard death (chaos ``kill()``): engine state —
        #: including every in-flight KV page — is gone
        self.alive = True
        #: True once drained/evacuated: keeps its slot in the fleet
        #: table for observability but takes no work
        self.retired = False

    # -- scheduling signals --------------------------------------------------
    @property
    def preempted(self) -> bool:
        return self.watcher.requested is not None

    def accepts_new(self) -> bool:
        """Can this replica take NEW admissions right now?"""
        return self.alive and not self.retired and not self.preempted

    def load(self) -> int:
        """Queue depth + occupied decode slots (see module docstring)."""
        return self.engine.queue_depth + self.engine.active_count

    def kv_free_fraction(self) -> float:
        """Allocatable fraction of the KV page pool — the pool-occupancy
        signal (same quantity as the ``_kv_pages_free`` gauge)."""
        a = self.engine.allocator
        return a.free_pages / max(1, a.num_pages)

    # -- lifecycle -----------------------------------------------------------
    def step(self) -> Dict[int, Dict[str, Any]]:
        return self.engine.step() if self.engine.has_work() else {}

    def kill(self) -> None:
        """Chaos hook: simulate an unannounced replica death (process
        gone, KV pages unrecoverable).  The router re-dispatches its
        in-flight requests on the next pump."""
        self.alive = False

    def health(self) -> Dict[str, Any]:
        h = {"role": self.role, "alive": self.alive, "retired": self.retired,
             "preempted": self.watcher.requested or "",
             "load": self.load() if self.alive else -1}
        if self.alive:
            h.update(queue_depth=self.engine.queue_depth,
                     active=self.engine.active_count,
                     kv_free_fraction=round(self.kv_free_fraction(), 4))
        return h


__all__ = ["EngineReplica", "ROLE_PREFILL", "ROLE_DECODE", "ROLE_MIXED",
           "ROLES"]
