"""One engine replica as the router sees it.

Wraps an :class:`InferenceEngineV2` with the fleet-level state the
router schedules on: a **role** (``prefill`` / ``decode`` / ``mixed`` —
a placement *preference*, not a capability gate: any engine can do
both, which is what makes lossless fallback possible when a pool
empties), a **health** state (alive / retired), a PR-5
:class:`PreemptionWatcher` so a maintenance notice or SIGTERM-style
signal against one replica turns into graceful drain-and-migrate
instead of dropped streams, and a **circuit breaker** against *gray
failure* — the replica that is slow or flaky rather than dead, which
``kill()``-style liveness never catches.

The breaker is a rolling window of per-``step()`` wall times and
exceptions plus a three-state machine:

``closed`` ──median > k x fleet median, or N consec. errors──▶ ``open``
``open``   ──cooldown pumps elapse──▶ ``half_open`` (probing)
``half_open`` ──probe steps healthy──▶ ``closed``  (or back to ``open``)

The latency rule compares this replica's rolling *median* step time
(sustained degradation) against the fleet median; p95 is kept on the
health surface for tail observability but a lone XLA-compile or GC
spike never trips the breaker.

The replica only *records and evaluates*; fleet-relative judgment (the
median of the OTHER replicas) and the consequences of a trip (drain of
new placement, re-dispatch of in-flight streams) belong to the router
(``FleetRouter._check_breakers``).  Thresholds come from the
``serving`` config block.

``load()`` is the router's least-loaded signal: queue depth + occupied
decode slots — the same quantities the engine publishes as the
``deepspeed_tpu_serving_queue_depth`` / ``_batch_occupancy`` gauges, read
directly so the N co-located replicas (which share one process-global
gauge) stay individually observable.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Callable, Dict, Optional

from ..resilience.preemption import PreemptionWatcher

ROLE_PREFILL = "prefill"
ROLE_DECODE = "decode"
ROLE_MIXED = "mixed"
ROLES = (ROLE_PREFILL, ROLE_DECODE, ROLE_MIXED)

BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half_open"


class EngineReplica:
    """A named engine + fleet-level lifecycle state."""

    def __init__(self, name: str, engine: Any, role: str = ROLE_MIXED,
                 breaker_window: int = 32):
        if role not in ROLES:
            raise ValueError(f"replica role {role!r} not in {ROLES}")
        self.name = name
        self.engine = engine
        self.role = role
        # the engine stamps this name as the owner of its reqtrace
        # ledger intervals and span attrs: N co-located replicas share
        # one process, so per-replica attribution must ride the engine
        engine.trace_owner = name
        #: signal/maintenance-notice injection point (PR 5): the router
        #: polls ``preempted`` each pump and retires the replica
        #: gracefully.  No process-level signal handlers here — N
        #: replicas share one process in the CPU drill, and a real
        #: deployment installs per-process watchers in the replica's
        #: launcher instead.
        self.watcher = PreemptionWatcher(install_signals=False)
        #: False after a hard death (chaos ``kill()``): engine state —
        #: including every in-flight KV page — is gone
        self.alive = True
        #: True once drained/evacuated: keeps its slot in the fleet
        #: table for observability but takes no work
        self.retired = False
        # -- circuit-breaker state (see module docstring) --
        self.breaker = BREAKER_CLOSED
        self.step_errors = 0       # lifetime step exceptions
        self.consec_errors = 0     # reset by any healthy step
        self._lat: deque = deque(maxlen=max(2, int(breaker_window)))
        #: rolling per-step error flags (same window): an INTERMITTENT
        #: flaky replica never runs up consec_errors, but a majority-
        #: erroring window still trips
        self._err: deque = deque(maxlen=max(2, int(breaker_window)))
        self._cooldown = 0         # open -> half_open countdown (pumps)
        self._probe_ok = 0         # healthy steps while half_open
        self._probe_err = False    # any error while half_open: re-trip
        #: gray-failure injection point (resilience/chaos.py
        #: ``SlowReplica`` / ``FlakyStep``): called with this replica at
        #: the top of every ``step()``; may sleep (slow replica) or
        #: raise (flaky step)
        self._chaos_hook: Optional[Callable[["EngineReplica"], None]] = None

    # -- scheduling signals --------------------------------------------------
    @property
    def preempted(self) -> bool:
        return self.watcher.requested is not None

    def accepts_new(self) -> bool:
        """Can this replica take NEW admissions right now?  An open
        breaker means degraded: drained of placement until the
        half-open probe readmits it."""
        return (self.alive and not self.retired and not self.preempted
                and self.breaker != BREAKER_OPEN)

    def load(self) -> int:
        """Queue depth + occupied decode slots (see module docstring)."""
        return self.engine.queue_depth + self.engine.active_count

    def kv_free_fraction(self) -> float:
        """Allocatable fraction of the KV page pool — the pool-occupancy
        signal (same quantity as the ``_kv_pages_free`` gauge)."""
        a = self.engine.allocator
        return a.free_pages / max(1, a.num_pages)

    # -- chaos injection -----------------------------------------------------
    def inject_chaos(self, hook: Optional[Callable[["EngineReplica"], None]]
                     ) -> None:
        """Install (or clear, with None) the per-step gray-failure hook."""
        self._chaos_hook = hook

    def clear_chaos(self) -> None:
        self._chaos_hook = None

    # -- breaker window ------------------------------------------------------
    @property
    def lat_samples(self) -> int:
        return len(self._lat)

    def step_p95(self) -> float:
        """p95 of the rolling step-latency window (0.0 while empty) —
        the tail-latency health surface."""
        if not self._lat:
            return 0.0
        xs = sorted(self._lat)
        return xs[min(len(xs) - 1, max(0, -(-95 * len(xs) // 100) - 1))]

    def step_p50(self) -> float:
        """Rolling MEDIAN step latency (0.0 while empty) — what the
        breaker's latency rule compares: a gray-failed replica is slow
        on EVERY step, so its median rises; an occasional XLA compile
        or GC spike moves only the tail (p95) and must not trip."""
        if not self._lat:
            return 0.0
        xs = sorted(self._lat)
        return xs[(len(xs) - 1) // 2]

    def _record_step(self, dt: float, error: bool) -> None:
        self._err.append(bool(error))
        if error:
            # error steps stay OUT of the latency window: a failure
            # raising in microseconds would drag p50 DOWN and let a
            # flaky replica evade the latency rule
            self.step_errors += 1
            self.consec_errors += 1
            if self.breaker == BREAKER_HALF_OPEN:
                self._probe_err = True
        else:
            self._lat.append(dt)
            self.consec_errors = 0
            if self.breaker == BREAKER_HALF_OPEN:
                self._probe_ok += 1

    def breaker_eval(self, fleet_median: float, cfg: Any
                     ) -> Optional[str]:
        """Advance the breaker one router pump against the fleet signal.

        Returns the transition taken — ``"trip"`` (-> open),
        ``"probe"`` (open -> half_open after cooldown), ``"recover"``
        (half_open -> closed after healthy probe steps) — or None.
        ``fleet_median`` is the fleet latency signal: the median of
        the OTHER replicas' rolling medians (0.0 = no fleet signal:
        only the error rule can trip).  This replica's own SUSTAINED
        latency (``step_p50``) is what's compared — a one-off compile
        or GC spike lifts only the tail and must not trip."""
        if self.breaker == BREAKER_OPEN:
            self._cooldown -= 1
            if self._cooldown <= 0:
                self.breaker = BREAKER_HALF_OPEN
                self._lat.clear()
                self._err.clear()
                self.consec_errors = 0
                self._probe_ok = 0
                self._probe_err = False
                return "probe"
            return None
        # error rules: a consecutive run, ANY error during a half-open
        # probe (docs/SERVING.md: probe errors re-trip), or a majority-
        # erroring window — the intermittent-fault profile that never
        # accumulates a consecutive run
        trip = self.consec_errors >= cfg.breaker_consec_errors
        if not trip and self.breaker == BREAKER_HALF_OPEN:
            trip = self._probe_err
        if (not trip and len(self._err) >= cfg.breaker_min_samples
                and 2 * sum(self._err) >= len(self._err)):
            trip = True
        if not trip and fleet_median > 0.0 and self._lat:
            # latency rule gate: breaker_min_samples when closed; at the
            # half-open DECISION point (probe complete) the probe steps
            # are the evidence — a still-slow replica must re-trip here,
            # not recover and flap (probe_steps < min_samples in every
            # shipped config, so waiting for min_samples would always
            # let recovery win)
            decide = (self.lat_samples >= cfg.breaker_min_samples
                      or (self.breaker == BREAKER_HALF_OPEN
                          and self._probe_ok >= cfg.breaker_probe_steps))
            if decide:
                floor = max(fleet_median, cfg.breaker_min_latency_s)
                trip = self.step_p50() > cfg.breaker_latency_factor * floor
        if trip:
            self.breaker = BREAKER_OPEN
            self._cooldown = int(cfg.breaker_cooldown_pumps)
            self._probe_ok = 0
            self._probe_err = False
            return "trip"
        if (self.breaker == BREAKER_HALF_OPEN
                and self._probe_ok >= cfg.breaker_probe_steps):
            self.breaker = BREAKER_CLOSED
            return "recover"
        return None

    # -- lifecycle -----------------------------------------------------------
    def step(self) -> Dict[int, Dict[str, Any]]:
        """One engine step, timed into the breaker window.  Exceptions
        (chaos hook or engine) are recorded as error steps and
        re-raised — TOLERATING them is the router's decision (it
        swallows per-replica step failures when breakers are enabled,
        letting consecutive errors trip the breaker instead of one
        replica's fault taking the fleet down)."""
        if not self.engine.has_work():
            return {}
        t0 = time.perf_counter()
        try:
            if self._chaos_hook is not None:
                self._chaos_hook(self)
            out = self.engine.step()
        except Exception:
            self._record_step(time.perf_counter() - t0, error=True)
            raise
        self._record_step(time.perf_counter() - t0, error=False)
        return out

    def kill(self) -> None:
        """Chaos hook: simulate an unannounced replica death (process
        gone, KV pages unrecoverable).  The router re-dispatches its
        in-flight requests on the next pump."""
        self.alive = False

    def health(self) -> Dict[str, Any]:
        h = {"role": self.role, "alive": self.alive, "retired": self.retired,
             "preempted": self.watcher.requested or "",
             "breaker": self.breaker,
             "step_p50_s": round(self.step_p50(), 6),
             "step_p95_s": round(self.step_p95(), 6),
             "step_errors": self.step_errors,
             "load": self.load() if self.alive else -1}
        if self.alive:
            h.update(queue_depth=self.engine.queue_depth,
                     active=self.engine.active_count,
                     kv_free_fraction=round(self.kv_free_fraction(), 4))
            tier = getattr(self.engine, "kv_tier", None)
            if tier is not None:
                # host-tier occupancy: the second-tier capacity signal
                # next to the device pool's kv_free_fraction
                h.update(kv_tier_host_pages=tier.host_pages,
                         kv_tier_host_bytes=tier.host_bytes,
                         kv_tier_hit_rate=round(tier.hit_rate, 4))
        return h


__all__ = ["EngineReplica", "ROLE_PREFILL", "ROLE_DECODE", "ROLE_MIXED",
           "ROLES", "BREAKER_CLOSED", "BREAKER_OPEN", "BREAKER_HALF_OPEN"]
