"""``serving`` config block: the multi-replica fleet front tier.

Parsed by ``runtime/config.py`` like every other block (a top-level
``"serving"`` key in the ds-config json) and consumed by
``serving/router.py``'s :class:`FleetRouter` / ``build_fleet``.  The
per-engine knobs (page pool geometry, chunked prefill, prefix cache)
stay in ``RaggedInferenceConfig``; this block only describes the fleet
ABOVE the engines: pool sizes, routing policy, and failure handling.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ..inference.v2.speculative import SpeculativeConfig
from ..runtime.config_utils import ConfigModel


@dataclasses.dataclass
class ServingConfig(ConfigModel):
    """Fleet topology + routing policy (docs/SERVING.md "Fleet
    serving")."""

    enabled: bool = False
    #: replicas that admit new requests and run (chunked) prefill
    prefill_replicas: int = 1
    #: replicas that continue decoding migrated sequences
    decode_replicas: int = 2
    #: prefill/decode disaggregation: ready sequences stream their KV
    #: pages from prefill to decode replicas.  False = one mixed pool of
    #: ``prefill_replicas + decode_replicas`` replicas, no migration.
    disaggregated: bool = True
    #: prompt pages hashed into the affinity key (PR 1 content-hash
    #: chain): more pages = finer-grained placement, fewer = broader
    #: prefix families co-located on one replica's prefix cache
    affinity_pages: int = 4
    #: least-loaded fallback threshold: the affinity choice is overridden
    #: when its load exceeds the least-loaded candidate's by MORE than
    #: this many requests (queue depth + occupied slots)
    load_gap: int = 4
    #: give up re-running a request after this many replica losses
    max_redispatch: int = 3
    #: chunked prefill size for prefill-pool replicas (tokens, rounded up
    #: to page_size by the engine); 0 = inherit the engine config
    prefill_chunk: int = 0
    #: step budget for ``InferenceEngineV2.drain`` during retirement
    drain_max_steps: int = 10_000
    #: fleet-wide speculative decoding (inference/v2/speculative.py):
    #: applied by ``build_fleet`` to EVERY replica's engine config
    #: (speculation only touches the decode phase and is lossless for
    #: greedy streams, so uniform application keeps migration /
    #: re-dispatch bit-identity trivially).  None = inherit whatever the
    #: base engine config says
    speculative: Optional[SpeculativeConfig] = None

    def validate(self) -> None:
        if isinstance(self.speculative, dict):
            # Optional[...] coercion swallows nested validation errors
            # (the Union branch treats them as "try the next type"); an
            # invalid speculative block must fail HERE, not at engine
            # construction
            self.speculative = SpeculativeConfig.from_dict(self.speculative)
        if self.prefill_replicas < 0 or self.decode_replicas < 0:
            raise ValueError("serving replica counts must be >= 0")
        if self.prefill_replicas + self.decode_replicas < 1:
            raise ValueError("serving needs at least one replica")
        if self.disaggregated and self.enabled and (
                self.prefill_replicas < 1 or self.decode_replicas < 1):
            raise ValueError(
                "serving.disaggregated needs >= 1 prefill AND >= 1 decode "
                "replica (set disaggregated=false for a mixed pool)")
        if self.affinity_pages < 1:
            raise ValueError("serving.affinity_pages must be >= 1")
        if self.load_gap < 1:
            raise ValueError("serving.load_gap must be >= 1")
        if self.max_redispatch < 0:
            raise ValueError("serving.max_redispatch must be >= 0")
        if self.drain_max_steps < 1:
            raise ValueError("serving.drain_max_steps must be >= 1")


__all__ = ["ServingConfig"]
