"""``serving`` config block: the multi-replica fleet front tier.

Parsed by ``runtime/config.py`` like every other block (a top-level
``"serving"`` key in the ds-config json) and consumed by
``serving/router.py``'s :class:`FleetRouter` / ``build_fleet``.  The
per-engine knobs (page pool geometry, chunked prefill, prefix cache)
stay in ``RaggedInferenceConfig``; this block only describes the fleet
ABOVE the engines: pool sizes, routing policy, and failure handling.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ..inference.v2.speculative import SpeculativeConfig
from ..runtime.config_utils import ConfigModel


@dataclasses.dataclass
class KVTierConfig(ConfigModel):
    """Tiered KV cache (docs/SERVING.md "Tiered KV cache"): host-RAM
    spill & restore of cold prefix-cache pages.

    Pages evicted from the device prefix-cache LRU are captured into a
    byte-budgeted host LRU (``serving/kv_tier.py``) keyed by the PR 1
    content-hash chain keys, in the pool's exact dtype (int8 codes +
    scales under ``kv_quant``), and restored — CRC-verified, bit
    identical — when a later request's prefix walks past the device
    hit.  The block rides on :class:`RaggedInferenceConfig` (per
    engine) and, fleet-wide, on ``serving.kv_tier`` where
    ``build_fleet`` applies it to every replica."""

    enabled: bool = False
    #: byte budget for spilled pages resident in host RAM (LRU beyond
    #: it); host RAM is typically 10-50x the HBM slice spared for
    #: cached KV, so the default is deliberately generous
    host_bytes: int = 1 << 30
    #: bound on pages pinned awaiting their D2H spill commit (the
    #: in-flight queue drained at step boundaries).  Evictions past the
    #: bound are simply not captured — the device never blocks on the
    #: host tier
    spill_inflight: int = 64
    #: queued-but-not-admitted requests whose host-tier restores are
    #: prefetched while the current batch decodes (0 = admission-time
    #: restore only)
    prefetch_requests: int = 1
    #: NVMe third tier under the host LRU (docs/SERVING.md
    #: "Cross-process fleet"): pages evicted from the byte-budgeted host
    #: LRU demote to a file-backed LRU (one DSTPUKV2 page record per
    #: file under ``nvme_dir``) instead of being dropped, and a host
    #: miss consults the files — CRC-verified on read, promote-on-hit.
    nvme_enabled: bool = False
    #: directory for the page files; empty = a per-tier mkdtemp under
    #: the system temp dir (fine for drills; production points this at
    #: an NVMe mount)
    nvme_dir: str = ""
    #: byte budget for page files on disk (LRU beyond it)
    nvme_bytes: int = 16 << 30

    def validate(self) -> None:
        if self.host_bytes < 0:
            raise ValueError("kv_tier.host_bytes must be >= 0")
        if self.spill_inflight < 1:
            raise ValueError("kv_tier.spill_inflight must be >= 1")
        if self.prefetch_requests < 0:
            raise ValueError("kv_tier.prefetch_requests must be >= 0")
        if self.nvme_bytes < 0:
            raise ValueError("kv_tier.nvme_bytes must be >= 0")


@dataclasses.dataclass
class TransportConfig(ConfigModel):
    """Cross-process KV transport (``serving/transport.py``): socket
    framing + the sender's bounded connect/send retry policy.  The
    backoff schedule mirrors the ``resilience/`` elastic-agent policy
    (exponential, capped, seeded jitter) so a dead peer costs a BOUNDED
    number of connect attempts — never an infinite reconnect loop."""

    #: bounded connect/reconnect attempts before the transport gives up
    #: (the caller keeps the pages — a dead peer loses nothing)
    connect_retries: int = 5
    #: exponential backoff between attempts: base * 2^(attempt-1),
    #: capped at max, times (1 + jitter * seeded_uniform)
    backoff_base_s: float = 0.05
    backoff_max_s: float = 2.0
    backoff_jitter: float = 0.25
    #: per-frame send/recv socket timeout (seconds); a peer that stalls
    #: longer than this mid-frame tears the connection instead of
    #: wedging the sender thread forever
    io_timeout_s: float = 30.0
    #: bounded depth of the async sender's in-flight queue (2 = double
    #: buffering: bundle N on the wire while N+1 serializes)
    sender_depth: int = 2

    def validate(self) -> None:
        if self.connect_retries < 1:
            raise ValueError("transport.connect_retries must be >= 1")
        if self.backoff_base_s < 0 or self.backoff_max_s < 0:
            raise ValueError("transport backoff seconds must be >= 0")
        if self.backoff_jitter < 0:
            raise ValueError("transport.backoff_jitter must be >= 0")
        if self.io_timeout_s <= 0:
            raise ValueError("transport.io_timeout_s must be > 0")
        if self.sender_depth < 1:
            raise ValueError("transport.sender_depth must be >= 1")


@dataclasses.dataclass
class AutoscaleConfig(ConfigModel):
    """Elastic fleet sizing (``serving/autoscale.py``): grow/shrink the
    replica count from queue-depth / TTFT-violation signals, reusing
    the drain/evacuation machinery so scale-down never drops a stream."""

    enabled: bool = False
    #: replica-count bounds the policy may move between
    min_replicas: int = 1
    max_replicas: int = 4
    #: grow when fleet queue depth per accepting replica exceeds this
    #: for ``grow_streak`` consecutive pump evaluations
    grow_queue_per_replica: float = 4.0
    grow_streak: int = 2
    #: grow when NEW TTFT deadline violations appeared since the last
    #: evaluation (0 disables the TTFT rule)
    grow_on_ttft_violations: bool = True
    #: shrink when the fleet has been under this queue-depth-per-replica
    #: for ``shrink_streak`` consecutive evaluations AND the candidate
    #: replica is idle enough to evacuate cheaply
    shrink_queue_per_replica: float = 0.5
    shrink_streak: int = 8
    #: pump evaluations to wait after ANY scale action before the next
    #: (hysteresis — a fresh replica needs time to absorb load)
    cooldown_pumps: int = 8

    def validate(self) -> None:
        if self.min_replicas < 1:
            raise ValueError("autoscale.min_replicas must be >= 1")
        if self.max_replicas < self.min_replicas:
            raise ValueError("autoscale.max_replicas must be >= "
                             "min_replicas")
        if self.grow_queue_per_replica <= 0:
            raise ValueError("autoscale.grow_queue_per_replica must be > 0")
        if self.shrink_queue_per_replica < 0:
            raise ValueError("autoscale.shrink_queue_per_replica must "
                             "be >= 0")
        if self.shrink_queue_per_replica >= self.grow_queue_per_replica:
            raise ValueError("autoscale.shrink_queue_per_replica must be "
                             "< grow_queue_per_replica (hysteresis band)")
        if self.grow_streak < 1 or self.shrink_streak < 1:
            raise ValueError("autoscale grow/shrink streaks must be >= 1")
        if self.cooldown_pumps < 0:
            raise ValueError("autoscale.cooldown_pumps must be >= 0")


@dataclasses.dataclass
class ServingConfig(ConfigModel):
    """Fleet topology + routing policy (docs/SERVING.md "Fleet
    serving")."""

    enabled: bool = False
    #: replicas that admit new requests and run (chunked) prefill
    prefill_replicas: int = 1
    #: replicas that continue decoding migrated sequences
    decode_replicas: int = 2
    #: prefill/decode disaggregation: ready sequences stream their KV
    #: pages from prefill to decode replicas.  False = one mixed pool of
    #: ``prefill_replicas + decode_replicas`` replicas, no migration.
    disaggregated: bool = True
    #: prompt pages hashed into the affinity key (PR 1 content-hash
    #: chain): more pages = finer-grained placement, fewer = broader
    #: prefix families co-located on one replica's prefix cache
    affinity_pages: int = 4
    #: least-loaded fallback threshold: the affinity choice is overridden
    #: when its load exceeds the least-loaded candidate's by MORE than
    #: this many requests (queue depth + occupied slots)
    load_gap: int = 4
    #: give up re-running a request after this many replica losses
    max_redispatch: int = 3
    #: chunked prefill size for prefill-pool replicas (tokens, rounded up
    #: to page_size by the engine); 0 = inherit the engine config
    prefill_chunk: int = 0
    #: step budget for ``InferenceEngineV2.drain`` during retirement
    drain_max_steps: int = 10_000
    #: fleet-wide speculative decoding (inference/v2/speculative.py):
    #: applied by ``build_fleet`` to EVERY replica's engine config
    #: (speculation only touches the decode phase and is lossless for
    #: greedy streams, so uniform application keeps migration /
    #: re-dispatch bit-identity trivially).  None = inherit whatever the
    #: base engine config says
    speculative: Optional[SpeculativeConfig] = None
    #: fleet-wide tiered KV cache (serving/kv_tier.py): applied by
    #: ``build_fleet`` to EVERY replica's engine config (spill/restore
    #: is bit-identical by contract, so uniform application keeps
    #: migration / re-dispatch bit-identity trivially).  None = inherit
    #: whatever the base engine config says
    kv_tier: Optional[KVTierConfig] = None
    #: fleet-wide fused multi-step decode horizon (docs/SERVING.md
    #: "Multi-step decode"): applied by ``build_fleet`` to EVERY
    #: replica's engine config.  Decode horizons are stream-identical
    #: by contract (greedy and sampled alike), so uniform application
    #: keeps migration / re-dispatch bit-identity trivially; replicas
    #: with speculative decoding enabled stand the horizon down on
    #: their own (one exclusive decode path at a time).  None =
    #: inherit whatever the base engine config says
    decode_horizon: Optional[int] = None

    # -- admission control & load shedding (serving/admission.py) -----------
    #: fleet-wide bounded queue: submissions are shed (RejectedError
    #: with a retry-after hint) once this many requests wait for
    #: admission across accepting replicas; 0 = unbounded
    max_queue_depth: int = 0
    #: KV-pool shed threshold: shed when even the coolest accepting
    #: replica's projected occupancy (current used pages + the request's
    #: estimated page cost) exceeds this fraction; 0.0 = off
    shed_occupancy: float = 0.0
    #: priority classes <= this value are NEVER shed by the rules above
    #: (they fail only when no live replica exists).  Default 0 protects
    #: exactly PRIORITY_INTERACTIVE.
    protect_priority: int = 0

    # -- replica circuit breakers (serving/replica.py state machine) --------
    #: detect gray failure: a replica whose rolling MEDIAN step latency
    #: (sustained — compile/GC spikes lift only the tail and never
    #: trip) exceeds ``breaker_latency_factor`` x the fleet median of
    #: the OTHER replicas, or which throws ``breaker_consec_errors``
    #: step exceptions in a row, trips open: drained of new placement,
    #: its in-flight streams re-dispatched (bit-identical recompute)
    breaker_enabled: bool = True
    breaker_latency_factor: float = 3.0
    breaker_consec_errors: int = 3
    #: rolling step-latency window length and the samples required
    #: before the latency rule may trip (noise floor)
    breaker_window: int = 32
    breaker_min_samples: int = 8
    #: latency floor (seconds): the fleet median is clamped up to this
    #: before the factor comparison, so microsecond-fast idle fleets
    #: don't trip on scheduler jitter
    breaker_min_latency_s: float = 0.005
    #: router pumps an open breaker waits before probing (half-open),
    #: and the healthy steps a half-open replica must serve to close
    breaker_cooldown_pumps: int = 8
    breaker_probe_steps: int = 4

    # -- live decode rebalancing (router._rebalance_decode) -----------------
    #: migrate DECODE load off hot replicas continuously (today's router
    #: only places NEW work; this moves RUNNING streams).  Bit-identity
    #: is the migration contract, so a moved stream is indistinguishable
    #: from one that stayed.
    rebalance_enabled: bool = False
    #: act when the hottest replica's load exceeds the coolest accepting
    #: peer's by MORE than this many requests
    rebalance_load_gap: int = 4
    #: also act when a replica's rolling p50 step latency exceeds this
    #: factor x the fleet median of its peers (keep BELOW
    #: breaker_latency_factor so rebalancing relieves a warm replica
    #: before the breaker declares it failed)
    rebalance_p50_factor: float = 2.0
    #: sequences moved per router pump (small: each migration steals a
    #: step slot from the destination)
    rebalance_max_per_pump: int = 2
    #: deadline awareness: a sequence with less than this many seconds
    #: of deadline budget left is never migrated (the move itself costs
    #: time the stream doesn't have)
    rebalance_min_deadline_s: float = 0.5

    #: elastic replica scaling policy (serving/autoscale.py); None =
    #: fixed fleet size
    autoscale: Optional[AutoscaleConfig] = None
    #: cross-process KV transport knobs (serving/transport.py); None =
    #: defaults (the transport is only exercised by cross-process
    #: replicas — single-process fleets never open a socket)
    transport: Optional[TransportConfig] = None

    def validate(self) -> None:
        if isinstance(self.speculative, dict):
            # Optional[...] coercion swallows nested validation errors
            # (the Union branch treats them as "try the next type"); an
            # invalid speculative block must fail HERE, not at engine
            # construction
            self.speculative = SpeculativeConfig.from_dict(self.speculative)
        if isinstance(self.kv_tier, dict):
            # same Optional[...] coercion hazard as speculative above:
            # an invalid kv_tier block must fail HERE with its own error
            self.kv_tier = KVTierConfig.from_dict(self.kv_tier)
        if self.kv_tier is not None:
            self.kv_tier.validate()
        if isinstance(self.autoscale, dict):
            # same Optional[...] coercion hazard: fail HERE, loudly
            self.autoscale = AutoscaleConfig.from_dict(self.autoscale)
        if self.autoscale is not None:
            self.autoscale.validate()
        if isinstance(self.transport, dict):
            self.transport = TransportConfig.from_dict(self.transport)
        if self.transport is not None:
            self.transport.validate()
        if self.decode_horizon is not None and self.decode_horizon < 1:
            raise ValueError("serving.decode_horizon must be >= 1 "
                             "(1 = the classic one-step decode loop)")
        if self.prefill_replicas < 0 or self.decode_replicas < 0:
            raise ValueError("serving replica counts must be >= 0")
        if self.prefill_replicas + self.decode_replicas < 1:
            raise ValueError("serving needs at least one replica")
        if self.disaggregated and self.enabled and (
                self.prefill_replicas < 1 or self.decode_replicas < 1):
            raise ValueError(
                "serving.disaggregated needs >= 1 prefill AND >= 1 decode "
                "replica (set disaggregated=false for a mixed pool)")
        if self.affinity_pages < 1:
            raise ValueError("serving.affinity_pages must be >= 1")
        if self.load_gap < 1:
            raise ValueError("serving.load_gap must be >= 1")
        if self.max_redispatch < 0:
            raise ValueError("serving.max_redispatch must be >= 0")
        if self.drain_max_steps < 1:
            raise ValueError("serving.drain_max_steps must be >= 1")
        if self.max_queue_depth < 0:
            raise ValueError("serving.max_queue_depth must be >= 0")
        if not 0.0 <= self.shed_occupancy <= 1.0:
            raise ValueError("serving.shed_occupancy must be in [0, 1] "
                             "(0 disables the pool-pressure shed rule)")
        if self.protect_priority < 0:
            raise ValueError("serving.protect_priority must be >= 0")
        if self.breaker_latency_factor <= 1.0:
            raise ValueError("serving.breaker_latency_factor must be > 1")
        if self.breaker_consec_errors < 1:
            raise ValueError("serving.breaker_consec_errors must be >= 1")
        if self.breaker_window < 2 or self.breaker_min_samples < 2:
            raise ValueError("serving.breaker_window and "
                             "breaker_min_samples must be >= 2")
        if self.breaker_min_samples > self.breaker_window:
            raise ValueError("serving.breaker_min_samples must be <= "
                             "breaker_window")
        if self.breaker_min_latency_s < 0:
            raise ValueError("serving.breaker_min_latency_s must be >= 0")
        if self.breaker_cooldown_pumps < 1 or self.breaker_probe_steps < 1:
            raise ValueError("serving.breaker_cooldown_pumps and "
                             "breaker_probe_steps must be >= 1")
        if self.rebalance_load_gap < 1:
            raise ValueError("serving.rebalance_load_gap must be >= 1")
        if self.rebalance_p50_factor <= 1.0:
            raise ValueError("serving.rebalance_p50_factor must be > 1")
        if self.rebalance_enabled and self.breaker_enabled and (
                self.rebalance_p50_factor >= self.breaker_latency_factor):
            raise ValueError(
                "serving.rebalance_p50_factor must be < "
                "breaker_latency_factor (rebalance relieves a warm "
                "replica BEFORE the breaker declares it failed)")
        if self.rebalance_max_per_pump < 1:
            raise ValueError("serving.rebalance_max_per_pump must be >= 1")
        if self.rebalance_min_deadline_s < 0:
            raise ValueError("serving.rebalance_min_deadline_s must "
                             "be >= 0")


__all__ = ["ServingConfig", "KVTierConfig", "AutoscaleConfig",
           "TransportConfig"]
