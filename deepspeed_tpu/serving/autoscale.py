"""Elastic fleet sizing: grow/shrink replicas from load signals.

The fleet so far had a FIXED topology: whatever ``build_fleet``
constructed is what traffic gets, however the queue looks.  This
module closes the loop: a :class:`FleetAutoscaler` watches two signals
each router pump — **queue depth per accepting replica** (the router's
own load measure) and **new TTFT SLO violations** (the
``deepspeed_tpu_serving_slo_ttft_violations_total`` counter the engine
already publishes) — and moves the replica count between
``autoscale.min_replicas`` and ``max_replicas``:

* **Grow** when the queue-per-replica signal has exceeded
  ``grow_queue_per_replica`` for ``grow_streak`` consecutive
  evaluations, or when new TTFT violations appeared since the last
  evaluation (latency debt is the leading indicator; queue depth the
  confirming one).  New replicas come from the injected
  ``spawn_replica`` factory — an in-process engine, or a cross-process
  :class:`~.transport.RemoteEngineProxy` replica; the autoscaler
  neither knows nor cares.
* **Shrink** when the fleet has idled under
  ``shrink_queue_per_replica`` for ``shrink_streak`` evaluations.
  Scale-down is LIFO (the most recently grown replica goes first —
  its caches are the coldest) and ALWAYS via
  ``router.retire_replica(name, migrate=True)``: decode-ready streams
  evacuate with their KV pages, everything else re-dispatches — a
  scale-down never drops a stream, the same contract preemption
  evacuation has honored since PR 6.

Failure policy mirrors the ``resilience/`` elastic-agent: a failed
spawn backs off exponentially (capped, seeded jitter) in PUMP units —
a broken replica factory costs a bounded, decaying trickle of
attempts, never a hot spawn loop.  ``cooldown_pumps`` of hysteresis
follow every action so a fresh replica gets to absorb load before the
signals are trusted again.

Owns the ``deepspeed_tpu_serving_autoscale_*`` metric family
(docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import random
from typing import Any, Callable, List, Optional

from ..telemetry import get_registry
from ..telemetry.spans import record_event
from ..utils.logging import logger
from .config import AutoscaleConfig
from .replica import ROLE_DECODE, ROLE_MIXED, ROLE_PREFILL, EngineReplica

_TTFT_COUNTER = "deepspeed_tpu_serving_slo_ttft_violations_total"


class FleetAutoscaler:
    """Pump-driven elastic sizing policy over a ``FleetRouter``.

    Call :meth:`evaluate` once per router pump (after ``router.step``);
    it returns the action taken — ``"grow"`` / ``"shrink"`` — or None.
    ``spawn_replica`` is called with a monotonically increasing index
    and must return a fresh :class:`~.replica.EngineReplica` (weights
    and page geometry matching the fleet)."""

    def __init__(self, router: Any,
                 config: Optional[AutoscaleConfig] = None,
                 spawn_replica: Optional[
                     Callable[[int], EngineReplica]] = None,
                 seed: int = 0):
        self.router = router
        self.config = config or AutoscaleConfig(enabled=True)
        self.spawn_replica = spawn_replica
        self._rand = random.Random(seed)
        self._grow_streak = 0
        self._shrink_streak = 0
        self._cooldown = 0
        self._spawn_failures = 0
        self._spawn_backoff = 0      # pumps left to skip after a failure
        self._spawn_index = 0
        self._ttft_seen = self._ttft_total()
        #: replicas THIS autoscaler grew, oldest first (LIFO shrink)
        self.grown: List[str] = []
        self._init_metrics()

    # -- telemetry -----------------------------------------------------------
    def _init_metrics(self) -> None:
        reg = get_registry()
        self._m_grow = reg.counter(
            "deepspeed_tpu_serving_autoscale_grow_total",
            "replicas added by the elastic sizing policy")
        self._m_shrink = reg.counter(
            "deepspeed_tpu_serving_autoscale_shrink_total",
            "replicas retired by the elastic sizing policy (always via "
            "evacuation — a scale-down never drops a stream)")
        self._m_replicas = reg.gauge(
            "deepspeed_tpu_serving_autoscale_replicas",
            "replicas currently accepting work, as the autoscaler "
            "counts them")
        self._m_qpr = reg.gauge(
            "deepspeed_tpu_serving_autoscale_queue_per_replica",
            "fleet queue depth per accepting replica — the grow/shrink "
            "occupancy signal")
        self._m_spawn_failures = reg.counter(
            "deepspeed_tpu_serving_autoscale_spawn_failures_total",
            "spawn_replica factory failures (each enters the bounded "
            "elastic-agent backoff schedule)")

    @staticmethod
    def _ttft_total() -> float:
        m = get_registry().get(_TTFT_COUNTER)
        return m.total() if m is not None else 0.0

    # -- signals -------------------------------------------------------------
    def _accepting(self) -> List[EngineReplica]:
        return [r for r in self.router.replicas.values()
                if r.accepts_new()]

    def _queue_per_replica(self) -> float:
        acc = self._accepting()
        if not acc:
            return float("inf")  # zero capacity and any queue = grow
        return sum(r.engine.queue_depth for r in acc) / len(acc)

    # -- the policy ----------------------------------------------------------
    def evaluate(self) -> Optional[str]:
        cfg = self.config
        acc = self._accepting()
        qpr = self._queue_per_replica()
        self._m_replicas.set(len(acc))
        self._m_qpr.set(0.0 if qpr == float("inf") else qpr)
        ttft_now = self._ttft_total()
        new_ttft = ttft_now - self._ttft_seen
        self._ttft_seen = ttft_now
        if self._cooldown > 0:
            self._cooldown -= 1
            return None
        if self._spawn_backoff > 0:
            self._spawn_backoff -= 1
            return None
        # streaks: consecutive evaluations, reset on any quiet reading
        self._grow_streak = (self._grow_streak + 1
                             if qpr > cfg.grow_queue_per_replica else 0)
        self._shrink_streak = (self._shrink_streak + 1
                               if qpr < cfg.shrink_queue_per_replica else 0)
        want_grow = (self._grow_streak >= cfg.grow_streak
                     or (cfg.grow_on_ttft_violations and new_ttft > 0
                         and qpr > 0))
        if want_grow and len(acc) < cfg.max_replicas:
            return self._grow(qpr, new_ttft)
        if (self._shrink_streak >= cfg.shrink_streak
                and len(acc) > cfg.min_replicas):
            return self._shrink(qpr)
        return None

    def _grow(self, qpr: float, new_ttft: float) -> Optional[str]:
        if self.spawn_replica is None:
            return None
        idx = self._spawn_index
        self._spawn_index += 1
        try:
            replica = self.spawn_replica(idx)
            self.router.add_replica(replica)
        except Exception as e:  # noqa: BLE001 — a broken factory must
            # back off, not kill the serving loop
            self._spawn_failures += 1
            self._m_spawn_failures.inc()
            self._spawn_backoff = self._backoff_pumps(self._spawn_failures)
            logger.warning(
                f"autoscale: spawn_replica failed ({e!r}); backing off "
                f"{self._spawn_backoff} pumps "
                f"(failure #{self._spawn_failures})")
            return None
        self._spawn_failures = 0
        self.grown.append(replica.name)
        self._grow_streak = 0
        self._cooldown = self.config.cooldown_pumps
        self._m_grow.inc()
        record_event("autoscale_grow", cat="serve", replica=replica.name,
                     queue_per_replica=round(qpr, 3),
                     new_ttft_violations=new_ttft,
                     fleet=len(self.router.replicas))
        logger.info(f"autoscale: grew fleet with {replica.name} "
                    f"(queue/replica={qpr:.2f}, "
                    f"new TTFT violations={new_ttft:.0f})")
        return "grow"

    def _shrink(self, qpr: float) -> Optional[str]:
        name = self._shrink_candidate()
        if name is None:
            return None
        self.router.retire_replica(name, migrate=True)
        if name in self.grown:
            self.grown.remove(name)
        self._shrink_streak = 0
        self._cooldown = self.config.cooldown_pumps
        self._m_shrink.inc()
        record_event("autoscale_shrink", cat="serve", replica=name,
                     queue_per_replica=round(qpr, 3),
                     fleet=len(self.router.replicas))
        logger.info(f"autoscale: retired {name} "
                    f"(queue/replica={qpr:.2f}); streams evacuated")
        return "shrink"

    def _shrink_candidate(self) -> Optional[str]:
        """LIFO: newest autoscaler-grown replica first (coldest
        caches); otherwise the least-loaded accepting replica whose
        removal keeps a disaggregated fleet functional (>= 1 prefill-
        capable AND >= 1 decode-capable replica remain)."""
        acc = self._accepting()
        for name in reversed(self.grown):
            r = self.router.replicas.get(name)
            if r is not None and r in acc and self._removable(r, acc):
                return name
        for r in sorted(acc, key=lambda x: (x.load(), x.name)):
            if self._removable(r, acc):
                return r.name
        return None

    def _removable(self, r: EngineReplica,
                   acc: List[EngineReplica]) -> bool:
        rest = [o for o in acc if o is not r]
        if not rest:
            return False
        if getattr(self.router.config, "disaggregated", False):
            has_prefill = any(o.role in (ROLE_PREFILL, ROLE_MIXED)
                              for o in rest)
            has_decode = any(o.role in (ROLE_DECODE, ROLE_MIXED)
                             for o in rest)
            return has_prefill and has_decode
        return True

    def _backoff_pumps(self, failures: int) -> int:
        """Elastic-agent schedule in pump units: exponential, capped,
        seeded jitter — bounded pressure on a broken factory."""
        base = min(2 ** max(0, failures - 1), 32)
        return max(1, int(round(
            base * (1.0 + 0.25 * self._rand.random()))))


__all__ = ["FleetAutoscaler", "AutoscaleConfig"]
