"""Fleet router: prefix-cache-affinity request scheduling across N
engine replicas, prefill/decode disaggregation, and failure handling.

One :class:`~..inference.v2.InferenceEngineV2` is one process-worth of
serving; the ROADMAP's millions-of-users scale needs a front tier above
many of them.  This router is that tier, host-side and device-free:

* **Placement** — requests are routed by *prefix-cache affinity*:
  the affinity key is the PR-1 content-hash chain over the prompt's
  leading full pages (``PrefixCache.chain_key``), so requests sharing a
  system prompt / few-shot template land on the replica whose prefix
  cache already holds those pages.  Rendezvous (highest-random-weight)
  hashing keeps the mapping deterministic and stable as replicas come
  and go; a **least-loaded fallback** (driven by the same queue-depth /
  occupancy quantities the serving gauges publish) overrides affinity
  when the favorite is more than ``load_gap`` requests hotter than the
  coolest candidate.
* **Disaggregation** — prefill-role replicas run (chunked) prefill;
  the moment a sequence is decode-ready its KV pages stream to a
  decode-role replica (``kv_transfer.migrate_sequence``, ref-count
  adoption on import).  If no decode replica has capacity the sequence
  simply keeps decoding where it is — roles are preferences, so the
  fleet degrades to mixed serving instead of losing work.
* **Lifecycle** — a replica death (chaos ``kill()``) re-dispatches its
  in-flight requests (prompt + tokens emitted so far, greedy streams
  stay bit-identical); a PR-5 preemption notice triggers graceful
  evacuation: decode-ready sequences migrate with their KV, the rest
  re-dispatch, and the replica retires without dropping a stream.

Everything observable flows through the ``deepspeed_tpu_serving_fleet_*``
metric family and ``fleet_*`` trace events (docs/SERVING.md catalog).
"""

from __future__ import annotations

import hashlib
import itertools
import statistics
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..inference.v2.engine_v2 import RaggedRequest
from ..inference.v2.ragged import PrefixCache, RejectedError
from ..telemetry import get_registry
from ..telemetry.reqtrace import get_reqtrace_ledger, slo_exemplar
from ..telemetry.spans import record_event
from ..utils.logging import logger
from .admission import AdmissionController, record_shed, retry_after_hint
from .config import ServingConfig
from .kv_transfer import migrate_sequence
from .replica import (BREAKER_OPEN, ROLE_DECODE, ROLE_MIXED, ROLE_PREFILL,
                      EngineReplica)

#: breaker_state gauge encoding (docs/OBSERVABILITY.md)
_BREAKER_STATE_CODE = {"closed": 0, "half_open": 1, "open": 2}

#: per-process router instance counter: the trace-id namespace.  Request
#: uids are PER-ENGINE (two replicas both have a uid 0), so cross-replica
#: correlation keys on the router-minted ``trace_id`` instead — and two
#: routers in one process (drills build several fleets) must not collide
#: either.  Counter-based, never uuid/time: drills replay bit-identically
#: under ``--seed``.
_ROUTER_SEQ = itertools.count()


# -- pure routing policy (unit-testable without engines) ---------------------
def affinity_key(prompt_ids: Sequence[int], page_size: int,
                 affinity_pages: int = 4) -> bytes:
    """Affinity key of a prompt: the PR-1 content-hash chain
    (``PrefixCache.chain_key``) over its leading full pages, capped at
    ``affinity_pages``.  Prompts shorter than one page hash whole —
    still deterministic, still groups identical prompts."""
    n_full = min(len(prompt_ids) // page_size, max(1, affinity_pages))
    if n_full == 0:
        return PrefixCache.chain_key(None, prompt_ids)
    key: Optional[bytes] = None
    for j in range(n_full):
        key = PrefixCache.chain_key(
            key, prompt_ids[j * page_size:(j + 1) * page_size])
    return key  # type: ignore[return-value]


def hrw_score(key: bytes, name: str) -> int:
    """Rendezvous weight of (request key, replica name): deterministic,
    uniform, and stable — removing one replica only re-homes the keys
    that mapped to it."""
    return int.from_bytes(
        hashlib.sha256(key + b"\x00" + name.encode()).digest()[:8], "big")


def pick_replica(key: bytes, candidates: Sequence[Any], load_gap: int
                 ) -> Tuple[Any, str]:
    """Choose among ``candidates`` (objects with ``.name`` and
    ``.load()``): the HRW-affinity favorite unless it is more than
    ``load_gap`` requests hotter than the least-loaded candidate, in
    which case the least-loaded one (ties broken by name, so the choice
    is deterministic).  Returns ``(replica, "affinity"|"least_loaded")``."""
    if not candidates:
        raise ValueError("no candidate replicas")
    favorite = max(candidates, key=lambda r: (hrw_score(key, r.name), r.name))
    loads = {r.name: r.load() for r in candidates}
    coolest = min(loads.values())
    if loads[favorite.name] - coolest <= load_gap:
        return favorite, "affinity"
    least = min(candidates, key=lambda r: (loads[r.name], r.name))
    return least, "least_loaded"


class _RequestRecord:
    """Router-side view of one request across replica hops."""

    __slots__ = ("request", "replica", "emitted", "done", "failed",
                 "redispatches", "finish_reason", "deadline_abs",
                 "trace_id", "submitted_at")

    def __init__(self, request: RaggedRequest,
                 trace_id: Optional[str] = None):
        self.request = request
        self.replica: Optional[str] = None  # current owner
        self.emitted: List[int] = []        # tokens streamed so far
        self.done = False
        self.failed = False
        self.redispatches = 0
        self.finish_reason = ""             # set when done
        #: the fleet-unique correlation key (router-minted)
        self.trace_id = trace_id
        #: FIRST-submission stamp: re-dispatch hops re-enqueue with a
        #: fresh engine clock, but end-to-end accounting (the reqtrace
        #: ledger) measures from here
        self.submitted_at = time.perf_counter()
        #: absolute expiry on this process's perf_counter clock; hops
        #: (re-dispatch) carry the REMAINING budget, not a fresh one
        self.deadline_abs = (self.submitted_at + request.deadline_s
                             if request.deadline_s is not None else None)

    def deadline_left(self) -> Optional[float]:
        if self.deadline_abs is None:
            return None
        return max(0.0, self.deadline_abs - time.perf_counter())


class FleetRouter:
    """Front tier over a list of :class:`EngineReplica`.

    Drive it like an engine: ``submit()`` requests, ``step()`` (one
    pump of the whole fleet) until done — or ``run_all()`` for batch
    use.  All replicas must share weights and page geometry (greedy
    streams are then bit-identical to a single engine, kill or no
    kill)."""

    def __init__(self, replicas: Sequence[EngineReplica],
                 config: Optional[ServingConfig] = None):
        if not replicas:
            raise ValueError("FleetRouter needs at least one replica")
        names = [r.name for r in replicas]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate replica names: {names}")
        ps = {r.engine.block.page_size for r in replicas}
        if len(ps) != 1:
            raise ValueError(f"replicas disagree on page_size: {ps} — "
                             "KV migration needs one geometry")
        self.config = config or ServingConfig()
        self.replicas: Dict[str, EngineReplica] = {r.name: r for r in replicas}
        self._page_size = ps.pop()
        self._requests: Dict[int, _RequestRecord] = {}
        self._uid = itertools.count()
        #: fleet request tracing: this router's trace-id namespace plus
        #: the (shared, process-default) lifecycle ledger — co-located
        #: replicas write into the same ledger, so one request is ONE
        #: trace across its prefill/decode/re-dispatch hops
        self._trace_prefix = f"r{next(_ROUTER_SEQ)}"
        self._trace_seq = itertools.count()
        self.reqtrace = get_reqtrace_ledger(create=True)
        self.admission = AdmissionController(self.config)
        self._init_metrics()
        self._publish()

    # -- telemetry -----------------------------------------------------------
    def _init_metrics(self) -> None:
        reg = get_registry()
        self._m_live = reg.gauge(
            "deepspeed_tpu_serving_fleet_replicas_live",
            "replicas accepting work (alive, not retired/preempted)")
        self._m_inflight = reg.gauge(
            "deepspeed_tpu_serving_fleet_inflight_requests",
            "submitted requests not yet finished")
        self._m_requests = reg.counter(
            "deepspeed_tpu_serving_fleet_requests_total",
            "requests submitted to the router")
        self._m_affinity = reg.counter(
            "deepspeed_tpu_serving_fleet_affinity_routed_total",
            "placements that followed prefix-cache affinity")
        self._m_least = reg.counter(
            "deepspeed_tpu_serving_fleet_least_loaded_routed_total",
            "placements that fell back to the least-loaded replica")
        self._m_migrations = reg.counter(
            "deepspeed_tpu_serving_fleet_migrations_total",
            "sequences streamed prefill -> decode (KV-page migration)")
        self._m_migrated_pages = reg.counter(
            "deepspeed_tpu_serving_fleet_migrated_pages_total",
            "KV pages moved by migration")
        self._m_migration_failures = reg.counter(
            "deepspeed_tpu_serving_fleet_migration_failures_total",
            "migrations refused for capacity (sequence stayed put)")
        self._m_redispatch = reg.counter(
            "deepspeed_tpu_serving_fleet_redispatches_total",
            "in-flight requests re-run after a replica loss")
        self._m_deaths = reg.counter(
            "deepspeed_tpu_serving_fleet_replica_deaths_total",
            "replicas lost without warning")
        self._m_preempt = reg.counter(
            "deepspeed_tpu_serving_fleet_replica_preemptions_total",
            "replicas evacuated after a preemption notice")
        self._m_drains = reg.counter(
            "deepspeed_tpu_serving_fleet_drains_total",
            "replica retirements via engine drain")
        self._m_failed = reg.counter(
            "deepspeed_tpu_serving_fleet_failed_requests_total",
            "requests abandoned after max_redispatch replica losses")
        # circuit-breaker half of the slo_* family (the deadline /
        # queue-wait / shed half lives on engine_v2 + admission.py)
        self._m_breaker_state = reg.gauge(
            "deepspeed_tpu_serving_slo_breaker_state",
            "per-replica breaker state: 0=closed, 1=half_open, 2=open",
            labelnames=("replica",))
        self._m_breaker_trips = reg.counter(
            "deepspeed_tpu_serving_slo_breaker_trips_total",
            "breakers tripped open (gray failure detected: slow or "
            "flaky replica drained of placement)")
        self._m_breaker_recover = reg.counter(
            "deepspeed_tpu_serving_slo_breaker_recoveries_total",
            "breakers closed again after a healthy half-open probe")
        self._m_rebalanced = reg.counter(
            "deepspeed_tpu_serving_fleet_rebalanced_total",
            "running decode streams migrated off hot replicas by live "
            "rebalancing (placement fixed AFTER admission, "
            "bit-identically)")
        self._m_rebalance_skipped = reg.counter(
            "deepspeed_tpu_serving_fleet_rebalance_skipped_deadline_total",
            "rebalance candidates left in place because their remaining "
            "deadline budget was below rebalance_min_deadline_s (the "
            "move itself costs time the stream does not have)")
        self._m_replicas_added = reg.counter(
            "deepspeed_tpu_serving_fleet_replicas_added_total",
            "replicas added to a running fleet (elastic scale-up)")

    def _publish(self) -> None:
        self._m_live.set(sum(1 for r in self.replicas.values()
                             if r.accepts_new()))
        self._m_inflight.set(sum(1 for rec in self._requests.values()
                                 if not rec.done))

    # -- placement -----------------------------------------------------------
    def _place_engine(self, req: RaggedRequest, target: EngineReplica,
                      cands: List[EngineReplica]
                      ) -> Optional[EngineReplica]:
        """Hand ``req`` to ``target``, falling back to the remaining
        candidates coolest-first when an engine-level bounded queue
        refuses — the ONE placement-retry policy, shared by new
        submissions and re-dispatch.  ``record_shed=False``: shed
        accounting (or not — in-flight streams are never shed) is the
        caller's.  Returns the accepting replica, or None when every
        candidate refused."""
        order = [target] + sorted((c for c in cands if c is not target),
                                  key=lambda r: (r.load(), r.name))
        for t in order:
            try:
                t.engine.put(req, record_shed=False)
            except RejectedError:
                continue
            return t
        return None

    def _candidates(self, phase: str) -> List[EngineReplica]:
        """Replicas that can take ``phase`` work, role-preferred with a
        lossless fallback to ANY accepting replica when the preferred
        pool is empty (e.g. every prefill replica died)."""
        roles = (phase, ROLE_MIXED)
        pref = [r for r in self.replicas.values()
                if r.accepts_new() and r.role in roles]
        if pref:
            return pref
        return [r for r in self.replicas.values() if r.accepts_new()]

    def _route(self, prompt_ids: Sequence[int],
               cands: Optional[List[EngineReplica]] = None
               ) -> Tuple[EngineReplica, str]:
        if cands is None:
            cands = self._candidates(ROLE_PREFILL)
        if not cands:
            raise RuntimeError("no live replica accepts work")
        key = affinity_key(prompt_ids, self._page_size,
                           self.config.affinity_pages)
        chosen, via = pick_replica(key, cands, self.config.load_gap)
        (self._m_affinity if via == "affinity" else self._m_least).inc()
        return chosen, via

    # -- request API ---------------------------------------------------------
    def submit(self, request: RaggedRequest) -> int:
        """Route + enqueue one request; returns the router-level uid its
        stream is keyed by (stable across migrations/re-dispatch).

        Under overload this raises :class:`RejectedError` (load
        shedding — bounded queue / KV-pool shed threshold, see
        ``serving/admission.py``) instead of queuing: the caller still
        holds the request and backs off ``retry_after_s``.  Requests at
        or below ``serving.protect_priority`` are never shed by the
        fleet rules; with engine-level hard bounds
        (``inference.v2 max_queue_depth``) they are refused only when
        EVERY accepting engine's queue is full — backpressure of last
        resort, counted as one shed."""
        # the trace id is minted BEFORE admission so a shed carries the
        # exemplar of the request it refused; the ledger entry of a shed
        # request finishes immediately (reason="shed") — its whole
        # lifetime was one queue_wait interval at the front door
        trace_id = f"{self._trace_prefix}-{next(self._trace_seq)}"
        request.trace_id = trace_id
        self.reqtrace.begin(trace_id, priority=request.priority)
        # admission BEFORE allocating a uid: a shed request was never in
        # the fleet (no record, no partial state to clean up)
        cands = self._candidates(ROLE_PREFILL)
        try:
            self.admission.check(request, cands)
        except RejectedError:
            self.reqtrace.finish(trace_id, "shed")
            raise
        except BaseException:
            self.reqtrace.discard(trace_id)
            raise
        target, via = self._route(request.prompt_ids, cands)
        uid = next(self._uid)
        rec = _RequestRecord(request, trace_id=trace_id)
        self._requests[uid] = rec
        tr = self.reqtrace.get(trace_id)
        if tr is not None:
            tr.uid = uid
        # an engine-level bounded queue may refuse the favorite: try the
        # remaining candidates coolest-first (record_shed=False in
        # _place_engine — at most ONE shed per request, counted here,
        # not per engine)
        try:
            req = RaggedRequest(
                prompt_ids=list(request.prompt_ids),
                max_new_tokens=request.max_new_tokens,
                temperature=request.temperature, eos_id=request.eos_id,
                uid=uid, priority=request.priority,
                deadline_s=request.deadline_s, trace_id=trace_id)
            placed = self._place_engine(req, target, cands)
            if placed is None:
                # roles are preferences, not gates: before shedding, try
                # the accepting replicas OUTSIDE the prefill-capable
                # pool (e.g. idle decode replicas — mixed-serving
                # degradation, the same lossless fallback _candidates
                # applies when the preferred pool is empty)
                rest = sorted(
                    (r for r in self.replicas.values()
                     if r.accepts_new() and r not in cands),
                    key=lambda r: (r.load(), r.name))
                if rest:
                    placed = self._place_engine(req, rest[0], rest)
            if placed is None:
                # every accepting engine's hard queue bound refused:
                # shed loudly (once)
                hint = retry_after_hint(
                    self.admission.fleet_queue_depth(cands))
                record_shed(request.priority, "engine_queue_full", hint,
                            uid=uid, trace_id=trace_id)
                self.reqtrace.finish(trace_id, "shed")
                logger.warning(
                    f"fleet: shed priority-{request.priority} request — "
                    "every accepting engine's bounded queue is full; "
                    f"retry after {hint}s")
                raise RejectedError("engine_queue_full",
                                    retry_after_s=hint,
                                    priority=request.priority)
            if placed is not target:
                target, via = placed, "engine_full_fallback"
        except BaseException:
            # the request was never admitted anywhere: a ghost record
            # with done=False would wedge has_work() True forever
            # (the shed path above already finished the ledger entry —
            # discard is a no-op for it)
            self._requests.pop(uid, None)
            self.reqtrace.discard(trace_id)
            raise
        rec.replica = target.name
        self._m_requests.inc()
        record_event("fleet_route", cat="serve", uid=uid,
                     replica=target.name, via=via,
                     priority=request.priority, trace_id=trace_id,
                     prompt_tokens=len(request.prompt_ids))
        self._publish()
        return uid

    def has_work(self) -> bool:
        return any(not rec.done for rec in self._requests.values())

    # -- failure handling ----------------------------------------------------
    def _redispatch(self, uid: int, charge: bool = True) -> None:
        """Re-run an unfinished request elsewhere.  ``charge=False`` is
        for planned retirements (drain handbacks): the request was not
        lost to a replica failure, so it neither consumes the
        ``max_redispatch`` replica-loss budget nor counts in the
        re-dispatch metric."""
        rec = self._requests[uid]
        if rec.done:
            return
        remaining = rec.request.max_new_tokens - len(rec.emitted)
        if remaining <= 0:
            rec.done = True
            self.reqtrace.finish(rec.trace_id, "complete")
            return
        if charge:
            rec.redispatches += 1
            if rec.redispatches > self.config.max_redispatch:
                rec.done = rec.failed = True
                rec.replica = None
                self._m_failed.inc()
                self.reqtrace.finish(rec.trace_id, "failed")
                logger.error(f"fleet: request {uid} abandoned after "
                             f"{rec.redispatches - 1} re-dispatches")
                return
        # continuation prompt = original prompt + tokens already
        # streamed: greedy decoding is deterministic, so the re-run
        # continues the stream bit-identically (the same recompute
        # contract engine preemption relies on)
        prompt = list(rec.request.prompt_ids) + list(rec.emitted)
        cands = self._candidates(ROLE_PREFILL)
        if not cands:
            rec.done = rec.failed = True
            self._m_failed.inc()
            self.reqtrace.finish(rec.trace_id, "failed")
            logger.error(f"fleet: request {uid} lost — no live replicas")
            return
        key = affinity_key(prompt, self._page_size,
                           self.config.affinity_pages)
        target, _via = pick_replica(key, cands, self.config.load_gap)
        tr = self.reqtrace.get(rec.trace_id)
        if tr is not None:
            # the prior-attempt ledger rides the re-dispatch (satellite:
            # no clock restart): attempts++ and back to queue_wait; the
            # replacement prefill classifies as recompute
            tr.note_redispatch()
        # the hop inherits the request's REMAINING deadline budget (a
        # re-dispatch never resets the SLO clock) and its priority.
        # An engine-level bounded queue may refuse the favorite — an
        # in-flight stream is NOT shed for that: try the remaining
        # candidates coolest-first before giving up.
        # an in-flight stream is never "shed": a refusal here is a
        # placement miss (the loss, if total, counts in
        # fleet_failed_requests_total), so no shed accounting
        placed = self._place_engine(RaggedRequest(
            prompt_ids=prompt, max_new_tokens=remaining,
            temperature=rec.request.temperature,
            eos_id=rec.request.eos_id, uid=uid,
            priority=rec.request.priority,
            deadline_s=rec.deadline_left(),
            trace_id=rec.trace_id), target, cands)
        if placed is None:
            rec.done = rec.failed = True
            self._m_failed.inc()
            self.reqtrace.finish(rec.trace_id, "failed")
            logger.error(f"fleet: request {uid} lost — every live replica "
                         "refused the re-dispatch (bounded queues full)")
            return
        target = placed
        rec.replica = target.name
        if charge:
            self._m_redispatch.inc()
        record_event("fleet_redispatch", cat="serve", uid=uid,
                     replica=target.name, emitted=len(rec.emitted),
                     attempt=rec.redispatches, planned=not charge,
                     **({} if rec.trace_id is None
                        else {"trace_id": rec.trace_id}))

    def _owned_uids(self, name: str) -> List[int]:
        return [uid for uid, rec in self._requests.items()
                if rec.replica == name and not rec.done]

    def _clear_breaker_gauge(self, r: EngineReplica) -> None:
        """A dead/retired replica must not export an open breaker
        forever: zero its ``breaker_state`` label on the way out."""
        self._m_breaker_state.set(0, replica=r.name)

    def _reap_dead(self) -> None:
        for r in self.replicas.values():
            if r.alive or r.retired:
                continue
            r.retired = True
            self._clear_breaker_gauge(r)
            lost = self._owned_uids(r.name)
            self._m_deaths.inc()
            record_event("fleet_replica_death", cat="serve",
                         replica=r.name, inflight=len(lost))
            logger.warning(f"fleet: replica {r.name} died with "
                           f"{len(lost)} in-flight request(s); "
                           "re-dispatching")
            for uid in lost:
                self._redispatch(uid)

    def _reap_preempted(self) -> None:
        for r in self.replicas.values():
            if not (r.alive and not r.retired and r.preempted):
                continue
            self._m_preempt.inc()
            record_event("fleet_replica_preempted", cat="serve",
                         replica=r.name, reason=r.watcher.requested)
            logger.warning(f"fleet: replica {r.name} preempted "
                           f"({r.watcher.requested}); evacuating")
            self._evacuate(r)

    def _evacuate(self, r: EngineReplica) -> None:
        """Graceful retirement: decode-ready sequences migrate with
        their KV pages; everything else (queued, mid-prefill) is
        re-dispatched; the replica ends retired with an empty engine."""
        for uid in list(r.engine.ready_uids()):
            # keep trying the rest on failure: a long sequence that fits
            # nowhere must not force shorter ones into full recompute
            self._try_migrate(uid, r)
        leftovers = r.engine.abort_all(reason="evacuate")
        r.retired = True
        self._clear_breaker_gauge(r)
        record_event("fleet_retire", cat="serve", replica=r.name,
                     redispatched=len(leftovers))
        for uid in leftovers:
            self._redispatch(uid)

    # -- disaggregation ------------------------------------------------------
    def _decode_targets(self, src: EngineReplica) -> List[EngineReplica]:
        return [r for r in self.replicas.values()
                if r is not src and r.accepts_new()
                and r.role in (ROLE_DECODE, ROLE_MIXED)]

    def _try_migrate(self, uid: int, src: EngineReplica) -> bool:
        rec = self._requests.get(uid)
        targets = sorted(self._decode_targets(src),
                         key=lambda r: (r.load(), r.name))
        for dst in targets:
            moved = migrate_sequence(src.engine, dst.engine, uid)
            if moved:
                if rec is not None:
                    rec.replica = dst.name
                self._m_migrations.inc()
                self._m_migrated_pages.inc(moved)
                record_event("fleet_migrate", cat="serve", uid=uid,
                             src=src.name, dst=dst.name, pages=moved,
                             **({} if rec is None or rec.trace_id is None
                                else {"trace_id": rec.trace_id}))
                return True
        self._m_migration_failures.inc()
        return False

    def _pump_migrations(self) -> None:
        """Stream decode-ready sequences off prefill-role replicas.
        Runs BEFORE the engines step, so a sequence whose prefill
        finished last pump never decodes on the prefill pool."""
        for r in self.replicas.values():
            if r.role != ROLE_PREFILL or not r.alive or r.retired:
                continue
            if not self._decode_targets(r):
                # decode pool gone: keep decoding here (mixed fallback)
                # without burning a migration-failure count per pump
                continue
            for uid in list(r.engine.ready_uids()):
                self._try_migrate(uid, r)

    # -- live decode rebalancing ---------------------------------------------
    def _hot_decode_replica(self, cands: List[EngineReplica]
                            ) -> Optional[EngineReplica]:
        """The replica rebalancing should relieve this pump, or None.
        Two signals, either suffices: **occupancy** — its load exceeds
        the coolest accepting peer's by more than
        ``rebalance_load_gap`` — or **latency** — its rolling p50
        exceeds ``rebalance_p50_factor`` x the median of its peers
        (the breaker's gray-failure signal at a LOWER threshold:
        rebalancing relieves a warm replica before the breaker
        declares it failed and recomputes everything)."""
        cfg = self.config
        by_load = sorted(cands, key=lambda r: (r.load(), r.name))
        hot = by_load[-1]
        if hot.load() - by_load[0].load() > cfg.rebalance_load_gap:
            return hot
        for r in sorted(cands, key=lambda x: -x.step_p50()):
            if r.lat_samples < cfg.breaker_min_samples:
                continue
            others = [o.step_p50() for o in cands if o is not r
                      and o.breaker != BREAKER_OPEN
                      and o.lat_samples >= cfg.breaker_min_samples]
            if not others:
                continue
            floor = max(statistics.median(others),
                        cfg.breaker_min_latency_s)
            if r.step_p50() > cfg.rebalance_p50_factor * floor:
                return r
        return None

    def _rebalance_decode(self) -> None:
        """Migrate RUNNING decode streams off a hot replica (the router
        historically only placed NEW work; this fixes placement after
        admission).  Bounded per pump, deadline-budget-aware (a stream
        with almost no budget left is never moved — the move costs
        time it doesn't have), and bit-identical by the migration
        contract: a moved stream is indistinguishable from one that
        stayed."""
        cfg = self.config
        cands = [r for r in self.replicas.values()
                 if r.alive and not r.retired
                 and r.role in (ROLE_DECODE, ROLE_MIXED)]
        if len(cands) < 2 or not any(r.accepts_new() for r in cands):
            return
        hot = self._hot_decode_replica(cands)
        if hot is None or not self._decode_targets(hot):
            return
        moved = 0
        for uid in list(hot.engine.ready_uids()):
            if moved >= cfg.rebalance_max_per_pump:
                break
            rec = self._requests.get(uid)
            left = rec.deadline_left() if rec is not None else None
            if left is not None and left < cfg.rebalance_min_deadline_s:
                self._m_rebalance_skipped.inc()
                continue
            if self._try_migrate(uid, hot):
                moved += 1
        if moved:
            self._m_rebalanced.inc(moved)
            record_event("fleet_rebalance", cat="serve", src=hot.name,
                         moved=moved, src_load=hot.load(),
                         src_p50_s=round(hot.step_p50(), 6))
            logger.info(f"fleet: rebalanced {moved} decode stream(s) "
                        f"off {hot.name}")

    # -- circuit breakers ----------------------------------------------------
    def _check_breakers(self) -> None:
        """Advance every live replica's breaker one pump.  The fleet
        signal for the latency rule is the median of the OTHER
        *same-role* replicas' rolling medians (open breakers and short
        windows excluded): prefill chunks and decode steps have
        different cost profiles, so cross-role comparison would trip
        healthy prefill replicas on a fleet of fast decoders.  A
        replica is only *relatively* slow — on a uniformly slow fleet
        (or a role with a single replica) the latency rule stays quiet
        and only consecutive step errors trip.  A trip drains the
        replica of new placement (its ``accepts_new`` goes False) and
        re-dispatches its in-flight streams through the bit-identical
        recompute path."""
        if not self.config.breaker_enabled:
            return
        live = [r for r in self.replicas.values()
                if r.alive and not r.retired]
        for r in live:
            others = [o.step_p50() for o in live
                      if o is not r and o.role == r.role
                      and o.breaker != BREAKER_OPEN
                      and o.lat_samples >= self.config.breaker_min_samples]
            med = statistics.median(others) if others else 0.0
            action = r.breaker_eval(med, self.config)
            if action == "trip":
                self._on_breaker_trip(r, med)
            elif action == "probe":
                record_event("breaker_probe", cat="serve", replica=r.name)
                logger.info(f"fleet: breaker half-open on {r.name} — "
                            "probing with live traffic")
            elif action == "recover":
                # dstpu-lint: allow[slo-exemplar] a recovery clears a
                # fault condition — there is no single offending request
                # whose trace_id could serve as the exemplar
                self._m_breaker_recover.inc()
                record_event("breaker_recover", cat="serve", replica=r.name)
                logger.info(f"fleet: breaker closed on {r.name} — "
                            "recovered after a healthy probe")
            self._m_breaker_state.set(_BREAKER_STATE_CODE[r.breaker],
                                      replica=r.name)

    def _on_breaker_trip(self, r: EngineReplica, fleet_median: float) -> None:
        self._m_breaker_trips.inc()
        lost = self._owned_uids(r.name)
        # the trip's exemplars are the streams it disrupted: every
        # in-flight request on the tripped replica links its trace
        for uid in lost:
            slo_exemplar("deepspeed_tpu_serving_slo_breaker_trips_total",
                         self._requests[uid].trace_id, replica=r.name,
                         uid=uid)
        record_event("breaker_trip", cat="serve", replica=r.name,
                     p50_s=round(r.step_p50(), 6),
                     p95_s=round(r.step_p95(), 6),
                     fleet_median_s=round(fleet_median, 6),
                     consec_errors=r.consec_errors, inflight=len(lost))
        logger.warning(
            f"fleet: breaker OPEN on {r.name} (median step "
            f"{r.step_p50() * 1e3:.1f}ms / p95 {r.step_p95() * 1e3:.1f}ms "
            f"vs fleet median {fleet_median * 1e3:.1f}ms, "
            f"{r.consec_errors} consecutive errors); draining placement, "
            f"re-dispatching {len(lost)} in-flight stream(s)")
        # free the degraded replica's queued + admitted work, then
        # re-run it elsewhere: greedy streams continue bit-identically
        # (prompt + emitted recompute, the replica-death contract)
        r.engine.abort_all(reason="breaker")
        for uid in lost:
            self._redispatch(uid)

    # -- the fleet pump ------------------------------------------------------
    def step(self) -> Dict[int, Dict[str, Any]]:
        """One pump: reap failures, evaluate breakers, migrate ready
        sequences, step every replica.  Returns ``{uid: {"tokens":
        [...], "done": bool}}`` keyed by router uids — the same shape as
        ``engine.step()`` (finished records carry ``finish_reason``)."""
        self._reap_dead()
        self._reap_preempted()
        self._check_breakers()
        if self.config.disaggregated:
            self._pump_migrations()
        if self.config.rebalance_enabled:
            self._rebalance_decode()
        out: Dict[int, Dict[str, Any]] = {}
        for r in self.replicas.values():
            if not (r.alive and not r.retired):
                continue
            try:
                stepped = r.step()
            except Exception as e:
                if not self.config.breaker_enabled:
                    raise
                # gray-failure tolerance: one replica's step fault must
                # not take the fleet down.  The error is recorded in the
                # replica's breaker window — consecutive faults trip the
                # breaker, which re-dispatches its streams.
                logger.warning(f"fleet: replica {r.name} step failed "
                               f"({e!r}); breaker evaluating "
                               f"({r.consec_errors} consecutive)")
                continue
            for uid, rec_out in stepped.items():
                rec = self._requests.get(uid)
                if rec is None:
                    continue
                rec.emitted.extend(rec_out["tokens"])
                if rec_out["done"]:
                    rec.done = True
                    rec.replica = None
                    rec.finish_reason = rec_out.get("finish_reason", "")
                merged = out.setdefault(uid, {"tokens": [], "done": False})
                merged["tokens"].extend(rec_out["tokens"])
                merged["done"] = rec_out["done"]
                if rec_out["done"]:
                    merged["finish_reason"] = rec.finish_reason
        self._publish()
        return out

    def run_all(self, requests: Sequence[RaggedRequest],
                max_steps: int = 10_000) -> Dict[int, List[int]]:
        """Convenience: submit + pump to completion; returns full
        generations keyed by router uid (submission order)."""
        uids = [self.submit(r) for r in requests]
        for _ in range(max_steps):
            if not self.has_work():
                break
            self.step()
        else:
            logger.warning("fleet run_all: max_steps reached with work "
                           "pending")
        return {u: list(self._requests[u].emitted) for u in uids}

    # -- lifecycle / observability ------------------------------------------
    def kill_replica(self, name: str) -> None:
        """Chaos hook: unannounced death; next ``step()`` re-dispatches."""
        self.replicas[name].kill()

    def add_replica(self, replica: EngineReplica) -> None:
        """Join a new replica to a RUNNING fleet (elastic scale-up, or
        a cross-process replica over a :class:`~.transport.
        RemoteEngineProxy`).  Same invariants as construction: unique
        name, identical page geometry — KV migration needs one
        geometry, and a remote engine advertises its page size at the
        transport handshake precisely so this check works unchanged."""
        if replica.name in self.replicas:
            raise ValueError(f"replica name {replica.name!r} already in "
                             "the fleet")
        if replica.engine.block.page_size != self._page_size:
            raise ValueError(
                f"replica {replica.name!r} page_size "
                f"{replica.engine.block.page_size} != fleet page_size "
                f"{self._page_size} — KV migration needs one geometry")
        self.replicas[replica.name] = replica
        self._m_replicas_added.inc()
        record_event("fleet_scale_up", cat="serve", replica=replica.name,
                     role=replica.role, fleet_size=len(self.replicas))
        logger.info(f"fleet: replica {replica.name} joined "
                    f"(role={replica.role}, fleet={len(self.replicas)})")
        self._publish()

    def retire_replica(self, name: str, migrate: bool = True) -> None:
        """Planned retirement.  ``migrate=True`` evacuates (KV migration
        + re-dispatch, nothing recomputed locally); ``migrate=False``
        drains in place — the engine finishes its admitted sequences and
        hands queued ones back for re-dispatch."""
        r = self.replicas[name]
        if not r.alive or r.retired:
            return
        if migrate:
            self._evacuate(r)
            return
        result = r.engine.drain(max_steps=self.config.drain_max_steps)
        self._m_drains.inc()
        unfinished: List[int] = []
        for uid, seq in result["finished"].items():
            rec = self._requests.get(uid)
            if rec is None:
                continue
            # seq.tokens = engine prompt + everything generated there;
            # the engine prompt already contained rec.emitted from hops
            # before this one
            new = seq.tokens[len(rec.request.prompt_ids) + len(rec.emitted):]
            rec.emitted.extend(int(t) for t in new)
            if seq.done:
                rec.done = True
                rec.replica = None
                rec.finish_reason = seq.finish_reason
            else:
                # drain hit drain_max_steps: the sequence is alive but
                # its replica is retiring — hand it elsewhere, else it
                # is stranded forever on a replica step() skips
                unfinished.append(uid)
        r.retired = True
        self._clear_breaker_gauge(r)
        if unfinished:
            # free the stragglers' pages/spans in the retiring engine
            # before re-running them elsewhere
            r.engine.abort_all(reason="drain_timeout")
        for uid in unfinished:
            self._redispatch(uid, charge=False)
        for seq in result["pending"]:
            self._redispatch(seq.uid, charge=False)
        self._publish()

    def request_state(self, uid: int) -> Dict[str, Any]:
        rec = self._requests[uid]
        return {"emitted": list(rec.emitted), "done": rec.done,
                "failed": rec.failed, "replica": rec.replica,
                "redispatches": rec.redispatches,
                "finish_reason": rec.finish_reason,
                "priority": rec.request.priority,
                "deadline_left_s": rec.deadline_left(),
                "trace_id": rec.trace_id}

    def health(self) -> Dict[str, Any]:
        return {name: r.health() for name, r in self.replicas.items()}


def build_fleet(model: Any, serving: Optional[ServingConfig] = None,
                engine_config: Any = None, params: Any = None,
                seed: int = 0) -> FleetRouter:
    """Construct a disaggregated fleet over one weight copy.

    Prefill replicas get ``serving.prefill_chunk`` chunked prefill (when
    set); decode replicas keep the base engine config.  With
    ``disaggregated=False`` every replica is mixed and no migration
    runs."""
    import dataclasses as _dc

    import jax

    from ..inference.v2 import InferenceEngineV2, RaggedInferenceConfig

    serving = serving or ServingConfig()
    base = engine_config or RaggedInferenceConfig()
    if serving.speculative is not None:
        # fleet-wide speculative block overrides the engine config on
        # every replica: speculation is decode-phase-only and lossless
        # for greedy streams, so uniform application preserves the
        # migration / re-dispatch bit-identity contract as-is
        base = _dc.replace(base, speculative=serving.speculative)
    if serving.kv_tier is not None:
        # fleet-wide tiered KV cache: spill/restore is bit-identical by
        # contract, so uniform application likewise preserves the
        # migration / re-dispatch bit-identity (each replica owns its
        # own host LRU — spilled pages are replica-local, like the
        # device prefix cache they extend)
        base = _dc.replace(base, kv_tier=serving.kv_tier)
    if serving.decode_horizon is not None:
        # fleet-wide fused multi-step decode: horizons are
        # stream-identical by contract, so uniform application keeps
        # migration / re-dispatch bit-identity trivially (speculative
        # replicas stand the horizon down themselves)
        base = _dc.replace(base, decode_horizon=serving.decode_horizon)
    if params is None:
        params = model.init_params(jax.random.PRNGKey(seed))
    replicas: List[EngineReplica] = []
    if serving.disaggregated:
        pf_cfg = base
        if serving.prefill_chunk > 0:
            pf_cfg = _dc.replace(base, prefill_chunk=serving.prefill_chunk)
        for i in range(serving.prefill_replicas):
            replicas.append(EngineReplica(
                f"prefill{i}",
                InferenceEngineV2(model, pf_cfg, params=params, seed=seed),
                role=ROLE_PREFILL, breaker_window=serving.breaker_window))
        for i in range(serving.decode_replicas):
            replicas.append(EngineReplica(
                f"decode{i}",
                InferenceEngineV2(model, base, params=params, seed=seed),
                role=ROLE_DECODE, breaker_window=serving.breaker_window))
    else:
        for i in range(serving.prefill_replicas + serving.decode_replicas):
            replicas.append(EngineReplica(
                f"replica{i}",
                InferenceEngineV2(model, base, params=params, seed=seed),
                role=ROLE_MIXED, breaker_window=serving.breaker_window))
    return FleetRouter(replicas, serving)


__all__ = ["FleetRouter", "build_fleet", "affinity_key", "hrw_score",
           "pick_replica"]
