"""Admission control & load shedding for the serving fleet.

The dominant production failure mode is not a crash but **overload**: a
burst of requests grows queues without bound, queued work holds its
submitters hostage, and the KV pool thrashes through admission →
preemption → re-prefill storms.  The cure is to *refuse* work loudly at
the front door while the fleet can still say no cheaply:

* **Bounded queue** — ``serving.max_queue_depth`` caps the fleet-wide
  number of requests waiting for admission (queue depth summed over
  accepting replicas).
* **Token-budget estimator** — a request's KV-page cost is known at
  submit time (``ceil((prompt + max_new_tokens) / page_size)``); when
  the best candidate replica's projected pool occupancy crosses
  ``serving.shed_occupancy`` the fleet is saturated and queuing more
  work only manufactures preemptions.
* **Priority floor** — shedding only ever drops work whose priority
  class is ABOVE ``serving.protect_priority`` (numerically greater =
  less urgent).  Interactive traffic is never shed by these rules; it
  fails only when no live replica exists at all.

A shed is a :class:`RejectedError` carrying a ``retry_after_s`` hint —
the submitter still holds the request and backs off, instead of the
fleet OOMing on its behalf.  Every shed counts
``deepspeed_tpu_serving_slo_shed_total`` (labeled by priority class) and
emits a ``shed`` trace event, so "where did my request go" is always
answerable from the metrics alone.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

from ..inference.v2.ragged import RejectedError
from ..telemetry import get_registry
from ..telemetry.reqtrace import slo_exemplar
from ..telemetry.spans import record_event
from ..utils.logging import logger


def shed_counter():
    """The (single-owner) shed counter, labeled by priority class."""
    return get_registry().counter(
        "deepspeed_tpu_serving_slo_shed_total",
        "requests refused by admission control (load shedding), by "
        "priority class and the engine-level bounded-queue rejections",
        labelnames=("priority",))


def retry_after_hint(queued: int, est_pages: int = 0) -> float:
    """Back-off hint for a shed request: proportional to the backlog it
    would have joined (a documented heuristic, not a promise — ~50ms of
    drain per queued request plus ~20ms per KV page it needs), clamped
    to [0.1s, 30s]."""
    return round(min(30.0, max(0.1, 0.05 * queued + 0.02 * est_pages)), 3)


def record_shed(priority: int, reason: str, retry_after_s: float,
                uid: Optional[int] = None,
                trace_id: Optional[str] = None) -> None:
    """Account one shed decision (counter + trace event + trace
    exemplar) — shared by the fleet controller below and the
    engine-level bounded queue."""
    shed_counter().inc(priority=str(int(priority)))
    slo_exemplar("deepspeed_tpu_serving_slo_shed_total", trace_id,
                 reason=reason, priority=int(priority))
    record_event("shed", cat="serve", priority=int(priority),
                 reason=reason, retry_after_s=retry_after_s,
                 **({} if uid is None else {"uid": uid}),
                 **({} if trace_id is None else {"trace_id": trace_id}))


def estimate_pages(prompt_tokens: int, max_new_tokens: int,
                   page_size: int) -> int:
    """KV pages a request will occupy if it runs to its token budget."""
    return -(-(prompt_tokens + max_new_tokens) // page_size)


class AdmissionController:
    """Fleet-front shed policy over a set of candidate replicas.

    Pure host logic: ``check()`` either returns (admit — with the
    estimated page cost, for event logging) or raises
    :class:`RejectedError`.  Candidates are any objects exposing the
    :class:`~.replica.EngineReplica` load surface (``engine.queue_depth``,
    ``engine.allocator.free_pages`` / ``num_pages``), so the policy is
    unit-testable with fakes."""

    def __init__(self, config: Any):
        self.config = config
        shed_counter()  # register the family even before the first shed

    # -- signals -------------------------------------------------------------
    @staticmethod
    def fleet_queue_depth(candidates: Sequence[Any]) -> int:
        return sum(r.engine.queue_depth for r in candidates)

    @staticmethod
    def best_free_pages(candidates: Sequence[Any]) -> int:
        return max((r.engine.allocator.free_pages for r in candidates),
                   default=0)

    @staticmethod
    def best_occupancy(candidates: Sequence[Any], extra_pages: int = 0
                       ) -> float:
        """Projected pool occupancy of the COOLEST candidate after
        placing ``extra_pages`` there — the fleet is only saturated when
        even its best replica is.  Can exceed 1.0 (the request's
        estimated pages overflow even the emptiest pool), so a
        ``shed_occupancy`` of 1.0 still arms the rule."""
        best = float("inf")
        for r in candidates:
            a = r.engine.allocator
            occ = (a.num_pages - a.free_pages + extra_pages) \
                / max(1, a.num_pages)
            best = min(best, occ)
        return best if best != float("inf") else 1.0

    # -- the decision --------------------------------------------------------
    def check(self, request: Any, candidates: Sequence[Any]) -> int:
        """Admit-or-shed for one request against the accepting replicas.

        Returns the estimated page cost on admit; raises
        :class:`RejectedError` on shed.  Requests at or below
        ``protect_priority`` are NEVER shed here."""
        cfg = self.config
        page_size = (candidates[0].engine.block.page_size
                     if candidates else 16)
        est = estimate_pages(len(request.prompt_ids),
                             request.max_new_tokens, page_size)
        prio = int(getattr(request, "priority", 1))
        if prio <= cfg.protect_priority or not candidates:
            return est
        queued = self.fleet_queue_depth(candidates)
        if cfg.max_queue_depth and queued >= cfg.max_queue_depth:
            self._shed(prio, "queue_full", queued, est,
                       uid=getattr(request, "uid", None),
                       trace_id=getattr(request, "trace_id", None))
        if cfg.shed_occupancy and \
                self.best_occupancy(candidates, est) > cfg.shed_occupancy:
            self._shed(prio, "pool_pressure", queued, est,
                       uid=getattr(request, "uid", None),
                       trace_id=getattr(request, "trace_id", None))
        return est

    def _shed(self, priority: int, reason: str, queued: int, est: int,
              uid: Optional[int] = None,
              trace_id: Optional[str] = None) -> None:
        hint = retry_after_hint(queued, est)
        record_shed(priority, reason, hint, uid=uid, trace_id=trace_id)
        logger.warning(
            f"admission: shed priority-{priority} request ({reason}: "
            f"{queued} queued fleet-wide, ~{est} KV pages needed); "
            f"retry after {hint}s")
        raise RejectedError(reason, retry_after_s=hint, priority=priority)


__all__ = ["AdmissionController", "RejectedError", "record_shed",
           "retry_after_hint", "estimate_pages", "shed_counter"]
