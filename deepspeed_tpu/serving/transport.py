"""Cross-process KV transport: the serving fleet's real wire.

Everything the single-process fleet proved — bit-identical KV
migration (PR 6), disaggregated prefill→decode streaming, breaker-led
evacuation — assumed the bundle never left the process.  This module
puts the DSTPUKV2 wire format (``kv_transfer.bundle_to_bytes`` /
``bundle_from_bytes``: versioned, CRC-per-page, deadline re-based
across clock domains) on an actual socket, so a replica can live in
another process (or, over TCP, another host) behind the SAME engine
surface the router already schedules on.

Pieces:

* **Frame protocol** — length-prefixed frames on a stream socket, one
  byte of frame kind (``J`` json control / ``B`` bundle bytes) + 8-byte
  LE length + payload.  A request is a json op frame, optionally
  followed by one bundle frame; the reply mirrors that.  Bundle
  payloads are raw :func:`~.kv_transfer.bundle_to_bytes` output — the
  per-page CRC32s ride inside, and the receiving side ALWAYS re-runs
  ``bundle_from_bytes``'s integrity pass, so a torn, truncated, or
  bit-flipped frame is refused with :class:`CorruptBundleError` naming
  the page, and the sender keeps the sequence (the PR 6 contract, now
  across processes).
* **:class:`BundleSender`** — the client side of one connection.  ALL
  socket I/O (connect, send, recv) lives on ONE dedicated sender
  thread; callers enqueue requests on a bounded queue and wait on a
  completion.  That single design choice buys three things: the
  engine/router hot path never touches a blocking socket call (the
  ``socket-hot`` lint rule enforces this shape), sends are async — the
  bounded queue IS the double buffer, bundle N rides the wire while
  N+1 serializes (:func:`pipelined_migrate`) — and connect/send
  failures retry on a BOUNDED, seeded, exponential backoff schedule
  mirroring the ``resilience/`` elastic-agent policy: a dead peer
  costs ``connect_retries`` attempts, never an infinite reconnect
  loop.
* **:class:`RemoteEngineProxy`** — an engine-shaped facade over a
  sender: ``put`` / ``step`` / ``export_sequence`` /
  ``import_sequence`` / ``drain`` / ``abort_all`` /
  ``assert_no_leaks`` … with the same signatures and refusal semantics
  as ``InferenceEngineV2``, so :class:`~.replica.EngineReplica` and
  the router schedule a cross-process replica with ZERO special
  cases.  ``migrate_sequence(local_engine, proxy, uid)`` just works —
  export here, CRC-verified import over there, release only on the
  ACK.
* **:class:`EngineServer` / :func:`spawn_engine_server`** — the child
  process: rebuilds an identical engine from a spec (same model size,
  same ``init_params(PRNGKey(seed))`` weights — weights are never
  shipped), binds the socket, and serves ops until shutdown.

Single-process fleets never open a socket — the transport only
activates when a replica is spawned remote (stand-down matrix in
docs/SERVING.md "Cross-process fleet").
"""

from __future__ import annotations

import dataclasses
import json
import os
import queue as queue_mod
import random
import socket
import tempfile
import threading
import time
import types
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..telemetry import get_registry
from ..telemetry.spans import record_event
from ..utils.logging import logger
from .config import TransportConfig
from .kv_transfer import CorruptBundleError, bundle_from_bytes, \
    bundle_to_bytes

_FRAME_JSON = b"J"
_FRAME_BUNDLE = b"B"
#: frame sanity bound: a tiny-model KV bundle is ~1 MB; 1 GiB means a
#: desynchronized stream, not a real payload
_MAX_FRAME = 1 << 30


class TransportError(RuntimeError):
    """The transport layer failed (connect retries exhausted, peer
    closed mid-frame, desynchronized stream).  Distinct from
    :class:`CorruptBundleError` — which means the bytes ARRIVED but
    failed integrity — so callers can retry transport faults while
    treating corruption as a refusal."""


# ---------------------------------------------------------------- metrics
class _Metrics:
    """``deepspeed_tpu_serving_transport_*`` family (single owner: this
    module; docs/OBSERVABILITY.md catalogs every row)."""

    _instance: Optional["_Metrics"] = None

    def __init__(self) -> None:
        reg = get_registry()
        self.frames_sent = reg.counter(
            "deepspeed_tpu_serving_transport_frames_sent_total",
            "frames written to a transport socket (control + bundle)")
        self.frames_recv = reg.counter(
            "deepspeed_tpu_serving_transport_frames_recv_total",
            "frames read off a transport socket (control + bundle)")
        self.bytes_sent = reg.counter(
            "deepspeed_tpu_serving_transport_bytes_sent_total",
            "payload bytes written to transport sockets")
        self.bytes_recv = reg.counter(
            "deepspeed_tpu_serving_transport_bytes_recv_total",
            "payload bytes read off transport sockets")
        self.connect_attempts = reg.counter(
            "deepspeed_tpu_serving_transport_connect_attempts_total",
            "socket connect attempts (bounded retry/backoff; one "
            "healthy session = one attempt)")
        self.connect_failures = reg.counter(
            "deepspeed_tpu_serving_transport_connect_failures_total",
            "connect attempts that failed and entered backoff")
        self.refused_bundles = reg.counter(
            "deepspeed_tpu_serving_transport_refused_bundles_total",
            "bundle frames refused on arrival (CRC mismatch / torn "
            "frame): the sender keeps the sequence, nothing is lost")
        self.rpc_seconds = reg.histogram(
            "deepspeed_tpu_serving_transport_rpc_seconds",
            "one request->reply round trip over the sender thread "
            "(enqueue to completion)")
        self.inflight = reg.gauge(
            "deepspeed_tpu_serving_transport_inflight_sends",
            "requests queued or on the wire in sender threads (the "
            "double-buffer depth actually in use)")

    @classmethod
    def get(cls) -> "_Metrics":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance


# ---------------------------------------------------------- frame protocol
def send_frame(sock: socket.socket, kind: bytes, payload: bytes) -> None:
    """One length-prefixed frame: kind byte + 8-byte LE length + payload."""
    sock.sendall(kind + len(payload).to_bytes(8, "little") + payload)
    m = _Metrics.get()
    m.frames_sent.inc()
    m.bytes_sent.inc(len(payload))


def recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise TransportError(
                f"peer closed mid-frame ({len(buf)}/{n} bytes arrived)")
        buf += chunk
    return bytes(buf)


def recv_frame(sock: socket.socket) -> Tuple[bytes, bytes]:
    """Read one frame; returns ``(kind, payload)``.  A bad kind byte or
    an absurd length means the stream desynchronized (torn peer) —
    :class:`TransportError`, tear the connection down."""
    head = recv_exact(sock, 9)
    kind = head[:1]
    n = int.from_bytes(head[1:], "little")
    if kind not in (_FRAME_JSON, _FRAME_BUNDLE):
        raise TransportError(f"desynchronized stream: frame kind {kind!r}")
    if n > _MAX_FRAME:
        raise TransportError(f"desynchronized stream: frame length {n}")
    payload = recv_exact(sock, n)
    m = _Metrics.get()
    m.frames_recv.inc()
    m.bytes_recv.inc(len(payload))
    return kind, payload


def _connect(address: Any, timeout: float) -> socket.socket:
    if isinstance(address, str):
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    else:
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    sock.settimeout(timeout)
    sock.connect(address if isinstance(address, str) else tuple(address))
    return sock


# ------------------------------------------------------------- the sender
class _Pending:
    """Completion handle for one in-flight request (the double-buffer
    token :func:`pipelined_migrate` overlaps on)."""

    __slots__ = ("_event", "reply", "blob", "error", "_t0")

    def __init__(self) -> None:
        self._event = threading.Event()
        self.reply: Optional[Dict[str, Any]] = None
        self.blob: Optional[bytes] = None
        self.error: Optional[BaseException] = None
        self._t0 = time.perf_counter()

    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None
             ) -> Tuple[Dict[str, Any], Optional[bytes]]:
        if not self._event.wait(timeout):
            raise TransportError("request timed out awaiting its reply")
        _Metrics.get().rpc_seconds.observe(time.perf_counter() - self._t0)
        if self.error is not None:
            raise self.error
        assert self.reply is not None
        return self.reply, self.blob

    def _resolve(self, reply=None, blob=None, error=None) -> None:
        self.reply, self.blob, self.error = reply, blob, error
        self._event.set()


class BundleSender:
    """Client side of one transport connection; ALL socket I/O on one
    sender thread (see module docstring for why).  ``sleep`` is
    injectable so tests assert the bounded backoff schedule without
    waiting it out."""

    def __init__(self, address: Any,
                 config: Optional[TransportConfig] = None, *,
                 seed: int = 0,
                 sleep: Callable[[float], None] = time.sleep):
        self.address = address
        self.config = config or TransportConfig()
        self._rand = random.Random(seed)
        self._sleep = sleep
        self._q: "queue_mod.Queue" = queue_mod.Queue(
            maxsize=self.config.sender_depth)
        self._sock: Optional[socket.socket] = None
        self._closed = False
        #: lifetime connect attempts (tests assert boundedness)
        self.connect_attempts = 0
        self.backoffs_taken: List[float] = []
        self._thread = threading.Thread(
            target=self._run, name="dstpu-transport-sender", daemon=True)
        self._thread.start()

    # -- public API (any thread) -------------------------------------------
    def request(self, op: Dict[str, Any], payload: Optional[bytes] = None,
                timeout: Optional[float] = None
                ) -> Tuple[Dict[str, Any], Optional[bytes]]:
        """Blocking request->reply round trip."""
        return self.request_async(op, payload).wait(timeout)

    def request_async(self, op: Dict[str, Any],
                      payload: Optional[bytes] = None) -> _Pending:
        """Enqueue and return immediately — the completion handle is
        the async double-buffer token: sequence N's bundle rides the
        wire (or waits its turn in the bounded queue) while the caller
        prepares N+1."""
        if self._closed:
            raise TransportError("sender is closed")
        pending = _Pending()
        _Metrics.get().inflight.inc()
        self._q.put((op, payload, pending))
        return pending

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._q.put(None)
        self._thread.join(timeout=10.0)
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    # -- sender thread ------------------------------------------------------
    def _backoff_delay(self, failures: int) -> float:
        """Elastic-agent schedule: exponential, capped, seeded jitter."""
        cfg = self.config
        delay = min(cfg.backoff_base_s * (2 ** max(0, failures - 1)),
                    cfg.backoff_max_s)
        return delay * (1.0 + cfg.backoff_jitter * self._rand.random())

    def _ensure_connected(self) -> socket.socket:
        if self._sock is not None:
            return self._sock
        m = _Metrics.get()
        self.connect_attempts += 1
        m.connect_attempts.inc()
        sock = _connect(self.address, self.config.io_timeout_s)
        self._sock = sock
        record_event("transport_connect", cat="serve",
                     address=str(self.address),
                     attempts=self.connect_attempts)
        return sock

    def _teardown(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _run(self) -> None:
        m = _Metrics.get()
        while True:
            item = self._q.get()
            if item is None:
                return
            op, payload, pending = item
            failures = 0
            while True:
                try:
                    sock = self._ensure_connected()
                    frame = dict(op)
                    frame["bundle_follows"] = payload is not None
                    send_frame(sock, _FRAME_JSON,
                               json.dumps(frame).encode())
                    if payload is not None:
                        send_frame(sock, _FRAME_BUNDLE, payload)
                    kind, data = recv_frame(sock)
                    if kind != _FRAME_JSON:
                        raise TransportError(
                            "desynchronized stream: reply must open with "
                            "a control frame")
                    reply = json.loads(data.decode())
                    blob = None
                    if reply.get("bundle_follows"):
                        kind, blob = recv_frame(sock)
                        if kind != _FRAME_BUNDLE:
                            raise TransportError(
                                "desynchronized stream: flagged bundle "
                                "frame missing")
                    m.inflight.dec()
                    pending._resolve(reply=reply, blob=blob)
                    break
                except (OSError, TransportError) as e:
                    # transport fault: tear down, bounded backoff, retry
                    # the WHOLE request (strict request->reply framing
                    # means a torn exchange left no partial state worth
                    # resuming)
                    self._teardown()
                    failures += 1
                    m.connect_failures.inc()
                    if failures >= self.config.connect_retries:
                        m.inflight.dec()
                        pending._resolve(error=TransportError(
                            f"transport to {self.address!r} failed after "
                            f"{failures} bounded attempts: {e}"))
                        break
                    delay = self._backoff_delay(failures)
                    self.backoffs_taken.append(delay)
                    self._sleep(delay)


# -------------------------------------------------------- the engine proxy
class _RemoteAllocator:
    """Pool-occupancy view of the remote engine (what ``load()`` /
    ``kv_free_fraction()`` / admission's ``estimate_pages`` read)."""

    def __init__(self, proxy: "RemoteEngineProxy"):
        self._proxy = proxy

    @property
    def free_pages(self) -> int:
        return int(self._proxy._stats()["free_pages"])

    @property
    def num_pages(self) -> int:
        return int(self._proxy._stats()["num_pages"])


class RemoteEngineProxy:
    """Engine-shaped facade over a :class:`BundleSender` — the router
    and :class:`~.replica.EngineReplica` schedule a cross-process
    replica through this with zero special cases.  Refusal semantics
    mirror the engine exactly: ``RejectedError`` re-raises with its
    reason/retry hint, a corrupt bundle raises
    :class:`CorruptBundleError` naming the page, ``import_sequence``
    returns False on capacity (never loses the source), and
    ``assert_no_leaks`` re-raises the remote ``AssertionError``."""

    def __init__(self, address: Any,
                 config: Optional[TransportConfig] = None, *,
                 seed: int = 0,
                 sleep: Callable[[float], None] = time.sleep):
        self._sender = BundleSender(address, config, seed=seed, sleep=sleep)
        self.trace_owner = "remote"  # EngineReplica re-stamps this
        self.kv_tier = None  # host tier lives in the REMOTE process
        hello, _ = self._sender.request({"op": "hello"})
        self._check(hello)
        self.block = types.SimpleNamespace(
            page_size=int(hello["page_size"]))
        self.max_seq_len = int(hello["max_seq_len"])
        self.allocator = _RemoteAllocator(self)
        self._stats_cache: Optional[Dict[str, Any]] = None

    # -- plumbing -----------------------------------------------------------
    @staticmethod
    def _check(reply: Dict[str, Any]) -> Dict[str, Any]:
        err = reply.get("err")
        if err is None:
            return reply
        msg = reply.get("msg", "")
        if err == "rejected":
            from .admission import RejectedError

            raise RejectedError(reply.get("reason", "remote"),
                                retry_after_s=float(
                                    reply.get("retry_after_s", 1.0)),
                                priority=reply.get("priority"))
        if err == "corrupt":
            _Metrics.get().refused_bundles.inc()
            raise CorruptBundleError(msg)
        if err == "value":
            raise ValueError(msg)
        if err == "key":
            raise KeyError(msg)
        if err == "assert":
            raise AssertionError(msg)
        raise RuntimeError(f"remote engine error: {msg}")

    def _rpc(self, op: Dict[str, Any], payload: Optional[bytes] = None
             ) -> Tuple[Dict[str, Any], Optional[bytes]]:
        reply, blob = self._sender.request(op, payload)
        self._stats_cache = None  # any op can change remote load
        return self._check(reply), blob

    def _stats(self) -> Dict[str, Any]:
        # one RPC serves the queue_depth/active_count/allocator reads a
        # single router pump makes back to back
        if self._stats_cache is None:
            reply, _ = self._sender.request({"op": "stats"})
            self._stats_cache = self._check(reply)
        return self._stats_cache

    # -- the engine surface -------------------------------------------------
    def put(self, request: Any, *, record_shed: bool = True) -> int:
        reply, _ = self._rpc({
            "op": "put", "record_shed": bool(record_shed),
            "request": {
                "prompt_ids": list(map(int, request.prompt_ids)),
                "max_new_tokens": request.max_new_tokens,
                "temperature": request.temperature,
                "eos_id": request.eos_id, "uid": request.uid,
                "priority": request.priority,
                "deadline_s": request.deadline_s,
                "trace_id": request.trace_id}})
        return int(reply["uid"])

    def has_work(self) -> bool:
        return bool(self._stats()["has_work"])

    @property
    def queue_depth(self) -> int:
        return int(self._stats()["queue_depth"])

    @property
    def active_count(self) -> int:
        return int(self._stats()["active_count"])

    def inflight_uids(self) -> List[int]:
        return [int(u) for u in self._stats()["inflight_uids"]]

    def ready_uids(self) -> List[int]:
        reply, _ = self._rpc({"op": "ready_uids"})
        return [int(u) for u in reply["uids"]]

    def step(self) -> Dict[int, Dict[str, Any]]:
        reply, _ = self._rpc({"op": "step"})
        return {int(u): r for u, r in reply["out"].items()}

    def export_sequence(self, uid: int) -> Any:
        """Pull one sequence across the wire.  The bundle frame is the
        serialized DSTPUKV2 record; ``bundle_from_bytes`` HERE re-runs
        the full integrity pass — the receiving side of the wire always
        re-verifies the CRCs, whichever direction the bundle flows."""
        reply, blob = self._rpc({"op": "export", "uid": int(uid)})
        if blob is None:
            raise TransportError("export reply carried no bundle frame")
        bundle = bundle_from_bytes(blob)
        record_event("transport_export", cat="serve", uid=int(uid),
                     nbytes=len(blob),
                     **({} if bundle.trace is None else
                        {"trace_id": bundle.trace.get("trace_id")}))
        return bundle

    def import_sequence(self, bundle: Any) -> bool:
        """Push one sequence across the wire (blocking).  Serialization
        happens here; the server side re-verifies every page CRC before
        adopting — a refused import leaves the remote engine untouched
        and this side still owns the sequence."""
        return self.import_commit(self.import_begin(bundle))

    def import_begin(self, bundle: Any) -> _Pending:
        """Async half of the double-buffered handoff: serialize and
        enqueue, return immediately.  The caller overlaps the next
        sequence's export/prefill with this one's wire time, then
        reaps the ACK via :meth:`import_commit`."""
        blob = bundle_to_bytes(bundle)
        pending = self._sender.request_async({"op": "import"}, blob)
        record_event("transport_import_begin", cat="serve",
                     uid=bundle.uid, nbytes=len(blob),
                     **({} if bundle.trace is None else
                        {"trace_id": bundle.trace.get("trace_id")}))
        return pending

    def import_commit(self, pending: _Pending,
                      timeout: Optional[float] = None) -> bool:
        reply, _ = pending.wait(timeout)
        self._stats_cache = None
        return bool(self._check(reply)["ok"])

    def release_sequence(self, uid: int, reason: str = "migrated") -> None:
        self._rpc({"op": "release", "uid": int(uid), "reason": reason})

    def abort_all(self, reason: str = "abort") -> List[int]:
        reply, _ = self._rpc({"op": "abort_all", "reason": reason})
        return [int(u) for u in reply["uids"]]

    def drain(self, max_steps: int = 10_000) -> Dict[str, Any]:
        reply, _ = self._rpc({"op": "drain", "max_steps": int(max_steps)})
        fin = {int(u): types.SimpleNamespace(**s)
               for u, s in reply["finished"].items()}
        pend = [types.SimpleNamespace(**s) for s in reply["pending"]]
        return {"finished": fin, "pending": pend}

    def assert_no_leaks(self) -> None:
        self._rpc({"op": "assert_no_leaks"})

    def close(self) -> None:
        """Close the REMOTE engine and shut the server loop down, then
        the local sender."""
        try:
            self._rpc({"op": "shutdown"})
        except (TransportError, RuntimeError):
            pass  # peer already gone — that is what shutdown wants
        self._sender.close()


def pipelined_migrate(src_engine: Any, proxy: RemoteEngineProxy,
                      uids: List[int]) -> int:
    """Stream several sequences to a remote engine with the double
    buffer engaged: while sequence N's bundle rides the wire, N+1 is
    exported (the prefill→decode handoff of N overlaps the prefill of
    N+1 — the reason the sender is async at all).  Each source
    sequence is released ONLY on its individual ACK, so a refused or
    torn import of any one sequence loses nothing.  Returns how many
    sequences moved."""
    inflight: List[Tuple[int, Any, _Pending]] = []
    moved = 0

    def _reap(entry) -> int:
        uid, bundle, pending = entry
        try:
            ok = proxy.import_commit(pending)
        except (CorruptBundleError, TransportError, ValueError) as e:
            logger.warning(f"pipelined_migrate: uid {uid} refused "
                           f"({e}); sequence stays on the source")
            return 0
        if not ok:
            return 0
        src_engine.release_sequence(uid, reason="migrated")
        record_event("transport_handoff", cat="serve", uid=uid,
                     pages=bundle.n_pages,
                     **({} if bundle.trace is None else
                        {"trace_id": bundle.trace.get("trace_id")}))
        return bundle.n_pages

    for uid in uids:
        bundle = src_engine.export_sequence(uid)
        inflight.append((uid, bundle, proxy.import_begin(bundle)))
        # reap ACKs behind the double-buffer horizon so at most
        # sender_depth bundles are in flight and releases stay ordered
        while len(inflight) >= max(1, proxy._sender.config.sender_depth):
            moved += 1 if _reap(inflight.pop(0)) else 0
    while inflight:
        moved += 1 if _reap(inflight.pop(0)) else 0
    return moved


# ------------------------------------------------------------- the server
class EngineServer:
    """Receiver side: owns an engine and serves ops off one connection.
    ALL socket I/O stays on the thread running :meth:`serve` — the
    receiver thread, never an engine step root (the engine only steps
    when a ``step`` frame asks it to)."""

    def __init__(self, engine: Any, listener: socket.socket):
        self.engine = engine
        self.listener = listener

    def serve(self) -> None:
        conn, _ = self.listener.accept()
        try:
            self._serve_conn(conn)
        finally:
            try:
                conn.close()
            finally:
                self.listener.close()

    def _serve_conn(self, conn: socket.socket) -> None:
        while True:
            try:
                kind, data = recv_frame(conn)
            except TransportError:
                return  # peer went away: the engine outlives the wire
            if kind != _FRAME_JSON:
                return  # desynchronized: nothing sane to reply
            op = json.loads(data.decode())
            blob = None
            if op.get("bundle_follows"):
                _, blob = recv_frame(conn)
            try:
                reply, out_blob = self._dispatch(op, blob)
            except Exception as e:  # noqa: BLE001 — every engine error
                # must cross the wire typed, not kill the server
                reply, out_blob = self._error_reply(e), None
            reply["bundle_follows"] = out_blob is not None
            send_frame(conn, _FRAME_JSON, json.dumps(reply).encode())
            if out_blob is not None:
                send_frame(conn, _FRAME_BUNDLE, out_blob)
            if op.get("op") == "shutdown":
                return

    @staticmethod
    def _error_reply(e: BaseException) -> Dict[str, Any]:
        from .admission import RejectedError

        if isinstance(e, RejectedError):
            return {"err": "rejected", "reason": e.reason,
                    "retry_after_s": e.retry_after_s,
                    "priority": e.priority, "msg": str(e)}
        if isinstance(e, CorruptBundleError):
            _Metrics.get().refused_bundles.inc()
            return {"err": "corrupt", "msg": str(e)}
        if isinstance(e, ValueError):
            return {"err": "value", "msg": str(e)}
        if isinstance(e, KeyError):
            return {"err": "key", "msg": str(e)}
        if isinstance(e, AssertionError):
            return {"err": "assert", "msg": str(e)}
        logger.error(f"EngineServer: op failed: {e!r}")
        return {"err": "runtime", "msg": f"{type(e).__name__}: {e}"}

    def _dispatch(self, op: Dict[str, Any], blob: Optional[bytes]
                  ) -> Tuple[Dict[str, Any], Optional[bytes]]:
        eng = self.engine
        name = op.get("op")
        if name == "hello":
            return {"page_size": eng.block.page_size,
                    "max_seq_len": eng.max_seq_len}, None
        if name == "stats":
            return {"queue_depth": eng.queue_depth,
                    "active_count": eng.active_count,
                    "has_work": eng.has_work(),
                    "inflight_uids": eng.inflight_uids(),
                    "free_pages": eng.allocator.free_pages,
                    "num_pages": eng.allocator.num_pages}, None
        if name == "put":
            from ..inference.v2 import RaggedRequest

            r = op["request"]
            uid = eng.put(RaggedRequest(
                prompt_ids=list(r["prompt_ids"]),
                max_new_tokens=r["max_new_tokens"],
                temperature=r["temperature"], eos_id=r["eos_id"],
                uid=r["uid"], priority=r["priority"],
                deadline_s=r["deadline_s"], trace_id=r["trace_id"]),
                record_shed=bool(op.get("record_shed", True)))
            return {"uid": uid}, None
        if name == "step":
            out = eng.step()
            return {"out": {str(u): r for u, r in out.items()}}, None
        if name == "ready_uids":
            return {"uids": eng.ready_uids()}, None
        if name == "export":
            bundle = eng.export_sequence(op["uid"])
            return {"ok": True}, bundle_to_bytes(bundle)
        if name == "import":
            if blob is None:
                raise ValueError("import op arrived without its bundle "
                                 "frame")
            # the receiving side ALWAYS re-verifies: per-page CRCs, the
            # trace block's own CRC, and the deadline transit clamp all
            # run here, before anything is adopted
            bundle = bundle_from_bytes(blob)
            return {"ok": eng.import_sequence(bundle)}, None
        if name == "release":
            eng.release_sequence(op["uid"],
                                 reason=op.get("reason", "migrated"))
            return {"ok": True}, None
        if name == "abort_all":
            return {"uids": eng.abort_all(op.get("reason", "abort"))}, None
        if name == "drain":
            res = eng.drain(op.get("max_steps", 10_000))
            ser = lambda s: {  # noqa: E731
                "uid": s.uid, "tokens": list(map(int, s.tokens)),
                "prompt_len": s.prompt_len,
                "finish_reason": getattr(s, "finish_reason", None)}
            return {"finished": {str(u): ser(s)
                                 for u, s in res["finished"].items()},
                    "pending": [ser(s) for s in res["pending"]]}, None
        if name == "assert_no_leaks":
            eng.assert_no_leaks()
            return {"ok": True}, None
        if name == "shutdown":
            eng.close()
            return {"ok": True}, None
        raise ValueError(f"unknown transport op {name!r}")


def _server_main(spec: Dict[str, Any], address: str) -> None:
    """Child-process entry: rebuild an identical engine from the spec
    (weights re-derived from ``init_params(PRNGKey(seed))`` — never
    shipped), bind, serve.  Top-level so ``spawn`` can import it."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    from ..inference.v2 import InferenceEngineV2, RaggedInferenceConfig
    from ..models.llama import llama_model

    model = llama_model(spec.get("model", "tiny"),
                        max_seq_len=spec.get("max_seq_len", 128))
    params = model.init_params(jax.random.PRNGKey(spec.get("seed", 0)))
    cfg = RaggedInferenceConfig.from_dict(spec.get("engine_config") or {})
    engine = InferenceEngineV2(model, cfg, params=params)
    listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    listener.bind(address)
    listener.listen(1)
    EngineServer(engine, listener).serve()


def spawn_engine_server(spec: Dict[str, Any], *,
                        address: Optional[str] = None,
                        wait_for_socket_s: float = 180.0
                        ) -> Tuple[Any, str]:
    """Spawn a child-process engine replica; returns ``(process,
    address)`` once the child's listener is bound.  Always the
    ``spawn`` start method — a forked JAX runtime is undefined
    behavior.  The child binds its socket only AFTER its engine is
    built, so the bounded wait here doubles as the ready handshake
    (cold JAX import + engine construction can take tens of seconds on
    a busy box); the transport's own bounded backoff then covers only
    genuine transport faults."""
    import multiprocessing

    cfg = spec.get("engine_config")
    if cfg is not None and dataclasses.is_dataclass(cfg):
        spec = dict(spec)
        spec["engine_config"] = cfg.to_dict() if hasattr(cfg, "to_dict") \
            else dataclasses.asdict(cfg)
    if address is None:
        address = os.path.join(
            tempfile.mkdtemp(prefix="dstpu_transport_"), "engine.sock")
    ctx = multiprocessing.get_context("spawn")
    proc = ctx.Process(target=_server_main, args=(spec, address),
                       daemon=True)
    proc.start()
    deadline = time.monotonic() + wait_for_socket_s
    while not os.path.exists(address):
        if proc.exitcode is not None:
            raise TransportError(
                f"engine server child died during startup "
                f"(exitcode {proc.exitcode})")
        if time.monotonic() > deadline:
            proc.terminate()
            raise TransportError(
                f"engine server gave no socket within "
                f"{wait_for_socket_s:.0f}s")
        time.sleep(0.05)
    return proc, address


__all__ = ["TransportError", "BundleSender", "RemoteEngineProxy",
           "EngineServer", "pipelined_migrate", "spawn_engine_server",
           "send_frame", "recv_frame", "recv_exact"]
