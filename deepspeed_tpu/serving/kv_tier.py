"""Tiered KV cache: host-RAM spill & restore of cold prefix pages.

The device prefix cache (PR 1, ``inference/v2/ragged.py``) turns shared
prompt prefixes into page-table lookups — until the distinct-prefix
working set outgrows the pool slice spared for cached KV and the LRU
starts evicting pages that will be needed again.  This module adds the
hierarchical-memory move the reference framework applies to training
state (ZeRO-Offload/Infinity: host RAM as the second tier): a
:class:`HostKVTier` captures pages on prefix-cache LRU eviction into a
**byte-budgeted host LRU** keyed by the PR 1 content-hash chain keys,
and the engine restores them — CRC-verified, bit-identical — when a
later request's prefix walks past the device hit.

State machine of one cached page::

    device (LRU-parked) --evict+capture--> spilling (ref-pinned)
        --D2H commit--> host (byte-budgeted LRU)
        --prefix walk hits--> restoring (H2D scatter)
        --register+park--> device (LRU-parked)

Contracts:

* **One serialization path** — capture uses ``model_runner.
  paged_gather_pages``'s exact-dtype page layout and stamps
  ``kv_transfer.page_crcs`` (the wire format's checksum rule); restore
  recomputes the CRC and REFUSES mismatches loudly (corrupt page
  dropped, counter bumped, the chain treated as a miss) — device state
  loses nothing on refusal, the engine simply prefills the suffix.
* **Pool dtype** — under ``kv_quant`` the tier stores int8 codes +
  fp32 scales directly (no dequant round trip, ~4x more pages per host
  byte); restore is bit-identical to a never-evicted page.
* **Async, off the hot path** — eviction only *queues* a capture
  (bounded by ``kv_tier.spill_inflight``; the page is pinned via
  refcount so eviction never races a live reader), the D2H copies
  drain in ONE batched gather at the next step boundary, and restores
  for queued-but-not-admitted requests prefetch while the current
  batch decodes.

The engine side (capture hook, drain, restore, prefetch) lives in
``inference/v2/engine_v2.py``; this module owns the host LRU, the
integrity rule, and the ``deepspeed_tpu_serving_kv_tier_*`` metric
family (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import hashlib
import os
import tempfile
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..telemetry import get_registry
from ..telemetry.spans import record_event
from ..utils.logging import logger
from .config import KVTierConfig  # noqa: F401  (re-export: the block's home)
from .kv_transfer import (CorruptBundleError, bundle_from_bytes,
                          bundle_to_bytes, page_crcs, pages_from_bytes,
                          pages_to_bytes)


class NVMeKVTier:
    """File-backed third tier under the host LRU: pages evicted from
    host RAM demote to one DSTPUKV2 page record per file (the wire
    format's exact serialization — :func:`~.kv_transfer.pages_to_bytes`
    — so the on-disk layout, dtype carriage, and per-page CRC rule are
    the SAME as the cross-process wire; the reference framework's
    swap_tensor/AIO tier is the blueprint).  A host miss consults the
    files: read, CRC-verified, promoted back — bit-identical or refused
    loudly.  Byte-budgeted LRU over file sizes; writes are atomic
    (tmp + rename) so a torn write can never be half-read as a page.

    Whole bundles can also sit spilled (:meth:`spill_bundle` /
    :meth:`restore_bundle`, riding ``bundle_to_bytes`` /
    ``bundle_from_bytes``): restore re-bases ``deadline_left_s``
    through the SAME transit clamp as the wire import
    (``kv_transfer.rebase_deadline_left``) — time spent spilled
    consumes the deadline budget, and clock skew never grants it back.
    """

    def __init__(self, config: Optional[KVTierConfig] = None):
        self.config = config or KVTierConfig(enabled=True, nvme_enabled=True)
        self.dir = self.config.nvme_dir or tempfile.mkdtemp(
            prefix="dstpu_kv_nvme_")
        os.makedirs(self.dir, exist_ok=True)
        self._lru: "OrderedDict[Any, Tuple[str, int]]" = OrderedDict()
        self._bytes = 0
        self.spilled_pages = 0
        self.restored_pages = 0
        self.evicted_pages = 0
        self.corrupt_pages = 0
        self.misses = 0
        self._init_metrics()

    def _init_metrics(self) -> None:
        reg = get_registry()
        self._m_spilled = reg.counter(
            "deepspeed_tpu_serving_kv_nvme_spilled_pages_total",
            "pages demoted from the host LRU to NVMe page files "
            "(DSTPUKV2 records, atomic tmp+rename writes)")
        self._m_restored = reg.counter(
            "deepspeed_tpu_serving_kv_nvme_restored_pages_total",
            "NVMe page files promoted back to the host tier "
            "(CRC-verified on read, bit-identical)")
        self._m_bytes = reg.gauge(
            "deepspeed_tpu_serving_kv_nvme_bytes",
            "bytes of KV page files on disk (byte-budgeted LRU)")
        self._m_evicted = reg.counter(
            "deepspeed_tpu_serving_kv_nvme_evicted_pages_total",
            "page files unlinked from the NVMe LRU to hold the byte "
            "budget (the tier's floor: past it, pages are recomputed)")
        self._m_corrupt = reg.counter(
            "deepspeed_tpu_serving_kv_nvme_corrupt_pages_total",
            "page files refusing restore on CRC mismatch or torn read "
            "(file unlinked; the walk treats the page as a miss)")
        self._m_miss = reg.counter(
            "deepspeed_tpu_serving_kv_nvme_misses_total",
            "restore walks that consulted the NVMe tier for a page it "
            "does not hold")
        self._m_hit_rate = reg.gauge(
            "deepspeed_tpu_serving_kv_nvme_hit_rate",
            "cumulative NVMe promotes / (promotes + NVMe misses)")

    def _publish(self) -> None:
        self._m_bytes.set(self._bytes)
        looked = self.restored_pages + self.misses
        if looked:
            self._m_hit_rate.set(self.restored_pages / looked)

    @staticmethod
    def _key_name(key: Any) -> str:
        if isinstance(key, bytes):
            return key.hex()
        return hashlib.sha256(repr(key).encode()).hexdigest()

    def _path(self, key: Any) -> str:
        return os.path.join(self.dir, self._key_name(key) + ".kvpage")

    def _write_atomic(self, path: str, blob: bytes) -> None:
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)

    @property
    def nvme_bytes(self) -> int:
        return self._bytes

    @property
    def nvme_pages(self) -> int:
        return len(self._lru)

    @property
    def hit_rate(self) -> float:
        looked = self.restored_pages + self.misses
        return self.restored_pages / looked if looked else 0.0

    def has(self, key: Any) -> bool:
        return key in self._lru

    def put(self, key: Any, arrays: Dict[str, np.ndarray]) -> bool:
        """Demote one page to disk (DSTPUKV2 record, atomic write),
        then unlink oldest files past the byte budget.  Returns False —
        nothing written — when the single record exceeds the whole
        budget."""
        blob = pages_to_bytes(arrays, {"tier": "nvme",
                                       "key": self._key_name(key)})
        if len(blob) > self.config.nvme_bytes:
            logger.warning(
                f"kv_nvme: one page record ({len(blob)} B) exceeds the "
                f"NVMe byte budget ({self.config.nvme_bytes} B); dropped")
            return False
        path = self._path(key)
        old = self._lru.pop(key, None)
        if old is not None:
            self._bytes -= old[1]
        self._write_atomic(path, blob)
        self._lru[key] = (path, len(blob))
        self._bytes += len(blob)
        self.spilled_pages += 1
        self._m_spilled.inc()
        while self._bytes > self.config.nvme_bytes:
            _, (p, nb) = self._lru.popitem(last=False)
            self._unlink(p)
            self._bytes -= nb
            self.evicted_pages += 1
            self._m_evicted.inc()
        self._publish()
        record_event("kv_nvme_demote", cat="serve",
                     nvme_pages=self.nvme_pages, nvme_bytes=self._bytes)
        return True

    def get(self, key: Any) -> Optional[Dict[str, np.ndarray]]:
        """CRC-verified read for promotion: the page's arrays
        (bit-identical to what was demoted) or None — on a genuine
        miss (counted), or LOUDLY on a corrupt/torn file, which is
        unlinked so the walk treats the page as a miss (refusal loses
        nothing; the device recomputes the suffix)."""
        entry = self._lru.get(key)
        if entry is None:
            self.misses += 1
            self._m_miss.inc()
            self._publish()
            return None
        path, nb = entry
        try:
            with open(path, "rb") as f:
                blob = f.read()
            arrays, _header = pages_from_bytes(blob)
        except (OSError, CorruptBundleError) as e:
            self._lru.pop(key, None)
            self._bytes -= nb
            self.corrupt_pages += 1
            self._m_corrupt.inc()
            self._unlink(path)
            self._publish()
            logger.error(
                f"kv_nvme: REFUSING promote of page {self._key_name(key)[:16]}"
                f"…: {e}; file dropped — the device recomputes the suffix, "
                "nothing is lost")
            return None
        self._lru.move_to_end(key)
        self.restored_pages += 1
        self._m_restored.inc()
        self._publish()
        record_event("kv_nvme_promote", cat="serve",
                     nvme_pages=self.nvme_pages)
        return arrays

    def pop(self, key: Any) -> None:
        """Drop one entry (promotion to host moved ownership up-tier)."""
        entry = self._lru.pop(key, None)
        if entry is not None:
            self._bytes -= entry[1]
            self._unlink(entry[0])
            self._publish()

    @staticmethod
    def _unlink(path: str) -> None:
        try:
            os.unlink(path)
        except OSError:
            pass

    # -- whole-bundle spill (sequence-level, not page-level) -----------------
    def spill_bundle(self, bundle: Any) -> str:
        """Park a whole exported sequence on disk (``bundle_to_bytes``
        — the serializer stamps ``sent_unix``/``deadline_left_s``, so
        the spilled record carries its SLO identity)."""
        path = os.path.join(self.dir, f"seq_{bundle.uid}.kvbundle")
        self._write_atomic(path, bundle_to_bytes(bundle))
        return path

    def restore_bundle(self, path: str) -> Any:
        """Re-hydrate a spilled sequence.  ``bundle_from_bytes`` runs
        the full wire-import integrity pass (per-page CRCs) AND re-bases
        ``deadline_left_s`` through ``rebase_deadline_left`` — the time
        the bundle sat spilled consumes its deadline budget exactly as
        wire transit would (a page that sat on NVMe gets no free
        deadline).  Raises :class:`CorruptBundleError` naming the page
        on a torn or bit-flipped file."""
        with open(path, "rb") as f:
            return bundle_from_bytes(f.read())

    def stats(self) -> Dict[str, float]:
        return {"nvme_spilled_pages": self.spilled_pages,
                "nvme_restored_pages": self.restored_pages,
                "nvme_pages": self.nvme_pages,
                "nvme_bytes": self._bytes,
                "nvme_evictions": self.evicted_pages,
                "nvme_corrupt_pages": self.corrupt_pages,
                "nvme_misses": self.misses,
                "nvme_hit_rate": self.hit_rate}


class HostKVTier:
    """Byte-budgeted host LRU of spilled KV pages, keyed by the prefix
    cache's content-hash chain keys.

    One entry per page: ``{leaf: np.ndarray[L, 1, page_size, KVH, D]}``
    in the pool's exact dtype (the ``paged_gather_pages`` layout) plus
    the capture-time CRC32.  Pure host state — safe to consult from the
    admission path; the only device work (gather/scatter) stays in the
    engine."""

    def __init__(self, config: Optional[KVTierConfig] = None):
        self.config = config or KVTierConfig(enabled=True)
        self._lru: "OrderedDict[Any, Tuple[Dict[str, np.ndarray], int, int]]" \
            = OrderedDict()  # key -> (arrays, crc, nbytes); oldest first
        self._bytes = 0
        # cumulative counters (mirrored onto the registry family below;
        # these stay the per-tier source of truth for bench/tests)
        self.spilled_pages = 0
        self.restored_pages = 0
        self.host_evictions = 0
        self.corrupt_pages = 0
        self.dropped_spills = 0
        self.hits = 0    # pages served from the host tier (on restore)
        self.misses = 0  # restore walks that ended on a page not held
        #: optional NVMe third tier: host-LRU evictions demote to page
        #: files instead of being dropped, and a host miss consults the
        #: files (promote-on-hit) before declaring a true miss
        self.nvme: Optional[NVMeKVTier] = (
            NVMeKVTier(self.config) if self.config.nvme_enabled else None)
        self._init_metrics()

    # -- telemetry -----------------------------------------------------------
    def _init_metrics(self) -> None:
        reg = get_registry()
        self._m_spilled = reg.counter(
            "deepspeed_tpu_serving_kv_tier_spilled_pages_total",
            "prefix-cache pages captured into the host tier on LRU "
            "eviction (D2H commit counted, not queueing)")
        self._m_restored = reg.counter(
            "deepspeed_tpu_serving_kv_tier_restored_pages_total",
            "host-tier pages restored into the device pool (H2D, "
            "CRC-verified bit-identical)")
        self._m_host_bytes = reg.gauge(
            "deepspeed_tpu_serving_kv_tier_host_bytes",
            "host RAM held by spilled KV pages (byte-budgeted LRU)")
        self._m_hit_rate = reg.gauge(
            "deepspeed_tpu_serving_kv_tier_hit_rate",
            "cumulative restored pages / (restored + restore walks that "
            "missed)")
        self._m_restore_h = reg.histogram(
            "deepspeed_tpu_serving_kv_tier_restore_seconds",
            "one batched host->device restore (H2D scatter + CRC "
            "verification) wall time")
        self._m_host_evict = reg.counter(
            "deepspeed_tpu_serving_kv_tier_host_evicted_pages_total",
            "spilled pages dropped from the host LRU to hold the byte "
            "budget")
        self._m_corrupt = reg.counter(
            "deepspeed_tpu_serving_kv_tier_corrupt_pages_total",
            "host-tier pages refusing restore on CRC mismatch (entry "
            "dropped; the device treats the page as a miss)")
        self._m_dropped = reg.counter(
            "deepspeed_tpu_serving_kv_tier_dropped_spills_total",
            "evictions whose spill was refused: the bounded in-flight "
            "queue was full, or a single page exceeded the whole host "
            "byte budget (the device never blocks on the tier either "
            "way)")

    def _publish(self) -> None:
        self._m_host_bytes.set(self._bytes)
        looked = self.restored_pages + self.misses
        if looked:
            self._m_hit_rate.set(self.restored_pages / looked)

    # -- the host LRU --------------------------------------------------------
    @property
    def host_bytes(self) -> int:
        return self._bytes

    @property
    def host_pages(self) -> int:
        return len(self._lru)

    @property
    def hit_rate(self) -> float:
        looked = self.restored_pages + self.misses
        return self.restored_pages / looked if looked else 0.0

    def has(self, key: Any) -> bool:
        """Membership without touching recency — the prefix walk's
        cheap consult (``PrefixCache.host_extend``).  Consults the NVMe
        tier too (dict membership, no file I/O): a demoted page is
        still a tier hit, it just costs a disk read at restore."""
        if key in self._lru:
            return True
        return self.nvme is not None and self.nvme.has(key)

    def insert(self, key: Any, arrays: Dict[str, np.ndarray],
               crc: int) -> bool:
        """Commit one captured page (the D2H copy already happened —
        ``arrays`` are host arrays in the pool's exact dtype).  Inserts
        at the MRU end, then evicts oldest entries past the byte
        budget.  Returns False — nothing stored — when the single page
        exceeds the whole budget."""
        nbytes = sum(a.nbytes for a in arrays.values())
        if nbytes > self.config.host_bytes:
            self.dropped_spills += 1
            self._m_dropped.inc()
            logger.warning(
                f"kv_tier: one page ({nbytes} B) exceeds the host byte "
                f"budget ({self.config.host_bytes} B); not spilled")
            return False
        old = self._lru.pop(key, None)
        if old is not None:
            self._bytes -= old[2]
        self._lru[key] = (arrays, int(crc) & 0xFFFFFFFF, nbytes)
        self._bytes += nbytes
        self.spilled_pages += 1
        self._m_spilled.inc()
        while self._bytes > self.config.host_bytes:
            k, (arrs, _, nb) = self._lru.popitem(last=False)
            self._bytes -= nb
            self.host_evictions += 1
            self._m_host_evict.inc()
            if self.nvme is not None:
                # demote instead of drop: the page's next stop is a
                # DSTPUKV2 file record, not recomputation
                self.nvme.put(k, arrs)
        self._publish()
        return True

    def get(self, key: Any) -> Optional[Dict[str, np.ndarray]]:
        """CRC-verified fetch for restore: returns the page's arrays
        (recency refreshed) or None — on a genuine miss, or LOUDLY on a
        CRC mismatch, where the corrupt entry is dropped so the walk
        treats the page as a miss and the device prefills the suffix
        instead (refusal loses nothing).  A host miss consults the NVMe
        tier (CRC-verified file read) and promotes a hit back into the
        host LRU — ownership moves up-tier, the file is dropped."""
        entry = self._lru.get(key)
        if entry is None:
            if self.nvme is None:
                return None
            arrays = self.nvme.get(key)
            if arrays is None:
                return None
            # promote: the page re-enters the host LRU at the MRU end
            # with its freshly verified CRC (pages_from_bytes already
            # refused any mismatch), and the file goes away
            crc = page_crcs(arrays, sorted(arrays))[0]
            self.nvme.pop(key)
            nbytes = sum(a.nbytes for a in arrays.values())
            if nbytes <= self.config.host_bytes:
                self._lru[key] = (arrays, crc, nbytes)
                self._bytes += nbytes
                while self._bytes > self.config.host_bytes:
                    k, (arrs, _, nb) = self._lru.popitem(last=False)
                    self._bytes -= nb
                    self.host_evictions += 1
                    self._m_host_evict.inc()
                    if k != key:  # never demote the page being served
                        self.nvme.put(k, arrs)
                self._publish()
            return arrays
        arrays, crc, _nbytes = entry
        got = page_crcs(arrays, sorted(arrays))[0]
        if got != crc:
            self._drop_corrupt(key, crc, got)
            return None
        self._lru.move_to_end(key)
        return arrays

    def _drop_corrupt(self, key: Any, want: int, got: int) -> None:
        _arrays, _crc, nb = self._lru.pop(key)
        self._bytes -= nb
        self.corrupt_pages += 1
        self._m_corrupt.inc()
        self._publish()
        kh = key.hex()[:16] if isinstance(key, bytes) else str(key)
        logger.error(
            f"kv_tier: REFUSING restore of page {kh}…: CRC32 {got:#010x} "
            f"!= captured {want:#010x} (host-RAM bit flip or torn copy); "
            "entry dropped — the device recomputes the suffix, nothing "
            "is lost")

    # -- accounting hooks (the engine calls these; trace events live
    # here so the kv_tier_* event names have a single owner) -----------------
    def note_capture_dropped(self, n: int = 1) -> None:
        """The in-flight spill queue was full: ``n`` evictions were not
        captured (pages recycled as before the tier existed)."""
        self.dropped_spills += n
        self._m_dropped.inc(n)

    def note_spill(self, pages: int, wall_s: float) -> None:
        """One drained spill batch committed ``pages`` D2H copies."""
        record_event("kv_tier_spill", cat="serve", pages=pages,
                     host_pages=self.host_pages, host_bytes=self._bytes,
                     wall_s=round(wall_s, 6))

    def note_restore(self, pages: int, wall_s: float) -> None:
        """One restore batch moved ``pages`` pages H2D."""
        self.restored_pages += pages
        self.hits += pages
        self._m_restored.inc(pages)
        self._m_restore_h.observe(wall_s)
        self._publish()
        record_event("kv_tier_restore", cat="serve", pages=pages,
                     host_pages=self.host_pages, wall_s=round(wall_s, 6))

    def note_miss(self) -> None:
        """A restore walk needed a page the tier does not hold."""
        self.misses += 1
        self._publish()

    def stats(self) -> Dict[str, float]:
        """Cumulative tier counters (bench_serving/--ab-kv-tier and the
        fleet drill machine-check these)."""
        out = {"spilled_pages": self.spilled_pages,
               "restored_pages": self.restored_pages,
               "host_pages": self.host_pages,
               "host_bytes": self._bytes,
               "host_evictions": self.host_evictions,
               "corrupt_pages": self.corrupt_pages,
               "dropped_spills": self.dropped_spills,
               "hit_rate": self.hit_rate}
        if self.nvme is not None:
            out.update(self.nvme.stats())
        return out


def page_slices(arrays: Dict[str, np.ndarray], j: int
                ) -> Dict[str, np.ndarray]:
    """Page ``j``'s own copy out of a ``paged_gather_pages`` batch:
    ``[L, 1, page_size, KVH, D]`` per leaf.  Copies — an entry must own
    its memory, not keep the whole gathered batch alive as a view."""
    return {name: np.ascontiguousarray(a[:, j:j + 1])
            for name, a in arrays.items()}


def batch_page_crcs(arrays: Dict[str, np.ndarray]) -> List[int]:
    """Per-page CRC32s of a gathered batch — literally the wire
    format's :func:`~.kv_transfer.page_crcs` (one serialization path)."""
    return page_crcs(arrays, sorted(arrays))


__all__ = ["HostKVTier", "NVMeKVTier", "KVTierConfig", "page_slices",
           "batch_page_crcs"]
