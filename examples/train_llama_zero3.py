"""Train a llama model with ZeRO-3 + tensor parallelism on a device mesh.

Runs anywhere:
  # 8-virtual-device CPU mesh
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/train_llama_zero3.py
  # real TPU slice: just run it (mesh axes spread over the chips)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("JAX_PLATFORMS"):
    # honor the env even where a site plugin pre-pinned the platform
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.models.llama import llama_model


def main():
    n_dev = len(jax.devices())
    model = llama_model("tiny" if n_dev <= 8 else "160m", max_seq_len=128)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 3e-3}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 3},
        "mesh": {"model": 2 if n_dev % 2 == 0 else 1, "data": -1},
        "gradient_clipping": 1.0,
        "steps_per_print": 10,
    })
    rng = np.random.RandomState(0)
    dp = engine.topology.dp_world_size
    vocab = model.config.vocab_size

    for step in range(50):
        ids = rng.randint(0, vocab, (2, 2 * dp, 128)).astype(np.int32)
        loss = engine.train_batch({"input_ids": jnp.asarray(ids)})
        if step % 10 == 0:
            print(f"step {step:3d}  loss {float(loss):.4f}")

    engine.save_checkpoint("/tmp/llama_ckpt_example")
    print("checkpoint saved; done")


if __name__ == "__main__":
    main()
