"""Layer-reduction distillation: init a shallow student from a trained
teacher, then fine-tune it with a soft-target KD loss.

Reference flow: ``init_compression`` with a ``layer_reduction`` config
re-initializes the student from configured teacher layers
(compression/compress.py ``student_initialization``); training then mixes
the CE objective with Hinton-style KD against the teacher's logits.

Run (CPU mesh):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/distill_student.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("JAX_PLATFORMS"):
    # a site plugin may have pinned another platform via jax.config; the
    # env var alone does not override it
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.compression.compress import (distillation_loss,
                                                init_compression)
from deepspeed_tpu.models.llama import llama_model
from deepspeed_tpu.models.transformer import (logits_fn, transformer_forward)
from deepspeed_tpu.parallel import mesh as mesh_mod


def main():
    rng = np.random.RandomState(0)
    batch = {"input_ids": jnp.asarray(
        rng.randint(0, 256, (1, 16, 64)).astype(np.int32))}

    # 1. a "trained" teacher (here: a few steps on the toy corpus)
    teacher_model = llama_model("tiny", max_seq_len=64, n_layers=4)
    engine, *_ = deepspeed_tpu.initialize(
        model=teacher_model,
        config={"train_micro_batch_size_per_gpu": 16,
                "optimizer": {"type": "AdamW", "params": {"lr": 3e-3}},
                "bf16": {"enabled": True}})
    for step in range(30):
        loss = engine.train_batch(batch)
    print(f"teacher loss after 30 steps: {float(loss):.4f}")
    teacher = jax.tree_util.tree_map(lambda x: x.astype(jnp.float32),
                                     engine.state.params)

    # 2. student: half the depth, layers 0 and 3 copied from the teacher
    student_model = llama_model("tiny", max_seq_len=64, n_layers=2)
    student0 = student_model.init_params(jax.random.PRNGKey(1))
    kd_config = {"compression_training": {"layer_reduction": {
        "enabled": True, "keep_number_layer": 2, "teacher_layer": [0, 3]}}}
    distilled, _ = init_compression(student0, kd_config,
                                    teacher_params=teacher)

    # 3. fine-tune with CE + KD (teacher logits precomputed per batch)
    t_cfg, s_cfg = teacher_model.config, student_model.config
    t_hidden, _ = transformer_forward(t_cfg, teacher, batch["input_ids"][0])
    t_logits = logits_fn(t_cfg, teacher, t_hidden)

    def kd_loss_fn(params, b, rng_):
        ce = student_model.loss_fn(params, b, rng_)
        s_hidden, _ = transformer_forward(s_cfg, params, b["input_ids"])
        s_logits = logits_fn(s_cfg, params, s_hidden)
        return 0.5 * ce + 0.5 * distillation_loss(s_logits, t_logits,
                                                  temperature=2.0)

    mesh_mod.reset_topology()
    student_engine, *_ = deepspeed_tpu.initialize(
        model=deepspeed_tpu.ModelSpec(lambda rng_: distilled, kd_loss_fn),
        config={"train_micro_batch_size_per_gpu": 16,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "bf16": {"enabled": True}})
    for step in range(20):
        loss = student_engine.train_batch(batch)
    print(f"student KD loss after 20 steps: {float(loss):.4f}")
    b0 = jax.tree_util.tree_map(lambda x: x[0], batch)
    print(f"student CE: {float(student_model.loss_fn(student_engine.state.params, b0, None)):.4f} "
          f"(random-init student would start near ln(256) = 5.55)")


if __name__ == "__main__":
    main()
