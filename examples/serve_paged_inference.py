"""Continuous-batching inference with the paged (ragged) engine.

Three prompts of different lengths run concurrently; pages are reclaimed
as sequences finish.  Add ``kv_quant=True`` for int8 KV pages or
``quant_bits=8`` for weight-only quantization.

  JAX_PLATFORMS=cpu python examples/serve_paged_inference.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("JAX_PLATFORMS"):
    # honor the env even where a site plugin pre-pinned the platform
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
import numpy as np

from deepspeed_tpu.inference.v2 import (InferenceEngineV2,
                                        RaggedInferenceConfig, RaggedRequest)
from deepspeed_tpu.models.llama import llama_model


def main():
    model = llama_model("tiny", max_seq_len=256)
    engine = InferenceEngineV2(model, RaggedInferenceConfig(
        dtype="fp32", page_size=16, num_pages=64, max_seqs=4,
        max_pages_per_seq=8, kv_quant=False))

    rng = np.random.RandomState(0)
    prompts = [list(rng.randint(0, model.config.vocab_size, n))
               for n in (7, 19, 33)]
    uids = [engine.put(RaggedRequest(prompt_ids=p, max_new_tokens=12))
            for p in prompts]

    # drive the scheduler step by step (a server loop would look like this)
    done = {}
    while engine.has_work():
        for uid, rec in engine.step().items():
            done.setdefault(uid, []).extend(rec["tokens"])
    for uid in uids:
        print(f"request {uid}: {done[uid]}")
    print(f"pages free again: {engine.allocator.free_pages}")


if __name__ == "__main__":
    main()
