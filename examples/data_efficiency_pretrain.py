"""Data-efficiency pretraining: analyzer -> curriculum -> variable batch.

The reference's data-efficiency library end to end (curriculum learning +
data analysis, runtime/data_pipeline):

  1. map-reduce the corpus offline (concurrent workers): per-sample seqlen
     AND an accumulate-type vocab histogram (the two-pass rarity recipe);
  2. train with a curriculum sampler that feeds easy (short) samples first
     and raises the difficulty cap on a schedule;
  3. batch by token budget (variable batch size) so short-sample phases
     pack more rows per step.

    python examples/data_efficiency_pretrain.py
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("JAX_PLATFORMS"):
    # honor the env var even when a site plugin pre-pinned jax_platforms
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.models.gpt2 import gpt2_model
from deepspeed_tpu.runtime.data_pipeline.curriculum import (
    CurriculumConfig, CurriculumScheduler, DeepSpeedDataSampler,
    VariableBatchConfig, batch_by_token_budget)
from deepspeed_tpu.runtime.data_pipeline.data_analyzer import (
    DataAnalyzer, load_difficulties, metric_seqlen, metric_total_vocab_freq,
    metric_vocab_histogram)

VOCAB, MAX_SEQ = 128, 64


def main() -> None:
    rng = np.random.RandomState(0)
    corpus = [{"input_ids": rng.randint(2, VOCAB, size=rng.randint(8, MAX_SEQ))}
              for _ in range(256)]
    workdir = tempfile.mkdtemp()

    # 1) offline analysis: concurrent map-reduce over 4 workers
    out = DataAnalyzer.run_map_reduce(
        corpus, save_path=workdir, num_workers=4,
        metric_names=["seqlen", "vocab"],
        metric_functions=[metric_seqlen, metric_vocab_histogram(VOCAB)],
        metric_types=["single_value_per_sample",
                      "accumulate_value_over_samples"])
    freq = out["vocab"]["accumulated"]
    rarity = metric_total_vocab_freq(freq)  # pass 2 uses the corpus stats
    print(f"analyzed {len(corpus)} samples; "
          f"median len {np.median(out['seqlen']['index_to_metric']):.0f}, "
          f"rarity(sample 0) {rarity(corpus[0]):.1f}")

    # 2) curriculum over the seqlen metric: fixed_root schedule raises the
    # cap from 16 toward MAX_SEQ over 90 steps (snapped to difficulty_step
    # increments: 16,16,16,24,24,... on the first steps)
    sched = CurriculumScheduler(CurriculumConfig(
        min_difficulty=16, max_difficulty=MAX_SEQ, schedule_type="fixed_root",
        total_curriculum_step=90))
    sampler = DeepSpeedDataSampler(
        load_difficulties(workdir, "seqlen"), sched, batch_size=64, seed=1)

    engine, *_ = deepspeed_tpu.initialize(
        model=gpt2_model("tiny", max_seq_len=MAX_SEQ, vocab_size=VOCAB,
                         attn_impl="xla"),
        config={"train_micro_batch_size_per_gpu": 1,  # x dp(8) = 8 rows
                "optimizer": {"type": "AdamW", "params": {"lr": 3e-4}},
                "zero_optimization": {"stage": 1}})

    # 3) variable batch: the token budget decides how MANY rows this
    # curriculum step trains; rows run through the engine in fixed-shape
    # micro-batches of 8 (TPU programs are static — the variable part is
    # the number of micro-steps, the last one padded by repetition).  The
    # per-group LR multipliers are what a variable-LR schedule applies
    # (reference variable_batch_size_and_lr wraps the scheduler); wire
    # them into your optax schedule to scale lr with realized batch size.
    vb = VariableBatchConfig(max_tokens_per_batch=512)
    for step in range(6):
        sampler.set_step(step)
        idx = sampler.next_indices()
        lens = np.asarray([len(corpus[i]["input_ids"]) for i in idx])
        groups, lr_mults = batch_by_token_budget(lens, vb)
        cap = int(sched.get_difficulty(step))
        losses, n_rows = [], 0
        for grp in groups:  # EVERY packed group trains
            rows = [int(idx[j]) for j in grp]
            n_rows += len(rows)
            for lo in range(0, len(rows), 8):
                chunk = rows[lo:lo + 8]
                chunk = (chunk * 8)[:8]  # pad the tail by repetition
                ids = np.zeros((1, 8, cap), np.int32)
                for r, row in enumerate(chunk):
                    seq = corpus[row]["input_ids"][:cap]
                    ids[0, r, :len(seq)] = seq
                losses.append(float(engine.train_batch(
                    {"input_ids": jnp.asarray(ids)})))
        print(f"step {step}: cap {cap:3d}, {n_rows} rows in {len(groups)} "
              f"token-budget groups -> {len(losses)} micro-batches, vblr "
              f"mults {min(lr_mults):.2f}..{max(lr_mults):.2f}, "
              f"mean loss {np.mean(losses):.3f}")


if __name__ == "__main__":
    os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    main()
