"""LoRA fine-tuning: train low-rank adapters over a frozen (optionally
int8-quantized) base through the engine.

The adapters are the only trainable leaves — the ModelSpec's loss closes
over the frozen base, so ZeRO shards and the optimizer update touch the
adapter tree alone (reference OptimizedLinear + LoRAConfig,
deepspeed/linear/).

  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/finetune_lora.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("JAX_PLATFORMS"):
    # honor the env even where a site plugin pre-pinned the platform
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.linear.optimized_linear import (LoRAConfig,
                                                   init_lora_linear,
                                                   lora_linear)


def main():
    rng = jax.random.PRNGKey(0)
    lora = LoRAConfig(lora_r=8, lora_alpha=16)
    in_dim, hidden, out_dim = 32, 64, 8

    # a tiny 2-layer "pretrained" MLP whose linears get LoRA adapters
    k1, k2 = jax.random.split(rng)
    layer1 = init_lora_linear(k1, in_dim, hidden, lora)
    layer2 = init_lora_linear(k2, hidden, out_dim, lora)
    frozen = {"l1": {k: v for k, v in layer1.items() if "lora" not in k},
              "l2": {k: v for k, v in layer2.items() if "lora" not in k}}
    adapters = {"l1": {k: v for k, v in layer1.items() if "lora" in k},
                "l2": {k: v for k, v in layer2.items() if "lora" in k}}

    def loss_fn(trainable, batch, _rng=None):
        x, y = batch
        p1 = {**frozen["l1"], **trainable["l1"]}
        p2 = {**frozen["l2"], **trainable["l2"]}
        h = jax.nn.gelu(lora_linear(p1, x, lora))
        pred = lora_linear(p2, h, lora)
        return jnp.mean((pred - y) ** 2)

    spec = deepspeed_tpu.ModelSpec(init_params=lambda rng: adapters,
                                   loss_fn=loss_fn)
    engine, *_ = deepspeed_tpu.initialize(model=spec, config={
        "train_micro_batch_size_per_gpu": 8,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 1},
    })

    dp = engine.topology.dp_world_size
    data_rng = np.random.RandomState(0)
    target = data_rng.randn(in_dim, out_dim).astype(np.float32)
    x_np = data_rng.randn(1, 8 * dp, in_dim).astype(np.float32)
    x = jnp.asarray(x_np)
    y = jnp.asarray(x_np[0] @ target)[None]
    losses = []
    for step in range(80):
        loss = engine.train_batch((x, y))  # device scalar; no per-step sync
        losses.append(loss)
        if step % 20 == 0:
            print(f"step {step:2d}  adapter-only loss {float(loss):.4f}")
    first, last = float(losses[0]), float(losses[-1])
    assert last < first * 0.5, "LoRA adapters failed to fit the batch"

    n_train = sum(x.size for x in jax.tree_util.tree_leaves(engine.state.params))
    n_total = n_train + sum(x.size for x in jax.tree_util.tree_leaves(frozen))
    print(f"trainable params: {n_train} / {n_total} "
          f"({100 * n_train / n_total:.1f}%) — done")


if __name__ == "__main__":
    main()
