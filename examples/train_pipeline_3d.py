"""3D-parallel training: pipeline x tensor x data on one mesh.

The llama trunk runs as pipeline stages over the 'pipe' axis (1F1B over
ppermute, per-tick remat so activation memory doesn't scale with
micro-batch count), tensor-parallel within each stage over 'model', and
data-parallel over the rest — BASELINE config #1's PipelineEngine flow
composed the TPU way.  Only the pipe and batch axes are manual inside
the pipeline's shard_map; the model axis stays auto, so GSPMD inserts
the tensor-parallel collectives within each stage.  (For stage-count
resharding of generic LayerSpec pipelines — resuming pipe=2 params on a
pipe=4 cluster — see ``PipelineModule.reshard_params``.)

Run on the 8-device CPU mesh:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/train_pipeline_3d.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax

if os.environ.get("JAX_PLATFORMS"):
    # honor the env var even when a site plugin pre-pinned jax_platforms
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.models.llama import llama_config
from deepspeed_tpu.parallel.mesh import MeshConfig, initialize_topology
from deepspeed_tpu.runtime.pipe.engine import pipelined_causal_lm

SEQ = 64


def main():
    initialize_topology(MeshConfig(pipe=2, model=2, data=-1))
    cfg = llama_config("tiny", max_seq_len=SEQ)
    model = pipelined_causal_lm(cfg, num_microbatches=2)

    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        config={
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 1,  # micro-batching is the pipe's
            "optimizer": {"type": "AdamW", "params": {"lr": 3e-3}},
            # fp32 here: bf16 TP all-reduces inside the pipe's manual
            # region trip an XLA CPU-backend AllReducePromotion crash on
            # the virtual mesh; the TPU backend reduces bf16 natively
            "zero_optimization": {"stage": 1},
            "mesh": {"pipe": 2, "model": 2, "data": -1},
        },
        topology=deepspeed_tpu.get_topology(),
    )

    rng = np.random.RandomState(0)
    corpus = rng.randint(0, cfg.vocab_size, (8, 4, SEQ)).astype(np.int32)
    for step in range(40):
        ids = corpus[step % len(corpus)]
        loss = engine.train_batch({"input_ids": jnp.asarray(ids)[None]})
        if step % 10 == 0:
            print(f"step {step:3d}  loss {float(loss):.4f}  "
                  f"lr {engine.get_lr()[0]:.2e}")
    print(f"final loss {float(loss):.4f}")
    assert np.isfinite(float(loss))


if __name__ == "__main__":
    main()
