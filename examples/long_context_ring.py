"""Long-context training with ring-attention context parallelism.

The sequence dim is sharded over the "sequence" mesh axis; K/V blocks
rotate the ring via ppermute while each rank's queries stay resident —
per-rank activation memory is 1/sp of the full sequence.

  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/long_context_ring.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("JAX_PLATFORMS"):
    # honor the env even where a site plugin pre-pinned the platform
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.models.llama import llama_model


def main():
    seq = 512  # global sequence; each of 8 ranks holds 64 tokens
    model = llama_model("tiny", max_seq_len=seq, attn_impl="ring",
                        loss_chunk=73)  # tiled logits-loss: 511 = 7*73
    engine, *_ = deepspeed_tpu.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 3e-3}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "mesh": {"sequence": 8, "data": -1},
    })
    rng = np.random.RandomState(0)
    for step in range(20):
        ids = rng.randint(0, model.config.vocab_size, (1, 1, seq)).astype(np.int32)
        loss = engine.train_batch({"input_ids": jnp.asarray(ids)})
        if step % 5 == 0:
            print(f"step {step:2d}  loss {float(loss):.4f}")
    print("done")


if __name__ == "__main__":
    main()
