"""Serve and fine-tune a published Hugging Face checkpoint.

The reference's flow (init_inference over a downloaded model dir, or
HF Trainer + ds_config for fine-tuning) on this runtime:

    python examples/import_hf_checkpoint.py /path/to/llama-checkpoint

Works with llama / mistral / qwen2 / mixtral / gpt2 directories containing
config.json plus model.safetensors[.index.json] or pytorch_model.bin.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("JAX_PLATFORMS"):
    # honor the env var even when a site plugin pre-pinned jax_platforms
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp
import numpy as np

import deepspeed_tpu


def main(model_dir: str) -> None:
    # --- inference: one call from checkpoint dir to generate -------------
    engine = deepspeed_tpu.init_inference(
        model_dir, {"dtype": "bf16", "replace_with_kernel_inject": True})
    prompt = jnp.asarray(
        np.random.RandomState(0).randint(0, 100, (1, 8)), jnp.int32)
    out = engine.generate(prompt, max_new_tokens=16, temperature=0.8,
                          top_p=0.95)
    print("generated ids:", np.asarray(out)[0, -16:])

    # --- fine-tune the same weights through the training engine ----------
    from deepspeed_tpu.checkpoint.hf_import import load_hf_model
    from deepspeed_tpu.models.llama import llama_model

    cfg, params = load_hf_model(model_dir)  # host-resident numpy tree
    trainer, *_ = deepspeed_tpu.initialize(
        model=llama_model(config=cfg),
        config={
            "train_micro_batch_size_per_gpu": 1,
            "gradient_accumulation_steps": 1,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-5}},
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 2},
        })
    # place the imported weights into the engine's sharded state
    import dataclasses

    shardings = jax.tree_util.tree_map(lambda x: x.sharding,
                                       trainer.state.params)
    dtypes = jax.tree_util.tree_map(lambda x: x.dtype, trainer.state.params)
    host = jax.tree_util.tree_map(lambda a, dt: np.asarray(a).astype(dt),
                                  params, dtypes)
    trainer.state = dataclasses.replace(
        trainer.state, params=jax.device_put(host, shardings))

    ids = jnp.asarray(np.random.RandomState(1).randint(
        0, cfg.vocab_size, (1, 1, 64)), jnp.int32)
    for step in range(3):
        loss = trainer.train_batch({"input_ids": ids})
        print(f"fine-tune step {step}: loss {float(loss):.4f}")


if __name__ == "__main__":
    main(sys.argv[1])
