"""Nightly-tier convergence runs (opt-in: ``pytest -m nightly``).

Kept in its own module so the harness (_run_parity and friends) imports
from test_convergence without inheriting its module-level mark.
"""

import jax
import pytest

from deepspeed_tpu.parallel.mesh import MeshConfig, initialize_topology
from tests.model.test_convergence import _run_parity

# nightly AND slow: the tier-1 CI command selects ``-m 'not slow'``, and
# without the slow mark this 2x200-step ZeRO-3 parity run (engine +
# fp32 control) consumed the entire tier-1 wall budget before any unit
# test got a turn — every run ended at the harness timeout
pytestmark = pytest.mark.slow


@pytest.mark.nightly
def test_llama_zero3_matches_control_scaled(devices8):
    """BASELINE config #4 one notch up from tiny (VERDICT r4 weak #5):
    8 layers x 512 hidden, seq 64, 200 steps, ZeRO-3 over 8 virtual
    chips vs the framework-free fp32 optax control.  Parity evidence at
    a scale where per-layer gathers, remat and bf16 accumulation all do
    real work — not just the tiny fixture shapes."""
    from deepspeed_tpu.models.llama import llama_config, llama_model

    initialize_topology(MeshConfig(data=8), jax.devices()[:8])
    cfg = llama_config("tiny", max_seq_len=64, attn_impl="xla",
                       hidden_size=512, n_layers=8, n_heads=8, n_kv_heads=8,
                       intermediate_size=1376, vocab_size=2048, remat=True)
    e, c = _run_parity(
        llama_model(config=cfg),
        {"train_micro_batch_size_per_gpu": 2,
         "optimizer": {"type": "AdamW",
                       "params": {"lr": 3e-4, "weight_decay": 0.01}},
         "bf16": {"enabled": True},
         "zero_optimization": {"stage": 3},
         "mesh": {"data": 8}},
        n_steps=200, drop=0.5, rtol=0.10, seq=64)
    print("llama zero3 scaled curves:", e[::25], c[::25])
