"""Cross-framework convergence parity (reference ``tests/model/``).

BASELINE.md driver configs reproduced at small scale (VERDICT r3 missing #3):

  #1 CIFAR-10 through the PIPELINE engine (reference
     DeepSpeedExamples/training/cifar + tests/model pipeline parity): a
     conv-free classifier on synthetic CIFAR-shaped data, trained through
     the pipe=2 engine, must land on the SAME loss as a plain-optax control
     training the identical model/params/batches.
  #2 BERT-style masked-LM, ZeRO-1, bf16, 8 virtual chips (reference
     BingBert convergence baseline): the engine's loss curve must track a
     plain-optax fp32 control within tolerance.

The control is deliberately framework-free (raw optax loop) so the test
catches engine-side objective drift: wrong loss scaling/averaging, gradient
corruption across the accumulate/apply boundary, sharding-induced math
changes.  Curves are recorded in docs/CONVERGENCE.md.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import deepspeed_tpu
from deepspeed_tpu.parallel.mesh import MeshConfig, initialize_topology
from deepspeed_tpu.runtime.pipe.module import LayerSpec, PipelineModule

pytestmark = pytest.mark.slow


# ---------------------------------------------------------------------------
# config 1: CIFAR-10 style classifier through the pipeline engine
# ---------------------------------------------------------------------------
IMG = 8 * 8 * 3  # synthetic CIFAR-shaped: 8x8 RGB flattened
NCLS = 10
HID = 32


def _cifar_batches(n_batches, bs, seed=0):
    """Learnable synthetic CIFAR: class prototypes + noise."""
    r = np.random.RandomState(seed)
    protos = r.randn(NCLS, IMG).astype(np.float32)
    out = []
    for _ in range(n_batches):
        y = r.randint(0, NCLS, (bs,))
        x = protos[y] + 0.3 * r.randn(bs, IMG).astype(np.float32)
        out.append((x.astype(np.float32), y.astype(np.int32)))
    return out


def _cifar_layers():
    def lin(key, din, dout, act):
        def init(rng):
            k = jax.random.fold_in(rng, key)
            return {"w": jax.random.normal(k, (din, dout)) * (1.0 / np.sqrt(din)),
                    "b": jnp.zeros((dout,))}

        def apply(p, x):
            y = x @ p["w"] + p["b"]
            return jnp.tanh(y) if act else y

        return LayerSpec(init, apply, name=f"lin{key}")

    return [lin(0, IMG, HID, True), lin(1, HID, HID, True),
            lin(2, HID, HID, True), lin(3, HID, NCLS, False)]


def _xent(logits, y):
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
    return -jnp.mean(jnp.take_along_axis(logp, y[..., None], -1))


def test_cifar_pipeline_matches_plain_optax(devices8):
    initialize_topology(MeshConfig(pipe=2, data=-1), jax.devices()[:8])
    pm = PipelineModule(_cifar_layers(), loss_fn=_xent, num_microbatches=2,
                        partition_method="uniform")
    lr = 3e-3
    engine, *_ = deepspeed_tpu.initialize(
        model=pm.to_model_spec(),
        config={"train_micro_batch_size_per_gpu": 4,
                "optimizer": {"type": "Adam", "params": {"lr": lr}},
                "zero_optimization": {"stage": 0},
                "mesh": {"pipe": 2, "data": -1}},
        topology=deepspeed_tpu.get_topology())

    # plain-optax control: identical starting params, model math, data order
    params_c = jax.tree_util.tree_map(
        lambda x: jnp.asarray(np.asarray(x)), engine.state.params)
    opt = optax.adam(lr)
    opt_state = opt.init(params_c)

    @jax.jit
    def control_step(params, opt_state, x, y):
        loss, g = jax.value_and_grad(
            lambda p: pm._dense_loss(p, x, y))(params)
        updates, opt_state = opt.update(g, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    batches = _cifar_batches(60, 16, seed=3)  # bs = dp(4) x micro_bs(4)
    e_curve, c_curve = [], []
    for x, y in batches:
        e_curve.append(float(engine.train_batch((x[None], y[None]))))
        params_c, opt_state, lc = control_step(params_c, opt_state,
                                               jnp.asarray(x), jnp.asarray(y))
        c_curve.append(float(lc))

    assert e_curve[-1] < 0.5 * e_curve[0], e_curve[::10]
    assert c_curve[-1] < 0.5 * c_curve[0], c_curve[::10]
    # the pipeline is an execution schedule, not a different objective:
    # final losses must agree tightly
    np.testing.assert_allclose(e_curve[-1], c_curve[-1], rtol=0.02, atol=1e-3)


# ---------------------------------------------------------------------------
# config 2: BERT masked-LM, ZeRO-1, bf16, 8 virtual chips
# ---------------------------------------------------------------------------
BSEQ = 16
BVOCAB = 64


def _mlm_batches(n_batches, bs, cfg, seed=0):
    """Small memorizable corpus with 15% masking (HF -100 convention)."""
    r = np.random.RandomState(seed)
    corpus = r.randint(4, BVOCAB, (8, BSEQ))  # 8 fixed sentences
    out = []
    for _ in range(n_batches):
        rows = r.randint(0, len(corpus), (bs,))
        ids = corpus[rows].copy()
        labels = np.full_like(ids, -100)
        mask = r.rand(bs, BSEQ) < 0.15
        mask[:, 0] = True  # at least one prediction per row
        labels[mask] = ids[mask]
        ids[mask] = 3  # [MASK]
        out.append({"input_ids": ids.astype(np.int32),
                    "labels": labels.astype(np.int32)})
    return out


def test_bert_mlm_zero1_bf16_matches_fp32_control(devices8):
    from deepspeed_tpu.models.bert import bert_config, bert_model, mlm_loss

    initialize_topology(MeshConfig(data=8), jax.devices()[:8])
    cfg = bert_config("tiny", vocab_size=BVOCAB, max_seq_len=BSEQ,
                      attn_impl="xla")
    lr = 1e-3
    engine, *_ = deepspeed_tpu.initialize(
        model=bert_model(config=cfg),
        config={"train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "AdamW",
                              "params": {"lr": lr, "weight_decay": 0.01}},
                "bf16": {"enabled": True},
                "zero_optimization": {"stage": 1},
                "mesh": {"data": 8}},
        topology=deepspeed_tpu.get_topology())

    # fp32 plain-optax control from the engine's own initial params (bf16 ->
    # fp32 widening is exact)
    params_c = jax.tree_util.tree_map(
        lambda x: jnp.asarray(np.asarray(x), jnp.float32),
        engine.state.params)
    opt = optax.adamw(lr, weight_decay=0.01)
    opt_state = opt.init(params_c)

    @jax.jit
    def control_step(params, opt_state, batch):
        loss, g = jax.value_and_grad(
            lambda p: mlm_loss(cfg, p, batch))(params)
        updates, opt_state = opt.update(g, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    batches = _mlm_batches(60, 16, cfg, seed=5)  # bs = dp(8) x micro_bs(2)
    e_curve, c_curve = [], []
    for b in batches:
        eb = {k: jnp.asarray(v)[None] for k, v in b.items()}  # gas dim
        e_curve.append(float(engine.train_batch(eb)))
        cb = {k: jnp.asarray(v) for k, v in b.items()}
        params_c, opt_state, lc = control_step(params_c, opt_state, cb)
        c_curve.append(float(lc))

    assert e_curve[-1] < 0.65 * e_curve[0], e_curve[::10]
    assert c_curve[-1] < 0.65 * c_curve[0], c_curve[::10]
    # bf16 compute vs fp32 control: curves track within 10%
    np.testing.assert_allclose(e_curve[-1], c_curve[-1], rtol=0.10)
    # record for docs/CONVERGENCE.md regeneration
    print("cifar/bert curves:", e_curve[::10], c_curve[::10])

# ---------------------------------------------------------------------------
# configs 3-5: GPT-2 ZeRO-2 + FusedAdam; Llama ZeRO-3; Mixtral ZeRO-3+EP+SP
# ---------------------------------------------------------------------------
LSEQ = 16


def _lm_batches(n_batches, bs, vocab, seed=0, seq=None):
    """Memorizable causal-LM corpus: 8 fixed sentences, resampled rows."""
    r = np.random.RandomState(seed)
    corpus = r.randint(1, vocab, (8, seq or LSEQ))
    return [{"input_ids": corpus[r.randint(0, len(corpus), (bs,))]
             .astype(np.int32)} for _ in range(n_batches)]


def _run_parity(model, ds_config, n_steps=60, bs=16, gas=1, seed=7,
                drop=0.65, rtol=0.10, control_model=None, seq=None):
    """Engine curve vs a framework-free fp32 optax control on identical
    params/data; returns both curves.  ``control_model`` swaps the loss
    the control differentiates (e.g. dense attention vs Ulysses)."""
    control_model = control_model or model
    lr = ds_config["optimizer"]["params"]["lr"]
    wd = ds_config["optimizer"]["params"].get("weight_decay", 0.0)
    engine, *_ = deepspeed_tpu.initialize(
        model=model, config=ds_config, topology=deepspeed_tpu.get_topology())
    params_c = jax.tree_util.tree_map(
        lambda x: jnp.asarray(np.asarray(x), jnp.float32),
        engine.state.params)
    opt = optax.adamw(lr, weight_decay=wd)
    opt_state = opt.init(params_c)

    @jax.jit
    def control_step(params, opt_state, batch):
        loss, g = jax.value_and_grad(
            lambda p: control_model.loss_fn(p, batch, None))(params)
        updates, opt_state = opt.update(g, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    vocab = model.config.vocab_size
    seq = seq or LSEQ
    batches = _lm_batches(n_steps, bs, vocab, seed=seed, seq=seq)
    e_curve, c_curve = [], []
    for b in batches:
        ids = b["input_ids"]
        eb = {"input_ids": jnp.asarray(ids).reshape(gas, bs // gas, seq)}
        e_curve.append(float(engine.train_batch(eb)))
        # the control applies ONE update on the same total batch: average
        # of micro-batch grads == grad of the full batch (linear loss avg)
        params_c, opt_state, lc = control_step(
            params_c, opt_state, {"input_ids": jnp.asarray(ids)})
        c_curve.append(float(lc))
    assert e_curve[-1] < drop * e_curve[0], e_curve[::10]
    assert c_curve[-1] < drop * c_curve[0], c_curve[::10]
    np.testing.assert_allclose(e_curve[-1], c_curve[-1], rtol=rtol)
    return e_curve, c_curve


def test_gpt2_zero2_fused_adam_matches_control(devices8):
    """BASELINE config #3 (GPT-2 + ds_config, ZeRO-2 + FusedAdam) at tiny
    scale: grad partitioning + gas accumulation must not change the math."""
    from deepspeed_tpu.models.gpt2 import gpt2_config, gpt2_model

    initialize_topology(MeshConfig(data=8), jax.devices()[:8])
    cfg = gpt2_config("tiny", max_seq_len=LSEQ, attn_impl="xla")
    e, c = _run_parity(
        gpt2_model(config=cfg),
        {"train_micro_batch_size_per_gpu": 1,
         "gradient_accumulation_steps": 2,
         "optimizer": {"type": "FusedAdam",
                       "params": {"lr": 1e-3, "weight_decay": 0.01}},
         "bf16": {"enabled": True},
         "zero_optimization": {"stage": 2},
         "mesh": {"data": 8}},
        gas=2)
    print("gpt2 zero2 curves:", e[::10], c[::10])


def test_llama_zero3_matches_control(devices8):
    """BASELINE config #4 (Llama ZeRO-3, no offload): param sharding +
    per-layer gathers are an execution detail, not an objective change."""
    from deepspeed_tpu.models.llama import llama_config, llama_model

    initialize_topology(MeshConfig(data=8), jax.devices()[:8])
    cfg = llama_config("tiny", max_seq_len=LSEQ, attn_impl="xla")
    e, c = _run_parity(
        llama_model(config=cfg),
        {"train_micro_batch_size_per_gpu": 2,
         "optimizer": {"type": "AdamW",
                       "params": {"lr": 1e-3, "weight_decay": 0.01}},
         "bf16": {"enabled": True},
         "zero_optimization": {"stage": 3},
         "mesh": {"data": 8}})
    print("llama zero3 curves:", e[::10], c[::10])


def test_mixtral_zero3_ep_sp_matches_control(devices8):
    """BASELINE config #5 (Mixtral ZeRO-3 + expert parallel + Ulysses SP)
    at tiny scale.  The control differentiates a DENSE-ATTENTION (xla)
    variant of the model in a plain-optax loop, so Ulysses-induced
    objective drift is caught; the dropless MoE routing math is shared
    between both sides (its own dense parity lives in test_moe_depth)."""
    from deepspeed_tpu.models.mixtral import mixtral_config, mixtral_model

    initialize_topology(MeshConfig(expert=2, sequence=2, data=-1),
                        jax.devices()[:8])
    cfg = mixtral_config("tiny", max_seq_len=LSEQ, attn_impl="ulysses",
                         moe_drop_tokens=False)
    cfg_dense = mixtral_config("tiny", max_seq_len=LSEQ, attn_impl="xla",
                               moe_drop_tokens=False)
    # batch ranks = repl x data x expert = 4: micro_bs 4 x dp 4 = the 16
    # rows fed per step (the batch triangle must price what actually runs)
    e, c = _run_parity(
        mixtral_model(config=cfg),
        {"train_micro_batch_size_per_gpu": 4,
         "optimizer": {"type": "AdamW",
                       "params": {"lr": 1e-3, "weight_decay": 0.01}},
         "bf16": {"enabled": True},
         "zero_optimization": {"stage": 3},
         "mesh": {"expert": 2, "sequence": 2, "data": -1}},
        rtol=0.15, control_model=mixtral_model(config=cfg_dense))
    print("mixtral zero3+ep+sp curves:", e[::10], c[::10])



def test_llama_hier_quantized_grad_reduce_matches_control(devices8):
    """PR-11 acceptance: the hierarchical + int8 gradient reduce
    (comm/collectives two-hop, int8 inter-slice exchange) trains to the
    same loss as the fp32 control — quantized collectives are a wire
    optimization, not an objective change (EQuARX / ZeRO++ claim,
    seed-matched curves)."""
    from deepspeed_tpu.models.llama import llama_config, llama_model

    initialize_topology(MeshConfig(data=8), jax.devices()[:8])
    cfg = llama_config("tiny", max_seq_len=LSEQ, attn_impl="xla")
    e, c = _run_parity(
        llama_model(config=cfg),
        {"train_micro_batch_size_per_gpu": 2,
         "optimizer": {"type": "AdamW",
                       "params": {"lr": 1e-3, "weight_decay": 0.01}},
         "zero_optimization": {"stage": 1,
                               "zero_hierarchical_grad_reduce": True,
                               "zero_hierarchy_inner": 2,
                               "zero_quantized_gradients": True},
         "mesh": {"data": 8}})
    print("llama hier+int8 curves:", e[::10], c[::10])


def test_error_feedback_compressed_reduce_converges_like_exact(devices8):
    """Error-feedback compressed all-reduce (comm/collectives codec +
    caller-owned residual) vs exact pmean on the same seed-matched SGD
    regression: the EF loss curve must track the exact curve — the
    residual carries what each round's quantization dropped, so the
    long-run descent is unbiased (1-bit-Adam-family claim)."""
    from jax.sharding import PartitionSpec as P

    from deepspeed_tpu.runtime.comm.compressed import compressed_all_reduce
    from deepspeed_tpu.utils.jax_compat import shard_map

    topo = initialize_topology(MeshConfig(data=8), jax.devices()[:8])
    rng = np.random.RandomState(0)
    w_true = rng.randn(24).astype(np.float32)
    # steps x ranks x per-rank batch x dim
    X = rng.randn(80, 8, 4, 24).astype(np.float32)
    y = X @ w_true

    def grad_fn(w, xb, yb):
        err = xb @ w - yb
        return xb.T @ err / xb.shape[0]

    @jax.jit
    def step_exact(w, xb, yb):
        g = jax.vmap(grad_fn, in_axes=(None, 0, 0))(w, xb, yb)
        return w - 0.05 * jnp.mean(g, 0)

    reduce_ef = shard_map(
        lambda g, e: compressed_all_reduce(g, e, "data"),
        check_vma=False, mesh=topo.mesh,
        in_specs=(P("data", None), P("data", None)),
        out_specs=(P("data", None), P("data", None)))

    @jax.jit
    def step_ef(carry, xb, yb):
        w, e = carry
        g = jax.vmap(grad_fn, in_axes=(None, 0, 0))(w, xb, yb)
        red, e = reduce_ef(g, e)
        return w - 0.05 * red[0], e

    w_a = jnp.zeros(24)
    w_b, e_b = jnp.zeros(24), jnp.zeros((8, 24))
    curve_a, curve_b = [], []
    for t in range(X.shape[0]):
        xb, yb = jnp.asarray(X[t]), jnp.asarray(y[t])
        w_a = step_exact(w_a, xb, yb)
        (w_b, e_b) = step_ef((w_b, e_b), xb, yb)
        flat_x, flat_y = xb.reshape(-1, 24), yb.reshape(-1)
        curve_a.append(float(jnp.mean((flat_x @ w_a - flat_y) ** 2)))
        curve_b.append(float(jnp.mean((flat_x @ w_b - flat_y) ** 2)))
    assert curve_a[-1] < 0.1 * curve_a[0]
    assert curve_b[-1] < 0.1 * curve_b[0]
    # seed-matched curves agree within a few percent at the end
    np.testing.assert_allclose(curve_b[-1], curve_a[-1], rtol=0.10)
