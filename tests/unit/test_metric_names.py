"""tools/check_metric_names.py runs as a tier-1 gate: every metric the
package registers is snake_case, deepspeed_tpu_-prefixed, single-owner,
single-type.  Also unit-tests the lint's own detection logic on a
synthetic tree so a silently-broken scanner can't green-light bad names.
"""

import importlib.util
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _load_lint():
    path = os.path.join(REPO, "tools", "check_metric_names.py")
    spec = importlib.util.spec_from_file_location("check_metric_names", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules.setdefault("check_metric_names", mod)
    spec.loader.exec_module(mod)
    return mod


def test_package_metric_names_pass():
    lint = _load_lint()
    errors = lint.check(REPO)
    assert not errors, "\n".join(errors)
    # sanity: the scan actually found the telemetry families (an empty
    # scan passing would be a broken scanner, not a clean package)
    names = set(lint.collect(REPO))
    assert "deepspeed_tpu_train_phase_seconds" in names
    assert "deepspeed_tpu_serving_decode_seconds" in names
    assert "deepspeed_tpu_comm_bytes_total" in names


def test_lint_catches_violations(tmp_path):
    lint = _load_lint()
    pkg = tmp_path / "deepspeed_tpu"
    pkg.mkdir()
    (tmp_path / "tools").mkdir()
    (pkg / "a.py").write_text(
        "reg.counter('deepspeed_tpu_BadCase_total')\n"
        "reg.gauge('deepspeed_tpu_dup')\n")
    (pkg / "b.py").write_text(
        "reg.counter('deepspeed_tpu_dup')\n"  # second site AND other type
        "Counter('deepspeed_tpu_ok_total')\n")
    errors = lint.check(str(tmp_path))
    joined = "\n".join(errors)
    assert "deepspeed_tpu_BadCase_total" in joined
    assert "multiple types" in joined
    assert "2 call sites" in joined
    # the clean constructor-registered name produced no error
    assert "deepspeed_tpu_ok_total'" not in joined


def test_catalog_drift_both_directions(tmp_path):
    """The docs/OBSERVABILITY.md catalog and the code must not drift:
    an undocumented registration fails BY NAME, and a dead catalog row
    (documented, unregistered) fails by name too."""
    lint = _load_lint()
    pkg = tmp_path / "deepspeed_tpu"
    pkg.mkdir()
    (tmp_path / "tools").mkdir()
    docs = tmp_path / "docs"
    docs.mkdir()
    (pkg / "a.py").write_text(
        "reg.counter('deepspeed_tpu_documented_total')\n"
        "reg.counter('deepspeed_tpu_undocumented_total')\n"
        "reg.counter('deepspeed_tpu_combined_hits_total')\n"
        "reg.counter('deepspeed_tpu_combined_misses_total')\n")
    (docs / "OBSERVABILITY.md").write_text(
        "| name | type |\n|---|---|\n"
        "| `deepspeed_tpu_documented_total` | counter |\n"
        "| `deepspeed_tpu_combined_hits_total` / `_misses_total` "
        "| counter |\n"
        "| `deepspeed_tpu_ghost_rows_total` | counter |\n")
    errors = lint.check(str(tmp_path))
    joined = "\n".join(errors)
    assert "deepspeed_tpu_undocumented_total" in joined
    assert "deepspeed_tpu_ghost_rows_total" in joined
    assert "dead catalog row" in joined
    # documented names (including the combined-row suffix expansion)
    # produced no errors
    assert "deepspeed_tpu_documented_total'" not in joined
    assert "deepspeed_tpu_combined_hits_total" not in joined
    assert "deepspeed_tpu_combined_misses_total" not in joined


def test_catalog_checks_skipped_without_doc(tmp_path):
    """Fixture trees without docs/OBSERVABILITY.md (like every other
    test here) must not be forced to carry a catalog."""
    lint = _load_lint()
    pkg = tmp_path / "deepspeed_tpu"
    pkg.mkdir()
    (tmp_path / "tools").mkdir()
    (pkg / "a.py").write_text("reg.counter('deepspeed_tpu_lonely_total')\n")
    assert lint.check(str(tmp_path)) == []


def test_lint_ignores_unrelated_calls(tmp_path):
    lint = _load_lint()
    pkg = tmp_path / "deepspeed_tpu"
    pkg.mkdir()
    (tmp_path / "tools").mkdir()
    (pkg / "a.py").write_text(
        "itertools.count('x')\n"
        "collections.Counter('abc')\n"
        "reg.counter(name_variable)\n")
    assert lint.check(str(tmp_path)) == []
