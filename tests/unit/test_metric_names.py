"""tools/check_metric_names.py runs as a tier-1 gate: every metric the
package registers is snake_case, deepspeed_tpu_-prefixed, single-owner,
single-type.  Also unit-tests the lint's own detection logic on a
synthetic tree so a silently-broken scanner can't green-light bad names.
"""

import importlib.util
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _load_lint():
    path = os.path.join(REPO, "tools", "check_metric_names.py")
    spec = importlib.util.spec_from_file_location("check_metric_names", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules.setdefault("check_metric_names", mod)
    spec.loader.exec_module(mod)
    return mod


def test_package_metric_names_pass():
    lint = _load_lint()
    errors = lint.check(REPO)
    assert not errors, "\n".join(errors)
    # sanity: the scan actually found the telemetry families (an empty
    # scan passing would be a broken scanner, not a clean package)
    names = set(lint.collect(REPO))
    assert "deepspeed_tpu_train_phase_seconds" in names
    assert "deepspeed_tpu_serving_decode_seconds" in names
    assert "deepspeed_tpu_comm_bytes_total" in names


def test_lint_catches_violations(tmp_path):
    lint = _load_lint()
    pkg = tmp_path / "deepspeed_tpu"
    pkg.mkdir()
    (tmp_path / "tools").mkdir()
    (pkg / "a.py").write_text(
        "reg.counter('deepspeed_tpu_BadCase_total')\n"
        "reg.gauge('deepspeed_tpu_dup')\n")
    (pkg / "b.py").write_text(
        "reg.counter('deepspeed_tpu_dup')\n"  # second site AND other type
        "Counter('deepspeed_tpu_ok_total')\n")
    errors = lint.check(str(tmp_path))
    joined = "\n".join(errors)
    assert "deepspeed_tpu_BadCase_total" in joined
    assert "multiple types" in joined
    assert "2 call sites" in joined
    # the clean constructor-registered name produced no error
    assert "deepspeed_tpu_ok_total'" not in joined


def test_catalog_drift_both_directions(tmp_path):
    """The docs/OBSERVABILITY.md catalog and the code must not drift:
    an undocumented registration fails BY NAME, and a dead catalog row
    (documented, unregistered) fails by name too."""
    lint = _load_lint()
    pkg = tmp_path / "deepspeed_tpu"
    pkg.mkdir()
    (tmp_path / "tools").mkdir()
    docs = tmp_path / "docs"
    docs.mkdir()
    (pkg / "a.py").write_text(
        "reg.counter('deepspeed_tpu_documented_total')\n"
        "reg.counter('deepspeed_tpu_undocumented_total')\n"
        "reg.counter('deepspeed_tpu_combined_hits_total')\n"
        "reg.counter('deepspeed_tpu_combined_misses_total')\n")
    (docs / "OBSERVABILITY.md").write_text(
        "| name | type |\n|---|---|\n"
        "| `deepspeed_tpu_documented_total` | counter |\n"
        "| `deepspeed_tpu_combined_hits_total` / `_misses_total` "
        "| counter |\n"
        "| `deepspeed_tpu_ghost_rows_total` | counter |\n")
    errors = lint.check(str(tmp_path))
    joined = "\n".join(errors)
    assert "deepspeed_tpu_undocumented_total" in joined
    assert "deepspeed_tpu_ghost_rows_total" in joined
    assert "dead catalog row" in joined
    # documented names (including the combined-row suffix expansion)
    # produced no errors
    assert "deepspeed_tpu_documented_total'" not in joined
    assert "deepspeed_tpu_combined_hits_total" not in joined
    assert "deepspeed_tpu_combined_misses_total" not in joined


def test_catalog_checks_skipped_without_doc(tmp_path):
    """Fixture trees without docs/OBSERVABILITY.md (like every other
    test here) must not be forced to carry a catalog."""
    lint = _load_lint()
    pkg = tmp_path / "deepspeed_tpu"
    pkg.mkdir()
    (tmp_path / "tools").mkdir()
    (pkg / "a.py").write_text("reg.counter('deepspeed_tpu_lonely_total')\n")
    assert lint.check(str(tmp_path)) == []


def test_reqtrace_family_is_single_owner_by_module(tmp_path):
    """The `deepspeed_tpu_serving_reqtrace_*` family belongs to
    `telemetry/reqtrace.py` alone: a second module minting into the
    family fails by name (it would fork the request-lifecycle
    accounting)."""
    lint = _load_lint()
    pkg = tmp_path / "deepspeed_tpu"
    (pkg / "telemetry").mkdir(parents=True)
    (tmp_path / "tools").mkdir()
    (pkg / "telemetry" / "reqtrace.py").write_text(
        "reg.counter('deepspeed_tpu_serving_reqtrace_requests_total')\n")
    (pkg / "rogue.py").write_text(
        "reg.gauge('deepspeed_tpu_serving_reqtrace_forked_requests')\n")
    errors = lint.check(str(tmp_path))
    joined = "\n".join(errors)
    assert "deepspeed_tpu_serving_reqtrace_forked_requests" in joined
    assert "outside the family owner" in joined
    assert "telemetry" in joined and "reqtrace.py" in joined
    # the legitimate owner's registration produced no error
    assert "deepspeed_tpu_serving_reqtrace_requests_total" not in joined


def test_package_registers_reqtrace_family_in_owner_module():
    """The real tree: all four reqtrace metrics exist and every one is
    registered in the owning module."""
    lint = _load_lint()
    names = lint.collect(REPO)
    family = {n: sites for n, sites in names.items()
              if n.startswith("deepspeed_tpu_serving_reqtrace_")}
    assert set(family) == {
        "deepspeed_tpu_serving_reqtrace_requests_total",
        "deepspeed_tpu_serving_reqtrace_phase_seconds_total",
        "deepspeed_tpu_serving_reqtrace_open_requests",
        "deepspeed_tpu_serving_reqtrace_exemplars_total"}
    owner = os.path.join("deepspeed_tpu", "telemetry", "reqtrace.py")
    for n, sites in family.items():
        assert all(f == owner for f, _ln, _t in sites), (n, sites)


def test_lint_ignores_unrelated_calls(tmp_path):
    lint = _load_lint()
    pkg = tmp_path / "deepspeed_tpu"
    pkg.mkdir()
    (tmp_path / "tools").mkdir()
    (pkg / "a.py").write_text(
        "itertools.count('x')\n"
        "collections.Counter('abc')\n"
        "reg.counter(name_variable)\n")
    assert lint.check(str(tmp_path)) == []


def test_numerics_family_is_single_owner_by_module(tmp_path):
    """The `deepspeed_tpu_train_numerics_*` family belongs to
    `telemetry/numerics.py` alone: a second module minting into the
    family fails by name (it would fork the training-health anomaly
    accounting the sentinel is the sole authority for)."""
    lint = _load_lint()
    pkg = tmp_path / "deepspeed_tpu"
    (pkg / "telemetry").mkdir(parents=True)
    (tmp_path / "tools").mkdir()
    (pkg / "telemetry" / "numerics.py").write_text(
        "reg.counter('deepspeed_tpu_train_numerics_anomalies_total')\n")
    (pkg / "rogue.py").write_text(
        "reg.counter('deepspeed_tpu_train_numerics_forked_total')\n")
    errors = lint.check(str(tmp_path))
    joined = "\n".join(errors)
    assert "deepspeed_tpu_train_numerics_forked_total" in joined
    assert "outside the family owner" in joined
    assert "telemetry" in joined and "numerics.py" in joined
    # the legitimate owner's registration produced no error
    assert "deepspeed_tpu_train_numerics_anomalies_total" not in joined


def test_package_registers_numerics_family_in_owner_module():
    """The real tree: the five numerics-observatory metrics exist and
    every one is registered in the owning module."""
    lint = _load_lint()
    names = lint.collect(REPO)
    family = {n: sites for n, sites in names.items()
              if n.startswith("deepspeed_tpu_train_numerics_")}
    assert set(family) == {
        "deepspeed_tpu_train_numerics_anomalies_total",
        "deepspeed_tpu_train_numerics_boundaries_total",
        "deepspeed_tpu_train_numerics_grad_nonfinite_elems",
        "deepspeed_tpu_train_numerics_grad_norm_median",
        "deepspeed_tpu_train_numerics_divergence_failures_total"}
    owner = os.path.join("deepspeed_tpu", "telemetry", "numerics.py")
    for n, sites in family.items():
        assert all(f == owner for f, _ln, _t in sites), (n, sites)
