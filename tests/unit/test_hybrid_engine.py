"""Hybrid engine tests (reference: tests/hybrid_engine/, runtime/hybrid_engine.py)."""

import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.models.llama import llama_model
from deepspeed_tpu.runtime.hybrid_engine import DeepSpeedHybridEngine

SEQ = 32


def _engine(**hybrid_extra):
    model = llama_model("tiny", max_seq_len=SEQ)
    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 5e-3}},
        "hybrid_engine": {"enabled": True, "max_out_tokens": 8, **hybrid_extra},
    }
    engine, *_ = deepspeed_tpu.initialize(model=model, config=cfg)
    return engine, model


def _batch(seed=0, gas=1, bs=2):
    rng = np.random.RandomState(seed)
    return {"input_ids": jnp.asarray(
        rng.randint(0, 256, (gas, bs, SEQ)), jnp.int32)}


def test_hybrid_engine_selected_by_config():
    engine, _ = _engine()
    assert isinstance(engine, DeepSpeedHybridEngine)


def test_train_then_generate_then_train():
    """The RLHF flip-flop: training steps and generation interleave, and
    generation always sees the live weights."""
    engine, _ = _engine()
    prompt = np.random.RandomState(1).randint(0, 256, (1, 8)).astype(np.int32)

    out1 = np.asarray(engine.generate(prompt, max_new_tokens=4))
    assert out1.shape == (1, 12)
    assert not engine.in_eval  # mode restored after generate

    l0 = float(engine.train_batch(_batch(0)))
    for i in range(5):
        li = float(engine.train_batch(_batch(0)))
    assert li < l0

    out2 = np.asarray(engine.generate(prompt, max_new_tokens=4))
    assert out2.shape == (1, 12)
    # (that generation sees the LIVE weights is asserted structurally in
    # test_generate_uses_updated_weights via leaf identity)


def test_generate_uses_updated_weights():
    engine, model = _engine()
    prompt = np.asarray([[1, 2, 3, 4]], np.int32)
    before = engine.state.params
    engine.generate(prompt, max_new_tokens=2)
    for i in range(8):
        engine.train_batch(_batch(i % 2))
    # params object identity changed across steps; the inference engine must
    # be refreshed on the next generate call
    engine.generate(prompt, max_new_tokens=2)
    ie = engine._inference_engine
    import jax

    t_leaves = jax.tree_util.tree_leaves(engine.state.params)
    i_leaves = jax.tree_util.tree_leaves(ie.params)
    assert all(a is b for a, b in zip(t_leaves, i_leaves))


def test_release_inference_cache():
    engine, _ = _engine(release_inference_cache=True)
    prompt = np.asarray([[5, 6, 7]], np.int32)
    engine.generate(prompt, max_new_tokens=2)
    assert engine._inference_engine is None


def test_eval_train_mode_flip():
    engine, _ = _engine()
    engine.eval()
    assert engine.in_eval
    engine.train()
    assert not engine.in_eval
