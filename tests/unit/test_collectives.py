"""comm/collectives — quantized & hierarchical collective layer (docs/COMM.md).

Tier-1 gates for the compression engine: codec round-trip error bounds,
bit-exactness of the ``compression=None`` paths, error-feedback residual
invariants, hierarchical two-hop correctness, wire-byte accounting (the
comms-logger columns and the ``deepspeed_tpu_comm_compression_*`` family),
and the two adoption sites that must track their exact counterparts —
quantized MoE dispatch and compressed ring attention.  Seed-matched
convergence parity of the hierarchical + int8 engine path rides at the
end (the fast version of the tests/model curve check).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import deepspeed_tpu
import deepspeed_tpu.comm as comm
from deepspeed_tpu.comm.collectives import (CompressionSpec, codec,
                                            compressed, hier_all_reduce)
from deepspeed_tpu.parallel.mesh import (DATA_AXIS, MeshTopology,
                                         initialize_topology)
from deepspeed_tpu.runtime.config import MeshConfig
from deepspeed_tpu.utils.groups import (hierarchy_split, inner_groups,
                                        outer_groups)
from deepspeed_tpu.utils.jax_compat import shard_map


# ------------------------------------------------------------------- codec
def test_codec_int8_roundtrip_error_bound():
    """Per-block int8: reconstruction error <= half a quantization step
    (scale/2 = max|block|/254) everywhere, pad sliced back off."""
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(4, 300).astype(np.float32))  # forces padding
    spec = CompressionSpec("int8", block=128)
    q, s, d = codec.quantize_blockwise(x, spec)
    assert q.dtype == jnp.int8 and q.shape == (4, 384)
    assert s.shape == (4, 3) and d == 300
    back = codec.dequantize_blockwise(q, s, d, jnp.float32)
    assert back.shape == x.shape
    step = np.repeat(np.asarray(s), 128, axis=-1)[:, :300]
    assert np.all(np.abs(np.asarray(back - x)) <= step / 2 + 1e-7)


@pytest.mark.skipif(codec.FP8_DTYPE is None,
                    reason="no float8_e4m3fn on this jax build")
def test_codec_fp8_roundtrip():
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(2, 256).astype(np.float32))
    spec = CompressionSpec("fp8")
    q, s, d = codec.quantize_blockwise(x, spec)
    assert q.dtype == codec.FP8_DTYPE
    back = codec.dequantize_blockwise(q, s, d, jnp.float32)
    # e4m3 keeps ~2 decimal digits within the block's dynamic range
    np.testing.assert_allclose(np.asarray(back), np.asarray(x),
                               atol=float(jnp.max(jnp.abs(x))) * 0.07)


def test_compression_spec_parse_and_validation():
    assert CompressionSpec.parse(None) is None
    assert CompressionSpec.parse("int8") == CompressionSpec("int8")
    spec = CompressionSpec("int8", block=64)
    assert CompressionSpec.parse(spec) is spec
    assert CompressionSpec.parse(
        {"format": "int8", "block": 64}).block == 64
    # the backward-compression flag flows through every config surface
    # that parses spec dicts (ep_a2a_compression / ring_compression /
    # overlap_compression)
    bw = CompressionSpec.parse({"format": "int8", "compress_backward": True})
    assert bw.compress_backward and not CompressionSpec("int8").compress_backward
    with pytest.raises(ValueError, match="format"):
        CompressionSpec("int4")
    with pytest.raises(TypeError):
        CompressionSpec.parse(128)
    # wire accounting helper: int8 codes + one fp32 scale per block
    x = jnp.zeros((2, 256), jnp.float32)
    q, s, _ = codec.quantize_blockwise(x, CompressionSpec("int8"))
    assert codec.logical_bytes(x) == 2 * 256 * 4
    assert codec.wire_bytes(q, s) == 2 * 256 + 2 * 2 * 4


# --------------------------------------------------- compressed verbs (8dev)
def _data_mesh(devices8):
    return MeshTopology(MeshConfig(data=-1), devices8).mesh


def test_compressed_all_reduce_and_error_feedback(devices8):
    mesh = _data_mesh(devices8)
    spec = CompressionSpec("int8", error_feedback=True)

    def body(g, e):
        return compressed.all_reduce(g, "mean", DATA_AXIS, spec, e)

    f = shard_map(body, check_vma=False, mesh=mesh,
                  in_specs=(P(DATA_AXIS, None), P(DATA_AXIS, None)),
                  out_specs=(P(DATA_AXIS, None), P(DATA_AXIS, None)))
    rng = np.random.RandomState(0)
    g = jnp.asarray(rng.randn(8, 400).astype(np.float32))
    out, err = f(g, jnp.zeros_like(g))
    expect = np.mean(np.asarray(g), axis=0)
    np.testing.assert_allclose(np.asarray(out)[0], expect, atol=0.05)
    # residual invariant: error = compensated - qdq(compensated), so
    # feeding it back next round keeps the long-run mean unbiased
    sent = codec.qdq(g, dataclasses.replace(spec, error_feedback=False))
    # the two-hop splits into world slots before quantizing; reproduce that
    per_rank = np.asarray(g)
    got_err = np.asarray(err)
    assert got_err.shape == per_rank.shape
    assert float(np.abs(got_err).max()) < 0.1
    del sent


def test_compressed_reduce_scatter_matches_exact(devices8):
    mesh = _data_mesh(devices8)

    def body(x):
        return compressed.reduce_scatter(x, "sum", DATA_AXIS,
                                         CompressionSpec("int8"),
                                         scatter_dim=0)

    f = shard_map(body, check_vma=False, mesh=mesh, in_specs=P(None, None),
                  out_specs=P(DATA_AXIS, None))
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(8, 256).astype(np.float32))
    out = f(x)
    # every rank contributed the same replicated x: result = 8 * x
    # (each of the 8 quantized partials carries up to half a quant step
    # of error, so the summed bound is 8 * max|x|/254 ~ 0.12)
    np.testing.assert_allclose(np.asarray(out), 8 * np.asarray(x),
                               atol=0.3)


def test_compressed_all_gather_and_all_to_all_roundtrip(devices8):
    mesh = _data_mesh(devices8)
    spec = CompressionSpec("int8")

    def gather_body(x):
        return compressed.all_gather(x, DATA_AXIS, spec, tensor_axis=0)

    f = shard_map(gather_body, check_vma=False, mesh=mesh,
                  in_specs=P(DATA_AXIS, None), out_specs=P(None, None))
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(8, 256).astype(np.float32))
    out = f(x)
    assert out.shape == (8, 256)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x),
                               atol=0.02)

    def a2a_body(x):  # [W, rows, cols] per rank -> exchange dim 0
        y = compressed.all_to_all(x, DATA_AXIS, spec, 0, 0, False)
        return compressed.all_to_all(y, DATA_AXIS, spec, 0, 0, False)

    g = shard_map(a2a_body, check_vma=False, mesh=mesh,
                  in_specs=P(None, DATA_AXIS, None),
                  out_specs=P(None, DATA_AXIS, None))
    x3 = jnp.asarray(rng.randn(8, 8, 256).astype(np.float32))
    round_trip = g(x3)
    # a2a is its own inverse at this layout; two lossy hops => 2 quant steps
    np.testing.assert_allclose(np.asarray(round_trip), np.asarray(x3),
                               atol=0.05)
    # the quantized-dim guard refuses a last-dim exchange
    with pytest.raises(ValueError, match="last"):
        compressed.all_to_all(jnp.zeros((4, 8)), DATA_AXIS, spec, 1, 1)


def test_module_api_bit_exact_when_compression_none(devices8):
    """compression=None must run the EXACT pre-existing lax paths — the
    lossless-off-by-default contract."""
    mesh = _data_mesh(devices8)
    x = jnp.asarray(np.random.RandomState(4).randn(8, 64).astype(np.float32))

    def pair(verb_kwargs):
        def body(x):
            a = comm.all_reduce(x, "sum", DATA_AXIS, **verb_kwargs)
            b = jax.lax.psum(x, DATA_AXIS)
            return a, b

        f = shard_map(body, check_vma=False, mesh=mesh, in_specs=P(DATA_AXIS),
                      out_specs=(P(DATA_AXIS), P(DATA_AXIS)))
        return f(x)

    a, b = pair({})
    assert np.array_equal(np.asarray(a), np.asarray(b))
    a, b = pair({"compression": None})
    assert np.array_equal(np.asarray(a), np.asarray(b))


# ------------------------------------------------------------- hierarchical
def test_hierarchy_split_and_groups():
    assert hierarchy_split(8, 2) == (2, 4)
    assert hierarchy_split(8, 4) == (4, 2)
    assert inner_groups(8, 2) == [[0, 1], [2, 3], [4, 5], [6, 7]]
    assert outer_groups(8, 2) == [[0, 2, 4, 6], [1, 3, 5, 7]]
    # every rank appears exactly once per grouping
    for groups in (inner_groups(8, 4), outer_groups(8, 4)):
        flat = sorted(r for g in groups for r in g)
        assert flat == list(range(8))
    for bad in (1, 3, 8, 16):
        with pytest.raises(ValueError):
            hierarchy_split(8, bad)
    with pytest.raises(ValueError, match="prime"):
        hierarchy_split(7, None)


@pytest.mark.parametrize("inner", [2, 4])
def test_hier_all_reduce_matches_psum(inner, devices8):
    mesh = _data_mesh(devices8)
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.randn(8, 130).astype(np.float32))  # odd: forces pad
    expect = np.mean(np.asarray(x), axis=0)

    for spec, atol in ((None, 1e-5), (CompressionSpec("int8"), 0.05)):
        def body(x):
            return hier_all_reduce(x, "mean", DATA_AXIS, inner, spec)

        f = shard_map(body, check_vma=False, mesh=mesh, in_specs=P(DATA_AXIS),
                      out_specs=P(DATA_AXIS))
        out = f(x)
        np.testing.assert_allclose(np.asarray(out)[0], expect, atol=atol)


# ------------------------------------------------- wire-byte accounting
def test_comms_logger_wire_columns_and_compression_family():
    """The satellite fix: bus-bandwidth math follows WIRE bytes (a
    compressed verb must not overstate achieved bandwidth), and the
    compression family isolates the compressed subset of a series."""
    from deepspeed_tpu.comm.comms_logger import CommsLogger
    from deepspeed_tpu.telemetry.registry import MetricsRegistry

    cl = CommsLogger(enabled=True)
    cl.append("all_reduce", "data", 1000, wire_size_bytes=250)
    cl.append("all_gather", "data", 800)  # exact call, same axis
    cl.append("all_gather", "data", 800, wire_size_bytes=200)  # compressed
    out = cl.log_summary(axis_sizes={"data": 8}, elapsed_s=1.0)
    assert "wire MB" in out and "bus MB" in out

    reg = MetricsRegistry()
    cl.publish(reg, axis_sizes={"data": 8})
    bus = reg.get("deepspeed_tpu_comm_bus_bytes_total")
    # bus follows wire: 250 * 2*(8-1)/8, not 1000 * ...
    assert bus.value(op="all_reduce", axis="data") == pytest.approx(
        250 * 2 * 7 / 8)
    cwire = reg.get("deepspeed_tpu_comm_compression_wire_bytes_total")
    csaved = reg.get("deepspeed_tpu_comm_compression_saved_bytes_total")
    cratio = reg.get("deepspeed_tpu_comm_compression_ratio")
    # only the compressed subset counts: the exact all_gather's 800 logical
    # bytes stay out of the family
    assert cwire.value(op="all_gather", axis="data") == 200
    assert csaved.value(op="all_gather", axis="data") == 600
    assert cratio.value(op="all_gather", axis="data") == pytest.approx(4.0)
    assert cwire.value(op="all_reduce", axis="data") == 250
    # idempotent re-publish: deltas only
    cl.publish(reg, axis_sizes={"data": 8})
    assert cwire.value(op="all_gather", axis="data") == 200


def test_compressed_verbs_report_wire_bytes(devices8):
    mesh = _data_mesh(devices8)
    cl = comm.configure_comms_logger(enabled=True)
    cl.reset()

    def body(x):
        return compressed.all_reduce(x, "mean", DATA_AXIS,
                                     CompressionSpec("int8"))

    f = shard_map(body, check_vma=False, mesh=mesh, in_specs=P(DATA_AXIS, None),
                  out_specs=P(DATA_AXIS, None))
    f(jnp.ones((8, 1024), jnp.float32))
    try:
        comp_logical = sum(r[3] for axes in cl.comms_dict.values()
                           for r in axes.values())
        comp_wire = sum(r[4] for axes in cl.comms_dict.values()
                        for r in axes.values())
        assert comp_wire > 0
        # int8 codes + fp32/128 block scales: ~3.9x under fp32 logical
        assert comp_logical / comp_wire > 3.5
    finally:
        cl.configure(enabled=False)
        cl.reset()


# ------------------------------------------------------- adoption parity
def test_moe_ep_compressed_dispatch_tracks_exact(devices8):
    from deepspeed_tpu.moe.sharded_moe import MoEConfig, moe_ffn

    B, S, H, F, E = 8, 4, 16, 24, 4
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(B, S, H).astype(np.float32))
    gate_w = jnp.asarray(rng.randn(H, E).astype(np.float32) * 0.1)
    experts = {k: jnp.asarray(rng.randn(E, H, F).astype(np.float32) * 0.1)
               for k in ("w_gate", "w_up")}
    experts["w_down"] = jnp.asarray(
        rng.randn(E, F, H).astype(np.float32) * 0.1)

    initialize_topology(MeshConfig(expert=2, data=2), devices8[:4])
    cfg = MoEConfig(num_experts=E, top_k=2, drop_tokens=False)
    out_fp, aux_fp = moe_ffn(x, gate_w, experts, cfg)
    out_q, aux_q = moe_ffn(
        x, gate_w, experts,
        dataclasses.replace(cfg, ep_a2a_compression="int8"))
    # routing metadata is exact, payloads are int8: outputs track closely
    scale = float(np.abs(np.asarray(out_fp)).max())
    np.testing.assert_allclose(np.asarray(out_q), np.asarray(out_fp),
                               atol=0.05 * max(scale, 1.0))
    np.testing.assert_allclose(float(aux_q), float(aux_fp), rtol=1e-3)


def test_ring_attention_compressed_tracks_dense_and_trains(devices8):
    from deepspeed_tpu.models.transformer import xla_attention
    from deepspeed_tpu.sequence.ring_attention import ring_attention

    initialize_topology(MeshConfig(data=1, sequence=8), devices8)
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (2, 64, 4, 16)) for kk in ks)
    ref = xla_attention(q, k, v, True)
    out = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, True, compression="int8"))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=0.05, rtol=0.05)
    # straight-through backward: gradients flow and track the exact ones
    g_ref = jax.grad(lambda q: jnp.sum(xla_attention(q, k, v, True) ** 2))(q)
    g_ring = jax.jit(jax.grad(lambda q: jnp.sum(ring_attention(
        q, k, v, True, compression="int8") ** 2)))(q)
    assert float(jnp.abs(g_ring).max()) > 0
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_ref),
                               atol=0.2, rtol=0.2)


def test_engine_hier_quantized_convergence_parity(devices8):
    """Acceptance gate: hierarchical + int8 ZeRO grad reduce matches the
    plain fp engine's seed-matched loss curve (fast sibling of the
    tests/model curve check)."""
    from deepspeed_tpu.models.llama import llama_model
    from deepspeed_tpu.parallel.mesh import reset_topology

    def run(zero_extra):
        reset_topology()
        model = llama_model("tiny", max_seq_len=32)
        engine, *_ = deepspeed_tpu.initialize(model=model, config={
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 1, **zero_extra}})
        rng = np.random.RandomState(0)
        dp = engine.topology.dp_world_size
        losses = []
        for _ in range(5):
            ids = rng.randint(0, model.config.vocab_size,
                              (1, dp, 32)).astype(np.int32)
            losses.append(float(engine.train_batch(
                {"input_ids": jnp.asarray(ids)})))
        return losses

    base = run({})
    hier_q = run({"zero_hierarchical_grad_reduce": True,
                  "zero_hierarchy_inner": 2,
                  "zero_quantized_gradients": True})
    assert np.allclose(base, hier_q, rtol=5e-3), (base, hier_q)

def test_backward_compression_and_residual_slots(devices8):
    """PR-15 differentiated-verb extension: ``compress_backward``
    quantizes the TRANSPOSED exchange (the fwd-only gap closed for MoE
    a2a / ring rotations), and the ``*_ef`` variants give that backward
    exchange its own error-feedback residual slot — the new residual
    exits as the error input's cotangent (the train-state channel
    contract)."""
    mesh = _data_mesh(devices8)
    rng = np.random.RandomState(7)
    x = jnp.asarray(rng.randn(8, 8, 256).astype(np.float32))

    # default spec: backward is the EXACT transposed exchange
    def grad_of(spec):
        def body(x):
            def loss(v):
                y = compressed.all_to_all(v, DATA_AXIS, spec, 0, 0, False)
                return jnp.sum(jnp.sin(y))

            return jax.grad(loss)(x)

        f = shard_map(body, check_vma=False, mesh=mesh,
                      in_specs=P(None, DATA_AXIS, None),
                      out_specs=P(None, DATA_AXIS, None))
        return np.asarray(f(x))

    g_exact = grad_of(CompressionSpec("int8"))
    g_comp = grad_of(CompressionSpec("int8", compress_backward=True))
    # compressed backward is close to (codec tolerance) but not the
    # bit-exact straight-through backward
    np.testing.assert_allclose(g_comp, g_exact, atol=0.05)
    assert (g_comp != g_exact).any(), \
        "compress_backward changed nothing — the bwd stayed exact"

    # residual slot: grad w.r.t. the error input IS the new residual =
    # compensated cotangent minus what the quantized bwd exchange sent
    def body_ef(x, err):
        def loss(v, e):
            y = compressed.all_to_all_ef(v, e, DATA_AXIS,
                                         CompressionSpec("int8"), 0, 0,
                                         False)
            return jnp.sum(jnp.sin(y))

        return jax.grad(loss, argnums=(0, 1))(x, err)

    f = shard_map(body_ef, check_vma=False, mesh=mesh,
                  in_specs=(P(None, DATA_AXIS, None),
                            P(None, DATA_AXIS, None)),
                  out_specs=(P(None, DATA_AXIS, None),
                             P(None, DATA_AXIS, None)))
    err0 = jnp.zeros_like(x)
    _, new_err = f(x, err0)
    assert np.abs(np.asarray(new_err)).max() > 0, \
        "EF residual never populated"
    # and the residual really compensates: a second round with the carried
    # residual reconstructs the exact cotangent better than round one
    def body_ct(x, err):
        def loss(v, e):
            y = compressed.all_to_all_ef(v, e, DATA_AXIS,
                                         CompressionSpec("int8"), 0, 0,
                                         False)
            return jnp.sum(jnp.sin(y))

        return jax.grad(loss, argnums=(0,))(x, err)[0]

    fc = shard_map(body_ct, check_vma=False, mesh=mesh,
                   in_specs=(P(None, DATA_AXIS, None),
                             P(None, DATA_AXIS, None)),
                   out_specs=P(None, DATA_AXIS, None))
    ct1 = np.asarray(fc(x, err0))
    ct2 = np.asarray(fc(x, new_err))
    # the two rounds differ exactly by the reinjected residual's effect
    assert (ct1 != ct2).any()


def test_ppermute_backward_compression(devices8):
    mesh = _data_mesh(devices8)
    perm = tuple((i, (i + 1) % 8) for i in range(8))
    rng = np.random.RandomState(9)
    x = jnp.asarray(rng.randn(8, 256).astype(np.float32))

    def grad_of(spec):
        def body(x):
            def loss(v):
                return jnp.sum(jnp.sin(
                    compressed.ppermute(v, perm, DATA_AXIS, spec)))

            return jax.grad(loss)(x)

        f = shard_map(body, check_vma=False, mesh=mesh,
                      in_specs=P(DATA_AXIS, None),
                      out_specs=P(DATA_AXIS, None))
        return np.asarray(f(x))

    g_exact = grad_of(CompressionSpec("int8"))
    g_comp = grad_of(CompressionSpec("int8", compress_backward=True))
    np.testing.assert_allclose(g_comp, g_exact, atol=0.05)
    assert (g_comp != g_exact).any()


def test_reduce_scatter_error_feedback(devices8):
    """The EF reduce-scatter (the stage-3 compressed-overlap primitive):
    single-hop, residual = full local payload error, layout-stable."""
    mesh = _data_mesh(devices8)
    spec = CompressionSpec("int8", error_feedback=True)

    def body(x, e):
        out, ne = compressed.reduce_scatter(x, "sum", DATA_AXIS, spec,
                                            scatter_dim=0, error=e[0])
        return out, ne[None]

    f = shard_map(body, check_vma=False, mesh=mesh,
                  in_specs=(P(None, None), P(DATA_AXIS, None, None)),
                  out_specs=(P(DATA_AXIS, None), P(DATA_AXIS, None, None)))
    rng = np.random.RandomState(11)
    x = jnp.asarray(rng.randn(8, 256).astype(np.float32))
    e0 = jnp.zeros((8,) + x.shape, jnp.float32)
    out, ne = f(x, e0)
    np.testing.assert_allclose(np.asarray(out), 8 * np.asarray(x), atol=0.3)
    assert np.abs(np.asarray(ne)).max() > 0
    # residual semantics: payload - qdq(payload) per rank
    q = codec.qdq(x, spec)
    np.testing.assert_allclose(np.asarray(ne)[0],
                               np.asarray(x - q), atol=1e-6)


def test_hier_all_reduce_error_feedback(devices8):
    """hier EF: the residual covers the ONE lossy point (this rank's
    hop-2 quantization of its slot) and reinjection converges the
    repeated reduce of a constant payload toward the exact mean."""
    mesh = _data_mesh(devices8)
    spec = CompressionSpec("int8", error_feedback=True)

    def body(x, e):
        out, ne = hier_all_reduce(
            x, op="mean", axis=DATA_AXIS, inner=2, spec=spec, error=e[0])
        return out[None], ne[None]

    f = shard_map(body, check_vma=False, mesh=mesh,
                  in_specs=(P(DATA_AXIS, None), P(DATA_AXIS, None)),
                  out_specs=(P(DATA_AXIS, None), P(DATA_AXIS, None)))
    rng = np.random.RandomState(13)
    x = jnp.asarray(rng.randn(8, 512).astype(np.float32))
    exact = np.asarray(x).mean(axis=0)
    err = jnp.zeros_like(x)
    history = []
    for _ in range(3):
        out, err = f(x, err)
        history.append(np.abs(np.asarray(out)[0] - exact).mean())
    # mean error with EF must not grow; the compensated rounds stay at
    # or below the first round's quantization error
    assert history[-1] <= history[0] * 1.5, history
    assert np.abs(np.asarray(err)).max() > 0
