"""Random-LTD, PLD, and data-analyzer tests (reference:
tests/unit/runtime/data_efficiency)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.runtime.data_pipeline.data_analyzer import (
    DataAnalyzer, load_difficulties, metric_seqlen, metric_total_vocab_freq)
from deepspeed_tpu.runtime.data_pipeline.data_routing import (
    PLDConfig, ProgressiveLayerDrop, RandomLTDConfig, pld_apply,
    random_ltd_apply, random_ltd_indices)


# ------------------------------------------------------------- random-LTD
def test_ltd_budget_schedule():
    cfg = RandomLTDConfig(enabled=True, start_token_budget=16,
                          schedule_steps=100)
    assert cfg.token_budget(0, 64) == 16
    assert cfg.token_budget(50, 64) == 40
    assert cfg.token_budget(100, 64) == 64
    assert cfg.token_budget(10_000, 64) == 64
    assert RandomLTDConfig(enabled=False).token_budget(0, 64) == 64


def test_ltd_apply_processes_only_kept():
    x = jnp.asarray(np.random.RandomState(0).randn(2, 8, 4), jnp.float32)
    keep = random_ltd_indices(jax.random.PRNGKey(0), 8, 3, 2)
    assert keep.shape == (2, 3)
    assert (np.diff(np.asarray(keep), axis=1) > 0).all()  # sorted, unique

    out = random_ltd_apply(lambda h: h + 100.0, x, keep)
    got = np.asarray(out)
    ref = np.asarray(x)
    for b in range(2):
        kept = set(np.asarray(keep[b]).tolist())
        for s in range(8):
            if s in kept:
                np.testing.assert_allclose(got[b, s], ref[b, s] + 100.0, rtol=1e-6)
            else:
                np.testing.assert_array_equal(got[b, s], ref[b, s])


def test_ltd_jit_fixed_budget():
    x = jnp.zeros((1, 16, 4))
    f = jax.jit(lambda x, k: random_ltd_apply(lambda h: h + 1, x, k))
    keep = random_ltd_indices(jax.random.PRNGKey(1), 16, 4, 1)
    assert f(x, keep).shape == x.shape


# ------------------------------------------------------------------- PLD
def test_pld_theta_schedule():
    pld = ProgressiveLayerDrop(PLDConfig(enabled=True, theta=0.5, gamma=0.01))
    assert pld.get_theta() == 1.0
    t100 = pld.update_state(100)
    t1000 = pld.update_state(1000)
    assert 0.5 < t1000 < t100 < 1.0
    assert abs(pld.update_state(10**6) - 0.5) < 1e-6
    # deeper layers drop more
    pld.update_state(1000)
    assert pld.layer_keep_prob(0, 12) > pld.layer_keep_prob(11, 12)


def test_pld_apply_eval_and_keep1():
    x = jnp.ones((2, 4, 4))
    blk = lambda v: v * 2  # noqa: E731
    out = pld_apply(blk, x, jax.random.PRNGKey(0), keep_prob=0.5, training=False)
    np.testing.assert_allclose(np.asarray(out), 2.0)
    out = pld_apply(blk, x, jax.random.PRNGKey(0), keep_prob=1.0)
    np.testing.assert_allclose(np.asarray(out), 2.0)


def test_pld_apply_expectation():
    x = jnp.ones((1, 2, 2))
    blk = lambda v: v + 1.0  # noqa: E731
    outs = [np.asarray(pld_apply(blk, x, jax.random.PRNGKey(i), keep_prob=0.5))
            for i in range(400)]
    mean = np.mean([o.mean() for o in outs])
    # E[out] = x + keep_prob * (delta/keep_prob) = x + 1
    assert abs(mean - 2.0) < 0.15


# ----------------------------------------------------------- data analyzer
def _dataset(n=20, seed=0):
    rng = np.random.RandomState(seed)
    return [{"input_ids": rng.randint(0, 50, size=rng.randint(4, 30))}
            for _ in range(n)]


def test_analyzer_map_reduce_single_worker(tmp_path):
    ds = _dataset()
    an = DataAnalyzer(ds, save_path=str(tmp_path))
    an.run_map()
    result = an.run_reduce()
    vals = result["seqlen"]["index_to_metric"]
    assert vals.shape == (20,)
    np.testing.assert_allclose(vals, [len(s["input_ids"]) for s in ds])
    order = result["seqlen"]["metric_to_sample"]
    lens = np.asarray([len(ds[i]["input_ids"]) for i in order])
    assert (np.diff(lens) >= 0).all()
    assert load_difficulties(str(tmp_path), "seqlen").shape == (20,)


def test_analyzer_multi_worker_matches_single(tmp_path):
    ds = _dataset(31)
    single = DataAnalyzer(ds, save_path=str(tmp_path / "s"))
    single.run_map()
    want = single.run_reduce()["seqlen"]["index_to_metric"]
    for w in range(3):
        DataAnalyzer(ds, save_path=str(tmp_path / "m"), num_workers=3,
                     worker_id=w).run_map()
    got = DataAnalyzer(ds, save_path=str(tmp_path / "m"),
                       num_workers=3).run_reduce()["seqlen"]["index_to_metric"]
    np.testing.assert_allclose(got, want)


def test_vocab_rarity_metric(tmp_path):
    freq = np.ones(50)
    freq[0] = 1000  # token 0 very common
    fn = metric_total_vocab_freq(freq)
    common = fn({"input_ids": np.zeros(10, np.int64)})
    rare = fn({"input_ids": np.full(10, 7, np.int64)})
    assert rare > common  # rare tokens = harder

    an = DataAnalyzer(_dataset(), metric_names=["rarity"],
                      metric_functions=[fn], save_path=str(tmp_path))
    an.run_map()
    assert an.run_reduce()["rarity"]["index_to_metric"].shape == (20,)


def test_analyzer_missing_shard_raises(tmp_path):
    an = DataAnalyzer(_dataset(), save_path=str(tmp_path), num_workers=2,
                      worker_id=0)
    an.run_map()  # worker 1 never runs
    with pytest.raises(FileNotFoundError):
        an.run_reduce()


def test_analyzer_accumulate_metric_two_pass(tmp_path):
    """Accumulate-type metric (reference accumulate_value_over_samples):
    corpus vocab histogram summed by map-reduce over 3 workers equals the
    direct count, then feeds the rarity metric — the reference's canonical
    two-pass curriculum."""
    from deepspeed_tpu.runtime.data_pipeline.data_analyzer import (
        metric_vocab_histogram)

    ds = _dataset(25)
    for w in range(3):
        DataAnalyzer(ds, metric_names=["vocab"], metric_types=
                     ["accumulate_value_over_samples"],
                     metric_functions=[metric_vocab_histogram(50)],
                     save_path=str(tmp_path), num_workers=3,
                     worker_id=w).run_map()
    out = DataAnalyzer(ds, metric_names=["vocab"], metric_types=
                       ["accumulate_value_over_samples"],
                       metric_functions=[metric_vocab_histogram(50)],
                       save_path=str(tmp_path), num_workers=3).run_reduce()
    freq = out["vocab"]["accumulated"]
    direct = np.zeros(50)
    for s in ds:
        direct += np.bincount(s["input_ids"], minlength=50)
    np.testing.assert_allclose(freq, direct)
    # pass 2: rarity from the accumulated frequency
    rarity = metric_total_vocab_freq(freq)
    assert np.isfinite(rarity(ds[0]))


def test_analyzer_concurrent_driver_matches_single(tmp_path):
    """run_map_reduce runs the per-worker maps concurrently and reduces
    once; output identical to the sequential single-worker path."""
    ds = _dataset(31, seed=4)
    single = DataAnalyzer(ds, save_path=str(tmp_path / "s"))
    single.run_map()
    want = single.run_reduce()["seqlen"]["index_to_metric"]
    got = DataAnalyzer.run_map_reduce(
        ds, save_path=str(tmp_path / "p"), num_workers=4)["seqlen"][
            "index_to_metric"]
    np.testing.assert_allclose(got, want)
