"""Direct tests for LR schedules, timers, flops profiler, env report
(reference tests/unit/runtime/test_lr_schedulers.py, unit/profiling,
unit/monitor; ours were only covered indirectly through the engine)."""

import time

import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.runtime.lr_schedules import (LRSchedulerShim, get_schedule)
from tests.unit.simple_model import random_batch, simple_mlp_spec


# ---------------------------------------------------------------- schedules
def test_warmup_lr_ramps_then_holds():
    s = get_schedule("WarmupLR", {"warmup_min_lr": 0.0, "warmup_max_lr": 0.1,
                                  "warmup_num_steps": 10}, base_lr=0.1)
    assert float(s(0)) == pytest.approx(0.0, abs=1e-6)
    # monotone non-decreasing ramp reaching max at warmup end, then flat
    ramp = [float(s(t)) for t in range(11)]
    assert all(a <= b + 1e-9 for a, b in zip(ramp, ramp[1:]))
    assert float(s(10)) == pytest.approx(0.1, rel=1e-5)
    assert float(s(1000)) == pytest.approx(0.1, rel=1e-5)


def test_warmup_decay_hits_zero_at_total():
    s = get_schedule("WarmupDecayLR",
                     {"total_num_steps": 100, "warmup_max_lr": 0.1,
                      "warmup_num_steps": 10}, base_lr=0.1)
    assert float(s(10)) == pytest.approx(0.1, rel=1e-6)
    assert float(s(100)) == pytest.approx(0.0, abs=1e-6)
    mid = float(s(55))
    assert 0.0 < mid < 0.1


def test_warmup_cosine_shape():
    s = get_schedule("WarmupCosineLR",
                     {"total_num_steps": 100, "warmup_num_steps": 10,
                      "cos_min_ratio": 0.1, "warmup_max_lr": 1.0},
                     base_lr=1.0)
    assert float(s(10)) == pytest.approx(1.0, rel=1e-4)
    assert float(s(100)) == pytest.approx(0.1, rel=1e-2)
    # monotone decreasing after warmup
    vals = [float(s(t)) for t in range(10, 101, 10)]
    assert all(a >= b - 1e-6 for a, b in zip(vals, vals[1:]))


def test_one_cycle_peaks_mid_cycle():
    s = get_schedule("OneCycle", {"cycle_min_lr": 0.01, "cycle_max_lr": 0.1,
                                  "cycle_first_step_size": 50}, base_lr=0.1)
    assert float(s(0)) == pytest.approx(0.01, rel=1e-4)
    assert float(s(50)) == pytest.approx(0.1, rel=1e-4)
    assert float(s(100)) == pytest.approx(0.01, rel=2e-2)


def test_lr_range_test_grows():
    s = get_schedule("LRRangeTest", {"lr_range_test_min_lr": 0.001,
                                     "lr_range_test_step_size": 10,
                                     "lr_range_test_step_rate": 1.0},
                     base_lr=0.001)
    assert float(s(0)) == pytest.approx(0.001, rel=1e-4)
    assert float(s(100)) > float(s(0))


def test_scheduler_shim_api():
    s = get_schedule("WarmupLR", {"warmup_max_lr": 0.1,
                                  "warmup_num_steps": 4}, base_lr=0.1)
    shim = LRSchedulerShim(s)
    for _ in range(4):
        shim.step()
    assert shim.get_last_lr()[0] == pytest.approx(0.1, rel=1e-6)
    sd = shim.state_dict()
    shim2 = LRSchedulerShim(s)
    shim2.load_state_dict(sd)
    assert shim2.get_last_lr() == shim.get_last_lr()


# ------------------------------------------------------------------- timers
def test_wallclock_timer_elapsed():
    from deepspeed_tpu.utils.timer import SynchronizedWallClockTimer

    timers = SynchronizedWallClockTimer()
    t = timers("unit")
    t.start()
    time.sleep(0.02)
    t.stop()
    elapsed = timers("unit").elapsed(reset=False)
    assert elapsed >= 0.01  # seconds


def test_throughput_timer_window_rate():
    from deepspeed_tpu.utils.timer import ThroughputTimer

    tt = ThroughputTimer(batch_size=4, steps_per_output=10**9)
    for _ in range(3):
        tt.start()
        time.sleep(0.005)
        tt.stop()
    assert tt.global_step_count == 3
    assert tt.total_elapsed >= 0.015


# ----------------------------------------------------------- flops profiler
def test_flops_profiler_reports_through_engine():
    engine, *_ = deepspeed_tpu.initialize(
        model=simple_mlp_spec(),
        config={"train_micro_batch_size_per_gpu": 4,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "flops_profiler": {"enabled": True, "profile_step": 1}})
    for i in range(3):
        engine.train_batch(random_batch(batch_size=4, seed=i, gas=1))
    prof = engine.flops_profiler
    assert prof is not None and prof.duration > 0
    assert prof.get_total_params() > 0
    assert prof.get_total_flops() > 0  # XLA cost analysis of the micro step


# --------------------------------------------------------------- env report
def test_env_report_runs():
    from deepspeed_tpu.env_report import main

    assert main([]) == 0


def test_per_module_flops_breakdown():
    """Per-module cost table (reference per-module MACs/params/latency,
    profiling/flops_profiler/profiler.py): rows for embed / per-layer
    attn+mlp / head, component flops summing near the whole forward."""
    import jax

    from deepspeed_tpu.models.llama import llama_config
    from deepspeed_tpu.models.transformer import (causal_lm_loss,
                                                  init_transformer_params)
    from deepspeed_tpu.profiling.flops_profiler import (
        cost_analysis_of, format_module_table, per_module_breakdown)

    cfg = llama_config("tiny", max_seq_len=32, attn_impl="xla")
    params = init_transformer_params(cfg, jax.random.PRNGKey(0))
    rows = per_module_breakdown(cfg, params, batch_size=2, seq_len=32)
    names = [r["module"] for r in rows]
    assert "embed" in names and "lm_head" in names
    assert f"layers.{cfg.n_layers - 1}.attn" in names
    assert f"layers.{cfg.n_layers - 1}.mlp" in names
    # params accounted: per-layer + embed == total (tied head)
    import numpy as np

    from deepspeed_tpu.profiling.flops_profiler import count_params
    assert sum(r["params"] for r in rows) == count_params(params)
    # component flops roughly cover the full forward (loss excluded)
    import jax.numpy as jnp
    ids = jnp.zeros((2, 32), jnp.int32)
    full = cost_analysis_of(jax.jit(
        lambda p, i: causal_lm_loss(cfg, p, i, None)), params, ids)
    covered = sum(r["flops"] for r in rows)
    assert covered > 0.5 * float(full.get("flops", 0.0))
    table = format_module_table(rows)
    assert "module" in table and "layers.0.attn" in table


def test_flops_profiler_prints_module_table(monkeypatch):
    """The engine profiler prints the per-module table at the profile
    step when the model exposes a TransformerConfig."""
    import numpy as np

    import deepspeed_tpu
    import deepspeed_tpu.profiling.flops_profiler as fp
    from deepspeed_tpu.models.llama import llama_model

    model = llama_model("tiny", max_seq_len=16, vocab_size=64, n_layers=2,
                        attn_impl="xla")
    engine, *_ = deepspeed_tpu.initialize(
        model=model,
        config={"train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "flops_profiler": {"enabled": True, "profile_step": 1}})
    lines = []
    monkeypatch.setattr(fp.logger, "info", lambda msg: lines.append(str(msg)))
    import jax.numpy as jnp
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (1, 2, 16)),
                      dtype=jnp.int32)
    for _ in range(3):
        engine.train_batch({"input_ids": ids})
    text = "\n".join(lines)
    assert "per-module profile" in text
    assert "layers.0.attn" in text
