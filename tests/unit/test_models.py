"""Model family tests: train each family end-to-end on the CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import (bert_model, gpt2_model, llama_model,
                                  mixtral_model)

SEQ = 32
BS = 4


def _lm_batch(vocab, seed=0, gas=1, bs=BS):
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, vocab, size=(gas, bs, SEQ)).astype(np.int32)
    return {"input_ids": jnp.asarray(ids)}


def _train(model, cfg_overrides=None, steps=6, vocab=256, batch_fn=_lm_batch):
    config = {
        "train_micro_batch_size_per_gpu": BS,
        "optimizer": {"type": "Adam", "params": {"lr": 5e-3}},
        "bf16": {"enabled": True},
    }
    config.update(cfg_overrides or {})
    engine, *_ = deepspeed_tpu.initialize(model=model, config=config)
    losses = []
    for i in range(steps):
        losses.append(float(engine.train_batch(batch_fn(vocab, seed=0))))
    assert np.isfinite(losses[-1])
    assert losses[-1] < losses[0], f"no learning: {losses}"
    return engine, losses


def test_llama_tiny_trains():
    _train(llama_model("tiny", max_seq_len=SEQ))


def test_llama_gqa_shapes():
    model = llama_model("tiny", max_seq_len=SEQ, n_kv_heads=2)
    _train(model)


def test_gpt2_tiny_trains():
    _train(gpt2_model("tiny"))


def test_bert_tiny_trains():
    def mlm_batch(vocab, seed=0, gas=1):
        rng = np.random.RandomState(seed)
        ids = rng.randint(0, vocab, size=(gas, BS, SEQ)).astype(np.int32)
        labels = np.where(rng.rand(gas, BS, SEQ) < 0.15, ids, -100).astype(np.int32)
        return {"input_ids": jnp.asarray(ids), "labels": jnp.asarray(labels)}

    _train(bert_model("tiny"), batch_fn=mlm_batch)


def test_mixtral_tiny_trains():
    _train(mixtral_model("tiny", max_seq_len=SEQ))


def test_llama_zero3_tp_mesh(devices8):
    """2-way TP x 4-way ZeRO-3: the composition milestone."""
    model = llama_model("tiny", max_seq_len=SEQ)
    engine, _ = _train(model, {"mesh": {"model": 2, "data": -1},
                               "zero_optimization": {"stage": 3}})
    # check a TP-ruled param is sharded over model axis AND a zero axis
    wq = engine.state.params["layers"]["attn"]["wq"]
    flat_axes = [a for s in wq.sharding.spec if s for a in (s if isinstance(s, tuple) else (s,))]
    assert "model" in flat_axes
    assert "data" in flat_axes


def test_mixtral_expert_parallel(devices8):
    model = mixtral_model("tiny", max_seq_len=SEQ)
    engine, _ = _train(model, {"mesh": {"expert": 4, "data": -1},
                               "zero_optimization": {"stage": 2}})
    w = engine.state.params["layers"]["mlp"]["w_up"]
    flat_axes = [a for s in w.sharding.spec if s for a in (s if isinstance(s, tuple) else (s,))]
    assert "expert" in flat_axes


def test_remat_trains():
    _train(llama_model("tiny", max_seq_len=SEQ, remat=True))


def test_unscanned_matches_scanned():
    m1 = llama_model("tiny", max_seq_len=SEQ, scan_layers=True)
    m2 = llama_model("tiny", max_seq_len=SEQ, scan_layers=False)
    rng = jax.random.PRNGKey(0)
    p1 = m1.init_params(rng)
    p2 = m2.init_params(rng)
    batch = jax.tree_util.tree_map(lambda x: x[0], _lm_batch(256))
    l1 = m1.loss_fn(p1, batch, None)
    l2 = m2.loss_fn(p2, batch, None)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
