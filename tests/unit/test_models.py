"""Model family tests: train each family end-to-end on the CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # multi-minute integration tier

import deepspeed_tpu
from deepspeed_tpu.models import (bert_model, gpt2_model, llama_model,
                                  mixtral_model)

SEQ = 32
BS = 4


def _lm_batch(vocab, seed=0, gas=1, bs=BS):
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, vocab, size=(gas, bs, SEQ)).astype(np.int32)
    return {"input_ids": jnp.asarray(ids)}


def _train(model, cfg_overrides=None, steps=6, vocab=256, batch_fn=_lm_batch):
    config = {
        "train_micro_batch_size_per_gpu": BS,
        "optimizer": {"type": "Adam", "params": {"lr": 5e-3}},
        "bf16": {"enabled": True},
    }
    config.update(cfg_overrides or {})
    engine, *_ = deepspeed_tpu.initialize(model=model, config=config)
    losses = []
    for i in range(steps):
        losses.append(float(engine.train_batch(batch_fn(vocab, seed=0))))
    assert np.isfinite(losses[-1])
    assert losses[-1] < losses[0], f"no learning: {losses}"
    return engine, losses


def test_llama_tiny_trains():
    _train(llama_model("tiny", max_seq_len=SEQ))


def test_llama_gqa_shapes():
    model = llama_model("tiny", max_seq_len=SEQ, n_kv_heads=2)
    _train(model)


def test_gpt2_tiny_trains():
    _train(gpt2_model("tiny"))


def test_bert_tiny_trains():
    def mlm_batch(vocab, seed=0, gas=1):
        rng = np.random.RandomState(seed)
        ids = rng.randint(0, vocab, size=(gas, BS, SEQ)).astype(np.int32)
        labels = np.where(rng.rand(gas, BS, SEQ) < 0.15, ids, -100).astype(np.int32)
        return {"input_ids": jnp.asarray(ids), "labels": jnp.asarray(labels)}

    _train(bert_model("tiny"), batch_fn=mlm_batch)


def test_mixtral_tiny_trains():
    _train(mixtral_model("tiny", max_seq_len=SEQ))


def test_llama_zero3_tp_mesh(devices8):
    """2-way TP x 4-way ZeRO-3: the composition milestone."""
    model = llama_model("tiny", max_seq_len=SEQ)
    engine, _ = _train(model, {"mesh": {"model": 2, "data": -1},
                               "zero_optimization": {"stage": 3}})
    # check a TP-ruled param is sharded over model axis AND a zero axis
    wq = engine.state.params["layers"]["attn"]["wq"]
    flat_axes = [a for s in wq.sharding.spec if s for a in (s if isinstance(s, tuple) else (s,))]
    assert "model" in flat_axes
    assert "data" in flat_axes


def test_mixtral_expert_parallel(devices8):
    model = mixtral_model("tiny", max_seq_len=SEQ)
    engine, _ = _train(model, {"mesh": {"expert": 4, "data": -1},
                               "zero_optimization": {"stage": 2}})
    w = engine.state.params["layers"]["mlp"]["w_up"]
    flat_axes = [a for s in w.sharding.spec if s for a in (s if isinstance(s, tuple) else (s,))]
    assert "expert" in flat_axes


def test_remat_trains():
    _train(llama_model("tiny", max_seq_len=SEQ, remat=True))


def test_unscanned_matches_scanned():
    m1 = llama_model("tiny", max_seq_len=SEQ, scan_layers=True)
    m2 = llama_model("tiny", max_seq_len=SEQ, scan_layers=False)
    rng = jax.random.PRNGKey(0)
    p1 = m1.init_params(rng)
    p2 = m2.init_params(rng)
    batch = jax.tree_util.tree_map(lambda x: x[0], _lm_batch(256))
    l1 = m1.loss_fn(p1, batch, None)
    l2 = m2.loss_fn(p2, batch, None)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)


def test_tiled_loss_matches_full():
    m_full = llama_model("tiny", max_seq_len=SEQ, attn_impl="xla")
    m_tiled = llama_model("tiny", max_seq_len=SEQ, attn_impl="xla", loss_chunk=8)
    # SEQ-1=31 not divisible by 8 -> pad seq to 33 so hidden[:, :-1] is 32
    import numpy as np
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 256, (2, 33)), jnp.int32)
    p = m_full.init_params(jax.random.PRNGKey(0))
    l1 = m_full.loss_fn(p, {"input_ids": ids}, None)
    l2 = m_tiled.loss_fn(p, {"input_ids": ids}, None)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
    g1 = jax.grad(lambda p: m_full.loss_fn(p, {"input_ids": ids}, None))(p)
    g2 = jax.grad(lambda p: m_tiled.loss_fn(p, {"input_ids": ids}, None))(p)
    a = jax.tree_util.tree_leaves(g1)
    b = jax.tree_util.tree_leaves(g2)
    for x, y in zip(a, b):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=1e-5, rtol=1e-3)


def test_mics_mesh_and_sharding(devices8):
    import deepspeed_tpu
    model = llama_model("tiny", max_seq_len=SEQ, attn_impl="xla")
    engine, *_ = deepspeed_tpu.initialize(
        model=model,
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 3, "mics_shard_size": 4}})
    topo = engine.topology
    assert topo.axis_size("data") == 4
    assert topo.axis_size("repl") == 2
    # params sharded over data (4-way), replicated over repl
    wq = engine.state.params["layers"]["attn"]["wq"]
    axes = [a for s in wq.sharding.spec if s for a in (s if isinstance(s, tuple) else (s,))]
    assert "data" in axes and "repl" not in axes
    # trains
    ids = np.random.RandomState(0).randint(0, 256, (1, 8, SEQ)).astype(np.int32)
    loss = engine.train_batch({"input_ids": jnp.asarray(ids)})
    assert np.isfinite(float(loss))


def test_flops_per_token_counts_active_experts_only():
    """MFU denominator: a mixtral layer prices top_k experts + router, not
    all experts (total-param pricing would overstate MoE MFU 4x at 8x/top2)."""
    from deepspeed_tpu.models.mixtral import mixtral_config
    from deepspeed_tpu.models.llama import llama_config
    from deepspeed_tpu.models.transformer import flops_per_token

    moe = mixtral_config("8x160m", max_seq_len=1024)
    dense = llama_config("160m", max_seq_len=1024)
    f_moe = flops_per_token(moe, 1024)
    f_dense = flops_per_token(dense, 1024)
    # same trunk; MoE adds (top_k - 1) extra expert MLPs + router per layer
    mlp = moe.hidden_size * moe.ffn_size * 3
    expect_extra = 6.0 * moe.n_layers * (
        (moe.moe_top_k - 1) * mlp + moe.hidden_size * moe.moe_experts)
    np.testing.assert_allclose(f_moe - f_dense, expect_extra, rtol=1e-6)
    # and nowhere near total-expert pricing
    assert f_moe < f_dense + 6.0 * moe.n_layers * 3 * mlp
