"""HF checkpoint import parity (VERDICT r3 missing #6).

Real end-to-end: a Hugging Face model is created with ``transformers``,
saved with ``save_pretrained`` (safetensors AND torch-bin flavors), imported
by ``checkpoint/hf_import.py``, and the runtime's jax forward must
reproduce the HF torch logits — catching name-mapping, transpose, RoPE
convention, and norm-eps drift in one assert.
"""

import json

import numpy as np
import pytest

pytestmark = pytest.mark.slow

jnp = pytest.importorskip("jax.numpy")
transformers = pytest.importorskip("transformers")

import dataclasses  # noqa: E402

import jax  # noqa: E402


def _logits_ours(cfg, params, ids):
    from deepspeed_tpu.models.transformer import logits_fn, transformer_forward

    hidden, _ = transformer_forward(cfg, params, jnp.asarray(ids))
    return np.asarray(logits_fn(cfg, params, hidden), np.float32)


def test_llama_safetensors_parity(tmp_path):
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    hf_cfg = LlamaConfig(
        vocab_size=96, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rms_norm_eps=1e-5, rope_theta=10000.0,
        tie_word_embeddings=False)
    torch.manual_seed(0)
    m = LlamaForCausalLM(hf_cfg).eval()
    m.save_pretrained(tmp_path)  # safetensors by default

    from deepspeed_tpu.checkpoint.hf_import import load_hf_model

    cfg, params = load_hf_model(str(tmp_path), dtype=jnp.float32)
    assert cfg.n_kv_heads == 2 and cfg.n_layers == 2
    cfg.attn_impl = "xla"

    ids = np.random.RandomState(0).randint(0, 96, (2, 12)).astype(np.int32)
    with torch.no_grad():
        want = m(torch.tensor(ids.astype(np.int64))).logits.float().numpy()
    got = _logits_ours(cfg, params, ids)
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-3)


def test_gpt2_torch_bin_parity(tmp_path):
    import torch
    from transformers import GPT2Config, GPT2LMHeadModel

    hf_cfg = GPT2Config(vocab_size=80, n_positions=64, n_embd=32, n_layer=2,
                        n_head=4)
    torch.manual_seed(1)
    m = GPT2LMHeadModel(hf_cfg).eval()
    m.save_pretrained(tmp_path, safe_serialization=False)  # pytorch_model.bin

    from deepspeed_tpu.checkpoint.hf_import import load_hf_model

    cfg, params = load_hf_model(str(tmp_path), dtype=jnp.float32)
    assert cfg.position == "learned" and cfg.norm == "layernorm"
    cfg.attn_impl = "xla"

    ids = np.random.RandomState(1).randint(0, 80, (2, 10)).astype(np.int32)
    with torch.no_grad():
        want = m(torch.tensor(ids.astype(np.int64))).logits.float().numpy()
    got = _logits_ours(cfg, params, ids)
    np.testing.assert_allclose(got, want, atol=5e-4, rtol=5e-3)


def test_mixtral_shape_mapping(tmp_path):
    """Mixtral MoE mapping: expert weights land [L, E, ...]-stacked (the
    full transformers MixtralForCausalLM is too heavy for the unit tier;
    shapes + a synthetic state dict cover the name map)."""
    from deepspeed_tpu.checkpoint.hf_import import (config_from_hf,
                                                    import_hf_params)

    c = {"model_type": "mixtral", "vocab_size": 64, "hidden_size": 16,
         "num_hidden_layers": 2, "num_attention_heads": 4,
         "num_key_value_heads": 2, "intermediate_size": 32,
         "num_local_experts": 4, "num_experts_per_tok": 2,
         "max_position_embeddings": 64}
    cfg = config_from_hf(c)
    assert cfg.moe_experts == 4 and cfg.moe_top_k == 2
    r = np.random.RandomState(0)
    state = {"model.embed_tokens.weight": r.randn(64, 16).astype(np.float32),
             "model.norm.weight": np.ones(16, np.float32),
             "lm_head.weight": r.randn(64, 16).astype(np.float32)}
    for i in range(2):
        pre = f"model.layers.{i}"
        state[f"{pre}.self_attn.q_proj.weight"] = r.randn(16, 16).astype(np.float32)
        state[f"{pre}.self_attn.k_proj.weight"] = r.randn(8, 16).astype(np.float32)
        state[f"{pre}.self_attn.v_proj.weight"] = r.randn(8, 16).astype(np.float32)
        state[f"{pre}.self_attn.o_proj.weight"] = r.randn(16, 16).astype(np.float32)
        state[f"{pre}.input_layernorm.weight"] = np.ones(16, np.float32)
        state[f"{pre}.post_attention_layernorm.weight"] = np.ones(16, np.float32)
        state[f"{pre}.block_sparse_moe.gate.weight"] = r.randn(4, 16).astype(np.float32)
        for e in range(4):
            state[f"{pre}.block_sparse_moe.experts.{e}.w1.weight"] = \
                r.randn(32, 16).astype(np.float32)
            state[f"{pre}.block_sparse_moe.experts.{e}.w2.weight"] = \
                r.randn(16, 32).astype(np.float32)
            state[f"{pre}.block_sparse_moe.experts.{e}.w3.weight"] = \
                r.randn(32, 16).astype(np.float32)
    p = import_hf_params(cfg, state, "mixtral")
    assert p["layers"]["mlp"]["w_gate"].shape == (2, 4, 16, 32)
    assert p["layers"]["mlp"]["w_down"].shape == (2, 4, 32, 16)
    assert p["layers"]["mlp"]["router"].shape == (2, 16, 4)
    # importable by the engine's init contract: same treedef as native init
    from deepspeed_tpu.models.mixtral import mixtral_config
    from deepspeed_tpu.models.transformer import init_transformer_params

    native_cfg = mixtral_config(
        "tiny", max_seq_len=64, vocab_size=64, hidden_size=16, n_layers=2,
        n_heads=4, n_kv_heads=2, intermediate_size=32, moe_experts=4,
        moe_use_residual=False, tie_embeddings=False)
    native = init_transformer_params(native_cfg, jax.random.PRNGKey(0))
    assert (jax.tree_util.tree_structure(jax.tree_util.tree_map(np.asarray, p))
            == jax.tree_util.tree_structure(
                jax.tree_util.tree_map(np.asarray, native)))


def test_safetensors_reader_roundtrip(tmp_path):
    """The native safetensors reader handles fp32/bf16/int dtypes."""
    import ml_dtypes
    import struct

    from deepspeed_tpu.checkpoint.hf_import import read_safetensors

    a = np.arange(12, dtype=np.float32).reshape(3, 4)
    b = np.arange(6, dtype=np.int64)
    c = (np.arange(4) / 3.0).astype(ml_dtypes.bfloat16)
    tensors = {"a": ("F32", a), "b": ("I64", b), "c": ("BF16", c)}
    header = {}
    off = 0
    payload = b""
    for name, (dt, arr) in tensors.items():
        raw = arr.tobytes()
        header[name] = {"dtype": dt, "shape": list(arr.shape),
                        "data_offsets": [off, off + len(raw)]}
        off += len(raw)
        payload += raw
    hjson = json.dumps(header).encode()
    path = tmp_path / "t.safetensors"
    path.write_bytes(struct.pack("<Q", len(hjson)) + hjson + payload)
    out = read_safetensors(str(path))
    np.testing.assert_array_equal(out["a"], a)
    np.testing.assert_array_equal(out["b"], b)
    np.testing.assert_array_equal(np.asarray(out["c"], np.float32),
                                  np.asarray(c, np.float32))


def test_init_inference_from_hf_directory(tmp_path):
    """The reference's end-user flow: point init_inference at a published
    checkpoint directory and generate."""
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    hf_cfg = LlamaConfig(
        vocab_size=96, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, tie_word_embeddings=False)
    torch.manual_seed(2)
    LlamaForCausalLM(hf_cfg).save_pretrained(tmp_path)

    import deepspeed_tpu

    engine = deepspeed_tpu.init_inference(str(tmp_path),
                                          {"dtype": "fp32",
                                           "attn_impl": "xla"})
    ids = np.random.RandomState(3).randint(0, 96, (1, 8)).astype(np.int32)
    out = engine.generate(jnp.asarray(ids), max_new_tokens=4)
    assert out.shape == (1, 12)
    assert int(np.asarray(out).max()) < 96


# -- export (reference zero_to_fp32 / save_16bit_model story) ---------------
def test_export_roundtrip_and_transformers_load(tmp_path):
    """Native params -> save_hf_checkpoint -> transformers.from_pretrained
    reproduces our logits; and re-importing returns the identical tree."""
    import torch
    from transformers import AutoModelForCausalLM

    from deepspeed_tpu.checkpoint.hf_export import save_hf_checkpoint
    from deepspeed_tpu.checkpoint.hf_import import load_hf_model
    from deepspeed_tpu.models.llama import llama_config
    from deepspeed_tpu.models.transformer import init_transformer_params

    cfg = llama_config("tiny", max_seq_len=64, vocab_size=96,
                       n_layers=2, n_heads=4, n_kv_heads=2,
                       attn_impl="xla", tie_embeddings=False,
                       dtype=jnp.float32)
    params = init_transformer_params(cfg, jax.random.PRNGKey(7))
    out = tmp_path / "export"
    save_hf_checkpoint(str(out), cfg, params, "llama")

    ids = np.random.RandomState(2).randint(0, 96, (2, 10)).astype(np.int32)
    ours = _logits_ours(cfg, params, ids)

    hf = AutoModelForCausalLM.from_pretrained(str(out)).eval()
    with torch.no_grad():
        theirs = hf(torch.tensor(ids.astype(np.int64))).logits.float().numpy()
    np.testing.assert_allclose(ours, theirs, atol=2e-4, rtol=2e-3)

    cfg2, params2 = load_hf_model(str(out), dtype=jnp.float32)
    flat1 = jax.tree_util.tree_flatten_with_path(params)[0]
    flat2 = jax.tree_util.tree_flatten_with_path(params2)[0]
    assert len(flat1) == len(flat2), (len(flat1), len(flat2))
    for (kp, a), (_, b) in zip(flat1, flat2):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32), atol=1e-6,
                                   err_msg=jax.tree_util.keystr(kp))


def test_export_gpt2_transformers_load(tmp_path):
    import torch
    from transformers import AutoModelForCausalLM

    from deepspeed_tpu.checkpoint.hf_export import save_hf_checkpoint
    from deepspeed_tpu.models.gpt2 import gpt2_config
    from deepspeed_tpu.models.transformer import init_transformer_params

    cfg = gpt2_config("tiny", vocab_size=80, max_seq_len=64,
                      attn_impl="xla", dtype=jnp.float32)
    params = init_transformer_params(cfg, jax.random.PRNGKey(8))
    out = tmp_path / "export"
    save_hf_checkpoint(str(out), cfg, params, "gpt2")

    ids = np.random.RandomState(3).randint(0, 80, (2, 9)).astype(np.int32)
    ours = _logits_ours(cfg, params, ids)
    hf = AutoModelForCausalLM.from_pretrained(str(out)).eval()
    with torch.no_grad():
        theirs = hf(torch.tensor(ids.astype(np.int64))).logits.float().numpy()
    np.testing.assert_allclose(ours, theirs, atol=5e-4, rtol=5e-3)


def test_export_import_mixtral_roundtrip(tmp_path):
    from deepspeed_tpu.checkpoint.hf_export import save_hf_checkpoint
    from deepspeed_tpu.checkpoint.hf_import import load_hf_model
    from deepspeed_tpu.models.mixtral import mixtral_config
    from deepspeed_tpu.models.transformer import init_transformer_params

    cfg = mixtral_config("tiny", max_seq_len=64, vocab_size=64,
                         moe_use_residual=False, tie_embeddings=False,
                         dtype=jnp.float32)
    params = init_transformer_params(cfg, jax.random.PRNGKey(9))
    save_hf_checkpoint(str(tmp_path), cfg, params, "mixtral")
    cfg2, params2 = load_hf_model(str(tmp_path), dtype=jnp.float32)
    assert cfg2.moe_experts == cfg.moe_experts
    flat1 = jax.tree_util.tree_flatten_with_path(params)[0]
    flat2 = jax.tree_util.tree_flatten_with_path(params2)[0]
    assert len(flat1) == len(flat2), (len(flat1), len(flat2))
    for (kp, a), (_, b) in zip(flat1, flat2):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32), atol=1e-6,
                                   err_msg=jax.tree_util.keystr(kp))


def test_checkpoint_cli_to_hf(tmp_path):
    """Partitioned native checkpoint -> `python -m deepspeed_tpu.checkpoint
    to-hf` -> transformers loads it (the offline zero_to_fp32-style flow)."""
    import torch
    from transformers import AutoModelForCausalLM

    import deepspeed_tpu
    from deepspeed_tpu.checkpoint.__main__ import main as ckpt_cli
    from deepspeed_tpu.checkpoint.hf_export import checkpoint_to_hf
    from deepspeed_tpu.models.llama import llama_config, llama_model

    cfg = llama_config("tiny", max_seq_len=32, vocab_size=64, n_layers=2,
                       attn_impl="xla", tie_embeddings=False,
                       dtype=jnp.float32)
    engine, *_ = deepspeed_tpu.initialize(
        model=llama_model(config=cfg),
        config={"train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 2}, "mesh": {"data": 8}})
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (1, 16, 16)),
                      jnp.int32)
    engine.train_batch({"input_ids": ids})
    engine.save_checkpoint(str(tmp_path / "native"), "t1")

    engine.save_checkpoint(str(tmp_path / "part"), "t1", partitioned=True)

    out = checkpoint_to_hf(str(tmp_path / "native"), "t1",
                           str(tmp_path / "hf"), cfg, "llama")
    out_p = checkpoint_to_hf(str(tmp_path / "part"), "t1",
                             str(tmp_path / "hf_p"), cfg, "llama")
    hf = AutoModelForCausalLM.from_pretrained(out).eval()
    probe = np.random.RandomState(4).randint(0, 64, (1, 8))
    with torch.no_grad():
        theirs = hf(torch.tensor(probe)).logits.float().numpy()
    ours = _logits_ours(cfg, jax.device_get(engine.state.params),
                        probe.astype(np.int32))
    np.testing.assert_allclose(ours, theirs, atol=3e-4, rtol=3e-3)
    # partitioned (per-rank shard) layout converts to the same weights
    from deepspeed_tpu.checkpoint.hf_import import load_hf_model
    _, p1 = load_hf_model(out, dtype=jnp.float32)
    _, p2 = load_hf_model(out_p, dtype=jnp.float32)
    for a, b in zip(jax.tree_util.tree_leaves(p1),
                    jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_hf_import_tensor_parallel_inference(tmp_path, devices8):
    """Imported HF weights shard over the model axis at placement (the
    reference's module_inject sharded loading): TP=2 inference engine, each
    device holds half the attention projections, generate still works."""
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    from deepspeed_tpu.parallel.mesh import MeshConfig, initialize_topology

    hf_cfg = LlamaConfig(
        vocab_size=96, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, tie_word_embeddings=False)
    torch.manual_seed(5)
    m = LlamaForCausalLM(hf_cfg).eval()
    m.save_pretrained(tmp_path)

    import deepspeed_tpu

    initialize_topology(MeshConfig(model=2, data=-1), jax.devices()[:8])
    engine = deepspeed_tpu.init_inference(
        str(tmp_path), {"dtype": "fp32", "attn_impl": "xla",
                        "tensor_parallel": {"tp_size": 2}})
    wq = engine.params["layers"]["attn"]["wq"]
    axes = [a for e in wq.sharding.spec if e
            for a in (e if isinstance(e, tuple) else (e,))]
    assert "model" in axes, wq.sharding
    ids = np.random.RandomState(6).randint(0, 96, (1, 8)).astype(np.int32)
    out = engine.generate(jnp.asarray(ids), max_new_tokens=4)
    assert out.shape == (1, 12)
    # sharded serving must still reproduce the HF logits
    with torch.no_grad():
        want = m(torch.tensor(ids.astype(np.int64))).logits.float().numpy()
    from deepspeed_tpu.checkpoint.hf_import import load_hf_model

    cfg, params = load_hf_model(str(tmp_path), dtype=jnp.float32)
    cfg.attn_impl = "xla"
    got = _logits_ours(cfg, params, ids)
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-3)


def test_v2_engine_from_pretrained(tmp_path):
    """Paged continuous batching straight from an HF checkpoint directory
    (reference inference-v2 model_implementations loading)."""
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                      RaggedInferenceConfig,
                                                      RaggedRequest)

    hf_cfg = LlamaConfig(
        vocab_size=96, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, tie_word_embeddings=False)
    torch.manual_seed(6)
    m = LlamaForCausalLM(hf_cfg).eval()
    m.save_pretrained(tmp_path)

    eng = InferenceEngineV2.from_pretrained(
        str(tmp_path), RaggedInferenceConfig(
            dtype="fp32", page_size=8, num_pages=32, max_seqs=2,
            max_pages_per_seq=8))
    prompt = list(np.random.RandomState(7).randint(0, 96, (6,)))
    out = eng.generate_all([RaggedRequest(prompt_ids=prompt,
                                          max_new_tokens=8)])[0]
    assert len(out) == 8
    # greedy continuation must match HF's
    with torch.no_grad():
        ids = torch.tensor([prompt])
        for _ in range(8):
            nxt = m(ids).logits[0, -1].argmax().item()
            ids = torch.cat([ids, torch.tensor([[nxt]])], dim=1)
    assert out == [int(t) for t in ids[0, 6:].tolist()], (out, ids[0, 6:])


def test_opt_parity(tmp_path):
    """OPT: pre-norm decoder, +2 position offset, relu FFN, tied head."""
    import torch
    from transformers import OPTConfig, OPTForCausalLM

    hf_cfg = OPTConfig(vocab_size=90, hidden_size=32, ffn_dim=64,
                       num_hidden_layers=2, num_attention_heads=4,
                       max_position_embeddings=64, do_layer_norm_before=True,
                       word_embed_proj_dim=32)
    torch.manual_seed(3)
    m = OPTForCausalLM(hf_cfg).eval()
    m.save_pretrained(tmp_path)

    from deepspeed_tpu.checkpoint.hf_import import load_hf_model

    cfg, params = load_hf_model(str(tmp_path), dtype=jnp.float32)
    assert cfg.position == "learned" and cfg.activation == "relu"
    cfg.attn_impl = "xla"
    ids = np.random.RandomState(8).randint(0, 90, (2, 10)).astype(np.int32)
    with torch.no_grad():
        want = m(torch.tensor(ids.astype(np.int64))).logits.float().numpy()
    got = _logits_ours(cfg, params, ids)
    np.testing.assert_allclose(got, want, atol=5e-4, rtol=5e-3)


def test_phi_parity(tmp_path):
    """Phi: parallel attn+MLP block, partial rotary, biased lm_head."""
    import torch
    from transformers import PhiConfig, PhiForCausalLM

    hf_cfg = PhiConfig(vocab_size=88, hidden_size=32, intermediate_size=64,
                       num_hidden_layers=2, num_attention_heads=4,
                       max_position_embeddings=64,
                       partial_rotary_factor=0.5)
    torch.manual_seed(4)
    m = PhiForCausalLM(hf_cfg).eval()
    m.save_pretrained(tmp_path)

    from deepspeed_tpu.checkpoint.hf_import import load_hf_model

    cfg, params = load_hf_model(str(tmp_path), dtype=jnp.float32)
    assert cfg.parallel_block and cfg.rotary_pct == 0.5
    cfg.attn_impl = "xla"
    ids = np.random.RandomState(9).randint(0, 88, (2, 10)).astype(np.int32)
    with torch.no_grad():
        want = m(torch.tensor(ids.astype(np.int64))).logits.float().numpy()
    got = _logits_ours(cfg, params, ids)
    np.testing.assert_allclose(got, want, atol=5e-4, rtol=5e-3)


def test_falcon_parity(tmp_path):
    """Falcon 7b-style: fused multi-query QKV split, parallel attn+MLP on
    one layernorm, tied head."""
    import torch
    from transformers import FalconConfig, FalconForCausalLM

    hf_cfg = FalconConfig(vocab_size=80, hidden_size=32,
                          num_hidden_layers=2, num_attention_heads=4,
                          multi_query=True, new_decoder_architecture=False,
                          parallel_attn=True, bias=False,
                          max_position_embeddings=64)
    torch.manual_seed(5)
    m = FalconForCausalLM(hf_cfg).eval()
    m.save_pretrained(tmp_path)

    from deepspeed_tpu.checkpoint.hf_import import load_hf_model

    cfg, params = load_hf_model(str(tmp_path), dtype=jnp.float32)
    assert cfg.parallel_block and cfg.n_kv_heads == 1
    cfg.attn_impl = "xla"
    ids = np.random.RandomState(10).randint(0, 80, (2, 10)).astype(np.int32)
    with torch.no_grad():
        want = m(torch.tensor(ids.astype(np.int64))).logits.float().numpy()
    got = _logits_ours(cfg, params, ids)
    np.testing.assert_allclose(got, want, atol=5e-4, rtol=5e-3)


@pytest.mark.parametrize("family", ["opt", "phi", "falcon"])
def test_export_new_families_transformers_load(tmp_path, family):
    """Export->transformers.from_pretrained logit parity for opt/phi/falcon
    (import the HF model, re-export ours, reload with transformers)."""
    import torch
    from transformers import (AutoModelForCausalLM, FalconConfig,
                              FalconForCausalLM, OPTConfig, OPTForCausalLM,
                              PhiConfig, PhiForCausalLM)

    from deepspeed_tpu.checkpoint.hf_export import save_hf_checkpoint
    from deepspeed_tpu.checkpoint.hf_import import load_hf_model

    torch.manual_seed(12)
    if family == "opt":
        m = OPTForCausalLM(OPTConfig(
            vocab_size=90, hidden_size=32, ffn_dim=64, num_hidden_layers=2,
            num_attention_heads=4, max_position_embeddings=64,
            do_layer_norm_before=True, word_embed_proj_dim=32))
    elif family == "phi":
        m = PhiForCausalLM(PhiConfig(
            vocab_size=88, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4,
            max_position_embeddings=64, partial_rotary_factor=0.5))
    else:
        m = FalconForCausalLM(FalconConfig(
            vocab_size=80, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=4, multi_query=True,
            new_decoder_architecture=False, parallel_attn=True, bias=False,
            max_position_embeddings=64))
    m = m.eval()
    src = tmp_path / "src"
    m.save_pretrained(src)
    cfg, params = load_hf_model(str(src), dtype=jnp.float32)
    out = tmp_path / "exported"
    save_hf_checkpoint(str(out), cfg, params, family)
    hf2 = AutoModelForCausalLM.from_pretrained(str(out)).eval()
    vocab = cfg.vocab_size
    ids = np.random.RandomState(13).randint(0, vocab, (2, 9))
    with torch.no_grad():
        want = m(torch.tensor(ids)).logits.float().numpy()
        got = hf2(torch.tensor(ids)).logits.float().numpy()
    np.testing.assert_allclose(got, want, atol=5e-4, rtol=5e-3)


def test_bert_mlm_parity(tmp_path):
    """BertForMaskedLM: post-norm encoder + full MLM prediction head must
    reproduce HF logits (bidirectional attention, segment embeddings,
    embeddings LayerNorm, exact gelu)."""
    import torch
    from transformers import BertConfig, BertForMaskedLM

    hf_cfg = BertConfig(vocab_size=100, hidden_size=32,
                        intermediate_size=64, num_hidden_layers=2,
                        num_attention_heads=4, max_position_embeddings=64,
                        type_vocab_size=2)
    torch.manual_seed(14)
    m = BertForMaskedLM(hf_cfg).eval()
    m.save_pretrained(tmp_path)

    from deepspeed_tpu.checkpoint.hf_import import load_hf_model
    from deepspeed_tpu.models.bert import mlm_logits
    from deepspeed_tpu.models.transformer import transformer_forward

    cfg, params = load_hf_model(str(tmp_path), dtype=jnp.float32)
    assert cfg.post_norm and not cfg.causal
    cfg.attn_impl = "xla"
    r = np.random.RandomState(11)
    ids = r.randint(0, 100, (2, 12)).astype(np.int32)
    tt = r.randint(0, 2, (2, 12)).astype(np.int32)
    with torch.no_grad():
        want = m(torch.tensor(ids.astype(np.int64)),
                 token_type_ids=torch.tensor(tt.astype(np.int64))
                 ).logits.float().numpy()
    hidden, _ = transformer_forward(cfg, params, jnp.asarray(ids),
                                    token_type_ids=jnp.asarray(tt))
    got = np.asarray(mlm_logits(cfg, params, hidden), np.float32)
    np.testing.assert_allclose(got, want, atol=5e-4, rtol=5e-3)


def test_bert_export_roundtrip(tmp_path):
    """BERT export: transformers reloads our re-export with identical MLM
    logits (post-norm, segment embeddings, full prediction head)."""
    import torch
    from transformers import AutoModelForMaskedLM, BertConfig, BertForMaskedLM

    from deepspeed_tpu.checkpoint.hf_export import save_hf_checkpoint
    from deepspeed_tpu.checkpoint.hf_import import load_hf_model

    hf_cfg = BertConfig(vocab_size=100, hidden_size=32, intermediate_size=64,
                        num_hidden_layers=2, num_attention_heads=4,
                        max_position_embeddings=64, type_vocab_size=2)
    torch.manual_seed(15)
    m = BertForMaskedLM(hf_cfg).eval()
    src = tmp_path / "src"
    m.save_pretrained(src)
    cfg, params = load_hf_model(str(src), dtype=jnp.float32)
    out = tmp_path / "exported"
    save_hf_checkpoint(str(out), cfg, params, "bert")
    hf2 = AutoModelForMaskedLM.from_pretrained(str(out)).eval()
    r = np.random.RandomState(16)
    ids = r.randint(0, 100, (2, 10))
    tt = r.randint(0, 2, (2, 10))
    with torch.no_grad():
        want = m(torch.tensor(ids), token_type_ids=torch.tensor(tt)
                 ).logits.float().numpy()
        got = hf2(torch.tensor(ids), token_type_ids=torch.tensor(tt)
                  ).logits.float().numpy()
    np.testing.assert_allclose(got, want, atol=5e-4, rtol=5e-3)


def test_falcon_new_arch_parity(tmp_path):
    """Falcon 40b/180b-style (new decoder architecture): grouped-KV fused
    QKV split and separate ln_attn/ln_mlp parallel norms."""
    import torch
    from transformers import FalconConfig, FalconForCausalLM

    hf_cfg = FalconConfig(vocab_size=80, hidden_size=32,
                          num_hidden_layers=2, num_attention_heads=4,
                          num_kv_heads=2, new_decoder_architecture=True,
                          parallel_attn=True, bias=False,
                          max_position_embeddings=64)
    torch.manual_seed(21)
    m = FalconForCausalLM(hf_cfg).eval()
    m.save_pretrained(tmp_path)

    from deepspeed_tpu.checkpoint.hf_import import load_hf_model

    cfg, params = load_hf_model(str(tmp_path), dtype=jnp.float32)
    assert cfg.parallel_block and cfg.n_kv_heads == 2
    assert "norm2" in params["layers"]  # ln_mlp imported
    cfg.attn_impl = "xla"
    ids = np.random.RandomState(17).randint(0, 80, (2, 10)).astype(np.int32)
    with torch.no_grad():
        want = m(torch.tensor(ids.astype(np.int64))).logits.float().numpy()
    got = _logits_ours(cfg, params, ids)
    np.testing.assert_allclose(got, want, atol=5e-4, rtol=5e-3)


def test_falcon_11b_style_parity(tmp_path):
    """Falcon2/11B-style: new decoder architecture (grouped KV) but a
    SINGLE shared input_layernorm (num_ln_in_parallel_attn=1) — the
    config, not key-sniffing, must pick both the split and the norms."""
    import torch
    from transformers import FalconConfig, FalconForCausalLM

    hf_cfg = FalconConfig(vocab_size=80, hidden_size=32,
                          num_hidden_layers=2, num_attention_heads=4,
                          num_kv_heads=2, new_decoder_architecture=True,
                          num_ln_in_parallel_attn=1,
                          parallel_attn=True, bias=False,
                          max_position_embeddings=64)
    torch.manual_seed(22)
    m = FalconForCausalLM(hf_cfg).eval()
    m.save_pretrained(tmp_path)

    from deepspeed_tpu.checkpoint.hf_import import load_hf_model

    cfg, params = load_hf_model(str(tmp_path), dtype=jnp.float32)
    assert cfg.parallel_norms == 1 and cfg.n_kv_heads == 2
    assert "norm2" not in params["layers"]
    cfg.attn_impl = "xla"
    ids = np.random.RandomState(18).randint(0, 80, (2, 10)).astype(np.int32)
    with torch.no_grad():
        want = m(torch.tensor(ids.astype(np.int64))).logits.float().numpy()
    got = _logits_ours(cfg, params, ids)
    np.testing.assert_allclose(got, want, atol=5e-4, rtol=5e-3)


def test_phi3_safetensors_parity(tmp_path):
    """phi3: llama-shaped with FUSED qkv_proj / gate_up_proj — the split
    must land every row in the right projection (an off-by-head split
    shows up immediately as logit divergence)."""
    import torch
    from transformers import Phi3Config, Phi3ForCausalLM

    hf_cfg = Phi3Config(
        vocab_size=96, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rms_norm_eps=1e-5, rope_theta=10000.0,
        tie_word_embeddings=False, pad_token_id=0)  # default pad id (32000)
    torch.manual_seed(3)
    m = Phi3ForCausalLM(hf_cfg).eval()
    m.save_pretrained(tmp_path)

    from deepspeed_tpu.checkpoint.hf_import import load_hf_model

    cfg, params = load_hf_model(str(tmp_path), dtype=jnp.float32)
    assert cfg.n_kv_heads == 2 and cfg.activation == "swiglu"
    cfg.attn_impl = "xla"

    ids = np.random.RandomState(3).randint(0, 96, (2, 12)).astype(np.int32)
    with torch.no_grad():
        want = m(torch.tensor(ids.astype(np.int64))).logits.float().numpy()
    got = _logits_ours(cfg, params, ids)
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-3)


def test_phi3_longrope_rejected(tmp_path):
    from deepspeed_tpu.checkpoint.hf_import import config_from_hf

    c = {"model_type": "phi3", "vocab_size": 96, "hidden_size": 32,
         "intermediate_size": 64, "num_hidden_layers": 2,
         "num_attention_heads": 4,
         "rope_scaling": {"type": "longrope", "short_factor": [1.0],
                          "long_factor": [1.0]}}
    with pytest.raises(ValueError, match="longrope"):
        config_from_hf(c)


def test_export_phi3_roundtrip_and_transformers_load(tmp_path):
    """phi3 export re-fuses q/k/v -> qkv_proj and gate/up -> gate_up_proj;
    Phi3ForCausalLM must load it and reproduce our logits, and re-import
    must return the identical tree."""
    import torch
    from transformers import AutoModelForCausalLM

    from deepspeed_tpu.checkpoint.hf_export import save_hf_checkpoint
    from deepspeed_tpu.checkpoint.hf_import import load_hf_model
    from deepspeed_tpu.models.llama import llama_config
    from deepspeed_tpu.models.transformer import init_transformer_params

    cfg = llama_config("tiny", max_seq_len=64, vocab_size=96,
                       n_layers=2, n_heads=4, n_kv_heads=2,
                       attn_impl="xla", tie_embeddings=False,
                       dtype=jnp.float32)
    params = init_transformer_params(cfg, jax.random.PRNGKey(9))
    out = tmp_path / "export_phi3"
    save_hf_checkpoint(str(out), cfg, params, "phi3")

    ids = np.random.RandomState(4).randint(0, 96, (2, 10)).astype(np.int32)
    ours = _logits_ours(cfg, params, ids)
    hf = AutoModelForCausalLM.from_pretrained(str(out)).eval()
    assert type(hf).__name__ == "Phi3ForCausalLM"
    with torch.no_grad():
        theirs = hf(torch.tensor(ids.astype(np.int64))).logits.float().numpy()
    np.testing.assert_allclose(ours, theirs, atol=2e-4, rtol=2e-3)

    cfg2, params2 = load_hf_model(str(out), dtype=jnp.float32)
    flat1 = jax.tree_util.tree_flatten_with_path(params)[0]
    flat2 = jax.tree_util.tree_flatten_with_path(params2)[0]
    assert len(flat1) == len(flat2)
    for (kp, a), (_, b) in zip(flat1, flat2):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32), atol=1e-6,
                                   err_msg=jax.tree_util.keystr(kp))


def test_qwen2_moe_safetensors_parity(tmp_path):
    """qwen2-moe: routed experts + always-on shared expert with a sigmoid
    per-token gate, norm_topk_prob=False (raw softmax weights). Logit
    parity pins the routing semantics end to end."""
    import torch
    from transformers import Qwen2MoeConfig, Qwen2MoeForCausalLM

    hf_cfg = Qwen2MoeConfig(
        vocab_size=96, hidden_size=32, intermediate_size=64,
        moe_intermediate_size=48, shared_expert_intermediate_size=56,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_experts=4, num_experts_per_tok=2, norm_topk_prob=False,
        decoder_sparse_step=1, mlp_only_layers=[],
        max_position_embeddings=64, rms_norm_eps=1e-5,
        tie_word_embeddings=False)
    torch.manual_seed(11)
    m = Qwen2MoeForCausalLM(hf_cfg).eval()
    m.save_pretrained(tmp_path)

    from deepspeed_tpu.checkpoint.hf_import import load_hf_model

    cfg, params = load_hf_model(str(tmp_path), dtype=jnp.float32)
    assert cfg.moe_experts == 4 and cfg.moe_shared_expert == 56
    assert cfg.moe_norm_topk is False and cfg.qkv_bias
    assert cfg.ffn_size == 48  # experts use moe_intermediate_size
    cfg.attn_impl = "xla"

    ids = np.random.RandomState(6).randint(0, 96, (2, 12)).astype(np.int32)
    with torch.no_grad():
        want = m(torch.tensor(ids.astype(np.int64))).logits.float().numpy()
    got = _logits_ours(cfg, params, ids)
    np.testing.assert_allclose(got, want, atol=5e-4, rtol=5e-3)


def test_export_qwen2_moe_roundtrip_and_transformers_load(tmp_path):
    """qwen2_moe export: per-expert names, shared expert + sigmoid gate,
    qkv biases; Qwen2MoeForCausalLM loads it and reproduces our logits;
    re-import returns the identical tree."""
    import torch
    from transformers import AutoModelForCausalLM

    from deepspeed_tpu.checkpoint.hf_export import save_hf_checkpoint
    from deepspeed_tpu.checkpoint.hf_import import load_hf_model
    from deepspeed_tpu.models.mixtral import mixtral_config, mixtral_model

    cfg = mixtral_config("tiny", max_seq_len=64, attn_impl="xla",
                         moe_drop_tokens=False, moe_shared_expert=56,
                         moe_norm_topk=False, qkv_bias=True,
                         intermediate_size=48, dtype=jnp.float32)
    params = mixtral_model(config=cfg).init_params(jax.random.PRNGKey(15))
    out = tmp_path / "export_q2moe"
    save_hf_checkpoint(str(out), cfg, params, "qwen2_moe")

    ids = np.random.RandomState(8).randint(0, cfg.vocab_size,
                                           (2, 10)).astype(np.int32)
    ours = _logits_ours(cfg, params, ids)
    hf = AutoModelForCausalLM.from_pretrained(str(out)).eval()
    assert type(hf).__name__ == "Qwen2MoeForCausalLM"
    with torch.no_grad():
        theirs = hf(torch.tensor(ids.astype(np.int64))).logits.float().numpy()
    np.testing.assert_allclose(ours, theirs, atol=5e-4, rtol=5e-3)

    cfg2, params2 = load_hf_model(str(out), dtype=jnp.float32)
    assert cfg2.moe_shared_expert == 56 and cfg2.moe_norm_topk is False
    flat1 = jax.tree_util.tree_flatten_with_path(params)[0]
    flat2 = jax.tree_util.tree_flatten_with_path(params2)[0]
    assert len(flat1) == len(flat2)
    for (kp, a), (_, b) in zip(flat1, flat2):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32), atol=1e-6,
                                   err_msg=jax.tree_util.keystr(kp))


def test_bloom_parity(tmp_path):
    """Bloom: ALiBi bias (softmax-equivalent formulation), per-head fused
    QKV split, word_embeddings_layernorm, tied head."""
    import torch
    from transformers import BloomConfig, BloomForCausalLM

    hf_cfg = BloomConfig(vocab_size=90, hidden_size=32, n_layer=2,
                         n_head=4, layer_norm_epsilon=1e-5)
    torch.manual_seed(11)
    m = BloomForCausalLM(hf_cfg).eval()
    m.save_pretrained(tmp_path)

    from deepspeed_tpu.checkpoint.hf_import import load_hf_model

    cfg, params = load_hf_model(str(tmp_path), dtype=jnp.float32)
    assert cfg.position == "alibi" and cfg.embed_norm
    ids = np.random.RandomState(12).randint(0, 90, (2, 10)).astype(np.int32)
    with torch.no_grad():
        want = m(torch.tensor(ids.astype(np.int64))).logits.float().numpy()
    got = _logits_ours(cfg, params, ids)
    np.testing.assert_allclose(got, want, atol=5e-4, rtol=5e-3)


def test_bloom_untied_head_parity(tmp_path):
    """tie_word_embeddings=false bloom: the separate lm_head.weight must be
    imported, not silently replaced by the tied embedding (ADVICE r5 —
    the hardcoded tied head produced wrong logits for untied variants)."""
    import torch
    from transformers import BloomConfig, BloomForCausalLM

    hf_cfg = BloomConfig(vocab_size=90, hidden_size=32, n_layer=2,
                         n_head=4, layer_norm_epsilon=1e-5,
                         tie_word_embeddings=False)
    torch.manual_seed(21)
    m = BloomForCausalLM(hf_cfg).eval()
    m.save_pretrained(tmp_path)

    from deepspeed_tpu.checkpoint.hf_import import load_hf_model

    cfg, params = load_hf_model(str(tmp_path), dtype=jnp.float32)
    assert not cfg.tie_embeddings and "lm_head" in params
    ids = np.random.RandomState(22).randint(0, 90, (2, 10)).astype(np.int32)
    with torch.no_grad():
        want = m(torch.tensor(ids.astype(np.int64))).logits.float().numpy()
    got = _logits_ours(cfg, params, ids)
    np.testing.assert_allclose(got, want, atol=5e-4, rtol=5e-3)


def test_gpt_neox_parity(tmp_path):
    """GPT-NeoX: per-head fused QKV, partial rotary (rotary_pct), parallel
    residual with separate norms, untied embed_out."""
    import torch
    from transformers import GPTNeoXConfig, GPTNeoXForCausalLM

    hf_cfg = GPTNeoXConfig(vocab_size=96, hidden_size=32,
                           num_hidden_layers=2, num_attention_heads=4,
                           intermediate_size=64, rotary_pct=0.25,
                           use_parallel_residual=True,
                           max_position_embeddings=64)
    torch.manual_seed(13)
    m = GPTNeoXForCausalLM(hf_cfg).eval()
    m.save_pretrained(tmp_path)

    from deepspeed_tpu.checkpoint.hf_import import load_hf_model

    cfg, params = load_hf_model(str(tmp_path), dtype=jnp.float32)
    assert cfg.parallel_block and cfg.parallel_norms == 2
    assert cfg.rotary_pct == 0.25 and not cfg.tie_embeddings
    cfg.attn_impl = "xla"
    ids = np.random.RandomState(14).randint(0, 96, (2, 10)).astype(np.int32)
    with torch.no_grad():
        want = m(torch.tensor(ids.astype(np.int64))).logits.float().numpy()
    got = _logits_ours(cfg, params, ids)
    np.testing.assert_allclose(got, want, atol=5e-4, rtol=5e-3)


def test_export_bloom_neox_transformers_load(tmp_path):
    """Export roundtrip: native bloom/neox trees -> HF directory ->
    transformers.from_pretrained logit parity."""
    import torch
    from transformers import AutoModelForCausalLM

    from deepspeed_tpu.checkpoint.hf_export import save_hf_checkpoint
    from deepspeed_tpu.models import bloom_model, gpt_neox_model

    for name, fam in (("bloom", bloom_model), ("gpt_neox", gpt_neox_model)):
        model = fam("tiny", max_seq_len=64)
        params = model.init_params(jax.random.PRNGKey(3))
        out = tmp_path / name
        save_hf_checkpoint(str(out), model.config, params, model_type=name)
        hf = AutoModelForCausalLM.from_pretrained(str(out)).eval()
        ids = np.random.RandomState(15).randint(0, 250, (2, 8)).astype(np.int32)
        with torch.no_grad():
            want = hf(torch.tensor(ids.astype(np.int64))).logits.float().numpy()
        cfg = dataclasses.replace(model.config, attn_impl="xla", dtype=jnp.float32)
        got = _logits_ours(cfg, jax.tree_util.tree_map(
            lambda x: jnp.asarray(x, jnp.float32), params), ids)
        np.testing.assert_allclose(got, want, atol=5e-4, rtol=5e-3, err_msg=name)
