"""Cross-process transport tests (serving/transport.py).

All FAST tier: the frame protocol, the engine proxy's typed error
mapping, and the wire-hardening contract — torn / truncated /
bit-flipped bundle frames over a REAL socket are refused with
``CorruptBundleError`` naming the page while the fake engine stays
untouched — plus the bounded, seeded backoff schedule (injectable
sleep, so the schedule is asserted, not waited out).  The engine here
is a pure-python fake speaking the dispatch surface; the true
cross-PROCESS oracle (spawned child, bit-identical streams) lives in
``tools/fleet_drill.py`` leg 9 and the slow tier.
"""

import json
import socket
import threading
from types import SimpleNamespace

import numpy as np
import pytest

from deepspeed_tpu.inference.v2 import KVPageBundle
from deepspeed_tpu.serving.admission import RejectedError
from deepspeed_tpu.serving.config import TransportConfig
from deepspeed_tpu.serving.kv_transfer import (CorruptBundleError,
                                               bundle_to_bytes)
from deepspeed_tpu.serving.transport import (_FRAME_BUNDLE, _FRAME_JSON,
                                             BundleSender, EngineServer,
                                             RemoteEngineProxy,
                                             TransportError, recv_frame,
                                             send_frame)


def _bundle(uid=7):
    arrays = {"k": np.arange(1 * 1 * 8 * 2 * 2, dtype=np.float32)
              .reshape(1, 1, 8, 2, 2)}
    return KVPageBundle(uid=uid, tokens=list(range(10)), prompt_len=9,
                        max_new_tokens=4, temperature=0.0, eos_id=None,
                        prefilled=9, decode_entry=False, page_size=8,
                        page_keys=[b"\x07" * 32],
                        src_pages=[{"page": 1, "refcount": 1,
                                    "key": b"\x07" * 32}],
                        arrays=arrays, model_sig=(1, 2, 2), kv_quant=False,
                        dtype="fp32")


class FakeEngine:
    """Pure-python engine surface for the dispatch table — records
    every mutating call so refusal tests can assert 'nothing adopted'."""

    def __init__(self):
        self.block = SimpleNamespace(page_size=8)
        self.max_seq_len = 64
        self.allocator = SimpleNamespace(free_pages=40, num_pages=64)
        self.queue_depth = 2
        self.active_count = 1
        self.puts = []
        self.imported = []
        self.released = []
        self.closed = False
        self.reject_puts = False

    def has_work(self):
        return True

    def inflight_uids(self):
        return [11]

    def ready_uids(self):
        return [11]

    def put(self, request, *, record_shed=True):
        if self.reject_puts:
            raise RejectedError("kv_pressure", retry_after_s=2.5,
                                priority=request.priority)
        self.puts.append(request)
        return int(request.uid)

    def step(self):
        return {11: {"tokens": [3, 4], "done": False}}

    def export_sequence(self, uid):
        return _bundle(uid)

    def import_sequence(self, bundle):
        self.imported.append(bundle)
        return True

    def release_sequence(self, uid, reason="migrated"):
        self.released.append((uid, reason))

    def abort_all(self, reason="abort"):
        return [11]

    def drain(self, max_steps=10_000):
        fin = SimpleNamespace(uid=11, tokens=[3, 4, 5], prompt_len=9,
                              finish_reason="eos")
        return {"finished": {11: fin}, "pending": []}

    def assert_no_leaks(self):
        pass

    def close(self):
        self.closed = True


@pytest.fixture
def served(tmp_path):
    """A FakeEngine behind a real AF_UNIX EngineServer on a thread."""
    address = str(tmp_path / "engine.sock")
    engine = FakeEngine()
    listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    listener.bind(address)
    listener.listen(1)
    t = threading.Thread(target=EngineServer(engine, listener).serve,
                         daemon=True)
    t.start()
    yield engine, address
    t.join(timeout=5.0)


def _fast_cfg(**kw):
    kw.setdefault("connect_retries", 5)
    kw.setdefault("backoff_base_s", 0.01)
    kw.setdefault("io_timeout_s", 5.0)
    return TransportConfig(**kw)


# ----------------------------- frame protocol -------------------------------
def test_frame_roundtrip_and_desync_refusal():
    a, b = socket.socketpair()
    try:
        send_frame(a, _FRAME_JSON, b'{"op":"x"}')
        send_frame(a, _FRAME_BUNDLE, b"\x00" * 1000)
        assert recv_frame(b) == (_FRAME_JSON, b'{"op":"x"}')
        assert recv_frame(b) == (_FRAME_BUNDLE, b"\x00" * 1000)
        # unknown kind byte = desynchronized stream, refused
        a.sendall(b"Z" + (0).to_bytes(8, "little"))
        with pytest.raises(TransportError, match="frame kind"):
            recv_frame(b)
        # absurd length = desynchronized stream, refused before reading
        a.sendall(_FRAME_JSON + (1 << 40).to_bytes(8, "little"))
        with pytest.raises(TransportError, match="frame length"):
            recv_frame(b)
    finally:
        a.close(), b.close()


def test_peer_close_mid_frame_is_transport_error():
    a, b = socket.socketpair()
    # promise 100 bytes, deliver 10, hang up: a TRANSPORT error (retry),
    # never a corrupt-bundle refusal
    a.sendall(_FRAME_BUNDLE + (100).to_bytes(8, "little") + b"x" * 10)
    a.close()
    try:
        with pytest.raises(TransportError, match="10/100 bytes"):
            recv_frame(b)
    finally:
        b.close()


# ----------------------------- proxy surface --------------------------------
def test_proxy_engine_surface_and_typed_errors(served):
    engine, address = served
    proxy = RemoteEngineProxy(address, _fast_cfg())
    assert proxy.block.page_size == 8 and proxy.max_seq_len == 64
    assert proxy.queue_depth == 2 and proxy.active_count == 1
    assert proxy.allocator.free_pages == 40
    assert proxy.allocator.num_pages == 64
    assert proxy.has_work() and proxy.inflight_uids() == [11]
    req = SimpleNamespace(prompt_ids=[1, 2, 3], max_new_tokens=4,
                          temperature=0.0, eos_id=None, uid=21,
                          priority=1, deadline_s=None, trace_id="t-21")
    assert proxy.put(req) == 21
    assert engine.puts[0].prompt_ids == [1, 2, 3]
    assert engine.puts[0].trace_id == "t-21"
    out = proxy.step()
    assert out == {11: {"tokens": [3, 4], "done": False}}
    assert 11 in out  # uids survive the JSON hop as ints
    assert proxy.ready_uids() == [11]
    # export = pull: bundle re-verified CLIENT-side, bit identical
    rt = proxy.export_sequence(11)
    assert rt.uid == 11 and np.array_equal(
        rt.arrays["k"], _bundle().arrays["k"])
    proxy.release_sequence(11, reason="migrated")
    assert engine.released == [(11, "migrated")]
    assert proxy.abort_all() == [11]
    d = proxy.drain()
    assert d["finished"][11].tokens == [3, 4, 5]
    assert d["finished"][11].finish_reason == "eos"
    proxy.assert_no_leaks()
    # a remote RejectedError crosses the wire typed, hint intact
    engine.reject_puts = True
    with pytest.raises(RejectedError) as exc:
        proxy.put(req)
    assert exc.value.reason == "kv_pressure"
    assert exc.value.retry_after_s == 2.5
    proxy.close()
    assert engine.closed


# ----------------------------- wire hardening -------------------------------
def _import_raw(proxy, blob):
    """Push raw bytes through the real socket as an import and run the
    reply through the proxy's typed-error mapping."""
    reply, _ = proxy._sender.request({"op": "import"}, blob)
    return proxy._check(reply)


def test_bitflip_refused_naming_page_and_nothing_adopted(served):
    engine, address = served
    proxy = RemoteEngineProxy(address, _fast_cfg())
    wire = bundle_to_bytes(_bundle())
    flipped = bytearray(wire)
    flipped[-5] ^= 0xFF  # one bit-flip in the last leaf's bytes
    with pytest.raises(CorruptBundleError, match=r"page\(s\)"):
        _import_raw(proxy, bytes(flipped))
    assert engine.imported == []  # refused BEFORE adoption
    # the intact bytes then import fine on the same connection — the
    # refusal cost one reply, not the session
    assert _import_raw(proxy, wire)["ok"] is True
    assert len(engine.imported) == 1
    proxy.close()


def test_truncated_and_torn_header_refused(served):
    engine, address = served
    proxy = RemoteEngineProxy(address, _fast_cfg())
    wire = bundle_to_bytes(_bundle())
    with pytest.raises(CorruptBundleError, match="truncated"):
        _import_raw(proxy, wire[:-7])  # torn mid-leaf
    with pytest.raises(CorruptBundleError, match="truncated"):
        _import_raw(proxy, wire[:20])  # torn inside the header
    with pytest.raises(CorruptBundleError):
        _import_raw(proxy, b"GARBAGE!" + wire[8:])  # wrong magic
    assert engine.imported == []
    proxy.close()


def test_refused_bundle_counter_and_export_pull_verified(tmp_path):
    """The RECEIVING side re-verifies whichever direction the bundle
    flows: a raw server replying with a corrupted bundle frame to an
    export (pull) is refused client-side, by name."""
    from deepspeed_tpu.telemetry import get_registry

    address = str(tmp_path / "raw.sock")
    wire = bytearray(bundle_to_bytes(_bundle()))
    wire[-5] ^= 0xFF
    listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    listener.bind(address)
    listener.listen(1)

    def _raw_server():
        conn, _ = listener.accept()
        with conn:
            recv_frame(conn)  # the export request
            send_frame(conn, _FRAME_JSON, json.dumps(
                {"ok": True, "bundle_follows": True}).encode())
            send_frame(conn, _FRAME_BUNDLE, bytes(wire))
        listener.close()

    t = threading.Thread(target=_raw_server, daemon=True)
    t.start()
    refused = get_registry().get(
        "deepspeed_tpu_serving_transport_refused_bundles_total")
    before = refused.total()
    sender = BundleSender(address, _fast_cfg())
    try:
        reply, blob = sender.request({"op": "export", "uid": 7})
        assert reply["ok"] and blob is not None
        from deepspeed_tpu.serving.kv_transfer import bundle_from_bytes
        with pytest.raises(CorruptBundleError, match=r"page\(s\)"):
            bundle_from_bytes(blob)
    finally:
        sender.close()
        t.join(timeout=5.0)
    # (the proxy's export_sequence wraps exactly this path and counts
    # the refusal; here we asserted the verification itself)
    assert refused.total() >= before


# ----------------------------- bounded backoff ------------------------------
def test_backoff_is_bounded_seeded_and_exponential(tmp_path):
    """A dead peer costs exactly ``connect_retries`` attempts on the
    documented schedule — asserted via injected sleep, not waited out."""
    import random as _random

    cfg = TransportConfig(connect_retries=5, backoff_base_s=0.05,
                          backoff_max_s=2.0, backoff_jitter=0.25)
    slept = []
    sender = BundleSender(str(tmp_path / "nobody.sock"), cfg, seed=7,
                          sleep=slept.append)
    with pytest.raises(TransportError, match="5 bounded attempts"):
        sender.request({"op": "hello"})
    assert sender.connect_attempts == 5
    assert len(sender.backoffs_taken) == 4  # no sleep after the last
    assert slept == sender.backoffs_taken
    # the exact elastic-agent schedule: capped exponential, seeded jitter
    r = _random.Random(7)
    expect = [min(0.05 * 2 ** (f - 1), 2.0) * (1 + 0.25 * r.random())
              for f in range(1, 5)]
    assert sender.backoffs_taken == pytest.approx(expect)
    assert max(sender.backoffs_taken) <= 2.0 * 1.25
    sender.close()
    # determinism: same seed, same dead peer -> the identical schedule
    sender2 = BundleSender(str(tmp_path / "nobody.sock"), cfg, seed=7,
                           sleep=lambda _d: None)
    with pytest.raises(TransportError):
        sender2.request({"op": "hello"})
    assert sender2.backoffs_taken == pytest.approx(sender.backoffs_taken)
    sender2.close()


def test_sender_refuses_after_close(tmp_path):
    sender = BundleSender(str(tmp_path / "nobody.sock"),
                          _fast_cfg(connect_retries=1),
                          sleep=lambda _d: None)
    sender.close()
    with pytest.raises(TransportError, match="closed"):
        sender.request({"op": "hello"})
