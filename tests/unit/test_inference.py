"""Inference engine tests (reference tests/unit/inference/).

Key property: KV-cache decode produces the same tokens as full re-forward
argmax (the cache is exact, not an approximation)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.inference.engine import InferenceConfig, InferenceEngine
from deepspeed_tpu.models import gpt2_model, llama_model
from deepspeed_tpu.models.transformer import (forward_with_cache,
                                              init_kv_cache,
                                              transformer_forward, logits_fn)


def _greedy_reference(model, params, ids, steps):
    """Generate by full re-forward each step (no cache)."""
    cfg = model.config
    ids = jnp.asarray(ids, jnp.int32)
    for _ in range(steps):
        hidden, _ = transformer_forward(cfg, params, ids)
        logits = logits_fn(cfg, params, hidden)
        nxt = jnp.argmax(logits[:, -1].astype(jnp.float32), axis=-1)
        ids = jnp.concatenate([ids, nxt[:, None]], axis=1)
    return ids


@pytest.mark.parametrize("family", ["llama", "gpt2"])
def test_cached_decode_matches_full_forward(family):
    model = (llama_model if family == "llama" else gpt2_model)(
        "tiny", **({"max_seq_len": 64} if family == "llama" else {}))
    model.config.attn_impl = "xla"
    eng = InferenceEngine(model, InferenceConfig.from_dict({"dtype": "fp32"}))
    prompt = np.random.RandomState(0).randint(0, 256, (2, 8))
    out = eng.generate(prompt, max_new_tokens=6)
    ref = _greedy_reference(model, eng.params, prompt, 6)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_prefill_cache_matches_forward():
    model = llama_model("tiny", max_seq_len=32, attn_impl="xla")
    params = model.init_params(jax.random.PRNGKey(0))
    ids = jnp.asarray(np.random.RandomState(1).randint(0, 256, (2, 10)), jnp.int32)
    cache = init_kv_cache(model.config, 2, 32, jnp.float32)
    logits_c, cache = forward_with_cache(model.config, params, ids, cache,
                                         jnp.zeros((2,), jnp.int32))
    hidden, _ = transformer_forward(model.config, params, ids)
    logits_f = logits_fn(model.config, params, hidden)
    np.testing.assert_allclose(np.asarray(logits_c), np.asarray(logits_f),
                               atol=2e-5, rtol=1e-4)
    assert int(cache["length"]) == 10


def test_init_inference_api():
    model = llama_model("tiny", max_seq_len=32, attn_impl="xla")
    eng = deepspeed_tpu.init_inference(model, config={"dtype": "fp32"},
                                       max_out_tokens=16)
    out = eng.generate(np.zeros((1, 4), np.int32), max_new_tokens=4)
    assert out.shape == (1, 8)


def test_sampling_temperature():
    model = llama_model("tiny", max_seq_len=32, attn_impl="xla")
    eng = InferenceEngine(model, InferenceConfig.from_dict({"dtype": "fp32"}))
    prompt = np.zeros((1, 4), np.int32)
    a = eng.generate(prompt, max_new_tokens=8, temperature=1.5, seed=1)
    b = eng.generate(prompt, max_new_tokens=8, temperature=1.5, seed=2)
    assert not np.array_equal(np.asarray(a), np.asarray(b))


def test_tp_inference(devices8):
    model = llama_model("tiny", max_seq_len=32, attn_impl="xla")
    eng = InferenceEngine(model, InferenceConfig.from_dict(
        {"dtype": "fp32", "tensor_parallel": {"tp_size": 2}}))
    wq = eng.params["layers"]["attn"]["wq"]
    axes = [a for s in wq.sharding.spec if s for a in (s if isinstance(s, tuple) else (s,))]
    assert "model" in axes
    out = eng.generate(np.zeros((1, 4), np.int32), max_new_tokens=4)
    assert out.shape == (1, 8)


def test_quantized_weights_still_generate():
    model = llama_model("tiny", max_seq_len=32, attn_impl="xla")
    eng = InferenceEngine(model, InferenceConfig.from_dict({"dtype": "fp32"}))
    out_ref = eng.generate(np.zeros((1, 4), np.int32), max_new_tokens=4)
    eng.module_quantize()
    out_q = eng.generate(np.zeros((1, 4), np.int32), max_new_tokens=4)
    assert out_q.shape == out_ref.shape


def test_generate_top_k_top_p_restrict_support():
    """top-k=1 must equal greedy; top-p near 0 likewise; plain temperature
    sampling may differ (it has full support)."""
    from deepspeed_tpu.inference.engine import InferenceEngine, InferenceConfig
    from deepspeed_tpu.models.llama import llama_model

    model = llama_model("tiny", max_seq_len=64, attn_impl="xla")
    eng = InferenceEngine(model, InferenceConfig(dtype="fp32", max_seq_len=64))
    prompt = np.random.RandomState(0).randint(0, 256, (1, 7))
    greedy = np.asarray(eng.generate(prompt, max_new_tokens=6))
    k1 = np.asarray(eng.generate(prompt, max_new_tokens=6, temperature=0.8,
                                 top_k=1, seed=3))
    np.testing.assert_array_equal(greedy, k1)
    p0 = np.asarray(eng.generate(prompt, max_new_tokens=6, temperature=0.8,
                                 top_p=1e-9, seed=5))
    np.testing.assert_array_equal(greedy, p0)
    # sampled path still runs and differs in general
    t = np.asarray(eng.generate(prompt, max_new_tokens=6, temperature=5.0,
                                seed=7))
    assert t.shape == greedy.shape


def test_default_inference_config_roundtrip():
    """default_inference_config (reference __init__.py:295): editable dict
    accepted back by init_inference."""
    import deepspeed_tpu

    cfg = deepspeed_tpu.default_inference_config()
    assert isinstance(cfg, dict) and not any(k.startswith("_") for k in cfg)
    cfg["dtype"] = "fp32"
    cfg["max_seq_len"] = 64
    eng = deepspeed_tpu.init_inference(llama_model("tiny", max_seq_len=64,
                                                   attn_impl="xla"),
                                       config=cfg)
    out = eng.generate(np.zeros((1, 4), np.int32), max_new_tokens=2)
    assert out.shape == (1, 6)
