"""Resilience subsystem tests (deepspeed_tpu/resilience/): verified
atomic commits, corruption fallback, preemption watcher + emergency
save, auto-resume, I/O retry, chaos injectors, and the elastic agent's
exit-code/backoff policy.  See docs/RESILIENCE.md."""

import json
import os

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.elasticity.elastic_agent import ElasticAgent
from deepspeed_tpu.resilience import (CorruptCheckpointError,
                                      PreemptionInterrupt, chaos,
                                      metrics as res_metrics)
from deepspeed_tpu.resilience.commit import (MANIFEST, begin_commit,
                                             checkpoint_commit, gc_tags,
                                             io_retry, list_tags,
                                             resolve_tag, verify_tag)
from deepspeed_tpu.resilience.preemption import (EXIT_CONFIG, EXIT_RESUMABLE,
                                                 PreemptionWatcher,
                                                 exit_code_for_exception)
from deepspeed_tpu.runtime.checkpoint_engine.engines import (
    CheckpointEngine, CheckpointSaveError, DecoupledCheckpointEngine,
    FastCheckpointEngine, NumpyCheckpointEngine)
from tests.unit.simple_model import random_batch, simple_mlp_spec


def _engine(resilience=None, checkpoint=None, stage=0):
    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": stage},
    }
    if resilience is not None:
        cfg["resilience"] = resilience
    if checkpoint is not None:
        cfg["checkpoint"] = checkpoint
    engine, *_ = deepspeed_tpu.initialize(model=simple_mlp_spec(), config=cfg)
    return engine


def _params_equal(a, b):
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_allclose(
            np.asarray(jax.device_get(x)), np.asarray(jax.device_get(y)),
            rtol=1e-6), a, b)


def _train(engine, steps, start=0):
    return [float(engine.train_batch(random_batch(batch_size=8,
                                                  seed=(start + i) % 3, gas=1)))
            for i in range(steps)]


# ------------------------------------------------------------ commit protocol
def test_commit_layout_and_verification(tmp_path, devices8):
    e = _engine()
    _train(e, 2)
    path = e.save_checkpoint(str(tmp_path))
    assert os.path.isdir(path) and path.endswith("global_step2")
    assert os.path.exists(os.path.join(path, MANIFEST))
    # no staging debris; latest pointer committed atomically
    assert not [f for f in os.listdir(tmp_path) if f.startswith("tmp.")]
    assert open(tmp_path / "latest").read().strip() == "global_step2"
    report = verify_tag(str(tmp_path), "global_step2")
    assert report["ok"] and report["verified"] and not report["problems"]
    # manifest carries step/world/mesh metadata + per-array checksums
    man = chaos.read_manifest(str(tmp_path), "global_step2")
    assert man["meta"]["global_steps"] == 2
    assert man["meta"]["world"] == 1
    assert "data" in man["meta"]["mesh"]
    assert man["meta"]["array_crc32"]
    assert all("crc32" in info for info in man["files"].values())


def test_unfinalized_staging_is_invisible_and_gced(tmp_path, devices8):
    # simulate a crash strictly before the commit point: staged files
    # exist, no rename happened
    staging = begin_commit(str(tmp_path), "crashed")
    with open(os.path.join(staging, "model.bin"), "wb") as f:
        f.write(b"x" * 128)
    tag, report = resolve_tag(str(tmp_path))
    assert tag is None and not report["ok"]
    # the next successful save garbage-collects the partial staging dir
    e = _engine()
    _train(e, 1)
    e.save_checkpoint(str(tmp_path))
    assert not [f for f in os.listdir(tmp_path) if f.startswith("tmp.")]
    tag, _ = resolve_tag(str(tmp_path))
    assert tag == "global_step1"


def test_partial_staging_from_chaos_is_never_a_candidate(tmp_path):
    chaos.make_partial_staging(str(tmp_path), "t9")
    assert list_tags(str(tmp_path)) == []
    removed = gc_tags(str(tmp_path))
    assert removed == ["tmp.t9"]


def test_gc_keep_n(tmp_path, devices8):
    e = _engine(resilience={"enabled": True, "save_dir": str(tmp_path),
                            "auto_resume": False, "emergency_save": False,
                            "keep_n": 2, "watch_signals": False})
    for _ in range(4):
        _train(e, 1)
        e.save_checkpoint(str(tmp_path))
    tags = list_tags(str(tmp_path))
    assert tags == ["global_step4", "global_step3"]
    assert open(tmp_path / "latest").read().strip() == "global_step4"


def test_bitflip_detected_counted_and_fallback(tmp_path, devices8):
    e1 = _engine()
    _train(e1, 1)
    e1.save_checkpoint(str(tmp_path))
    good_params = jax.tree_util.tree_map(np.asarray, e1.state.params)
    _train(e1, 1, start=1)
    e1.save_checkpoint(str(tmp_path))
    chaos.bitflip_array(str(tmp_path), "global_step2", seed=3)

    before = res_metrics.corrupt_checkpoints_total().total()
    e2 = _engine()
    path, _ = e2.load_checkpoint(str(tmp_path))
    assert path is not None and path.endswith("global_step1")
    assert e2.global_steps == 1
    _params_equal(e2.state.params, good_params)
    assert res_metrics.corrupt_checkpoints_total().total() == before + 1


def test_torn_manifest_falls_back(tmp_path, devices8):
    e1 = _engine()
    _train(e1, 1)
    e1.save_checkpoint(str(tmp_path))
    _train(e1, 1, start=1)
    e1.save_checkpoint(str(tmp_path))
    chaos.tear_manifest(str(tmp_path), "global_step2")
    e2 = _engine()
    path, _ = e2.load_checkpoint(str(tmp_path))
    assert path.endswith("global_step1") and e2.global_steps == 1


def test_explicit_corrupt_tag_raises(tmp_path, devices8):
    e1 = _engine()
    _train(e1, 1)
    e1.save_checkpoint(str(tmp_path))
    chaos.bitflip_array(str(tmp_path), "global_step1", seed=0)
    e2 = _engine()
    with pytest.raises(CorruptCheckpointError, match="global_step1"):
        e2.load_checkpoint(str(tmp_path), tag="global_step1")


def test_stale_latest_pointer_falls_back(tmp_path, devices8):
    e1 = _engine()
    _train(e1, 1)
    e1.save_checkpoint(str(tmp_path))
    chaos.corrupt_latest_pointer(str(tmp_path))
    before = res_metrics.corrupt_checkpoints_total().total()
    e2 = _engine()
    path, _ = e2.load_checkpoint(str(tmp_path))
    assert path.endswith("global_step1")
    # a dangling pointer is a lookup failure, not data corruption
    assert res_metrics.corrupt_checkpoints_total().total() == before


def test_explicit_missing_tag_is_not_corruption(tmp_path, devices8):
    e1 = _engine()
    _train(e1, 1)
    e1.save_checkpoint(str(tmp_path))
    before = res_metrics.corrupt_checkpoints_total().total()
    e2 = _engine()
    with pytest.raises(FileNotFoundError, match="no_such_tag"):
        e2.load_checkpoint(str(tmp_path), tag="no_such_tag")
    assert res_metrics.corrupt_checkpoints_total().total() == before


def test_foreign_subdirs_survive_gc_and_resolution(tmp_path, devices8):
    # a save_dir that also holds non-checkpoint dirs (tensorboard/,
    # logs/): GC must never delete them, resolution must never load them
    logs = tmp_path / "tensorboard"
    logs.mkdir()
    (logs / "events.out").write_text("not a checkpoint")
    e = _engine(resilience={"enabled": True, "save_dir": str(tmp_path),
                            "auto_resume": False, "emergency_save": False,
                            "keep_n": 1, "watch_signals": False})
    for _ in range(3):
        _train(e, 1)
        e.save_checkpoint(str(tmp_path))
    assert (logs / "events.out").exists()  # keep_n GC left it alone
    assert list_tags(str(tmp_path)) == ["global_step3"]
    chaos.corrupt_latest_pointer(str(tmp_path), target="tensorboard")
    tag, _ = resolve_tag(str(tmp_path))
    assert tag == "global_step3"  # the foreign dir is not a candidate


def test_manifest_entry_without_crc_is_reported_not_crash(tmp_path, devices8):
    e = _engine()
    _train(e, 1)
    e.save_checkpoint(str(tmp_path))
    man_path = tmp_path / "global_step1" / MANIFEST
    man = json.loads(man_path.read_text())
    next(iter(man["files"].values())).pop("crc32")  # version-skewed entry
    man_path.write_text(json.dumps(man))
    report = verify_tag(str(tmp_path), "global_step1")
    assert not report["ok"] and report["problems"]  # reported, no TypeError


def test_legacy_checkpoint_without_manifest_loads_unverified(tmp_path, devices8):
    e1 = _engine()
    _train(e1, 1)
    e1.save_checkpoint(str(tmp_path))
    os.remove(tmp_path / "global_step1" / MANIFEST)
    report = verify_tag(str(tmp_path), "global_step1")
    assert report["ok"] and not report["verified"]
    e2 = _engine()
    path, _ = e2.load_checkpoint(str(tmp_path))
    assert path.endswith("global_step1") and e2.global_steps == 1


# --------------------------------------------------------- checkpoint engines
@pytest.mark.parametrize("ckpt_cfg", [
    {},                                   # sync NumpyCheckpointEngine
    {"parallel_write_pipeline": True},    # FastCheckpointEngine (AIO)
    {"async_save": True},                 # DecoupledCheckpointEngine
], ids=["sync", "fast", "decoupled"])
def test_engine_roundtrip_every_checkpoint_engine_kind(tmp_path, devices8,
                                                       ckpt_cfg):
    e1 = _engine(checkpoint=ckpt_cfg, stage=2)
    _train(e1, 2)
    e1.save_checkpoint(str(tmp_path), partitioned=True)
    report = verify_tag(str(tmp_path), "global_step2")
    assert report["ok"] and report["verified"]
    e2 = _engine(checkpoint=ckpt_cfg, stage=2)
    path, _ = e2.load_checkpoint(str(tmp_path))
    assert path is not None and e2.global_steps == 2
    _params_equal(e1.state.params, e2.state.params)
    _train(e2, 1)  # loaded state trains on


def test_fast_engine_zero_size_arrays_roundtrip(tmp_path):
    ce = FastCheckpointEngine(thread_count=2)
    arrays = {"empty1d": np.empty((0,), np.float32),
              "empty2d": np.empty((3, 0), np.int32),
              "scalar": np.float32(7.0).reshape(()),
              "normal": np.arange(12, dtype=np.float32).reshape(3, 4)}
    ce.save(arrays, str(tmp_path / "fast"))
    out = ce.load(str(tmp_path / "fast"))
    for k, v in arrays.items():
        assert out[k].shape == v.shape and out[k].dtype == v.dtype, k
        np.testing.assert_array_equal(out[k], v)
    # zero-size entries are manifest-only (no ambiguous 0-byte files)
    with open(tmp_path / "fast" / "manifest.json") as f:
        man = json.load(f)
    assert man["empty1d"].get("empty") and "file" not in man["empty1d"]


def test_fast_engine_manifest_written_atomically(tmp_path):
    ce = FastCheckpointEngine(thread_count=2)
    ce.save({"a": np.ones(8, np.float32)}, str(tmp_path / "fast"))
    files = os.listdir(tmp_path / "fast")
    assert "manifest.json" in files
    assert not [f for f in files if ".tmp." in f], files


class _FailingInner(CheckpointEngine):
    def save(self, arrays, path):
        raise IOError(f"disk on fire while writing {path}")


class _RecordingInner(CheckpointEngine):
    def __init__(self):
        self.events = []

    def save(self, arrays, path):
        import time

        self.events.append(("start", path))
        time.sleep(0.1)
        self.events.append(("end", path))

    def load(self, path):
        return {}


def test_decoupled_failure_attributed_to_owning_save(tmp_path):
    ce = DecoupledCheckpointEngine(inner=_FailingInner())
    ce.save({"x": np.ones(4, np.float32)}, str(tmp_path / "first_ckpt"))
    # the failure surfaces at the next boundary, naming the save that
    # OWNED it (first_ckpt) — not the save that happened to join
    with pytest.raises(CheckpointSaveError, match="first_ckpt") as ei:
        ce.save({"x": np.ones(4, np.float32)}, str(tmp_path / "second_ckpt"))
    assert ei.value.path == str(tmp_path / "first_ckpt")
    assert "second_ckpt" not in str(ei.value)
    # the engine recovered: the error was consumed, next commit is clean
    assert ce.commit("after") is True


def test_decoupled_commit_reports_owning_tag(tmp_path):
    ce = DecoupledCheckpointEngine(inner=_FailingInner())
    ce.save({"x": np.ones(4, np.float32)}, str(tmp_path / "ck"))
    with pytest.raises(CheckpointSaveError, match="tag 'step7'"):
        ce.commit("step7")


def test_decoupled_one_in_flight_contract(tmp_path):
    inner = _RecordingInner()
    ce = DecoupledCheckpointEngine(inner=inner)
    ce.save({"x": np.ones(4, np.float32)}, str(tmp_path / "a"))
    ce.save({"x": np.ones(4, np.float32)}, str(tmp_path / "b"))
    ce.commit("final")
    # writes never interleave: a fully ends before b starts
    assert inner.events == [("start", str(tmp_path / "a")),
                            ("end", str(tmp_path / "a")),
                            ("start", str(tmp_path / "b")),
                            ("end", str(tmp_path / "b"))]


# ------------------------------------------------- preemption + auto-resume
def test_preemption_emergency_save_and_resumable_exit(tmp_path, devices8):
    res = {"enabled": True, "save_dir": str(tmp_path), "keep_n": 4,
           "watch_signals": False}
    e = _engine(resilience=res)
    _train(e, 2)
    before = res_metrics.emergency_saves_total().total()
    chaos.simulate_preemption(e.resilience)
    # honored at the NEXT step boundary: the step completes, then the
    # engine emergency-saves and exits resumable
    with pytest.raises(PreemptionInterrupt) as ei:
        e.train_batch(random_batch(batch_size=8, seed=0, gas=1))
    assert ei.value.code == EXIT_RESUMABLE
    assert res_metrics.emergency_saves_total().total() == before + 1
    report = verify_tag(str(tmp_path), "emergency_step3")
    assert report["ok"] and report["verified"]
    assert open(tmp_path / "latest").read().strip() == "emergency_step3"

    # a PreemptionInterrupt is a SystemExit: it must NOT be swallowed by
    # generic except-Exception handlers in user loops
    assert isinstance(ei.value, SystemExit)

    # relaunch: a fresh engine auto-resumes from the emergency tag
    restores_before = res_metrics.restores_total().total()
    e2 = _engine(resilience=res)
    assert e2.global_steps == 3
    _params_equal(e.state.params, e2.state.params)
    assert res_metrics.restores_total().total() == restores_before + 1
    _train(e2, 1, start=3)  # resumed state trains on


def test_auto_resume_fresh_start_when_empty(tmp_path, devices8):
    e = _engine(resilience={"enabled": True, "save_dir": str(tmp_path / "none"),
                            "watch_signals": False})
    assert e.global_steps == 0
    _train(e, 1)


def test_auto_resume_skips_corrupt_newest(tmp_path, devices8):
    res = {"enabled": True, "save_dir": str(tmp_path), "auto_resume": True,
           "emergency_save": False, "watch_signals": False}
    e1 = _engine(resilience=res)
    assert e1.global_steps == 0
    _train(e1, 1)
    e1.save_checkpoint(str(tmp_path))
    _train(e1, 1, start=1)
    e1.save_checkpoint(str(tmp_path))
    chaos.bitflip_array(str(tmp_path), "global_step2", seed=1)
    e2 = _engine(resilience=res)
    assert e2.global_steps == 1  # newest skipped, previous good tag used


def test_io_retry_rides_out_flaky_fs(tmp_path, devices8):
    res = {"enabled": True, "save_dir": str(tmp_path), "auto_resume": False,
           "emergency_save": False, "io_retries": 3,
           "io_retry_base_s": 0.01, "watch_signals": False}
    e = _engine(resilience=res)
    _train(e, 1)
    before = res_metrics.io_retries_total().total()
    chaos.install_io_fault(chaos.FlakyIO(fail_ops=2))
    try:
        path = e.save_checkpoint(str(tmp_path))
    finally:
        chaos.install_io_fault(None)
    assert os.path.isdir(path)
    assert verify_tag(str(tmp_path), "global_step1")["ok"]
    assert res_metrics.io_retries_total().total() == before + 2


def test_io_retry_gives_up_after_budget():
    calls = []

    def always_fails():
        calls.append(1)
        raise OSError("nope")

    with pytest.raises(OSError):
        io_retry(always_fails, retries=2, base_delay_s=0.0)
    assert len(calls) == 3  # 1 try + 2 retries


def test_preemption_watcher_notify_is_sticky_and_clearable():
    w = PreemptionWatcher(install_signals=False)
    assert w.requested is None
    w.notify("chaos:test")
    w.notify("second")  # first reason wins
    assert w.requested == "chaos:test"
    w.clear()
    assert w.requested is None


def test_exit_code_contract():
    assert exit_code_for_exception(ValueError("bad config")) == EXIT_CONFIG
    assert exit_code_for_exception(RuntimeError("boom")) == 1
    assert exit_code_for_exception(PreemptionInterrupt()) == EXIT_RESUMABLE
    assert exit_code_for_exception(SystemExit()) == 0  # bare sys.exit()
    assert exit_code_for_exception(SystemExit("msg")) == 1
    assert exit_code_for_exception(SystemExit(7)) == 7


# ------------------------------------------------------------- elastic agent
def _scripted_agent(rcs, **kw):
    agent = ElasticAgent(restart_delay_s=kw.pop("restart_delay_s", 0.0), **kw)
    seq = list(rcs)

    def fake_attempt(cmds):
        return seq.pop(0)

    agent._run_attempt = fake_attempt
    return agent


def test_agent_exponential_backoff_with_jitter(monkeypatch):
    agent = _scripted_agent([1, 1, 1, 1], restart_delay_s=1.0,
                            max_restarts=3, backoff_jitter=0.5, seed=0)
    slept = []
    monkeypatch.setattr("time.sleep", lambda s: slept.append(s))
    rc = agent.run("train.py")
    assert rc == 1 and agent.attempts == 4
    assert len(slept) == 3
    for i, s in enumerate(slept):
        base = 1.0 * (2 ** i)
        assert base <= s <= base * 1.5, (i, s)  # doubled + bounded jitter


def test_agent_stops_on_non_resumable_exit():
    agent = _scripted_agent([EXIT_CONFIG, 0], max_restarts=5)
    rc = agent.run("train.py")
    assert rc == EXIT_CONFIG
    assert agent.attempts == 1  # config errors are NOT relaunched


def test_agent_resumable_exit_does_not_consume_budget():
    # preempt, preempt, crash, then success — with max_restarts=1 the
    # crash is the only draw on the failure budget
    agent = _scripted_agent([EXIT_RESUMABLE, EXIT_RESUMABLE, 1, 0],
                            max_restarts=1)
    rc = agent.run("train.py")
    assert rc == 0
    assert agent.attempts == 4
    assert agent.preemptions == 2


def test_agent_caps_preemption_relaunches():
    agent = _scripted_agent([EXIT_RESUMABLE] * 4, max_restarts=5,
                            max_preemption_restarts=2)
    rc = agent.run("train.py")
    assert rc == EXIT_RESUMABLE
    assert agent.preemptions == 3  # 2 relaunches + the one that gave up


def test_agent_logs_attempts_to_event_ring():
    from deepspeed_tpu.telemetry import (FlightRecorder,
                                         install_flight_recorder)

    fr = FlightRecorder()
    install_flight_recorder(fr)
    try:
        agent = _scripted_agent([1, 0], max_restarts=2)
        assert agent.run("train.py") == 0
        events = [e for e in fr._events if e["name"] == "elastic_attempt"]
        assert len(events) >= 2
        assert events[0]["world"] == 1 and events[0]["attempt"] == 1
    finally:
        install_flight_recorder(None)
