"""Indexed dataset (reference data_sampling/indexed_dataset.py):
byte-compatible Megatron .bin/.idx roundtrip."""

import numpy as np
import pytest

from deepspeed_tpu.runtime.data_pipeline.indexed_dataset import (
    MMapIndexedDataset, MMapIndexedDatasetBuilder, index_file_path,
    make_dataset, merge_datasets)


def _build(prefix, seqs, dtype=np.int32, docs_every=2):
    b = MMapIndexedDatasetBuilder(str(prefix), dtype=dtype)
    for i, s in enumerate(seqs):
        b.add_item(s)
        if (i + 1) % docs_every == 0:
            b.end_document()
    return b.finalize()


def test_roundtrip_and_get(tmp_path):
    rng = np.random.RandomState(0)
    seqs = [rng.randint(0, 30000, n).astype(np.int32) for n in (5, 17, 1, 64)]
    _build(tmp_path / "corpus", seqs)
    ds = MMapIndexedDataset(str(tmp_path / "corpus"))
    assert len(ds) == 4
    for want, got in zip(seqs, ds[0:4]):
        np.testing.assert_array_equal(want, got)
    np.testing.assert_array_equal(ds.get(3, offset=10, length=20),
                                  seqs[3][10:30])
    assert list(ds.doc_idx) == [0, 2, 4]


def test_reference_format_header(tmp_path):
    """The .idx header must be the exact Megatron layout (magic, version,
    dtype code 4 for int32)."""
    _build(tmp_path / "c", [np.arange(3, dtype=np.int32)])
    raw = open(index_file_path(str(tmp_path / "c")), "rb").read()
    assert raw[:9] == b"MMIDIDX\x00\x00"
    assert raw[9:17] == (1).to_bytes(8, "little")  # version
    assert raw[17] == 4  # int32 code, reference dtypes table


def test_uint16_tokens_and_merge(tmp_path):
    a = [np.asarray([1, 2, 3], np.uint16), np.asarray([9], np.uint16)]
    b = [np.asarray([7, 7], np.uint16)]
    _build(tmp_path / "a", a, dtype=np.uint16, docs_every=1)
    _build(tmp_path / "b", b, dtype=np.uint16, docs_every=1)
    merge_datasets([str(tmp_path / "a"), str(tmp_path / "b")],
                   str(tmp_path / "m"))
    m = make_dataset(str(tmp_path / "m"))
    assert m.dtype == np.uint16
    np.testing.assert_array_equal(m[0], a[0])
    np.testing.assert_array_equal(m[2], b[0])
    assert len(m.doc_idx) == 4  # 3 docs + leading 0


def test_make_dataset_validation(tmp_path):
    with pytest.raises(FileNotFoundError):
        make_dataset(str(tmp_path / "missing"))
    with pytest.raises(ValueError):
        make_dataset(str(tmp_path / "x"), impl="lazy")


def test_merge_preserves_trailing_open_document(tmp_path):
    b = MMapIndexedDatasetBuilder(str(tmp_path / "t"))
    b.add_item(np.asarray([1, 2], np.int32))
    b.end_document()
    b.add_item(np.asarray([3], np.int32))  # trailing, no end_document
    b.finalize()
    merge_datasets([str(tmp_path / "t")], str(tmp_path / "tm"))
    m = MMapIndexedDataset(str(tmp_path / "tm"))
    assert len(m) == 2  # the trailing sequence survives
    np.testing.assert_array_equal(m[1], [3])


def test_merge_rejects_dtype_mismatch(tmp_path):
    _build(tmp_path / "i32", [np.asarray([70000], np.int32)])
    _build(tmp_path / "u16", [np.asarray([1], np.uint16)], dtype=np.uint16)
    with pytest.raises(ValueError):
        merge_datasets([str(tmp_path / "u16"), str(tmp_path / "i32")],
                       str(tmp_path / "bad"))


def test_empty_shard_reads_as_len_zero(tmp_path):
    MMapIndexedDatasetBuilder(str(tmp_path / "e")).finalize()
    ds = MMapIndexedDataset(str(tmp_path / "e"))
    assert len(ds) == 0
