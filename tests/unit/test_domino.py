"""Domino TP overlap tests (reference: runtime/domino/transformer.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from deepspeed_tpu.models.transformer import (TransformerConfig,
                                              init_transformer_params,
                                              transformer_forward)
from deepspeed_tpu.runtime.domino import DominoConfig, domino_transformer_forward

pytestmark = pytest.mark.slow  # multi-minute integration tier


def _mesh(tp):
    return Mesh(np.array(jax.devices()[:tp]), ("model",))


def _cfg(**kw):
    base = dict(vocab_size=128, hidden_size=32, n_layers=2, n_heads=4,
                intermediate_size=64, max_seq_len=32, dtype=jnp.float32,
                attn_impl="xla", scan_layers=False)
    base.update(kw)
    return TransformerConfig(**base)


def _check_matches_dense(cfg, tp=4, n_chunks=2, batch=4):
    params = init_transformer_params(cfg, jax.random.PRNGKey(0))
    ids = jnp.asarray(
        np.random.RandomState(0).randint(0, cfg.vocab_size, (batch, 16)), jnp.int32)
    want, _aux = transformer_forward(cfg, params, ids)
    with _mesh(tp) as mesh:
        got = domino_transformer_forward(cfg, params, ids, mesh,
                                         n_chunks=n_chunks)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_domino_matches_dense_llama_style():
    _check_matches_dense(_cfg())  # rope + rmsnorm + swiglu, no bias


def test_domino_matches_dense_gpt2_style():
    _check_matches_dense(_cfg(position="learned", norm="layernorm",
                              activation="gelu", use_bias=True))


def test_domino_gqa():
    _check_matches_dense(_cfg(n_kv_heads=2), tp=2)


def test_domino_four_chunks():
    _check_matches_dense(_cfg(), n_chunks=4, batch=8)


def test_domino_validates():
    cfg = _cfg()
    params = init_transformer_params(cfg, jax.random.PRNGKey(0))
    ids = jnp.zeros((3, 16), jnp.int32)
    with _mesh(4) as mesh:
        with pytest.raises(ValueError):  # batch 3 % 2 chunks
            domino_transformer_forward(cfg, params, ids, mesh)
        with pytest.raises(ValueError):  # moe unsupported
            domino_transformer_forward(
                _cfg(moe_experts=4), params, jnp.zeros((4, 16), jnp.int32), mesh)


def test_domino_config_object():
    cfg = _cfg()
    params = init_transformer_params(cfg, jax.random.PRNGKey(0))
    ids = jnp.zeros((4, 16), jnp.int32)
    with _mesh(2) as mesh:
        out = domino_transformer_forward(
            cfg, params, ids, mesh,
            domino_config=DominoConfig(n_chunks=2, axis="model"))
    assert out.shape == (4, 16, cfg.hidden_size)
