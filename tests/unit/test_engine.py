"""Engine end-to-end tests: the minimum slice (SURVEY §7 build order #2)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from tests.unit.simple_model import random_batch, random_dataset, simple_mlp_spec


def _make_engine(config_overrides=None, **kw):
    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
    }
    cfg.update(config_overrides or {})
    engine, _, _, _ = deepspeed_tpu.initialize(model=simple_mlp_spec(), config=cfg, **kw)
    return engine


def _loss_decreases(engine, steps=20, gas=1):
    losses = []
    for i in range(steps):
        batch = random_batch(batch_size=16, seed=i % 4, gas=gas)
        loss = engine.train_batch(batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0], f"loss did not decrease: {losses[0]} -> {losses[-1]}"
    return losses


def test_train_fp32():
    engine = _make_engine()
    _loss_decreases(engine)
    assert int(engine.state.step) == 20


def test_train_bf16():
    engine = _make_engine({"bf16": {"enabled": True}})
    _loss_decreases(engine)


def test_train_fp16_loss_scaling():
    engine = _make_engine({"fp16": {"enabled": True, "initial_scale_power": 8}})
    _loss_decreases(engine)
    assert engine.loss_scale() > 0


def test_grad_accumulation():
    engine = _make_engine({"gradient_accumulation_steps": 4})
    _loss_decreases(engine, steps=8, gas=4)
    assert int(engine.state.step) == 8


def test_forward_backward_step_compat():
    """The DeepSpeed-style training loop."""
    engine = _make_engine({"gradient_accumulation_steps": 2})
    losses = []
    for i in range(16):
        batch = random_batch(batch_size=16, seed=i % 4)
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert int(engine.state.step) == 8  # 16 micro / gas 2
    # compare same-seed batches: 12 is seed 0, as is 0
    assert losses[12] < losses[0]


def test_abandoned_microstep_then_train_batch():
    """An incremental forward/backward without step() leaves a nonzero
    grad-accumulation buffer; train_batch must reset it (advisor r3) so the
    fused step matches a clean engine that never saw the abandoned step."""
    engine = _make_engine()
    control = _make_engine()
    # abandoned micro-step: forward+backward, never step()
    engine.backward(engine(random_batch(batch_size=16, seed=9, gas=0)))
    acc = jax.tree_util.tree_leaves(engine.state.grad_acc)
    assert any(float(jnp.abs(a).max()) > 0 for a in acc), "no stale acc to test"
    batch = random_batch(batch_size=16, seed=1, gas=1)
    l1 = float(engine.train_batch(batch))
    l2 = float(control.train_batch(batch))
    assert l1 == pytest.approx(l2)
    for a, b in zip(jax.tree_util.tree_leaves(engine.state.params),
                    jax.tree_util.tree_leaves(control.state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)
    # buffer was reset, not consumed
    assert all(float(jnp.abs(a).max()) == 0
               for a in jax.tree_util.tree_leaves(engine.state.grad_acc))


def test_abandoned_microstep_gas2_boundary_realigned():
    """With gas>1 the reset must also void the abandoned micro-steps in the
    host counter, or the incremental API's accumulation boundary stays
    phase-shifted forever after."""
    engine = _make_engine({"gradient_accumulation_steps": 2})
    engine.backward(engine(random_batch(batch_size=16, seed=9, gas=0)))
    engine.train_batch(random_batch(batch_size=16, seed=1, gas=2))
    steps_before = int(engine.state.step)
    # resume the incremental loop: boundary must need TWO micro-steps again
    engine.backward(engine(random_batch(batch_size=16, seed=2, gas=0)))
    assert not engine.is_gradient_accumulation_boundary()
    engine.step()  # not a boundary: must NOT apply
    assert int(engine.state.step) == steps_before
    engine.backward(engine(random_batch(batch_size=16, seed=3, gas=0)))
    assert engine.is_gradient_accumulation_boundary()
    engine.step()
    assert int(engine.state.step) == steps_before + 1


def test_gradient_clipping():
    engine = _make_engine({"gradient_clipping": 0.01})
    engine.train_batch(random_batch(batch_size=16, gas=1))
    assert engine.get_global_grad_norm() >= 0


def test_scheduler_warmup():
    engine = _make_engine({
        "scheduler": {"type": "WarmupLR",
                      "params": {"warmup_min_lr": 0.0, "warmup_max_lr": 0.01,
                                 "warmup_num_steps": 10}}})
    lr0 = engine.get_lr()[0]
    for i in range(5):
        engine.train_batch(random_batch(batch_size=8, seed=i, gas=1))
    assert engine.get_lr()[0] > lr0


def test_dataloader_training():
    data = random_dataset(64)
    engine, _, loader, _ = deepspeed_tpu.initialize(
        model=simple_mlp_spec(),
        config={"train_micro_batch_size_per_gpu": 4,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}},
        training_data=data)
    assert loader is not None
    it = iter(deepspeed_tpu.runtime.dataloader.RepeatingLoader(loader))
    l0 = float(engine.train_batch(data_iter=it))
    for _ in range(10):
        l1 = float(engine.train_batch(data_iter=it))
    assert np.isfinite(l1)


def test_eval_batch():
    engine = _make_engine()
    out = engine.eval_batch(random_batch(batch_size=4))
    assert out.shape == (4, 16)


@pytest.mark.parametrize("stage", [0, 1, 2, 3])
def test_zero_stages_train(stage, devices8):
    engine = _make_engine({"zero_optimization": {"stage": stage},
                           "bf16": {"enabled": True}})
    _loss_decreases(engine, steps=10)


def test_zero_stage3_params_sharded(devices8):
    engine = _make_engine({"zero_optimization": {"stage": 3}})
    # master params must be sharded over the data axis
    leaf = engine.state.params["layer_0"]["w"]
    spec = leaf.sharding.spec
    assert any(s is not None for s in spec), f"stage-3 param not sharded: {spec}"


def test_zero_stage3_persistence_threshold(devices8):
    """Params at/below stage3_param_persistence_threshold keep an
    unpartitioned live copy (reference persistence semantics); master state
    still shards."""
    engine = _make_engine({"zero_optimization": {
        "stage": 3, "stage3_param_persistence_threshold": 10_000_000}})
    # every SimpleModel param is under the (huge) threshold -> all live
    # params replicated; the fp32 master remains zero-sharded
    leaf = engine.state.params["layer_0"]["w"]
    plan = engine.zero_plan
    live = plan.param_spec("layer_0/w", tuple(leaf.shape))
    master = plan.master_spec("layer_0/w", tuple(leaf.shape))
    assert all(s is None for s in live), f"persistent param sharded: {live}"
    assert any(s is not None for s in master), \
        f"master must shard regardless of persistence: {master}"
    # threshold below the param size -> live param shards again
    engine2 = _make_engine({"zero_optimization": {
        "stage": 3, "stage3_param_persistence_threshold": 1}})
    live2 = engine2.zero_plan.param_spec("layer_0/w", tuple(leaf.shape))
    assert any(s is not None for s in live2)
    # and it still trains
    _loss_decreases(engine, steps=5)


def test_offload_param_config_reaches_pass(devices8):
    """zero_optimization.offload_param.device=cpu routes through the
    offload_params pass; on the CPU backend (no host memory spaces) it
    must warn and keep training rather than crash — on TPU it pins the
    fp32 master to pinned_host."""
    engine = _make_engine({"zero_optimization": {
        "stage": 3, "offload_param": {"device": "cpu"}}})
    _loss_decreases(engine, steps=5)


def test_zero_stage0_params_replicated(devices8):
    engine = _make_engine({"zero_optimization": {"stage": 0}})
    leaf = engine.state.params["layer_0"]["w"]
    assert all(s is None for s in leaf.sharding.spec)


def test_no_sync_and_batch_size_setters(devices8):
    engine = _make_engine({"zero_optimization": {"stage": 1}})
    with engine.no_sync():
        engine.train_batch(random_batch(batch_size=8, gas=1))
    # stage >= 2 must refuse (reference engine.no_sync assert)
    e2 = _make_engine({"zero_optimization": {"stage": 2}})
    with pytest.raises(AssertionError):
        e2.no_sync()
    # gas-only batch resize; next call retraces at the new shape
    micro = engine.config.train_micro_batch_size_per_gpu
    dp = engine.topology.dp_world_size
    engine.set_train_batch_size(micro * dp * 2)
    assert engine.gradient_accumulation_steps() == 2
    engine.train_batch(random_batch(batch_size=8, gas=2))
    with pytest.raises(ValueError):
        engine.set_train_batch_size(micro * dp * 2 + 1)


def test_checkpoint_roundtrip(tmp_path):
    engine = _make_engine()
    for i in range(3):
        engine.train_batch(random_batch(batch_size=8, seed=i, gas=1))
    params_before = jax.device_get(engine.state.params)
    engine.save_checkpoint(str(tmp_path), client_state={"foo": 1})

    engine2 = _make_engine()
    path, client = engine2.load_checkpoint(str(tmp_path))
    assert path is not None
    assert client == {"foo": 1}
    assert engine2.global_steps == 3
    after = jax.device_get(engine2.state.params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6), params_before, after)
    # resumed training works
    engine2.train_batch(random_batch(batch_size=8, gas=1))


def test_checkpoint_reshard_across_zero_stage(tmp_path, devices8):
    """Save at stage 0, load at stage 3 (the universal-checkpoint promise)."""
    e0 = _make_engine({"zero_optimization": {"stage": 0}})
    e0.train_batch(random_batch(batch_size=8, gas=1))
    e0.save_checkpoint(str(tmp_path))

    e3 = _make_engine({"zero_optimization": {"stage": 3}})
    e3.load_checkpoint(str(tmp_path))
    a = jax.device_get(e0.state.params["layer_0"]["w"])
    b = jax.device_get(e3.state.params["layer_0"]["w"])
    np.testing.assert_allclose(a, b, rtol=1e-6)


def test_sanity_checks_catches_nonfinite_loss():
    """Opt-in NaN guard (reference is_sanity_checks_enabled): a poisoned
    param tree must raise at the step instead of training on garbage."""
    engine = _make_engine({"sanity_checks": True})
    engine.train_batch(random_batch(batch_size=16, gas=1))  # healthy step
    import dataclasses

    poisoned = jax.tree_util.tree_map(
        lambda x: jnp.full_like(x, jnp.nan), engine.state.params)
    engine.state = dataclasses.replace(engine.state, params=poisoned)
    with pytest.raises(FloatingPointError, match="non-finite loss"):
        engine.train_batch(random_batch(batch_size=16, gas=1))


def test_profiler_trace_roundtrip(tmp_path):
    """start/stop_profiler_trace writes an XLA trace directory."""
    engine = _make_engine()
    engine.start_profiler_trace(str(tmp_path))
    engine.train_batch(random_batch(batch_size=16, gas=1))
    engine.stop_profiler_trace()
    import glob

    assert glob.glob(str(tmp_path) + "/**/*.pb", recursive=True) or \
        glob.glob(str(tmp_path) + "/**/*.json*", recursive=True) or \
        glob.glob(str(tmp_path) + "/plugins/**", recursive=True)


def test_sanity_checks_covers_incremental_loop():
    """The guard must also fire in the forward/backward/step cadence."""
    engine = _make_engine({"sanity_checks": True})
    import dataclasses

    engine.state = dataclasses.replace(
        engine.state, params=jax.tree_util.tree_map(
            lambda x: jnp.full_like(x, jnp.nan), engine.state.params))
    with pytest.raises(FloatingPointError, match="non-finite loss"):
        engine.backward(engine(random_batch(batch_size=16, gas=0)))
        engine.step()


def test_sanity_checks_tolerates_fp16_overflow_skip():
    """A dynamic-loss-scale SKIPPED step (overflow handled, scale lowered)
    is recovery in action — sanity_checks must not abort on it; a
    non-finite loss WITHOUT a skip still raises."""
    import dataclasses

    engine = _make_engine({"fp16": {"enabled": True,
                                    "initial_scale_power": 8},
                           "sanity_checks": True})
    engine.train_batch(random_batch(batch_size=16, gas=1))
    # overflow step: skipped_steps advanced past the pre-step snapshot ->
    # the non-finite loss is the scaler recovering, not garbage
    engine.state = dataclasses.replace(
        engine.state,
        skipped_steps=engine.state.skipped_steps + 1)
    before = int(engine.state.skipped_steps) - 1
    engine._sanity_check_maybe(jnp.asarray(jnp.inf), before)  # no raise
    # same loss with NO skip this step -> abort
    with pytest.raises(FloatingPointError):
        engine._sanity_check_maybe(jnp.asarray(jnp.inf),
                                   int(engine.state.skipped_steps))
    # legacy one-arg call: no tolerance, non-finite always aborts
    with pytest.raises(FloatingPointError):
        engine._sanity_check_maybe(jnp.asarray(jnp.nan))
    # persistent divergence: skipping EVERY step runs out of tolerance
    engine._sanity_skip_run = 0
    with pytest.raises(FloatingPointError, match="consecutive"):
        for _ in range(engine._SANITY_MAX_SKIP_RUN + 2):
            engine.state = dataclasses.replace(
                engine.state, skipped_steps=engine.state.skipped_steps + 1)
            engine._sanity_check_maybe(
                jnp.asarray(jnp.nan), int(engine.state.skipped_steps) - 1)
    # a finite loss resets the run counter
    engine._sanity_check_maybe(jnp.asarray(1.0), None)
    assert engine._sanity_skip_run == 0


def test_initialize_adopts_model_parameters():
    """Reference-signature parity: ``initialize(model_parameters=<pytree>)``
    starts the engine from the given values (distilled students, imported
    weights) rather than the model's random init."""
    import deepspeed_tpu
    from deepspeed_tpu.models import llama_model

    model = llama_model("tiny", max_seq_len=16)
    given = model.init_params(jax.random.PRNGKey(123))
    engine, *_ = deepspeed_tpu.initialize(
        model=model, model_parameters=given,
        config={"train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}})
    got = engine.state.params["layers"]["attn"]["wq"]
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(given["layers"]["attn"]["wq"],
                                          np.float32), rtol=1e-2, atol=1e-2)
    ids = {"input_ids": jnp.ones((1, 2, 16), jnp.int32)}
    assert np.isfinite(float(engine.train_batch(ids)))
